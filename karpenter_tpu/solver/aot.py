"""AOT executable snapshot/restore: compiled solver programs that survive exec.

The persistent XLA compilation cache (utils/jaxtools.py) already skips the
XLA *compile* on restart, but a fresh process still pays the full jax TRACE
of every solver program — seconds per executable, tens of seconds across the
warmup ladder (ROADMAP open item 5). This module closes that gap with
``jax.experimental.serialize_executable``: when ``KARPENTER_TPU_AOT_RESTORE``
is on (and ``KARPENTER_TPU_STATE_DIR`` set), every solver program the process
compiles is serialized — executable bytes plus in/out pytree defs — into an
ISA-keyed snapshot directory, and a restarted process deserializes the lot in
tens of milliseconds instead of retracing.

How it plugs in: solver/jax_backend.py routes its jitted dispatch through
:func:`maybe_begin`. The AOT table is keyed by the TRUE static configuration
of each entry function — not just the registry's (fn, claims, shapes) key,
because ``bounds_free`` / ``max_run`` / ``with_topo`` / ``wavefront`` are
derived from concrete problem VALUES and baked into the executable; a
restored program invoked under mismatched statics would silently compute
wrong placements. :func:`_call_spec` recomputes each fn's statics exactly the
way its public entry point does, so a table hit is a program that the jit
path would have dispatched identically.

Restore classification (``karpenter_solver_aot_restore_total{result}`` and
``karpenter_restore_fallback_total{reason}``): every snapshot entry either
restores or lands in one classified failure — truncated / corrupt / checksum
/ version-skew (frame or jax version) / isa-mismatch / flag-mismatch /
deserialize-error — and a failure always degrades to a cold trace+compile,
never an exception on the solve path. The program registry (obs/programs.py)
records dispatches served from a restored executable under the first-class
``restored`` cache source.

Recovery sequencing for /readyz (operator/serving.py): the recovery runner
(solver/warmup.py restore_and_probe) drives the phase machine here —
``idle -> restoring -> probing -> ready|failed`` — and readiness is held
false while a recovery is in flight, so traffic never lands on executables
that have not passed a probe solve.

Flag off: :func:`maybe_begin` is one env read returning None — the dispatch
path, placements, and the narrow-body census (2394 eqns) are untouched.
"""

from __future__ import annotations

import logging
import os
import pickle
import threading
import time
from typing import Dict, List, Optional, Tuple

log = logging.getLogger(__name__)

AOT_VERSION = 1
_FILE_SUFFIX = ".aot"

# restore failure reasons (doubles as the bounded label-value set)
REASONS = (
    "missing", "truncated", "corrupt", "checksum", "version-skew",
    "isa-mismatch", "flag-mismatch", "deserialize-error", "probe-failed",
)


def enabled() -> bool:
    """AOT snapshot/restore is opt-in twice over: the flag AND a state dir.
    Either unset means zero overhead and a byte-identical dispatch path."""
    return (
        os.environ.get("KARPENTER_TPU_AOT_RESTORE", "") not in ("", "0")
        and bool(os.environ.get("KARPENTER_TPU_STATE_DIR"))
    )


def state_dir() -> Optional[str]:
    return os.environ.get("KARPENTER_TPU_STATE_DIR") or None


def aot_dir() -> Optional[str]:
    """Snapshot directory, keyed by host ISA exactly like the persistent
    compile cache: an executable serialized on one microarchitecture must
    never deserialize on another."""
    root = state_dir()
    if not root:
        return None
    from karpenter_tpu.obs.programs import isa_tag

    return os.path.join(root, "aot", isa_tag())


def _device_tag() -> str:
    """The platform the lowering targets right now (the small-batch dispatch
    can pin CPU on a TPU host, so fn+shape alone underdetermines the
    executable)."""
    try:
        import jax

        dev = getattr(jax.config, "jax_default_device", None)
        if dev is not None:
            return str(getattr(dev, "platform", dev))
        return str(jax.default_backend())
    except Exception:
        return "unknown"


# -- call specs: the true statics of each solver entry fn ----------------------


class _Spec:
    __slots__ = ("fn", "lower_args", "dyn", "statics")

    def __init__(self, fn, lower_args: tuple, dyn: tuple, statics: Tuple[str, ...]):
        self.fn = fn
        self.lower_args = lower_args
        self.dyn = dyn
        self.statics = statics


def _call_spec(solve_name: str, problem, max_claims: int, init) -> Optional[_Spec]:
    """Mirror each public entry point's static derivation (ops/ffd_step.py,
    ops/ffd_sweeps.py, ops/ffd_runs.py): the returned spec's ``lower_args``
    reproduce the exact jitted call, ``dyn`` are the runtime arguments a
    Compiled takes (statics are baked), and ``statics`` feed the table key."""
    from karpenter_tpu.ops.ffd_core import problem_bounds_free

    if solve_name == "solve_ffd_sweeps":
        from karpenter_tpu.ops.ffd_sweeps import (
            _solve_ffd_sweeps_fresh_jit,
            _wavefront_lanes,
        )

        bf = problem_bounds_free(problem)
        wf = _wavefront_lanes()
        return _Spec(
            _solve_ffd_sweeps_fresh_jit,
            (problem, int(max_claims), bf, wf),
            (problem,),
            (f"C{int(max_claims)}", f"bf{int(bf)}", f"wf{int(wf)}"),
        )
    if solve_name == "solve_ffd_sweeps_carried":
        from karpenter_tpu.ops.ffd_sweeps import (
            _solve_ffd_sweeps_carried_jit,
            _wavefront_lanes,
        )

        bf = problem_bounds_free(problem)
        wf = _wavefront_lanes()
        carry = tuple(init)
        return _Spec(
            _solve_ffd_sweeps_carried_jit,
            (problem, carry, int(max_claims), bf, wf),
            (problem, carry),
            (f"C{int(max_claims)}", f"bf{int(bf)}", f"wf{int(wf)}", "carried"),
        )
    if solve_name == "solve_ffd_sweeps_policy":
        from karpenter_tpu.ops.ffd_sweeps import (
            _solve_ffd_sweeps_fresh_policy_jit,
            _wavefront_lanes,
        )
        from karpenter_tpu.solver import ordering

        bf = problem_bounds_free(problem)
        wf = _wavefront_lanes()
        pw = ordering.lane_weights_static()
        return _Spec(
            _solve_ffd_sweeps_fresh_policy_jit,
            (problem, int(max_claims), bf, wf, pw),
            (problem,),
            # the weights digest keys the table: the floats are baked into the
            # executable, so two artifacts must never share a snapshot entry
            (f"C{int(max_claims)}", f"bf{int(bf)}", f"wf{int(wf)}",
             f"pol{ordering.weights_digest()}"),
        )
    if solve_name == "solve_ffd_sweeps_carried_policy":
        from karpenter_tpu.ops.ffd_sweeps import (
            _solve_ffd_sweeps_carried_policy_jit,
            _wavefront_lanes,
        )
        from karpenter_tpu.solver import ordering

        bf = problem_bounds_free(problem)
        wf = _wavefront_lanes()
        pw = ordering.lane_weights_static()
        carry = tuple(init)
        return _Spec(
            _solve_ffd_sweeps_carried_policy_jit,
            (problem, carry, int(max_claims), bf, wf, pw),
            (problem, carry),
            (f"C{int(max_claims)}", f"bf{int(bf)}", f"wf{int(wf)}",
             f"pol{ordering.weights_digest()}", "carried"),
        )
    if solve_name == "shard_sweeps":
        # the mesh-partitioned stacked-sweeps program (shard/solve.py): the
        # jitted fn is reconstructed from the SAME statics the factory cache
        # keys on — default mesh + claim bucket + bounds_free(stacked batch)
        # + wavefront — so the lowered call is the exact dispatch
        from karpenter_tpu.ops.ffd_sweeps import _wavefront_lanes
        from karpenter_tpu.parallel.mesh import (
            default_mesh,
            shard_sweeps_program,
        )
        from karpenter_tpu import shard as shard_flags

        mesh = default_mesh(shard_flags.min_devices())
        if mesh is None:
            return None
        bf = problem_bounds_free(problem)
        wf = _wavefront_lanes()
        fn = shard_sweeps_program(mesh, int(max_claims), bf, wf)
        return _Spec(
            fn,
            (problem,),
            (problem,),
            (
                f"C{int(max_claims)}", f"bf{int(bf)}", f"wf{int(wf)}",
                f"mesh{mesh.devices.size}", "shard",
            ),
        )
    if solve_name == "residual_screen":
        # the incremental consolidation screen (parallel/mesh.py): ``problem``
        # packs (base union problem, carried base-world state, variants tree,
        # shared run-trim indices). with_topo is False by the delta path's
        # standdown contract — a base problem with topology runs never
        # reaches this dispatch
        from karpenter_tpu.ops.ffd_runs import max_run_bucket
        from karpenter_tpu.parallel.mesh import _residual_screen_jit

        base, carried, tree, run_idx = problem
        mr = max_run_bucket(base)
        return _Spec(
            _residual_screen_jit,
            (base, carried, tree, run_idx, mr, False),
            (base, carried, tree, run_idx),
            (f"C{int(max_claims)}", f"mr{int(mr)}", "residual"),
        )
    if solve_name == "relax_place":
        from karpenter_tpu.ops.relax import _relax_place_jit, relax_passes

        bf = problem_bounds_free(problem)
        rp = relax_passes()
        return _Spec(
            _relax_place_jit,
            (problem, int(max_claims), bf, rp),
            (problem,),
            (f"C{int(max_claims)}", f"bf{int(bf)}", f"rp{int(rp)}"),
        )
    if solve_name == "relax2_place":
        # the convex phase-1 program (ops/relax2.py): iteration count and
        # step size are static scan/gradient parameters baked into the
        # executable, so they key the table entry alongside the claim bucket
        from karpenter_tpu.ops.relax import relax_passes
        from karpenter_tpu.ops.relax2 import (
            _relax2_place_jit,
            pgd_iters,
            pgd_step,
        )

        bf = problem_bounds_free(problem)
        rp = relax_passes()
        it = pgd_iters()
        st = pgd_step()
        return _Spec(
            _relax2_place_jit,
            (problem, int(max_claims), bf, it, st, rp),
            (problem,),
            (f"C{int(max_claims)}", f"bf{int(bf)}", f"it{int(it)}",
             f"st{st:g}", f"rp{int(rp)}", "relax2"),
        )
    if solve_name == "verify_gate":
        # the device verification program (verify/device.py): ``problem`` is
        # a GateProblem view and ``init`` carries (GateArgs, bounds_free) —
        # the caller computed bounds_free from the gate's own tensors (plus
        # the published claim rows), so respect it rather than rederiving
        from karpenter_tpu.verify.device import _gate_jit

        ga, bf = init
        return _Spec(
            _gate_jit,
            (problem, ga, bool(bf)),
            (problem, ga),
            (f"C{int(max_claims)}", f"bf{int(bf)}", "gate"),
        )
    if solve_name == "solve_ffd_fused_gate":
        # the DeviceWorld fused solve+gate dispatch (ops/fused.py): ``init``
        # carries (pod_check, bounds_free, wavefront, gate_bounds_free) — the
        # caller derived all three statics from the unpadded spliced problem,
        # so respect them rather than rederiving from the padded world
        from karpenter_tpu.ops.fused import _solve_ffd_fused_gate_jit

        pod_check, bf, wf, gbf = init
        return _Spec(
            _solve_ffd_fused_gate_jit,
            (problem, pod_check, int(max_claims), bool(bf), int(wf), bool(gbf)),
            (problem, pod_check),
            (f"C{int(max_claims)}", f"bf{int(bf)}", f"wf{int(wf)}",
             f"gbf{int(gbf)}", "fused"),
        )
    if solve_name == "patch_world":
        # the DeviceWorld row patch (ops/fused.py): donation of the carried
        # world survives lowering, so the AOT-served call reclaims the prior
        # world's buffers exactly like the plain jit dispatch
        from karpenter_tpu.ops.fused import _patch_world_jit

        return _Spec(
            _patch_world_jit,
            (problem, init),
            (problem, init),
            (f"C{int(max_claims)}", "patch"),
        )
    if solve_name == "solve_ffd":
        from karpenter_tpu.ops.ffd_step import _solve_ffd_fresh_jit, _solve_ffd_jit

        bf = problem_bounds_free(problem)
        if init is None:
            return _Spec(
                _solve_ffd_fresh_jit,
                (problem, int(max_claims), bf),
                (problem,),
                (f"C{int(max_claims)}", f"bf{int(bf)}", "fresh"),
            )
        return _Spec(
            _solve_ffd_jit,
            (problem, init, bf),
            (problem, init),
            (f"bf{int(bf)}", "carried"),
        )
    if solve_name == "solve_ffd_runs":
        from karpenter_tpu.ops.ffd_runs import (
            _solve_ffd_runs_fresh_jit,
            _solve_ffd_runs_jit,
            has_topo_runs,
            max_run_bucket,
        )

        mr = max_run_bucket(problem)
        wt = has_topo_runs(problem)
        if init is None:
            return _Spec(
                _solve_ffd_runs_fresh_jit,
                (problem, int(max_claims), mr, wt),
                (problem,),
                (f"C{int(max_claims)}", f"mr{int(mr)}", f"wt{int(wt)}", "fresh"),
            )
        return _Spec(
            _solve_ffd_runs_jit,
            (problem, init, mr, wt),
            (problem, init),
            (f"mr{int(mr)}", f"wt{int(wt)}", "carried"),
        )
    return None


def _entry_key(fn_name: str, dyn: tuple, statics: Tuple[str, ...]) -> str:
    import jax

    from karpenter_tpu.obs.programs import _digest, flag_digest, isa_tag, shape_digest

    tree = _digest(repr(jax.tree_util.tree_structure(dyn)))
    return "/".join(
        [fn_name, f"s{shape_digest(dyn)}", f"t{tree}", "-".join(statics),
         f"f{flag_digest()}", f"d{_device_tag()}", isa_tag()]
    )


# -- the executable table ------------------------------------------------------


class _Entry:
    __slots__ = ("key", "compiled", "source", "path", "dispatched")

    def __init__(self, key: str, compiled, source: str, path: Optional[str]):
        self.key = key
        self.compiled = compiled
        self.source = source  # "compiled" | "restored"
        self.path = path
        self.dispatched = 0


_lock = threading.Lock()
_table: Dict[str, _Entry] = {}
_warned: set = set()


def _warn_once(tag: str, msg: str, *args) -> None:
    if tag in _warned:
        return
    _warned.add(tag)
    log.warning(msg, *args)


def table_size() -> int:
    with _lock:
        return len(_table)


def restored_count() -> int:
    with _lock:
        return sum(1 for e in _table.values() if e.source == "restored")


def reset_table() -> None:
    """Drop the in-memory table (tests / simulated restart). Snapshot files
    stay on disk — that is the point."""
    with _lock:
        _table.clear()


def clear_restored(reason: str = "probe-failed") -> int:
    """Evict restored executables (probe failure): subsequent dispatches pay
    a fresh trace+compile instead of trusting an executable that could not
    produce a valid placement. Returns how many were dropped."""
    from karpenter_tpu.metrics.registry import AOT_RESTORE, RESTORE_FALLBACK

    with _lock:
        bad = [k for k, e in _table.items() if e.source == "restored"]
        for k in bad:
            del _table[k]
    if bad:
        AOT_RESTORE.inc({"result": reason}, len(bad))
        RESTORE_FALLBACK.inc({"reason": f"aot-{reason}"})
    return len(bad)


class _Handle:
    """One AOT-served dispatch: ``call()`` launches the Compiled (dynamic
    args only; statics are baked), ``source_override`` tells the program
    registry when the executable came off disk instead of a compile."""

    __slots__ = ("entry", "spec")

    def __init__(self, entry: _Entry, spec: _Spec):
        self.entry = entry
        self.spec = spec

    def call(self):
        self.entry.dispatched += 1
        return self.entry.compiled(*self.spec.dyn)

    @property
    def source_override(self) -> Optional[str]:
        if self.entry.source == "restored":
            from karpenter_tpu.obs.programs import SOURCE_RESTORED

            return SOURCE_RESTORED
        return None


def maybe_begin(solve_fn, problem, max_claims: int, init) -> Optional[_Handle]:
    """The jax_backend dispatch hook. Returns a handle when AOT mode serves
    this call (table hit, or miss compiled + persisted write-through), None
    to fall through to the plain jit path. NEVER raises: any AOT-layer error
    is a classified fallback — the solve must not inherit new failure
    modes."""
    if not enabled():
        return None
    from karpenter_tpu.metrics.registry import RESTORE_FALLBACK

    try:
        spec = _call_spec(solve_fn.__name__, problem, max_claims, init)
        if spec is None:
            return None
        key = _entry_key(spec.fn.__name__, spec.dyn, spec.statics)
        with _lock:
            entry = _table.get(key)
        if entry is None:
            compiled = spec.fn.lower(*spec.lower_args).compile()
            entry = _Entry(key, compiled, "compiled", None)
            entry.path = _persist_entry(key, compiled)
            with _lock:
                _table.setdefault(key, entry)
                entry = _table[key]
        return _Handle(entry, spec)
    except Exception as exc:  # noqa: BLE001 — degrade to the jit path
        RESTORE_FALLBACK.inc({"reason": "aot-dispatch-error"})
        _warn_once(
            "dispatch", "aot: dispatch hook degraded to jit path: %s: %s",
            type(exc).__name__, exc,
        )
        return None


# -- snapshot persistence ------------------------------------------------------


def _entry_path(key: str) -> Optional[str]:
    directory = aot_dir()
    if directory is None:
        return None
    from karpenter_tpu.obs.programs import _digest

    return os.path.join(directory, _digest(key, 20) + _FILE_SUFFIX)


def _persist_entry(key: str, compiled) -> Optional[str]:
    """Write-through snapshot of one executable. Best-effort: a snapshot
    failure costs the NEXT process a compile, never this one a solve."""
    from karpenter_tpu.metrics.registry import RESTORE_FALLBACK

    path = _entry_path(key)
    if path is None:
        return None
    try:
        import jax
        from jax.experimental import serialize_executable as se

        from karpenter_tpu.obs.programs import flag_digest, isa_tag
        from karpenter_tpu.utils import persist

        payload_bytes, in_tree, out_tree = se.serialize(compiled)
        blob = pickle.dumps(
            {"key": key, "serialized": (payload_bytes, in_tree, out_tree)},
            protocol=pickle.HIGHEST_PROTOCOL,
        )
        persist.write_framed(
            path, blob, kind="aot-entry", version=AOT_VERSION,
            meta={
                "key": key,
                "isa": isa_tag(),
                "flags": flag_digest(),
                "device": _device_tag(),
                "jax": jax.__version__,
            },
        )
        return path
    except Exception as exc:  # noqa: BLE001
        RESTORE_FALLBACK.inc({"reason": "aot-persist-error"})
        _warn_once(
            "persist", "aot: snapshot write failed (restore disabled for "
            "this program): %s: %s", type(exc).__name__, exc,
        )
        return None


def restore() -> Dict:
    """Load every snapshot entry matching this host's ISA / flag config /
    jax version into the table as ``restored`` executables. Every entry
    resolves to exactly one classified result — restored, or a failure
    reason — so no recovery is ever 'unknown'. Returns a summary dict."""
    from karpenter_tpu.metrics.registry import AOT_RESTORE, RESTORE_FALLBACK
    from karpenter_tpu.utils.persist import PersistError, load_framed

    t0 = time.perf_counter()
    summary: Dict = {"entries": 0, "restored": 0, "failures": {}}

    def fail(reason: str) -> None:
        summary["failures"][reason] = summary["failures"].get(reason, 0) + 1
        AOT_RESTORE.inc({"result": reason})
        RESTORE_FALLBACK.inc({"reason": f"aot-{reason}"})

    directory = aot_dir()
    if not enabled() or directory is None or not os.path.isdir(directory):
        summary["seconds"] = time.perf_counter() - t0
        return summary
    import jax
    from jax.experimental import serialize_executable as se

    from karpenter_tpu.obs.programs import flag_digest, isa_tag

    for name in sorted(os.listdir(directory)):
        if not name.endswith(_FILE_SUFFIX):
            continue
        summary["entries"] += 1
        path = os.path.join(directory, name)
        try:
            header, payload = load_framed(
                path, kind="aot-entry", min_version=AOT_VERSION
            )
        except PersistError as exc:
            fail(exc.reason)
            continue
        meta = header.get("meta", {})
        if meta.get("isa") != isa_tag() or meta.get("device") != _device_tag():
            fail("isa-mismatch")
            continue
        if meta.get("flags") != flag_digest():
            fail("flag-mismatch")
            continue
        if meta.get("jax") != jax.__version__:
            fail("version-skew")
            continue
        try:
            blob = pickle.loads(payload)
            key = blob["key"]
            payload_bytes, in_tree, out_tree = blob["serialized"]
            compiled = se.deserialize_and_load(payload_bytes, in_tree, out_tree)
        except Exception as exc:  # noqa: BLE001 — checksummed, but be exhaustive
            fail("deserialize-error")
            _warn_once(
                "deserialize", "aot: entry %s failed to deserialize: %s: %s",
                name, type(exc).__name__, exc,
            )
            continue
        with _lock:
            _table[key] = _Entry(key, compiled, "restored", path)
        summary["restored"] += 1
        AOT_RESTORE.inc({"result": "restored"})
    summary["seconds"] = time.perf_counter() - t0
    return summary


def snapshot_files() -> List[str]:
    directory = aot_dir()
    if directory is None or not os.path.isdir(directory):
        return []
    return sorted(
        os.path.join(directory, n)
        for n in os.listdir(directory)
        if n.endswith(_FILE_SUFFIX)
    )


# -- recovery state machine (consulted by /readyz) -----------------------------

PHASE_IDLE = "idle"
PHASE_RESTORING = "restoring"
PHASE_PROBING = "probing"
PHASE_READY = "ready"
PHASE_FAILED = "failed"

_recovery_lock = threading.Lock()
_recovery_phase = PHASE_IDLE
_last_recovery: Optional[Dict] = None


def set_recovery_phase(phase: str) -> None:
    global _recovery_phase
    with _recovery_lock:
        _recovery_phase = phase


def recovery_phase() -> str:
    with _recovery_lock:
        return _recovery_phase


def recovery_blocking() -> bool:
    """True while a recovery is in flight: /readyz must stay false until the
    restored executables pass a probe solve. ``failed`` does NOT block —
    recovery degrades to cold compiles, it never holds the process hostage."""
    with _recovery_lock:
        return _recovery_phase in (PHASE_RESTORING, PHASE_PROBING)


def finish_recovery(record: Optional[Dict], phase: str) -> None:
    global _recovery_phase, _last_recovery
    with _recovery_lock:
        _recovery_phase = phase
        if record is not None:
            _last_recovery = dict(record)


def last_recovery() -> Optional[Dict]:
    """The /statusz ``last_restart_recovery`` payload (None before any)."""
    with _recovery_lock:
        return dict(_last_recovery) if _last_recovery is not None else None


def reset_recovery_for_tests() -> None:
    global _recovery_phase, _last_recovery
    with _recovery_lock:
        _recovery_phase = PHASE_IDLE
        _last_recovery = None
