"""Solver pre-warm: compile the standard shape buckets ahead of traffic.

The solver compiles one executable per (pod-bucket, lane-bucket, claim-slot,
run-mode) combination (ops/padding.py pow2 buckets; solver/jax_backend.py
bucketed recompiles). A fresh process therefore pays tens of seconds of XLA
compile on its first reconcile — a production liability for a 10 s-poll
disruption controller. Warming solves two tiny synthetic batches (one
topology-free, one with a zonal spread) through the REAL backend entrypoint,
so the executables land in the in-process jit cache and, on TPU, in the
persistent compile cache (utils/jaxtools.py) where every future process
reloads them in well under a second.

The reference has no equivalent knob (Go compiles nothing at runtime); this
is the TPU-native cost the framework pays for its batched solver, amortized
at operator startup instead of first traffic (VERDICT r2 weak #4).

Program-keying flags must MATCH between the warming process and the serving
process: ``KARPENTER_TPU_WAVEFRONT`` (and ``_WIDTH``) is a static jit
argument, so the wavefront and non-wavefront narrow steps are DISTINCT
executables — warming with the flag in one position buys nothing for a
server running the other. The same holds for ``KARPENTER_TPU_PACKED_GATES``,
the stride/window knobs, and ``KARPENTER_TPU_RELAX`` (and ``_RELAX_PASSES``):
with the relax flag on, every warm batch routes through the two-phase entry,
so the relaxation program (ops/relax.py) and the carried repair sweeps
compile — and AOT-serialize/restore (solver/aot.py) — at the SAME pod and
claim buckets as the narrow step; with it off, the warms compile the plain
sweeps program instead, so a mismatched server recompiles on first contact
either way. ``KARPENTER_TPU_RELAX2`` (and ``_RELAX2_ITERS``/``_RELAX2_STEP``,
both static jit arguments baked into the program key) follows the identical
contract for the convex phase-1 solve (ops/relax2.py): flag-on warms compile
and AOT-snapshot the projected-gradient program plus the carried repair at
the warmed buckets; a server with a different iteration count or step size
keys to a different executable and recompiles. With ``KARPENTER_TPU_DEVICE_GATE`` on (the default), each warm
solve additionally drives the device verification gate (verify/), so the
gate program compiles and AOT-serializes at the same buckets too.
``KARPENTER_TPU_ORDER_POLICY`` joins the same contract: with it on, every
warm routes through the policy solve entries (solve_ffd_sweeps_policy and
the carried repair twin), whose baked-in scorer weights are part of the
program — so the warming process must also see the SAME weights artifact
(solver/ordering.py) as the server, or the warmed executables are keyed to
the wrong weight digest and the server recompiles.
"""

from __future__ import annotations

from typing import Optional, Sequence


def bucket_ladder(max_pods: int) -> list:
    """Every pod-axis bucket (ops/padding.py pod_axis_bucket) up to
    ``max_pods`` — the shapes a workload that grows to max_pods will compile
    along the way."""
    from karpenter_tpu.ops.padding import pod_axis_bucket

    out, n = [], 9
    while n <= max_pods:
        b = pod_axis_bucket(n)
        out.append(b)
        n = b + 1
    return out


def claim_ladder(max_claims: int) -> list:
    """Every claim-slot bucket (ops/padding.py claim_axis_bucket) up to
    ``max_claims`` — the shapes a slot-overflow escalation walks through.
    With claim-axis windowing (KARPENTER_TPU_CLAIM_WINDOW, default on) the
    ladder above 128 is 160/192/224/256/...; with it off, pow2 doubles."""
    from karpenter_tpu.ops.padding import claim_axis_bucket

    out, n = [], 1
    while n <= max_claims:
        b = claim_axis_bucket(n)
        out.append(b)
        n = b + 1
    return out


def prewarm_claim_buckets(
    solver=None, max_claims: int = 256, instance_types_n: int = 100, catalog=None
) -> int:
    """Compile the claim-slot escalation ladder up to ``max_claims``: one
    sweeps executable per claim bucket. A claim-heavy batch that overflows
    its slots escalates through exactly these shapes (jax_backend's
    _SlotOverflow retry), and every step is a fresh XLA compile unless it
    was warmed here — the 256-slot program alone used to be the "cliff"
    compile. Each bucket C is warmed by solving C pods with claim_slots
    pinned to C through the REAL backend entrypoint: the executable cache
    keys on shapes, so the solve doesn't need to open C claims. Returns the
    number of buckets warmed; failures stop the ladder (warming is an
    optimization, never a liveness dependency)."""
    import random

    from karpenter_tpu.apis.nodepool import NodePool
    from karpenter_tpu.apis.objects import Container, ObjectMeta, Pod, PodSpec
    from karpenter_tpu.cloudprovider.fake import instance_types
    from karpenter_tpu.solver.encode import template_from_nodepool
    from karpenter_tpu.solver.jax_backend import JaxSolver

    if solver is None:
        solver = JaxSolver()
    its = catalog if catalog else instance_types(instance_types_n)
    tpl = template_from_nodepool(
        NodePool(metadata=ObjectMeta(name="prewarm-claims")), its, range(len(its))
    )
    rng = random.Random(1)
    warmed = 0
    from karpenter_tpu.obs import trace

    with trace.cycle("warmup", kind="claim-ladder", max_claims=max_claims):
        for c in claim_ladder(max_claims):
            pods = [
                Pod(
                    metadata=ObjectMeta(name=f"warm-claims-{c}-{i}"),
                    spec=PodSpec(
                        containers=[
                            Container(requests={"cpu": rng.choice([0.1, 0.5, 1.0])})
                        ]
                    ),
                )
                for i in range(c)
            ]
            try:
                # the ladder ascends, so pinning claim_slots selects bucket c
                # exactly (the backend caps at claim_axis_bucket(len(pods)) == c)
                solver.claim_slots = c
                _warm_gate(solver.solve(pods, its, [tpl]), pods, its, [tpl])
                warmed += 1
            except Exception:
                return warmed
    return warmed


def prewarm_solver(
    solver=None,
    pod_buckets: Sequence[int] = (9, 33),
    instance_types_n: int = 100,
    max_pods: int = 0,
    catalog=None,
) -> int:
    """Compile the small standard buckets (pow2 pads: 16 and 64 pods) with
    and without topology interaction, plus — when ``max_pods`` is set (the
    operator's --prewarm-max-pods) — every pod bucket up to it. Returns the
    number of batches solved. Safe to call from a background thread; failures
    are swallowed — warming is an optimization, never a liveness dependency.

    By default the warm uses a synthetic instance-type catalog, which covers
    only the synthetic shape buckets: a production batch whose padded
    lane/type buckets differ still compiles its own executables on first
    contact. Pass ``catalog`` (the operator's LIVE instance types, as
    maybe_prewarm_in_background does) to warm the exact lane/type buckets
    production encodings will hit — the advisor-r3 gap where synthetic
    warming missed the real workload's shapes."""
    import random

    from karpenter_tpu.apis import labels as wk
    from karpenter_tpu.apis.nodepool import NodePool
    from karpenter_tpu.apis.objects import (
        DO_NOT_SCHEDULE,
        LabelSelector,
        ObjectMeta,
        TopologySpreadConstraint,
    )
    from karpenter_tpu.cloudprovider.fake import instance_types
    from karpenter_tpu.solver.encode import template_from_nodepool
    from karpenter_tpu.solver.jax_backend import JaxSolver

    from karpenter_tpu.apis.objects import Container, Pod, PodSpec

    if solver is None:
        solver = JaxSolver()
    its = catalog if catalog else instance_types(instance_types_n)
    tpl = template_from_nodepool(
        NodePool(metadata=ObjectMeta(name="prewarm")), its, range(len(its))
    )
    rng = random.Random(0)

    def make(n, topo: bool):
        pods = []
        for i in range(n):
            p = Pod(
                metadata=ObjectMeta(name=f"warm-{n}-{i}", labels={"warm": "w"}),
                spec=PodSpec(
                    containers=[Container(requests={"cpu": rng.choice([0.1, 0.5, 1.0])})]
                ),
            )
            if topo and i % 3 == 0:
                # a DoNotSchedule zonal spread drives the RUN_TOPO /
                # topology-gate programs, the slowest-compiling family
                p.spec.topology_spread_constraints = [
                    TopologySpreadConstraint(
                        max_skew=1,
                        topology_key=wk.LABEL_TOPOLOGY_ZONE,
                        when_unsatisfiable=DO_NOT_SCHEDULE,
                        label_selector=LabelSelector(match_labels={"warm": "w"}),
                    )
                ]
            pods.append(p)
        return pods

    solved = 0
    # the topology-free and topology programs are distinct executables
    # (G=0 early-exits statically; has_topo_runs is a static argument), and
    # each pod bucket is its own shape — warm the cross product. The large
    # ladder warms topology shapes only (the expensive family; topology-free
    # large batches reuse most of the work via the persistent cache).
    from karpenter_tpu.ops.padding import pod_axis_bucket

    buckets = list(pod_buckets)
    warmed_shapes = {pod_axis_bucket(b) for b in buckets}
    ladder = [b for b in bucket_ladder(max_pods) if b not in warmed_shapes]
    from karpenter_tpu.obs import trace

    with trace.cycle("warmup", kind="solver", max_pods=max_pods):
        for n in buckets:
            for topo in (False, True):
                try:
                    pods = make(n, topo)
                    _warm_gate(solver.solve(pods, its, [tpl]), pods, its, [tpl])
                    solved += 1
                except Exception:
                    return solved
        for n in ladder:
            try:
                pods = make(n, True)
                _warm_gate(solver.solve(pods, its, [tpl]), pods, its, [tpl])
                solved += 1
            except Exception:
                return solved
    return solved


def _warm_gate(result, pods, its, tpls) -> None:
    """Drive the device verification gate over a warm solve result so its
    program compiles (and AOT-serializes) at the same pod/claim buckets the
    solve itself warmed — the gate is on the serving hot path whenever
    KARPENTER_TPU_DEVICE_GATE is on. Failures are swallowed like every other
    warm step."""
    try:
        from karpenter_tpu import verify

        if verify.enabled() and getattr(result, "verify_ctx", None) is not None:
            verify.full_gate(result, pods, its, tpls)
    except Exception:
        pass


def prewarm_device_world(
    solver=None,
    pod_buckets: Sequence[int] = (9, 33),
    instance_types_n: int = 100,
    catalog=None,
) -> int:
    """Compile the DeviceWorld programs (ops/fused.py: the patched-scatter
    ``patch_world`` and the fused ``solve_ffd_fused_gate``) at the standard
    pod buckets by driving two real flag-on cycles per bucket: the first
    adopts (fused compile), the second swaps one pod so the delta splices a
    row and the patch program compiles too. No-op unless
    KARPENTER_TPU_DEVICE_WORLD is on — the programs only exist on that path.
    The warm templates carry a finite remaining-resource limit so the
    relax-applicable standdown can't silently skip the compile (relax never
    fires against limited templates; the executable cache keys on shapes, so
    production's limitless templates still hit these executables). Returns
    cycles served by the resident path; both entries also flow through the
    AOT snapshot table (solver/aot.py) when KARPENTER_TPU_STATE_DIR is set,
    so a restarted process restores them without a compile."""
    import dataclasses
    import random

    from karpenter_tpu.apis.nodepool import NodePool
    from karpenter_tpu.apis.objects import Container, ObjectMeta, Pod, PodSpec
    from karpenter_tpu.cloudprovider.fake import instance_types
    from karpenter_tpu.solver.encode import template_from_nodepool
    from karpenter_tpu.solver.jax_backend import JaxSolver
    from karpenter_tpu.streaming import device_world

    if not device_world.enabled():
        return 0
    if solver is None:
        solver = JaxSolver()
    its = catalog if catalog else instance_types(instance_types_n)
    tpl = template_from_nodepool(
        NodePool(metadata=ObjectMeta(name="prewarm-world")), its, range(len(its))
    )
    tpl = dataclasses.replace(tpl, remaining_resources={"cpu": 1e12})
    rng = random.Random(2)

    def make(n):
        return [
            Pod(
                metadata=ObjectMeta(name=f"warm-world-{n}-{i}"),
                spec=PodSpec(
                    containers=[
                        Container(requests={"cpu": rng.choice([0.1, 0.5, 1.0])})
                    ]
                ),
            )
            for i in range(n)
        ]

    served = 0
    from karpenter_tpu.obs import trace

    with trace.cycle("warmup", kind="device-world"):
        for n in pod_buckets:
            try:
                pods = make(n)
                solver.solve(pods, its, [tpl])  # adopt: fused program compiles
                dw = solver._device_world
                if dw is None or dw.last_outcome is None or (
                    dw.last_outcome.startswith("standdown")
                ):
                    continue
                served += 1
                pods2 = list(pods)
                pods2[0] = make(1)[0]  # one fresh row: patch program compiles
                solver.solve(pods2, its, [tpl])
                if dw.last_outcome in ("patched", "repatched"):
                    served += 1
                # the next bucket must re-adopt, not stand down on drift noise
                solver.reset_streaming_state()
            except Exception:
                return served
    return served


def prewarm_screen(n_candidates: int) -> bool:
    """Compile the consolidation screen program for the eighth-pow2
    candidate buckets up to ``n_candidates`` (disruption/batch.py pads the
    subset axis with ops/padding.screen_axis_bucket, so these are the
    executables a reconcile pass will request). When the incremental screen
    is on (KARPENTER_TPU_SCREEN_DELTA) the scorer routes through the
    residual-lane program instead, so this same walk compiles that program's
    lane/run buckets too. Synthetic-shape caveat as in prewarm_solver."""
    from karpenter_tpu.disruption.batch import bench_candidate_scoring
    from karpenter_tpu.obs import trace
    from karpenter_tpu.ops.padding import screen_axis_bucket

    try:
        with trace.cycle("warmup", kind="screen", candidates=n_candidates):
            n = 8
            while n <= n_candidates:
                b = screen_axis_bucket(n)
                # mesh="auto" matches production score_subsets: on multi-device
                # hosts the sharded program (and its device-rounded B) is the
                # executable a reconcile pass will actually request
                bench_candidate_scoring(b, mesh="auto")
                n = b + 1
        return True
    except Exception:
        return False


def prewarm_shard(n_pods: int = 256, instance_types_n: int = 100, catalog=None) -> bool:
    """Compile the mesh-partitioned solve program (KARPENTER_TPU_SHARD,
    shard/solve.py) at the per-device bucket a fleet batch of ``n_pods``
    splittable pods lands on. The shard program is its own executable family
    (shard_map over the mesh; cached per mesh/claim-bucket/bounds_free/
    wavefront in parallel/mesh.py), so an unwarmed server pays its first
    fleet-scale compile on the scale-out burst it exists to absorb. No-op
    (False) when the flag is off or the host has a single device; failures
    are swallowed — warming is an optimization, never a liveness
    dependency."""
    import random

    from karpenter_tpu import shard as shard_flags
    from karpenter_tpu.apis.nodepool import NodePool
    from karpenter_tpu.apis.objects import Container, ObjectMeta, Pod, PodSpec
    from karpenter_tpu.cloudprovider.fake import instance_types
    from karpenter_tpu.obs import trace
    from karpenter_tpu.parallel.mesh import default_mesh
    from karpenter_tpu.solver.encode import template_from_nodepool
    from karpenter_tpu.solver.jax_backend import JaxSolver

    if not shard_flags.enabled():
        return False
    if default_mesh(shard_flags.min_devices()) is None:
        return False
    its = catalog if catalog else instance_types(instance_types_n)
    tpl = template_from_nodepool(
        NodePool(metadata=ObjectMeta(name="prewarm-shard")), its, range(len(its))
    )
    rng = random.Random(2)
    pods = [
        Pod(
            metadata=ObjectMeta(name=f"warm-shard-{i}"),
            spec=PodSpec(
                containers=[Container(requests={"cpu": rng.choice([0.1, 0.5, 1.0])})]
            ),
        )
        for i in range(max(n_pods, shard_flags.min_pods()))
    ]
    try:
        with trace.cycle("warmup", kind="shard", pods=len(pods)):
            solver = JaxSolver()
            solver.solve(pods, its, [tpl])
            return bool(
                solver.last_shard and solver.last_shard.get("reason") is None
            )
    except Exception:
        return False


def _probe_solve(n_pods: int = 12, instance_types_n: int = 20) -> bool:
    """One small solve through the REAL backend entrypoint, checked hard:
    every pod accounted exactly once and the fast validator gate clean. This
    is what restored AOT executables must pass before /readyz goes true — a
    deserialized program that launches but computes garbage fails here, and
    the recovery degrades to cold compiles instead of serving it."""
    import random

    from karpenter_tpu.apis.nodepool import NodePool
    from karpenter_tpu.apis.objects import Container, ObjectMeta, Pod, PodSpec
    from karpenter_tpu.cloudprovider.fake import instance_types
    from karpenter_tpu.solver import validator as val
    from karpenter_tpu.solver.encode import template_from_nodepool
    from karpenter_tpu.solver.jax_backend import JaxSolver

    its = instance_types(instance_types_n)
    tpl = template_from_nodepool(
        NodePool(metadata=ObjectMeta(name="recovery-probe")), its, range(len(its))
    )
    rng = random.Random(3)
    pods = [
        Pod(
            metadata=ObjectMeta(name=f"probe-{i}"),
            spec=PodSpec(
                containers=[Container(requests={"cpu": rng.choice([0.1, 0.5, 1.0])})]
            ),
        )
        for i in range(n_pods)
    ]
    result = JaxSolver().solve(pods, its, [tpl])
    seen: list = []
    for idxs in result.node_pods.values():
        seen.extend(idxs)
    for c in result.new_claims:
        seen.extend(c.pod_indices)
    seen.extend(result.failures)
    if sorted(seen) != list(range(n_pods)):
        return False
    return not val.validate_result(result, pods, its, [tpl], level="fast")


def restore_and_probe() -> Optional[dict]:
    """The restart-recovery sequence, driving solver/aot.py's phase machine
    (idle -> restoring -> probing -> ready|failed):

      1. deserialize every matching AOT executable snapshot into the table
         (``restored`` cache source, classified failure counters);
      2. when anything restored, run a probe solve — the standard small
         bucket, which the warmup ladder snapshots first, so the probe
         actually exercises a restored executable — and on failure evict
         every restored entry (classified ``probe-failed``): traffic then
         pays cold compiles, never trusts an unproven deserialization;
      3. record the recovery (wall seconds into
         ``solver_restart_recovery_seconds``, trace id + summary into
         ``aot.last_recovery()`` for /statusz ``last_restart_recovery``).

    /readyz is held false by ``aot.recovery_blocking()`` for the whole
    sequence. Returns the recovery record, or None when AOT restore is off.
    Never raises: recovery degrades, it does not take the process down."""
    import logging
    import time

    from karpenter_tpu.solver import aot

    if not aot.enabled():
        return None
    from karpenter_tpu.metrics.registry import RESTART_RECOVERY_SECONDS
    from karpenter_tpu.obs import trace

    log = logging.getLogger(__name__)
    t0 = time.perf_counter()
    record: dict = {}
    aot.set_recovery_phase(aot.PHASE_RESTORING)
    try:
        with trace.cycle("recovery", kind="restart"):
            record["trace_id"] = trace.current_trace_id()
            record["aot"] = aot.restore()
            aot.set_recovery_phase(aot.PHASE_PROBING)
            if record["aot"]["restored"]:
                ok = _probe_solve()
                record["probe"] = "passed" if ok else "failed"
                if not ok:
                    record["evicted"] = aot.clear_restored()
            else:
                record["probe"] = "skipped"
        phase = (
            aot.PHASE_FAILED if record.get("probe") == "failed" else aot.PHASE_READY
        )
    except Exception:  # noqa: BLE001 — recovery is never a liveness dependency
        log.warning("restart recovery failed", exc_info=True)
        record["probe"] = record.get("probe", "error")
        phase = aot.PHASE_FAILED
    record["phase"] = phase
    record["seconds"] = round(time.perf_counter() - t0, 4)
    RESTART_RECOVERY_SECONDS.observe(record["seconds"])
    aot.finish_recovery(record, phase)
    log.info("restart recovery: %s", record)
    return record


def maybe_recover_in_background() -> Optional["object"]:
    """Operator.start() hook: when AOT restore is enabled, mark recovery as
    blocking SYNCHRONOUSLY (so a /readyz probe racing the thread start still
    sees not-ready) and run :func:`restore_and_probe` on a daemon thread."""
    import threading

    from karpenter_tpu.solver import aot

    if not aot.enabled():
        return None
    aot.set_recovery_phase(aot.PHASE_RESTORING)
    t = threading.Thread(
        target=restore_and_probe, daemon=True,
        name="karpenter-tpu/restart-recovery",
    )
    t.start()
    return t


def warmup_ready(thread: Optional["object"]) -> bool:
    """Readiness predicate for /readyz: True once the background warm
    finished (or never ran — a skipped warm must not hold readiness
    hostage, it is an optimization, not a liveness dependency)."""
    return thread is None or not thread.is_alive()


def persistent_cache_enabled() -> bool:
    """Whether the cross-process compile cache is active
    (utils/jaxtools.py enable_compilation_cache)."""
    try:
        import jax

        return bool(jax.config.jax_compilation_cache_dir)
    except Exception:
        return False


def _on_accelerator() -> bool:
    try:
        import jax

        return jax.devices()[0].platform != "cpu"
    except Exception:
        return False


def maybe_prewarm_in_background(options, cloud_provider=None) -> Optional["object"]:
    """Operator.start() hook: warm in a daemon thread when enabled, the
    persistent cache is active, and an accelerator backend is attached. CPU
    runs skip — production CPU operators still benefit from the on-disk cache
    populated by their first real solve, while test/dev CPU runs (the only
    place start() runs on CPU today) must not burn the single-core host on
    background compiles. The platform probe (jax.devices() forces PJRT
    backend init, seconds on a tunneled TPU) runs INSIDE the daemon thread so
    start() never blocks on it.

    When a ``cloud_provider`` is given, its live catalog drives the warm so
    the compiled lane/type buckets match what production encodings request."""
    import threading

    if not getattr(options, "prewarm_solver", True):
        return None
    if not persistent_cache_enabled():
        return None

    def probe_then_warm():
        import logging

        log = logging.getLogger(__name__)
        if not _on_accelerator():
            return
        catalog = None
        if cloud_provider is not None:
            try:
                catalog = cloud_provider.get_instance_types(None)
            except Exception:
                # synthetic shapes still warm the machinery, but the
                # production lane/type buckets will recompile on first
                # contact — make the downgrade visible
                log.warning(
                    "prewarm: live catalog unavailable, warming synthetic "
                    "shape buckets only", exc_info=True
                )
        try:
            # warming is an optimization, never a liveness dependency — a
            # catalog the encoder rejects must not kill the thread or skip
            # the screen warm below
            prewarm_solver(
                max_pods=getattr(options, "prewarm_max_pods", 0),
                catalog=catalog,
            )
        except Exception:
            log.warning("prewarm: solver warm failed", exc_info=True)
        n_claims = getattr(options, "prewarm_claim_slots", 0)
        if n_claims:
            try:
                # claim-heavy workloads escalate through the claim-bucket
                # ladder; warming it makes each _SlotOverflow retry a cache
                # hit instead of a fresh compile
                prewarm_claim_buckets(max_claims=n_claims, catalog=catalog)
            except Exception:
                log.warning("prewarm: claim-ladder warm failed", exc_info=True)
        n_screen = getattr(options, "prewarm_screen_candidates", 0)
        if n_screen:
            try:
                prewarm_screen(n_screen)
            except Exception:
                log.warning("prewarm: screen warm failed", exc_info=True)
        try:
            # fleet-scale partitioned program (no-op unless KARPENTER_TPU_SHARD
            # is on and a mesh exists): first scale-out burst should hit a
            # warm executable, not a cold shard_map compile
            prewarm_shard(catalog=catalog)
        except Exception:
            log.warning("prewarm: shard warm failed", exc_info=True)
        try:
            # device-resident continuous solve (no-op unless
            # KARPENTER_TPU_DEVICE_WORLD is on): the steady-state churn path
            # should never pay its first patch/fused compile mid-serving
            prewarm_device_world(catalog=catalog)
        except Exception:
            log.warning("prewarm: device-world warm failed", exc_info=True)
        # the startup compile bill, itemized (obs/programs.py): how many
        # programs the warm compiled, what they cost, and how many came
        # back from the persistent cache instead of a cold trace
        from karpenter_tpu.obs import programs

        if programs.enabled():
            s = programs.registry().summary()
            log.info(
                "prewarm: %d programs, %d launches, %.1fs compile "
                "(by source: %s)",
                s["programs"], s["launches"], s["compile_s"],
                s["by_source"],
            )

    t = threading.Thread(
        target=probe_then_warm, daemon=True, name="karpenter-tpu/solver-prewarm"
    )
    t.start()
    return t
