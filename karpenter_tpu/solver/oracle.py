"""Pure-Python FFD oracle.

Mirror of the reference scheduler's placement semantics
(scheduler.go:238-285, nodeclaim.go:65-119, existingnode.go:64-124), used as
the golden model the JAX solver is property-tested against, and available as
the ``oracle`` solver backend for debugging.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.objects import Pod
from karpenter_tpu.cloudprovider.types import InstanceType
from karpenter_tpu.scheduling import Requirements, pod_requirements
from karpenter_tpu.solver.backend import (
    FAIL_INCOMPATIBLE,
    Placement,
    SolveResult,
    SolverBackend,
)
from karpenter_tpu.solver.encode import NodeInfo, TemplateInfo, ffd_order
from karpenter_tpu.utils import resources as res


def _fits(requests: Dict[str, float], available: Dict[str, float]) -> bool:
    # same tolerance as ops/masks.py fits() so both backends agree bit-for-bit
    for name, q in requests.items():
        avail = available.get(name, 0.0)
        if q > avail + 1e-6 + 1e-6 * abs(avail):
            return False
    return True


def _has_offering(it: InstanceType, reqs: Requirements) -> bool:
    return len(it.offerings.available().requirements(reqs)) > 0


@dataclass
class _OpenClaim:
    template_index: int
    template: TemplateInfo
    requirements: Requirements
    requests: Dict[str, float]
    it_indices: List[int]
    pod_indices: List[int] = field(default_factory=list)
    seq: int = 0


@dataclass
class _NodeBin:
    info: NodeInfo
    requirements: Requirements
    requests: Dict[str, float]
    pod_indices: List[int] = field(default_factory=list)


class OracleSolver(SolverBackend):
    def __init__(self, well_known: frozenset = wk.WELL_KNOWN_LABELS):
        self.well_known = well_known

    def solve(
        self,
        pods: Sequence[Pod],
        instance_types: Sequence[InstanceType],
        templates: Sequence[TemplateInfo],
        nodes: Sequence[NodeInfo] = (),
        pod_requirements_override: Optional[Sequence[Requirements]] = None,
    ) -> SolveResult:
        pod_reqs = (
            list(pod_requirements_override)
            if pod_requirements_override is not None
            else [pod_requirements(p) for p in pods]
        )
        order = ffd_order(pods)

        node_bins = [
            _NodeBin(
                info=n,
                requirements=n.requirements.copy(),
                requests=dict(n.daemon_overhead),
            )
            for n in nodes
        ]
        claims: List[_OpenClaim] = []
        result = SolveResult()

        for pi in order:
            pod, reqs = pods[pi], pod_reqs[pi]
            requests = {**res.pod_requests(pod), res.PODS: 1.0}
            if self._try_nodes(pi, pod, reqs, requests, node_bins):
                continue
            if self._try_claims(pi, pod, reqs, requests, claims, instance_types):
                continue
            if self._try_templates(pi, pod, reqs, requests, claims, templates, instance_types):
                continue
            result.failures[pi] = FAIL_INCOMPATIBLE

        for nb in node_bins:
            if nb.pod_indices:
                result.node_pods[nb.info.name] = nb.pod_indices
        for claim in claims:
            result.new_claims.append(
                Placement(
                    template_index=claim.template_index,
                    nodepool_name=claim.template.nodepool_name,
                    pod_indices=claim.pod_indices,
                    instance_type_indices=claim.it_indices,
                    requirements=claim.requirements,
                    requests=claim.requests,
                )
            )
        return result

    # -- placement attempts, in reference priority order ----------------------

    def _try_nodes(self, pi, pod, reqs, requests, node_bins) -> bool:
        for nb in node_bins:
            if nb.info.taints.tolerates(pod):
                continue
            merged = res.merge(nb.requests, requests)
            if not _fits(merged, nb.info.available):
                continue
            # strict Compatible — no well-known allowance (existingnode.go:94)
            if not nb.requirements.is_compatible(reqs):
                continue
            nb.requests = merged
            nb.requirements.add(*reqs.values())
            nb.pod_indices.append(pi)
            return True
        return False

    def _try_claims(self, pi, pod, reqs, requests, claims, instance_types) -> bool:
        for claim in sorted(claims, key=lambda c: (len(c.pod_indices), c.seq)):
            if claim.template.taints.tolerates(pod):
                continue
            if not claim.requirements.is_compatible(reqs, self.well_known):
                continue
            narrowed = claim.requirements.copy()
            narrowed.add(*reqs.values())
            merged = res.merge(claim.requests, requests)
            surviving = [
                ti
                for ti in claim.it_indices
                if not instance_types[ti].requirements.intersects(narrowed)
                and _fits(merged, instance_types[ti].allocatable())
                and _has_offering(instance_types[ti], narrowed)
            ]
            if not surviving:
                continue
            claim.requirements = narrowed
            claim.requests = merged
            claim.it_indices = surviving
            claim.pod_indices.append(pi)
            return True
        return False

    def _try_templates(self, pi, pod, reqs, requests, claims, templates, instance_types) -> bool:
        for ti_idx, tpl in enumerate(templates):
            if tpl.taints.tolerates(pod):
                continue
            if not tpl.requirements.is_compatible(reqs, self.well_known):
                continue
            narrowed = tpl.requirements.copy()
            narrowed.add(*reqs.values())
            merged = res.merge(tpl.daemon_overhead, requests)
            surviving = [
                t
                for t in tpl.instance_type_indices
                if not instance_types[t].requirements.intersects(narrowed)
                and _fits(merged, instance_types[t].allocatable())
                and _has_offering(instance_types[t], narrowed)
            ]
            if not surviving:
                continue
            claims.append(
                _OpenClaim(
                    template_index=ti_idx,
                    template=tpl,
                    requirements=narrowed,
                    requests=merged,
                    it_indices=surviving,
                    pod_indices=[pi],
                    seq=len(claims),
                )
            )
            return True
        return False
