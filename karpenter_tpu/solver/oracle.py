"""Pure-Python FFD oracle.

Mirror of the reference scheduler's placement semantics
(scheduler.go:140-285, nodeclaim.go:65-119, existingnode.go:64-124,
topology.go, preferences.go), used as the golden model the JAX solver is
property-tested against, and available as the ``oracle`` solver backend.

The relax-and-retry loop is pass-structured: each pass attempts every queued
pod once in FFD order against persistent bin state; after a pass, every failed
pod is relaxed one notch (preferences.go ladder) and retried. The reference
interleaves retries within one queue using cycle detection
(scheduler.go:150-170, queue.go:46-70) — the pass structure reaches the same
fixed point and both backends here implement it identically.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.objects import IN, Pod
from karpenter_tpu.cloudprovider.types import InstanceType
from karpenter_tpu.provisioning.preferences import Preferences
from karpenter_tpu.provisioning.topology import Topology
from karpenter_tpu.scheduling import (
    Requirement,
    Requirements,
    has_preferred_node_affinity,
    pod_requirements,
    strict_pod_requirements,
)
from karpenter_tpu.scheduling.hostports import HostPort, get_host_ports
from karpenter_tpu.solver.backend import (
    FAIL_INCOMPATIBLE,
    Placement,
    SolveResult,
    SolverBackend,
)
from karpenter_tpu.solver.encode import (
    NodeInfo,
    TemplateInfo,
    claim_hostname,
    domains_from_instance_types,
    ffd_order,
)
from karpenter_tpu.utils import resources as res


def _fits(requests: Dict[str, float], available: Dict[str, float]) -> bool:
    # same tolerance as ops/masks.py fits() so both backends agree bit-for-bit
    for name, q in requests.items():
        avail = available.get(name, 0.0)
        if q > avail + 1e-6 + 1e-6 * abs(avail):
            return False
    return True


def _has_offering(it: InstanceType, reqs: Requirements) -> bool:
    return len(it.offerings.available().requirements(reqs)) > 0


def _port_conflict(used: List[HostPort], ports: List[HostPort]) -> bool:
    return any(new.matches(existing) for new in ports for existing in used)


@dataclass
class _OpenClaim:
    template_index: int
    template: TemplateInfo
    requirements: Requirements
    requests: Dict[str, float]
    it_indices: List[int]
    pod_indices: List[int] = field(default_factory=list)
    used_ports: List[HostPort] = field(default_factory=list)
    seq: int = 0


@dataclass
class _NodeBin:
    info: NodeInfo
    requirements: Requirements
    requests: Dict[str, float]
    pod_indices: List[int] = field(default_factory=list)
    used_ports: List[HostPort] = field(default_factory=list)
    vol_counts: Dict[str, int] = field(default_factory=dict)

    def vol_fits(self, pod_vols) -> bool:
        """Count-based CSI gate — intentionally the jax kernel's semantics
        (see volumeusage.py docstring) so both backends agree bit-for-bit."""
        if not pod_vols:
            return True
        for driver, ids in pod_vols.items():
            limit = self.info.volume_limits.get(driver)
            if limit is None:
                continue
            if self.vol_counts.get(driver, 0) + len(ids) > limit:
                return False
        return True

    def vol_add(self, pod_vols) -> None:
        for driver, ids in (pod_vols or {}).items():
            if driver in self.info.volume_limits:
                self.vol_counts[driver] = self.vol_counts.get(driver, 0) + len(ids)


class OracleSolver(SolverBackend):
    def __init__(self, well_known: frozenset = wk.WELL_KNOWN_LABELS):
        self.well_known = well_known

    def solve(
        self,
        pods: Sequence[Pod],
        instance_types: Sequence[InstanceType],
        templates: Sequence[TemplateInfo],
        nodes: Sequence[NodeInfo] = (),
        pod_requirements_override: Optional[Sequence[Requirements]] = None,
        topology: Optional[Topology] = None,
        cluster_pods: Sequence = (),
        domains: Optional[Dict[str, set]] = None,
        pod_volumes: Optional[Sequence[Dict[str, frozenset]]] = None,
    ) -> SolveResult:
        # copy-on-write: pods are only copied when relaxation mutates them;
        # a caller-provided topology is isolated so the caller's group state
        # never sees this solve's relaxations (matches jax_backend)
        work = list(pods)
        copied = set()
        if domains is None:
            domains = domains_from_instance_types(instance_types, templates)
        topo = (
            topology.clone()
            if topology is not None
            else Topology(domains, batch_pods=work, cluster_pods=cluster_pods)
        )
        for n in nodes:
            topo.register(wk.LABEL_HOSTNAME, n.name)
        prefs = Preferences(
            tolerate_prefer_no_schedule=any(
                t.effect == "PreferNoSchedule" for tpl in templates for t in tpl.taints
            )
        )

        node_bins = [
            _NodeBin(
                info=n,
                requirements=n.requirements.copy(),
                requests=dict(n.daemon_overhead),
                used_ports=list(n.host_ports),
                vol_counts=dict(n.volume_used),
            )
            for n in nodes
        ]
        claims: List[_OpenClaim] = []
        remaining = [
            dict(t.remaining_resources) if t.remaining_resources is not None else None
            for t in templates
        ]
        result = SolveResult()

        queue = list(range(len(work)))
        while queue:
            progress = False
            failed: List[int] = []
            for pi in [queue[i] for i in ffd_order([work[i] for i in queue])]:
                pod = work[pi]
                # the override pins label requirements for the whole solve —
                # relax still runs its full ladder but node-affinity steps
                # can't change the pinned reqs (jax parity)
                if pod_requirements_override is not None:
                    reqs = pod_requirements_override[pi]
                    strict = reqs
                else:
                    reqs = pod_requirements(pod)
                    strict = (
                        strict_pod_requirements(pod)
                        if has_preferred_node_affinity(pod)
                        else reqs
                    )
                requests = {**res.pod_requests(pod), res.PODS: 1.0}
                ports = get_host_ports(pod)
                vols = pod_volumes[pi] if pod_volumes is not None else None
                if (
                    self._try_nodes(pi, pod, reqs, strict, requests, ports, vols,
                                    node_bins, topo)
                    or self._try_claims(
                        pi, pod, reqs, strict, requests, ports, claims, instance_types, topo
                    )
                    or self._try_templates(
                        pi, pod, reqs, strict, requests, ports, claims, templates,
                        instance_types, remaining, topo,
                    )
                ):
                    progress = True
                else:
                    failed.append(pi)
            relaxed_any = False
            for pi in failed:
                if pi not in copied:
                    work[pi] = copy.deepcopy(work[pi])
                    copied.add(pi)
                if prefs.relax(work[pi]) is not None:
                    relaxed_any = True
                    topo.update(work[pi])
            if not progress and not relaxed_any:
                # same host-side forensics as the jax backend (forensics.py)
                from karpenter_tpu.solver.forensics import failure_reason

                for pi in failed:
                    result.failures[pi] = failure_reason(
                        work[pi],
                        instance_types,
                        templates,
                        pod_reqs=(
                            pod_requirements_override[pi]
                            if pod_requirements_override is not None
                            else None
                        ),
                        well_known=self.well_known,
                    ) or FAIL_INCOMPATIBLE
                from karpenter_tpu.obs import explain as obs_explain

                if obs_explain.enabled():
                    # the terminal pass committed nothing, so this state is
                    # exactly what every failed pod was last evaluated against
                    result.explain = self._explain(
                        failed, work, pod_requirements_override, pod_volumes,
                        node_bins, claims, templates, instance_types,
                        remaining, topo, total_pods=len(work),
                    )
                break
            queue = failed

        for nb in node_bins:
            if nb.pod_indices:
                result.node_pods[nb.info.name] = nb.pod_indices
        for claim in claims:
            reqs_out = claim.requirements.copy()
            reqs_out.delete(wk.LABEL_HOSTNAME)  # FinalizeScheduling (nodeclaim.go:123-127)
            result.new_claims.append(
                Placement(
                    template_index=claim.template_index,
                    nodepool_name=claim.template.nodepool_name,
                    pod_indices=claim.pod_indices,
                    instance_type_indices=claim.it_indices,
                    requirements=reqs_out,
                    requests=claim.requests,
                )
            )
        return result

    # -- placement attempts, in reference priority order ----------------------

    def _try_nodes(self, pi, pod, reqs, strict, requests, ports, vols, node_bins, topo) -> bool:
        for nb in node_bins:
            if nb.info.taints.tolerates(pod):
                continue
            if _port_conflict(nb.used_ports, ports):
                continue
            if not nb.vol_fits(vols):
                continue
            merged_requests = res.merge(nb.requests, requests)
            if not _fits(merged_requests, nb.info.available):
                continue
            # strict Compatible — no well-known allowance (existingnode.go:94)
            if not nb.requirements.is_compatible(reqs):
                continue
            merged = nb.requirements.copy()
            merged.add(*reqs.values())
            topo_reqs = topo.add_requirements(strict, merged, pod)
            if topo_reqs is None or not merged.is_compatible(topo_reqs):
                continue
            merged.add(*topo_reqs.values())
            nb.requests = merged_requests
            nb.requirements = merged
            nb.pod_indices.append(pi)
            nb.used_ports.extend(ports)
            nb.vol_add(vols)
            topo.record(pod, merged)
            return True
        return False

    def _try_claims(
        self, pi, pod, reqs, strict, requests, ports, claims, instance_types, topo
    ) -> bool:
        for claim in sorted(claims, key=lambda c: (len(c.pod_indices), c.seq)):
            if claim.template.taints.tolerates(pod):
                continue
            if _port_conflict(claim.used_ports, ports):
                continue
            if not claim.requirements.is_compatible(reqs, self.well_known):
                continue
            narrowed = claim.requirements.copy()
            narrowed.add(*reqs.values())
            topo_reqs = topo.add_requirements(strict, narrowed, pod, self.well_known)
            if topo_reqs is None or not narrowed.is_compatible(topo_reqs, self.well_known):
                continue
            narrowed.add(*topo_reqs.values())
            merged = res.merge(claim.requests, requests)
            surviving = [
                ti
                for ti in claim.it_indices
                if not instance_types[ti].requirements.intersects(narrowed)
                and _fits(merged, instance_types[ti].allocatable())
                and _has_offering(instance_types[ti], narrowed)
            ]
            if not surviving:
                continue
            claim.requirements = narrowed
            claim.requests = merged
            claim.it_indices = surviving
            claim.pod_indices.append(pi)
            claim.used_ports.extend(ports)
            topo.record(pod, narrowed, self.well_known)
            return True
        return False

    def _try_templates(
        self, pi, pod, reqs, strict, requests, ports, claims, templates,
        instance_types, remaining, topo,
    ) -> bool:
        # the prospective claim's hostname is minted once for this step;
        # registration is rolled back if no template accepts the pod (the
        # reference leaks ghost registrations here — both backends don't)
        hostname = claim_hostname(len(claims))
        topo.register(wk.LABEL_HOSTNAME, hostname)
        for ti_idx, tpl in enumerate(templates):
            if tpl.taints.tolerates(pod):
                continue
            if not tpl.requirements.is_compatible(reqs, self.well_known):
                continue
            narrowed = tpl.requirements.copy()
            narrowed.add(Requirement(wk.LABEL_HOSTNAME, IN, [hostname]))
            narrowed.add(*reqs.values())
            topo_reqs = topo.add_requirements(strict, narrowed, pod, self.well_known)
            if topo_reqs is None or not narrowed.is_compatible(topo_reqs, self.well_known):
                continue
            narrowed.add(*topo_reqs.values())
            merged = res.merge(tpl.daemon_overhead, requests)
            # nodepool limits: drop instance types whose capacity exceeds the
            # pool's remaining headroom (filterByRemainingResources)
            universe = tpl.instance_type_indices
            if remaining[ti_idx] is not None:
                universe = [
                    t
                    for t in universe
                    if _fits(
                        {
                            name: instance_types[t].capacity.get(name, 0.0)
                            for name in remaining[ti_idx]
                        },
                        remaining[ti_idx],
                    )
                ]
            surviving = [
                t
                for t in universe
                if not instance_types[t].requirements.intersects(narrowed)
                and _fits(merged, instance_types[t].allocatable())
                and _has_offering(instance_types[t], narrowed)
            ]
            if not surviving:
                continue
            if remaining[ti_idx] is not None:
                # pessimistic headroom burn (subtractMax, scheduler.go:347-364)
                max_cap = res.max_resources(
                    *(instance_types[t].capacity for t in surviving)
                )
                remaining[ti_idx] = {
                    name: q - max_cap.get(name, 0.0)
                    for name, q in remaining[ti_idx].items()
                }
            claims.append(
                _OpenClaim(
                    template_index=ti_idx,
                    template=tpl,
                    requirements=narrowed,
                    requests=merged,
                    it_indices=surviving,
                    pod_indices=[pi],
                    used_ports=list(ports),
                    seq=len(claims),
                )
            )
            topo.record(pod, narrowed, self.well_known)
            return True
        # roll back the ghost hostname registration
        for tg in list(topo.topologies.values()) + list(topo.inverse_topologies.values()):
            if tg.key == wk.LABEL_HOSTNAME and tg.domains.get(hostname) == 0:
                del tg.domains[hostname]
        return False

    # -- explainability (obs/explain.py): per-family re-run of the gates ------
    # The host half of the parity pair: the same checks _try_nodes/_try_claims/
    # _try_templates short-circuit through are evaluated exhaustively per
    # candidate, then folded through the SAME encode/decode helpers the device
    # attribution path uses — the parity test compares reasons, and any drift
    # is a real semantic divergence, not a taxonomy mismatch.

    def _explain(self, failed, work, override, pod_volumes, node_bins, claims,
                 templates, instance_types, remaining, topo, total_pods):
        import time

        from karpenter_tpu.obs import explain as ox

        t0 = time.perf_counter()
        report = ox.ExplainReport(
            backend=type(self).__name__,
            total_pods=total_pods,
            scheduled=total_pods - len(failed),
        )
        for pi in failed:
            pod = work[pi]
            if override is not None:
                reqs = strict = override[pi]
            else:
                reqs = pod_requirements(pod)
                strict = (
                    strict_pod_requirements(pod)
                    if has_preferred_node_affinity(pod)
                    else reqs
                )
            requests = {**res.pod_requests(pod), res.PODS: 1.0}
            ports = get_host_ports(pod)
            vols = pod_volumes[pi] if pod_volumes is not None else None
            words = ox.pack_words((
                self._node_families(pod, reqs, strict, requests, ports, vols,
                                    node_bins, topo),
                self._claim_families(pod, reqs, strict, requests, ports,
                                     claims, instance_types, topo),
                self._template_families(pod, reqs, strict, requests, claims,
                                        templates, instance_types, remaining,
                                        topo),
            ))
            expl = ox.decode_pod(pi, ox._KIND_FAIL, words)
            if expl.reason == ox.REASON_RESOURCES:
                better = ox.resource_hint(requests, instance_types)
                if better:
                    expl.hint = better
            report.pods[pi] = expl
        report.overhead_s = time.perf_counter() - t0
        ox.publish(report)
        return report

    def _topo_fails(self, strict, merged, pod, topo, allow=frozenset()) -> bool:
        try:
            topo_reqs = topo.add_requirements(strict, merged, pod, allow)
        except Exception:
            return True
        return topo_reqs is None or not merged.is_compatible(topo_reqs, allow)

    def _node_families(self, pod, reqs, strict, requests, ports, vols,
                       node_bins, topo):
        from karpenter_tpu.obs import explain as ox

        fails = [[False] * len(node_bins) for _ in range(ox.NUM_FAMILIES)]
        for e, nb in enumerate(node_bins):
            fails[ox.FAM_TAINTS][e] = bool(nb.info.taints.tolerates(pod))
            fails[ox.FAM_PORTS][e] = _port_conflict(nb.used_ports, ports)
            fails[ox.FAM_VOLUME][e] = not nb.vol_fits(vols)
            fails[ox.FAM_RESOURCES][e] = not _fits(
                res.merge(nb.requests, requests), nb.info.available
            )
            compat = nb.requirements.is_compatible(reqs)
            fails[ox.FAM_REQUIREMENTS][e] = not compat
            merged = nb.requirements.copy()
            merged.add(*reqs.values())
            fails[ox.FAM_TOPOLOGY][e] = self._topo_fails(strict, merged, pod, topo)
        return ox.encode_family_bits(fails, [True] * len(node_bins))

    def _claim_families(self, pod, reqs, strict, requests, ports, claims,
                        instance_types, topo):
        from karpenter_tpu.obs import explain as ox

        fails = [[False] * len(claims) for _ in range(ox.NUM_FAMILIES)]
        for e, claim in enumerate(claims):
            fails[ox.FAM_TAINTS][e] = bool(claim.template.taints.tolerates(pod))
            fails[ox.FAM_PORTS][e] = _port_conflict(claim.used_ports, ports)
            compat = claim.requirements.is_compatible(reqs, self.well_known)
            narrowed = claim.requirements.copy()
            narrowed.add(*reqs.values())
            topo_fail = self._topo_fails(strict, narrowed, pod, topo, self.well_known)
            fails[ox.FAM_TOPOLOGY][e] = topo_fail
            if not topo_fail:
                topo_reqs = topo.add_requirements(strict, narrowed, pod, self.well_known)
                narrowed.add(*topo_reqs.values())
            merged = res.merge(claim.requests, requests)
            co = [
                ti for ti in claim.it_indices
                if not instance_types[ti].requirements.intersects(narrowed)
                and _has_offering(instance_types[ti], narrowed)
            ]
            has_fit = any(
                _fits(merged, instance_types[ti].allocatable()) for ti in co
            )
            has_base = bool(claim.it_indices)
            fails[ox.FAM_RESOURCES][e] = (bool(co) and not has_fit) or not has_base
            fails[ox.FAM_REQUIREMENTS][e] = not compat or (has_base and not co)
        return ox.encode_family_bits(fails, [True] * len(claims))

    def _template_families(self, pod, reqs, strict, requests, claims,
                           templates, instance_types, remaining, topo):
        from karpenter_tpu.obs import explain as ox

        # mint the same prospective hostname the terminal _try_templates used
        hostname = claim_hostname(len(claims))
        topo.register(wk.LABEL_HOSTNAME, hostname)
        fails = [[False] * len(templates) for _ in range(ox.NUM_FAMILIES)]
        try:
            for e, tpl in enumerate(templates):
                fails[ox.FAM_TAINTS][e] = bool(tpl.taints.tolerates(pod))
                compat = tpl.requirements.is_compatible(reqs, self.well_known)
                narrowed = tpl.requirements.copy()
                narrowed.add(Requirement(wk.LABEL_HOSTNAME, IN, [hostname]))
                narrowed.add(*reqs.values())
                topo_fail = self._topo_fails(strict, narrowed, pod, topo, self.well_known)
                fails[ox.FAM_TOPOLOGY][e] = topo_fail
                if not topo_fail:
                    topo_reqs = topo.add_requirements(strict, narrowed, pod, self.well_known)
                    narrowed.add(*topo_reqs.values())
                merged = res.merge(tpl.daemon_overhead, requests)
                universe = tpl.instance_type_indices
                has_base = bool(universe)
                if remaining[e] is not None:
                    universe = [
                        t for t in universe
                        if _fits(
                            {
                                name: instance_types[t].capacity.get(name, 0.0)
                                for name in remaining[e]
                            },
                            remaining[e],
                        )
                    ]
                has_cap = bool(universe)
                fails[ox.FAM_CLAIM_CAPACITY][e] = has_base and not has_cap
                co = [
                    t for t in universe
                    if not instance_types[t].requirements.intersects(narrowed)
                    and _has_offering(instance_types[t], narrowed)
                ]
                has_fit = any(
                    _fits(merged, instance_types[t].allocatable()) for t in co
                )
                fails[ox.FAM_RESOURCES][e] = bool(co) and not has_fit
                fails[ox.FAM_REQUIREMENTS][e] = (
                    not compat or not has_base or (has_cap and not co)
                )
        finally:
            for tg in list(topo.topologies.values()) + list(
                topo.inverse_topologies.values()
            ):
                if tg.key == wk.LABEL_HOSTNAME and tg.domains.get(hostname) == 0:
                    del tg.domains[hostname]
        return ox.encode_family_bits(fails, [True] * len(templates))
