"""Degraded-mesh resilience: per-device health, recarve, probation
(``KARPENTER_TPU_MESH_HEALTH``).

Every multi-device layer — the shard_map partitioned solve (shard/), the
carved-slice serve replicas (serve/replica.py), the device-resident world
(streaming/device_world.py) — assumed the device set it saw at startup is
the device set it has forever. This module makes mesh shrinkage a
CLASSIFIED, recoverable event instead of an unclassified exception inside a
fused dispatch:

  state machine   healthy -> lost | degraded   (a dispatch failure, reported
                                                by the consumer that caught
                                                the typed exception)
                  lost | degraded -> probation (a re-entry probe passed)
                  probation -> healthy         (``probation_probes()``
                                                CONSECUTIVE clean probes —
                                                one good probe does not
                                                un-flap a flapping chip)
                  probation -> lost | degraded (a probe failed or the device
                                                failed again mid-probation)

  recarve         ``tracker().recarve(reason)`` classifies the event
                  (``solver_mesh_recarve_total{reason}``: device-lost /
                  device-degraded / probe-failed / recovered), re-exports
                  the per-state device census (``solver_mesh_devices``),
                  and returns the healthy device list. Consumers rebuild
                  their meshes from it: ``parallel.mesh.default_mesh`` and
                  ``carve_meshes`` exclude unhealthy devices whenever the
                  flag is on, so the next dispatch — and the next serve
                  ReplicaSet carve — simply never sees the failed device.

  recovery clock  the first failure starts a timer; ``note_green()`` (called
                  by a consumer after its first successful solve on the
                  recarved mesh) observes ``solver_mesh_recovery_seconds``
                  — the measured latency cost of the contract "a device
                  failure costs latency, never a dropped cycle, a wrong
                  placement, or an unclassified outcome".

Fault injection rides the shared grammar (testing/faults.py ``device`` site:
``device[2].loss@3``, ``device[0].degraded=0.05@*``); ``dispatch_check``
is the hook consumers call once per mesh dispatch. Flag off AND no injector
installed, every hook is one module-attribute read and no tracker exists —
the flag-off dispatch path is bit-identical (census-pinned in tier-1).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from karpenter_tpu.metrics.registry import (
    MESH_DEVICES,
    MESH_RECARVE,
    MESH_RECOVERY_SECONDS,
)
from karpenter_tpu.obs import flight, slo, trace
from karpenter_tpu.testing import faults

log = logging.getLogger(__name__)

# classified recarve reasons — the bounded label-value set for
# solver_mesh_recarve_total and the vocabulary of tests/test_mesh_health.py
REASON_DEVICE_LOST = "device-lost"
REASON_DEVICE_DEGRADED = "device-degraded"
REASON_PROBE_FAILED = "probe-failed"
REASON_RECOVERED = "recovered"
REASONS = (
    REASON_DEVICE_LOST, REASON_DEVICE_DEGRADED, REASON_PROBE_FAILED,
    REASON_RECOVERED,
)

STATE_HEALTHY = "healthy"
STATE_DEGRADED = "degraded"
STATE_LOST = "lost"
STATE_PROBATION = "probation"
STATES = (STATE_HEALTHY, STATE_DEGRADED, STATE_LOST, STATE_PROBATION)


def enabled() -> bool:
    """KARPENTER_TPU_MESH_HEALTH, default OFF: mesh carving consults the
    health tracker only when on. Off = zero overhead and a bit-identical
    dispatch path (tier-1 census pin holds the proof); fault-injection
    hooks still fire when an injector is installed, so chaos runs can
    exercise the typed exceptions without the flag."""
    return os.environ.get("KARPENTER_TPU_MESH_HEALTH", "0") not in ("", "0")


def probe_interval_s() -> float:
    """KARPENTER_TPU_MESH_PROBE_S: minimum seconds between probe passes over
    the excluded devices (default 5). ``probe(force=True)`` ignores it."""
    try:
        return max(0.0, float(os.environ.get("KARPENTER_TPU_MESH_PROBE_S", "5")))
    except ValueError:
        return 5.0


def probation_probes() -> int:
    """KARPENTER_TPU_MESH_PROBATION: consecutive clean probes a failed
    device must pass before it rejoins the mesh (default 2) — re-entry
    probation, so one lucky probe doesn't re-admit a flapping chip."""
    try:
        return max(1, int(os.environ.get("KARPENTER_TPU_MESH_PROBATION", "2")))
    except ValueError:
        return 2


def classify_failure(exc: BaseException) -> Optional[str]:
    """The recarve reason for an exception a mesh dispatch raised, or None
    when it is not a device-health event (the caller's ordinary error
    discipline then applies). Typed injected faults classify exactly; real
    runtime errors classify conservatively on the runtime's own device-loss
    markers — a misclassified generic error would recarve a healthy mesh."""
    if isinstance(exc, faults.FaultDeviceDegraded):
        return REASON_DEVICE_DEGRADED
    if isinstance(exc, faults.FaultDeviceLost):
        return REASON_DEVICE_LOST
    text = f"{type(exc).__name__}: {exc}"
    if "XlaRuntimeError" in type(exc).__name__ and any(
        marker in text for marker in ("DEVICE_LOST", "device lost")
    ):
        return REASON_DEVICE_LOST
    return None


def failed_device(exc: BaseException) -> int:
    """The device index an exception names (typed faults carry it; real
    runtime errors default to device 0 — the recarve excludes it and the
    probe path sorts out the rest)."""
    return int(getattr(exc, "device", 0))


@dataclass
class DeviceHealth:
    state: str = STATE_HEALTHY
    reason: Optional[str] = None
    since: float = 0.0
    clean_probes: int = 0
    failures: int = 0
    history: List[str] = field(default_factory=list)


class MeshHealth:
    """Thread-safe per-device health registry. One per process
    (``tracker()``): the shard path, the serve replicas, and the device
    world all dispatch onto the same local devices, so a loss any of them
    observes must shrink the mesh for all of them."""

    def __init__(self, time_fn=time.monotonic):
        self._time = time_fn
        self._lock = threading.Lock()
        self._states: Dict[int, DeviceHealth] = {}
        self._failed_at: Optional[float] = None  # recovery clock start
        self._last_probe_at: Optional[float] = None
        self.last_recovery_s: Optional[float] = None
        self.recarves: List[tuple] = []  # (reason, device) classified log

    # -- state reads -----------------------------------------------------------

    def state_of(self, device_id: int) -> str:
        with self._lock:
            ent = self._states.get(int(device_id))
            return ent.state if ent is not None else STATE_HEALTHY

    def healthy_devices(self, devices=None) -> list:
        """``devices`` (default: all local devices) minus everything not
        currently healthy — the device list meshes are carved from."""
        if devices is None:
            import jax

            devices = jax.devices()
        with self._lock:
            bad = {
                d for d, ent in self._states.items()
                if ent.state != STATE_HEALTHY
            }
        return [d for d in devices if int(getattr(d, "id", d)) not in bad]

    def unhealthy_ids(self) -> List[int]:
        with self._lock:
            return sorted(
                d for d, ent in self._states.items()
                if ent.state != STATE_HEALTHY
            )

    # -- transitions -----------------------------------------------------------

    def report_failure(self, device_id: int, reason: str) -> None:
        """A consumer caught a classified device failure on ``device_id``.
        Starts the recovery clock if no failure is already pending; a
        failure during probation resets the device's clean-probe streak."""
        now = self._time()
        state = (
            STATE_DEGRADED if reason == REASON_DEVICE_DEGRADED else STATE_LOST
        )
        with self._lock:
            ent = self._states.setdefault(int(device_id), DeviceHealth())
            ent.state = state
            ent.reason = reason
            ent.since = now
            ent.clean_probes = 0
            ent.failures += 1
            ent.history.append(state)
            if self._failed_at is None:
                self._failed_at = now
        flight.record(
            flight.KIND_MESH_FAULT, device=int(device_id), reason=reason,
            state=state,
        )
        log.warning(
            "mesh_health: device %d -> %s (%s, failure #%d)",
            device_id, state, reason, ent.failures,
        )

    def recarve(self, reason: str, device: Optional[int] = None) -> list:
        """Classify one recarve event and return the healthy device list the
        consumer rebuilds its mesh from. Every recarve is counted under a
        bounded reason (REASONS) and re-exports the device census gauge."""
        if reason not in REASONS:  # bounded-label contract, like admission
            raise ValueError(f"unclassified recarve reason {reason!r}")
        MESH_RECARVE.inc({"reason": reason})
        with self._lock:
            self.recarves.append((reason, device))
        healthy = self.healthy_devices()
        self._export()
        flight.record(
            flight.KIND_MESH_RECARVE, reason=reason, device=device,
            healthy=len(healthy),
        )
        if reason != REASON_RECOVERED:
            # a shrinking recarve is an incident: snapshot the ring with the
            # fault + recarve chain in it (growing back is routine)
            flight.snapshot_dump("recarve")
        with trace.span("mesh_recarve", reason=reason, healthy=len(healthy)):
            pass
        log.warning(
            "mesh_health: recarve (%s): %d healthy device(s), excluded=%s",
            reason, len(healthy), self.unhealthy_ids(),
        )
        return healthy

    def note_green(self) -> None:
        """First successful solve on the recarved mesh: close the recovery
        clock into ``solver_mesh_recovery_seconds``. No-op when no failure
        is pending, so consumers may call it after every green solve."""
        with self._lock:
            if self._failed_at is None:
                return
            elapsed = max(0.0, self._time() - self._failed_at)
            self._failed_at = None
            self.last_recovery_s = elapsed
        MESH_RECOVERY_SECONDS.observe(elapsed)
        if slo.enabled():
            slo.on_recovery(elapsed)
            flight.record(
                flight.KIND_MESH_RECOVERED, seconds=round(elapsed, 6),
            )

    # -- probes / probation ----------------------------------------------------

    def probe(self, devices=None, force: bool = False) -> Dict[int, str]:
        """Re-probe every excluded device (rate-limited to one pass per
        ``probe_interval_s()`` unless forced). A clean probe moves the
        device into probation and advances its streak; ``probation_probes``
        consecutive clean probes re-admit it (recarve reason 'recovered').
        A failed probe — real, or an injected ``device[n]`` rule matching
        this visit — zeroes the streak (reason 'probe-failed'). Returns
        {device_id: state} for the devices probed."""
        now = self._time()
        with self._lock:
            if (
                not force
                and self._last_probe_at is not None
                and now - self._last_probe_at < probe_interval_s()
            ):
                return {}
            self._last_probe_at = now
            suspect = sorted(
                d for d, ent in self._states.items()
                if ent.state != STATE_HEALTHY
            )
        if not suspect:
            return {}
        if devices is None:
            import jax

            devices = jax.devices()
        by_id = {int(getattr(d, "id", d)): d for d in devices}
        out: Dict[int, str] = {}
        for dev_id in suspect:
            ok = self._probe_one(dev_id, by_id.get(dev_id))
            with self._lock:
                ent = self._states[dev_id]
                if ok:
                    ent.clean_probes += 1
                    if ent.clean_probes >= probation_probes():
                        ent.state = STATE_HEALTHY
                        ent.reason = None
                        ent.history.append(STATE_HEALTHY)
                    else:
                        ent.state = STATE_PROBATION
                        ent.reason = ent.reason or REASON_PROBE_FAILED
                        ent.history.append(STATE_PROBATION)
                else:
                    ent.clean_probes = 0
                    ent.state = STATE_LOST
                    ent.reason = REASON_PROBE_FAILED
                    ent.history.append(STATE_LOST)
                out[dev_id] = ent.state
            if ok and out[dev_id] == STATE_HEALTHY:
                self.recarve(REASON_RECOVERED, device=dev_id)
            elif not ok:
                self.recarve(REASON_PROBE_FAILED, device=dev_id)
        self._export()
        return out

    def _probe_one(self, dev_id: int, dev) -> bool:
        """One probe visit: consult the fault injector first (a probe IS a
        device-site visit — replay determinism needs it on the shared
        schedule), then run the real probe program when the device object is
        available."""
        injector = faults.active()
        if injector is not None:
            rule = injector.draw("device")
            if rule is not None and faults.device_index(rule) == dev_id:
                return False
        if dev is None:
            return False
        from karpenter_tpu.verify.device import probe_device

        return probe_device(dev)

    # -- export / introspection ------------------------------------------------

    def _export(self) -> None:
        import jax

        try:
            total = len(jax.devices())
        except Exception:  # noqa: BLE001 — census export must never raise
            total = 0
        with self._lock:
            counts = {s: 0 for s in STATES}
            for ent in self._states.values():
                if ent.state != STATE_HEALTHY:
                    counts[ent.state] += 1
        excluded = sum(counts.values())
        counts[STATE_HEALTHY] = max(0, total - excluded)
        for state, count in counts.items():
            MESH_DEVICES.set(float(count), {"state": state})

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "devices": {
                    str(d): {
                        "state": ent.state,
                        "reason": ent.reason,
                        "clean_probes": ent.clean_probes,
                        "failures": ent.failures,
                    }
                    for d, ent in sorted(self._states.items())
                },
                "recarves": [
                    {"reason": r, "device": d} for r, d in self.recarves
                ],
                "recovery_pending": self._failed_at is not None,
                "last_recovery_s": self.last_recovery_s,
            }

    def reset(self) -> None:
        with self._lock:
            self._states.clear()
            self.recarves.clear()
            self._failed_at = None
            self._last_probe_at = None
            self.last_recovery_s = None


# -- process-wide tracker ------------------------------------------------------

_tracker: Optional[MeshHealth] = None
_tracker_lock = threading.Lock()


def tracker() -> MeshHealth:
    """The process-wide health registry (created on first use)."""
    global _tracker
    with _tracker_lock:
        if _tracker is None:
            _tracker = MeshHealth()
        return _tracker


def has_tracker() -> bool:
    return _tracker is not None


def note_green() -> None:
    """Module-level shortcut consumers call after every successful mesh
    solve: closes a pending recovery clock, costs one attribute read when no
    tracker was ever created (the flag-off steady state)."""
    if _tracker is not None:
        _tracker.note_green()


def reset() -> None:
    """Drop the tracker (tests)."""
    global _tracker
    with _tracker_lock:
        _tracker = None


# -- the dispatch hook ---------------------------------------------------------


def dispatch_check(devices=None) -> None:
    """Fault-injection hook at mesh dispatch sites (shard/solve.py, the
    serve stacked dispatch, the device-world cycle). One ``device``-site
    draw per dispatch; a matching rule whose target device participates in
    this dispatch is realized (FaultDeviceLost / FaultDeviceDegraded — the
    degraded kind sleeps first). Disabled-path cost is one module-attribute
    read; ``devices=None`` means every local device participates."""
    injector = faults.active()
    if injector is None:
        return
    rule = injector.draw("device")
    if rule is None:
        return
    target = faults.device_index(rule)
    if devices is not None:
        ids = {int(getattr(d, "id", d)) for d in devices}
        if target not in ids:
            return
    faults.realize_device_fault(rule)


def handle_dispatch_failure(exc: BaseException) -> Optional[list]:
    """Shared consumer recovery step: classify ``exc``; when it is a device
    failure, mark the device, recarve, and return the healthy device list
    to rebuild a mesh from. Returns None when the exception is not a
    device-health event (the caller re-raises into its ordinary error
    discipline)."""
    reason = classify_failure(exc)
    if reason is None:
        return None
    tr = tracker()
    tr.report_failure(failed_device(exc), reason)
    return tr.recarve(reason, device=failed_device(exc))
