"""The JAX solver backend.

Encodes the batch (solver/encode.py), runs the lax.scan FFD (ops/ffd.py), and
decodes device output back into the host result model. Claim-slot capacity is
a static compile dimension: the backend starts from a bucketed guess and
doubles on overflow (KIND_NO_SLOT), so recompiles stay rare and bounded —
SURVEY.md §7 hard part (3): pad-and-mask with bucketed compile sizes.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from karpenter_tpu.apis.objects import Pod
from karpenter_tpu.cloudprovider.types import InstanceType
from karpenter_tpu.scheduling import Requirements
from karpenter_tpu.solver.backend import (
    FAIL_INCOMPATIBLE,
    Placement,
    SolveResult,
    SolverBackend,
)
from karpenter_tpu.solver.encode import Encoder, NodeInfo, TemplateInfo
from karpenter_tpu.ops.padding import pad_problem, pow2_bucket
from karpenter_tpu.ops.ffd import (
    KIND_CLAIM,
    KIND_FAIL,
    KIND_NEW_CLAIM,
    KIND_NODE,
    KIND_NO_SLOT,
    solve_ffd,
)


class JaxSolver(SolverBackend):
    def __init__(self, well_known=None, initial_claim_slots: int = 32):
        from karpenter_tpu.apis import labels as wk

        self.well_known = well_known if well_known is not None else wk.WELL_KNOWN_LABELS
        # grows on overflow and persists — a steady workload pays the
        # doubling retries once, not per solve
        self.claim_slots = pow2_bucket(initial_claim_slots)

    def solve(
        self,
        pods: Sequence[Pod],
        instance_types: Sequence[InstanceType],
        templates: Sequence[TemplateInfo],
        nodes: Sequence[NodeInfo] = (),
        pod_requirements_override: Optional[Sequence[Requirements]] = None,
    ) -> SolveResult:
        if not pods:
            return SolveResult()
        encoded = Encoder(self.well_known).encode(
            pods, instance_types, templates, nodes, pod_requirements_override
        )
        problem, meta = pad_problem(encoded.problem), encoded.meta

        max_claims = min(self.claim_slots, pow2_bucket(len(pods)))
        while True:
            result = solve_ffd(problem, max_claims)
            kinds = np.asarray(result.kind)
            if not (kinds == KIND_NO_SLOT).any() or max_claims >= len(pods):
                break
            max_claims = min(pow2_bucket(max_claims * 2), pow2_bucket(len(pods)))
            self.claim_slots = max(self.claim_slots, max_claims)

        indices = np.asarray(result.index)
        claim_tpl = np.asarray(result.state.claim_tpl)
        claim_it_ok = np.asarray(result.state.claim_it_ok)
        claim_open = np.asarray(result.state.claim_open)
        claim_requests = np.asarray(result.state.claim_requests)

        out = SolveResult()
        slot_to_claim = {}
        for slot in range(max_claims):
            if claim_open[slot]:
                tpl_idx = int(claim_tpl[slot])
                placement = Placement(
                    template_index=tpl_idx,
                    nodepool_name=meta.template_names[tpl_idx],
                    instance_type_indices=[int(t) for t in np.flatnonzero(claim_it_ok[slot])],
                    requests={
                        name: float(claim_requests[slot, ri])
                        for ri, name in enumerate(meta.resource_names)
                        if claim_requests[slot, ri] > 0
                    },
                )
                slot_to_claim[slot] = placement
                out.new_claims.append(placement)

        for row in range(len(meta.pod_order)):  # rows past this are padding
            kind, index = kinds[row], indices[row]
            pod_idx = meta.pod_order[row]  # problem rows are FFD-sorted
            if kind == KIND_NODE:
                out.node_pods.setdefault(meta.node_names[index], []).append(pod_idx)
            elif kind in (KIND_CLAIM, KIND_NEW_CLAIM):
                slot_to_claim[int(index)].pod_indices.append(pod_idx)
            else:
                out.failures[pod_idx] = FAIL_INCOMPATIBLE
        return out
