"""The JAX solver backend.

Encodes the batch (solver/encode.py), runs the lax.scan FFD (ops/ffd.py) in
relax-and-retry passes with carried device state, and decodes back into the
host result model. Claim-slot capacity is a static compile dimension: the
backend starts from a bucketed guess and restarts with double the slots on
overflow (KIND_NO_SLOT), so recompiles stay rare and bounded — SURVEY.md §7
hard part (3): pad-and-mask with bucketed compile sizes.

Pass structure (the reference's queue requeue + relaxation,
scheduler.go:150-170): each pass scans the queued pods once against carried
FFDState (bins + topology counters persist); failed pods are relaxed one
notch (provisioning/preferences.py) and retried until a pass places nothing
and relaxes nothing. The vocabulary is frozen from the original unrelaxed
batch so carried state keeps valid lane indices across passes.

Two-phase solve (KARPENTER_TPU_RELAX, round 15): in sweeps mode the backend
can first run one dense relaxation program (ops/relax.py) that places the
eligible bulk of the batch by waterfill over pods x template bins, then feed
the residue into the SAME sweeps program as a repair pass carrying phase 1's
claim landscape (solve_ffd_sweeps_carried). Every relaxed result is
full-gated through the validator before the backend returns it; a violation
triggers one fallback re-solve with relaxation off
(solver_relax_fallback_total). Flag off, nothing changes: same programs,
bit-identical placements.

Convex phase 1 (KARPENTER_TPU_RELAX2, round 22): when enabled, the backend
tries the projected-gradient convex solve (ops/relax2.py) ahead of the
waterfill — same carried handoff, same full-gate contract, its own
allow_relax2 retry latch. Standdowns are classified into the round-15
counter (solver_relax_fallback_total{reason}) and recorded in last_relax2;
any standdown falls through to the waterfill unchanged. The module is
imported lazily, so flag off the solve path never loads it.
"""

from __future__ import annotations

import copy
from typing import Dict, Optional, Sequence

import jax
import numpy as np

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.objects import Pod
from karpenter_tpu.metrics.registry import (
    COMPILE_CACHE,
    ORDER_POLICY_SOLVES,
    RELAX_FALLBACK,
    TRANSFER_BYTES,
)
from karpenter_tpu.obs import programs, trace
from karpenter_tpu.solver import aot
from karpenter_tpu.cloudprovider.types import InstanceType
from karpenter_tpu.provisioning.preferences import Preferences
from karpenter_tpu.provisioning.topology import Topology
from karpenter_tpu.scheduling import Requirements
from karpenter_tpu.solver.backend import (
    FAIL_INCOMPATIBLE,
    Placement,
    SolveResult,
    SolverBackend,
)
from karpenter_tpu.solver.encode import (
    Encoder,
    NodeInfo,
    TemplateInfo,
    domains_from_instance_types,
)
from karpenter_tpu.ops.padding import claim_axis_bucket, pad_problem, pow2_bucket
from karpenter_tpu.ops import relax
from karpenter_tpu.ops.ffd import (
    KIND_CLAIM,
    KIND_NEW_CLAIM,
    KIND_NODE,
    KIND_NO_SLOT,
    IterCounts,
    solve_ffd,
    solve_ffd_runs,
    solve_ffd_sweeps,
    solve_ffd_sweeps_carried,
    solve_ffd_sweeps_carried_policy,
    solve_ffd_sweeps_policy,
)
from karpenter_tpu.solver import ordering

# The per-pod scan is the production default. Measured on the reference's
# diverse bench mix AFTER the claim-slot-growth fix (both paths correct,
# C=128): per-pod beats the run-compressed scan 2.0s vs 5.4s per device pass
# at 10k pods on CPU and 2.8s vs 5.7s end-to-end on TPU v5e — the mix's
# average run length (~2.4) doesn't amortize the run machinery, and the
# topology-run inner loop serializes worse than the vectorized per-pod step.
# Run compression still powers the consolidation screen (parallel/mesh.py
# batched_screen), whose candidate pods ARE long identical runs; set
# KARPENTER_TPU_RUNS=1 to opt the provisioning path back in.
import os as _os

_USE_RUNS = _os.environ.get("KARPENTER_TPU_RUNS", "0").lower() in ("1", "true", "yes")
_TIMING = _os.environ.get("KARPENTER_TPU_TIMING", "") == "1"

# Adaptive dispatch: batches at or below this many pods (and existing nodes)
# run the SAME XLA program on the host CPU backend instead of the accelerator.
# A tunneled TPU pays a fixed ~70ms runtime roundtrip per solve, which
# dominates end-to-end latency for interactive single-pod provisions; the
# reference's in-process Go solver answers those in microseconds
# (scheduling_benchmark_test.go's floor is throughput-only). Running the
# identical jitted program on the CPU device keeps bit-exact semantics (the
# 64-seed parity fuzz already exercises it on CPU) with no second solver
# implementation. 0 disables.
_HOST_SMALL_BATCH = int(_os.environ.get("KARPENTER_TPU_HOST_SMALL_BATCH", "32"))

if _TIMING:
    import sys as _sys
    import time as _time

    def _now():
        return _time.perf_counter()

    def _t(label, t0):
        _sys.stderr.write(
            f"  [timing] {label}: {_time.perf_counter() - t0:.4f}s\n"
        )
        return _time.perf_counter()
else:  # zero-cost when diagnostics are off

    def _now():
        return 0.0

    def _t(label, t0):
        return 0.0


class _SlotOverflow(Exception):
    pass


# Program keys this process has dispatched at least once. jax.jit's executable
# cache is process-global and keyed by abstract shapes, so (solve fn,
# claim-slot bucket, padded leaf shapes/dtypes) is a faithful proxy: a key
# seen before hits the jit cache (or the on-disk executable cache), a new key
# pays a compile. Feeds karpenter_solver_compile_cache_total and the
# compile|narrow span naming.
_COMPILED_PROGRAMS: set = set()


def _program_key(solve_fn, max_claims: int, problem) -> tuple:
    return (
        solve_fn.__name__,
        int(max_claims),
        tuple(
            (tuple(leaf.shape), str(getattr(leaf, "dtype", type(leaf).__name__)))
            for leaf in jax.tree_util.tree_leaves(problem)
        ),
    )


def _nbytes(arrays) -> int:
    return int(sum(getattr(a, "nbytes", 0) for a in jax.tree_util.tree_leaves(arrays)))


def decode_claim_requirements(meta, adm_row, comp_row, gt_row, lt_row, defined_row):
    """Invert encode_reqs for one claim row: the narrowed requirement state
    the solve committed becomes the claim's Requirements — what the reference
    puts on the launched NodeClaim (nodeclaimtemplate.go:55-81). The
    hostname pin is dropped the way FinalizeScheduling does
    (nodeclaim.go:123-127)."""
    from karpenter_tpu.models.problem import GT_NONE, LT_NONE
    from karpenter_tpu.scheduling.requirements import Requirement

    out = Requirements()
    for ki, key in enumerate(meta.keys):
        if not defined_row[ki] or key == wk.LABEL_HOSTNAME:
            continue
        vals = meta.values_per_key[ki]
        if not comp_row[ki]:
            members = [v for vi, v in enumerate(vals) if adm_row[ki][vi]]
            out.add(Requirement._make(key, False, members))
        else:
            excluded = [v for vi, v in enumerate(vals) if not adm_row[ki][vi]]
            gt = int(gt_row[ki])
            lt = int(lt_row[ki])
            out.add(
                Requirement._make(
                    key, True, excluded,
                    gt if gt != int(GT_NONE) else None,
                    lt if lt != int(LT_NONE) else None,
                )
            )
    return out


def decode_claim_placements(out, meta, max_claims, np_final, pod_kinds) -> None:
    """Final bin-state decode shared by the per-pass path below and the
    device-resident fused path (streaming/device_world.py): turn the fetched
    claim tensors into published Placements and route every placed pod to its
    node or claim. ``np_final`` is the 9-tuple fetched off the final FFDState
    (claim_open, claim_tpl, claim_it_ok, claim_requests, then the five
    claim_req leaves); None means no claim state exists (nothing placed on
    claims)."""
    slot_to_claim = {}
    if np_final is not None:
        (claim_open, claim_tpl, claim_it_ok, claim_requests,
         claim_adm, claim_comp, claim_gt, claim_lt, claim_def) = np_final
        for slot in range(max_claims):
            if slot < len(claim_open) and claim_open[slot]:
                tpl_idx = int(claim_tpl[slot])
                placement = Placement(
                    template_index=tpl_idx,
                    nodepool_name=meta.template_names[tpl_idx],
                    instance_type_indices=[
                        int(t)
                        for t in np.flatnonzero(claim_it_ok[slot])
                        if t < len(meta.instance_type_names)
                    ],
                    requirements=decode_claim_requirements(
                        meta, claim_adm[slot], claim_comp[slot],
                        claim_gt[slot], claim_lt[slot], claim_def[slot],
                    ),
                    requests={
                        name: float(claim_requests[slot, ri])
                        for ri, name in enumerate(meta.resource_names)
                        if claim_requests[slot, ri] > 0
                    },
                )
                slot_to_claim[slot] = placement
                out.new_claims.append(placement)
    for orig, (kind, index) in pod_kinds.items():
        if kind == KIND_NODE:
            out.node_pods.setdefault(meta.node_names[index], []).append(orig)
        else:
            slot_to_claim[index].pod_indices.append(orig)


def _remap_group_state(state, old_keys, new_keys, padded_problem):
    """Rebuild grp_counts/grp_registered for a changed group set: carried rows
    move to their new position (matched by group hash); new groups take their
    seeded rows from the freshly-encoded problem."""
    import dataclasses

    old_counts = np.asarray(state.grp_counts)
    old_reg = np.asarray(state.grp_registered)
    new_counts = np.array(padded_problem.grp_counts0)
    new_reg = np.array(padded_problem.grp_registered0)
    pos_of_old = {k: i for i, k in enumerate(old_keys)}
    V = min(old_counts.shape[1], new_counts.shape[1])
    for new_i, k in enumerate(new_keys):
        old_i = pos_of_old.get(k)
        if old_i is not None and old_i < old_counts.shape[0]:
            new_counts[new_i, :V] = old_counts[old_i, :V]
            new_reg[new_i, :V] = old_reg[old_i, :V]
    return dataclasses.replace(state, grp_counts=new_counts, grp_registered=new_reg)


class JaxSolver(SolverBackend):
    def __init__(self, well_known=None, initial_claim_slots: int = 32):
        # every entrypoint that constructs this backend benefits from the
        # persistent executable cache (idempotent config update)
        from karpenter_tpu.utils.jaxtools import enable_compilation_cache

        enable_compilation_cache()
        # IterCounts (narrow, sweeps, chain_commits, chain_pods, wave_commits,
        # wave_pods, retry_lanes) of the LAST sweeps-mode solve; None before
        # any, and reset by non-sweeps solves so stale counts are never
        # misattributed. last_wave_hist is the matching width histogram
        # (list of ints) when the wavefront ran, else None.
        self.last_iters = None
        self.last_wave_hist = None
        self.well_known = (
            well_known if well_known is not None else wk.WELL_KNOWN_LABELS
        )
        # grows on overflow and persists — a steady workload pays the
        # escalation retries once, not per solve
        self.claim_slots = claim_axis_bucket(initial_claim_slots)
        # lifetime count of _SlotOverflow escalations (each one is a full
        # recompile at the next claim bucket) — benches record it alongside
        # wall time to attribute escalation cost
        self.claim_escalations = 0
        # lifetime program-cache lookups (see _program_key) — bench.py takes
        # deltas per shape to report the compile-cache hit rate
        self.compile_cache_hits = 0
        self.compile_cache_misses = 0
        # obs/explain.ExplainReport of the LAST solve (KARPENTER_TPU_EXPLAIN
        # only); None before any explained solve and reset per solve
        self.last_explain = None
        # phase-1 relaxation telemetry of the LAST solve
        # (KARPENTER_TPU_RELAX only): dict with eligible/placed/demoted/
        # claims counts; None when the last solve was pure FFD — including
        # after a validator fallback, since the returned placements are then
        # not relaxed
        self.last_relax = None
        # convex phase-1 telemetry of the LAST solve (KARPENTER_TPU_RELAX2,
        # ops/relax2.py): {"reason": None, placed, pgd_iterations, phase_s,
        # ...} when the returned result rode relax2, {"reason": <classified>}
        # on a standdown (solver_relax_fallback_total{reason}), None when the
        # phase never ran (flag off, or relaxable pods kept sweeps mode off)
        self.last_relax2 = None
        # lifetime count of full-gate rejections that forced a re-solve with
        # relaxation off (mirrors solver_relax_fallback_total per backend,
        # both phase-1 flavors)
        self.relax_fallbacks = 0
        # telemetry dict of the LAST partitioned-solve attempt
        # (KARPENTER_TPU_SHARD, shard/solve.py): {"reason": None, partitions,
        # lanes, pad_frac, ...} on success, {"reason": <classified>} on a
        # standdown, None when the shard path never ran
        self.last_shard = None
        # device-resident continuous-solve handle (KARPENTER_TPU_DEVICE_WORLD,
        # streaming/device_world.py): constructed on the first enabled cycle,
        # dropped via reset_streaming_state. Flag off, stays None forever.
        self._device_world = None

    def reset_streaming_state(self) -> None:
        """Quarantine/rejection hook (supervisor._reset_streaming): drop the
        device-resident world and its delta state so a rejected result can
        never seed the next patched cycle. No-op when DeviceWorld never ran."""
        if self._device_world is not None:
            self._device_world.reset()

    def solve(
        self,
        pods: Sequence[Pod],
        instance_types: Sequence[InstanceType],
        templates: Sequence[TemplateInfo],
        nodes: Sequence[NodeInfo] = (),
        pod_requirements_override: Optional[Sequence[Requirements]] = None,
        topology: Optional[Topology] = None,
        cluster_pods: Sequence = (),
        domains: Optional[Dict[str, set]] = None,
        pod_volumes: Optional[Sequence[Dict[str, frozenset]]] = None,
    ) -> SolveResult:
        if not pods:
            return SolveResult()
        # DeviceWorld eligibility must see the CALLER's domains: the derived
        # default below is what a cold solve would use anyway, so it never
        # blocks the resident path — only explicitly threaded domains do
        caller_domains = domains
        if domains is None:
            domains = domains_from_instance_types(instance_types, templates)

        # long-lived processes accumulate compiled executables across shape
        # buckets; bound their mmap footprint before it hits vm.max_map_count
        # (utils/jaxtools.py)
        from karpenter_tpu.utils.jaxtools import bound_executable_maps

        t0 = _now()
        bound_executable_maps()
        t0 = _t("maps-guard", t0)
        self.last_explain = None  # never misattribute a prior solve's report
        self.last_relax2 = None  # ditto for the convex phase-1 record
        max_claims = min(self.claim_slots, claim_axis_bucket(len(pods)))
        # passthrough: when the supervisor (or provisioner) already opened
        # this cycle, phases land directly under its span; a direct backend
        # call becomes its own cycle root
        allow_relax = True
        allow_relax2 = True
        with trace.cycle(
            "solve", backend=type(self).__name__, passthrough=True, pods=len(pods)
        ), self._dispatch_device(len(pods), len(nodes)):
            if _os.environ.get("KARPENTER_TPU_DEVICE_WORLD", "0") not in ("", "0"):
                # device-resident continuous solve (streaming/device_world.py):
                # the encoded world stays in donated device buffers across
                # cycles; deltas are applied as jitted row patches and ONE
                # fused dispatch returns solve + gate counts + decode tensors.
                # None = classified standdown (solver_world_patch_total) —
                # fall through to the legacy path unchanged. Lazy import:
                # flag off, the subsystem is never even loaded.
                from karpenter_tpu.streaming import device_world

                if self._device_world is None:
                    self._device_world = device_world.DeviceWorld(self)
                resident = self._device_world.try_solve(
                    pods, instance_types, templates, nodes,
                    pod_requirements_override, topology, cluster_pods,
                    caller_domains, pod_volumes, max_claims,
                )
                if resident is not None:
                    return resident
            if _os.environ.get("KARPENTER_TPU_SHARD", "0") not in ("", "0"):
                # partitioned fleet-scale path (KARPENTER_TPU_SHARD): split
                # the batch into independent sub-problems and run them as ONE
                # mesh-partitioned program. None = classified standdown
                # (solver_shard_fallback_total) — fall through unchanged.
                # Lazy import: flag off, the subsystem is never even loaded.
                from karpenter_tpu.shard import try_shard_solve

                sharded = try_shard_solve(
                    self, pods, instance_types, templates, nodes,
                    pod_requirements_override, topology, cluster_pods,
                    domains, pod_volumes,
                )
                if sharded is not None:
                    return sharded
            while True:
                try:
                    result = self._solve_with_slots(
                        pods, instance_types, templates, nodes,
                        pod_requirements_override, topology, cluster_pods, domains,
                        max_claims, pod_volumes, allow_relax, allow_relax2,
                    )
                except _SlotOverflow:
                    if max_claims >= len(pods):
                        raise RuntimeError("claim slots exhausted at pod count") from None
                    # one bucket step per overflow: with claim windowing the
                    # ladder above 128 is 160/192/224/... instead of doubling
                    # straight to 256 — a 134-claim batch stops at the 160
                    # program (~1.9x data, not ~4x)
                    max_claims = min(
                        claim_axis_bucket(max_claims + 1), claim_axis_bucket(len(pods))
                    )
                    self.claim_slots = max(self.claim_slots, max_claims)
                    self.claim_escalations += 1
                    with trace.span("escalate", max_claims=max_claims):
                        pass
                    continue
                relax2_used = (
                    self.last_relax2 is not None
                    and self.last_relax2.get("reason") is None
                )
                if self.last_relax is not None or relax2_used:
                    # the relaxed-solve contract: phase-1 placements are
                    # validator-equivalent rather than bit-identical, so EVERY
                    # result the two-phase path produced is full-gated before
                    # it leaves the backend — waterfill and convex phase 1
                    # alike; a violation falls back to a re-solve with the
                    # offending phase off (the safe, parity-proven path). The
                    # gate rides the device program when the result carries a
                    # GateContext (verify/, KARPENTER_TPU_DEVICE_GATE) — the
                    # change that makes relax-by-default affordable — and is
                    # the host full_gate_relaxed otherwise.
                    from karpenter_tpu.verify import gate_relaxed

                    violations = gate_relaxed(
                        result, pods, instance_types, templates, nodes,
                        pod_requirements_override, cluster_pods, domains,
                    )
                    if violations:
                        RELAX_FALLBACK.inc({"reason": "gate-rejected"})
                        self.relax_fallbacks += 1
                        if relax2_used:
                            allow_relax2 = False
                            self.last_relax2 = {"reason": "gate-rejected"}
                        else:
                            allow_relax = False
                        with trace.span(
                            "relax_fallback", violations=len(violations)
                        ):
                            pass
                        continue
                return result

    def _explain(
        self, out, problem, state, meta, kinds, failed, failed_rows,
        pod_kinds, instance_types, total_pods,
    ):
        """Run the post-pass gate attribution (ops/ffd_step.attribute_pods)
        over the failed rows, decode reasons, attach bounded winning-candidate
        rationale, and publish the ExplainReport (ring + metrics). Returns the
        raw attribution words (stamped into FFDResult.explain)."""
        import time

        from karpenter_tpu.obs import explain as obs_explain
        from karpenter_tpu.ops.ffd_step import attribute_pods

        t0 = time.perf_counter()
        with trace.span("explain", failed=len(failed)):
            words = attribute_pods(problem, state, failed_rows)
            report = obs_explain.ExplainReport(
                backend=type(self).__name__,
                trace_id=trace.current_trace_id(),
                total_pods=total_pods,
                scheduled=total_pods - len(failed),
            )
            pod_requests = np.asarray(problem.pod_requests)
            for i, orig in enumerate(failed):
                row = failed_rows[i]
                expl = obs_explain.decode_pod(orig, int(kinds[row]), words[i])
                if expl.reason == obs_explain.REASON_RESOURCES:
                    requests = {
                        name: float(pod_requests[row, ri])
                        for ri, name in enumerate(meta.resource_names)
                        if ri < pod_requests.shape[1] and pod_requests[row, ri] > 0
                    }
                    hint = obs_explain.resource_hint(requests, instance_types)
                    if hint:
                        expl.hint = hint
                report.pods[orig] = expl
            if pod_kinds:
                report.nominations = self._nominations(
                    problem, state, meta, pod_kinds
                )
            report.overhead_s = time.perf_counter() - t0
            trace.attr("reasons", report.counts())
            trace.attr("overhead_s", round(report.overhead_s, 6))
            obs_explain.publish(report)
        self.last_explain = report
        out.explain = report
        return words

    def _nominations(self, problem, state, meta, pod_kinds):
        """Winning-candidate rationale for up to KARPENTER_TPU_EXPLAIN_MAX
        scheduled pods in commit order (pod_kinds preserves insertion order
        across passes): the chosen bin and its per-resource slack against the
        end-of-pass bin state — the margins the pod's commit left behind."""
        import itertools

        from karpenter_tpu.obs import explain as obs_explain

        cap = obs_explain.max_pods()
        node_requests, claim_requests, claim_it_ok = jax.device_get(
            (state.node_requests, state.claim_requests, state.claim_it_ok)
        )
        node_avail = np.asarray(problem.node_avail)
        it_alloc = np.asarray(problem.it_alloc)
        R = len(meta.resource_names)
        noms = {}
        for orig, (kind, index) in itertools.islice(pod_kinds.items(), cap):
            if kind == KIND_NODE and index < len(node_avail):
                slack = node_avail[index][:R] - node_requests[index][:R]
                bin_name = meta.node_names[index]
            elif index < len(claim_requests):
                surviving = np.flatnonzero(claim_it_ok[index])
                best = (
                    it_alloc[surviving].max(axis=0)
                    if len(surviving)
                    else np.zeros(it_alloc.shape[1])
                )
                slack = best[:R] - claim_requests[index][:R]
                bin_name = int(index)
            else:
                continue
            margins = {
                meta.resource_names[ri]: round(float(slack[ri]), 6)
                for ri in range(min(R, len(slack)))
            }
            worst = (
                min(margins.items(), key=lambda kv: kv[1])
                if margins
                else (None, 0.0)
            )
            noms[orig] = {
                "kind": obs_explain.KIND_NAMES[kind],
                "bin": bin_name,
                "margin_basis": "end-of-pass",
                "margins": margins,
                "min_margin": {"resource": worst[0], "value": worst[1]},
            }
        return noms

    @staticmethod
    def _dispatch_device(n_pods: int, n_nodes: int):
        """Small problems run on the host CPU device (see _HOST_SMALL_BATCH);
        everything else keeps the process default (TPU when present)."""
        import contextlib

        if 0 < _HOST_SMALL_BATCH and n_pods <= _HOST_SMALL_BATCH and n_nodes <= _HOST_SMALL_BATCH:
            try:
                cpu = jax.devices("cpu")[0]
            except RuntimeError:
                return contextlib.nullcontext()
            if jax.default_backend() != "cpu":
                return jax.default_device(cpu)
        return contextlib.nullcontext()

    def _relax_phase(self, problem, max_claims):
        """Phase 1 of the two-phase solve (KARPENTER_TPU_RELAX): dispatch the
        dense relaxation program over the padded problem and return its
        RelaxOut (carried state + per-pod verdicts + residue mask), or None
        when it placed nothing — the plain sweeps program is strictly better
        then (nothing to seed, no second executable to compile). Instrumented
        exactly like the generic dispatch below: program-key cache accounting,
        AOT executable table, program registry, transfer bytes, trace span."""
        relax_place = relax.relax_place
        key = _program_key(relax_place, max_claims, problem)
        cache_hit = key in _COMPILED_PROGRAMS
        _COMPILED_PROGRAMS.add(key)
        COMPILE_CACHE.inc({"result": "hit" if cache_hit else "miss"})
        if cache_hit:
            self.compile_cache_hits += 1
            span_name = "relax"
        else:
            self.compile_cache_misses += 1
            span_name = "compile"
        prob_bytes = _nbytes(problem)
        TRANSFER_BYTES.inc({"direction": "h2d"}, prob_bytes)
        reg_eqns = None
        if not cache_hit and programs.eqns_enabled():
            reg_eqns = programs.maybe_count_eqns(
                lambda: jax.make_jaxpr(
                    lambda: relax_place(problem, max_claims)
                )()
            )
        aot_handle = aot.maybe_begin(relax_place, problem, max_claims, None)
        obs = programs.begin_dispatch(relax_place.__name__, max_claims, problem)
        with trace.span(
            span_name,
            cache="hit" if cache_hit else "miss",
            program=relax_place.__name__,
        ) as sp:
            if aot_handle is not None:
                rout = aot_handle.call()
            else:
                rout = relax_place(problem, max_claims)
            # the stats scalars are all phase 2 needs on the host; the state
            # and verdict tensors stay on device and ride into the carried
            # sweeps dispatch (which donates them back)
            stats = jax.device_get(rout.stats)
            d2h = _nbytes(stats)
            TRANSFER_BYTES.inc({"direction": "d2h"}, d2h)
            if obs is not None:
                source = obs.finish(
                    problem_bytes=prob_bytes,
                    result_bytes=d2h,
                    eqns=reg_eqns,
                    source_override=(
                        aot_handle.source_override
                        if aot_handle is not None else None
                    ),
                )
                if sp is not None:
                    sp.attrs["program_key"] = obs.key
                    sp.attrs["cache_source"] = source
            self.last_relax = {
                "eligible": int(stats.eligible),
                "placed": int(stats.placed),
                "demoted": int(stats.demoted),
                "claims": int(stats.claims),
            }
            if sp is not None:
                for field, value in self.last_relax.items():
                    sp.count(field, value)
        if self.last_relax["placed"] <= 0:
            self.last_relax = None
            return None
        return rout

    def _relax2_standdown(self, reason, **info):
        """Classified convex-phase-1 standdown: count it on the round-15
        fallback counter (bounded vocabulary, ops/relax2.STANDDOWN_REASONS),
        record it for supervisor.status()/bench, and fall through to the
        waterfill/sweeps path by returning None. Mirrors shard/solve.py's
        _standdown playbook."""
        RELAX_FALLBACK.inc({"reason": reason})
        self.last_relax2 = {"reason": reason, **info}
        with trace.span("relax2_standdown", reason=reason):
            pass
        return None

    def _relax2_phase(self, problem, max_claims):
        """Convex phase 1 (KARPENTER_TPU_RELAX2): dispatch the projected-
        gradient + rounding program (ops/relax2.py) and return its RelaxOut,
        or None on a classified standdown — the waterfill and the sweeps
        repair then run exactly as if the flag were off. Instrumented like
        _relax_phase; lazy import keeps the module off the flag-off solve
        path entirely."""
        import time as _time_mod

        from karpenter_tpu.ops import relax2

        t_phase = _time_mod.perf_counter()
        try:
            if not relax2.relax_applicable(problem):
                return self._relax2_standdown("finite-pool")
            relax2_place = relax2.relax2_place
            key = _program_key(relax2_place, max_claims, problem)
            cache_hit = key in _COMPILED_PROGRAMS
            _COMPILED_PROGRAMS.add(key)
            COMPILE_CACHE.inc({"result": "hit" if cache_hit else "miss"})
            if cache_hit:
                self.compile_cache_hits += 1
                span_name = "relax2"
            else:
                self.compile_cache_misses += 1
                span_name = "compile"
            prob_bytes = _nbytes(problem)
            TRANSFER_BYTES.inc({"direction": "h2d"}, prob_bytes)
            reg_eqns = None
            if not cache_hit and programs.eqns_enabled():
                reg_eqns = programs.maybe_count_eqns(
                    lambda: jax.make_jaxpr(
                        lambda: relax2_place(problem, max_claims)
                    )()
                )
            aot_handle = aot.maybe_begin(relax2_place, problem, max_claims, None)
            obs = programs.begin_dispatch(
                relax2_place.__name__, max_claims, problem
            )
            with trace.span(
                span_name,
                cache="hit" if cache_hit else "miss",
                program=relax2_place.__name__,
            ) as sp:
                if aot_handle is not None:
                    rout = aot_handle.call()
                else:
                    rout = relax2_place(problem, max_claims)
                # the stats scalars are all the host needs; state and verdict
                # tensors stay on device and ride the carried sweeps dispatch
                stats = jax.device_get(rout.stats)
                d2h = _nbytes(stats)
                TRANSFER_BYTES.inc({"direction": "d2h"}, d2h)
                if obs is not None:
                    source = obs.finish(
                        problem_bytes=prob_bytes,
                        result_bytes=d2h,
                        eqns=reg_eqns,
                        source_override=(
                            aot_handle.source_override
                            if aot_handle is not None else None
                        ),
                    )
                    if sp is not None:
                        sp.attrs["program_key"] = obs.key
                        sp.attrs["cache_source"] = source
            eligible = int(stats.eligible)
            residual = float(stats.residual)
            capviol = float(stats.capviol)
            if eligible <= 0:
                return self._relax2_standdown(
                    relax2.classify_ineligible(problem)
                )
            if not relax2.converged(residual, capviol):
                return self._relax2_standdown(
                    "non-convergence", residual=residual, capviol=capviol,
                    pgd_iterations=int(stats.pgd_iterations),
                )
            if int(stats.placed) <= 0:
                return self._relax2_standdown(
                    "rounding-overflow", eligible=eligible,
                    overflow=int(stats.overflow),
                    round_demoted=int(stats.round_demoted),
                )
            self.last_relax2 = {
                "reason": None,
                "eligible": eligible,
                "placed": int(stats.placed),
                "demoted": int(stats.demoted),
                "claims": int(stats.claims),
                "pgd_iterations": int(stats.pgd_iterations),
                "residual": residual,
                "capviol": capviol,
                "rounding": {
                    "overflow": int(stats.overflow),
                    "demoted": int(stats.round_demoted),
                },
                "phase_s": round(_time_mod.perf_counter() - t_phase, 6),
            }
            return rout
        except Exception as exc:  # never trade latency for an unsolved batch
            return self._relax2_standdown("error", error=repr(exc))

    def _solve_with_slots(
        self, pods, instance_types, templates, nodes,
        pod_requirements_override, topology, cluster_pods, domains, max_claims,
        pod_volumes=None, allow_relax=True, allow_relax2=True,
    ) -> SolveResult:
        t_init = _now()
        self.last_relax = None  # never misattribute a prior attempt's phase 1
        # copy-on-write: pods are only copied when relaxation is about to
        # mutate them — the common all-scheduled case pays no deepcopy
        work = list(pods)
        copied = set()
        vocab_pods = list(pods)  # frozen vocabulary seed (originals never mutate)
        # a caller-provided topology is isolated per attempt, so a _SlotOverflow
        # retry re-evaluates the unrelaxed pods against unrelaxed group state
        topo = (
            topology.clone()
            if topology is not None
            else Topology(domains, batch_pods=work, cluster_pods=cluster_pods)
        )
        for n in nodes:
            topo.register(wk.LABEL_HOSTNAME, n.name)
        prefs = Preferences(
            tolerate_prefer_no_schedule=any(
                t.effect == "PreferNoSchedule" for tpl in templates for t in tpl.taints
            )
        )
        encoder = Encoder(self.well_known)

        # When nothing in the batch can relax, the retry passes are pure
        # requeue-until-no-progress — fused into ONE device program
        # (solve_ffd_sweeps): attempt order, carried state, and NO_SLOT
        # timing are identical to the pass-per-launch loop, so this is an
        # exact fast path, not an approximation. Any relaxable pod (or a
        # PreferNoSchedule blanket, which makes every pod relaxable once)
        # keeps the per-pass loop: the reference relaxes one notch per
        # failed attempt (scheduler.go:157-168) and that requires host
        # re-encoding between passes.
        use_sweeps = (
            not _USE_RUNS
            and not prefs.tolerate_prefer_no_schedule
            and not any(Preferences.is_relaxable(p) for p in work)
        )
        _t("pre-loop-init", t_init)
        out = SolveResult()
        pod_kinds: Dict[int, tuple] = {}  # original index -> (kind, bin index)
        state = None
        meta = None
        np_final = None
        prev_group_keys = None
        donated_total = 0  # carried-state bytes reclaimed in place this solve
        queue = list(range(len(work)))
        while queue:
            t0 = _now()
            with trace.span("encode", queue=len(queue)):
                encoded = encoder.encode(
                    [work[i] for i in queue],
                    instance_types,
                    templates,
                    nodes,
                    # the override pins label requirements for the whole solve —
                    # relaxation still runs its full ladder, but node-affinity
                    # steps can't change the pinned reqs (only topology-side
                    # effects like spread node-filters survive); the override's
                    # full universe seeds the frozen vocabulary
                    pod_reqs_override=(
                        [pod_requirements_override[i] for i in queue]
                        if pod_requirements_override is not None
                        else None
                    ),
                    topology=topo,
                    num_claim_slots=max_claims,
                    vocab_pods=vocab_pods,
                    vocab_reqs=pod_requirements_override,
                    pod_volumes=(
                        [pod_volumes[i] for i in queue]
                        if pod_volumes is not None
                        else None
                    ),
                )
            t0 = _t(f"encode q={len(queue)}", t0)
            with trace.span("bucket", max_claims=max_claims):
                # each pass pads to its own queue's pow2 bucket: a retry pass
                # over the failed minority scans far fewer steps than the full
                # batch, at the cost of at most log2(P) cached compiles per
                # shape family
                problem, meta = pad_problem(encoded.problem), encoded.meta
                t0 = _t("pad", t0)
                group_keys = [
                    tg.hash_key()
                    for tg in list(topo.topologies.values())
                    + list(topo.inverse_topologies.values())
                ]
                if state is not None and group_keys != prev_group_keys:
                    # relaxation changed the group set (e.g. a dropped OR term
                    # produced a new spread node-filter): remap carried rows to
                    # the new group order; brand-new groups start from the
                    # fresh census, exactly like the reference's countDomains
                    # on Update
                    state = _remap_group_state(
                        state, prev_group_keys, group_keys, problem
                    )
                prev_group_keys = group_keys
            t0 = _t("group-remap", t0)
            if _USE_RUNS:
                solve = solve_ffd_runs
            elif use_sweeps:
                # learned ordering (KARPENTER_TPU_ORDER_POLICY): the policy
                # entries are signature-identical twins with the scorer and
                # requeue sort compiled in; distinct __name__ keeps program
                # keys, AOT table entries, and registry rows separate
                if ordering.lanes_enabled():
                    solve = solve_ffd_sweeps_policy
                    ORDER_POLICY_SOLVES.inc({"part": "lane"})
                else:
                    solve = solve_ffd_sweeps
            else:
                solve = solve_ffd
            rout = None
            if (
                use_sweeps
                and allow_relax2
                and state is None
                and _os.environ.get("KARPENTER_TPU_RELAX2", "0") == "1"
            ):
                # convex phase 1 (KARPENTER_TPU_RELAX2): projected-gradient
                # solve over the fractional pod x bin polytope, rounded and
                # committed (ops/relax2.py). None = classified standdown
                # (solver_relax_fallback_total{reason}) — fall through to
                # the waterfill unchanged. The env check here (not a relax2
                # helper) keeps the module un-imported on the flag-off path.
                rout = self._relax2_phase(problem, max_claims)
            if (
                rout is None
                and use_sweeps
                and allow_relax
                and state is None
                and relax.enabled()
                and relax.relax_applicable(problem)
            ):
                # phase 1 (KARPENTER_TPU_RELAX): one dense relaxation program
                # places the eligible bulk, then the SAME sweeps loop repairs
                # the residue carrying phase 1's claim landscape and per-pod
                # verdicts. Sweeps mode runs exactly one pass, so phase 1
                # only ever fires here with fresh state.
                rout = self._relax_phase(problem, max_claims)
            if use_sweeps:
                if rout is not None:
                    import dataclasses

                    if ordering.lanes_enabled():
                        solve = solve_ffd_sweeps_carried_policy
                        ORDER_POLICY_SOLVES.inc({"part": "lane"})
                    else:
                        solve = solve_ffd_sweeps_carried
                    state = (rout.state, rout.kind, rout.index)
                    problem = dataclasses.replace(
                        problem, pod_active=rout.residue_active
                    )
            # compile-cache accounting: a program key this process has not
            # dispatched yet pays a compile (or an on-disk cache load), so the
            # device span is named "compile" for it; repeat keys are pure
            # execution and span as the solver mode ("sweeps"/"narrow").
            key = _program_key(solve, max_claims, problem)
            cache_hit = key in _COMPILED_PROGRAMS
            _COMPILED_PROGRAMS.add(key)
            COMPILE_CACHE.inc({"result": "hit" if cache_hit else "miss"})
            if cache_hit:
                self.compile_cache_hits += 1
                span_name = "sweeps" if use_sweeps else "narrow"
            else:
                self.compile_cache_misses += 1
                span_name = "compile"
            prob_bytes = _nbytes(problem)
            carried_in = _nbytes(state) if state is not None else 0
            h2d = prob_bytes + carried_in
            TRANSFER_BYTES.inc({"direction": "h2d"}, h2d)
            # carried entries marked _donates_carry consume their input state
            # in place (donate_argnums), so the carried bytes are reclaimed
            # rather than copied — solver_device_bytes{kind="donated"}
            donated = (
                carried_in if getattr(solve, "_donates_carry", False) else 0
            )
            donated_total += donated
            # program-registry jaxpr census (KARPENTER_TPU_PROGRAMS_EQNS):
            # re-trace the exact call pattern once per cold key, OUTSIDE the
            # dispatch timing so the count never pollutes compile wall time
            reg_eqns = None
            if not cache_hit and programs.eqns_enabled():
                # nullary closure: solve() inspects the CONCRETE problem on
                # the host (problem_bounds_free etc.) before entering jit, so
                # the problem must not itself be a tracer; the inner jitted
                # call still lands as a counted sub-jaxpr
                prev_state = state
                reg_eqns = programs.maybe_count_eqns(
                    lambda: jax.make_jaxpr(
                        lambda: solve(problem, max_claims, init=prev_state)
                    )()
                )
            # AOT executable table (KARPENTER_TPU_AOT_RESTORE): when on, the
            # dispatch is served by a snapshot-backed Compiled (restored off
            # disk, or compiled-and-persisted write-through); None falls
            # through to the plain jit path — including on ANY aot-layer error
            aot_handle = aot.maybe_begin(solve, problem, max_claims, state)
            # program registry (KARPENTER_TPU_PROGRAMS): None when off
            obs = programs.begin_dispatch(solve.__name__, max_claims, problem)
            with trace.span(
                span_name,
                cache="hit" if cache_hit else "miss",
                program=solve.__name__,
            ) as sp:
                if aot_handle is not None:
                    result = aot_handle.call()
                else:
                    result = solve(problem, max_claims, init=state)
                state = result.state
                # one batched fetch: device_get issues async copies for all
                # buffers before waiting, so the pass pays a single runtime
                # roundtrip instead of one per array. The sweeps fast path
                # always exits after this pass, so the final-decode state
                # rides the same roundtrip.
                if use_sweeps:
                    fetched = jax.device_get(
                        (
                            result.kind,
                            result.index,
                            result.iters,
                            result.wave_hist,
                            state.claim_open,
                            state.claim_tpl,
                            state.claim_it_ok,
                            state.claim_requests,
                            state.claim_req.admitted,
                            state.claim_req.comp,
                            state.claim_req.gt,
                            state.claim_req.lt,
                            state.claim_req.defined,
                        )
                    )
                    kinds, indices, _iters, _whist, *np_final = fetched
                    # the device-cost diagnostic (rides the same roundtrip):
                    # IterCounts named fields, still tuple-compatible
                    self.last_iters = IterCounts(*(int(x) for x in _iters))
                    # i32[W+1] wavefront-width histogram; None when the
                    # wavefront is off (flag-off keeps the program unchanged)
                    self.last_wave_hist = (
                        [int(x) for x in _whist] if _whist is not None else None
                    )
                else:
                    fetched = jax.device_get((result.kind, result.index))
                    kinds, indices = fetched
                    np_final = None
                    self.last_iters = None
                    self.last_wave_hist = None
                d2h = _nbytes(fetched)
                TRANSFER_BYTES.inc({"direction": "d2h"}, d2h)
                if obs is not None:
                    # dispatch + fetch observed: wall is the compile cost on
                    # a first dispatch (memory hits record launch/bytes only)
                    source = obs.finish(
                        problem_bytes=prob_bytes,
                        carried_bytes=carried_in,
                        result_bytes=d2h,
                        donated_bytes=donated,
                        eqns=reg_eqns,
                        source_override=(
                            aot_handle.source_override
                            if aot_handle is not None else None
                        ),
                    )
                    if sp is not None:
                        # Perfetto waterfalls name the program that compiled
                        sp.attrs["program_key"] = obs.key
                        sp.attrs["cache_source"] = source
                if sp is not None:
                    sp.count("h2d_bytes", h2d)
                    sp.count("d2h_bytes", d2h)
                    if self.last_iters is not None:
                        for field, value in zip(
                            IterCounts._fields, self.last_iters
                        ):
                            sp.count(field, value)
            t0 = _t("device-solve", t0)
            if (kinds[: len(queue)] == KIND_NO_SLOT).any():
                raise _SlotOverflow()

            with trace.span("decode"):
                failed = []
                failed_rows = []  # device row per failed orig (explain lookup)
                progress = False
                for row in range(len(meta.pod_order)):
                    orig = queue[meta.pod_order[row]]
                    kind, index = int(kinds[row]), int(indices[row])
                    if kind in (KIND_NODE, KIND_CLAIM, KIND_NEW_CLAIM):
                        pod_kinds[orig] = (kind, index)
                        progress = True
                    else:
                        failed.append(orig)
                        failed_rows.append(row)
                relaxed_any = False
                if not use_sweeps:  # sweeps imply nothing is relaxable
                    for orig in failed:
                        if orig not in copied:
                            work[orig] = copy.deepcopy(work[orig])
                            copied.add(orig)
                        if prefs.relax(work[orig]) is not None:
                            relaxed_any = True
                            topo.update(work[orig])
            t0 = _t("decode+relax", t0)
            if use_sweeps or (not progress and not relaxed_any):
                # terminal failures: reconstruct the reference's per-template
                # forensics host-side (solver/forensics.py) — failed pods are
                # rare, and the rendered reason matches the oracle's exactly
                from karpenter_tpu.solver.forensics import failure_reason

                for orig in failed:
                    out.failures[orig] = failure_reason(
                        work[orig],
                        instance_types,
                        templates,
                        pod_reqs=(
                            pod_requirements_override[orig]
                            if pod_requirements_override is not None
                            else None
                        ),
                        well_known=self.well_known,
                    ) or FAIL_INCOMPATIBLE
                # placement explainability (single flag check per solve; the
                # attribution pass is a separate program over the final state,
                # so placements are bit-identical with the flag on or off)
                from karpenter_tpu.obs import explain as obs_explain

                if obs_explain.enabled() and state is not None:
                    result.explain = self._explain(
                        out, problem, state, meta, kinds, failed, failed_rows,
                        pod_kinds, instance_types, len(pods),
                    )
                break
            queue = failed

        # -- decode final bin state (single batched fetch, see device_get note)
        t_dec = _now()
        with trace.span("decode", final=True):
            if state is not None and np_final is None:
                np_final = jax.device_get(
                    (state.claim_open, state.claim_tpl, state.claim_it_ok,
                     state.claim_requests, state.claim_req.admitted,
                     state.claim_req.comp, state.claim_req.gt,
                     state.claim_req.lt, state.claim_req.defined)
                )
                TRANSFER_BYTES.inc({"direction": "d2h"}, _nbytes(np_final))
            decode_claim_placements(
                out, meta, max_claims,
                np_final if state is not None else None, pod_kinds,
            )
        _t("final-decode", t_dec)
        # per-solve-cycle device-memory watermark (KARPENTER_TPU_PROGRAMS):
        # live/peak device bytes + the carried FFDState footprint — the
        # numbers the carried-buffer diet (ROADMAP open item 1) tracks
        programs.sample_memory(
            carried_bytes=_nbytes(state) if state is not None else 0,
            pods=len(pods),
            cycle=trace.current_trace_id(),
            donated_bytes=donated_total,
        )
        if use_sweeps and meta is not None:
            # single-pass solves hand the device gate (verify/) the exact
            # padded tensors this result decoded from; multi-pass ladders
            # re-encode per pass (the final problem covers only the last
            # queue) so they stay on the host validator
            from karpenter_tpu import verify

            out.verify_ctx = verify.make_context(
                problem, meta, max_claims, len(pods),
                pod_requirements_override is not None,
            )
        return out
