"""Scheduling-failure forensics — why a pod could not be placed.

The reference deliberately does NOT short-circuit instance-type filtering so
it can tell the operator which criteria eliminated every instance type:
filterInstanceTypesByRequirements tracks per-criterion and pairwise results
(nodeclaim.go:225-260) and FailureReason() renders them (nodeclaim.go:161-221);
the scheduler wraps each template's failure with the nodepool name and
daemonset overhead (scheduler.go:268-283) and the event carries the message
(scheduling/events.go:52-56).

The tensor solver reduces a failed pod to one flag; these helpers reconstruct
the reference's forensics HOST-SIDE at decode time — failed pods are rare, so
a straight-line Python pass over the (price-capped) instance-type lists costs
microseconds and keeps the device program lean. Both backends call the same
function, so the rendered reasons are backend-independent.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from karpenter_tpu.apis.objects import Pod
from karpenter_tpu.cloudprovider.types import InstanceType
from karpenter_tpu.scheduling import Requirements, pod_requirements
from karpenter_tpu.solver.encode import TemplateInfo
from karpenter_tpu.utils import resources as res


@dataclass
class FilterResults:
    """filterInstanceTypesByRequirements' accumulator (nodeclaim.go:225-260):
    which single criteria and which pairs some instance type satisfied."""

    requirements_met: bool = False
    fits: bool = False
    has_offering: bool = False
    requirements_and_fits: bool = False
    requirements_and_offering: bool = False
    fits_and_offering: bool = False
    remaining: List[int] = field(default_factory=list)
    requests: Dict[str, float] = field(default_factory=dict)

    def failure_reason(self) -> str:
        """FailureReason (nodeclaim.go:161-221), string-for-string."""
        if self.remaining:
            return ""
        r, f, o = self.requirements_met, self.fits, self.has_offering
        if not r and not f and not o:
            return (
                "no instance type met the scheduling requirements or had "
                "enough resources or had a required offering"
            )
        if not r and not f:
            return "no instance type met the scheduling requirements or had enough resources"
        if not r and not o:
            return "no instance type met the scheduling requirements or had a required offering"
        if not f and not o:
            return "no instance type had enough resources or had a required offering"
        if not r:
            return "no instance type met all requirements"
        if not f:
            msg = "no instance type has enough resources"
            # the reference's special case for a user typo (m vs M)
            if self.requests.get(res.CPU, 0.0) >= 1_000_000:
                msg += " (CPU request >= 1 Million, m vs M typo?)"
            return msg
        if not o:
            return "no instance type has the required offering"
        if self.requirements_and_fits:
            return (
                "no instance type which met the scheduling requirements and "
                "had enough resources, had a required offering"
            )
        if self.fits_and_offering:
            return (
                "no instance type which had enough resources and the required "
                "offering met the scheduling requirements"
            )
        if self.requirements_and_offering:
            return (
                "no instance type which met the scheduling requirements and "
                "the required offering had the required resources"
            )
        return "no instance type met the requirements/resources/offering tuple"


def _it_fits(it: InstanceType, requests: Dict[str, float]) -> bool:
    alloc = it.allocatable()
    for name, q in requests.items():
        avail = alloc.get(name, 0.0)
        if q > avail + 1e-6 + 1e-6 * abs(avail):
            return False
    return True


def filter_instance_types(
    instance_types: Sequence[InstanceType],
    indices: Sequence[int],
    reqs: Requirements,
    requests: Dict[str, float],
) -> FilterResults:
    """The non-short-circuiting filter (nodeclaim.go:225-260) over a
    template's instance-type universe."""
    results = FilterResults(requests=dict(requests))
    for ti in indices:
        it = instance_types[ti]
        it_compat = not it.requirements.intersects(reqs)  # empty = intersects
        it_fits = _it_fits(it, requests)
        it_offer = len(it.offerings.available().requirements(reqs)) > 0
        results.requirements_met = results.requirements_met or it_compat
        results.fits = results.fits or it_fits
        results.has_offering = results.has_offering or it_offer
        results.requirements_and_fits = results.requirements_and_fits or (
            it_compat and it_fits and not it_offer
        )
        results.requirements_and_offering = results.requirements_and_offering or (
            it_compat and it_offer and not it_fits
        )
        results.fits_and_offering = results.fits_and_offering or (
            it_fits and it_offer and not it_compat
        )
        if it_compat and it_fits and it_offer:
            results.remaining.append(ti)
    return results


def failure_reason(
    pod: Pod,
    instance_types: Sequence[InstanceType],
    templates: Sequence[TemplateInfo],
    pod_reqs: Optional[Requirements] = None,
    well_known=None,
) -> str:
    """Render the reference's per-template failure forensics for one
    unschedulable pod (scheduler.go:268-283 error chain + FailureReason).
    The device solver already decided the pod fails; this explains why."""
    from karpenter_tpu.apis import labels as wk

    if well_known is None:
        well_known = wk.WELL_KNOWN_LABELS
    reqs = pod_reqs if pod_reqs is not None else pod_requirements(pod)
    requests = {**res.pod_requests(pod), res.PODS: 1.0}
    parts: List[str] = []
    for tpl in templates:
        # NodeClaim.Add's gate order (nodeclaim.go:65-119)
        untolerated = tpl.taints.tolerates(pod)  # error strings, empty = ok
        if untolerated:
            parts.append(
                f'incompatible with nodepool "{tpl.nodepool_name}", '
                f"{'; '.join(untolerated)}"
            )
            continue
        if not tpl.requirements.is_compatible(reqs, well_known):
            errs = tpl.requirements.compatible(reqs, well_known)
            parts.append(
                f'incompatible with nodepool "{tpl.nodepool_name}", '
                f"incompatible requirements, {'; '.join(errs)}"
            )
            continue
        merged = tpl.requirements.copy()
        merged.add(*reqs.values())
        overhead = dict(tpl.daemon_overhead)
        total = dict(requests)
        for k, v in overhead.items():
            total[k] = total.get(k, 0.0) + v
        fr = filter_instance_types(
            instance_types, tpl.instance_type_indices, merged, total
        )
        reason = fr.failure_reason()
        if not reason:
            # every per-IT criterion passes on this template, so the solver's
            # verdict came from the stateful gates the replayed filter cannot
            # see (topology counters, limits headroom, port/volume usage)
            reason = (
                "did not fit topology/limit constraints against current state"
            )
        parts.append(
            f'incompatible with nodepool "{tpl.nodepool_name}", '
            f"daemonset overhead={_fmt_resources(overhead)}, {reason}"
        )
    if not parts:
        return "no nodepools available"
    return "; ".join(parts)


_quarantine_seq = 0


def _quarantine_max() -> int:
    """Ring size for on-disk quarantine dumps (KARPENTER_TPU_QUARANTINE_MAX,
    default 32): a crash-looping validator must not fill the disk."""
    import os

    try:
        return max(1, int(os.environ.get("KARPENTER_TPU_QUARANTINE_MAX", "32")))
    except ValueError:
        return 32


def _tenant_quarantine_max() -> int:
    """Per-tenant ring size (KARPENTER_TPU_QUARANTINE_TENANT_MAX, default 8).
    Tenant dumps live in their own ``tenant-<id>/`` namespace with their own
    cap, so one noisy tenant can only ever evict its OWN forensics — the
    global ring used to be oldest-first across all dumps, which let a
    crash-looping tenant erase every other tenant's evidence."""
    import os

    try:
        return max(
            1, int(os.environ.get("KARPENTER_TPU_QUARANTINE_TENANT_MAX", "8"))
        )
    except ValueError:
        return 8


def _tenant_dirname(tenant: str) -> str:
    """Filesystem-safe namespace directory for a tenant's quarantine ring."""
    import re

    return "tenant-" + re.sub(r"[^A-Za-z0-9._-]", "-", tenant)


def _evict_quarantine(directory: str, keep: int) -> None:
    """Oldest-first eviction down to ``keep`` files. The timestamp-pid-seq
    filename sorts lexicographically wrong across epochs of different digit
    counts, so order on mtime (ties broken by name for determinism)."""
    import os

    try:
        entries = [
            (os.path.getmtime(os.path.join(directory, name)), name)
            for name in os.listdir(directory)
            if name.startswith("quarantine-") and name.endswith(".json")
        ]
    except OSError:
        return
    entries.sort()
    for _, name in entries[: max(0, len(entries) - keep)]:
        try:
            os.remove(os.path.join(directory, name))
        except OSError:
            pass


def dump_quarantine(
    result,
    violations: Sequence,
    backend: str = "",
    directory: Optional[str] = None,
    parent_trace_id: Optional[str] = None,
    tenant: Optional[str] = None,
) -> Optional[str]:
    """Write a rejected SolveResult to a forensics JSON file so a bad
    placement can be diagnosed offline after the supervisor failed over.
    Directory: ``KARPENTER_TPU_QUARANTINE_DIR`` (default
    /tmp/karpenter-tpu-quarantine), bounded to the newest
    ``KARPENTER_TPU_QUARANTINE_MAX`` dumps (oldest evicted first). With a
    ``tenant``, the dump lands in that tenant's ``tenant-<id>/`` namespace
    with its own ``KARPENTER_TPU_QUARANTINE_TENANT_MAX`` ring — eviction
    never crosses tenant boundaries. Best-effort — quarantine must never be
    the thing that breaks the failover path — returns the path or None."""
    import json
    import os
    import time

    global _quarantine_seq
    directory = directory or os.environ.get(
        "KARPENTER_TPU_QUARANTINE_DIR", "/tmp/karpenter-tpu-quarantine"
    )
    keep = _quarantine_max()
    if tenant:
        directory = os.path.join(directory, _tenant_dirname(tenant))
        keep = _tenant_quarantine_max()
    try:
        os.makedirs(directory, exist_ok=True)
        _quarantine_seq += 1
        path = os.path.join(
            directory,
            f"quarantine-{int(time.time())}-{os.getpid()}-{_quarantine_seq}.json",
        )
        from karpenter_tpu.obs import trace

        payload = {
            "backend": backend,
            "tenant": tenant,
            # the solve cycle that produced this rejected result — grep the
            # id across /debug/traces and logs to reconstruct the timeline
            "trace_id": trace.current_trace_id(),
            # the previous cycle in the same stream (SupervisedSolver threads
            # it forward), so a churn lineage reconstructs end to end
            "parent_trace_id": parent_trace_id,
            "violations": [str(v) for v in violations],
            "new_claims": [
                {
                    "template_index": c.template_index,
                    "nodepool_name": c.nodepool_name,
                    "pod_indices": list(c.pod_indices),
                    "instance_type_indices": list(c.instance_type_indices),
                    "requests": dict(c.requests),
                    "requirements": str(c.requirements),
                }
                for c in result.new_claims
            ],
            "node_pods": {k: list(v) for k, v in result.node_pods.items()},
            "failures": {str(k): v for k, v in result.failures.items()},
        }
        explain = getattr(result, "explain", None)
        if explain is not None:
            # decision provenance travels with the quarantined result: the
            # offline diagnosis starts from the per-pod gate attribution
            payload["explain"] = (
                explain.to_dict() if hasattr(explain, "to_dict") else explain
            )
        # atomic tmp+rename: a crash (or SIGKILL) mid-dump must leave either
        # no file or a complete one — a torn half-JSON used to poison every
        # later loader pass over the ring
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1, default=str)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _evict_quarantine(directory, keep)
        return path
    except Exception:
        return None


def load_quarantine(
    directory: Optional[str] = None, limit: int = 0,
    tenant: Optional[str] = None,
) -> List[Dict]:
    """Load the quarantine ring, newest first, each payload annotated with
    its ``_path``. Walks the shared ring AND every tenant namespace (or just
    one tenant's with ``tenant=``). Tolerant by design: unparseable or
    unreadable files — pre-fix torn dumps, bit rot, concurrent eviction —
    are SKIPPED, never raised; offline forensics must degrade to the dumps
    that survived. Use :func:`scan_quarantine` to also see which paths were
    skipped."""
    return scan_quarantine(directory, limit, tenant)[0]


def scan_quarantine(
    directory: Optional[str] = None, limit: int = 0,
    tenant: Optional[str] = None,
) -> Tuple[List[Dict], List[str]]:
    """Like :func:`load_quarantine` but also returns the paths that failed
    to parse (so tooling can report how much of the ring was torn)."""
    import json
    import os

    directory = directory or os.environ.get(
        "KARPENTER_TPU_QUARANTINE_DIR", "/tmp/karpenter-tpu-quarantine"
    )
    roots = [directory]
    if tenant:
        roots = [os.path.join(directory, _tenant_dirname(tenant))]
    else:
        try:
            roots += sorted(
                os.path.join(directory, name)
                for name in os.listdir(directory)
                if name.startswith("tenant-")
                and os.path.isdir(os.path.join(directory, name))
            )
        except OSError:
            pass
    entries: List[Tuple[float, str]] = []
    for root in roots:
        try:
            entries += [
                (os.path.getmtime(os.path.join(root, name)),
                 os.path.join(root, name))
                for name in os.listdir(root)
                if name.startswith("quarantine-") and name.endswith(".json")
            ]
        except OSError:
            continue
    entries.sort(reverse=True)  # newest first
    loaded: List[Dict] = []
    skipped: List[str] = []
    for _, path in entries:
        if limit and len(loaded) >= limit:
            break
        try:
            with open(path) as f:
                payload = json.load(f)
            if not isinstance(payload, dict):
                raise ValueError("quarantine payload is not an object")
        except (OSError, ValueError):
            skipped.append(path)
            continue
        payload["_path"] = path
        loaded.append(payload)
    return loaded, skipped


def _fmt_resources(requests: Dict[str, float]) -> str:
    if not requests:
        return "{}"
    inner = ",".join(f'"{k}":"{_fmt_qty(k, v)}"' for k, v in sorted(requests.items()))
    return "{" + inner + "}"


def _fmt_qty(name: str, v: float) -> str:
    if name == res.MEMORY or name == res.EPHEMERAL_STORAGE:
        if v >= 1024**3 and v % 1024**3 == 0:
            return f"{int(v // 1024**3)}Gi"
        if v >= 1024**2 and v % 1024**2 == 0:
            return f"{int(v // 1024**2)}Mi"
    if v == int(v):
        return str(int(v))
    return f"{v:g}"
