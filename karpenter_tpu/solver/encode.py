"""Tensor codec: API objects -> SchedulingProblem.

Builds the per-batch closed-world vocabulary (label key -> lane dictionary) and
encodes pods, instance types, nodepool templates, and existing nodes into the
struct-of-arrays model in models/problem.py. See that module's docstring for
the encoding invariants.

Reference correspondence: this replaces the object graph the Go scheduler
builds in NewScheduler (provisioner.go:204-296) — requirement maps, taints,
daemon overhead — with dense arrays; what the reference recomputes per
pod-placement attempt (nodeclaim.go:225-260) becomes one-time encoding plus
on-device kernels.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.nodepool import NodePool
from karpenter_tpu.apis.objects import Pod, Taint
from karpenter_tpu.cloudprovider.types import InstanceType
from karpenter_tpu.models.problem import (
    CT_KEY,
    GT_NONE,
    HOSTNAME_KEY,
    LT_NONE,
    ProblemMeta,
    ReqTensor,
    SchedulingProblem,
    ZONE_KEY,
)
from karpenter_tpu.ops.padding import pow2_bucket
from karpenter_tpu.provisioning.topology import Topology, TOPOLOGY_TYPE_SPREAD
from karpenter_tpu.scheduling import (
    Requirement,
    Requirements,
    Taints,
    has_preferred_node_affinity,
    pod_requirements,
    strict_pod_requirements,
)
from karpenter_tpu.scheduling.hostports import HostPort, get_host_ports
from karpenter_tpu.scheduling.requirements import label_requirements
from karpenter_tpu.utils import resources as res


@dataclass
class TemplateInfo:
    """Host-side view of one NodeClaimTemplate (scheduling/nodeclaimtemplate.go:43-53):
    pool requirements + labels, taints, daemonset overhead, instance types, and
    the NodePool's remaining resource headroom (None = no limits)."""

    nodepool_name: str
    requirements: Requirements
    taints: Taints
    daemon_overhead: Dict[str, float]
    instance_type_indices: List[int]
    remaining_resources: Optional[Dict[str, float]] = None


@dataclass
class NodeInfo:
    """Host-side view of one existing node entering the solve
    (scheduling/existingnode.go:40-62)."""

    name: str
    requirements: Requirements  # label requirements (+hostname)
    taints: Taints
    available: Dict[str, float]  # allocatable - scheduled pod requests
    daemon_overhead: Dict[str, float]  # unscheduled daemonset requests
    host_ports: List["HostPort"] = None  # type: ignore[assignment]
    # CSI attach state (volumeusage.go): current unique-volume counts and the
    # node's per-driver limits (absent driver = unlimited)
    volume_used: Dict[str, int] = None  # type: ignore[assignment]
    volume_limits: Dict[str, int] = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.host_ports is None:
            self.host_ports = []
        if self.volume_used is None:
            self.volume_used = {}
        if self.volume_limits is None:
            self.volume_limits = {}


@dataclass
class EncodedProblem:
    problem: SchedulingProblem
    meta: ProblemMeta


# longest run one scan step commits; bounds the per-step ordinal-mapping
# tensors ([C, MAX_RUN] in ops/ffd.py) and the output-window scratch
MAX_RUN_LEN = 512


def constraint_signature(p: Pod) -> str:
    """Deterministic digest of everything that can distinguish two pods'
    encoded rows besides their resource requests. Used as an FFD sort
    tie-break so identical pods become *consecutive* queue rows and compress
    into runs (ops/ffd.py). Purely an ordering heuristic — run formation
    itself re-checks byte-identical encodings — so an imprecise digest can
    only cost compression, never correctness."""
    spec = p.spec
    # PERF-SENSITIVE ordering: moving labels all the way to the END (past
    # ports) was measured to DOUBLE the 10k bench's device time — reordering
    # pod CLASSES within a size tier changes the claim landscape every later
    # pod packs against (docs/PERF_NOTES.md item 5). A/B any change to this
    # list on the bench before landing it.
    #
    # Labels sit AFTER the spread constraints but BEHIND a 2-way label
    # bucket (round-6 A/B'd). Pods sharing a spread/affinity shape but
    # differing only in own labels become consecutive — exactly the
    # adjacency the chain-identity commits (pod_eqprev_chain) batch over.
    # The bucket caps that adjacency on purpose: a fully contiguous
    # same-selector hostname-spread cohort opens a fresh claim per pod
    # (each wants a zero-count domain), which blew the 10k bench past its
    # 128-claim bucket (134 needed) and onto the 256-slot program — a
    # 3.5x wall-time cliff. Interleaving two label halves bounds the
    # consecutive same-selector demand (97 claims at 10k) while keeping
    # runs long enough for the chain commits (93% of the queue batched).
    labels = repr(sorted((p.metadata.labels or {}).items()))
    parts = [
        p.namespace,
        repr(sorted(spec.node_selector.items())),
        repr(spec.affinity),
        repr(spec.tolerations),
        str(sum(labels.encode()) % 2),
        repr(spec.topology_spread_constraints),
        labels,
        repr([(c.ports or []) for c in spec.containers]),
    ]
    return "|".join(parts)


def ffd_order(pods: Sequence[Pod], requests_of=None) -> List[int]:
    """The FFD queue order: cpu desc, memory desc, then a constraint-signature
    tie-break, then creation time / sequence. The primary keys mirror the
    reference queue sort (queue.go:76-111); the signature tie-break is this
    framework's own refinement — the reference breaks resource ties purely by
    age, which is arbitrary for placement quality, while grouping
    equal-signature pods lets the device solver commit whole runs per scan
    step. Shared by every backend — parity depends on a single definition.
    ``requests_of`` lets callers share a memoized pod_requests (the encoder
    computes requests for several tensors; pods are immutable within a call).

    KARPENTER_TPU_ORDER_POLICY inserts a learned score (solver/ordering.py)
    BETWEEN the resource keys and the signature: the policy reorders pod
    CLASSES within a resource tier — the seam the round-6 signature A/B
    identified as the lever on the claim landscape — while FFD's
    resource-descending property and the identical-pod adjacency the chain
    commits need both survive (identical pods get identical features, and
    the signature still groups them below the score). Because every backend
    shares this one definition, the flag moves the device solver, the host
    oracle, and the streaming delta/warm re-solves in lockstep. Flag off,
    the keys below are built exactly as before — bit-identical ordering."""
    if requests_of is None:
        requests_of = res.pod_requests
    from karpenter_tpu.solver import ordering

    scores = ordering.order_scores(pods, requests_of) if ordering.enabled() else None
    keys = []
    for i, p in enumerate(pods):
        requests = requests_of(p)
        key = [
            -requests.get(res.CPU, 0.0),
            -requests.get(res.MEMORY, 0.0),
        ]
        if scores is not None:
            key.append(-float(scores[i]))
        key.extend(
            (
                constraint_signature(p),
                p.metadata.creation_timestamp or 0.0,
                p.metadata.creation_seq,
                i,
            )
        )
        keys.append(tuple(key))
    return sorted(range(len(pods)), key=lambda i: keys[i])


class _Vocab:
    def __init__(self):
        self.keys: List[str] = []
        self.key_index: Dict[str, int] = {}
        self.values: List[Dict[str, int]] = []

    def key(self, k: str) -> int:
        if k not in self.key_index:
            self.key_index[k] = len(self.keys)
            self.keys.append(k)
            self.values.append({})
        return self.key_index[k]

    def value(self, k: str, v: str) -> int:
        ki = self.key(k)
        vals = self.values[ki]
        if v not in vals:
            vals[v] = len(vals)
        return vals[v]

    def add_requirements(self, reqs: Requirements):
        for key in reqs:
            r = reqs.get(key)
            self.key(key)
            for v in r.values:
                self.value(key, v)

    def add_values_for_active_keys(self, reqs: Requirements):
        """Intern values only for keys already in the vocabulary.

        Instance types are always the *right side* of Intersects/Compatible
        (nodeclaim.go:262-264); a key no pod/template/node/topology entity
        defines can never fail those checks (`both_defined` gates every
        per-key test, requirements.go:241-258), so instance-type-only keys —
        e.g. 400+ instance-type-name lanes — are dropped from the device
        tensors entirely. Values of *active* keys must still be interned:
        a NotIn pod requirement admits lanes it has never seen, so the
        instance type's own values need lanes for the intersection test."""
        for key in reqs:
            ki = self.key_index.get(key)
            if ki is None:
                continue
            r = reqs.get(key)
            for v in r.values:
                self.value(key, v)


def build_vocab(
    vocab_pods: Sequence[Pod],
    templates: Sequence[TemplateInfo],
    nodes: Sequence[NodeInfo],
    groups: Sequence,
    claim_hostnames: Sequence[str],
    instance_types: Sequence[InstanceType],
    override_reqs_list: Optional[Sequence[Requirements]] = None,
    vocab_reqs: Optional[Sequence[Requirements]] = None,
) -> _Vocab:
    """The full vocabulary build, in the exact insertion order the encoder
    commits to. Module-level so the streaming delta encoder
    (streaming/delta.py) can rebuild and compare vocabularies against its
    cached encode without re-running the expensive tensor sections — lane
    numbering is insertion-ordered, so any shared-code drift here would
    silently break patched-vs-cold bit parity."""
    vocab = _Vocab()
    # zone / capacity-type / hostname keys always exist at pinned indices
    # (offering checks + claim hostname minting index them statically)
    zone_k = vocab.key(wk.LABEL_TOPOLOGY_ZONE)
    ct_k = vocab.key(wk.CAPACITY_TYPE_LABEL_KEY)
    hostname_k = vocab.key(wk.LABEL_HOSTNAME)
    if (zone_k, ct_k, hostname_k) != (ZONE_KEY, CT_KEY, HOSTNAME_KEY):
        # device kernels index these statically; survive python -O
        raise AssertionError(
            f"pinned vocab keys moved: {(zone_k, ct_k, hostname_k)}"
        )
    for p in vocab_pods:
        # seed EVERY affinity term, not just the active one: relaxation
        # can surface later OR terms / lighter preferences in later
        # passes, and the frozen vocabulary must already cover them
        vocab.add_requirements(label_requirements(p.spec.node_selector))
        aff = p.spec.affinity.node_affinity if p.spec.affinity else None
        if aff is not None:
            for term in aff.required:
                vocab.add_requirements(
                    Requirements.from_node_selector_requirements(*term.match_expressions)
                )
            for pref in aff.preferred:
                vocab.add_requirements(
                    Requirements.from_node_selector_requirements(
                        *pref.preference.match_expressions
                    )
                )
    # vocab_reqs (stable, full-universe order) must seed BEFORE the
    # per-pass pod_reqs_list, whose FFD-queue order varies across relax
    # passes — otherwise override-only keys/values get different lane
    # indices per pass and carried solver state misreads them
    if vocab_reqs is not None:
        for reqs in vocab_reqs:
            vocab.add_requirements(reqs)
    if override_reqs_list is not None:
        for reqs in override_reqs_list:
            vocab.add_requirements(reqs)
    for t in templates:
        vocab.add_requirements(t.requirements)
    for n in nodes:
        vocab.add_requirements(n.requirements)
    # topology domains + node-filter terms + claim hostname placeholders
    for tg in groups:
        vocab.key(tg.key)
        for domain in tg.domains:
            vocab.value(tg.key, domain)
        for term in tg.node_filter.terms:
            vocab.add_requirements(term)
    for h in claim_hostnames:
        vocab.value(wk.LABEL_HOSTNAME, h)
    # instance types go LAST and never create keys (active-key compaction:
    # see add_values_for_active_keys) — the key set above is exactly what
    # left-side states can ever define, so compat on any other key is
    # statically true and the lanes would be dead weight in the hot
    # [bins x instance-types] product
    for it in instance_types:
        vocab.add_values_for_active_keys(it.requirements)
        for o in it.offerings:
            vocab.value(wk.LABEL_TOPOLOGY_ZONE, o.zone)
            vocab.value(wk.CAPACITY_TYPE_LABEL_KEY, o.capacity_type)
    return vocab


def _reqs_digest(reqs: Requirements):
    """Canonical hashable form of a Requirements object — the encode fold
    is a pure function of it, so identical-class entities (duplicated pods,
    repeated templates) share one fold."""
    return tuple(
        sorted(
            (
                key,
                r.complement,
                frozenset(r.values),
                r.greater_than,
                r.less_than,
            )
            for key, r in ((k, reqs.get(k)) for k in reqs)
        )
    )


def encode_reqs_with_vocab(
    entities: Sequence[Requirements], vocab: _Vocab, lane_valid: np.ndarray
) -> ReqTensor:
    """Requirement rows under a fixed vocabulary. Row content is a pure
    function of (requirements, vocab), so the streaming delta encoder can
    build rows for just the new pods and splice them next to cached rows
    while staying bit-identical to a cold encode."""
    K, V = lane_valid.shape
    E = len(entities)
    # fold to requirement CLASSES first: at 10k diverse pods only a
    # few hundred exist, and the per-value has() probing is the
    # dominant host cost of this section (PERF_NOTES item 4). The
    # tensors are then built once per class and every entity row is
    # ONE fancy-index gather — no per-pod numpy row copies
    folded: Dict[tuple, int] = {}
    reps: List[Requirements] = []
    cls_of = np.empty(E, dtype=np.int32)
    for e, reqs in enumerate(entities):
        digest = _reqs_digest(reqs)
        ci = folded.get(digest)
        if ci is None:
            ci = folded[digest] = len(reps)
            reps.append(reqs)
        cls_of[e] = ci
    U = len(reps)
    admitted = np.zeros((U, K, V), dtype=bool)
    comp = np.zeros((U, K), dtype=bool)
    gt = np.full((U, K), GT_NONE, dtype=np.int32)
    lt = np.full((U, K), LT_NONE, dtype=np.int32)
    defined = np.zeros((U, K), dtype=bool)
    for u, reqs in enumerate(reps):
        # undefined keys are identity elements: full-admit complements
        admitted[u] = lane_valid
        comp[u] = True
        for key in reqs:
            r = reqs.get(key)
            # inactive key (instance-type rows only): no left-side
            # state defines it, so Intersects can't fail on it —
            # leaving the row undefined here is exact
            ki = vocab.key_index.get(key)
            if ki is None:
                continue
            defined[u, ki] = True
            comp[u, ki] = r.complement
            if r.greater_than is not None:
                gt[u, ki] = r.greater_than
            if r.less_than is not None:
                lt[u, ki] = r.less_than
            row = np.zeros(V, dtype=bool)
            for value, vi in vocab.values[ki].items():
                row[vi] = r.has(value)
            admitted[u, ki] = row
    return ReqTensor(
        admitted=admitted[cls_of],
        comp=comp[cls_of],
        gt=gt[cls_of],
        lt=lt[cls_of],
        defined=defined[cls_of],
    )


def segment_runs(
    pod_reqs: ReqTensor,
    pod_strict_reqs: ReqTensor,
    pod_requests: np.ndarray,
    pod_tol_tpl: np.ndarray,
    pod_tol_node: np.ndarray,
    pod_ports: np.ndarray,
    pod_port_conflict: np.ndarray,
    pod_vol_counts: np.ndarray,
    pod_grp_match: np.ndarray,
    pod_grp_selects: np.ndarray,
    pod_grp_owned: np.ndarray,
    G: int,
):
    """Run segmentation over the assembled pod-axis arrays: consecutive queue
    rows with identical encodings commit as one scan step (ops/ffd.py run
    solver); topology-inert runs take the closed-form analytic commit; runs
    that interact with topology groups take the light per-pod inner loop
    (ops/topo_runs.py) unless they carry host ports or CSI volumes (whose
    within-run interactions the closed node-capacity form does not model —
    those stay on the per-pod step). Eligibility is re-checked on byte
    equality of the encoded rows themselves, so the sort-signature heuristic
    can never cause a false merge. Module-level (shared with
    streaming/delta.py) so patched encodes segment identically to cold ones.

    Returns (run_start, run_len, run_mode, pod_eqprev, pod_eqprev_gate,
    pod_eqprev_chain)."""
    from karpenter_tpu.models.problem import RUN_ANALYTIC, RUN_SINGLE, RUN_TOPO

    P = len(pod_requests)
    # gate_interacts: some group GATES this pod's placement (matched
    # regular groups / victim of an inverse group). selects-only pods are
    # merely COUNTED by other pods' groups — their placement decisions
    # are topology-blind, and their record deltas aggregate per bin, so
    # the analytic run commit handles them exactly (its record sum).
    gate_interacts = (
        pod_grp_match.any(axis=1) | pod_grp_owned.any(axis=1)
    ) if G else np.zeros(P, dtype=bool)
    interacts = (
        gate_interacts | pod_grp_selects.any(axis=1)
    ) if G else np.zeros(P, dtype=bool)
    has_ports = pod_ports.any(axis=1) if pod_ports.size else np.zeros(P, dtype=bool)
    has_vols = (
        pod_vol_counts.any(axis=1) if pod_vol_counts.size else np.zeros(P, dtype=bool)
    )
    mergeable = ~(interacts & (has_ports | has_vols))
    # run formation needs only CONSECUTIVE-row equality of the encoded
    # lanes, which vectorizes to one elementwise comparison per array —
    # no hashing. Equal rows have equal interacts/ports/vols, so checking
    # mergeable[i] for the run head covers every member.
    if P > 1:
        same_as_prev = np.ones(P, dtype=bool)
        same_as_prev[0] = False
        for a in (
            pod_reqs.admitted, pod_reqs.comp, pod_reqs.gt, pod_reqs.lt,
            pod_reqs.defined, pod_strict_reqs.admitted,
            pod_strict_reqs.comp, pod_strict_reqs.gt,
            pod_strict_reqs.lt, pod_strict_reqs.defined,
            pod_requests, pod_tol_tpl, pod_tol_node,
            pod_ports, pod_port_conflict, pod_vol_counts,
            pod_grp_match, pod_grp_selects, pod_grp_owned,
        ):
            if a.size:
                flat = a.reshape(P, -1)
                same_as_prev[1:] &= (flat[1:] == flat[:-1]).all(axis=1)
    else:
        same_as_prev = np.zeros(P, dtype=bool)
    pod_eqprev = same_as_prev.copy()  # byte-identity with the previous row
    # gate-identity: equality over only the arrays that can influence a
    # topology-blind pod's own placement (labels/selectors may differ —
    # they only change who counts whom, which the analytic commit's
    # record sum aggregates exactly). Only meaningful between rows that
    # are NOT gate-interacting and carry no ports/volumes when records
    # are in play (mirroring `mergeable`).
    if P > 1:
        gate_same = np.ones(P, dtype=bool)
        gate_same[0] = False
        for a in (
            pod_reqs.admitted, pod_reqs.comp, pod_reqs.gt, pod_reqs.lt,
            pod_reqs.defined, pod_requests, pod_tol_tpl, pod_tol_node,
            pod_ports, pod_port_conflict, pod_vol_counts,
        ):
            if a.size:
                flat = a.reshape(P, -1)
                gate_same[1:] &= (flat[1:] == flat[:-1]).all(axis=1)
        eligible = ~gate_interacts & mergeable
        gate_same &= eligible
        gate_same[1:] &= eligible[:-1]
    else:
        gate_same = np.zeros(P, dtype=bool)
    pod_eqprev_gate = gate_same
    # CHAIN-identity: equality over every array that can influence a
    # pod's OWN placement verdict. The full select side may differ (own
    # labels) — no gate reads it except through match∩selects (spread
    # self-count, affinity self-select bootstrap), which IS compared.
    # Differing selects only change who records whom, and both chain
    # consumers (the stride's weighted record, the run commits'
    # per-member record gather) sum records per member, so a chain
    # commit stays bit-identical to stepping its members one at a time.
    if P > 1 and G:
        chain_same = np.ones(P, dtype=bool)
        chain_same[0] = False
        for a in (
            pod_reqs.admitted, pod_reqs.comp, pod_reqs.gt, pod_reqs.lt,
            pod_reqs.defined, pod_strict_reqs.admitted,
            pod_strict_reqs.comp, pod_strict_reqs.gt,
            pod_strict_reqs.lt, pod_strict_reqs.defined,
            pod_requests, pod_tol_tpl, pod_tol_node,
            pod_ports, pod_port_conflict, pod_vol_counts,
            pod_grp_match, pod_grp_owned,
            pod_grp_match & pod_grp_selects,
        ):
            if a.size:
                flat = a.reshape(P, -1)
                chain_same[1:] &= (flat[1:] == flat[:-1]).all(axis=1)
        # ports/volumes + topology interaction stays per-pod (mirrors
        # `mergeable`): the chain commits don't model within-chain port
        # and CSI interactions against shifting topology counters
        chain_same &= mergeable
        chain_same[1:] &= mergeable[:-1]
        pod_eqprev_chain = pod_eqprev | chain_same
    else:
        pod_eqprev_chain = pod_eqprev.copy()
    run_start_l: List[int] = []
    run_len_l: List[int] = []
    run_mode_l: List[int] = []
    i = 0
    while i < P:
        j = i + 1
        if mergeable[i]:
            # runs extend over byte-identical rows AND chain-identical
            # ones: the analytic commit (ops/ffd_runs.py) gathers each
            # member's select row for its record sum, and the topo run
            # commit (ops/topo_runs.py) rebuilds the per-member
            # PodTopoStatics, so both stay exact when only the select
            # side differs across the run
            while j < P and j - i < MAX_RUN_LEN and pod_eqprev_chain[j]:
                j += 1
        run_start_l.append(i)
        run_len_l.append(j - i)
        # length-1 runs go through the battle-tested per-pod step; the
        # run commits are only entered when they actually pay
        if j - i == 1:
            run_mode_l.append(RUN_SINGLE)
        elif gate_interacts[i]:
            run_mode_l.append(RUN_TOPO)
        else:
            run_mode_l.append(RUN_ANALYTIC)
        i = j
    return (
        np.array(run_start_l, dtype=np.int32),
        np.array(run_len_l, dtype=np.int32),
        np.array(run_mode_l, dtype=np.int32),
        pod_eqprev,
        pod_eqprev_gate,
        pod_eqprev_chain,
    )


class Encoder:
    """Encodes one scheduling batch. The vocabulary is rebuilt per batch —
    label spaces are open-ended, so there is no global dictionary to maintain
    (SURVEY.md §7 'per-batch dictionary + explicit residual')."""

    def __init__(self, well_known_labels: frozenset = wk.WELL_KNOWN_LABELS):
        self.well_known = well_known_labels

    def encode(
        self,
        pods: Sequence[Pod],
        instance_types: Sequence[InstanceType],
        templates: Sequence[TemplateInfo],
        nodes: Sequence[NodeInfo] = (),
        pod_reqs_override: Optional[Sequence[Requirements]] = None,
        topology: Optional[Topology] = None,
        num_claim_slots: int = 0,
        vocab_pods: Optional[Sequence[Pod]] = None,
        vocab_reqs: Optional[Sequence[Requirements]] = None,
        pod_volumes: Optional[Sequence[Dict[str, frozenset]]] = None,
        vocab_nodes: Optional[Sequence[NodeInfo]] = None,
        vocab_resources: Optional[Sequence[str]] = None,
    ) -> EncodedProblem:
        """``vocab_pods`` seeds the vocabulary (defaults to ``pods``): across
        the relax-and-retry passes the vocabulary must stay identical so the
        carried solver state keeps valid lane indices — callers pass the
        original unrelaxed batch there while ``pods`` shrinks to the retry
        queue. ``vocab_reqs`` seeds requirement sets that exist outside any pod
        spec (the full pod_reqs_override universe) for the same reason.
        ``vocab_nodes`` and ``vocab_resources`` extend the same freeze to the
        node-label / host-port / CSI-driver vocabularies and the resource-axis
        ordering: the partitioned solve (shard/) encodes disjoint pod/node
        slices that must stack into ONE batched program, so every
        shape-determining dictionary is seeded from the full batch while the
        tensor sections cover only this partition's rows."""
        # -- 1. FFD queue order: cpu desc, mem desc, creation, uid (queue.go:76-111)
        pod_reqs_list = (
            list(pod_reqs_override)
            if pod_reqs_override is not None
            else [pod_requirements(p) for p in pods]
        )
        pod_strict_list = (
            list(pod_reqs_list)
            if pod_reqs_override is not None
            else [
                strict_pod_requirements(p) if has_preferred_node_affinity(p) else r
                for p, r in zip(pods, pod_reqs_list)
            ]
        )
        # requests are re-read for several tensors below; pods never mutate
        # within one encode call, so memoize by object identity
        _req_memo: Dict[int, Dict[str, float]] = {}

        def preq(p):
            r = _req_memo.get(id(p))
            if r is None:
                r = res.pod_requests(p)
                _req_memo[id(p)] = r
            return r

        order = ffd_order(pods, requests_of=preq)
        pods = [pods[i] for i in order]
        pod_reqs_list = [pod_reqs_list[i] for i in order]
        pod_strict_list = [pod_strict_list[i] for i in order]
        pod_volumes_list = (
            [pod_volumes[i] for i in order] if pod_volumes is not None else None
        )
        if vocab_pods is None:
            vocab_pods = pods
        if vocab_nodes is None:
            vocab_nodes = nodes

        groups = []
        if topology is not None:
            groups = list(topology.topologies.values()) + list(
                topology.inverse_topologies.values()
            )
            inverse_from = len(topology.topologies)

        # -- 2. vocabulary over every value mentioned anywhere (build_vocab —
        # shared with streaming/delta.py, which replays it to prove lane
        # stability before patching rows)
        claim_hostnames = [claim_hostname(i) for i in range(num_claim_slots)]
        vocab = build_vocab(
            vocab_pods,
            templates,
            vocab_nodes,
            groups,
            claim_hostnames,
            instance_types,
            override_reqs_list=(
                pod_reqs_list if pod_reqs_override is not None else None
            ),
            vocab_reqs=vocab_reqs,
        )
        zone_k, ct_k, hostname_k = ZONE_KEY, CT_KEY, HOSTNAME_KEY

        K = len(vocab.keys)
        V = max((len(v) for v in vocab.values), default=1) or 1

        lane_valid = np.zeros((K, V), dtype=bool)
        lane_numeric = np.full((K, V), np.nan, dtype=np.float32)
        lane_lex_rank = np.full((K, V), 2**30, dtype=np.int32)
        for ki, vals in enumerate(vocab.values):
            for value, vi in vals.items():
                lane_valid[ki, vi] = True
                try:
                    lane_numeric[ki, vi] = float(int(value))
                except ValueError:
                    pass
            for rank, value in enumerate(sorted(vals)):
                lane_lex_rank[ki, vals[value]] = rank
        key_wellknown = np.array([k in self.well_known for k in vocab.keys], dtype=bool)

        # -- 3. resource axis
        resource_names = (
            list(vocab_resources)
            if vocab_resources is not None
            else [res.CPU, res.MEMORY, res.PODS, res.EPHEMERAL_STORAGE]
        )
        seen = set(resource_names)

        def note_resources(rl):
            for name in rl:
                if name not in seen:
                    seen.add(name)
                    resource_names.append(name)

        for p in pods:
            note_resources(preq(p))
        for it in instance_types:
            note_resources(it.capacity)
        for t in templates:
            note_resources(t.daemon_overhead)
        for n in vocab_nodes:
            note_resources(n.available)

        # -- 4. requirement tensors (encode_reqs_with_vocab — shared with the
        # streaming delta encoder so spliced new-pod rows are bit-identical)
        def encode_reqs(entities: List[Requirements]) -> ReqTensor:
            return encode_reqs_with_vocab(entities, vocab, lane_valid)

        pod_reqs = encode_reqs(pod_reqs_list)
        pod_strict_reqs = encode_reqs(pod_strict_list)
        it_reqs = encode_reqs([it.requirements for it in instance_types])
        # the run commit hoists its template x instance-type product out of
        # the claim-open loop on the invariant that instance types never
        # define the hostname key (a fresh claim's minted hostname exists
        # precisely because nothing else names it, nodeclaim.go:46-63);
        # enforce the hoist's precondition here rather than assuming it
        if it_reqs.defined[:, HOSTNAME_KEY].any():  # survive python -O
            raise AssertionError(
                "instance type requirements must not define the hostname key"
            )
        tpl_reqs = encode_reqs([t.requirements for t in templates])
        node_reqs = encode_reqs([n.requirements for n in nodes])

        # -- 5. resources
        def dense(rl) -> np.ndarray:
            return np.array(res.to_dense(rl, resource_names), dtype=np.float32)

        pod_requests = np.stack(
            [dense({**preq(p), res.PODS: 1.0}) for p in pods]
        ) if pods else np.zeros((0, len(resource_names)), dtype=np.float32)
        it_alloc = np.stack([dense(it.allocatable()) for it in instance_types]) if instance_types else np.zeros((0, len(resource_names)), dtype=np.float32)
        it_cap = np.stack([dense(it.capacity) for it in instance_types]) if instance_types else np.zeros((0, len(resource_names)), dtype=np.float32)
        tpl_overhead = np.stack([dense(t.daemon_overhead) for t in templates]) if templates else np.zeros((0, len(resource_names)), dtype=np.float32)
        node_avail = np.stack([dense(n.available) for n in nodes]) if nodes else np.zeros((0, len(resource_names)), dtype=np.float32)
        node_overhead = np.stack([dense(n.daemon_overhead) for n in nodes]) if nodes else np.zeros((0, len(resource_names)), dtype=np.float32)

        # -- 6. offerings
        T = len(instance_types)
        O = max((len(it.offerings) for it in instance_types), default=1) or 1
        offer_zone = np.zeros((T, O), dtype=np.int32)
        offer_ct = np.zeros((T, O), dtype=np.int32)
        offer_ok = np.zeros((T, O), dtype=bool)
        offer_price = np.full((T, O), np.inf, dtype=np.float32)
        for ti, it in enumerate(instance_types):
            for oi, o in enumerate(it.offerings):
                offer_zone[ti, oi] = vocab.values[zone_k][o.zone]
                offer_ct[ti, oi] = vocab.values[ct_k][o.capacity_type]
                offer_ok[ti, oi] = o.available
                offer_price[ti, oi] = o.price
        # dense (zone-lane x ct-lane) availability per instance type: lets the
        # solver's has_offering run as one MXU matmul over the bin batch
        # instead of per-offering lane gathers (TPU gathers cost more than the
        # whole packed compat product — see masks.has_offering_zc). Only built
        # when both sub-vocabularies fit the fixed 32-lane window; otherwise
        # None and the kernels fall back to the gather formulation.
        n_zone = len(vocab.values[zone_k])
        n_ct = len(vocab.values[ct_k])
        if n_zone <= 32 and n_ct <= 32:
            zb = int(pow2_bucket(max(n_zone, 1), lo=8))
            cb = int(pow2_bucket(max(n_ct, 1), lo=8))
            offer_zc = np.zeros((T, zb, cb), dtype=bool)
            np.logical_or.at(
                offer_zc,
                (np.arange(T)[:, None].repeat(O, 1), offer_zone, offer_ct),
                offer_ok,
            )
        else:
            offer_zc = None

        # -- 7. templates' instance-type universes + taints + limit headroom
        TPL = len(templates)
        tpl_it_ok = np.zeros((TPL, T), dtype=bool)
        tpl_remaining = np.full((TPL, len(resource_names)), np.inf, dtype=np.float32)
        for ti, t in enumerate(templates):
            tpl_it_ok[ti, list(t.instance_type_indices)] = True
            if t.remaining_resources is not None:
                for ri, name in enumerate(resource_names):
                    if name in t.remaining_resources:
                        tpl_remaining[ti, ri] = t.remaining_resources[name]

        # toleration folding: tolerates() reads only pod.spec.tolerations
        # (a tuple of frozen dataclasses), so a 10k batch collapses to a
        # handful of toleration CLASSES — compute one row per class and
        # expand by fancy index instead of P x TPL / P x N python loops
        tol_cls: Dict[tuple, int] = {}
        tol_reps: List[Pod] = []
        pod_tol_cls = np.empty(len(pods), dtype=np.int32)
        for pi, p in enumerate(pods):
            tk = tuple(p.spec.tolerations)
            ci = tol_cls.get(tk)
            if ci is None:
                ci = tol_cls[tk] = len(tol_reps)
                tol_reps.append(p)
            pod_tol_cls[pi] = ci
        cls_tol_tpl = np.zeros((len(tol_reps), TPL), dtype=bool)
        for ci, rep in enumerate(tol_reps):
            for ti, t in enumerate(templates):
                cls_tol_tpl[ci, ti] = not t.taints.tolerates(rep)
        cls_tol_node = np.zeros((len(tol_reps), len(nodes)), dtype=bool)
        for ci, rep in enumerate(tol_reps):
            for ni, n in enumerate(nodes):
                cls_tol_node[ci, ni] = not n.taints.tolerates(rep)
        pod_tol_tpl = cls_tol_tpl[pod_tol_cls]
        pod_tol_node = cls_tol_node[pod_tol_cls]

        # -- 8. host-port lanes: vocab over every distinct port tuple in the
        # batch, with a precomputed lane-vs-lane conflict matrix (wildcard IPs
        # fold in here, so the device check is a plain mask AND). Lanes come
        # from the frozen vocab_pods so carried port masks stay valid across
        # relax passes.
        pod_port_lists = [get_host_ports(p) for p in pods]
        port_vocab: Dict[HostPort, int] = {}
        for p in vocab_pods:
            for hp in get_host_ports(p):
                port_vocab.setdefault(hp, len(port_vocab))
        for n in vocab_nodes:
            for hp in n.host_ports:
                port_vocab.setdefault(hp, len(port_vocab))
        PT = max(len(port_vocab), 1)
        lanes = list(port_vocab.keys())
        conflict = np.zeros((PT, PT), dtype=bool)
        for a, hp_a in enumerate(lanes):
            for b, hp_b in enumerate(lanes):
                conflict[a, b] = hp_a.matches(hp_b)
        # port-row folding, same class trick as tolerations: the rows are a
        # pure function of the pod's port tuple (almost always empty), so
        # build one (ports, conflict) row pair per distinct tuple
        pod_ports = np.zeros((len(pods), PT), dtype=bool)
        pod_port_conflict = np.zeros((len(pods), PT), dtype=bool)
        port_rows: Dict[tuple, Tuple[np.ndarray, np.ndarray]] = {}
        for pi, plist in enumerate(pod_port_lists):
            pk = tuple(plist)
            rows = port_rows.get(pk)
            if rows is None:
                prow = np.zeros(PT, dtype=bool)
                crow = np.zeros(PT, dtype=bool)
                for hp in plist:
                    li = port_vocab[hp]
                    prow[li] = True
                    crow |= conflict[li]
                rows = port_rows[pk] = (prow, crow)
            pod_ports[pi] = rows[0]
            pod_port_conflict[pi] = rows[1]
        # -- CSI attach limits: one lane per driver that is limited on some
        # node (drivers no node limits never gate; see volumeusage.py)
        drivers = sorted({d for n in vocab_nodes for d in n.volume_limits})
        D = len(drivers)
        driver_idx = {d: i for i, d in enumerate(drivers)}
        pod_vol_counts = np.zeros((len(pods), D), dtype=np.int32)
        if pod_volumes_list is not None and D:
            for pi, vols in enumerate(pod_volumes_list):
                for d, ids in (vols or {}).items():
                    if d in driver_idx:
                        pod_vol_counts[pi, driver_idx[d]] = len(ids)
        node_vol_used = np.zeros((len(nodes), D), dtype=np.int32)
        node_vol_limits = np.full((len(nodes), D), 2**30, dtype=np.int32)
        for ni, n in enumerate(nodes):
            for d, count in n.volume_used.items():
                if d in driver_idx:
                    node_vol_used[ni, driver_idx[d]] = count
            for d, limit in n.volume_limits.items():
                node_vol_limits[ni, driver_idx[d]] = limit

        node_used_ports = np.zeros((len(nodes), PT), dtype=bool)
        for ni, n in enumerate(nodes):
            for hp in n.host_ports:
                node_used_ports[ni, port_vocab[hp]] = True

        # -- 9. topology groups (regular first, then inverse)
        G = len(groups)
        # F=0 when no group carries a real node filter (the common case): the
        # record() filter product then vmaps over an empty axis and compiles
        # away entirely. A filter containing an EMPTY term matches every node
        # (OR semantics, and an empty Requirements is Compatible with
        # anything), so such a filter is equivalent to no filter at all —
        # TopologyNodeFilter.for_pod emits exactly that for pods without node
        # affinity.
        def _real_terms(tg):
            terms = list(tg.node_filter.terms)
            if any(len(t) == 0 for t in terms):
                return []
            return terms

        F = max((len(_real_terms(tg)) for tg in groups), default=0)
        grp_type = np.zeros(G, dtype=np.int32)
        grp_key = np.zeros(G, dtype=np.int32)
        grp_max_skew = np.full(G, 2**31 - 1, dtype=np.int32)
        grp_min_domains = np.full(G, -1, dtype=np.int32)
        grp_counts0 = np.zeros((G, V), dtype=np.int32)
        grp_registered0 = np.zeros((G, V), dtype=bool)
        grp_inverse = np.zeros(G, dtype=bool)
        grp_has_filter = np.zeros(G, dtype=bool)
        grp_filter_valid = np.zeros((G, F), dtype=bool)
        filter_rows: List[Requirements] = []
        for gi, tg in enumerate(groups):
            grp_type[gi] = tg.type
            grp_key[gi] = vocab.key_index[tg.key]
            grp_max_skew[gi] = tg.max_skew
            if tg.min_domains is not None:
                grp_min_domains[gi] = tg.min_domains
            grp_inverse[gi] = topology is not None and gi >= inverse_from
            for domain, count in tg.domains.items():
                lane = vocab.values[grp_key[gi]][domain]
                grp_registered0[gi, lane] = True
                grp_counts0[gi, lane] = count
            terms = _real_terms(tg)
            grp_has_filter[gi] = bool(terms)
            for fi, term in enumerate(terms):
                grp_filter_valid[gi, fi] = True
            filter_rows.extend(terms + [Requirements()] * (F - len(terms)))
        grp_filter_flat = encode_reqs(filter_rows)  # [(G*F), K, V]
        grp_filter = ReqTensor(
            admitted=grp_filter_flat.admitted.reshape(G, F, K, V),
            comp=grp_filter_flat.comp.reshape(G, F, K),
            gt=grp_filter_flat.gt.reshape(G, F, K),
            lt=grp_filter_flat.lt.reshape(G, F, K),
            defined=grp_filter_flat.defined.reshape(G, F, K),
        ) if G else ReqTensor(
            admitted=np.zeros((0, F, K, V), dtype=bool),
            comp=np.zeros((0, F, K), dtype=bool),
            gt=np.zeros((0, F, K), dtype=np.int32),
            lt=np.zeros((0, F, K), dtype=np.int32),
            defined=np.zeros((0, F, K), dtype=bool),
        )
        pod_grp_selects = np.zeros((len(pods), G), dtype=bool)
        pod_grp_owned = np.zeros((len(pods), G), dtype=bool)
        # selects() depends only on (namespace, labels) — a large batch has
        # few distinct label sets, so cache rows instead of P x G matching;
        # ownership inverts each group's owner set instead of P x G lookups
        # one row per uid: the queue is deduplicated upstream, so a uid maps
        # to exactly one batch row — if that ever changes, ownership marking
        # must mark EVERY row of the uid, not just the last
        if len({p.uid for p in pods}) != len(pods):  # survive python -O
            raise AssertionError("duplicate pod uid in batch")
        uid_to_pi = {p.uid: pi for pi, p in enumerate(pods)}
        for gi, tg in enumerate(groups):
            for uid in tg.owners:
                pi = uid_to_pi.get(uid)
                if pi is not None:
                    pod_grp_owned[pi, gi] = True
        sel_cache: Dict[Tuple, np.ndarray] = {}
        for pi, p in enumerate(pods):
            lk = (p.namespace, tuple(sorted(p.metadata.labels.items())))
            row = sel_cache.get(lk)
            if row is None:
                row = np.fromiter((tg.selects(p) for tg in groups), bool, G)
                sel_cache[lk] = row
            pod_grp_selects[pi] = row
        pod_grp_match = np.where(
            grp_inverse[None, :], pod_grp_selects, pod_grp_owned
        ) if G else np.zeros((len(pods), G), dtype=bool)
        claim_hostname_lane = np.array(
            [vocab.values[hostname_k][h] for h in claim_hostnames], dtype=np.int32
        )

        # -- 10. run segmentation (segment_runs -- shared with the streaming
        # delta encoder so patched encodes segment identically to cold ones)
        P = len(pods)
        (
            run_start,
            run_len,
            run_mode,
            pod_eqprev,
            pod_eqprev_gate,
            pod_eqprev_chain,
        ) = segment_runs(
            pod_reqs, pod_strict_reqs, pod_requests, pod_tol_tpl, pod_tol_node,
            pod_ports, pod_port_conflict, pod_vol_counts,
            pod_grp_match, pod_grp_selects, pod_grp_owned, G,
        )
        pod_active = np.ones(P, dtype=bool)

        problem = SchedulingProblem(
            lane_valid=lane_valid,
            lane_numeric=lane_numeric,
            lane_lex_rank=lane_lex_rank,
            key_wellknown=key_wellknown,
            pod_reqs=pod_reqs,
            pod_requests=pod_requests,
            pod_tol_tpl=pod_tol_tpl,
            pod_tol_node=pod_tol_node,
            pod_ports=pod_ports,
            pod_port_conflict=pod_port_conflict,
            pod_strict_reqs=pod_strict_reqs,
            it_reqs=it_reqs,
            it_alloc=it_alloc,
            it_cap=it_cap,
            offer_zone=offer_zone,
            offer_ct=offer_ct,
            offer_ok=offer_ok,
            offer_price=offer_price,
            offer_zc=offer_zc,
            tpl_reqs=tpl_reqs,
            tpl_overhead=tpl_overhead,
            tpl_it_ok=tpl_it_ok,
            tpl_remaining=tpl_remaining,
            node_reqs=node_reqs,
            node_avail=node_avail,
            node_overhead=node_overhead,
            node_used_ports=node_used_ports,
            pod_vol_counts=pod_vol_counts,
            node_vol_used=node_vol_used,
            node_vol_limits=node_vol_limits,
            grp_type=grp_type,
            grp_key=grp_key,
            grp_max_skew=grp_max_skew,
            grp_min_domains=grp_min_domains,
            grp_counts0=grp_counts0,
            grp_registered0=grp_registered0,
            grp_inverse=grp_inverse,
            grp_has_filter=grp_has_filter,
            grp_filter=grp_filter,
            grp_filter_valid=grp_filter_valid,
            pod_grp_match=pod_grp_match,
            pod_grp_selects=pod_grp_selects,
            pod_grp_owned=pod_grp_owned,
            claim_hostname_lane=claim_hostname_lane,
            pod_active=pod_active,
            run_start=run_start,
            run_len=run_len,
            run_mode=run_mode,
            pod_eqprev=pod_eqprev,
            pod_eqprev_gate=pod_eqprev_gate,
            pod_eqprev_chain=pod_eqprev_chain,
        )
        meta = ProblemMeta(
            keys=list(vocab.keys),
            values_per_key=[
                [v for v, _ in sorted(vals.items(), key=lambda kv: kv[1])]
                for vals in vocab.values
            ],
            resource_names=resource_names,
            pod_order=order,
            template_names=[t.nodepool_name for t in templates],
            instance_type_names=[it.name for it in instance_types],
            node_names=[n.name for n in nodes],
            zone_key_idx=zone_k,
            ct_key_idx=ct_k,
            hostname_key_idx=hostname_k,
        )
        return EncodedProblem(problem=problem, meta=meta)


def claim_hostname(slot: int) -> str:
    """Placeholder hostname minted per claim for hostname-topology purposes
    (nodeclaim.go:48); both solver backends must agree on the naming."""
    return f"hostname-placeholder-{slot:04d}"


def domains_from_instance_types(
    instance_types: Sequence[InstanceType], templates: Sequence[TemplateInfo] = ()
) -> Dict[str, set]:
    """Default per-key domain universe: every value an instance type or
    template requirement could produce (the provisioner's domain census,
    provisioner.go:248-281)."""
    domains: Dict[str, set] = {}
    for it in instance_types:
        for key in it.requirements:
            r = it.requirements.get(key)
            if not r.complement:
                domains.setdefault(key, set()).update(r.values)
    for t in templates:
        for key in t.requirements:
            r = t.requirements.get(key)
            if not r.complement:
                domains.setdefault(key, set()).update(r.values)
    return domains


def template_from_nodepool(
    nodepool: NodePool,
    instance_types: Sequence[InstanceType],
    instance_type_indices: Sequence[int],
    daemon_pods: Sequence[Pod] = (),
) -> TemplateInfo:
    """Build a TemplateInfo the way NewNodeClaimTemplate + getDaemonOverhead do
    (nodeclaimtemplate.go:43-53, scheduler.go:324-341)."""
    tpl = nodepool.spec.template
    requirements = Requirements()
    requirements.add(
        *Requirements.from_node_selector_requirements(*tpl.spec.requirements).values()
    )
    labels = {**tpl.labels, wk.NODEPOOL_LABEL_KEY: nodepool.name}
    requirements.add(*label_requirements(labels).values())
    taints = Taints(tpl.spec.taints)

    daemons = []
    for p in daemon_pods:
        if taints.tolerates(p):
            continue
        if not requirements.is_compatible(pod_requirements(p), wk.WELL_KNOWN_LABELS):
            continue
        daemons.append(p)
    overhead = res.requests_for_pods(*daemons) if daemons else {}

    return TemplateInfo(
        nodepool_name=nodepool.name,
        requirements=requirements,
        taints=taints,
        daemon_overhead=overhead,
        instance_type_indices=list(instance_type_indices),
    )
