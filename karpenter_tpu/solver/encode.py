"""Tensor codec: API objects -> SchedulingProblem.

Builds the per-batch closed-world vocabulary (label key -> lane dictionary) and
encodes pods, instance types, nodepool templates, and existing nodes into the
struct-of-arrays model in models/problem.py. See that module's docstring for
the encoding invariants.

Reference correspondence: this replaces the object graph the Go scheduler
builds in NewScheduler (provisioner.go:204-296) — requirement maps, taints,
daemon overhead — with dense arrays; what the reference recomputes per
pod-placement attempt (nodeclaim.go:225-260) becomes one-time encoding plus
on-device kernels.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.nodepool import NodePool
from karpenter_tpu.apis.objects import Pod, Taint
from karpenter_tpu.cloudprovider.types import InstanceType
from karpenter_tpu.models.problem import (
    GT_NONE,
    LT_NONE,
    ProblemMeta,
    ReqTensor,
    SchedulingProblem,
)
from karpenter_tpu.scheduling import Requirement, Requirements, Taints, pod_requirements
from karpenter_tpu.scheduling.requirements import label_requirements
from karpenter_tpu.utils import resources as res


@dataclass
class TemplateInfo:
    """Host-side view of one NodeClaimTemplate (scheduling/nodeclaimtemplate.go:43-53):
    pool requirements + labels, taints, daemonset overhead, instance types."""

    nodepool_name: str
    requirements: Requirements
    taints: Taints
    daemon_overhead: Dict[str, float]
    instance_type_indices: List[int]


@dataclass
class NodeInfo:
    """Host-side view of one existing node entering the solve
    (scheduling/existingnode.go:40-62)."""

    name: str
    requirements: Requirements  # label requirements (+hostname)
    taints: Taints
    available: Dict[str, float]  # allocatable - scheduled pod requests
    daemon_overhead: Dict[str, float]  # unscheduled daemonset requests


@dataclass
class EncodedProblem:
    problem: SchedulingProblem
    meta: ProblemMeta


def ffd_order(pods: Sequence[Pod]) -> List[int]:
    """The FFD queue order: cpu desc, memory desc, creation time, creation
    sequence (queue.go:76-111). Shared by every backend — parity depends on a
    single definition."""
    keys = []
    for i, p in enumerate(pods):
        requests = res.pod_requests(p)
        keys.append(
            (
                -requests.get(res.CPU, 0.0),
                -requests.get(res.MEMORY, 0.0),
                p.metadata.creation_timestamp,
                p.metadata.creation_seq,
                i,
            )
        )
    return sorted(range(len(pods)), key=lambda i: keys[i])


class _Vocab:
    def __init__(self):
        self.keys: List[str] = []
        self.key_index: Dict[str, int] = {}
        self.values: List[Dict[str, int]] = []

    def key(self, k: str) -> int:
        if k not in self.key_index:
            self.key_index[k] = len(self.keys)
            self.keys.append(k)
            self.values.append({})
        return self.key_index[k]

    def value(self, k: str, v: str) -> int:
        ki = self.key(k)
        vals = self.values[ki]
        if v not in vals:
            vals[v] = len(vals)
        return vals[v]

    def add_requirements(self, reqs: Requirements):
        for key in reqs:
            r = reqs.get(key)
            self.key(key)
            for v in r.values:
                self.value(key, v)


class Encoder:
    """Encodes one scheduling batch. The vocabulary is rebuilt per batch —
    label spaces are open-ended, so there is no global dictionary to maintain
    (SURVEY.md §7 'per-batch dictionary + explicit residual')."""

    def __init__(self, well_known_labels: frozenset = wk.WELL_KNOWN_LABELS):
        self.well_known = well_known_labels

    def encode(
        self,
        pods: Sequence[Pod],
        instance_types: Sequence[InstanceType],
        templates: Sequence[TemplateInfo],
        nodes: Sequence[NodeInfo] = (),
        pod_reqs_override: Optional[Sequence[Requirements]] = None,
    ) -> EncodedProblem:
        # -- 1. FFD queue order: cpu desc, mem desc, creation, uid (queue.go:76-111)
        pod_reqs_list = (
            list(pod_reqs_override)
            if pod_reqs_override is not None
            else [pod_requirements(p) for p in pods]
        )
        order = ffd_order(pods)
        pods = [pods[i] for i in order]
        pod_reqs_list = [pod_reqs_list[i] for i in order]

        # -- 2. vocabulary over every value mentioned anywhere
        vocab = _Vocab()
        # zone / capacity-type keys always exist (offering checks index them)
        zone_k = vocab.key(wk.LABEL_TOPOLOGY_ZONE)
        ct_k = vocab.key(wk.CAPACITY_TYPE_LABEL_KEY)
        for reqs in pod_reqs_list:
            vocab.add_requirements(reqs)
        for it in instance_types:
            vocab.add_requirements(it.requirements)
            for o in it.offerings:
                vocab.value(wk.LABEL_TOPOLOGY_ZONE, o.zone)
                vocab.value(wk.CAPACITY_TYPE_LABEL_KEY, o.capacity_type)
        for t in templates:
            vocab.add_requirements(t.requirements)
        for n in nodes:
            vocab.add_requirements(n.requirements)

        K = len(vocab.keys)
        V = max((len(v) for v in vocab.values), default=1) or 1

        lane_valid = np.zeros((K, V), dtype=bool)
        lane_numeric = np.full((K, V), np.nan, dtype=np.float32)
        for ki, vals in enumerate(vocab.values):
            for value, vi in vals.items():
                lane_valid[ki, vi] = True
                try:
                    lane_numeric[ki, vi] = float(int(value))
                except ValueError:
                    pass
        key_wellknown = np.array([k in self.well_known for k in vocab.keys], dtype=bool)

        # -- 3. resource axis
        resource_names = [res.CPU, res.MEMORY, res.PODS, res.EPHEMERAL_STORAGE]
        seen = set(resource_names)

        def note_resources(rl):
            for name in rl:
                if name not in seen:
                    seen.add(name)
                    resource_names.append(name)

        for p in pods:
            note_resources(res.pod_requests(p))
        for it in instance_types:
            note_resources(it.capacity)
        for t in templates:
            note_resources(t.daemon_overhead)
        for n in nodes:
            note_resources(n.available)

        # -- 4. requirement tensors
        def encode_reqs(entities: List[Requirements]) -> ReqTensor:
            E = len(entities)
            admitted = np.zeros((E, K, V), dtype=bool)
            comp = np.zeros((E, K), dtype=bool)
            gt = np.full((E, K), GT_NONE, dtype=np.int32)
            lt = np.full((E, K), LT_NONE, dtype=np.int32)
            defined = np.zeros((E, K), dtype=bool)
            for e, reqs in enumerate(entities):
                # undefined keys are identity elements: full-admit complements
                admitted[e] = lane_valid
                comp[e] = True
                for key in reqs:
                    r = reqs.get(key)
                    ki = vocab.key_index[key]
                    defined[e, ki] = True
                    comp[e, ki] = r.complement
                    if r.greater_than is not None:
                        gt[e, ki] = r.greater_than
                    if r.less_than is not None:
                        lt[e, ki] = r.less_than
                    row = np.zeros(V, dtype=bool)
                    for value, vi in vocab.values[ki].items():
                        row[vi] = r.has(value)
                    admitted[e, ki] = row
            return ReqTensor(admitted=admitted, comp=comp, gt=gt, lt=lt, defined=defined)

        pod_reqs = encode_reqs(pod_reqs_list)
        it_reqs = encode_reqs([it.requirements for it in instance_types])
        tpl_reqs = encode_reqs([t.requirements for t in templates])
        node_reqs = encode_reqs([n.requirements for n in nodes])

        # -- 5. resources
        def dense(rl) -> np.ndarray:
            return np.array(res.to_dense(rl, resource_names), dtype=np.float32)

        pod_requests = np.stack(
            [dense({**res.pod_requests(p), res.PODS: 1.0}) for p in pods]
        ) if pods else np.zeros((0, len(resource_names)), dtype=np.float32)
        it_alloc = np.stack([dense(it.allocatable()) for it in instance_types]) if instance_types else np.zeros((0, len(resource_names)), dtype=np.float32)
        it_cap = np.stack([dense(it.capacity) for it in instance_types]) if instance_types else np.zeros((0, len(resource_names)), dtype=np.float32)
        tpl_overhead = np.stack([dense(t.daemon_overhead) for t in templates]) if templates else np.zeros((0, len(resource_names)), dtype=np.float32)
        node_avail = np.stack([dense(n.available) for n in nodes]) if nodes else np.zeros((0, len(resource_names)), dtype=np.float32)
        node_overhead = np.stack([dense(n.daemon_overhead) for n in nodes]) if nodes else np.zeros((0, len(resource_names)), dtype=np.float32)

        # -- 6. offerings
        T = len(instance_types)
        O = max((len(it.offerings) for it in instance_types), default=1) or 1
        offer_zone = np.zeros((T, O), dtype=np.int32)
        offer_ct = np.zeros((T, O), dtype=np.int32)
        offer_ok = np.zeros((T, O), dtype=bool)
        offer_price = np.full((T, O), np.inf, dtype=np.float32)
        for ti, it in enumerate(instance_types):
            for oi, o in enumerate(it.offerings):
                offer_zone[ti, oi] = vocab.values[zone_k][o.zone]
                offer_ct[ti, oi] = vocab.values[ct_k][o.capacity_type]
                offer_ok[ti, oi] = o.available
                offer_price[ti, oi] = o.price

        # -- 7. templates' instance-type universes + taints
        TPL = len(templates)
        tpl_it_ok = np.zeros((TPL, T), dtype=bool)
        for ti, t in enumerate(templates):
            tpl_it_ok[ti, list(t.instance_type_indices)] = True

        pod_tol_tpl = np.zeros((len(pods), TPL), dtype=bool)
        for pi, p in enumerate(pods):
            for ti, t in enumerate(templates):
                pod_tol_tpl[pi, ti] = not t.taints.tolerates(p)
        pod_tol_node = np.zeros((len(pods), len(nodes)), dtype=bool)
        for pi, p in enumerate(pods):
            for ni, n in enumerate(nodes):
                pod_tol_node[pi, ni] = not n.taints.tolerates(p)

        problem = SchedulingProblem(
            lane_valid=lane_valid,
            lane_numeric=lane_numeric,
            key_wellknown=key_wellknown,
            pod_reqs=pod_reqs,
            pod_requests=pod_requests,
            pod_tol_tpl=pod_tol_tpl,
            pod_tol_node=pod_tol_node,
            it_reqs=it_reqs,
            it_alloc=it_alloc,
            it_cap=it_cap,
            offer_zone=offer_zone,
            offer_ct=offer_ct,
            offer_ok=offer_ok,
            offer_price=offer_price,
            tpl_reqs=tpl_reqs,
            tpl_overhead=tpl_overhead,
            tpl_it_ok=tpl_it_ok,
            node_reqs=node_reqs,
            node_avail=node_avail,
            node_overhead=node_overhead,
        )
        meta = ProblemMeta(
            keys=list(vocab.keys),
            values_per_key=[
                [v for v, _ in sorted(vals.items(), key=lambda kv: kv[1])]
                for vals in vocab.values
            ],
            resource_names=resource_names,
            pod_order=order,
            template_names=[t.nodepool_name for t in templates],
            instance_type_names=[it.name for it in instance_types],
            node_names=[n.name for n in nodes],
            zone_key_idx=zone_k,
            ct_key_idx=ct_k,
        )
        return EncodedProblem(problem=problem, meta=meta)


def template_from_nodepool(
    nodepool: NodePool,
    instance_types: Sequence[InstanceType],
    instance_type_indices: Sequence[int],
    daemon_pods: Sequence[Pod] = (),
) -> TemplateInfo:
    """Build a TemplateInfo the way NewNodeClaimTemplate + getDaemonOverhead do
    (nodeclaimtemplate.go:43-53, scheduler.go:324-341)."""
    tpl = nodepool.spec.template
    requirements = Requirements()
    requirements.add(
        *Requirements.from_node_selector_requirements(*tpl.spec.requirements).values()
    )
    labels = {**tpl.labels, wk.NODEPOOL_LABEL_KEY: nodepool.name}
    requirements.add(*label_requirements(labels).values())
    taints = Taints(tpl.spec.taints)

    daemons = []
    for p in daemon_pods:
        if taints.tolerates(p):
            continue
        if not requirements.is_compatible(pod_requirements(p), wk.WELL_KNOWN_LABELS):
            continue
        daemons.append(p)
    overhead = res.requests_for_pods(*daemons) if daemons else {}

    return TemplateInfo(
        nodepool_name=nodepool.name,
        requirements=requirements,
        taints=taints,
        daemon_overhead=overhead,
        instance_type_indices=list(instance_type_indices),
    )
