"""Learned ordering policy: flags, weights artifact, host-side FFD tie-break.

This is the host half of KARPENTER_TPU_ORDER_POLICY (the device half — the
jitted lane scorer the sweep requeue sorts by — is ops/policy.py). It owns:

  * the flag reads. ``enabled()`` turns on the learned tie-break inside
    ``solver/encode.ffd_order`` — the ONE ordering definition every backend
    shares (device solver, host oracle, streaming delta/warm re-solves), so
    flipping the flag keeps all of them in lockstep and the oracle
    differential stays an equality test. ``lanes_enabled()`` additionally
    routes the backend to the policy solve entries
    (ops/ffd_sweeps.solve_ffd_sweeps_policy), whose per-sweep requeue sort is
    the learned wavefront lane picker. ``KARPENTER_TPU_ORDER_POLICY_LANES=0``
    isolates the host tie-break for A/Bs and for the corpus recorder, which
    must evaluate many candidate weight vectors without recompiling the solve
    program per candidate (the host order is data, not program).
  * the weights artifact: one versioned ``utils/persist.py``-framed file
    carrying both heads — ``host`` (features from un-encoded Pod objects,
    scored before the FFD sort) and ``lane`` (features from the encoded
    problem tensors, baked into the policy programs as jit-static constants).
    Load failures are CLASSIFIED (the persist reasons) and degrade to the
    built-in zero weights — score ties everywhere, which the stable sort
    resolves to exactly the static order, so a corrupt artifact costs
    nothing, not even iterations. ``solver_order_policy_loads_total{outcome}``
    records every resolution.
  * the score evaluation for the tie-break: batched numpy over the pod list,
    one matmul — ``solver_order_policy_score_seconds`` keeps its cost honest.

The committed artifact (``order_policy.v1.bin``) is produced by
``tools/train_order.py`` from corpora recorded with
``bench.py --record-order-corpus``; both are seeded and replay-deterministic,
so retraining from the committed corpus reproduces the committed bytes.

Flag off, every public function here short-circuits on one env read and
``ffd_order`` builds the exact pre-policy sort keys — bit-identical ordering,
untouched solve programs (census-pinned).
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from karpenter_tpu.metrics.registry import (
    ORDER_POLICY_LOADS,
    ORDER_POLICY_SCORE_SECONDS,
    ORDER_POLICY_SOLVES,
)

FLAG = "KARPENTER_TPU_ORDER_POLICY"
LANES_FLAG = "KARPENTER_TPU_ORDER_POLICY_LANES"
WEIGHTS_ENV = "KARPENTER_TPU_ORDER_POLICY_WEIGHTS"

WEIGHTS_KIND = "order-policy"
WEIGHTS_VERSION = 1
HOST_FEATURE_VERSION = 1
N_HOST_FEATURES = 10

_DEFAULT_ARTIFACT = os.path.join(os.path.dirname(__file__), "order_policy.v1.bin")


def enabled() -> bool:
    """Learned tie-break active (read per call, like the wavefront flag, so
    tests and the corpus recorder can toggle without reimports)."""
    return os.environ.get(FLAG, "") not in ("", "0")


def lanes_enabled() -> bool:
    """Device half active: the backend dispatches the policy solve entries
    whose requeue sort replaces the wavefront lane picker."""
    return enabled() and os.environ.get(LANES_FLAG, "1") != "0"


def builtin_weights() -> Dict:
    """Zero weights: every score ties, the stable sort preserves the static
    order exactly. The classified-fallback target — flag-on with a missing or
    corrupt artifact must cost nothing."""
    return {
        "arch": "linear",
        "feature_version": HOST_FEATURE_VERSION,
        "lane_feature_version": 1,
        "host": {"w": [0.0] * N_HOST_FEATURES, "b": 0.0, "hidden": None},
        "lane": {"w": [0.0] * 10, "b": 0.0, "hidden": None},
    }


_lock = threading.Lock()
_cache: Optional[Dict] = None
_cache_path: Optional[str] = None
_override: Optional[Dict] = None


def artifact_path() -> str:
    return os.environ.get(WEIGHTS_ENV) or _DEFAULT_ARTIFACT


def set_override(weights: Optional[Dict]) -> None:
    """Install an in-process weight dict (corpus recorder / trainer candidate
    evaluation). None restores artifact loading."""
    global _override
    with _lock:
        _override = weights


def reset_for_tests() -> None:
    global _cache, _cache_path, _override
    with _lock:
        _cache = None
        _cache_path = None
        _override = None


def _load_artifact(path: str) -> Dict:
    import json

    from karpenter_tpu.ops.policy import LANE_FEATURE_VERSION
    from karpenter_tpu.utils.persist import PersistError, load_framed

    try:
        _header, payload = load_framed(
            path, kind=WEIGHTS_KIND, min_version=WEIGHTS_VERSION
        )
        weights = json.loads(payload.decode())
    except PersistError as exc:
        ORDER_POLICY_LOADS.inc({"outcome": exc.reason})
        return builtin_weights()
    except Exception:  # noqa: BLE001 — malformed payload is corruption too
        ORDER_POLICY_LOADS.inc({"outcome": "corrupt"})
        return builtin_weights()
    if (
        weights.get("feature_version") != HOST_FEATURE_VERSION
        or weights.get("lane_feature_version") != LANE_FEATURE_VERSION
    ):
        # weights trained against a different feature layout must not score
        # this one — same classified degrade as a frame version skew
        ORDER_POLICY_LOADS.inc({"outcome": "version-skew"})
        return builtin_weights()
    ORDER_POLICY_LOADS.inc({"outcome": "loaded"})
    return weights


def active_weights() -> Dict:
    """The weight dict in force: override > artifact (cached per path) >
    built-in zeros. Never raises."""
    global _cache, _cache_path
    with _lock:
        if _override is not None:
            return _override
        path = artifact_path()
        if _cache is not None and _cache_path == path:
            return _cache
    loaded = _load_artifact(path)
    with _lock:
        _cache = loaded
        _cache_path = path
        return _cache


def _head_static(head: Dict):
    hidden = head.get("hidden")
    hidden_t = None
    if hidden:
        hidden_t = (
            tuple(tuple(float(x) for x in row) for row in hidden["w"]),
            tuple(float(x) for x in hidden["b"]),
        )
    arch = "mlp" if hidden_t is not None else "linear"
    return (arch, tuple(float(x) for x in head["w"]), float(head["b"]), hidden_t)


def lane_weights_static():
    """The lane head as a hashable nested tuple — the jit-static argument of
    the policy solve entries (ops/ffd_sweeps.py). Equal weights hash equal, so
    program caching and the AOT table key off content, not load events."""
    return _head_static(active_weights()["lane"])


def weights_digest(weights: Optional[Dict] = None) -> str:
    """Short content digest of the active weights — AOT table entries and the
    program registry use it so two processes with different artifacts never
    share an executable."""
    w = weights if weights is not None else active_weights()
    blob = repr((_head_static(w["host"]), _head_static(w["lane"]))).encode()
    return hashlib.sha256(blob).hexdigest()[:12]


# -- host-side feature head (un-encoded Pod objects) ---------------------------


def host_features(pods: Sequence, requests_of=None, signatures=None) -> np.ndarray:
    """f32[n, N_HOST_FEATURES] over API Pod objects — the pre-encode sibling
    of ops/policy.lane_features. Identical pods produce identical rows (the
    adjacency guarantee the chain commits need survives any weights).
    ``signatures`` shares encode.constraint_signature results when the caller
    already computed them."""
    from karpenter_tpu.utils import resources as res
    from karpenter_tpu.solver.encode import constraint_signature

    if requests_of is None:
        requests_of = res.pod_requests
    n = len(pods)
    if signatures is None:
        signatures = [constraint_signature(p) for p in pods]
    sig_count: Dict[str, int] = {}
    for s in signatures:
        sig_count[s] = sig_count.get(s, 0) + 1
    feats = np.zeros((n, N_HOST_FEATURES), np.float32)
    for i, p in enumerate(pods):
        requests = requests_of(p)
        spec = p.spec
        aff = spec.affinity
        node_terms = len(aff.node_affinity.required) if aff and aff.node_affinity else 0
        pod_aff = len(aff.pod_affinity.required) if aff and aff.pod_affinity else 0
        pod_anti = (
            len(aff.pod_anti_affinity.required) if aff and aff.pod_anti_affinity else 0
        )
        has_ports = any(c.ports for c in spec.containers)
        extra = sum(1 for k in requests if k not in (res.CPU, res.MEMORY))
        feats[i] = (
            np.log1p(requests.get(res.CPU, 0.0)),
            np.log1p(requests.get(res.MEMORY, 0.0) / 2.0**20),
            float(extra),
            float(len(spec.node_selector) + node_terms),
            float(len(spec.tolerations)),
            float(has_ports),
            float(len(spec.topology_spread_constraints)),
            float(pod_aff),
            float(pod_anti),
            sig_count[signatures[i]] / max(n, 1),
        )
    return feats


def _eval_head(feats: np.ndarray, head_static) -> np.ndarray:
    arch, w, b, hidden = head_static
    x = feats
    if arch == "mlp" and hidden is not None:
        w1 = np.asarray(hidden[0], np.float32)
        b1 = np.asarray(hidden[1], np.float32)
        x = np.tanh(x @ w1.T + b1)
    return (x @ np.asarray(w, np.float32) + np.float32(b)).astype(np.float32)


def order_scores(pods: Sequence, requests_of=None, signatures=None) -> np.ndarray:
    """f32[n] learned priority per pod (higher sorts earlier within its
    resource tier). The ffd_order hook — one batched feature pass + one
    matmul, timed by solver_order_policy_score_seconds."""
    t0 = time.perf_counter()
    feats = host_features(pods, requests_of, signatures)
    scores = _eval_head(feats, _head_static(active_weights()["host"]))
    ORDER_POLICY_SCORE_SECONDS.observe(time.perf_counter() - t0)
    ORDER_POLICY_SOLVES.inc({"part": "host"})
    return scores
