"""SolverBackend interface and result model.

The seam between the host control plane and the compute core (BASELINE.json
north star: a pluggable `scheduling-solver`). Two backends ship:

  - ``oracle``  (solver/oracle.py): straight-line Python mirroring the Go
    FFD semantics exactly — the semantic ground truth and parity baseline.
  - ``jax``     (solver/jax_backend.py): the tensorized lax.scan solver.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from karpenter_tpu.apis.objects import Pod
from karpenter_tpu.cloudprovider.types import InstanceType
from karpenter_tpu.scheduling import Requirements
from karpenter_tpu.solver.encode import NodeInfo, TemplateInfo

FAIL_INCOMPATIBLE = "incompatible"


@dataclass
class Placement:
    """One new claim produced by a solve: the pods packed onto it, the
    surviving instance types (input order, as the reference preserves it), and
    the narrowed requirement state."""

    template_index: int
    nodepool_name: str
    pod_indices: List[int] = field(default_factory=list)  # indices into input pods
    instance_type_indices: List[int] = field(default_factory=list)
    requirements: Optional[Requirements] = None
    requests: Dict[str, float] = field(default_factory=dict)


@dataclass
class SolveResult:
    new_claims: List[Placement] = field(default_factory=list)
    # existing-node name -> pod indices placed there this round
    node_pods: Dict[str, List[int]] = field(default_factory=dict)
    # failed pod index -> reason
    failures: Dict[int, str] = field(default_factory=dict)
    # obs/explain.ExplainReport decision provenance (KARPENTER_TPU_EXPLAIN
    # only; None when the flag is off or the backend doesn't attribute)
    explain: Optional[object] = None
    # verify.GateContext stashed by single-pass jax solves: the padded
    # problem + meta this result decoded from, which the device-side
    # verification gate (verify/) re-reads. None from the oracle backend,
    # multi-pass relax-ladder solves, and any synthetic/stripped result —
    # all of which the host validator handles as before. Excluded from
    # equality/repr: it is provenance, not part of the placement.
    verify_ctx: Optional[object] = field(default=None, compare=False, repr=False)

    def num_scheduled(self) -> int:
        return sum(len(c.pod_indices) for c in self.new_claims) + sum(
            len(v) for v in self.node_pods.values()
        )


class SolverBackend(abc.ABC):
    """One pass of the FFD pack (no relaxation loop — the provisioning layer
    owns relax-and-retry, scheduler.go:150-170)."""

    @abc.abstractmethod
    def solve(
        self,
        pods: Sequence[Pod],
        instance_types: Sequence[InstanceType],
        templates: Sequence[TemplateInfo],
        nodes: Sequence[NodeInfo] = (),
        pod_requirements_override: Optional[Sequence[Requirements]] = None,
        topology=None,  # Optional[Topology]: caller-owned group state to clone
        cluster_pods: Sequence = (),  # (Pod, node labels) pairs for the census
        domains: Optional[Dict[str, set]] = None,  # per-key domain universe
        pod_volumes: Optional[Sequence[Dict[str, frozenset]]] = None,  # per-pod
        # resolved CSI volumes (driver -> unique volume ids), parallel to pods
    ) -> SolveResult:
        ...
