"""Solver supervisor — deadlines, retries, circuit-broken fallback, salvage.

The SolverBackend the controllers actually call (operator/operator.py wires
every Provisioner through it). It wraps a primary backend (normally the JAX
solver) and an optional fallback (the pure-Python oracle — the slow exact
baseline CvxCluster-style systems pair against their fast solver) with:

  deadline   every primary solve runs under a wall-clock watchdog
             (``KARPENTER_TPU_SOLVE_DEADLINE_S``; 0 disables and the call is
             inlined with zero overhead). The watchdog is a join(timeout) on
             a daemon worker thread: a hung device call cannot be cancelled
             from Python, so the thread is abandoned and the cycle proceeds.
  classify   failures map to classes — compile / device / nan / deadline /
             encode / unknown — by exception type and message. Transient
             classes (device, deadline) are retried with capped exponential
             backoff and deterministic jitter (crc32 of the attempt, never
             the salted ``hash()``); deterministic classes go straight to
             fallback: recompiling the same program or re-running the same
             NaN-producing reduction cannot change the answer.
  validate   successful results pass the invariant gate (solver/validator.py)
             before leaving; a violation quarantines the result to disk
             (forensics.dump_quarantine), counts as a primary failure, and
             fails over — a bad placement must never reach a cloud Create.
  circuit    N consecutive primary failures trip the breaker: solves route
             straight to the fallback until a cooldown elapses, then one
             half-open probe decides between closing and re-opening. State is
             exported via the ``solver_circuit_state`` gauge and /statusz.
  salvage    when no backend can answer, the cycle is never dropped: the
             supervisor returns a SolveResult that requeues every pod via
             ``failures`` (the provisioning layer retries next cycle), and a
             validation failure with no fallback strips only the violating
             bins, keeping the placements that verified.

On the fault-free path the supervisor wraps, never alters, the primary's
result: the same object comes back bit-identical, and the added work is one
validator pass (level ``fast`` is linear in pods; ``KARPENTER_TPU_VALIDATE=0``
removes even that).
"""

from __future__ import annotations

import contextvars
import logging
import os
import threading
import time
import zlib
from typing import Dict, List, Optional, Sequence

from karpenter_tpu.metrics.registry import (
    SOLVE_DEADLINE_EXCEEDED,
    SOLVER_CIRCUIT_STATE,
    SOLVER_FALLBACK,
    SOLVER_RETRIES,
    VALIDATOR_REJECTIONS,
)
from karpenter_tpu.obs import flight, slo, trace
from karpenter_tpu.solver import validator as val
from karpenter_tpu.solver.backend import SolveResult, SolverBackend
from karpenter_tpu.testing import faults

log = logging.getLogger(__name__)

CLASS_COMPILE = "compile"
CLASS_DEVICE = "device"
CLASS_NAN = "nan"
CLASS_DEADLINE = "deadline"
CLASS_ENCODE = "encode"
CLASS_VALIDATION = "validation"
CLASS_UNKNOWN = "unknown"

# retrying helps only when the same call can succeed next time
RETRYABLE = frozenset({CLASS_DEVICE, CLASS_DEADLINE, CLASS_UNKNOWN})

CIRCUIT_CLOSED = "closed"
CIRCUIT_HALF_OPEN = "half-open"
CIRCUIT_OPEN = "open"
_CIRCUIT_GAUGE = {CIRCUIT_CLOSED: 0, CIRCUIT_HALF_OPEN: 1, CIRCUIT_OPEN: 2}


class DeadlineExceeded(Exception):
    """The watchdog gave up on a solve."""


class NaNResultError(Exception):
    """The solve returned NaN/inf request tensors (diverged reduction)."""


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def classify_failure(exc: BaseException) -> str:
    """Map an exception from the solve path to a failure class. Type name
    first (the injected fault types and jaxlib's exceptions carry their class
    in the name), then message patterns for the exceptions XLA wraps in
    RuntimeError."""
    if isinstance(exc, DeadlineExceeded):
        return CLASS_DEADLINE
    if isinstance(exc, NaNResultError):
        return CLASS_NAN
    name = type(exc).__name__.lower()
    msg = str(exc).lower()
    if "encode" in name:
        return CLASS_ENCODE
    if "compil" in name or "compil" in msg or "lowering" in msg or "mosaic" in msg:
        return CLASS_COMPILE
    if (
        "device" in name
        or "xlaruntime" in name
        or any(tok in msg for tok in ("resource_exhausted", "device", "pjrt", "dma"))
    ):
        return CLASS_DEVICE
    return CLASS_UNKNOWN


class SupervisedSolver(SolverBackend):
    def __init__(
        self,
        primary: SolverBackend,
        fallback: Optional[SolverBackend] = None,
        deadline_s: Optional[float] = None,
        retries: Optional[int] = None,
        circuit_threshold: Optional[int] = None,
        circuit_cooldown_s: Optional[float] = None,
        validate: Optional[str] = None,
        backoff_base_s: Optional[float] = None,
        time_fn=time.monotonic,
        sleep_fn=time.sleep,
        streaming: Optional[bool] = None,
        tenant: Optional[str] = None,
    ):
        # ``tenant`` names the stream this supervisor serves under the
        # multi-tenant layer (serve/): it namespaces the quarantine ring and
        # journal, labels the circuit/rejection/warm metrics, and scopes
        # tenant-selected fault rules. None (the default) is byte-identical
        # to the pre-tenant behavior — no label, shared ring, global faults.
        self.tenant = tenant
        # KARPENTER_TPU_DELTA=1 (or streaming=True) wraps the primary in the
        # warm-state streaming layer: delta-diffed snapshots re-solve only the
        # churned frontier, with cold fallback above KARPENTER_TPU_DELTA_MAX_FRAC
        # (see docs/SERVING.md). The fallback backend stays unwrapped — it is
        # the reference answer the streaming path degrades to.
        if streaming is None:
            streaming = os.environ.get("KARPENTER_TPU_DELTA", "") not in ("", "0")
        if streaming:
            from karpenter_tpu.streaming.warm import StreamingSolver

            if not isinstance(primary, StreamingSolver):
                primary = StreamingSolver(primary, tenant=tenant)
            elif tenant is not None and primary.tenant is None:
                primary.set_tenant(tenant)
        self.primary = primary
        self.fallback = fallback
        self.deadline_s = (
            deadline_s
            if deadline_s is not None
            else _env_float("KARPENTER_TPU_SOLVE_DEADLINE_S", 0.0)
        )
        self.retries = (
            retries
            if retries is not None
            else int(_env_float("KARPENTER_TPU_SOLVE_RETRIES", 1))
        )
        self.circuit_threshold = (
            circuit_threshold
            if circuit_threshold is not None
            else int(_env_float("KARPENTER_TPU_CIRCUIT_THRESHOLD", 3))
        )
        self.circuit_cooldown_s = (
            circuit_cooldown_s
            if circuit_cooldown_s is not None
            else _env_float("KARPENTER_TPU_CIRCUIT_COOLDOWN_S", 30.0)
        )
        if validate is None:
            validate = os.environ.get("KARPENTER_TPU_VALIDATE", "1")
        self.validate_level = {"0": "off", "1": "fast", "2": "full"}.get(
            validate, validate
        )
        self.backoff_base_s = (
            backoff_base_s
            if backoff_base_s is not None
            else _env_float("KARPENTER_TPU_RETRY_BACKOFF_S", 0.05)
        )
        self.backoff_cap_s = 2.0
        self._time = time_fn
        self._sleep = sleep_fn
        self._lock = threading.Lock()
        self._circuit = CIRCUIT_CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._solve_seq = 0
        # previous cycle's trace id, threaded into the next cycle as
        # parent_trace_id: a churn stream greps as one lineage in
        # /debug/traces and in quarantine dumps
        self._last_trace_id: Optional[str] = None
        self.last_failure: Optional[Dict[str, str]] = None
        self.counters: Dict[str, int] = {
            "solve_retries": 0,
            "solve_fallbacks": 0,
            "validator_rejections": 0,
            "deadline_exceeded": 0,
            "salvaged": 0,
        }
        SOLVER_CIRCUIT_STATE.set(0, self._labels())

    def _labels(self, **labels) -> Optional[Dict[str, str]]:
        """Metric labels with the tenant folded in. Returns the exact
        pre-tenant shape (None for no labels) when untenanted, so existing
        series and their tests stay bit-identical. The tenant label value
        goes through tenant_label() — bounded at fleet scale (overflow
        tenants aggregate into 'other'); quarantine/journal namespaces keep
        the raw id."""
        if self.tenant is not None:
            from karpenter_tpu.metrics.registry import tenant_label

            labels["tenant"] = tenant_label(self.tenant)
        return labels or None

    # -- public introspection (serving.py /statusz) ---------------------------

    def circuit_state(self) -> str:
        with self._lock:
            # an elapsed cooldown shows as half-open: the next solve probes
            if (
                self._circuit == CIRCUIT_OPEN
                and self._time() - self._opened_at >= self.circuit_cooldown_s
            ):
                return CIRCUIT_HALF_OPEN
            return self._circuit

    def status(self) -> Dict:
        from karpenter_tpu.obs import programs

        out = {
            "primary": type(self.primary).__name__,
            "fallback": type(self.fallback).__name__ if self.fallback else None,
            "tenant": self.tenant,
            "circuit": self.circuit_state(),
            "consecutive_failures": self._consecutive_failures,
            "deadline_s": self.deadline_s,
            "validate": self.validate_level,
            "counters": dict(self.counters),
            "last_failure": self.last_failure,
        }
        if programs.enabled():
            # which compiled programs the supervised path has been paying
            # for (compile seconds, cache-source split, last memory sample)
            out["programs"] = programs.registry().summary()
        from karpenter_tpu.obs import explain as obs_explain

        if obs_explain.enabled() or len(obs_explain.ring()):
            # decision provenance of recent solves (/debug/explain drills in)
            out["explain"] = obs_explain.summary()
        last_shard = getattr(self.primary, "last_shard", None)
        if last_shard is not None:
            # the partitioned-solve attempt of the last supervised solve
            # (KARPENTER_TPU_SHARD): reason=None means the mesh path served
            # it; otherwise the classified standdown that sent the solve to
            # the ordinary unsharded program
            out["shard"] = last_shard
        last_relax2 = getattr(self.primary, "last_relax2", None)
        if last_relax2 is not None:
            # the convex phase-1 attempt of the last supervised solve
            # (KARPENTER_TPU_RELAX2): reason=None means the returned result
            # rode relax2 (phase walls, iterations-to-convergence, placed
            # counts, rounding stats); otherwise the classified standdown
            # that sent phase 1 back to the waterfill/sweeps path
            out["relax2"] = last_relax2
        return out

    # -- circuit transitions --------------------------------------------------

    def _set_circuit(self, state: str) -> None:
        self._circuit = state
        SOLVER_CIRCUIT_STATE.set(_CIRCUIT_GAUGE[state], self._labels())

    def _route(self) -> str:
        """Where this solve starts: 'primary' (closed, or half-open probe) or
        'fallback' (open and cooling down). With no fallback there is nothing
        to route to, so the primary is always tried."""
        if self.fallback is None:
            return "primary"
        with self._lock:
            if self._circuit == CIRCUIT_CLOSED:
                return "primary"
            if self._time() - self._opened_at >= self.circuit_cooldown_s:
                self._set_circuit(CIRCUIT_HALF_OPEN)
                return "primary"
            return "fallback"

    def _record_primary_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            if self._circuit != CIRCUIT_CLOSED:
                log.info("solver circuit closed: primary backend recovered")
            self._set_circuit(CIRCUIT_CLOSED)

    def _record_primary_failure(self) -> None:
        opened = False
        with self._lock:
            self._consecutive_failures += 1
            if self._circuit == CIRCUIT_HALF_OPEN:
                # failed probe: restart the cooldown
                self._opened_at = self._time()
                self._set_circuit(CIRCUIT_OPEN)
                opened = True
            elif (
                self._circuit == CIRCUIT_CLOSED
                and self._consecutive_failures >= self.circuit_threshold
            ):
                self._opened_at = self._time()
                self._set_circuit(CIRCUIT_OPEN)
                opened = True
                log.warning(
                    "solver circuit opened after %d consecutive failures",
                    self._consecutive_failures,
                )
        if opened and slo.enabled():
            # a tripped breaker is an incident: capture the ring now, while
            # the failures that opened it are still in it
            flight.record(
                flight.KIND_CIRCUIT, state=CIRCUIT_OPEN, tenant=self.tenant,
                failures=self._consecutive_failures,
            )
            flight.snapshot_dump("circuit-open")

    # -- the solve ------------------------------------------------------------

    def solve(
        self,
        pods,
        instance_types,
        templates,
        nodes=(),
        pod_requirements_override=None,
        topology=None,
        cluster_pods=(),
        domains=None,
        pod_volumes=None,
    ) -> SolveResult:
        kwargs = dict(
            nodes=nodes,
            pod_requirements_override=pod_requirements_override,
            topology=topology,
            cluster_pods=cluster_pods,
            domains=domains,
            pod_volumes=pod_volumes,
        )
        self._solve_seq += 1
        attrs = {"pods": len(pods)}
        if self._last_trace_id:
            attrs["parent_trace_id"] = self._last_trace_id
        with trace.cycle(
            "solve", backend=type(self.primary).__name__, **attrs
        ):
            result = None
            t0 = time.perf_counter()
            try:
                result = self._solve_supervised(
                    pods, instance_types, templates, kwargs
                )
                return result
            finally:
                trace_id = trace.current_trace_id()
                if trace_id is not None:
                    self._last_trace_id = trace_id
                if slo.enabled():
                    duration_s = time.perf_counter() - t0
                    scheduled = result.num_scheduled() if result is not None else 0
                    failed = (
                        len(result.failures) if result is not None else len(pods)
                    )
                    slo.on_solve_cycle(duration_s, scheduled, failed)
                    flight.record(
                        flight.KIND_SOLVE_CYCLE, tenant=self.tenant,
                        duration_s=round(duration_s, 6), pods=len(pods),
                        scheduled=scheduled, failed=failed,
                    )

    def _solve_supervised(self, pods, instance_types, templates, kwargs) -> SolveResult:
        route = self._route()
        failure_class = None
        if route == "primary":
            result, failure_class = self._solve_primary(
                pods, instance_types, templates, kwargs
            )
            if result is not None:
                return result
        # primary skipped (open circuit) or exhausted — fall back
        if self.fallback is not None:
            from_name = type(self.primary).__name__
            to_name = type(self.fallback).__name__
            SOLVER_FALLBACK.inc({"from": from_name, "to": to_name})
            self.counters["solve_fallbacks"] += 1
            flight.record(flight.KIND_SOLVE_FALLBACK, **{
                "from": from_name, "to": to_name,
                "class": failure_class or "circuit-open",
            })
            log.warning(
                "solve falling back %s -> %s (class=%s, trace=%s)",
                from_name, to_name, failure_class or "circuit-open",
                trace.current_trace_id(),
            )
            with trace.span(
                "fallback",
                **{"from": from_name, "to": to_name,
                   "class": failure_class or "circuit-open"},
            ):
                try:
                    result = self.fallback.solve(
                        pods, instance_types, templates, **kwargs
                    )
                except Exception:
                    log.exception("fallback backend failed; salvaging the cycle")
                    return self._salvage(pods, failure_class or "fallback-error")
                violations = self._validate(
                    result, pods, instance_types, templates, kwargs
                )
            if violations:
                # both backends disagree with the invariants: keep what
                # verified, requeue the rest
                self._quarantine(result, violations, backend=to_name)
                self._reset_streaming()
                return val.strip_violations(
                    result, violations, self._requeue_reason(CLASS_VALIDATION)
                )
            return result
        return self._salvage(pods, failure_class or "primary-error")

    def _solve_primary(self, pods, instance_types, templates, kwargs):
        """Returns (result, None) on success or (None, failure_class) once
        retries are exhausted."""
        attempts = 1 + max(0, self.retries)
        failure_class = None
        for attempt in range(attempts):
            try:
                result = self._attempt(pods, instance_types, templates, kwargs)
            except Exception as exc:
                failure_class = classify_failure(exc)
                self.last_failure = {
                    "class": failure_class,
                    "error": f"{type(exc).__name__}: {exc}",
                }
                trace_id = trace.current_trace_id()
                if trace_id:
                    self.last_failure["trace_id"] = trace_id
                if self._last_trace_id:
                    self.last_failure["parent_trace_id"] = self._last_trace_id
                if failure_class == CLASS_DEADLINE:
                    SOLVE_DEADLINE_EXCEEDED.inc()
                    self.counters["deadline_exceeded"] += 1
                if failure_class in RETRYABLE and attempt + 1 < attempts:
                    SOLVER_RETRIES.inc({"class": failure_class})
                    self.counters["solve_retries"] += 1
                    flight.record(flight.KIND_SOLVE_RETRY, **{
                        "class": failure_class, "attempt": attempt + 1,
                    })
                    with trace.span(
                        "retry", **{"class": failure_class, "attempt": attempt + 1}
                    ):
                        self._sleep(self._backoff(attempt))
                    continue
                log.warning(
                    "primary solve failed (class=%s, attempt %d/%d): %s",
                    failure_class, attempt + 1, attempts, exc,
                )
                self._record_primary_failure()
                return None, failure_class
            violations = self._validate(
                result, pods, instance_types, templates, kwargs
            )
            if violations:
                failure_class = CLASS_VALIDATION
                self.last_failure = {
                    "class": CLASS_VALIDATION,
                    "error": "; ".join(str(v) for v in violations[:4]),
                }
                trace_id = trace.current_trace_id()
                if trace_id:
                    self.last_failure["trace_id"] = trace_id
                if self._last_trace_id:
                    self.last_failure["parent_trace_id"] = self._last_trace_id
                self._reset_streaming()
                self._quarantine(
                    result, violations, backend=type(self.primary).__name__
                )
                self._record_primary_failure()
                if self.fallback is not None:
                    return None, failure_class
                # no fallback: keep the verified placements, requeue the rest
                self._record_salvage()
                return (
                    val.strip_violations(
                        result, violations, self._requeue_reason(CLASS_VALIDATION)
                    ),
                    None,
                )
            self._record_primary_success()
            return result, None
        return None, failure_class

    def _attempt(self, pods, instance_types, templates, kwargs) -> SolveResult:
        """One primary solve under the watchdog, with solve-site fault
        injection applied (only the primary is ever injected — the fallback
        must stay trustworthy for the chaos suite to mean anything). A
        tenanted supervisor runs the whole attempt inside its tenant's fault
        scope, so tenant-selected rules fire only for this stream (the
        watchdog worker inherits the scope through copy_context)."""
        import contextlib

        scope = (
            faults.tenant_scope(self.tenant)
            if self.tenant is not None
            else contextlib.nullcontext()
        )
        with scope:
            injector = faults.active()
            rule = injector.draw("solve") if injector is not None else None

            def call():
                if rule is not None:
                    if rule.kind == "hang":
                        time.sleep(rule.param or 30.0)
                    else:
                        faults.raise_solve_fault(rule)
                result = self.primary.solve(
                    pods, instance_types, templates, **kwargs
                )
                if rule is not None and rule.kind == "nan":
                    faults.corrupt_result(result)
                return result

            result = self._with_deadline(call)
        if val.has_nan(result):
            raise NaNResultError("NaN/inf in result request tensors")
        return result

    def _with_deadline(self, fn):
        if self.deadline_s <= 0:
            return fn()
        box: Dict[str, object] = {}
        done = threading.Event()
        # The worker inherits the caller's contextvars (copy_context) so the
        # active trace/span propagate into it and the backend's phase spans
        # land in the right tree.
        ctx = contextvars.copy_context()

        def run():
            try:
                box["result"] = ctx.run(fn)
            except BaseException as exc:  # propagate to the waiting thread
                box["error"] = exc
            finally:
                done.set()

        worker = threading.Thread(
            target=run, daemon=True, name="karpenter-tpu/solve-worker"
        )
        worker.start()
        if not done.wait(self.deadline_s):
            # the worker cannot be cancelled; abandon it (daemon) and move on
            raise DeadlineExceeded(f"solve exceeded {self.deadline_s:g}s deadline")
        if "error" in box:
            raise box["error"]  # type: ignore[misc]
        return box["result"]

    def _backoff(self, attempt: int) -> float:
        base = min(self.backoff_base_s * (2.0 ** attempt), self.backoff_cap_s)
        # deterministic jitter in [0.5, 1.5): crc32, not the salted hash()
        frac = zlib.crc32(f"{self._solve_seq}:{attempt}".encode()) / 2**32
        return base * (0.5 + frac)

    # -- validation / quarantine / salvage ------------------------------------

    def _validate(
        self, result, pods, instance_types, templates, kwargs
    ) -> List[val.Violation]:
        violations = self._validate_inner(
            result, pods, instance_types, templates, kwargs
        )
        if slo.enabled() and self.validate_level != "off":
            # gate-integrity objective: every validated result is one event,
            # a rejection is budget burn (min_events=1 — one quarantine is
            # an incident, not noise)
            slo.on_gate(not violations)
            if violations:
                flight.record(
                    flight.KIND_VALIDATOR_REJECT, tenant=self.tenant,
                    count=len(violations),
                    invariants=sorted({v.invariant for v in violations[:8]}),
                )
        return violations

    def _validate_inner(
        self, result, pods, instance_types, templates, kwargs
    ) -> List[val.Violation]:
        if self.validate_level == "off":
            return []
        violations = self._device_gate(result, pods, instance_types, templates, kwargs)
        if violations is not None:
            for v in violations:
                VALIDATOR_REJECTIONS.inc(self._labels(invariant=v.invariant))
            if violations:
                self.counters["validator_rejections"] += 1
            return violations
        try:
            violations = val.validate_result(
                result,
                pods,
                instance_types,
                templates,
                nodes=kwargs["nodes"],
                pod_requirements_override=kwargs["pod_requirements_override"],
                cluster_pods=kwargs["cluster_pods"],
                domains=kwargs["domains"],
                level=self.validate_level,
            )
        except Exception:
            # the gate must never take down a healthy solve
            log.exception("validator crashed; passing result through")
            return []
        for v in violations:
            VALIDATOR_REJECTIONS.inc(self._labels(invariant=v.invariant))
        if violations:
            self.counters["validator_rejections"] += 1
        return violations

    def _device_gate(
        self, result, pods, instance_types, templates, kwargs
    ) -> Optional[List[val.Violation]]:
        """Try the device-side verification gate (verify/) before the host
        validator. Returns the canonical violation list when the gate owned
        the verdict, or None when it is off/not applicable (no verify_ctx,
        shape mismatch, gate crash) so the host path keeps the cycle.

        When the gate engages, verification runs at FULL rigor regardless of
        validate_level: a device accept is sound against the full host gate
        (the device predicates are tolerance-tighter), and a device reject is
        host-confirmed at full level before anything is stripped — so the
        level knob only governs the fallback host path's cost.
        """
        from karpenter_tpu import verify

        if not verify.enabled():
            return None
        if getattr(result, "verify_ctx", None) is None:
            return None
        outcome = verify.full_gate(
            result,
            pods,
            instance_types,
            templates,
            nodes=kwargs["nodes"],
            pod_requirements_override=kwargs["pod_requirements_override"],
            cluster_pods=kwargs["cluster_pods"],
            domains=kwargs["domains"],
        )
        if outcome is None:
            return None
        return list(outcome.violations)

    def _reset_streaming(self) -> None:
        """A rejected result must never seed the next warm solve: drop the
        streaming layer's carried placement state (no-op for plain backends)."""
        reset = getattr(self.primary, "reset_streaming_state", None)
        if reset is not None:
            reset()

    def _quarantine(self, result, violations, backend: str) -> None:
        from karpenter_tpu.solver.forensics import dump_quarantine

        path = dump_quarantine(
            result, violations, backend=backend,
            parent_trace_id=self._last_trace_id, tenant=self.tenant,
        )
        if slo.enabled():
            # cross-link the incident lineage: the flight ring names the
            # quarantine file, the dump that follows carries the ring
            flight.record(
                flight.KIND_QUARANTINE, backend=backend, tenant=self.tenant,
                path=path, violations=len(violations),
            )
            flight.snapshot_dump("validator-reject")
        log.error(
            "validator rejected %s result (%d violation(s), first: %s)%s",
            backend, len(violations), violations[0],
            f"; forensics at {path}" if path else "",
        )

    def _requeue_reason(self, failure_class: str) -> str:
        return (
            f"solver unavailable ({failure_class}); pod requeued for the "
            f"next provisioning cycle"
        )

    def _record_salvage(self) -> None:
        self.counters["salvaged"] += 1

    def _salvage(self, pods: Sequence, failure_class: str) -> SolveResult:
        """No backend could answer: complete the cycle anyway by requeueing
        every pod — FailedScheduling events fire and the next cycle retries,
        instead of the controllers seeing an exception and dropping the batch."""
        self._record_salvage()
        flight.record(flight.KIND_SOLVE_SALVAGE, **{
            "class": failure_class, "pods": len(pods),
        })
        with trace.span("salvage", **{"class": failure_class}):
            reason = self._requeue_reason(failure_class)
            return SolveResult(failures={i: reason for i in range(len(pods))})
