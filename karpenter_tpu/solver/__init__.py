from karpenter_tpu.solver.encode import Encoder, EncodedProblem  # noqa: F401
from karpenter_tpu.solver.backend import SolverBackend, SolveResult, Placement  # noqa: F401
