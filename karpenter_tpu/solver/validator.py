"""Host-side invariant gate over SolveResults.

The tensor solver's placements drive real node launches, so before a result
leaves the solver layer the supervisor (solver/supervisor.py) replays the
cheap, provable placement invariants against the ORIGINAL host-side inputs:

  pod-accounting          every input pod lands in exactly one of
                          {a new claim, an existing node, failures}
  claim-requests          a claim's request tensor equals daemonset overhead
                          plus the sum of its pods' requests (the device
                          accumulates in float32, so comparison is
                          relative-tolerant)
  claim-instance-types    a claim keeps at least one surviving instance type
  claim-capacity          the recomputed requests fit at least one of the
                          claim's listed instance types' allocatable
  taint-admissibility     every placed pod tolerates its bin's hard taints
                          (NoSchedule/NoExecute — PreferNoSchedule is soft
                          and relaxation may have added a blanket toleration
                          the original pod spec lacks)
  host-port               host ports are pairwise disjoint within each bin
                          (and against an existing node's already-used ports)
  requirement-intersection a placed pod's label requirements intersect its
                          bin's narrowed requirements (skipped for relaxable
                          pods — relaxation legally drops requirement terms)
  node-unknown/node-capacity  existing-node placements name a known node and
                          fit its available resources
  topology-skew (full)    DoNotSchedule spread skew bounds for non-hostname
                          keys, checked only when the cohort is exactly
                          reconstructible (see _check_topology_skew)
  instance-type-survivor (full)  every listed instance type is compatible
                          with / fits / offers under the claim requirements

Checks are deliberately NECESSARY conditions only: a violation proves the
result is unsafe to act on; silence does not prove optimality. Anything that
cannot be decided from the inputs without replaying the solve (relaxation
ladders, multi-valued topology domains) is skipped rather than guessed — a
false rejection would needlessly fail over a healthy backend.

Levels: ``fast`` (default; everything linear in pods+claims) and ``full``
(adds the per-claim instance-type sweep and topology-skew bounds).
``KARPENTER_TPU_VALIDATE`` picks the supervisor default: 0=off, 1=fast,
2=full.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.objects import (
    DO_NOT_SCHEDULE,
    NO_EXECUTE,
    NO_SCHEDULE,
    Pod,
)
from karpenter_tpu.cloudprovider.types import InstanceType
from karpenter_tpu.provisioning.preferences import Preferences
from karpenter_tpu.scheduling import Requirements, pod_requirements
from karpenter_tpu.scheduling.requirements import EXISTS
from karpenter_tpu.scheduling.hostports import get_host_ports
from karpenter_tpu.scheduling.taints import Taints
from karpenter_tpu.solver.backend import SolveResult
from karpenter_tpu.solver.encode import (
    NodeInfo,
    TemplateInfo,
    domains_from_instance_types,
)
from karpenter_tpu.utils import resources as res

# The jax backend accumulates requests in float32 on device; the recompute
# here is float64, so equality and fits checks carry float32-scale slack.
REL_TOL = 1e-4
ABS_TOL = 1e-6


@dataclass
class Violation:
    invariant: str
    detail: str
    claim_index: Optional[int] = None
    node_name: Optional[str] = None
    pod_indices: Tuple[int, ...] = ()

    def __str__(self) -> str:
        where = ""
        if self.claim_index is not None:
            where = f" [claim {self.claim_index}]"
        elif self.node_name is not None:
            where = f" [node {self.node_name}]"
        return f"{self.invariant}{where}: {self.detail}"


def _close(a: float, b: float) -> bool:
    return abs(a - b) <= ABS_TOL + REL_TOL * max(abs(a), abs(b))


def _fits_loose(requests: Dict[str, float], available: Dict[str, float]) -> bool:
    for name, q in requests.items():
        avail = available.get(name, 0.0)
        if q > avail + ABS_TOL + REL_TOL * abs(avail):
            return False
    return True


def has_nan(result: SolveResult) -> bool:
    """NaN/inf anywhere in the result's request tensors — the signature of a
    diverged device reduction; such a result must never reach the validator's
    arithmetic, let alone a cloud Create call."""
    for claim in result.new_claims:
        for v in claim.requests.values():
            if v != v or v in (float("inf"), float("-inf")):
                return True
    return False


def _hard_taints(taints: Taints) -> Taints:
    return Taints(t for t in taints if t.effect in (NO_SCHEDULE, NO_EXECUTE))


def checked_requirements(pod: Pod) -> Optional[Requirements]:
    """A placed pod's label requirements, when they are provably still in
    force: relaxation may have legally dropped affinity terms, so relaxable
    pods are skipped unless an override pins them. Pods with no node selector
    and no node affinity have empty requirements — trivially intersecting —
    and skip the recompute entirely (the common case on large batches; this
    keeps the fast gate sub-0.5% of a 10k solve). Module-level so the device
    gate (verify/gate.py) derives its pod_check mask from the same predicate
    the host intersection checks use."""
    if not pod.spec.node_selector:
        aff = pod.spec.affinity
        if aff is None or aff.node_affinity is None:
            return None
    if Preferences.is_relaxable(pod):
        return None
    return pod_requirements(pod)


def _port_clashes(pods_ports: List[Tuple[int, list]], pre_used: list) -> List[str]:
    errs = []
    used = [(None, p) for p in pre_used]
    for pi, ports in pods_ports:
        for port in ports:
            for owner, existing in used:
                if port.matches(existing):
                    errs.append(
                        f"pod {pi} port {port.protocol}/{port.port} clashes "
                        f"with {'node' if owner is None else f'pod {owner}'}"
                    )
        used.extend((pi, p) for p in ports)
    return errs


def validate_result(
    result: SolveResult,
    pods: Sequence[Pod],
    instance_types: Sequence[InstanceType],
    templates: Sequence[TemplateInfo],
    nodes: Sequence[NodeInfo] = (),
    pod_requirements_override: Optional[Sequence[Requirements]] = None,
    cluster_pods: Sequence = (),
    domains: Optional[Dict[str, set]] = None,
    level: str = "fast",
    *,
    claim_scope: Optional[set] = None,
    node_scope: Optional[set] = None,
    check_topology: bool = True,
) -> List[Violation]:
    """``claim_scope`` / ``node_scope`` / ``check_topology`` scope the check
    to a row subset (verify/ incremental re-checks and the sampled float64
    audit): None means every bin, a set restricts the per-claim / per-node
    loops to those claim indices / node names. Pod accounting always runs —
    it is the cross-bin invariant scoping cannot localize. Defaults keep the
    historical full-surface behavior bit-for-bit."""
    from karpenter_tpu.obs import trace

    with trace.span("validate", level=level) as sp:
        violations = _validate_result(
            result, pods, instance_types, templates, nodes,
            pod_requirements_override, cluster_pods, domains, level,
            claim_scope=claim_scope, node_scope=node_scope,
            check_topology=check_topology,
        )
        if sp is not None and violations:
            sp.count("violations", len(violations))
        return violations


def full_gate_relaxed(
    result: SolveResult,
    pods: Sequence[Pod],
    instance_types: Sequence[InstanceType],
    templates: Sequence[TemplateInfo],
    nodes: Sequence[NodeInfo] = (),
    pod_requirements_override: Optional[Sequence[Requirements]] = None,
    cluster_pods: Sequence = (),
    domains: Optional[Dict[str, set]] = None,
) -> List[Violation]:
    """The relaxed-solve contract (KARPENTER_TPU_RELAX, round 15): phase-1
    placements are validator-equivalent to FFD rather than bit-identical, so
    EVERY result the two-phase path produces is full-gated here before the
    backend returns it — a violation makes the backend redo the solve with
    relaxation off (solver_relax_fallback_total) instead of acting on it.
    Just validate_result at the full level under the relax span's roof; a
    named wrapper so call sites and tests pin the contract, not a string."""
    return validate_result(
        result, pods, instance_types, templates, nodes,
        pod_requirements_override, cluster_pods, domains, level="full",
    )


def _validate_result(
    result: SolveResult,
    pods: Sequence[Pod],
    instance_types: Sequence[InstanceType],
    templates: Sequence[TemplateInfo],
    nodes: Sequence[NodeInfo] = (),
    pod_requirements_override: Optional[Sequence[Requirements]] = None,
    cluster_pods: Sequence = (),
    domains: Optional[Dict[str, set]] = None,
    level: str = "fast",
    *,
    claim_scope: Optional[set] = None,
    node_scope: Optional[set] = None,
    check_topology: bool = True,
) -> List[Violation]:
    violations: List[Violation] = []
    node_by_name = {n.name: n for n in nodes}

    # -- pod accounting -------------------------------------------------------
    seen: Dict[int, str] = {}

    def account(pi: int, where: str):
        if pi in seen:
            violations.append(
                Violation(
                    "pod-accounting",
                    f"pod {pi} placed in both {seen[pi]} and {where}",
                    pod_indices=(pi,),
                )
            )
        seen[pi] = where

    for ci, claim in enumerate(result.new_claims):
        for pi in claim.pod_indices:
            account(pi, f"claim {ci}")
    for name, indices in result.node_pods.items():
        for pi in indices:
            account(pi, f"node {name}")
    for pi in result.failures:
        account(pi, "failures")
    missing = [pi for pi in range(len(pods)) if pi not in seen]
    if missing:
        violations.append(
            Violation(
                "pod-accounting",
                f"{len(missing)} pod(s) dropped (neither placed nor failed): "
                f"{missing[:8]}",
                pod_indices=tuple(missing[:8]),
            )
        )
    out_of_range = [pi for pi in seen if not 0 <= pi < len(pods)]
    if out_of_range:
        violations.append(
            Violation(
                "pod-accounting",
                f"placement references unknown pod indices {out_of_range[:8]}",
            )
        )
        return violations  # downstream checks would index out of bounds

    def reqs_of(pi: int) -> Optional[Requirements]:
        if pod_requirements_override is not None:
            return pod_requirements_override[pi]
        return checked_requirements(pods[pi])

    # -- per-claim invariants -------------------------------------------------
    for ci, claim in enumerate(result.new_claims):
        if claim_scope is not None and ci not in claim_scope:
            continue
        if not 0 <= claim.template_index < len(templates):
            violations.append(
                Violation(
                    "claim-template",
                    f"unknown template index {claim.template_index}",
                    claim_index=ci,
                )
            )
            continue
        tpl = templates[claim.template_index]
        if not claim.pod_indices:
            violations.append(
                Violation("claim-empty", "claim schedules no pods", claim_index=ci)
            )
            continue

        # requests must equal daemon overhead + sum of pod requests
        expected = dict(tpl.daemon_overhead)
        for pi in claim.pod_indices:
            expected = res.merge(
                expected, {**res.pod_requests(pods[pi]), res.PODS: 1.0}
            )
        keys = set(expected) | set(claim.requests)
        for key in keys:
            if not _close(expected.get(key, 0.0), claim.requests.get(key, 0.0)):
                violations.append(
                    Violation(
                        "claim-requests",
                        f"requests[{key}]={claim.requests.get(key, 0.0):g} but "
                        f"pods sum to {expected.get(key, 0.0):g}",
                        claim_index=ci,
                    )
                )
                break

        its = [
            ti for ti in claim.instance_type_indices
            if 0 <= ti < len(instance_types)
        ]
        if len(its) != len(claim.instance_type_indices):
            violations.append(
                Violation(
                    "claim-instance-types",
                    "placement references unknown instance-type indices",
                    claim_index=ci,
                )
            )
        if not its:
            violations.append(
                Violation(
                    "claim-instance-types",
                    "no surviving instance types",
                    claim_index=ci,
                )
            )
        else:
            # a valid claim fits EVERY listed type, so the loop exits on the
            # first check; an overpacked bin scans all of them and reports
            if not any(
                _fits_loose(expected, instance_types[ti].allocatable())
                for ti in its
            ):
                violations.append(
                    Violation(
                        "claim-capacity",
                        f"recomputed requests {expected} exceed allocatable of "
                        f"all {len(its)} listed instance types",
                        claim_index=ci,
                    )
                )

        hard = _hard_taints(tpl.taints)
        if hard:
            for pi in claim.pod_indices:
                errs = hard.tolerates(pods[pi])
                if errs:
                    violations.append(
                        Violation(
                            "taint-admissibility",
                            f"pod {pi}: {'; '.join(errs)}",
                            claim_index=ci,
                            pod_indices=(pi,),
                        )
                    )

        clashes = _port_clashes(
            [(pi, get_host_ports(pods[pi])) for pi in claim.pod_indices], []
        )
        for err in clashes:
            violations.append(Violation("host-port", err, claim_index=ci))

        if claim.requirements is not None:
            for pi in claim.pod_indices:
                reqs = reqs_of(pi)
                if reqs is None:
                    continue
                errs = claim.requirements.intersects(reqs)
                if errs:
                    violations.append(
                        Violation(
                            "requirement-intersection",
                            f"pod {pi}: {'; '.join(errs)}",
                            claim_index=ci,
                            pod_indices=(pi,),
                        )
                    )

        if level == "full" and claim.requirements is not None:
            for ti in its:
                it = instance_types[ti]
                if it.requirements.intersects(claim.requirements):
                    violations.append(
                        Violation(
                            "instance-type-survivor",
                            f"{it.name} conflicts with claim requirements",
                            claim_index=ci,
                        )
                    )
                    break
                if not _fits_loose(expected, it.allocatable()):
                    violations.append(
                        Violation(
                            "instance-type-survivor",
                            f"{it.name} cannot fit the claim's requests",
                            claim_index=ci,
                        )
                    )
                    break
                if not it.offerings.available().requirements(claim.requirements):
                    violations.append(
                        Violation(
                            "instance-type-survivor",
                            f"{it.name} has no offering under claim requirements",
                            claim_index=ci,
                        )
                    )
                    break

    # -- existing-node invariants ---------------------------------------------
    for name, indices in result.node_pods.items():
        if node_scope is not None and name not in node_scope:
            continue
        node = node_by_name.get(name)
        if node is None:
            violations.append(
                Violation(
                    "node-unknown",
                    f"placement targets node {name!r} not in the solve inputs",
                    node_name=name,
                )
            )
            continue
        merged = dict(node.daemon_overhead)
        for pi in indices:
            merged = res.merge(merged, {**res.pod_requests(pods[pi]), res.PODS: 1.0})
        if not _fits_loose(merged, node.available):
            violations.append(
                Violation(
                    "node-capacity",
                    f"pods {indices} plus daemon overhead exceed available "
                    f"resources",
                    node_name=name,
                )
            )
        hard = _hard_taints(node.taints)
        if hard:
            for pi in indices:
                errs = hard.tolerates(pods[pi])
                if errs:
                    violations.append(
                        Violation(
                            "taint-admissibility",
                            f"pod {pi}: {'; '.join(errs)}",
                            node_name=name,
                            pod_indices=(pi,),
                        )
                    )
        clashes = _port_clashes(
            [(pi, get_host_ports(pods[pi])) for pi in indices],
            list(node.host_ports),
        )
        for err in clashes:
            violations.append(Violation("host-port", err, node_name=name))
        for pi in indices:
            reqs = reqs_of(pi)
            if reqs is None:
                continue
            errs = node.requirements.intersects(reqs)
            if errs:
                violations.append(
                    Violation(
                        "requirement-intersection",
                        f"pod {pi}: {'; '.join(errs)}",
                        node_name=name,
                        pod_indices=(pi,),
                    )
                )

    if level == "full" and check_topology:
        violations.extend(
            _check_topology_skew(
                result, pods, instance_types, templates, nodes,
                pod_requirements_override, cluster_pods, domains,
            )
        )
    return violations


def _check_topology_skew(
    result: SolveResult,
    pods: Sequence[Pod],
    instance_types: Sequence[InstanceType],
    templates: Sequence[TemplateInfo],
    nodes: Sequence[NodeInfo],
    pod_requirements_override,
    cluster_pods: Sequence,
    domains: Optional[Dict[str, set]],
) -> List[Violation]:
    """DoNotSchedule spread skew over the full registered domain universe,
    for non-hostname keys. Checked only when the final counts are exactly
    reconstructible without replaying the solve:

      - every batch pod matching the selector carries the identical
        constraint (one shared cohort),
      - no cluster pod matches the selector (no pre-existing counts),
      - every matching pod was placed (a failed pod never consumed a slot),
      - no matching pod is relaxable (relaxation may drop the constraint),
      - no matching pod carries its own requirement on the topology key
        (which would shrink its eligible-domain set below the universe),
      - every matched placement pins the key to a single domain value.

    Hostname spreads are out of scope: their domain universe grows with each
    minted claim, so the end-state counts cannot bound what any prefix of
    the mint sequence saw, and an overpacked hostname shows up as a
    capacity violation anyway.
    """
    violations: List[Violation] = []
    if domains is None:
        domains = domains_from_instance_types(instance_types, templates)

    # bin of every placed pod: pod index -> key-valued Requirements container
    placed_reqs: Dict[int, Requirements] = {}
    for claim in result.new_claims:
        if claim.requirements is None:
            return violations
        for pi in claim.pod_indices:
            placed_reqs[pi] = claim.requirements
    node_by_name = {n.name: n for n in nodes}
    for name, indices in result.node_pods.items():
        node = node_by_name.get(name)
        if node is None:
            return violations
        for pi in indices:
            placed_reqs[pi] = node.requirements

    # group constraints by (key, skew, selector CONTENT): every cohort pod
    # carries its own constraint instance, so an identity dedup would rescan
    # the same O(P) cohort once per member — quadratic on spread-heavy mixes.
    # The check depends only on the constraint's content, so content-equal
    # signatures are one class and one scan.
    from karpenter_tpu.provisioning.topology import _selector_key

    checked = set()
    for pi, pod in enumerate(pods):
        for tsc in pod.spec.topology_spread_constraints or ():
            if tsc.when_unsatisfiable != DO_NOT_SCHEDULE:
                continue
            key = tsc.topology_key
            if key == wk.LABEL_HOSTNAME or key not in domains:
                continue
            sig = (key, tsc.max_skew, _selector_key(tsc.label_selector))
            if sig in checked:
                continue
            checked.add(sig)
            selector = tsc.label_selector
            cohort = [
                qi for qi, q in enumerate(pods)
                if selector is not None and selector.matches(q.metadata.labels)
            ]
            if not cohort:
                continue
            # preconditions: exact cohort, fully placed, constraint-identical
            ok = True
            for qi in cohort:
                q = pods[qi]
                same = [
                    c for c in (q.spec.topology_spread_constraints or ())
                    if c.topology_key == key
                    and c.when_unsatisfiable == DO_NOT_SCHEDULE
                    and c.max_skew == tsc.max_skew
                ]
                if not same or Preferences.is_relaxable(q):
                    ok = False
                    break
                own = (
                    pod_requirements_override[qi]
                    if pod_requirements_override is not None
                    else pod_requirements(q)
                )
                own_req = own.get(key)
                if own_req is not None and not (
                    # a bare Exists (what pod_requirements synthesizes for
                    # every spread topology key) admits every domain value
                    # and must not disable the check
                    own_req.operator() == EXISTS
                    and own_req.greater_than is None
                    and own_req.less_than is None
                ):
                    ok = False
                    break
                if qi not in placed_reqs:
                    ok = False
                    break
            if not ok:
                continue
            if any(
                selector.matches(cp[0].metadata.labels) if isinstance(cp, tuple)
                else selector.matches(cp.metadata.labels)
                for cp in cluster_pods
            ):
                continue
            counts: Dict[str, int] = {d: 0 for d in domains[key]}
            exact = True
            for qi in cohort:
                req = placed_reqs[qi].get(key)
                values = req.sorted_values() if req is not None else []
                if len(values) != 1 or values[0] not in counts:
                    exact = False
                    break
                counts[values[0]] += 1
            if not exact:
                continue
            skew = max(counts.values()) - min(counts.values())
            if skew > tsc.max_skew:
                # pin the whole cohort: the content dedup reports each class
                # once, and strip_violations must still requeue every bin the
                # cohort occupies (the identity dedup used to reach them via
                # one violation per member)
                violations.append(
                    Violation(
                        "topology-skew",
                        f"key {key}: domain counts {counts} skew {skew} > "
                        f"max_skew {tsc.max_skew}",
                        pod_indices=tuple(cohort),
                    )
                )
    return violations


def strip_violations(
    result: SolveResult, violations: Sequence[Violation], reason: str
) -> SolveResult:
    """Salvage: a fresh SolveResult without the violating bins, their pods
    requeued via ``failures`` (the provisioning layer re-solves them next
    cycle). Used when a validation failure has no healthy backend to fail
    over to — the rest of the committed placements are still safe."""
    pod_bin: Dict[int, List] = {}
    for ci, claim in enumerate(result.new_claims):
        for pi in claim.pod_indices:
            pod_bin.setdefault(pi, []).append(("claim", ci))
    for name, indices in result.node_pods.items():
        for pi in indices:
            pod_bin.setdefault(pi, []).append(("node", name))
    bad_claims = {v.claim_index for v in violations if v.claim_index is not None}
    bad_nodes = {v.node_name for v in violations if v.node_name is not None}
    # a violation pinned to pods rather than a bin (accounting, skew) strips
    # every bin holding those pods
    for v in violations:
        if v.claim_index is None and v.node_name is None:
            for pi in v.pod_indices:
                for kind, ref in pod_bin.get(pi, []):
                    (bad_claims if kind == "claim" else bad_nodes).add(ref)
    out = SolveResult(failures=dict(result.failures))
    for ci, claim in enumerate(result.new_claims):
        if ci in bad_claims:
            for pi in claim.pod_indices:
                out.failures[pi] = reason
        else:
            out.new_claims.append(claim)
    for name, indices in result.node_pods.items():
        if name in bad_nodes:
            for pi in indices:
                out.failures[pi] = reason
        else:
            out.node_pods[name] = indices
    for v in violations:
        for pi in v.pod_indices:
            if pi not in pod_bin and pi not in out.failures:
                out.failures[pi] = reason
    return out
