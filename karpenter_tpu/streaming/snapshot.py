"""Crash-consistent streaming-state journal: warm solves that survive exec.

Since round 11 the 16.1x warm-vs-cold advantage lives entirely in
``StreamingSolver._prev`` — process memory. A restart therefore used to be a
double cold-start: retrace every executable AND re-solve the whole world
cold. This module journals the accepted cycle state (the previous snapshot's
pods/nodes with their identity digests, the accepted ``SolveResult``, the FFD
queue order, and the certification prefix — exactly ``_StreamState``) through
the shared framed-file protocol (utils/persist.py: atomic tmp+rename+fsync,
sha256, version header), so a freshly exec'd process re-enters the warm path
on its FIRST cycle.

Safety before speed, in three layers:

  1. every way the file can be wrong is a CLASSIFIED cold-start fallback
     (``karpenter_solver_state_restore_total{outcome}``): missing, truncated,
     corrupt, checksum, version-skew, isa-mismatch, stale, error — loading
     never raises into the solve path;
  2. a decoded journal is admitted only behind the FULL-level validator gate
     (outcome ``validator`` when rejected) — the same gate every warm merge
     passes, so a restored state cannot assert placements a live one
     couldn't;
  3. even an admitted journal only SEEDS the delta diff: the next cycle
     still diffs the live world against it, and any divergence falls out as
     the ordinary cold-world-changed / cold-threshold outcomes.

A wrong placement is therefore unreachable from a bad journal; the worst
case is always one cold solve. ``reset_streaming_state`` (the supervisor's
quarantine hook) also invalidates the on-disk journal — a quarantined result
must not resurrect after a crash.

Enabled by ``KARPENTER_TPU_STATE_DIR`` alone (the journal is useful without
AOT executable restore); cadence via ``KARPENTER_TPU_STATE_SNAPSHOT_EVERY``
(journal every Nth accepted cycle, default 1), staleness bound via
``KARPENTER_TPU_STATE_MAX_AGE_S`` (default 900 s).
"""

from __future__ import annotations

import logging
import os
import pickle
import time
from typing import Optional, Tuple

log = logging.getLogger(__name__)

JOURNAL_VERSION = 1

# classified restore outcomes (the bounded metric label-value set)
OUTCOMES = (
    "restored", "missing", "truncated", "corrupt", "checksum",
    "version-skew", "isa-mismatch", "stale", "validator", "error",
)


def enabled() -> bool:
    return bool(os.environ.get("KARPENTER_TPU_STATE_DIR"))


def journal_path(namespace: Optional[str] = None) -> Optional[str]:
    """Journal file location; ``namespace`` (the serve layer passes the
    tenant id) isolates each tenant stream's journal so one tenant's
    invalidation or corruption can never cost another its warm restart."""
    root = os.environ.get("KARPENTER_TPU_STATE_DIR")
    if not root:
        return None
    if namespace:
        import re

        safe = re.sub(r"[^A-Za-z0-9._-]", "-", namespace)
        return os.path.join(root, "stream", safe, "journal.snap")
    return os.path.join(root, "stream", "journal.snap")


def cadence() -> int:
    try:
        return max(1, int(os.environ.get("KARPENTER_TPU_STATE_SNAPSHOT_EVERY", "1")))
    except ValueError:
        return 1


def max_age_s() -> float:
    try:
        return float(os.environ.get("KARPENTER_TPU_STATE_MAX_AGE_S", "900"))
    except ValueError:
        return 900.0


_warned: set = set()


def _warn_once(tag: str, msg: str, *args) -> None:
    if tag in _warned:
        return
    _warned.add(tag)
    log.warning(msg, *args)


def save(state, namespace: Optional[str] = None) -> bool:
    """Journal one accepted ``_StreamState``. Best-effort: a journal failure
    costs the NEXT process a cold solve, never this one anything — so every
    failure is a warn + counter, never an exception. Returns success."""
    from karpenter_tpu.metrics.registry import RESTORE_FALLBACK
    from karpenter_tpu.obs.programs import isa_tag
    from karpenter_tpu.testing import faults
    from karpenter_tpu.utils import persist

    path = journal_path(namespace)
    if path is None:
        return False
    try:
        payload = pickle.dumps(state, protocol=pickle.HIGHEST_PROTOCOL)
    except Exception as exc:  # noqa: BLE001 — an unpicklable field, not a bug here
        RESTORE_FALLBACK.inc({"reason": "journal-persist-error"})
        _warn_once(
            "pickle", "stream journal: state not picklable, journaling "
            "disabled for this cycle: %s: %s", type(exc).__name__, exc,
        )
        return False
    faults.crash_point("journal.pre-write")
    try:
        persist.write_framed(
            path, payload, kind="stream-journal", version=JOURNAL_VERSION,
            meta={
                "isa": isa_tag(),
                "pods": len(state.pods),
                "nodes": len(state.nodes),
                "certified": len(state.certified_uids),
            },
        )
    except OSError as exc:
        RESTORE_FALLBACK.inc({"reason": "journal-persist-error"})
        _warn_once(
            "write", "stream journal: write failed: %s: %s",
            type(exc).__name__, exc,
        )
        return False
    faults.crash_point("journal.post-write")
    return True


def invalidate(namespace: Optional[str] = None) -> None:
    """Remove the on-disk journal (quarantine / reset): a state the live
    process rejected must not be what the next process restores."""
    path = journal_path(namespace)
    if path is None:
        return
    try:
        os.remove(path)
    except FileNotFoundError:
        pass
    except OSError as exc:
        _warn_once(
            "invalidate", "stream journal: invalidate failed: %s: %s",
            type(exc).__name__, exc,
        )


def load(namespace: Optional[str] = None) -> Tuple[str, Optional[object]]:
    """Restore the journal: ``(outcome, state)`` where outcome is one of
    :data:`OUTCOMES` and state is a ``_StreamState`` only for ``restored``.
    Counts every attempt in ``solver_state_restore_total{outcome}`` and every
    degradation in ``restore_fallback_total{reason=journal-*}`` — a restore
    is never unclassified and never raises."""
    from karpenter_tpu.metrics.registry import RESTORE_FALLBACK, STATE_RESTORE
    from karpenter_tpu.obs.programs import isa_tag
    from karpenter_tpu.utils.persist import PersistError, load_framed

    def classify(outcome: str) -> Tuple[str, None]:
        STATE_RESTORE.inc({"outcome": outcome})
        # "missing" is the normal first boot, not a degradation
        if outcome not in ("restored", "missing"):
            RESTORE_FALLBACK.inc({"reason": f"journal-{outcome}"})
        return outcome, None

    path = journal_path(namespace)
    if path is None:
        return classify("missing")
    try:
        header, payload = load_framed(
            path, kind="stream-journal", min_version=JOURNAL_VERSION
        )
    except PersistError as exc:
        return classify(exc.reason)
    if header.get("meta", {}).get("isa") != isa_tag():
        return classify("isa-mismatch")
    age = time.time() - float(header.get("created_unix", 0.0))
    if age > max_age_s():
        return classify("stale")
    try:
        state = pickle.loads(payload)
    except Exception:  # noqa: BLE001 — checksummed, but be exhaustive
        return classify("error")
    try:
        from karpenter_tpu.solver import validator as val

        violations = val.validate_result(
            state.result, state.pods, state.instance_types, state.templates,
            nodes=state.nodes, level="full",
        )
    except Exception:  # noqa: BLE001 — a malformed state that crashes the gate
        return classify("error")
    if violations:
        _warn_once(
            "validator", "stream journal: restored state rejected by the "
            "full validator gate (%d violations) — cold start", len(violations),
        )
        return classify("validator")
    STATE_RESTORE.inc({"outcome": "restored"})
    return "restored", state
