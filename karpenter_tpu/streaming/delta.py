"""Incremental delta encode: snapshot diffing + row-patched SchedulingProblem.

The class-keyed encoder (round 8) made every pod-axis tensor a pure function
of (pod spec, frozen vocabulary): requirement rows gather from per-class
tables, toleration/port rows fold by class, and run segmentation reads only
the assembled rows. That purity is what makes churn a *row patch*: when the
vocabulary, resource axis, port lanes, and the instance-type/template/node
sides are provably unchanged, a new snapshot's problem is the previous
problem with rows gathered for surviving pods and freshly encoded rows
spliced in for arrivals — bit-identical to a cold encode by construction,
because both paths run the same shared functions (``build_vocab``,
``encode_reqs_with_vocab``, ``segment_runs`` in solver/encode.py) over the
same inputs.

``DeltaEncoder`` never guesses: every precondition is *checked*, not assumed
(the vocabulary is rebuilt and compared, the resource axis re-derived, port
lanes re-interned), and any mismatch falls back to a cold encode with the
reason recorded in ``last_patch``. The parity fuzz in
tests/test_streaming_parity.py asserts array-for-array equality of patched
vs cold encodes across random churn sequences.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from karpenter_tpu.apis.objects import Pod
from karpenter_tpu.cloudprovider.types import InstanceType
from karpenter_tpu.models.problem import (
    CT_KEY,
    HOSTNAME_KEY,
    ProblemMeta,
    ReqTensor,
    SchedulingProblem,
    ZONE_KEY,
)
from karpenter_tpu.scheduling import (
    has_preferred_node_affinity,
    pod_requirements,
    strict_pod_requirements,
)
from karpenter_tpu.scheduling.hostports import HostPort, get_host_ports
from karpenter_tpu.solver.encode import (
    EncodedProblem,
    Encoder,
    NodeInfo,
    TemplateInfo,
    _Vocab,
    build_vocab,
    claim_hostname,
    encode_reqs_with_vocab,
    ffd_order,
    segment_runs,
)
from karpenter_tpu.utils import resources as res


def _digest(parts: Sequence[str]) -> str:
    return hashlib.blake2b("|".join(parts).encode(), digest_size=16).hexdigest()


def pod_digest(p: Pod) -> str:
    """Deterministic digest of every pod field any encoded tensor reads
    (selectors, affinity, tolerations, spread, containers incl. requests and
    ports, labels, FFD sort inputs). Two pods with equal digests produce
    byte-identical encoded rows under the same vocabulary; an imprecise
    (over-wide) digest only costs reuse, never correctness."""
    spec = p.spec
    return _digest(
        (
            p.namespace,
            p.metadata.name,
            repr(sorted((p.metadata.labels or {}).items())),
            repr(sorted(spec.node_selector.items())),
            repr(spec.affinity),
            repr(spec.tolerations),
            repr(spec.topology_spread_constraints),
            repr(spec.containers),
            repr(spec.init_containers),
            repr(sorted(spec.overhead.items())),
            repr(spec.volumes),
            repr(p.metadata.creation_timestamp),
            str(p.metadata.creation_seq),
        )
    )


def node_info_digest(n: NodeInfo) -> str:
    """Digest of every NodeInfo field the encode reads. A changed digest
    under the same name means the node's gates may have moved (capacity,
    taints, ports, CSI state) — the delta layer treats it as remove+add."""
    return _digest(
        (
            n.name,
            repr(n.requirements),
            repr(list(n.taints)),
            repr(sorted(n.available.items())),
            repr(sorted(n.daemon_overhead.items())),
            repr(sorted(str(hp) for hp in n.host_ports)),
            repr(sorted(n.volume_used.items())),
            repr(sorted(n.volume_limits.items())),
        )
    )


def template_digest(t: TemplateInfo) -> str:
    return _digest(
        (
            t.nodepool_name,
            repr(t.requirements),
            repr(list(t.taints)),
            repr(sorted(t.daemon_overhead.items())),
            repr(list(t.instance_type_indices)),
            repr(sorted(t.remaining_resources.items()))
            if t.remaining_resources is not None
            else "None",
        )
    )


def instance_type_digest(it: InstanceType) -> str:
    return _digest(
        (
            it.name,
            repr(it.requirements),
            repr(sorted(it.capacity.items())),
            repr(
                [
                    (o.zone, o.capacity_type, o.available, o.price)
                    for o in it.offerings
                ]
            ),
        )
    )


@dataclass
class SnapshotDelta:
    """What changed between two cluster snapshots, in terms the warm solve
    and the delta encoder both consume. Pod entries are indices into the
    *current* pod list (uids for removals — they have no current index)."""

    added_pods: List[int] = field(default_factory=list)
    changed_pods: List[int] = field(default_factory=list)
    removed_pods: List[str] = field(default_factory=list)
    added_nodes: List[str] = field(default_factory=list)
    changed_nodes: List[str] = field(default_factory=list)
    removed_nodes: List[str] = field(default_factory=list)
    templates_changed: bool = False
    its_changed: bool = False
    prev_pod_count: int = 0

    @property
    def pod_events(self) -> int:
        return len(self.added_pods) + len(self.changed_pods) + len(self.removed_pods)

    @property
    def node_events(self) -> int:
        return len(self.added_nodes) + len(self.changed_nodes) + len(self.removed_nodes)

    @property
    def frac(self) -> float:
        """Delta fraction: churned pods relative to the previous batch size
        (the KARPENTER_TPU_DELTA_MAX_FRAC threshold compares against this)."""
        return self.pod_events / max(1, self.prev_pod_count)


def diff_snapshots(
    prev_pods: Sequence[Pod],
    prev_nodes: Sequence[NodeInfo],
    cur_pods: Sequence[Pod],
    cur_nodes: Sequence[NodeInfo],
    prev_pod_digests: Optional[Dict[str, str]] = None,
    prev_node_digests: Optional[Dict[str, str]] = None,
) -> Tuple[SnapshotDelta, Dict[str, str], Dict[str, str]]:
    """Diff two snapshots. Returns (delta, cur_pod_digests, cur_node_digests)
    so callers can thread the current digests into the next diff instead of
    recomputing the previous side every cycle."""
    if prev_pod_digests is None:
        prev_pod_digests = {p.uid: pod_digest(p) for p in prev_pods}
    if prev_node_digests is None:
        prev_node_digests = {n.name: node_info_digest(n) for n in prev_nodes}
    delta = SnapshotDelta(prev_pod_count=len(prev_pods))
    cur_pod_digests: Dict[str, str] = {}
    for i, p in enumerate(cur_pods):
        d = cur_pod_digests[p.uid] = pod_digest(p)
        old = prev_pod_digests.get(p.uid)
        if old is None:
            delta.added_pods.append(i)
        elif old != d:
            delta.changed_pods.append(i)
    delta.removed_pods = [u for u in prev_pod_digests if u not in cur_pod_digests]
    cur_node_digests: Dict[str, str] = {}
    for n in cur_nodes:
        d = cur_node_digests[n.name] = node_info_digest(n)
        old = prev_node_digests.get(n.name)
        if old is None:
            delta.added_nodes.append(n.name)
        elif old != d:
            delta.changed_nodes.append(n.name)
    delta.removed_nodes = [
        name for name in prev_node_digests if name not in cur_node_digests
    ]
    return delta, cur_pod_digests, cur_node_digests


@dataclass
class _DeltaState:
    """Everything a row patch gathers from: the previous encode plus the
    host-side side tables (vocab, port lanes, digests) needed to prove the
    patch preconditions and encode arrival rows."""

    pods: List[Pod]  # FFD queue order (matches problem rows)
    uid_row: Dict[str, int]
    pod_digests: Dict[str, str]  # by uid
    nodes: List[NodeInfo]
    node_row: Dict[str, int]
    node_digests: Dict[str, str]  # by name
    problem: SchedulingProblem
    meta: ProblemMeta
    vocab: _Vocab
    port_vocab: Dict[HostPort, int]
    port_conflict: np.ndarray
    drivers: List[str]
    instance_types: List[InstanceType]
    it_digests: List[str]
    templates: List[TemplateInfo]
    tpl_digests: List[str]
    num_claim_slots: int


def _vocab_from_meta(meta: ProblemMeta) -> _Vocab:
    """Reconstruct the exact vocabulary from a cold encode's meta:
    values_per_key lists values in lane order, so re-interning in list order
    reproduces every index."""
    v = _Vocab()
    for ki, key in enumerate(meta.keys):
        v.key(key)
        for value in meta.values_per_key[ki]:
            v.value(key, value)
    return v


def _vocabs_equal(a: _Vocab, b: _Vocab) -> bool:
    return a.keys == b.keys and a.values == b.values


def _build_port_vocab(
    sorted_pods: Sequence[Pod], nodes: Sequence[NodeInfo]
) -> Dict[HostPort, int]:
    pv: Dict[HostPort, int] = {}
    for p in sorted_pods:
        for hp in get_host_ports(p):
            pv.setdefault(hp, len(pv))
    for n in nodes:
        for hp in n.host_ports:
            pv.setdefault(hp, len(pv))
    return pv


def _digest_list(objs, fn, cached_objs=None, cached_digests=None):
    """Digest a list with an identity fast path: churn streams pass the same
    instance-type/template objects every cycle, so `is`-equality skips the
    repr work entirely."""
    if (
        cached_objs is not None
        and len(objs) == len(cached_objs)
        and all(a is b for a, b in zip(objs, cached_objs))
    ):
        return list(cached_digests)
    return [fn(o) for o in objs]


class DeltaEncoder:
    """Stateful encoder: first call (and any call whose patch preconditions
    fail) runs a cold ``Encoder.encode``; subsequent calls patch the cached
    problem's rows. ``last_patch`` reports what the last call did:

        {"mode": "cold"|"patched", "reason": ..., "reused_rows": int,
         "fresh_rows": int, "pods": int}

    Only the batch-solve argument subset is patchable (no per-pass override
    requirements, no topology groups, no CSI pod volumes) — exactly the
    arguments the streaming path produces. Anything else is a checked cold
    fallback, never a wrong answer.
    """

    def __init__(self, well_known_labels=None):
        self.encoder = Encoder(**({} if well_known_labels is None else {"well_known_labels": well_known_labels}))
        self._state: Optional[_DeltaState] = None
        self.last_patch: Dict[str, object] = {}
        # per-row previous-world index of the last _patch (-1 = fresh), or
        # None after a cold encode — the DeviceWorld path turns this into a
        # device gather plan (ops/fused.build_patch_args)
        self.last_rows_prev: Optional[np.ndarray] = None
        self.stats = {"cold": 0, "patched": 0}

    def reset(self) -> None:
        self._state = None

    # -- entry ---------------------------------------------------------------

    def encode(
        self,
        pods: Sequence[Pod],
        instance_types: Sequence[InstanceType],
        templates: Sequence[TemplateInfo],
        nodes: Sequence[NodeInfo] = (),
        num_claim_slots: int = 0,
        **kwargs,
    ) -> EncodedProblem:
        if any(v is not None for v in kwargs.values()):
            # per-pass overrides / topology / CSI volumes: the cached row
            # tables don't model them, and a later patch against this state
            # wouldn't either — encode cold and drop the state
            self._state = None
            return self._cold(
                pods, instance_types, templates, nodes, num_claim_slots,
                reason="unsupported-args", cache=False, **kwargs,
            )
        reason = self._patch_blocker(
            pods, instance_types, templates, nodes, num_claim_slots
        )
        if reason is not None:
            return self._cold(
                pods, instance_types, templates, nodes, num_claim_slots,
                reason=reason,
            )
        return self._patch(pods, instance_types, templates, nodes, num_claim_slots)

    # -- cold path -----------------------------------------------------------

    def _cold(
        self,
        pods,
        instance_types,
        templates,
        nodes,
        num_claim_slots,
        reason: str,
        cache: bool = True,
        **kwargs,
    ) -> EncodedProblem:
        encoded = self.encoder.encode(
            pods,
            instance_types,
            templates,
            nodes=nodes,
            num_claim_slots=num_claim_slots,
            **kwargs,
        )
        self.stats["cold"] += 1
        self.last_rows_prev = None
        self.last_patch = {
            "mode": "cold",
            "reason": reason,
            "reused_rows": 0,
            "fresh_rows": len(pods),
            "pods": len(pods),
        }
        if cache:
            meta = encoded.meta
            sorted_pods = [pods[i] for i in meta.pod_order]
            pv = _build_port_vocab(sorted_pods, nodes)
            self._state = _DeltaState(
                pods=sorted_pods,
                uid_row={p.uid: i for i, p in enumerate(sorted_pods)},
                pod_digests={p.uid: pod_digest(p) for p in sorted_pods},
                nodes=list(nodes),
                node_row={n.name: i for i, n in enumerate(nodes)},
                node_digests={n.name: node_info_digest(n) for n in nodes},
                problem=encoded.problem,
                meta=meta,
                vocab=_vocab_from_meta(meta),
                port_vocab=pv,
                port_conflict=self._conflict_matrix(pv),
                drivers=sorted({d for n in nodes for d in n.volume_limits}),
                instance_types=list(instance_types),
                it_digests=[instance_type_digest(it) for it in instance_types],
                templates=list(templates),
                tpl_digests=[template_digest(t) for t in templates],
                num_claim_slots=num_claim_slots,
            )
        return encoded

    @staticmethod
    def _conflict_matrix(port_vocab: Dict[HostPort, int]) -> np.ndarray:
        PT = max(len(port_vocab), 1)
        lanes = list(port_vocab.keys())
        conflict = np.zeros((PT, PT), dtype=bool)
        for a, hp_a in enumerate(lanes):
            for b, hp_b in enumerate(lanes):
                conflict[a, b] = hp_a.matches(hp_b)
        return conflict

    # -- patch preconditions ---------------------------------------------------

    def _patch_blocker(
        self, pods, instance_types, templates, nodes, num_claim_slots
    ) -> Optional[str]:
        st = self._state
        if st is None:
            return "first-encode"
        if not pods:
            return "empty-batch"
        if num_claim_slots != st.num_claim_slots:
            return "claim-slots"
        if _digest_list(
            templates, template_digest, st.templates, st.tpl_digests
        ) != st.tpl_digests or len(templates) != len(st.templates):
            return "templates-changed"
        if _digest_list(
            instance_types, instance_type_digest, st.instance_types, st.it_digests
        ) != st.it_digests or len(instance_types) != len(st.instance_types):
            return "instance-types-changed"
        # nodes: removals keep the cached rows selectable (the node axis is
        # column-masked), though a removed hostname usually leaves the
        # vocabulary too and the vocab comparison below then decides cold;
        # adds/changes/reorders invalidate the node axis outright
        prev_row = -1
        for n in nodes:
            row = st.node_row.get(n.name)
            if row is None:
                return "node-added"
            if node_info_digest(n) != st.node_digests[n.name]:
                return "node-changed"
            if row <= prev_row:
                return "node-reordered"
            prev_row = row
        if sorted({d for n in nodes for d in n.volume_limits}) != st.drivers:
            return "driver-drift"
        return None

    # -- the row patch ---------------------------------------------------------

    def _patch(
        self, pods, instance_types, templates, nodes, num_claim_slots
    ) -> EncodedProblem:
        st = self._state
        assert st is not None
        prev = st.problem

        _req_memo: Dict[int, Dict[str, float]] = {}

        def preq(p):
            r = _req_memo.get(id(p))
            if r is None:
                r = _req_memo[id(p)] = res.pod_requests(p)
            return r

        order = ffd_order(pods, requests_of=preq)
        spods = [pods[i] for i in order]
        P = len(spods)

        # which sorted rows gather from cache vs. encode fresh
        cur_digests = {p.uid: pod_digest(p) for p in spods}
        rows_prev = np.full(P, -1, dtype=np.int64)
        for i, p in enumerate(spods):
            row = st.uid_row.get(p.uid)
            if row is not None and st.pod_digests[p.uid] == cur_digests[p.uid]:
                rows_prev[i] = row
        cached = rows_prev >= 0
        cached_rows = rows_prev[cached]
        fresh_pos = np.where(~cached)[0]
        fresh_pods = [spods[i] for i in fresh_pos]

        # vocabulary must be provably stable: rebuild over the new snapshot
        # with the shared build_vocab and compare. Rebuilding is dict interning
        # only — the expensive part of a cold encode is the per-class tensor
        # fold this patch skips.
        claim_hostnames = [claim_hostname(i) for i in range(num_claim_slots)]
        vocab = build_vocab(
            spods, templates, nodes, (), claim_hostnames, instance_types
        )
        if not _vocabs_equal(vocab, st.vocab):
            return self._cold(
                pods, instance_types, templates, nodes, num_claim_slots,
                reason="vocab-drift",
            )
        # resource axis must match lane-for-lane
        resource_names = [res.CPU, res.MEMORY, res.PODS, res.EPHEMERAL_STORAGE]
        seen = set(resource_names)
        for rl in (
            [preq(p) for p in spods]
            + [it.capacity for it in instance_types]
            + [t.daemon_overhead for t in templates]
            + [n.available for n in nodes]
        ):
            for name in rl:
                if name not in seen:
                    seen.add(name)
                    resource_names.append(name)
        if resource_names != st.meta.resource_names:
            return self._cold(
                pods, instance_types, templates, nodes, num_claim_slots,
                reason="resource-drift",
            )
        # port lanes are interned in pod-queue-then-node order; compare
        pv = _build_port_vocab(spods, nodes)
        if list(pv) != list(st.port_vocab):
            return self._cold(
                pods, instance_types, templates, nodes, num_claim_slots,
                reason="port-drift",
            )

        lane_valid = prev.lane_valid
        K, V = lane_valid.shape
        R = len(resource_names)
        node_sel = np.array(
            [st.node_row[n.name] for n in nodes], dtype=np.int64
        )

        # fresh rows through the exact shared encode functions
        fresh_reqs_list = [pod_requirements(p) for p in fresh_pods]
        fresh_strict_list = [
            strict_pod_requirements(p) if has_preferred_node_affinity(p) else r
            for p, r in zip(fresh_pods, fresh_reqs_list)
        ]
        fresh_reqs = encode_reqs_with_vocab(fresh_reqs_list, vocab, lane_valid)
        fresh_strict = encode_reqs_with_vocab(fresh_strict_list, vocab, lane_valid)

        def splice_req(prev_t: ReqTensor, fresh_t: ReqTensor) -> ReqTensor:
            out = {}
            for f in ("admitted", "comp", "gt", "lt", "defined"):
                pa = getattr(prev_t, f)
                fa = getattr(fresh_t, f)
                arr = np.empty((P,) + pa.shape[1:], dtype=pa.dtype)
                arr[cached] = pa[cached_rows]
                arr[fresh_pos] = fa
                out[f] = arr
            return ReqTensor(**out)

        pod_reqs = splice_req(prev.pod_reqs, fresh_reqs)
        pod_strict_reqs = splice_req(prev.pod_strict_reqs, fresh_strict)

        def splice(prev_a: np.ndarray, tail_shape, fill_fresh) -> np.ndarray:
            arr = np.zeros((P,) + tail_shape, dtype=prev_a.dtype)
            arr[cached] = prev_a[cached_rows]
            for j, pos in enumerate(fresh_pos):
                fill_fresh(arr[pos], fresh_pods[j])
            return arr

        def dense(rl) -> np.ndarray:
            return np.array(res.to_dense(rl, resource_names), dtype=np.float32)

        pod_requests = splice(
            prev.pod_requests,
            (R,),
            lambda row, p: np.copyto(row, dense({**preq(p), res.PODS: 1.0})),
        )

        TPL = len(templates)

        def fill_tol_tpl(row, p):
            for ti, t in enumerate(templates):
                row[ti] = not t.taints.tolerates(p)

        pod_tol_tpl = splice(prev.pod_tol_tpl, (TPL,), fill_tol_tpl)

        # node-axis columns: gather surviving columns for cached rows, encode
        # fresh rows directly against the surviving node list
        N = len(nodes)
        pod_tol_node = np.zeros((P, N), dtype=prev.pod_tol_node.dtype)
        pod_tol_node[cached] = prev.pod_tol_node[cached_rows][:, node_sel]
        for j, pos in enumerate(fresh_pos):
            p = fresh_pods[j]
            for ni, n in enumerate(nodes):
                pod_tol_node[pos, ni] = not n.taints.tolerates(p)

        PT = max(len(pv), 1)
        conflict = st.port_conflict

        def fill_ports(pair, p):
            prow, crow = pair
            for hp in get_host_ports(p):
                li = pv[hp]
                prow[li] = True
                crow |= conflict[li]

        pod_ports = np.zeros((P, PT), dtype=bool)
        pod_port_conflict = np.zeros((P, PT), dtype=bool)
        pod_ports[cached] = prev.pod_ports[cached_rows]
        pod_port_conflict[cached] = prev.pod_port_conflict[cached_rows]
        for j, pos in enumerate(fresh_pos):
            fill_ports((pod_ports[pos], pod_port_conflict[pos]), fresh_pods[j])

        D = len(st.drivers)
        # pod volumes are an unsupported (cold-only) argument, so every pod's
        # volume row is zero on both paths
        pod_vol_counts = np.zeros((P, D), dtype=prev.pod_vol_counts.dtype)

        G = 0
        pod_grp_match = np.zeros((P, G), dtype=bool)
        pod_grp_selects = np.zeros((P, G), dtype=bool)
        pod_grp_owned = np.zeros((P, G), dtype=bool)

        (
            run_start,
            run_len,
            run_mode,
            pod_eqprev,
            pod_eqprev_gate,
            pod_eqprev_chain,
        ) = segment_runs(
            pod_reqs, pod_strict_reqs, pod_requests, pod_tol_tpl, pod_tol_node,
            pod_ports, pod_port_conflict, pod_vol_counts,
            pod_grp_match, pod_grp_selects, pod_grp_owned, G,
        )

        problem = SchedulingProblem(
            lane_valid=prev.lane_valid,
            lane_numeric=prev.lane_numeric,
            lane_lex_rank=prev.lane_lex_rank,
            key_wellknown=prev.key_wellknown,
            pod_reqs=pod_reqs,
            pod_requests=pod_requests,
            pod_tol_tpl=pod_tol_tpl,
            pod_tol_node=pod_tol_node,
            pod_ports=pod_ports,
            pod_port_conflict=pod_port_conflict,
            pod_strict_reqs=pod_strict_reqs,
            it_reqs=prev.it_reqs,
            it_alloc=prev.it_alloc,
            it_cap=prev.it_cap,
            offer_zone=prev.offer_zone,
            offer_ct=prev.offer_ct,
            offer_ok=prev.offer_ok,
            offer_price=prev.offer_price,
            offer_zc=prev.offer_zc,
            tpl_reqs=prev.tpl_reqs,
            tpl_overhead=prev.tpl_overhead,
            tpl_it_ok=prev.tpl_it_ok,
            tpl_remaining=prev.tpl_remaining,
            node_reqs=ReqTensor(
                admitted=prev.node_reqs.admitted[node_sel],
                comp=prev.node_reqs.comp[node_sel],
                gt=prev.node_reqs.gt[node_sel],
                lt=prev.node_reqs.lt[node_sel],
                defined=prev.node_reqs.defined[node_sel],
            ),
            node_avail=prev.node_avail[node_sel],
            node_overhead=prev.node_overhead[node_sel],
            node_used_ports=prev.node_used_ports[node_sel],
            pod_vol_counts=pod_vol_counts,
            node_vol_used=prev.node_vol_used[node_sel],
            node_vol_limits=prev.node_vol_limits[node_sel],
            grp_type=prev.grp_type,
            grp_key=prev.grp_key,
            grp_max_skew=prev.grp_max_skew,
            grp_min_domains=prev.grp_min_domains,
            grp_counts0=prev.grp_counts0,
            grp_registered0=prev.grp_registered0,
            grp_inverse=prev.grp_inverse,
            grp_has_filter=prev.grp_has_filter,
            grp_filter=prev.grp_filter,
            grp_filter_valid=prev.grp_filter_valid,
            pod_grp_match=pod_grp_match,
            pod_grp_selects=pod_grp_selects,
            pod_grp_owned=pod_grp_owned,
            claim_hostname_lane=prev.claim_hostname_lane,
            pod_active=np.ones(P, dtype=bool),
            run_start=run_start,
            run_len=run_len,
            run_mode=run_mode,
            pod_eqprev=pod_eqprev,
            pod_eqprev_gate=pod_eqprev_gate,
            pod_eqprev_chain=pod_eqprev_chain,
        )
        meta = ProblemMeta(
            keys=st.meta.keys,
            values_per_key=st.meta.values_per_key,
            resource_names=resource_names,
            pod_order=order,
            template_names=st.meta.template_names,
            instance_type_names=st.meta.instance_type_names,
            node_names=[n.name for n in nodes],
            zone_key_idx=ZONE_KEY,
            ct_key_idx=CT_KEY,
            hostname_key_idx=HOSTNAME_KEY,
        )
        self.stats["patched"] += 1
        self.last_rows_prev = rows_prev
        self.last_patch = {
            "mode": "patched",
            "reason": "",
            "reused_rows": int(cached.sum()),
            "fresh_rows": int(len(fresh_pos)),
            "pods": P,
        }
        self._state = _DeltaState(
            pods=spods,
            uid_row={p.uid: i for i, p in enumerate(spods)},
            pod_digests=cur_digests,
            nodes=list(nodes),
            node_row={n.name: i for i, n in enumerate(nodes)},
            node_digests={n.name: st.node_digests[n.name] for n in nodes},
            problem=problem,
            meta=meta,
            vocab=vocab,
            port_vocab=pv,
            port_conflict=conflict,
            drivers=st.drivers,
            instance_types=list(instance_types),
            it_digests=st.it_digests,
            templates=list(templates),
            tpl_digests=st.tpl_digests,
            num_claim_slots=num_claim_slots,
        )
        return EncodedProblem(problem=problem, meta=meta)
