"""Warm-state re-solve: reuse the previous placement under churn.

``StreamingSolver`` wraps any ``SolverBackend``. Each cycle it diffs the
incoming snapshot against the previous one (streaming/delta.py digests) and
splits the batch into three buckets:

  resolved   pods whose gates could have changed — arrivals, spec changes,
             previous failures, residents of removed/changed nodes, and (on
             any churn at all) every topology-constrained pod, since counts
             anywhere can move a skew gate. These re-solve through the inner
             backend against the *residual* world: real nodes with pinned
             capacity pre-consumed, plus each surviving claim exposed as a
             pseudo-node so re-solved pods can still join it. The sub-solve
             goes through the inner backend's ordinary entry, so with
             ``KARPENTER_TPU_RELAX`` on it takes the same two-phase
             relaxation+repair path (and full-level gate) as any other
             solve — no streaming-side switch exists or is needed.
  reused     everything else — pinned to its previous bin verbatim. The
             merged result must pass the validator's FULL-level gate or the
             whole cycle falls back to a cold solve.
  certified  the subset of ``reused`` that is *provably* identical to what a
             cold solve of the current snapshot would produce: the FFD-queue
             prefix that matches the previous queue up to the first churned
             pod. FFD placement is sequential — everything before the first
             perturbation replays move-for-move (node removals only shrink
             the bin list ahead of the iteration order; node adds go cold) —
             and beyond it the delete-cascade can reshuffle, so certification
             stops there. tests/test_streaming_parity.py fuzzes exactly this
             contract: certified placements bit-identical to cold, the rest
             validator-clean.

Fallback triggers (all recorded in ``last_outcome`` and the
``solver_warm_solves_total`` counter): first cycle, delta fraction above
``KARPENTER_TPU_DELTA_MAX_FRAC`` (default 0.15), instance-type/template/node
universe changes, unsupported solve arguments, validator rejection, or any
exception inside the warm path. A fallback is always a plain inner solve of
the full batch — the warm path can degrade, never corrupt.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.objects import IN, Pod
from karpenter_tpu.cloudprovider.types import InstanceType
from karpenter_tpu.metrics.registry import DELTA_REUSE_RATIO, WARM_SOLVES
from karpenter_tpu.obs import flight, slo, trace
from karpenter_tpu.scheduling import Requirement, Requirements, pod_requirements
from karpenter_tpu.scheduling.hostports import get_host_ports
from karpenter_tpu.solver import validator as val
from karpenter_tpu.solver.backend import Placement, SolveResult, SolverBackend
from karpenter_tpu.solver.encode import NodeInfo, TemplateInfo, ffd_order
from karpenter_tpu.solver.oracle import _fits, _has_offering
from karpenter_tpu.streaming.delta import (
    DeltaEncoder,
    SnapshotDelta,
    diff_snapshots,
)
from karpenter_tpu.streaming import snapshot as journal
from karpenter_tpu.testing import faults
from karpenter_tpu.utils import resources as res

_WARM_CLAIM_PREFIX = "warm-claim-"


def _has_topology_constraints(p: Pod) -> bool:
    if p.spec.topology_spread_constraints:
        return True
    aff = p.spec.affinity
    if aff is None:
        return False
    return bool(
        (aff.pod_affinity and (aff.pod_affinity.required or aff.pod_affinity.preferred))
        or (
            aff.pod_anti_affinity
            and (aff.pod_anti_affinity.required or aff.pod_anti_affinity.preferred)
        )
    )


@dataclass
class _StreamState:
    """Previous accepted cycle: the snapshot, its result, and the FFD queue
    order plus certification frontier needed to prove the next prefix."""

    pods: List[Pod]
    pod_digests: Dict[str, str]
    nodes: List[NodeInfo]
    node_digests: Dict[str, str]
    instance_types: List[InstanceType]
    templates: List[TemplateInfo]
    result: SolveResult
    order_uids: List[str]  # FFD queue order of `pods`
    certified_uids: frozenset  # uids whose placements are provably cold-identical
    # uid -> ("node", name) | ("claim", index) | ("fail", reason)
    placement_of: Dict[str, Tuple[str, object]] = field(default_factory=dict)
    # True when this state was restored from the on-disk journal: universe
    # comparisons must go by content digest — unpickled objects can never
    # pass the identity fast path
    restored: bool = False


def _index_placements(pods: Sequence[Pod], result: SolveResult) -> Dict[str, Tuple[str, object]]:
    out: Dict[str, Tuple[str, object]] = {}
    for name, idxs in result.node_pods.items():
        for i in idxs:
            out[pods[i].uid] = ("node", name)
    for ci, c in enumerate(result.new_claims):
        for i in c.pod_indices:
            out[pods[i].uid] = ("claim", ci)
    for i, reason in result.failures.items():
        out[pods[i].uid] = ("fail", reason)
    return out


class StreamingSolver(SolverBackend):
    """SolverBackend wrapper adding warm-state re-solve. Safe to wire under
    SupervisedSolver (KARPENTER_TPU_DELTA=1 does exactly that); stateless
    callers just see a normal backend that happens to get faster under churn.

    ``maintain_encoded`` additionally runs a DeltaEncoder over every supported
    snapshot so tensor backends (and the bench) can read ``last_encoded`` —
    off by default because a host-side inner solve doesn't need the tensors.
    """

    def __init__(
        self,
        inner: SolverBackend,
        max_frac: Optional[float] = None,
        maintain_encoded: bool = False,
        tenant: Optional[str] = None,
    ):
        self.inner = inner
        if max_frac is None:
            max_frac = float(os.environ.get("KARPENTER_TPU_DELTA_MAX_FRAC", "0.15"))
        self.max_frac = max_frac
        self.maintain_encoded = maintain_encoded
        # the serve layer names each stream: tenant labels the warm-solve
        # counter and namespaces the journal so per-tenant streams restore
        # (and invalidate) independently. None = pre-tenant behavior exactly.
        self.tenant = tenant
        self.delta_encoder = DeltaEncoder()
        self.last_encoded = None
        self._prev: Optional[_StreamState] = None
        self.last_outcome: Optional[str] = None
        self.last_reuse_ratio = 0.0
        self.last_delta: Optional[SnapshotDelta] = None
        self.last_certified_uids: frozenset = frozenset()
        self.counters: Dict[str, int] = {}
        self._accepts = 0
        # crash-consistent journal (KARPENTER_TPU_STATE_DIR): a fresh process
        # restores the last accepted cycle and re-enters the warm path on its
        # first solve — classified fallback to cold on ANY journal defect
        self.restored_from_journal = False
        self.last_restore_outcome: Optional[str] = None
        if journal.enabled():
            outcome, state = journal.load(namespace=self.tenant)
            self.last_restore_outcome = outcome
            if state is not None:
                state.restored = True
                self._prev = state
                self.restored_from_journal = True

    def set_tenant(self, tenant: Optional[str]) -> None:
        """Adopt a tenant identity after construction (the supervisor wraps
        pre-built streaming solvers). Re-runs the journal restore under the
        tenant namespace only while still cold — live warm state wins."""
        self.tenant = tenant
        if tenant is not None and self._prev is None and journal.enabled():
            outcome, state = journal.load(namespace=tenant)
            self.last_restore_outcome = outcome
            if state is not None:
                state.restored = True
                self._prev = state
                self.restored_from_journal = True

    # supervisor calls this on validator rejection: a quarantined result must
    # never seed the next warm cycle
    def reset_streaming_state(self) -> None:
        self._prev = None
        self.delta_encoder.reset()
        # the inner backend may hold its own carried device state (JaxSolver's
        # DeviceWorld): the quarantine contract covers the whole stack, so the
        # reset propagates down the same hook
        inner_reset = getattr(self.inner, "reset_streaming_state", None)
        if callable(inner_reset):
            inner_reset()
        # the on-disk journal mirrors _prev: a quarantined result must not
        # resurrect in the next process either
        if journal.enabled():
            journal.invalidate(namespace=self.tenant)

    reset = reset_streaming_state

    # -- entry ----------------------------------------------------------------

    def solve(
        self,
        pods: Sequence[Pod],
        instance_types: Sequence[InstanceType],
        templates: Sequence[TemplateInfo],
        nodes: Sequence[NodeInfo] = (),
        pod_requirements_override=None,
        topology=None,
        cluster_pods: Sequence = (),
        domains=None,
        pod_volumes=None,
    ) -> SolveResult:
        faults.crash_point("cycle.enter")
        pods = list(pods)
        nodes = list(nodes)
        unsupported = (
            pod_requirements_override is not None
            or topology is not None
            or len(cluster_pods) > 0
            or domains is not None
            or pod_volumes is not None
        )
        if unsupported:
            # consolidation sims and override passes carry state the pinning
            # logic doesn't model; stay out of their way entirely
            self.reset_streaming_state()
            result = self.inner.solve(
                pods, instance_types, templates, nodes=nodes,
                pod_requirements_override=pod_requirements_override,
                topology=topology, cluster_pods=cluster_pods,
                domains=domains, pod_volumes=pod_volumes,
            )
            self._finish("cold-unsupported", 0.0, len(pods))
            return result

        prev = self._prev
        with trace.span("delta_encode", pods=len(pods)):
            if prev is None:
                delta, pod_digests, node_digests = diff_snapshots(
                    (), (), pods, nodes
                )
            else:
                delta, pod_digests, node_digests = diff_snapshots(
                    prev.pods, prev.nodes, pods, nodes,
                    prev_pod_digests=prev.pod_digests,
                    prev_node_digests=prev.node_digests,
                )
            self.last_delta = delta
            trace.attr("pod_events", delta.pod_events)
            trace.attr("node_events", delta.node_events)
            if self.maintain_encoded:
                self.last_encoded = self.delta_encoder.encode(
                    pods, instance_types, templates, nodes=nodes
                )
                trace.attr("encode_mode", self.delta_encoder.last_patch.get("mode"))

        cold_reason = self._cold_reason(prev, delta, pods, instance_types, templates)
        if cold_reason is None:
            try:
                with trace.span("warm_solve", pods=len(pods)):
                    out = self._warm(
                        prev, delta, pods, pod_digests, instance_types, templates, nodes
                    )
                    if out is not None:
                        result, seeds, certified, order = out
                        ratio = (len(pods) - len(seeds)) / max(1, len(pods))
                        trace.attr("resolved", len(seeds))
                        trace.attr("reused", len(pods) - len(seeds))
                        trace.attr("certified", len(certified))
                        self._accept(
                            pods, pod_digests, nodes, node_digests,
                            instance_types, templates, result, certified,
                            order=order,
                        )
                        self._finish("warm", ratio, len(pods))
                        return result
                    cold_reason = "warm-rejected"
            except Exception:  # noqa: BLE001 — degrade to cold, never fail the cycle
                cold_reason = "warm-error"

        result = self.inner.solve(pods, instance_types, templates, nodes=nodes)
        # a cold solve IS the reference answer: every placement is certified
        self._accept(
            pods, pod_digests, nodes, node_digests, instance_types, templates,
            result, frozenset(p.uid for p in pods),
        )
        self._finish(cold_reason, 0.0, len(pods))
        return result

    # -- bookkeeping ----------------------------------------------------------

    def _finish(self, outcome: str, ratio: float, pods: int) -> None:
        self.last_outcome = outcome
        self.last_reuse_ratio = ratio
        self.counters[outcome] = self.counters.get(outcome, 0) + 1
        labels = {"outcome": outcome}
        if self.tenant is not None:
            # bounded label value (overflow tenants -> "other"); the raw
            # tenant id still namespaces the journal and quarantine
            from karpenter_tpu.metrics.registry import tenant_label

            labels["tenant"] = tenant_label(self.tenant)
        WARM_SOLVES.inc(labels=labels)
        DELTA_REUSE_RATIO.set(ratio)
        if slo.enabled():
            # stream-warm objective: cold leaks burn budget, warm hits and
            # legitimate first-cold solves earn it
            slo.on_stream(outcome)
            flight.record(
                flight.KIND_STREAM_CYCLE, outcome=outcome, pods=pods,
                tenant=self.tenant, reuse_ratio=round(ratio, 4),
            )
        trace.attr("streaming_outcome", outcome)
        trace.attr("reuse_ratio", round(ratio, 4))

    def _accept(
        self, pods, pod_digests, nodes, node_digests, instance_types, templates,
        result, certified, order=None,
    ) -> None:
        # the warm path already sorted the queue for _certify — reuse it
        # rather than paying the O(P log P) constraint-signature sort twice
        # per cycle; cold accepts (no order threaded) still compute their own
        if order is None:
            order = ffd_order(pods)
        self._prev = _StreamState(
            pods=pods,
            pod_digests=pod_digests,
            nodes=nodes,
            node_digests=node_digests,
            instance_types=list(instance_types),
            templates=list(templates),
            result=result,
            order_uids=[pods[i].uid for i in order],
            certified_uids=frozenset(certified),
            placement_of=_index_placements(pods, result),
        )
        self.last_certified_uids = frozenset(certified)
        self._accepts += 1
        if journal.enabled() and self._accepts % journal.cadence() == 0:
            journal.save(self._prev, namespace=self.tenant)

    def _cold_reason(self, prev, delta, pods, instance_types, templates) -> Optional[str]:
        if prev is None:
            return "cold-first"
        if not pods:
            return "cold-first"
        if delta.added_nodes or delta.changed_nodes:
            # node adds/changes move every bin decision after them; removals
            # are handled warm (residents become seeds)
            return "cold-world-changed"
        if self._universe_changed(
            instance_types, prev.instance_types, prev.restored,
        ) or self._universe_changed(templates, prev.templates, prev.restored):
            return "cold-world-changed"
        if delta.frac > self.max_frac:
            return "cold-threshold"
        return None

    @staticmethod
    def _universe_changed(cur, prev, prev_restored: bool) -> bool:
        """Instance-type/template universe comparison: object identity in the
        steady state (the provisioner passes the same lists), content digests
        when the previous state came off the journal (identity cannot survive
        a pickle round trip)."""
        if len(cur) != len(prev):
            return True
        if all(a is b for a, b in zip(cur, prev)):
            return False
        if not prev_restored:
            return True
        from karpenter_tpu.streaming.delta import (
            instance_type_digest,
            template_digest,
        )
        from karpenter_tpu.solver.encode import TemplateInfo

        fn = (
            template_digest
            if prev and isinstance(prev[0], TemplateInfo)
            else instance_type_digest
        )
        return any(fn(a) != fn(b) for a, b in zip(cur, prev))

    # -- the warm path --------------------------------------------------------

    def _warm(
        self, prev, delta, pods, pod_digests, instance_types, templates, nodes
    ):
        """Returns (result, seed_indices, certified_uids) or None when the
        merged result fails the exit gate (incremental row-scoped check when
        KARPENTER_TPU_DEVICE_GATE is on, full validator otherwise)."""
        uid_index = {p.uid: i for i, p in enumerate(pods)}
        removed_node_names = set(delta.removed_nodes)

        seeds = set(delta.added_pods) | set(delta.changed_pods)
        for uid, (kind, payload) in prev.placement_of.items():
            i = uid_index.get(uid)
            if i is None:
                continue
            if kind == "fail":
                seeds.add(i)  # a delete/reclaim may have freed its blocker
            elif kind == "node" and payload in removed_node_names:
                seeds.add(i)
        # topology closure: any churn can move a count any constrained pod's
        # skew/affinity gate reads, so all of them re-solve together
        churned = delta.pod_events > 0 or delta.node_events > 0
        if churned:
            for i, p in enumerate(pods):
                if _has_topology_constraints(p):
                    seeds.add(i)
        if len(seeds) == len(pods):
            return None  # nothing to reuse — cold is strictly simpler

        # pinned pods keep their previous bin; build the merged skeleton
        merged = SolveResult()
        pinned: List[Tuple[int, str, Dict[str, str]]] = []  # (idx, bin name, labels)
        surviving_claims: Dict[int, Placement] = {}
        claim_members: Dict[int, List[int]] = {}
        for uid, (kind, payload) in prev.placement_of.items():
            i = uid_index.get(uid)
            if i is None or i in seeds:
                continue
            if kind == "node":
                merged.node_pods.setdefault(payload, []).append(i)
            elif kind == "claim":
                claim_members.setdefault(payload, []).append(i)

        node_by_name = {n.name: n for n in nodes}
        for name, idxs in merged.node_pods.items():
            if name not in node_by_name:
                return None  # placement map out of sync with the node diff
            labels = node_by_name[name].requirements.labels()
            for i in idxs:
                pinned.append((i, name, labels))

        claim_index_map: Dict[int, int] = {}
        for ci, members in sorted(claim_members.items()):
            old = prev.result.new_claims[ci]
            requests = dict(templates[old.template_index].daemon_overhead)
            for i in members:
                requests = res.merge(requests, {**res.pod_requests(pods[i]), res.PODS: 1.0})
            pl = Placement(
                template_index=old.template_index,
                nodepool_name=old.nodepool_name,
                pod_indices=list(members),
                instance_type_indices=list(old.instance_type_indices),
                requirements=old.requirements.copy(),
                requests=requests,
            )
            claim_index_map[ci] = len(merged.new_claims)
            merged.new_claims.append(pl)
            surviving_claims[ci] = pl
            labels = old.requirements.labels()
            labels[wk.LABEL_HOSTNAME] = _WARM_CLAIM_PREFIX + str(ci)
            for i in members:
                pinned.append((i, _WARM_CLAIM_PREFIX + str(ci), labels))

        # residual world: real nodes with pinned consumption folded into the
        # overhead side, surviving claims as joinable pseudo-nodes — the
        # shared construction streaming/residual.py states (the incremental
        # consolidation screen pins the same world at the FFDState level)
        from karpenter_tpu.streaming.residual import (
            claim_pseudo_node,
            pinned_node_residuals,
        )

        pinned_by_bin: Dict[str, List[int]] = {}
        for i, bin_name, _ in pinned:
            pinned_by_bin.setdefault(bin_name, []).append(i)
        sub_nodes: List[NodeInfo] = pinned_node_residuals(
            nodes, pods, pinned_by_bin
        )
        for ci, pl in sorted(surviving_claims.items()):
            sub_nodes.append(
                claim_pseudo_node(
                    ci, pl, pods, instance_types, templates,
                    prefix=_WARM_CLAIM_PREFIX,
                )
            )

        sub_indices = sorted(seeds)
        sub_pods = [pods[i] for i in sub_indices]
        census = [(pods[i], labels) for i, _, labels in pinned]
        sub_result = self.inner.solve(
            sub_pods, instance_types, templates, nodes=sub_nodes,
            cluster_pods=census,
        )

        # fold the sub-solve back in, re-narrowing any claim it joined
        joined: Dict[int, List[int]] = {}
        for name, idxs in sub_result.node_pods.items():
            gidx = [sub_indices[si] for si in idxs]
            if name.startswith(_WARM_CLAIM_PREFIX):
                joined.setdefault(int(name[len(_WARM_CLAIM_PREFIX):]), []).extend(gidx)
            else:
                merged.node_pods.setdefault(name, []).extend(gidx)
        for c in sub_result.new_claims:
            merged.new_claims.append(
                Placement(
                    template_index=c.template_index,
                    nodepool_name=c.nodepool_name,
                    pod_indices=[sub_indices[si] for si in c.pod_indices],
                    instance_type_indices=list(c.instance_type_indices),
                    requirements=c.requirements,
                    requests=c.requests,
                )
            )
        for si, reason in sub_result.failures.items():
            merged.failures[sub_indices[si]] = reason
        sub_explain = getattr(sub_result, "explain", None)
        if sub_explain is not None:
            # failed pods are always seeds, so the sub-solve attributed every
            # failure; re-key its report to batch-global indices (the inner
            # backend already published ring/metrics — this is result-carried
            # provenance for events, quarantine dumps, and the provisioner)
            import dataclasses

            from karpenter_tpu.obs import explain as obs_explain

            remapped = obs_explain.ExplainReport(
                backend=sub_explain.backend,
                trace_id=sub_explain.trace_id,
                total_pods=len(pods),
                scheduled=len(pods) - len(merged.failures),
                overhead_s=sub_explain.overhead_s,
            )
            for si, expl in sub_explain.pods.items():
                remapped.pods[sub_indices[si]] = dataclasses.replace(
                    expl, pod=sub_indices[si]
                )
            for si, nom in sub_explain.nominations.items():
                remapped.nominations[sub_indices[si]] = nom
            merged.explain = remapped
        for ci, gidx in joined.items():
            pl = surviving_claims[ci]
            for i in gidx:
                pl.requirements.add(*pod_requirements(pods[i]).values())
                pl.requests = res.merge(
                    pl.requests, {**res.pod_requests(pods[i]), res.PODS: 1.0}
                )
                pl.pod_indices.append(i)
            pl.requirements.delete(wk.LABEL_HOSTNAME)
            surviving = [
                ti
                for ti in pl.instance_type_indices
                if not instance_types[ti].requirements.intersects(pl.requirements)
                and _fits(pl.requests, instance_types[ti].allocatable())
                and _has_offering(instance_types[ti], pl.requirements)
            ]
            if not surviving:
                return None
            pl.instance_type_indices = surviving

        from karpenter_tpu import verify

        if verify.enabled():
            # re-gate only what this merge touched: sub-solve claims, reused
            # claims the fold-back joined (re-narrowed), and nodes that
            # received pods. Untouched reused pins were proven when the
            # previous result was accepted and their pods' digests are
            # unchanged; the incremental gate still rides a seeded audit
            # sample of them each cycle. Topology skew re-runs whenever any
            # seed carries a spread constraint — the topology closure above
            # guarantees skew cohorts are then entirely inside the seed set.
            n_sub = len(sub_result.new_claims)
            touched_claims = set(
                range(len(merged.new_claims) - n_sub, len(merged.new_claims))
            )
            touched_claims |= {claim_index_map[ci] for ci in joined}
            scope = verify.IncrementalScope(
                claim_indices=touched_claims,
                node_names={
                    name
                    for name in sub_result.node_pods
                    if not name.startswith(_WARM_CLAIM_PREFIX)
                },
                check_topology=any(
                    _has_topology_constraints(pods[i]) for i in seeds
                ),
                total_claims=len(merged.new_claims),
                total_nodes=len(nodes),
            )
            violations = verify.incremental_gate(
                merged, pods, instance_types, templates, nodes, scope
            )
        else:
            violations = val.validate_result(
                merged, pods, instance_types, templates, nodes=nodes, level="full"
            )
        if violations:
            return None

        order = ffd_order(pods)
        certified = self._certify(prev, delta, pods, seeds, order)
        return merged, seeds, certified, order

    def _certify(self, prev, delta, pods, seeds, order) -> frozenset:
        """The FFD-queue prefix provably identical to a cold solve: positions
        matching the previous queue uid-for-uid, stopping at the first seed,
        the first pod outside the previous cycle's own certified set, or (when
        the node set shrank) the first topology-constrained pod — a removed
        node's hostname leaves every spread denominator, which can move any
        later constrained pick."""
        node_set_changed = bool(delta.removed_nodes)
        certified: List[str] = []
        for pos, i in enumerate(order):
            uid = pods[i].uid
            if pos >= len(prev.order_uids) or prev.order_uids[pos] != uid:
                break
            if i in seeds or uid not in prev.certified_uids:
                break
            if node_set_changed and _has_topology_constraints(pods[i]):
                break
            certified.append(uid)
        return frozenset(certified)
