"""Residual-world construction — the shared statement of "pin what stayed".

Two fast paths in this codebase re-solve a small remainder of a problem
against a world whose earlier decisions are pinned:

  - the streaming warm re-solve (streaming/warm.py): churn seeds re-solve
    against nodes whose kept pods' consumption is folded into the daemon
    overhead, with surviving claims exposed as joinable pseudo-nodes;
  - the incremental consolidation screen (disruption/screen_delta.py):
    candidate residents re-solve against a carried FFDState whose node/claim
    consumption the base-world solve accumulated on device.

The warm path pins at the NodeInfo level (it re-encodes a sub-problem), the
screen pins at the FFDState level (it stays on device), but the residual
world they construct is the same object: capacity minus everything the kept
placement consumes, ports and pod-count included. This module holds the
NodeInfo-level builders so warm.py and the screen-delta oracle tests state
that construction once.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.objects import IN
from karpenter_tpu.scheduling import Requirement
from karpenter_tpu.scheduling.hostports import get_host_ports
from karpenter_tpu.solver.encode import NodeInfo
from karpenter_tpu.utils import resources as res

# hostname prefix of claim pseudo-nodes (must never collide with a real node)
CLAIM_PREFIX = "@claim-"


def pinned_node_residuals(
    nodes: Sequence[NodeInfo],
    pods: Sequence,
    pinned_by_bin: Dict[str, List[int]],
) -> List[NodeInfo]:
    """Real nodes with their pinned pods' consumption folded into the
    overhead side: available capacity is untouched (the encoder subtracts
    overhead), host ports extend with the pinned pods' reservations, and the
    implicit pods=1 resource rides along — so a re-solve sees exactly the
    capacity the pinned placement leaves behind."""
    out: List[NodeInfo] = []
    for n in nodes:
        overhead = dict(n.daemon_overhead)
        ports = list(n.host_ports)
        for i in pinned_by_bin.get(n.name, ()):
            overhead = res.merge(
                overhead, {**res.pod_requests(pods[i]), res.PODS: 1.0}
            )
            ports.extend(get_host_ports(pods[i]))
        out.append(
            NodeInfo(
                name=n.name,
                requirements=n.requirements.copy(),
                taints=n.taints,
                available=dict(n.available),
                daemon_overhead=overhead,
                host_ports=ports,
                volume_used=dict(n.volume_used),
                volume_limits=dict(n.volume_limits),
            )
        )
    return out


def claim_pseudo_node(
    ci: int,
    placement,
    pods: Sequence,
    instance_types: Sequence,
    templates: Sequence,
    prefix: str = CLAIM_PREFIX,
) -> NodeInfo:
    """A surviving claim as a joinable pseudo-node: hostname-pinned so only
    an explicit requirement can land there, capacity the elementwise MIN
    over its surviving instance types (a joining pod must fit EVERY one, so
    actuation keeps its full choice set), consumption-so-far as overhead."""
    name = prefix + str(ci)
    reqs = placement.requirements.copy()
    reqs.add(Requirement(wk.LABEL_HOSTNAME, IN, [name]))
    alloc = None
    for ti in placement.instance_type_indices:
        a = instance_types[ti].allocatable()
        alloc = a if alloc is None else {
            k: min(alloc.get(k, float("inf")), a.get(k, float("inf")))
            for k in set(alloc) | set(a)
        }
    ports: List = []
    for i in placement.pod_indices:
        ports.extend(get_host_ports(pods[i]))
    return NodeInfo(
        name=name,
        requirements=reqs,
        taints=templates[placement.template_index].taints,
        available=alloc or {},
        daemon_overhead=dict(placement.requests),
        host_ports=ports,
    )
