"""DeviceWorld: the encoded world lives on device across solve cycles.

The legacy cycle pays host encode -> full H2D -> solve dispatch -> gate
dispatch -> D2H -> host decode every time, even when the DeltaEncoder
(streaming/delta.py) proves that only a few pod rows changed. This handle
keeps the padded ``SchedulingProblem`` RESIDENT in device buffers between
cycles and turns each supported cycle into:

  1. host delta encode — the existing row splice, which also yields
     ``last_rows_prev``: the per-row map into the previous world;
  2. ``patch_world`` (ops/fused.py) — a jitted gather that rewrites the
     pod-axis leaves of the DONATED resident world in place from a small
     fresh-row stack (O(changed) H2D instead of O(world));
  3. ``solve_ffd_fused_gate`` — the sweeps solve and the device verification
     gate (verify/device.py) in ONE dispatch, returning the placement AND
     its invariant counts in a single batched fetch; explain attribution
     reuses the resident tensors when enabled.

Both dispatches are enqueued asynchronously (``KARPENTER_TPU_DEVICE_WORLD_
PIPELINE``, default on): the host builds the next dispatch's arguments and
runs its bookkeeping while the device executes, and ``last_cycle`` reports
the measured overlap fraction. True encode(N+1)-against-solve(N) pipelining
is bounded by snapshot arrival — the knob controls intra-cycle overlap.

Round-11 discipline throughout: anything the patched path cannot prove is a
CLASSIFIED standdown to the untouched legacy path
(``solver_world_patch_total{outcome}``), and any post-solve surprise
(slot overflow, nonzero gate counts, an exception) additionally drops the
resident world and the delta state so a stale world can never serve a later
cycle. A delta bug costs latency, never correctness; the bit-identity fuzz
in tests/test_device_world.py holds the patched world to ``pad_problem(cold
encode)`` array-for-array.

Default OFF (``KARPENTER_TPU_DEVICE_WORLD``); flag off, the backend never
constructs this object and every program it would dispatch stays untraced.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np

from karpenter_tpu.metrics.registry import (
    COMPILE_CACHE,
    TRANSFER_BYTES,
    WORLD_PATCH,
)
from karpenter_tpu.obs import programs, trace
from karpenter_tpu.ops import relax
from karpenter_tpu.ops.ffd import (
    KIND_CLAIM,
    KIND_NEW_CLAIM,
    KIND_NODE,
    KIND_NO_SLOT,
    IterCounts,
)
from karpenter_tpu.ops.ffd_core import problem_bounds_free
from karpenter_tpu.ops.fused import (
    build_patch_args,
    patch_world,
    solve_ffd_fused_gate,
)
from karpenter_tpu.ops.padding import pad_problem, pod_axis_bucket, pow2_bucket
from karpenter_tpu.provisioning.preferences import Preferences
from karpenter_tpu.solver import aot, ordering
from karpenter_tpu.solver.backend import FAIL_INCOMPATIBLE, SolveResult
from karpenter_tpu.streaming.delta import DeltaEncoder

log = logging.getLogger(__name__)


def enabled() -> bool:
    """KARPENTER_TPU_DEVICE_WORLD, default OFF. Read per call so tests and
    operators can toggle a live process; the first enabled cycle adopts a
    world, the first disabled one simply stops consulting it."""
    return os.environ.get("KARPENTER_TPU_DEVICE_WORLD", "0") not in ("", "0")


def pipeline_depth() -> int:
    """KARPENTER_TPU_DEVICE_WORLD_PIPELINE: 0 synchronizes after every
    dispatch (debug/measurement baseline); >= 1 (default) enqueues the patch
    and fused solve asynchronously so host argument-building and bookkeeping
    overlap device execution."""
    try:
        return max(0, int(os.environ.get("KARPENTER_TPU_DEVICE_WORLD_PIPELINE", "1")))
    except ValueError:
        return 1


def _relax_would_fire(templates) -> bool:
    """Host mirror of ops/relax.relax_applicable WITHOUT encoding: the dense
    phase-1 program fires exactly when no template carries a finite remaining
    limit (tpl_remaining all +inf — solver/encode.py step 7). The fused
    program has no relax phase, so those cycles stand down BEFORE the delta
    encoder advances — a post-encode bail would desync the resident world
    from the delta state."""
    for t in templates:
        rr = getattr(t, "remaining_resources", None)
        if rr and any(np.isfinite(v) for v in rr.values()):
            return False
    return True


class DeviceWorld:
    """Per-backend handle owning the resident world, its DeltaEncoder, and
    the patch/fused dispatch loop. Constructed lazily by JaxSolver on the
    first enabled cycle; ``reset()`` is wired into the backend's
    ``reset_streaming_state`` hook so validator rejection or a supervisor
    quarantine drops the world the same way it drops streaming state."""

    def __init__(self, backend):
        self.backend = backend
        self.delta = DeltaEncoder(well_known_labels=backend.well_known)
        self.world = None  # device-resident padded SchedulingProblem
        self.meta = None
        self.node_names: Optional[List[str]] = None
        self.max_claims: Optional[int] = None
        # consecutive on-device patches since the last adopt (0 right after
        # an adopt): the first patch reports "patched", later ones
        # "repatched" so the steady state is visible at a glance
        self.patched_streak = 0
        self.cold_solves = 0  # full-world uploads (adopts) — the counted exception
        self.cycles = 0  # cycles this handle actually served
        self.counters: Dict[str, int] = {}
        self.last_outcome: Optional[str] = None
        self.last_cycle: Dict[str, float] = {}
        # uid -> (pod digest, checked_requirements(pod) is not None): the
        # host's only O(P) per-cycle obligation besides the delta diff
        self._check_cache: Dict[str, tuple] = {}

    # supervisor/backend reset hook: a quarantined or rejected result must
    # never leave a stale world to patch against
    def reset(self) -> None:
        self.world = None
        self.meta = None
        self.node_names = None
        self.max_claims = None
        self.patched_streak = 0
        self.delta.reset()
        self._check_cache.clear()

    def _record(self, outcome: str) -> None:
        self.counters[outcome] = self.counters.get(outcome, 0) + 1
        self.last_outcome = outcome
        WORLD_PATCH.inc({"outcome": outcome})
        trace.attr("world_outcome", outcome)

    def _standdown(self, reason: str) -> None:
        self._record("standdown-" + reason)
        return None

    # -- entry -----------------------------------------------------------------

    def try_solve(
        self,
        pods: Sequence,
        instance_types: Sequence,
        templates: Sequence,
        nodes: Sequence,
        pod_requirements_override,
        topology,
        cluster_pods: Sequence,
        domains,
        pod_volumes,
        max_claims: int,
    ) -> Optional[SolveResult]:
        """One cycle through the device-resident path, or None on a
        classified standdown (the caller's legacy path then serves the cycle
        unchanged). Every pre-encode standdown leaves BOTH the world and the
        delta state untouched — they stay in lockstep for the next supported
        cycle."""
        from karpenter_tpu.solver import jax_backend as jb
        from karpenter_tpu.streaming.warm import _has_topology_constraints

        if (
            pod_requirements_override is not None
            or topology is not None
            or len(cluster_pods) > 0
            or domains is not None
            or pod_volumes is not None
        ):
            return self._standdown("unsupported-args")
        if jb._USE_RUNS:
            return self._standdown("runs-mode")
        if os.environ.get("KARPENTER_TPU_SHARD", "0") not in ("", "0"):
            return self._standdown("shard")
        if ordering.lanes_enabled():
            return self._standdown("order-policy")
        if any(
            t.effect == "PreferNoSchedule" for tpl in templates for t in tpl.taints
        ) or any(Preferences.is_relaxable(p) for p in pods):
            # the per-pass relax ladder re-encodes between launches; the
            # resident world models exactly one encode per cycle
            return self._standdown("not-sweeps")
        if any(_has_topology_constraints(p) for p in pods):
            # delta worlds are G=0 by contract (streaming/delta.py)
            return self._standdown("topology")
        if relax.enabled() and _relax_would_fire(templates):
            return self._standdown("relax-applicable")

        from karpenter_tpu.solver import mesh_health
        from karpenter_tpu.testing import faults as _faults

        try:
            if _faults.active() is not None:
                # fault-injection hook: the resident world lives on exactly
                # the devices its buffers sit on — a device rule targeting
                # one of them fires here, before any dispatch touches them
                leaves = (
                    jax.tree_util.tree_leaves(self.world)
                    if self.world is not None else []
                )
                devs = list(leaves[0].devices()) if leaves else None
                mesh_health.dispatch_check(devs)
            return self._cycle(pods, instance_types, templates, nodes, max_claims)
        except Exception as exc:  # noqa: BLE001 — degrade to legacy, drop the world
            if mesh_health.handle_dispatch_failure(exc) is not None:
                # the device died WITH the resident buffers: reset and let a
                # later cycle re-adopt from scratch on whatever devices the
                # recarved mesh kept — a world whose buffers died is never
                # resurrected (patching against it would read garbage)
                self.reset()
                self._record("standdown-device-lost")
                return None
            log.warning(
                "device_world: standdown on error, world dropped: %s: %s",
                type(exc).__name__, exc, exc_info=True,
            )
            self.reset()
            self._record("standdown-error")
            return None

    # -- the cycle -------------------------------------------------------------

    def _cycle(self, pods, instance_types, templates, nodes, max_claims):
        from karpenter_tpu.solver import jax_backend as jb

        backend = self.backend
        pipelined = pipeline_depth() >= 1
        t0 = time.perf_counter()
        with trace.span("encode", queue=len(pods)):
            encoded = self.delta.encode(
                pods, instance_types, templates, nodes=nodes,
                num_claim_slots=max_claims,
            )
        spliced, meta = encoded.problem, encoded.meta
        mode = self.delta.last_patch.get("mode")
        rows_prev = self.delta.last_rows_prev
        t_encode = time.perf_counter()

        # -- stage 1: bring the resident world up to date ----------------------
        donated = 0
        h2d = 0
        if mode == "patched" and rows_prev is not None and self.world is not None:
            drift = self._drift(spliced, nodes, meta, max_claims)
        else:
            drift = self.delta.last_patch.get("reason") or "no-world"
        if drift is None:
            stage_outcome = "patched" if self.patched_streak == 0 else "repatched"
            self.patched_streak += 1
            args = build_patch_args(spliced, rows_prev, self.world)
            h2d = jb._nbytes(args)
            donated = jb._nbytes(self.world)
            key = jb._program_key(patch_world, max_claims, (self.world, args))
            cache_hit = key in jb._COMPILED_PROGRAMS
            jb._COMPILED_PROGRAMS.add(key)
            COMPILE_CACHE.inc({"result": "hit" if cache_hit else "miss"})
            if cache_hit:
                backend.compile_cache_hits += 1
            else:
                backend.compile_cache_misses += 1
            TRANSFER_BYTES.inc({"direction": "h2d"}, h2d)
            aot_handle = aot.maybe_begin(patch_world, self.world, max_claims, args)
            obs = programs.begin_dispatch(
                "patch_world", max_claims, (self.world, args)
            )
            with trace.span(
                "patch" if cache_hit else "compile",
                cache="hit" if cache_hit else "miss",
                program="patch_world",
            ) as sp:
                if aot_handle is not None:
                    self.world = aot_handle.call()
                else:
                    self.world = patch_world(self.world, args)
                if not pipelined:
                    jax.block_until_ready(self.world)
                if obs is not None:
                    source = obs.finish(
                        problem_bytes=h2d,
                        carried_bytes=donated,
                        donated_bytes=donated,
                        source_override=(
                            aot_handle.source_override
                            if aot_handle is not None else None
                        ),
                    )
                    if sp is not None:
                        sp.attrs["program_key"] = obs.key
                        sp.attrs["cache_source"] = source
                if sp is not None:
                    sp.count("h2d_bytes", h2d)
                    sp.count("donated_bytes", donated)
        else:
            stage_outcome = "adopt-" + drift
            self.patched_streak = 0
            self.cold_solves += 1
            padded = pad_problem(spliced)
            h2d = jb._nbytes(padded)
            with trace.span("world_adopt", reason=drift) as sp:
                self.world = jax.device_put(padded)
                if not pipelined:
                    jax.block_until_ready(self.world)
                TRANSFER_BYTES.inc({"direction": "h2d"}, h2d)
                if sp is not None:
                    sp.count("h2d_bytes", h2d)
        self.meta = meta
        self.node_names = list(meta.node_names)
        self.max_claims = max_claims
        t_patch = time.perf_counter()

        # -- stage 2 args: built on the host WHILE the device patches ----------
        bf = problem_bounds_free(spliced)
        gbf = self._gate_bounds_free(spliced)
        from karpenter_tpu.ops.ffd_sweeps import _wavefront_lanes

        wf = _wavefront_lanes()
        pod_check = self._pod_check(pods, meta)
        t_prep = time.perf_counter()

        # -- stage 2: fused solve + gate, one dispatch, one batched fetch ------
        solve_key = jb._program_key(solve_ffd_fused_gate, max_claims, self.world)
        cache_hit = solve_key in jb._COMPILED_PROGRAMS
        jb._COMPILED_PROGRAMS.add(solve_key)
        COMPILE_CACHE.inc({"result": "hit" if cache_hit else "miss"})
        if cache_hit:
            backend.compile_cache_hits += 1
        else:
            backend.compile_cache_misses += 1
        pc_bytes = int(pod_check.nbytes)
        world_bytes = jb._nbytes(self.world)
        TRANSFER_BYTES.inc({"direction": "h2d"}, pc_bytes)
        reg_eqns = None
        if not cache_hit and programs.eqns_enabled():
            world, pc = self.world, pod_check
            reg_eqns = programs.maybe_count_eqns(
                lambda: jax.make_jaxpr(
                    lambda: solve_ffd_fused_gate(world, pc, max_claims, bf, wf, gbf)
                )()
            )
        aot_handle = aot.maybe_begin(
            solve_ffd_fused_gate, self.world, max_claims, (pod_check, bf, wf, gbf)
        )
        obs = programs.begin_dispatch(
            "solve_ffd_fused_gate", max_claims, self.world,
            statics={"bf": int(bf), "wf": int(wf), "gbf": int(gbf)},
        )
        with trace.span(
            "fused" if cache_hit else "compile",
            cache="hit" if cache_hit else "miss",
            program="solve_ffd_fused_gate",
        ) as sp:
            if aot_handle is not None:
                result, counts = aot_handle.call()
            else:
                result, counts = solve_ffd_fused_gate(
                    self.world, pod_check, max_claims, bf, wf, gbf
                )
            if not pipelined:
                jax.block_until_ready(counts)
            t_dispatch = time.perf_counter()
            state = result.state
            fetched = jax.device_get(
                (
                    result.kind, result.index, result.iters, result.wave_hist,
                    counts,
                    state.claim_open, state.claim_tpl, state.claim_it_ok,
                    state.claim_requests, state.claim_req.admitted,
                    state.claim_req.comp, state.claim_req.gt,
                    state.claim_req.lt, state.claim_req.defined,
                )
            )
            t_fetch = time.perf_counter()
            kinds, indices, _iters, _whist, counts_np, *np_final = fetched
            backend.last_iters = IterCounts(*(int(x) for x in _iters))
            backend.last_wave_hist = (
                [int(x) for x in _whist] if _whist is not None else None
            )
            d2h = jb._nbytes(fetched)
            TRANSFER_BYTES.inc({"direction": "d2h"}, d2h)
            if obs is not None:
                source = obs.finish(
                    problem_bytes=pc_bytes,
                    carried_bytes=world_bytes,
                    result_bytes=d2h,
                    eqns=reg_eqns,
                    source_override=(
                        aot_handle.source_override
                        if aot_handle is not None else None
                    ),
                )
                if sp is not None:
                    sp.attrs["program_key"] = obs.key
                    sp.attrs["cache_source"] = source
            if sp is not None:
                sp.count("h2d_bytes", pc_bytes)
                sp.count("d2h_bytes", d2h)
                for field, value in zip(IterCounts._fields, backend.last_iters):
                    sp.count(field, value)

        # -- classified post-solve standdowns: reset, legacy serves the cycle --
        if (np.asarray(kinds)[: len(pods)] == KIND_NO_SLOT).any():
            # the legacy path owns the escalation ladder (and the recompile);
            # a resident world at the old claim bucket is useless after it
            self.reset()
            return self._standdown("slot-overflow")
        counts_np = np.asarray(counts_np)
        if counts_np.any():
            from karpenter_tpu.verify import device as vdev

            nonzero = {
                vdev.INVARIANTS[i]: int(counts_np[i])
                for i in range(len(vdev.INVARIANTS))
                if counts_np[i]
            }
            log.warning(
                "device_world: fused gate rejected the patched-world solve "
                "(%s) — world dropped, cycle served by the legacy path",
                nonzero,
            )
            self.reset()
            return self._standdown("gate-reject")

        # -- decode ------------------------------------------------------------
        out = SolveResult()
        with trace.span("decode"):
            pod_kinds: Dict[int, tuple] = {}
            failed, failed_rows = [], []
            for row in range(len(meta.pod_order)):
                orig = meta.pod_order[row]  # the batch is the full pod list
                kind, index = int(kinds[row]), int(indices[row])
                if kind in (KIND_NODE, KIND_CLAIM, KIND_NEW_CLAIM):
                    pod_kinds[orig] = (kind, index)
                else:
                    failed.append(orig)
                    failed_rows.append(row)
            from karpenter_tpu.solver.forensics import failure_reason

            for orig in failed:
                out.failures[orig] = failure_reason(
                    pods[orig], instance_types, templates,
                    well_known=backend.well_known,
                ) or FAIL_INCOMPATIBLE
            from karpenter_tpu.obs import explain as obs_explain

            if obs_explain.enabled():
                # attribution reads the RESIDENT tensors — no host re-upload
                result.explain = backend._explain(
                    out, self.world, state, meta, kinds, failed, failed_rows,
                    pod_kinds, instance_types, len(pods),
                )
            jb.decode_claim_placements(out, meta, max_claims, np_final, pod_kinds)
        t_decode = time.perf_counter()

        # the composite gate consumes the fused counts instead of dispatching
        # its own program; screen/skew/audit still run on the published decode
        from karpenter_tpu import verify

        out.verify_ctx = verify.make_context(
            spliced, meta, max_claims, len(pods), False, fused_counts={}
        )
        backend.last_relax = None  # the fused path never runs phase 1
        programs.sample_memory(
            carried_bytes=jb._nbytes(state),
            pods=len(pods),
            cycle=trace.current_trace_id(),
            donated_bytes=donated,
            world_bytes=world_bytes,
        )

        overlapped = (t_prep - t_patch) if pipelined else 0.0
        blocked = t_fetch - t_dispatch
        self.cycles += 1
        self.last_cycle = {
            "outcome": stage_outcome,
            "encode_ms": (t_encode - t0) * 1e3,
            "patch_ms": (t_patch - t_encode) * 1e3,
            "prep_ms": (t_prep - t_patch) * 1e3,
            "solve_ms": (t_fetch - t_prep) * 1e3,
            "decode_ms": (t_decode - t_fetch) * 1e3,
            "cycle_ms": (t_decode - t0) * 1e3,
            "h2d_bytes": h2d + pc_bytes,
            "donated_bytes": donated,
            "world_bytes": world_bytes,
            "overlap_frac": (
                overlapped / (overlapped + blocked)
                if (overlapped + blocked) > 0 else 0.0
            ),
        }
        self._record(stage_outcome)
        return out

    # -- helpers ---------------------------------------------------------------

    def _drift(self, spliced, nodes, meta, max_claims) -> Optional[str]:
        """None when the resident buffers can absorb this delta as a row
        patch; else the adopt reason. The delta preconditions already pin the
        K/V/R/T/TPL/O/PT axes (vocab/resource/port equality, template and
        instance-type identity) — only the pod/node buckets and node order
        can still move."""
        if self.max_claims != max_claims:
            return "claim-slots"
        if int(self.world.pod_requests.shape[0]) != pod_axis_bucket(
            int(np.asarray(spliced.pod_requests).shape[0])
        ):
            return "shape-drift"
        n = len(nodes)
        if int(self.world.node_avail.shape[0]) != (
            pow2_bucket(n, lo=8) if n else 0
        ):
            return "shape-drift"
        if self.node_names != list(meta.node_names):
            return "node-axis-drift"
        if spliced.pod_eqprev is None or self.world.pod_eqprev is None:
            return "shape-drift"
        return None

    def _pod_check(self, pods, meta) -> np.ndarray:
        """bool[P_bucket] per padded row: would the host validator check this
        pod's requirement intersection (checked_requirements non-None)?
        Digest-cached per uid so the steady state pays O(changed), matching
        the delta encoder's own reuse."""
        from karpenter_tpu.solver.validator import checked_requirements
        from karpenter_tpu.streaming.delta import pod_digest

        st = self.delta._state
        digests = (
            st.pod_digests if st is not None
            else {p.uid: pod_digest(p) for p in pods}
        )
        Pb = int(self.world.pod_requests.shape[0])
        pod_check = np.zeros(Pb, dtype=bool)
        for row, orig in enumerate(meta.pod_order):
            p = pods[orig]
            d = digests.get(p.uid) or pod_digest(p)
            ent = self._check_cache.get(p.uid)
            if ent is None or ent[0] != d:
                ent = (d, checked_requirements(p) is not None)
                self._check_cache[p.uid] = ent
            pod_check[row] = ent[1]
        if len(self._check_cache) > 2 * len(pods) + 64:
            live = {p.uid for p in pods}
            self._check_cache = {
                uid: ent for uid, ent in self._check_cache.items() if uid in live
            }
        return pod_check

    @staticmethod
    def _gate_bounds_free(spliced) -> bool:
        from karpenter_tpu.verify import device as vdev

        return vdev.gate_bounds_free(vdev.gate_problem(spliced))
