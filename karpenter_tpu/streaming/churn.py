"""Seeded churn load generator: arrivals, deletes, spot reclaims.

Drives a streaming solver with the traffic shape production actually sees —
a steady arrival process, random deletes, and provider-initiated spot
reclaims injected through the shared ``testing/faults.py`` grammar
(``cloud.reclaim``), so chaos specs and churn configs read identically:

    KARPENTER_TPU_FAULTS="seed=7;cloud.reclaim=2@p0.1"

Everything is seeded: the arrival/delete RNG from ``ChurnConfig.seed``, the
reclaim draws from the fault injector's own (seed, site, call#) hash. The
same config replays the same pod stream byte-for-byte, which is what lets
the parity fuzz compare warm and cold solves of identical snapshots.

``run_churn`` is the shared harness: bench.py's churn scenario, the chaos
sweep's reclaim row, and the streaming tests all call it rather than
reimplementing the drive loop.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

from karpenter_tpu.apis.objects import Container, ObjectMeta, Pod, PodSpec
from karpenter_tpu.solver.encode import NodeInfo
from karpenter_tpu.testing import faults
from karpenter_tpu.utils import resources as res


def default_pod_factory(name: str, rng: random.Random) -> Pod:
    """A plausible mixed-size arrival: cpu/memory drawn from a small set of
    shapes so FFD runs still form (identical shapes compress)."""
    cpu, mem = rng.choice(
        ((0.25, 0.5e9), (0.5, 1e9), (1.0, 2e9), (2.0, 4e9), (4.0, 8e9))
    )
    return Pod(
        metadata=ObjectMeta(name=name, labels={"app": rng.choice(("web", "api", "batch"))}),
        spec=PodSpec(
            containers=[Container(requests={res.CPU: cpu, res.MEMORY: mem})]
        ),
    )


@dataclass
class ChurnConfig:
    seed: int = 0
    arrivals_per_cycle: int = 8
    deletes_per_cycle: int = 4
    min_pods: int = 1  # deletes never drain the batch below this


@dataclass
class ChurnEvent:
    cycle: int
    arrived: List[Pod] = field(default_factory=list)
    deleted: List[Pod] = field(default_factory=list)
    reclaimed: List[str] = field(default_factory=list)  # node names


class ChurnProcess:
    """Mutable cluster snapshot advanced one solve cycle at a time. ``pods``
    and ``nodes`` are the current snapshot; ``step()`` applies one cycle of
    churn and returns what changed."""

    def __init__(
        self,
        pods: Sequence[Pod],
        nodes: Sequence[NodeInfo] = (),
        pod_factory: Callable[[str, random.Random], Pod] = default_pod_factory,
        config: Optional[ChurnConfig] = None,
    ):
        self.config = config or ChurnConfig()
        self.rng = random.Random(self.config.seed)
        self.pods: List[Pod] = list(pods)
        self.nodes: List[NodeInfo] = list(nodes)
        self.pod_factory = pod_factory
        self.cycle = 0
        self.events: List[ChurnEvent] = []

    def step(self) -> ChurnEvent:
        ev = ChurnEvent(cycle=self.cycle)
        self.cycle += 1
        n_del = min(
            self.config.deletes_per_cycle,
            max(0, len(self.pods) - self.config.min_pods),
        )
        if n_del:
            for pos in sorted(
                self.rng.sample(range(len(self.pods)), n_del), reverse=True
            ):
                ev.deleted.append(self.pods.pop(pos))
        for j in range(self.config.arrivals_per_cycle):
            p = self.pod_factory(f"churn-{ev.cycle}-{j}", self.rng)
            ev.arrived.append(p)
            self.pods.append(p)
        # provider-initiated spot reclaim, through the shared fault grammar:
        # one 'cloud' draw per cycle, width = rule.param
        inj = faults.active()
        if inj is not None and self.nodes:
            rule = inj.draw("cloud")
            if rule is not None and rule.kind == "reclaim":
                ev.reclaimed = faults.reclaim_targets(
                    rule, [n.name for n in self.nodes], inj.seed, inj.calls("cloud")
                )
                gone = set(ev.reclaimed)
                self.nodes = [n for n in self.nodes if n.name not in gone]
        self.events.append(ev)
        return ev


def run_churn(
    solver,
    process: ChurnProcess,
    instance_types,
    templates,
    cycles: int,
    validate: bool = False,
) -> List[Dict[str, object]]:
    """Drive ``solver`` through ``cycles`` churn steps. Returns one record per
    cycle: pod count, wall seconds, and — when the solver is a StreamingSolver
    (or wraps its telemetry surface) — the streaming outcome and reuse ratio.
    ``validate=True`` runs the full-level invariant gate on every cycle's
    result and records the violation count (the chaos sweep's survival bar)."""
    records: List[Dict[str, object]] = []
    for _ in range(cycles):
        ev = process.step()
        start = time.perf_counter()
        result = solver.solve(
            process.pods, instance_types, templates, nodes=process.nodes
        )
        seconds = time.perf_counter() - start
        rec: Dict[str, object] = {
            "cycle": ev.cycle,
            "pods": len(process.pods),
            "nodes": len(process.nodes),
            "arrived": len(ev.arrived),
            "deleted": len(ev.deleted),
            "reclaimed": len(ev.reclaimed),
            "scheduled": result.num_scheduled(),
            "failures": len(result.failures),
            "seconds": seconds,
        }
        # streaming telemetry: the solver itself, or (for SupervisedSolver)
        # the wrapped primary
        src = solver
        if getattr(src, "last_outcome", None) is None:
            src = getattr(solver, "primary", solver)
        outcome = getattr(src, "last_outcome", None)
        if outcome is not None:
            rec["outcome"] = outcome
            rec["reuse_ratio"] = getattr(src, "last_reuse_ratio", 0.0)
        if validate:
            from karpenter_tpu.solver import validator as val

            violations = val.validate_result(
                result,
                list(process.pods),
                instance_types,
                templates,
                nodes=process.nodes,
                level="full",
            )
            rec["violations"] = len(violations)
        records.append(rec)
    return records
