"""Streaming solve: incremental delta encode + warm-state re-solve under churn.

Production traffic is a stream — pod arrivals, deletes, spot reclaims,
rolling deploys — while the batch solver re-encodes and re-places the whole
world each cycle. This package turns it into a continuous one:

  delta.py   snapshot digests + diff, and a DeltaEncoder that patches rows of
             the previous SchedulingProblem in place (the class-keyed encoder
             makes pod/node deltas row patches) instead of a full rebuild —
             bit-identical to a cold encode or it falls back to one.
  warm.py    StreamingSolver: reuses the previous placement as the starting
             claim landscape and re-places only pods whose gates could have
             changed, falling back to a full solve past a delta-fraction
             threshold or on a validator rejection.
  snapshot.py  crash-consistent journal of the accepted cycle state
             (atomic framed writes via utils/persist.py, classified restore
             outcomes, full validator gate) so a restarted process re-enters
             the warm path on its first solve.
  churn.py   seeded arrival/delete/reclaim load generator driving
             testing/faults.py's ``cloud.reclaim`` grammar, with a
             sustained pods/s-under-churn harness shared by bench.py,
             tools/chaos_sweep.py, and the parity fuzz.

docs/SERVING.md documents the warm-state contract (resolved / reused /
certified buckets) and the knobs.
"""

from karpenter_tpu.streaming.delta import DeltaEncoder, SnapshotDelta, diff_snapshots
from karpenter_tpu.streaming.warm import StreamingSolver

__all__ = [
    "DeltaEncoder",
    "SnapshotDelta",
    "diff_snapshots",
    "StreamingSolver",
]
