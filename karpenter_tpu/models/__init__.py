from karpenter_tpu.models.problem import (  # noqa: F401
    ReqTensor,
    SchedulingProblem,
    GT_NONE,
    LT_NONE,
)
