"""The tensorized scheduling problem — struct-of-arrays over a closed vocabulary.

This is the data model the TPU solver operates on. The reference walks pointer
graphs (pods -> Requirements maps -> string sets,
pkg/controllers/provisioning/scheduling/nodeclaim.go:225-260); here the same
information is a fixed-shape bundle of arrays so that requirement intersection,
fit checks and offering checks become vectorized boolean kernels over

  P  pods            K  label keys        R  resource names
  T  instance types  V  value lanes       O  offerings per type
  N  existing nodes  TPL nodepool templates

Closed-world requirement encoding (ground truth: the host-side algebra in
scheduling/requirements.py, itself mirroring reference
pkg/scheduling/requirement.go):

Every label value mentioned anywhere in a batch (pod selectors/affinities,
instance-type requirements, node labels, offerings) is interned into a per-key
vocabulary of <= V lanes. A Requirement for key k becomes:

  admitted[k, v]  bool   vocab lane v satisfies Requirement.Has(value_v)
                         (integer Gt/Lt bounds already folded in)
  comp[k]         bool   complement set: admits values OUTSIDE the vocab too
                         (NotIn / Exists / Gt / Lt)
  gt[k], lt[k]    int32  integer bounds with +-inf sentinels
  defined[k]      bool   key present in the Requirements map

Undefined keys encode as full-admit complements (admitted=lane_valid,
comp=True, no bounds), which makes them identities under intersection — so
intersection of two requirement rows is uniformly:

  admitted' = admitted_a & admitted_b          comp' = comp_a & comp_b
  gt' = max(gt_a, gt_b)   lt' = min(lt_a, lt_b)   defined' = def_a | def_b

and the reference's ``Intersection(...).Len() != 0`` nonempty test becomes

  nonempty = any(admitted') | (comp' & (gt' < lt'))

which is exact over the closed world: admitted lanes each satisfy both sides'
bounds by construction, and a complement result is nonempty in the reference
unless its bounds collapsed (requirement.go:135-137; Len() deliberately
ignores bounds for complements, requirement.go:210-215).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List

import jax
import numpy as np

GT_NONE = np.int32(-(2**31) + 1)
LT_NONE = np.int32(2**31 - 1)

# Vocab key indices the encoder pins (solver/encode.py seeds these first, in
# this order, and asserts it; the device kernels index them statically).
ZONE_KEY = 0
CT_KEY = 1
HOSTNAME_KEY = 2

# run commit modes (SchedulingProblem.run_mode)
RUN_SINGLE = 0  # per-pod step, one pod per scan step
RUN_ANALYTIC = 1  # closed-form multi-pod commit (no topology interaction)
RUN_TOPO = 2  # light per-pod inner loop (topology-interacting identical pods)


@jax.tree_util.register_dataclass
@dataclass
class ReqTensor:
    """Requirement state for a batch of entities: arrays shaped [..., K, V] /
    [..., K]. The leading axes are entity axes (or absent for a single row)."""

    admitted: Any  # bool[..., K, V]
    comp: Any  # bool[..., K]
    gt: Any  # int32[..., K]
    lt: Any  # int32[..., K]
    defined: Any  # bool[..., K]

    @property
    def shape(self):
        return self.admitted.shape

    def row(self, idx) -> "ReqTensor":
        return ReqTensor(
            admitted=self.admitted[idx],
            comp=self.comp[idx],
            gt=self.gt[idx],
            lt=self.lt[idx],
            defined=self.defined[idx],
        )


@jax.tree_util.register_dataclass
@dataclass
class SchedulingProblem:
    """One batch of the provisioning problem, fully tensorized.

    Static (per-batch constant) arrays describing the vocabulary:
      lane_valid   bool[K, V]    lane is a real vocab value for this key
      lane_numeric f32[K, V]     integer value of the lane (NaN if non-numeric)
      key_wellknown bool[K]      key is a well-known label (Compatible allowance)

    Pods (sorted by the FFD queue order before encoding):
      pod_reqs     ReqTensor[P]  NewPodRequirements (preferences folded in)
      pod_requests f32[P, R]     effective resource requests (incl pods=1)
      pod_tol_tpl  bool[P, TPL]  pod tolerates template taints
      pod_tol_node bool[P, N]    pod tolerates existing-node taints
      pod_ports    bool[P, PT]   host-port lanes the pod reserves
      pod_port_conflict bool[P, PT]  lanes that CONFLICT with the pod's ports
                   (precomputed via HostPort.matches incl. 0.0.0.0 wildcards)
      pod_strict_reqs ReqTensor[P]  strict requirements (preferences excluded)
                   — the podDomains side of topology evaluation

    Topology groups (regular spread/affinity/anti-affinity groups first, then
    inverse anti-affinity groups; see provisioning/topology.py):
      grp_type     i32[G]        0 spread / 1 affinity / 2 anti-affinity
      grp_key      i32[G]        vocab key index the group spreads over
      grp_max_skew i32[G]
      grp_min_domains i32[G]     -1 when unset
      grp_counts0  i32[G, V]     seeded domain counts (cluster census)
      grp_registered0 bool[G, V] known domain lanes
      grp_inverse  bool[G]       inverse anti-affinity group
      grp_has_filter bool[G]     spread node-filter present
      grp_filter   ReqTensor[G, F]  node-filter OR terms
      grp_filter_valid bool[G, F]
      pod_grp_match bool[P, G]   group participates in this pod's placement
                   (owned for regular; selects-victim for inverse)
      pod_grp_selects bool[P, G] group's selector selects the pod (Record)
      pod_grp_owned bool[P, G]   pod owns the group (inverse Record)
      claim_hostname_lane i32[C] hostname vocab lane minted per claim slot

    Instance types:
      it_reqs      ReqTensor[T]
      it_alloc     f32[T, R]     allocatable = capacity - overhead
      it_cap       f32[T, R]     raw capacity (nodepool limits accounting)
      offer_zone / offer_ct int32[T, O]  lanes into the zone / capacity-type keys
      offer_ok     bool[T, O]    offering exists and is available
      offer_price  f32[T, O]

    Templates (one per NodePool, pre-sorted by weight):
      tpl_reqs     ReqTensor[TPL]
      tpl_overhead f32[TPL, R]   daemonset overhead requests
      tpl_it_ok    bool[TPL, T]  instance types offered by this template's pool
      tpl_remaining f32[TPL, R]  NodePool limits headroom (+inf = unlimited);
                   the scan subtracts the pessimistic max instance capacity on
                   every claim open (scheduler.go:347-364)

    Existing nodes (pre-sorted: initialized first, then name):
      node_reqs    ReqTensor[N]  label requirements (+hostname)
      node_avail   f32[N, R]     allocatable - current pod requests
      node_overhead f32[N, R]    unscheduled daemonset overhead
      node_used_ports bool[N, PT] host-port lanes already reserved on the node
    """

    # vocab statics
    lane_valid: Any
    lane_numeric: Any
    lane_lex_rank: Any  # i32[K, V] rank of the lane's value in sorted order —
    #   topology tie-breaks use it so device picks match the oracle's
    #   lexicographic rule regardless of lane interning order
    key_wellknown: Any
    # pods
    pod_reqs: ReqTensor
    pod_requests: Any
    pod_tol_tpl: Any
    pod_tol_node: Any
    pod_ports: Any
    pod_port_conflict: Any
    pod_strict_reqs: ReqTensor
    # instance types
    it_reqs: ReqTensor
    it_alloc: Any
    it_cap: Any
    offer_zone: Any
    offer_ct: Any
    offer_ok: Any
    offer_price: Any
    # templates
    tpl_reqs: ReqTensor
    tpl_overhead: Any
    tpl_it_ok: Any
    tpl_remaining: Any
    # existing nodes
    node_reqs: ReqTensor
    node_avail: Any
    node_overhead: Any
    node_used_ports: Any
    # CSI attach limits (volumeusage.go); D = drivers with a limit on some
    # node. Count-based (per-pod) semantics — conservative vs the host-side
    # unique-volume sets (see scheduling/volumeusage.py docstring)
    pod_vol_counts: Any  # i32[P, D]
    node_vol_used: Any  # i32[N, D]
    node_vol_limits: Any  # i32[N, D]  (huge when unlimited)
    # topology
    grp_type: Any
    grp_key: Any
    grp_max_skew: Any
    grp_min_domains: Any
    grp_counts0: Any
    grp_registered0: Any
    grp_inverse: Any
    grp_has_filter: Any
    grp_filter: ReqTensor
    grp_filter_valid: Any
    pod_grp_match: Any
    pod_grp_selects: Any
    pod_grp_owned: Any
    claim_hostname_lane: Any
    # run-length compression of the FFD queue (ops/ffd.py runs solver):
    # consecutive queue rows with byte-identical encodings and no topology
    # interaction form one run committed in a single scan step. pod_active
    # masks rows out of a solve without changing the run structure (the
    # batched consolidation screen flips it per candidate subset).
    pod_active: Any = None  # bool[P]
    run_start: Any = None  # i32[RN] first queue row of each run
    run_len: Any = None  # i32[RN] rows in the run (0 = padding run)
    # RUN_SINGLE per-pod step / RUN_ANALYTIC closed-form commit /
    # RUN_TOPO light per-pod inner loop over topology counters
    run_mode: Any = None  # i32[RN]
    # dense (zone-lane x ct-lane) availability bool[T, Zb, Cb] — the
    # MXU-matmul form of has_offering (masks.has_offering_zc); None when a
    # sub-vocabulary exceeds the 32-lane window (fallback: lane gathers)
    offer_zc: Any = None
    # bool[P] queue row is byte-identical to the previous row (the run
    # segmentation's same_as_prev) — the stride commit's identical-pod
    # verdict-batching test
    pod_eqprev: Any = None
    # bool[P] row equals the previous row on every GATE-relevant array and
    # both rows are topology-blind (no matched/owned groups; labels and
    # select-sides may differ) — the stride's analytic-chain test
    pod_eqprev_gate: Any = None
    # bool[P] CHAIN-identity with the previous row: equal on everything that
    # can influence the pod's own placement verdict — strict/effective reqs,
    # requests, tolerations, ports, volumes, grp_match, grp_owned, and
    # match∩selects (the only slice of the select side any gate reads) —
    # while the full select side may differ (own labels). The stride's
    # spread/affinity chain commits batch over these runs; records are
    # summed per member (weighted record), so differing selects stay exact.
    pod_eqprev_chain: Any = None

    @property
    def num_runs(self) -> int:
        return self.run_start.shape[0]

    @property
    def num_groups(self) -> int:
        return self.grp_type.shape[0]

    @property
    def num_pods(self) -> int:
        return self.pod_requests.shape[0]

    @property
    def num_instance_types(self) -> int:
        return self.it_alloc.shape[0]

    @property
    def num_templates(self) -> int:
        return self.tpl_overhead.shape[0]

    @property
    def num_nodes(self) -> int:
        return self.node_avail.shape[0]

    @property
    def num_keys(self) -> int:
        return self.lane_valid.shape[0]

    @property
    def num_lanes(self) -> int:
        return self.lane_valid.shape[1]

    @property
    def num_resources(self) -> int:
        return self.pod_requests.shape[1]


@dataclass
class ProblemMeta:
    """Host-side companions to a SchedulingProblem: the dictionaries needed to
    decode solver output back into API objects. Not a pytree — never crosses
    into jit."""

    keys: List[str] = field(default_factory=list)
    values_per_key: List[List[str]] = field(default_factory=list)
    resource_names: List[str] = field(default_factory=list)
    pod_order: List[int] = field(default_factory=list)  # problem row -> input pod index
    template_names: List[str] = field(default_factory=list)
    instance_type_names: List[str] = field(default_factory=list)
    node_names: List[str] = field(default_factory=list)
    zone_key_idx: int = -1
    ct_key_idx: int = -1
    hostname_key_idx: int = -1
