"""Per-tenant state: the isolated solver stack and its stream accounting.

One ``TenantState`` per registered stream. Everything that can fail, carry
state, or be quarantined is tenant-private (the solver stack); everything
shared (compiled executables, the device, the dispatcher thread) is
stateless with respect to tenants — that split is the isolation contract
the chaos suite verifies.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Deque, Dict, Optional

from karpenter_tpu.solver.backend import SolverBackend

# enough samples for a stable p99 over a churn stream's recent window
# without unbounded growth in a long-lived process
_LATENCY_WINDOW = 512


def build_tenant_solver(
    tenant_id: str,
    primary: Optional[SolverBackend] = None,
    fallback: Optional[SolverBackend] = None,
    **supervisor_kwargs,
) -> SolverBackend:
    """The default per-tenant stack: a SupervisedSolver owning this tenant's
    circuit breaker, quarantine namespace, journal namespace, and fault
    scope. ``primary`` defaults to a fresh JaxSolver — per-tenant instances
    share the process-global jit cache, so N tenants pay each program's
    compile once, not N times."""
    from karpenter_tpu.solver.supervisor import SupervisedSolver

    if primary is None:
        from karpenter_tpu.solver.jax_backend import JaxSolver

        primary = JaxSolver()
    return SupervisedSolver(
        primary, fallback=fallback, tenant=tenant_id, **supervisor_kwargs
    )


class TenantState:
    """One tenant stream: its solver stack, bounded queue, DWRR balance, and
    counters. The queue and counters are guarded by the service lock (the
    dispatcher and submitters share it); the solver is touched only by the
    dispatcher thread."""

    def __init__(
        self,
        tenant_id: str,
        solver: SolverBackend,
        weight: float = 1.0,
        deadline_s: float = 0.0,
        queue_depth: int = 8,
        cls: str = "default",
    ):
        self.id = tenant_id
        self.solver = solver
        self.weight = max(0.001, float(weight))
        # default wall-clock budget a request inherits when submitted
        # without an explicit deadline; 0 = no budget
        self.deadline_s = float(deadline_s)
        self.queue_depth = int(queue_depth)
        self.cls = cls
        self.queue: Deque = deque()
        self.deficit = 0.0
        # ready == this stream sits in its class's ready-ring (nonempty
        # queue). Idle streams are NOT swept by the dispatcher at all — that
        # is the O(active) contract at 1k registered tenants.
        self.ready = False
        self.counters: Dict[str, int] = {
            "submitted": 0,
            "completed": 0,
            "shed": 0,
            "errors": 0,
            "batched": 0,
        }
        self._latencies: Deque[float] = deque(maxlen=_LATENCY_WINDOW)
        self._lat_lock = threading.Lock()

    def record_latency(self, seconds: float) -> None:
        with self._lat_lock:
            self._latencies.append(seconds)

    def latency_quantile(self, q: float) -> float:
        """Windowed latency quantile (q in [0, 1]); 0.0 before any sample."""
        with self._lat_lock:
            samples = sorted(self._latencies)
        if not samples:
            return 0.0
        idx = min(len(samples) - 1, max(0, int(q * len(samples))))
        return samples[idx]

    def circuit_state(self) -> Optional[str]:
        fn = getattr(self.solver, "circuit_state", None)
        return fn() if fn is not None else None

    def snapshot(self) -> Dict:
        """The /debug/tenants row: queue pressure, fairness balance, outcome
        counters, latency quantiles, and the solver's own health."""
        out = {
            "tenant": self.id,
            "class": self.cls,
            "weight": self.weight,
            "deadline_s": self.deadline_s,
            "queued": len(self.queue),
            "queue_depth": self.queue_depth,
            "deficit": round(self.deficit, 3),
            "counters": dict(self.counters),
            "latency_p50_s": round(self.latency_quantile(0.50), 6),
            "latency_p99_s": round(self.latency_quantile(0.99), 6),
        }
        circuit = self.circuit_state()
        if circuit is not None:
            out["circuit"] = circuit
        status = getattr(self.solver, "status", None)
        if status is not None:
            try:
                out["last_failure"] = status().get("last_failure")
            except Exception:  # noqa: BLE001 — introspection must not break the page
                pass
        return out
