"""Cross-tenant opportunistic batching: stack compatible solves on device.

``parallel/mesh.batched_screen`` already compiles a vmapped multi-pass FFD
over a [B] candidate axis for the consolidation screen. The serve dispatcher
reuses that exact program across TENANTS: when several streams have cold,
generic, shape-compatible requests queued at the same instant, they stack
into one device dispatch and amortize the launch + transfer overhead B ways.

Strictly opportunistic, never load-bearing:

  * only structurally simple requests qualify (``batchable``): no existing
    nodes, no overrides/volumes/topology arguments, nothing relaxable, a
    cold warm-state stream, a closed circuit, and a real JaxSolver at the
    bottom of the tenant's stack. Everything else takes the tenant's own
    supervised solve untouched.
  * every decoded lane passes the FULL-level validator gate before it is
    returned; a violation (or any shape mismatch, slot overflow, or
    exception anywhere in the stacked path) silently stands that lane down
    to the solo path — ``serve_batch_total{result="fallback"}``.
  * batched results never seed streaming state; the tenant stays cold, so
    a later warm cycle diffs against nothing this path produced.
  * fault injection disables stacking wholesale (``faults.active()``): the
    chaos suite's per-tenant blast-radius proof must see one stream per
    solve site.

The decode mirrors solver/jax_backend.py's final decode (rows via
``meta.pod_order``, claims via the carried slot tensors and
``decode_claim_requirements``) for the restricted no-nodes case.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import numpy as np

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.provisioning.preferences import Preferences
from karpenter_tpu.provisioning.topology import Topology
from karpenter_tpu.solver import validator as val
from karpenter_tpu.solver.backend import (
    FAIL_INCOMPATIBLE,
    Placement,
    SolveResult,
)
from karpenter_tpu.solver.encode import Encoder, domains_from_instance_types
from karpenter_tpu.ops.ffd import KIND_CLAIM, KIND_NEW_CLAIM, KIND_NODE, KIND_NO_SLOT
from karpenter_tpu.ops.padding import claim_axis_bucket, pad_problem
from karpenter_tpu.testing import faults

# generic-kwargs contract a batchable request must satisfy: anything beyond
# these defaults (pinned nodes, overrides, explicit topology, volumes) keeps
# the request on the tenant's own solve path
_GENERIC_KWARGS = {
    "nodes": (),
    "pod_requirements_override": None,
    "topology": None,
    "cluster_pods": (),
    "domains": None,
    "pod_volumes": None,
}


def _unwrap_inner(solver):
    """Walk SupervisedSolver.primary -> StreamingSolver.inner to the backend
    that would actually run, plus the streaming layer if present."""
    streaming = None
    seen = set()
    while id(solver) not in seen:
        seen.add(id(solver))
        if hasattr(solver, "primary"):
            solver = solver.primary
            continue
        if hasattr(solver, "inner") and hasattr(solver, "reset_streaming_state"):
            streaming = solver
            solver = solver.inner
            continue
        break
    return solver, streaming


def batchable(request, solver) -> bool:
    """Can this request ride a cross-tenant stacked dispatch? Conservative by
    design: a False here costs one solo solve, a wrong True could cost
    correctness."""
    if faults.active() is not None:
        return False
    if not request.pods:
        return False
    for key, default in _GENERIC_KWARGS.items():
        value = request.kwargs.get(key, default)
        if key in ("nodes", "cluster_pods"):
            if len(value or ()) != 0:
                return False
        elif value is not None:
            return False
    # anything relaxable needs the per-pass host relax loop (mirrors the
    # backend's use_sweeps condition)
    if any(
        t.effect == "PreferNoSchedule"
        for tpl in request.templates
        for t in tpl.taints
    ):
        return False
    if any(Preferences.is_relaxable(p) for p in request.pods):
        return False
    circuit = getattr(solver, "circuit_state", None)
    if circuit is not None and circuit() != "closed":
        return False
    inner, streaming = _unwrap_inner(solver)
    if streaming is not None and streaming._prev is not None:
        # a warm stream's next answer depends on carried state; only cold
        # streams can take the stateless stacked path
        return False
    from karpenter_tpu.solver.jax_backend import JaxSolver

    return isinstance(inner, JaxSolver)


def _shape_key(problem) -> tuple:
    return tuple(
        (tuple(leaf.shape), str(getattr(leaf, "dtype", type(leaf).__name__)))
        for leaf in jax.tree_util.tree_leaves(problem)
    )


def _decode_lane(
    pods, instance_types, templates, meta, max_claims,
    kinds, indices,
    claim_open, claim_tpl, claim_it_ok, claim_requests,
    claim_adm, claim_comp, claim_gt, claim_lt, claim_def,
) -> Optional[SolveResult]:
    """One lane of the stacked result back into the host model — the
    jax_backend final decode restricted to the no-existing-nodes case.
    Returns None (fall back to solo) on slot overflow."""
    from karpenter_tpu.solver.jax_backend import decode_claim_requirements

    n_real = len(meta.pod_order)
    if (np.asarray(kinds[:n_real]) == KIND_NO_SLOT).any():
        return None
    out = SolveResult()
    slot_to_claim = {}
    for slot in range(max_claims):
        if slot < len(claim_open) and claim_open[slot]:
            tpl_idx = int(claim_tpl[slot])
            placement = Placement(
                template_index=tpl_idx,
                nodepool_name=meta.template_names[tpl_idx],
                instance_type_indices=[
                    int(t)
                    for t in np.flatnonzero(claim_it_ok[slot])
                    if t < len(meta.instance_type_names)
                ],
                requirements=decode_claim_requirements(
                    meta, claim_adm[slot], claim_comp[slot],
                    claim_gt[slot], claim_lt[slot], claim_def[slot],
                ),
                requests={
                    name: float(claim_requests[slot, ri])
                    for ri, name in enumerate(meta.resource_names)
                    if claim_requests[slot, ri] > 0
                },
            )
            slot_to_claim[slot] = placement
            out.new_claims.append(placement)
    failed = []
    for row in range(n_real):
        orig = meta.pod_order[row]
        kind, index = int(kinds[row]), int(indices[row])
        if kind == KIND_NODE:
            out.node_pods.setdefault(meta.node_names[index], []).append(orig)
        elif kind in (KIND_CLAIM, KIND_NEW_CLAIM) and index in slot_to_claim:
            slot_to_claim[index].pod_indices.append(orig)
        else:
            failed.append(orig)
    if failed:
        from karpenter_tpu.solver.forensics import failure_reason

        for orig in failed:
            out.failures[orig] = failure_reason(
                pods[orig], instance_types, templates,
                well_known=wk.WELL_KNOWN_LABELS,
            ) or FAIL_INCOMPATIBLE
    # claims no pod landed in would launch empty capacity — stand down
    # instead (the solo path never produces them)
    if any(not c.pod_indices for c in out.new_claims):
        return None
    return out


def stacked_solve(group: Sequence, mesh="auto") -> List[Optional[SolveResult]]:
    """Solve a group of batchable requests in one ``batched_screen``
    dispatch. Returns one entry per request: a validator-clean SolveResult,
    or None where the stacked path stood down (that request then runs its
    tenant's ordinary solo solve). Never raises — any failure in here is a
    fallback, not an outage.

    ``mesh`` selects the device slice the stacked dispatch runs on: a Mesh
    (a serve replica's carved slice from parallel/mesh.carve_meshes), None
    for a single-device vmap, or the default ``"auto"`` which resolves to
    parallel/mesh.default_mesh() at dispatch time."""
    results: List[Optional[SolveResult]] = [None] * len(group)
    if len(group) < 2:
        return results
    try:
        from karpenter_tpu.parallel.mesh import (
            batched_screen,
            default_mesh,
            stack_problems,
        )

        shared_claims = max(
            claim_axis_bucket(len(r.pods)) for r in group
        )
        encoded = []
        for r in group:
            domains = domains_from_instance_types(r.instance_types, r.templates)
            topo = Topology(domains, batch_pods=list(r.pods), cluster_pods=())
            enc = Encoder(wk.WELL_KNOWN_LABELS).encode(
                list(r.pods), r.instance_types, r.templates, (),
                topology=topo, num_claim_slots=shared_claims,
                vocab_pods=list(r.pods),
            )
            encoded.append((pad_problem(enc.problem), enc.meta))
        key0 = _shape_key(encoded[0][0])
        lanes = [
            i for i in range(len(group)) if _shape_key(encoded[i][0]) == key0
        ]
        if len(lanes) < 2:
            return results
        batch = stack_problems([encoded[i][0] for i in lanes])
        # the SAME mesh-sharded screen dispatch the consolidation scorer uses
        # (parallel/mesh.py batched_screen with lane-axis padding): one
        # program per shape family in the census, and on multi-device hosts
        # the tenant lanes actually distribute instead of vmapping on one
        # device. A replica passes its own carved slice here so fleets
        # partition the host instead of contending for all of it.
        if isinstance(mesh, str):
            mesh = default_mesh()
        fr = batched_screen(batch, shared_claims, mesh=mesh)
        state = fr.state
        fetched = jax.device_get((
            fr.kind, fr.index,
            state.claim_open, state.claim_tpl, state.claim_it_ok,
            state.claim_requests, state.claim_req.admitted,
            state.claim_req.comp, state.claim_req.gt,
            state.claim_req.lt, state.claim_req.defined,
        ))
        (kinds, indices, claim_open, claim_tpl, claim_it_ok,
         claim_requests, claim_adm, claim_comp, claim_gt, claim_lt,
         claim_def) = fetched
        for li, i in enumerate(lanes):
            r = group[i]
            try:
                decoded = _decode_lane(
                    list(r.pods), r.instance_types, r.templates,
                    encoded[i][1], shared_claims,
                    kinds[li], indices[li],
                    claim_open[li], claim_tpl[li], claim_it_ok[li],
                    claim_requests[li], claim_adm[li], claim_comp[li],
                    claim_gt[li], claim_lt[li], claim_def[li],
                )
                if decoded is None:
                    continue
                violations = val.validate_result(
                    decoded, list(r.pods), r.instance_types, r.templates,
                    nodes=(), level="full",
                )
                if violations:
                    continue
                results[i] = decoded
            except Exception:  # noqa: BLE001 — one bad lane must not sink the rest
                continue
        return results
    except Exception:  # noqa: BLE001 — the stacked path degrades, never breaks
        return [None] * len(group)
