"""Queue-wait estimation for admission: a time-decayed EWMA of per-request
service time.

The admission gate sheds a request at the door when ``backlog x estimate``
exceeds its wait bound (docs/SERVING.md). Two properties the raw per-request
EWMA the dispatcher used to carry did not have:

  time decay     the old estimate was updated only when a solve COMPLETED, so
                 across an idle gap it froze at whatever the last busy period
                 measured. The first requests of the next burst were then shed
                 against a stale number (a warm cache and an idle device serve
                 the new burst much faster than the saturated tail of the old
                 one). Here the estimate decays toward zero with wall-clock
                 age: ``estimate(t) = ewma x max(floor, 0.5^(age/half_life))``.
  staleness floor the decay never goes below ``floor`` x the learned value: a
                 service that was genuinely slow does not forget that entirely
                 just because nobody asked for a minute — the first burst
                 request still meets SOME skepticism, the hundredth meets a
                 fresh estimate again.

Fed with per-request SERVICE time (dispatch wall amortized over the stacked
group), not queue-inclusive latency: predicted wait is ``backlog x per-request
service``; feeding queue-inclusive latency double-counts the queue and makes
admission collapse under exactly the sustained load it exists to manage.

Knobs (read by the dispatcher at construction, docs/SERVING.md):

  KARPENTER_TPU_SERVE_EWMA_HALF_LIFE_S  decay half-life, seconds (5)
  KARPENTER_TPU_SERVE_EWMA_FLOOR        staleness floor fraction (0.25)
"""

from __future__ import annotations

import threading
import time
from typing import Optional

# heavily weighted to history so one fast warm solve doesn't swing the
# admission gate open mid-overload (same alpha the dispatcher always used)
DEFAULT_ALPHA = 0.2


class WaitEstimator:
    """Thread-safe: the dispatcher observes, submitter threads read."""

    def __init__(
        self,
        alpha: float = DEFAULT_ALPHA,
        half_life_s: float = 5.0,
        floor: float = 0.25,
        time_fn=time.monotonic,
    ):
        self.alpha = float(alpha)
        self.half_life_s = max(1e-3, float(half_life_s))
        self.floor = min(1.0, max(0.0, float(floor)))
        self._time = time_fn
        self._lock = threading.Lock()
        self._ewma = 0.0
        self._observed_at: Optional[float] = None
        self.observations = 0

    def observe(self, service_s: float, now: Optional[float] = None) -> None:
        """Fold one completed request's per-request service time in."""
        if service_s < 0:
            return
        now = self._time() if now is None else now
        with self._lock:
            self._ewma = (
                service_s
                if self._ewma == 0
                else (1 - self.alpha) * self._ewma + self.alpha * service_s
            )
            self._observed_at = now
            self.observations += 1

    def seed(self, service_s: float, now: Optional[float] = None) -> None:
        """Pessimistically pre-load the estimate (replica failover: a
        survivor absorbing a dead replica's tenants should meet the surge
        with backpressure BEFORE the first migrated solve completes). Only
        raises the estimate — a survivor that already learned it is slower
        keeps its own number."""
        if service_s <= 0:
            return
        now = self._time() if now is None else now
        with self._lock:
            if service_s > self._ewma:
                self._ewma = float(service_s)
                self._observed_at = now

    def per_request_s(self, now: Optional[float] = None) -> float:
        """The decayed per-request service estimate; 0.0 before any sample
        (no estimate means no predicted-wait shedding — admission falls back
        to the queue-depth bound alone)."""
        now = self._time() if now is None else now
        with self._lock:
            if self._ewma == 0 or self._observed_at is None:
                return 0.0
            age = max(0.0, now - self._observed_at)
            decay = max(self.floor, 0.5 ** (age / self.half_life_s))
            return self._ewma * decay

    def predicted_wait_s(self, backlog: int, now: Optional[float] = None) -> float:
        return max(0, int(backlog)) * self.per_request_s(now)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "ewma_s": round(self._ewma, 6),
                "observations": self.observations,
                "half_life_s": self.half_life_s,
                "floor": self.floor,
            }
