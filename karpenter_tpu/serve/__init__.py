"""Multi-tenant solve service: N tenant streams over one solver process.

The provisioning stack below this package is single-stream: one
SupervisedSolver owns one circuit breaker, one StreamingSolver carries one
warm state, one quarantine ring collects one stream's rejected results. A
real control plane multiplexes many independent clusters (tenants) onto one
warmed-up solver process — compiled executables and the device are shared,
everything stateful must not be. This package adds that layer:

  isolation   every tenant gets its OWN SupervisedSolver stack (circuit
              breaker, warm streaming state, quarantine namespace, journal
              namespace, deadline budget) built through the tenant plumbing
              each of those layers grew: ``SupervisedSolver(tenant=...)``,
              ``forensics.dump_quarantine(tenant=...)``,
              ``snapshot.journal_path(namespace=...)``,
              ``faults.tenant_scope``. A fault in one tenant's stream trips
              that tenant's circuit and quarantines into that tenant's ring;
              the chaos suite (tools/chaos_sweep.py tenant-isolation row)
              proves the blast radius stops there.
  fairness    a single dispatcher thread drains the per-tenant bounded
              queues by deficit-weighted round robin: each sweep a nonempty
              queue earns ``weight x quantum`` pod-units of deficit and the
              first stream whose head fits its balance runs. A heavy tenant
              cannot starve a light one; an idle tenant cannot hoard credit.
  admission   every request the service cannot serve is CLASSIFIED, never
              silently dropped: queue-full, predicted-wait, and expired
              requests resolve as ``overloaded`` outcomes; capacity and
              shutdown rejections as ``rejected`` (serve_admission_total).
  batching    shape-compatible cold generic requests from different tenants
              are opportunistically stacked into one ``batched_screen``
              device dispatch (serve/batch.py) — the candidate-axis
              machinery the consolidation screen already compiles, now
              amortizing across tenants. Every batched lane is full-gated by
              the validator; any doubt falls back to the tenant's own
              supervised solve.

Flag contract: the layer activates only through explicit construction or
``KARPENTER_TPU_SERVE=1``; with the flag unset nothing here is imported by
the single-tenant path and placements are bit-identical to the pre-serve
tree (the flag-off kernel census stays exactly 2,394 eqns).

At fleet scale (1,000+ registered streams) the flat layer grows a hierarchy
(docs/SERVING.md "Fleet scale"): tenant CLASSES above tenants with two-level
deficit accounting, ready-rings so idle streams cost zero dispatcher work,
shared per-shape program pools (serve/pool.py) keeping cross-tenant
co-batching hot at 1k tenants, and replica sets (serve/replica.py) each
owning a carved mesh slice. Hot-path metrics aggregate to the bounded
tenant-class label; per-tenant detail stays in /debug/tenants.

Knobs (all read at construction; see docs/SERVING.md):

  KARPENTER_TPU_SERVE                  enable the serve layer (operator wiring)
  KARPENTER_TPU_SERVE_MAX_TENANTS      tenant capacity bound (16)
  KARPENTER_TPU_SERVE_QUEUE_DEPTH      per-tenant queue bound (8)
  KARPENTER_TPU_SERVE_QUANTUM          DWRR pod-units earned per sweep (64)
  KARPENTER_TPU_SERVE_WEIGHTS          per-tenant weights, "a=4,b=1"
  KARPENTER_TPU_SERVE_CLASSES          tenant-class weights, "gold=4,bronze=1"
                                       (unset = one implicit "default" class:
                                       the flat, bit-identical 16-tenant path)
  KARPENTER_TPU_SERVE_ADMIT_DEADLINE_S predicted-wait shed bound (0 = off)
  KARPENTER_TPU_SERVE_BATCH            cross-tenant stacking (1)
  KARPENTER_TPU_SERVE_BATCH_LANES      max lanes per stacked dispatch (8)
  KARPENTER_TPU_SERVE_REPLICAS         serve replicas / mesh slices (1)
  KARPENTER_TPU_SERVE_BIG_PODS         big-tenant placement threshold (1024)
  KARPENTER_TPU_SERVE_EWMA_HALF_LIFE_S wait-estimate decay half-life (5)
  KARPENTER_TPU_SERVE_EWMA_FLOOR       wait-estimate staleness floor (0.25)
"""

from __future__ import annotations

import os
from typing import Dict, Optional


def enabled() -> bool:
    """The operator wires a SolveService only when this is set; the flag-off
    process never constructs the layer (zero overhead, identical programs)."""
    return os.environ.get("KARPENTER_TPU_SERVE", "") not in ("", "0")


def max_tenants() -> int:
    try:
        return max(1, int(os.environ.get("KARPENTER_TPU_SERVE_MAX_TENANTS", "16")))
    except ValueError:
        return 16


def queue_depth() -> int:
    try:
        return max(1, int(os.environ.get("KARPENTER_TPU_SERVE_QUEUE_DEPTH", "8")))
    except ValueError:
        return 8


def quantum() -> float:
    try:
        return max(1.0, float(os.environ.get("KARPENTER_TPU_SERVE_QUANTUM", "64")))
    except ValueError:
        return 64.0


def admit_deadline_s() -> float:
    try:
        return float(os.environ.get("KARPENTER_TPU_SERVE_ADMIT_DEADLINE_S", "0"))
    except ValueError:
        return 0.0


def batching_enabled() -> bool:
    return os.environ.get("KARPENTER_TPU_SERVE_BATCH", "1") not in ("", "0")


def batch_lanes() -> int:
    """Max lanes per stacked dispatch: wider stops amortizing and starts
    inflating the padded batch (one lane's latency holds every lane hostage)."""
    try:
        return max(2, int(os.environ.get("KARPENTER_TPU_SERVE_BATCH_LANES", "8")))
    except ValueError:
        return 8


def replicas() -> int:
    try:
        return max(1, int(os.environ.get("KARPENTER_TPU_SERVE_REPLICAS", "1")))
    except ValueError:
        return 1


def big_tenant_pods() -> int:
    """Expected-pods threshold above which a tenant is placed on the replica
    owning the largest mesh slice (the round-18 sharded path's home)."""
    try:
        return max(1, int(os.environ.get("KARPENTER_TPU_SERVE_BIG_PODS", "1024")))
    except ValueError:
        return 1024


def ewma_half_life_s() -> float:
    try:
        return max(
            1e-3,
            float(os.environ.get("KARPENTER_TPU_SERVE_EWMA_HALF_LIFE_S", "5")),
        )
    except ValueError:
        return 5.0


def ewma_floor() -> float:
    try:
        return min(1.0, max(
            0.0, float(os.environ.get("KARPENTER_TPU_SERVE_EWMA_FLOOR", "0.25"))
        ))
    except ValueError:
        return 0.25


def parse_weights(spec: Optional[str] = None) -> Dict[str, float]:
    """``KARPENTER_TPU_SERVE_WEIGHTS="a=4,b=1"`` -> {"a": 4.0, "b": 1.0}.
    Malformed entries are skipped (an operator typo must not take down the
    service); unlisted tenants default to weight 1."""
    if spec is None:
        spec = os.environ.get("KARPENTER_TPU_SERVE_WEIGHTS", "")
    out: Dict[str, float] = {}
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry or "=" not in entry:
            continue
        name, _, raw = entry.partition("=")
        try:
            weight = float(raw)
        except ValueError:
            continue
        if name.strip() and weight > 0:
            out[name.strip()] = weight
    return out


def parse_classes(spec: Optional[str] = None) -> Dict[str, float]:
    """``KARPENTER_TPU_SERVE_CLASSES="gold=4,bronze=1"`` -> class weights.
    Same grammar and tolerance as parse_weights. Empty/unset means ONE
    implicit ``default`` class — the dispatcher then skips class-level
    accounting entirely and the 16-tenant flat DWRR path is bit-identical."""
    if spec is None:
        spec = os.environ.get("KARPENTER_TPU_SERVE_CLASSES", "")
    return parse_weights(spec)


DEFAULT_CLASS = "default"


# The live service this process is running, if any — serving.py's
# /debug/tenants resolves through here when the OperatorStatus was not
# explicitly wired with one. Plain module global, set/cleared by
# SolveService.start()/close() (one serve layer per process is the model,
# matching the one-process-one-device assumption everywhere else).
_current = None


def current_service():
    return _current


def _set_current(service) -> None:
    global _current
    _current = service


from karpenter_tpu.serve.dispatcher import (  # noqa: E402  (re-export)
    ServeOutcome,
    SolveService,
    Ticket,
)
from karpenter_tpu.serve.tenant import TenantState, build_tenant_solver  # noqa: E402

__all__ = [
    "DEFAULT_CLASS",
    "ServeOutcome",
    "SolveService",
    "TenantState",
    "Ticket",
    "admit_deadline_s",
    "batch_lanes",
    "batching_enabled",
    "big_tenant_pods",
    "build_tenant_solver",
    "current_service",
    "enabled",
    "ewma_floor",
    "ewma_half_life_s",
    "max_tenants",
    "parse_classes",
    "parse_weights",
    "quantum",
    "queue_depth",
    "replicas",
]
