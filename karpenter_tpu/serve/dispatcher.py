"""The serve dispatcher: bounded queues, DWRR fairness, classified admission.

One dispatcher thread drains every tenant's queue — solves are serialized
onto the device exactly as the single-tenant operator serializes cycles, so
per-tenant solver state needs no locking and device contention is structural,
not emergent. Fairness and isolation live at the queue boundary:

  admission (``submit``, caller's thread)
      a request is either queued or resolved immediately with a CLASSIFIED
      outcome: ``overloaded-queue-full`` (its tenant's bounded queue is
      full), ``overloaded-predicted-wait`` (the queue-wait estimate already
      exceeds the admit/request deadline — shedding at the door beats
      timing out after burning device time), ``rejected-max-tenants``,
      ``rejected-shutdown``. serve_admission_total counts every decision.

  fairness (``_collect``, dispatcher thread)
      deficit-weighted round robin in pod-units: when no stream can afford
      its head request, every backlogged stream earns ``weight x quantum``;
      the rotation then serves each stream while its balance lasts. An
      emptied queue forfeits its balance (no hoarding credit while idle).

  execution (``_execute``)
      the request's wall-clock budget (explicit per-request deadline, else
      the tenant's default) is inherited by the solve: the tenant solver's
      watchdog deadline is narrowed to the REMAINING budget for the call.
      Already-expired requests resolve as ``overloaded-expired`` without
      touching the device. Cross-tenant batchable groups take one stacked
      device dispatch (serve/batch.py) with per-lane solo fallback.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from karpenter_tpu.metrics.registry import (
    SERVE_ADMISSION,
    SERVE_BATCH,
    SERVE_CYCLE_SECONDS,
    SERVE_CYCLES,
    SERVE_FAIRNESS_DEFICIT,
    SERVE_QUEUE_DEPTH,
)
from karpenter_tpu.solver.backend import SolveResult

# classified admission / completion outcome vocabulary (the bounded metric
# label-value sets; tools/metrics_lint.py checks the tenant axis separately)
STATUS_OK = "ok"
STATUS_OVERLOADED = "overloaded"
STATUS_REJECTED = "rejected"
STATUS_ERROR = "error"
STATUS_PENDING = "pending"

ADMIT_ACCEPTED = "accepted"
ADMIT_QUEUE_FULL = "overloaded-queue-full"
ADMIT_PREDICTED_WAIT = "overloaded-predicted-wait"
ADMIT_EXPIRED = "overloaded-expired"
ADMIT_MAX_TENANTS = "rejected-max-tenants"
ADMIT_SHUTDOWN = "rejected-shutdown"

# wait-estimate smoothing: heavily weighted to history so one fast warm
# solve doesn't swing the admission gate open mid-overload
_EWMA_ALPHA = 0.2

# a stacked dispatch wider than this stops amortizing and starts inflating
# the padded batch (and one lane's latency holds every lane hostage)
_MAX_BATCH_LANES = 8


@dataclass
class ServeOutcome:
    """What a submitted request resolved to. ``status`` is always one of the
    STATUS_* constants; an unserved request carries its admission class in
    ``reason`` — the caller can always tell shed from failed from served."""

    status: str
    tenant: str = ""
    reason: str = ""
    result: Optional[SolveResult] = None
    latency_s: float = 0.0
    path: str = ""  # "solo" | "batched" | "" (never solved)


class Ticket:
    """The caller's handle on a submitted request."""

    def __init__(self, tenant: str):
        self._tenant = tenant
        self._event = threading.Event()
        self._outcome: Optional[ServeOutcome] = None

    def resolve(self, outcome: ServeOutcome) -> None:
        self._outcome = outcome
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> ServeOutcome:
        """Block for the outcome; a timeout returns a non-final ``pending``
        outcome (the request is still queued or running)."""
        if self._event.wait(timeout):
            assert self._outcome is not None
            return self._outcome
        return ServeOutcome(status=STATUS_PENDING, tenant=self._tenant)


@dataclass
class _Request:
    tenant: str
    pods: Sequence
    instance_types: Sequence
    templates: Sequence
    kwargs: Dict
    deadline_s: float  # effective wall budget (0 = none)
    submitted_at: float
    ticket: Ticket
    cost: float = field(init=False)

    def __post_init__(self):
        # DWRR service cost in pod-units: fairness is about device time,
        # which scales with batch size, not request count
        self.cost = float(max(1, len(self.pods)))


class SolveService:
    """The multi-tenant solve service. Construct explicitly (tests, bench,
    chaos) or let the operator wire it under ``KARPENTER_TPU_SERVE=1``."""

    def __init__(
        self,
        solver_factory=None,
        max_tenants: Optional[int] = None,
        queue_depth: Optional[int] = None,
        quantum: Optional[float] = None,
        admit_deadline_s: Optional[float] = None,
        weights: Optional[Dict[str, float]] = None,
        batching: Optional[bool] = None,
        time_fn=time.monotonic,
    ):
        from karpenter_tpu import serve as cfg
        from karpenter_tpu.serve.tenant import build_tenant_solver

        self._solver_factory = solver_factory or build_tenant_solver
        self.max_tenants = max_tenants if max_tenants is not None else cfg.max_tenants()
        self.queue_depth = queue_depth if queue_depth is not None else cfg.queue_depth()
        self.quantum = quantum if quantum is not None else cfg.quantum()
        self.admit_deadline_s = (
            admit_deadline_s
            if admit_deadline_s is not None
            else cfg.admit_deadline_s()
        )
        self.weights = weights if weights is not None else cfg.parse_weights()
        self.batching = batching if batching is not None else cfg.batching_enabled()
        self._time = time_fn
        self._cond = threading.Condition()
        self._tenants: Dict[str, "TenantState"] = {}
        self._order: List[str] = []  # DWRR rotation
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self._ewma_solve_s = 0.0

    # -- tenant registry ------------------------------------------------------

    def register_tenant(
        self,
        tenant_id: str,
        weight: Optional[float] = None,
        deadline_s: float = 0.0,
        solver=None,
    ):
        """Create (or return) a tenant stream. Raises ValueError at the
        tenant capacity bound — ``submit`` classifies that as
        ``rejected-max-tenants`` instead of raising at the caller."""
        from karpenter_tpu.serve.tenant import TenantState

        with self._cond:
            existing = self._tenants.get(tenant_id)
            if existing is not None:
                return existing
            if len(self._tenants) >= self.max_tenants:
                raise ValueError(
                    f"tenant capacity {self.max_tenants} reached "
                    f"(KARPENTER_TPU_SERVE_MAX_TENANTS)"
                )
            state = TenantState(
                tenant_id,
                solver if solver is not None else self._solver_factory(tenant_id),
                weight=(
                    weight
                    if weight is not None
                    else self.weights.get(tenant_id, 1.0)
                ),
                deadline_s=deadline_s,
                queue_depth=self.queue_depth,
            )
            self._tenants[tenant_id] = state
            self._order.append(tenant_id)
            return state

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "SolveService":
        from karpenter_tpu import serve as cfg

        with self._cond:
            if self._closed:
                raise RuntimeError("SolveService is closed")
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop, daemon=True,
                    name="karpenter-tpu/serve-dispatcher",
                )
                self._thread.start()
        cfg._set_current(self)
        return self

    def close(self, timeout: float = 10.0) -> None:
        """Stop dispatching and resolve everything still queued as
        ``rejected-shutdown`` — shutdown shedding is classified like any
        other unserved outcome."""
        from karpenter_tpu import serve as cfg

        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
        drained: List[_Request] = []
        with self._cond:
            for state in self._tenants.values():
                while state.queue:
                    drained.append(state.queue.popleft())
                    state.counters["shed"] += 1
                SERVE_QUEUE_DEPTH.set(0, {"tenant": state.id})
        for req in drained:
            SERVE_ADMISSION.inc({"tenant": req.tenant, "outcome": ADMIT_SHUTDOWN})
            req.ticket.resolve(ServeOutcome(
                status=STATUS_REJECTED, tenant=req.tenant, reason=ADMIT_SHUTDOWN,
            ))
        if cfg.current_service() is self:
            cfg._set_current(None)

    def healthy(self) -> bool:
        """Readiness contribution: closed or a dead dispatcher thread means
        queued requests would wait forever."""
        with self._cond:
            if self._closed:
                return False
            return self._thread is None or self._thread.is_alive()

    # -- admission ------------------------------------------------------------

    def submit(
        self,
        tenant_id: str,
        pods: Sequence,
        instance_types: Sequence,
        templates: Sequence,
        deadline_s: Optional[float] = None,
        **kwargs,
    ) -> Ticket:
        """Admit one solve request. Always returns a Ticket; an unadmitted
        request's ticket is already resolved with its classification."""
        ticket = Ticket(tenant_id)

        def refuse(status: str, outcome: str, known_tenant: bool) -> Ticket:
            # the tenant label stays bounded: unregistered ids never mint a
            # series (rejected-max-tenants is exactly the unregistered case)
            label = tenant_id if known_tenant else "-"
            SERVE_ADMISSION.inc({"tenant": label, "outcome": outcome})
            ticket.resolve(ServeOutcome(
                status=status, tenant=tenant_id, reason=outcome,
            ))
            return ticket

        with self._cond:
            if self._closed:
                return refuse(
                    STATUS_REJECTED, ADMIT_SHUTDOWN,
                    tenant_id in self._tenants,
                )
            state = self._tenants.get(tenant_id)
            if state is None:
                try:
                    state = self.register_tenant(tenant_id)
                except ValueError:
                    return refuse(STATUS_REJECTED, ADMIT_MAX_TENANTS, False)
            effective_deadline = (
                deadline_s if deadline_s is not None else state.deadline_s
            ) or 0.0
            if len(state.queue) >= state.queue_depth:
                state.counters["shed"] += 1
                return refuse(STATUS_OVERLOADED, ADMIT_QUEUE_FULL, True)
            # predicted-wait shedding: with a wait bound configured (the
            # service-wide admit deadline and/or this request's own budget)
            # and a solve-time estimate in hand, a request that would wait
            # past its bound is shed NOW instead of expiring in queue
            bound = min(
                self.admit_deadline_s or float("inf"),
                effective_deadline or float("inf"),
            )
            if bound != float("inf") and self._ewma_solve_s > 0:
                backlog = sum(len(t.queue) for t in self._tenants.values())
                if backlog * self._ewma_solve_s > bound:
                    state.counters["shed"] += 1
                    return refuse(STATUS_OVERLOADED, ADMIT_PREDICTED_WAIT, True)
            req = _Request(
                tenant=tenant_id, pods=pods, instance_types=instance_types,
                templates=templates, kwargs=kwargs,
                deadline_s=effective_deadline, submitted_at=self._time(),
                ticket=ticket,
            )
            state.queue.append(req)
            state.counters["submitted"] += 1
            SERVE_ADMISSION.inc({"tenant": tenant_id, "outcome": ADMIT_ACCEPTED})
            SERVE_QUEUE_DEPTH.set(len(state.queue), {"tenant": tenant_id})
            started = self._thread is not None
            self._cond.notify_all()
        if not started:
            self.start()
        return ticket

    def solve(
        self,
        tenant_id: str,
        pods: Sequence,
        instance_types: Sequence,
        templates: Sequence,
        deadline_s: Optional[float] = None,
        timeout: Optional[float] = None,
        **kwargs,
    ) -> ServeOutcome:
        """submit + wait: the blocking convenience the churn streams use."""
        return self.submit(
            tenant_id, pods, instance_types, templates,
            deadline_s=deadline_s, **kwargs,
        ).wait(timeout)

    # -- dispatch loop --------------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._closed and not any(
                    t.queue for t in self._tenants.values()
                ):
                    self._cond.wait(0.5)
                if self._closed:
                    return
                picked, cobatch = self._collect_locked()
            if picked is None:
                continue
            self._execute(picked, cobatch)

    def _pop_locked(self, state) -> Optional[_Request]:
        """Pop a tenant's head request, resolving it immediately when its
        wall budget already expired in queue (``overloaded-expired`` — the
        device never sees it). Returns None when the pop produced no
        runnable request."""
        req = state.queue.popleft()
        SERVE_QUEUE_DEPTH.set(len(state.queue), {"tenant": state.id})
        if req.deadline_s > 0 and (
            self._time() - req.submitted_at
        ) >= req.deadline_s:
            state.counters["shed"] += 1
            SERVE_ADMISSION.inc(
                {"tenant": state.id, "outcome": ADMIT_EXPIRED}
            )
            req.ticket.resolve(ServeOutcome(
                status=STATUS_OVERLOADED, tenant=state.id,
                reason=ADMIT_EXPIRED,
                latency_s=self._time() - req.submitted_at,
            ))
            return None
        return req

    def _collect_locked(self) -> Tuple[Optional[_Request], List[_Request]]:
        """One DWRR decision. Sweeps the rotation for a stream whose balance
        covers its head request; when none can afford theirs, every
        backlogged stream earns weight x quantum and the sweep repeats
        (guaranteed to terminate: balances grow, costs don't)."""
        while True:
            backlogged = False
            for tenant_id in list(self._order):
                state = self._tenants[tenant_id]
                if not state.queue:
                    # idle streams don't bank credit
                    if state.deficit:
                        state.deficit = 0.0
                        SERVE_FAIRNESS_DEFICIT.set(0.0, {"tenant": tenant_id})
                    continue
                backlogged = True
                if state.queue[0].cost > state.deficit:
                    continue
                req = self._pop_locked(state)
                # served (or expired): this stream yields the rotation
                self._order.remove(tenant_id)
                self._order.append(tenant_id)
                if req is None:
                    return None, []
                state.deficit -= req.cost
                SERVE_FAIRNESS_DEFICIT.set(
                    state.deficit, {"tenant": tenant_id}
                )
                return req, self._gather_cobatch_locked(req, state)
            if not backlogged:
                return None, []
            for tenant_id in self._order:
                state = self._tenants[tenant_id]
                if state.queue:
                    state.deficit += state.weight * self.quantum
                    SERVE_FAIRNESS_DEFICIT.set(
                        state.deficit, {"tenant": tenant_id}
                    )

    def _gather_cobatch_locked(self, lead: _Request, lead_state) -> List[_Request]:
        """Other tenants' batchable heads that can ride the lead request's
        device dispatch — each still pays its own deficit (stacking changes
        the dispatch, not the accounting)."""
        from karpenter_tpu.serve import batch as xbatch

        if not self.batching:
            return []
        if not xbatch.batchable(lead, lead_state.solver):
            return []
        out: List[_Request] = []
        for tenant_id in list(self._order):
            if len(out) + 1 >= _MAX_BATCH_LANES:
                break
            state = self._tenants[tenant_id]
            if state is lead_state or not state.queue:
                continue
            head = state.queue[0]
            if head.cost > state.deficit:
                continue
            if not xbatch.batchable(head, state.solver):
                continue
            req = self._pop_locked(state)
            if req is None:
                continue
            state.deficit -= req.cost
            SERVE_FAIRNESS_DEFICIT.set(state.deficit, {"tenant": tenant_id})
            out.append(req)
        return out

    # -- execution ------------------------------------------------------------

    def _execute(self, lead: _Request, cobatch: List[_Request]) -> None:
        group = [lead] + cobatch
        stacked: List[Optional[SolveResult]] = [None] * len(group)
        if len(group) > 1:
            from karpenter_tpu.serve import batch as xbatch

            stacked = xbatch.stacked_solve(group)
        for req, pre in zip(group, stacked):
            if pre is not None:
                SERVE_BATCH.inc({"result": "hit"})
                self._finish_ok(req, pre, path="batched")
            else:
                if len(group) > 1:
                    SERVE_BATCH.inc({"result": "fallback"})
                self._execute_solo(req)

    def _execute_solo(self, req: _Request) -> None:
        state = self._tenants[req.tenant]
        solver = state.solver
        # deadline inheritance: the tenant watchdog gets the REMAINING wall
        # budget for this call (never widened past its configured value)
        configured = getattr(solver, "deadline_s", None)
        override = configured is not None and req.deadline_s > 0
        if override:
            remaining = req.deadline_s - (self._time() - req.submitted_at)
            if remaining <= 0:
                state.counters["shed"] += 1
                SERVE_ADMISSION.inc(
                    {"tenant": req.tenant, "outcome": ADMIT_EXPIRED}
                )
                req.ticket.resolve(ServeOutcome(
                    status=STATUS_OVERLOADED, tenant=req.tenant,
                    reason=ADMIT_EXPIRED,
                    latency_s=self._time() - req.submitted_at,
                ))
                return
            solver.deadline_s = (
                min(configured, remaining) if configured > 0 else remaining
            )
        try:
            result = solver.solve(
                req.pods, req.instance_types, req.templates, **req.kwargs
            )
        except Exception as exc:  # noqa: BLE001 — a tenant solve must never kill the loop
            state.counters["errors"] += 1
            req.ticket.resolve(ServeOutcome(
                status=STATUS_ERROR, tenant=req.tenant,
                reason=f"{type(exc).__name__}: {exc}",
                latency_s=self._time() - req.submitted_at, path="solo",
            ))
            return
        finally:
            if override:
                solver.deadline_s = configured
        self._finish_ok(req, result, path="solo")

    def _finish_ok(self, req: _Request, result: SolveResult, path: str) -> None:
        state = self._tenants[req.tenant]
        latency = self._time() - req.submitted_at
        state.counters["completed"] += 1
        if path == "batched":
            state.counters["batched"] += 1
        state.record_latency(latency)
        self._ewma_solve_s = (
            latency
            if self._ewma_solve_s == 0
            else (1 - _EWMA_ALPHA) * self._ewma_solve_s + _EWMA_ALPHA * latency
        )
        SERVE_CYCLES.inc({"tenant": req.tenant, "path": path})
        SERVE_CYCLE_SECONDS.observe(latency)
        req.ticket.resolve(ServeOutcome(
            status=STATUS_OK, tenant=req.tenant, result=result,
            latency_s=latency, path=path,
        ))

    # -- introspection (/debug/tenants, /statusz) -----------------------------

    def snapshot(self) -> Dict:
        with self._cond:
            tenants = [
                self._tenants[tid].snapshot() for tid in self._order
            ]
            return {
                "closed": self._closed,
                "dispatcher_alive": (
                    self._thread is not None and self._thread.is_alive()
                ),
                "batching": self.batching,
                "quantum": self.quantum,
                "queue_depth": self.queue_depth,
                "max_tenants": self.max_tenants,
                "admit_deadline_s": self.admit_deadline_s,
                "ewma_solve_s": round(self._ewma_solve_s, 6),
                "tenants": tenants,
            }

    def summary(self) -> Dict:
        """The /statusz serve section: fleet totals, not per-tenant rows
        (those live in /debug/tenants)."""
        with self._cond:
            totals = {"submitted": 0, "completed": 0, "shed": 0, "errors": 0,
                      "batched": 0}
            queued = 0
            circuits: Dict[str, int] = {}
            for state in self._tenants.values():
                queued += len(state.queue)
                for key in totals:
                    totals[key] += state.counters[key]
                circuit = state.circuit_state()
                if circuit is not None:
                    circuits[circuit] = circuits.get(circuit, 0) + 1
            return {
                "tenants": len(self._tenants),
                "queued": queued,
                "healthy": self.healthy(),
                "batching": self.batching,
                "circuits": circuits,
                **totals,
            }
