"""The serve dispatcher: bounded queues, hierarchical DWRR, classified
admission.

One dispatcher thread drains every tenant's queue — solves are serialized
onto the device exactly as the single-tenant operator serializes cycles, so
per-tenant solver state needs no locking and device contention is structural,
not emergent. Fairness and isolation live at the queue boundary:

  admission (``submit``, caller's thread)
      a request is either queued or resolved immediately with a CLASSIFIED
      outcome: ``overloaded-queue-full`` (its tenant's bounded queue is
      full), ``overloaded-predicted-wait`` (the decayed queue-wait estimate
      already exceeds the admit/request deadline — shedding at the door
      beats timing out after burning device time),
      ``overloaded-saturated`` (class-aware shedding under sustained
      over-subscription: a lower class's slice of the admit bound is
      exhausted while higher classes still admit), ``rejected-max-tenants``,
      ``rejected-shutdown``. serve_admission_total counts every decision by
      tenant CLASS (bounded label; per-tenant detail in /debug/tenants).

  fairness (``_collect``, dispatcher thread)
      hierarchical deficit-weighted round robin in pod-units. Tenant classes
      sit above tenants: the class ready-ring rotates classes whose balance
      covers their candidate; within a class, the tenant ready-ring rotates
      members the same way. Replenish is per level — members of a blocked
      class earn ``weight x quantum`` when none can afford its head, classes
      earn ``class_weight x quantum`` when every backlogged class is gated.
      An emptied queue forfeits its balance at BOTH levels (no hoarding
      credit while idle). With one class registered the class level
      disappears entirely and the schedule is bit-identical to the flat
      16-tenant DWRR. Only READY (backlogged) streams are ever swept: a
      ready-ring per class makes each decision O(active), so 990 idle
      registered tenants cost the dispatcher nothing.

  execution (``_execute``)
      the request's wall-clock budget (explicit per-request deadline, else
      the tenant's default) is inherited by the solve: the tenant solver's
      watchdog deadline is narrowed to the REMAINING budget for the call.
      Already-expired requests resolve as ``overloaded-expired`` without
      touching the device. Cross-tenant batchable groups take one stacked
      device dispatch (serve/batch.py) on this service's mesh (a replica's
      carved slice under serve/replica.py), with riders found through the
      shared per-shape program pool (serve/pool.py) in O(family) instead of
      a sweep of the whole registry.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from karpenter_tpu.metrics.registry import (
    SERVE_ACTIVE,
    SERVE_ADMISSION,
    SERVE_BATCH,
    SERVE_CYCLE_SECONDS,
    SERVE_CYCLES,
    SERVE_FAIRNESS_DEFICIT,
    SERVE_POOL,
    SERVE_QUEUE_DEPTH,
)
from karpenter_tpu.obs import flight, slo
from karpenter_tpu.serve.estimator import WaitEstimator
from karpenter_tpu.serve.pool import ProgramPool, shape_family
from karpenter_tpu.solver.backend import SolveResult

# classified admission / completion outcome vocabulary (the bounded metric
# label-value sets; tools/metrics_lint.py checks the cls axis separately)
STATUS_OK = "ok"
STATUS_OVERLOADED = "overloaded"
STATUS_REJECTED = "rejected"
STATUS_ERROR = "error"
STATUS_PENDING = "pending"

ADMIT_ACCEPTED = "accepted"
ADMIT_QUEUE_FULL = "overloaded-queue-full"
ADMIT_PREDICTED_WAIT = "overloaded-predicted-wait"
ADMIT_SATURATED = "overloaded-saturated"
ADMIT_EXPIRED = "overloaded-expired"
ADMIT_MAX_TENANTS = "rejected-max-tenants"
ADMIT_SHUTDOWN = "rejected-shutdown"

# a stacked dispatch wider than this stops amortizing and starts inflating
# the padded batch; overridable via KARPENTER_TPU_SERVE_BATCH_LANES
_MAX_BATCH_LANES = 8

# stacked dispatches run on the service's own mesh; "auto" resolves to
# parallel/mesh.default_mesh() at dispatch time (None = single-device vmap)
AUTO_MESH = "auto"


@dataclass
class ServeOutcome:
    """What a submitted request resolved to. ``status`` is always one of the
    STATUS_* constants; an unserved request carries its admission class in
    ``reason`` — the caller can always tell shed from failed from served."""

    status: str
    tenant: str = ""
    reason: str = ""
    result: Optional[SolveResult] = None
    latency_s: float = 0.0
    path: str = ""  # "solo" | "batched" | "" (never solved)


class Ticket:
    """The caller's handle on a submitted request."""

    def __init__(self, tenant: str):
        self._tenant = tenant
        self._event = threading.Event()
        self._outcome: Optional[ServeOutcome] = None

    def resolve(self, outcome: ServeOutcome) -> None:
        self._outcome = outcome
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> ServeOutcome:
        """Block for the outcome; a timeout returns a non-final ``pending``
        outcome (the request is still queued or running)."""
        if self._event.wait(timeout):
            assert self._outcome is not None
            return self._outcome
        return ServeOutcome(status=STATUS_PENDING, tenant=self._tenant)


@dataclass
class _Request:
    tenant: str
    pods: Sequence
    instance_types: Sequence
    templates: Sequence
    kwargs: Dict
    deadline_s: float  # effective wall budget (0 = none)
    submitted_at: float
    ticket: Ticket
    cost: float = field(init=False)

    def __post_init__(self):
        # DWRR service cost in pod-units: fairness is about device time,
        # which scales with batch size, not request count
        self.cost = float(max(1, len(self.pods)))


@dataclass
class TenantClass:
    """One tier of the class hierarchy: its DWRR balance, its ready-ring of
    backlogged member streams, and its aggregate accounting. The class set
    is operator config (KARPENTER_TPU_SERVE_CLASSES) — a bounded label."""

    name: str
    weight: float = 1.0
    deficit: float = 0.0
    queued: int = 0
    served_pods: float = 0.0
    ring: List[str] = field(default_factory=list)

    def snapshot(self) -> Dict:
        return {
            "class": self.name,
            "weight": self.weight,
            "deficit": round(self.deficit, 3),
            "queued": self.queued,
            "ready": len(self.ring),
            "served_pods": round(self.served_pods, 1),
        }


class SolveService:
    """The multi-tenant solve service. Construct explicitly (tests, bench,
    chaos, serve/replica.py) or let the operator wire it under
    ``KARPENTER_TPU_SERVE=1``."""

    def __init__(
        self,
        solver_factory=None,
        max_tenants: Optional[int] = None,
        queue_depth: Optional[int] = None,
        quantum: Optional[float] = None,
        admit_deadline_s: Optional[float] = None,
        weights: Optional[Dict[str, float]] = None,
        classes: Optional[Dict[str, float]] = None,
        batching: Optional[bool] = None,
        batch_lanes: Optional[int] = None,
        mesh=AUTO_MESH,
        name: str = "",
        time_fn=time.monotonic,
    ):
        from karpenter_tpu import serve as cfg
        from karpenter_tpu.serve.tenant import build_tenant_solver

        self._solver_factory = solver_factory or build_tenant_solver
        self.max_tenants = max_tenants if max_tenants is not None else cfg.max_tenants()
        self.queue_depth = queue_depth if queue_depth is not None else cfg.queue_depth()
        self.quantum = quantum if quantum is not None else cfg.quantum()
        self.admit_deadline_s = (
            admit_deadline_s
            if admit_deadline_s is not None
            else cfg.admit_deadline_s()
        )
        self.weights = weights if weights is not None else cfg.parse_weights()
        self.batching = batching if batching is not None else cfg.batching_enabled()
        self.batch_lanes = (
            batch_lanes if batch_lanes is not None else cfg.batch_lanes()
        )
        self.mesh = mesh
        self.name = name
        self._time = time_fn
        self._cond = threading.Condition()
        self._tenants: Dict[str, "TenantState"] = {}
        self._order: List[str] = []  # registration order (introspection only)
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        # class hierarchy: configured classes exist up front; tenants landing
        # in an unconfigured class mint it at weight 1 (tolerant, like
        # parse_weights). One class total == the flat DWRR fast path.
        self.class_weights = dict(
            classes if classes is not None else cfg.parse_classes()
        )
        if not self.class_weights:
            self.class_weights = {cfg.DEFAULT_CLASS: 1.0}
        self._classes: Dict[str, TenantClass] = {
            cname: TenantClass(name=cname, weight=w)
            for cname, w in self.class_weights.items()
        }
        self._max_class_weight = max(
            c.weight for c in self._classes.values()
        )
        self._class_ring: List[str] = []  # classes with ready members
        self._backlog = 0  # total queued requests (maintained, never summed)
        self._pool = ProgramPool()
        self._wait = WaitEstimator(
            half_life_s=cfg.ewma_half_life_s(),
            floor=cfg.ewma_floor(),
            time_fn=time_fn,
        )
        # scheduling-cost telemetry: the O(active) contract is measured, not
        # asserted — scans / decisions must track the READY population
        self._decisions = 0
        self._scans = 0
        self._replenish_rounds = 0

    # -- tenant registry ------------------------------------------------------

    def _class_for(self, cname: str) -> TenantClass:
        c = self._classes.get(cname)
        if c is None:
            c = TenantClass(name=cname, weight=self.class_weights.get(cname, 1.0))
            self._classes[cname] = c
            self._max_class_weight = max(self._max_class_weight, c.weight)
        return c

    def register_tenant(
        self,
        tenant_id: str,
        weight: Optional[float] = None,
        deadline_s: float = 0.0,
        solver=None,
        tenant_class: Optional[str] = None,
    ):
        """Create (or return) a tenant stream. Raises ValueError at the
        tenant capacity bound — ``submit`` classifies that as
        ``rejected-max-tenants`` instead of raising at the caller.
        Registration is O(1): a registered-but-idle tenant costs the
        dispatcher nothing until its first request."""
        from karpenter_tpu import serve as cfg
        from karpenter_tpu.serve.tenant import TenantState

        with self._cond:
            existing = self._tenants.get(tenant_id)
            if existing is not None:
                return existing
            if len(self._tenants) >= self.max_tenants:
                raise ValueError(
                    f"tenant capacity {self.max_tenants} reached "
                    f"(KARPENTER_TPU_SERVE_MAX_TENANTS)"
                )
            cname = tenant_class if tenant_class is not None else cfg.DEFAULT_CLASS
            self._class_for(cname)
            state = TenantState(
                tenant_id,
                solver if solver is not None else self._solver_factory(tenant_id),
                weight=(
                    weight
                    if weight is not None
                    else self.weights.get(tenant_id, 1.0)
                ),
                deadline_s=deadline_s,
                queue_depth=self.queue_depth,
                cls=cname,
            )
            self._tenants[tenant_id] = state
            self._order.append(tenant_id)
            return state

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "SolveService":
        from karpenter_tpu import serve as cfg

        with self._cond:
            if self._closed:
                raise RuntimeError("SolveService is closed")
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop, daemon=True,
                    name=f"karpenter-tpu/serve-dispatcher{self.name and '-' + self.name}",
                )
                self._thread.start()
        if not self.name:
            # replicas (serve/replica.py) register their set instead
            cfg._set_current(self)
        return self

    def close(self, timeout: float = 10.0) -> None:
        """Stop dispatching and resolve everything still queued as
        ``rejected-shutdown`` — shutdown shedding is classified like any
        other unserved outcome."""
        from karpenter_tpu import serve as cfg

        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
        drained: List[Tuple[_Request, str]] = []
        with self._cond:
            for state in self._tenants.values():
                while state.queue:
                    drained.append((state.queue.popleft(), state.cls))
                    state.counters["shed"] += 1
                state.ready = False
            for c in self._classes.values():
                c.queued = 0
                c.deficit = 0.0
                c.ring = []
                SERVE_QUEUE_DEPTH.set(0, {"cls": c.name})
                SERVE_ACTIVE.set(0, {"cls": c.name})
            self._class_ring = []
            self._backlog = 0
            self._pool = ProgramPool()
        for req, cname in drained:
            SERVE_ADMISSION.inc({"cls": cname, "outcome": ADMIT_SHUTDOWN})
            req.ticket.resolve(ServeOutcome(
                status=STATUS_REJECTED, tenant=req.tenant, reason=ADMIT_SHUTDOWN,
            ))
        if cfg.current_service() is self:
            cfg._set_current(None)

    def healthy(self) -> bool:
        """Readiness contribution: closed or a dead dispatcher thread means
        queued requests would wait forever."""
        with self._cond:
            if self._closed:
                return False
            return self._thread is None or self._thread.is_alive()

    # -- admission ------------------------------------------------------------

    def submit(
        self,
        tenant_id: str,
        pods: Sequence,
        instance_types: Sequence,
        templates: Sequence,
        deadline_s: Optional[float] = None,
        **kwargs,
    ) -> Ticket:
        """Admit one solve request. Always returns a Ticket; an unadmitted
        request's ticket is already resolved with its classification.
        O(1) in the registered-tenant count: the backlog is a maintained
        counter, never a sweep."""
        ticket = Ticket(tenant_id)

        def refuse(status: str, outcome: str, cls_label: str) -> Ticket:
            # the cls label stays bounded: classes are operator config, and
            # unregistered ids never mint anything ("-" is the placeholder)
            SERVE_ADMISSION.inc({"cls": cls_label, "outcome": outcome})
            if slo.enabled():
                slo.on_serve_admission(cls_label, False)
                flight.record(
                    flight.KIND_ADMISSION, outcome=outcome,
                    cls=cls_label, tenant=tenant_id,
                )
            ticket.resolve(ServeOutcome(
                status=status, tenant=tenant_id, reason=outcome,
            ))
            return ticket

        with self._cond:
            state = self._tenants.get(tenant_id)
            if self._closed:
                return refuse(
                    STATUS_REJECTED, ADMIT_SHUTDOWN,
                    state.cls if state is not None else "-",
                )
            if state is None:
                try:
                    state = self.register_tenant(tenant_id)
                except ValueError:
                    return refuse(STATUS_REJECTED, ADMIT_MAX_TENANTS, "-")
            c = self._classes[state.cls]
            effective_deadline = (
                deadline_s if deadline_s is not None else state.deadline_s
            ) or 0.0
            if len(state.queue) >= state.queue_depth:
                state.counters["shed"] += 1
                return refuse(STATUS_OVERLOADED, ADMIT_QUEUE_FULL, c.name)
            # predicted-wait shedding: with a wait bound configured (the
            # service-wide admit deadline and/or this request's own budget)
            # and a solve-rate estimate in hand, a request that would wait
            # past its bound is shed NOW instead of expiring in queue. The
            # estimate is the TIME-DECAYED per-request service EWMA
            # (serve/estimator.py): stale estimates from a previous busy
            # period decay instead of over-shedding the next burst's head.
            per_req = self._wait.per_request_s()
            if per_req > 0:
                bound = min(
                    self.admit_deadline_s or float("inf"),
                    effective_deadline or float("inf"),
                )
                predicted = self._backlog * per_req
                if bound != float("inf") and predicted > bound:
                    state.counters["shed"] += 1
                    return refuse(
                        STATUS_OVERLOADED, ADMIT_PREDICTED_WAIT, c.name
                    )
                # class-aware saturation shedding: under sustained over-
                # subscription each class owns a (w_c / w_max) slice of the
                # admit bound, so lower classes shed at the door while the
                # top class still admits. One registered class => factor 1
                # => this branch never fires (flat admission, bit-identical).
                if len(self._classes) > 1 and self.admit_deadline_s > 0:
                    factor = c.weight / self._max_class_weight
                    if factor < 1.0 and predicted > self.admit_deadline_s * factor:
                        state.counters["shed"] += 1
                        return refuse(
                            STATUS_OVERLOADED, ADMIT_SATURATED, c.name
                        )
            req = _Request(
                tenant=tenant_id, pods=pods, instance_types=instance_types,
                templates=templates, kwargs=kwargs,
                deadline_s=effective_deadline, submitted_at=self._time(),
                ticket=ticket,
            )
            self._enqueue_locked(state, c, req)
            state.counters["submitted"] += 1
            SERVE_ADMISSION.inc({"cls": c.name, "outcome": ADMIT_ACCEPTED})
            slo.on_serve_admission(c.name, True)
            started = self._thread is not None
            self._cond.notify_all()
        if not started:
            self.start()
        return ticket

    def solve(
        self,
        tenant_id: str,
        pods: Sequence,
        instance_types: Sequence,
        templates: Sequence,
        deadline_s: Optional[float] = None,
        timeout: Optional[float] = None,
        **kwargs,
    ) -> ServeOutcome:
        """submit + wait: the blocking convenience the churn streams use."""
        return self.submit(
            tenant_id, pods, instance_types, templates,
            deadline_s=deadline_s, **kwargs,
        ).wait(timeout)

    # -- ready-ring maintenance (all under the service lock) ------------------

    def _enqueue_locked(self, state, c: TenantClass, req: _Request) -> None:
        state.queue.append(req)
        c.queued += 1
        self._backlog += 1
        SERVE_QUEUE_DEPTH.set(c.queued, {"cls": c.name})
        if not state.ready:
            state.ready = True
            if not c.ring:
                self._class_ring.append(c.name)
            c.ring.append(state.id)
            SERVE_ACTIVE.set(len(c.ring), {"cls": c.name})
        if self.batching and len(state.queue) == 1:
            self._note_head_locked(state)

    def _note_head_locked(self, state) -> None:
        """Keep the program pool's family index pointing at this stream's
        current head (serve/pool.py). Eligibility is re-verified at gather
        time — the pool is an index, not a promise."""
        from karpenter_tpu.serve import batch as xbatch

        if not state.queue:
            self._pool.clear(state.id)
            return
        head = state.queue[0]
        self._pool.note_head(
            state.id, head, xbatch.batchable(head, state.solver)
        )

    def _forfeit_locked(self, state) -> None:
        """Tenant-level idle forfeit: an emptied stream leaves the ring with
        a zero balance — no hoarding credit while idle."""
        state.ready = False
        if state.deficit:
            state.deficit = 0.0

    def _drop_from_ring_locked(self, c: TenantClass, state) -> None:
        """Remove an emptied stream from its class ring, forfeiting at both
        levels when the class itself goes idle."""
        try:
            c.ring.remove(state.id)
        except ValueError:
            pass
        self._forfeit_locked(state)
        if not c.ring:
            if c.name in self._class_ring:
                self._class_ring.remove(c.name)
            # class-level idle forfeit: an emptied class loses its balance
            if c.deficit:
                c.deficit = 0.0
                if len(self._classes) > 1:
                    SERVE_FAIRNESS_DEFICIT.set(0.0, {"cls": c.name})
        SERVE_ACTIVE.set(len(c.ring), {"cls": c.name})

    def _rotate_locked(self, c: TenantClass, state) -> None:
        """A served (or expired) stream yields its turn: tenant to the back
        of its class ring, class to the back of the class ring."""
        try:
            c.ring.remove(state.id)
        except ValueError:
            pass
        if state.queue:
            c.ring.append(state.id)
        else:
            self._forfeit_locked(state)
        if c.name in self._class_ring:
            self._class_ring.remove(c.name)
        if c.ring:
            self._class_ring.append(c.name)
        elif c.deficit:
            c.deficit = 0.0
            if len(self._classes) > 1:
                SERVE_FAIRNESS_DEFICIT.set(0.0, {"cls": c.name})
        SERVE_ACTIVE.set(len(c.ring), {"cls": c.name})

    # -- dispatch loop --------------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._cond:
                # O(1) idle check: the backlog is maintained at enqueue/pop,
                # never recomputed by sweeping 1,000 registered tenants
                while not self._closed and self._backlog == 0:
                    self._cond.wait(0.5)
                if self._closed:
                    return
                picked, cobatch = self._collect_locked()
            if picked is None:
                continue
            self._execute(picked, cobatch)

    def _pop_locked(self, state) -> Optional[_Request]:
        """Pop a tenant's head request, resolving it immediately when its
        wall budget already expired in queue (``overloaded-expired`` — the
        device never sees it). Returns None when the pop produced no
        runnable request."""
        req = state.queue.popleft()
        c = self._classes[state.cls]
        c.queued -= 1
        self._backlog -= 1
        SERVE_QUEUE_DEPTH.set(c.queued, {"cls": c.name})
        if self.batching:
            self._note_head_locked(state)
        if req.deadline_s > 0 and (
            self._time() - req.submitted_at
        ) >= req.deadline_s:
            state.counters["shed"] += 1
            SERVE_ADMISSION.inc({"cls": c.name, "outcome": ADMIT_EXPIRED})
            if slo.enabled():
                slo.on_serve_admission(c.name, False)
                flight.record(
                    flight.KIND_ADMISSION, outcome=ADMIT_EXPIRED,
                    cls=c.name, tenant=state.id,
                )
            req.ticket.resolve(ServeOutcome(
                status=STATUS_OVERLOADED, tenant=state.id,
                reason=ADMIT_EXPIRED,
                latency_s=self._time() - req.submitted_at,
            ))
            return None
        return req

    def _affordable_member_locked(self, c: TenantClass):
        """First stream in the class ring whose balance covers its head —
        the intra-class DWRR candidate. O(ready members of this class)."""
        for tid in c.ring:
            self._scans += 1
            state = self._tenants[tid]
            if state.queue and state.queue[0].cost <= state.deficit:
                return state
        return None

    def _collect_locked(self) -> Tuple[Optional[_Request], List[_Request]]:
        """One hierarchical DWRR decision, O(active). Sweeps the ready
        classes for one whose balance covers its intra-class candidate;
        replenish is per level and only for backlogged parties (guaranteed
        to terminate: balances grow, costs don't). With one registered class
        the class level vanishes and this IS the flat DWRR schedule."""
        hierarchical = len(self._classes) > 1
        while True:
            if not self._class_ring:
                return None, []
            for cname in list(self._class_ring):
                c = self._classes[cname]
                if not c.ring:
                    continue
                pick = self._affordable_member_locked(c)
                while pick is None:
                    # intra-class replenish: this class has backlog but no
                    # member can afford its head — members earn their keep
                    # independently of the other classes' pace
                    for tid in c.ring:
                        member = self._tenants[tid]
                        member.deficit += member.weight * self.quantum
                    pick = self._affordable_member_locked(c)
                if hierarchical and pick.queue[0].cost > c.deficit:
                    continue  # the class balance gates its candidate
                self._decisions += 1
                req = self._pop_locked(pick)
                if req is not None:
                    # pay BEFORE rotating: a pop-to-empty rotate forfeits the
                    # balance, and charging after the forfeit would bank a
                    # negative deficit against the stream's next busy period
                    pick.deficit -= req.cost
                    c.served_pods += req.cost
                    if hierarchical:
                        c.deficit -= req.cost
                        SERVE_FAIRNESS_DEFICIT.set(c.deficit, {"cls": cname})
                self._rotate_locked(c, pick)
                if req is None:
                    return None, []
                return req, self._gather_cobatch_locked(req, pick)
            # every backlogged class is gated by its class balance:
            # class-level replenish (idle classes are not in the ring and
            # earn nothing)
            for cname in self._class_ring:
                c = self._classes[cname]
                c.deficit += c.weight * self.quantum
                SERVE_FAIRNESS_DEFICIT.set(c.deficit, {"cls": cname})
            self._replenish_rounds += 1

    def _gather_cobatch_locked(self, lead: _Request, lead_state) -> List[_Request]:
        """Other tenants' batchable heads that can ride the lead request's
        device dispatch — each still pays its own deficit at both levels
        (stacking changes the dispatch, not the accounting). Riders come
        from the shared program pool's shape-family index: O(family), not a
        sweep of the registry."""
        from karpenter_tpu.serve import batch as xbatch

        if not self.batching:
            return []
        if not xbatch.batchable(lead, lead_state.solver):
            return []
        hierarchical = len(self._classes) > 1
        out: List[_Request] = []
        for tid in self._pool.candidates(shape_family(lead)):
            if len(out) + 1 >= self.batch_lanes:
                break
            state = self._tenants.get(tid)
            if state is None or state is lead_state or not state.queue:
                continue
            head = state.queue[0]
            if head.cost > state.deficit:
                continue
            c = self._classes[state.cls]
            if hierarchical and head.cost > c.deficit:
                continue
            if not xbatch.batchable(head, state.solver):
                continue
            req = self._pop_locked(state)
            if req is None:
                if not state.queue:
                    self._drop_from_ring_locked(c, state)
                continue
            state.deficit -= req.cost
            c.served_pods += req.cost
            if hierarchical:
                c.deficit -= req.cost
                SERVE_FAIRNESS_DEFICIT.set(c.deficit, {"cls": c.name})
            if not state.queue:
                self._drop_from_ring_locked(c, state)
            out.append(req)
        SERVE_POOL.inc({"result": "hit" if out else "alone"})
        return out

    # -- execution ------------------------------------------------------------

    def _execute(self, lead: _Request, cobatch: List[_Request]) -> None:
        group = [lead] + cobatch
        started = self._time()
        stacked: List[Optional[SolveResult]] = [None] * len(group)
        if len(group) > 1:
            from karpenter_tpu.serve import batch as xbatch

            from karpenter_tpu.solver import mesh_health

            try:
                mesh_health.dispatch_check(
                    list(self.mesh.devices.flat)
                    if self.mesh is not None and not isinstance(self.mesh, str)
                    else None
                )
                stacked = xbatch.stacked_solve(group, mesh=self.mesh)
            except Exception as exc:  # noqa: BLE001 — classified or re-raised
                if mesh_health.handle_dispatch_failure(exc) is None:
                    raise
                # a device in this replica's slice died mid-dispatch: the
                # tracker recarved around it. Degrade THIS replica to the
                # unsliced path (mesh=None -> default device) and serve the
                # whole group solo below — a device loss costs batching
                # throughput, never a dropped cycle. ReplicaSet.failover
                # handles the stronger whole-replica-death case.
                self.mesh = None
                stacked = [None] * len(group)
        for req, pre in zip(group, stacked):
            if pre is not None:
                SERVE_BATCH.inc({"result": "hit"})
                self._finish_ok(req, pre, path="batched")
            else:
                if len(group) > 1:
                    SERVE_BATCH.inc({"result": "fallback"})
                self._execute_solo(req)
        # the admission estimator learns per-request SERVICE time: dispatch
        # wall amortized across the group (queue wait excluded — predicted
        # wait is backlog x service, so queue-inclusive feeding would
        # double-count the queue and over-shed sustained load)
        elapsed = self._time() - started
        if elapsed >= 0:
            self._wait.observe(elapsed / len(group))

    def _execute_solo(self, req: _Request) -> None:
        state = self._tenants[req.tenant]
        solver = state.solver
        # deadline inheritance: the tenant watchdog gets the REMAINING wall
        # budget for this call (never widened past its configured value)
        configured = getattr(solver, "deadline_s", None)
        override = configured is not None and req.deadline_s > 0
        if override:
            remaining = req.deadline_s - (self._time() - req.submitted_at)
            if remaining <= 0:
                state.counters["shed"] += 1
                SERVE_ADMISSION.inc(
                    {"cls": state.cls, "outcome": ADMIT_EXPIRED}
                )
                if slo.enabled():
                    slo.on_serve_admission(state.cls, False)
                    flight.record(
                        flight.KIND_ADMISSION, outcome=ADMIT_EXPIRED,
                        cls=state.cls, tenant=req.tenant,
                    )
                req.ticket.resolve(ServeOutcome(
                    status=STATUS_OVERLOADED, tenant=req.tenant,
                    reason=ADMIT_EXPIRED,
                    latency_s=self._time() - req.submitted_at,
                ))
                return
            solver.deadline_s = (
                min(configured, remaining) if configured > 0 else remaining
            )
        try:
            result = solver.solve(
                req.pods, req.instance_types, req.templates, **req.kwargs
            )
        except Exception as exc:  # noqa: BLE001 — a tenant solve must never kill the loop
            state.counters["errors"] += 1
            flight.record(
                flight.KIND_SERVE_COMPLETE, cls=state.cls, tenant=req.tenant,
                status=STATUS_ERROR, error=type(exc).__name__,
            )
            req.ticket.resolve(ServeOutcome(
                status=STATUS_ERROR, tenant=req.tenant,
                reason=f"{type(exc).__name__}: {exc}",
                latency_s=self._time() - req.submitted_at, path="solo",
            ))
            return
        finally:
            if override:
                solver.deadline_s = configured
        self._finish_ok(req, result, path="solo")

    def _finish_ok(self, req: _Request, result: SolveResult, path: str) -> None:
        state = self._tenants[req.tenant]
        latency = self._time() - req.submitted_at
        state.counters["completed"] += 1
        if path == "batched":
            state.counters["batched"] += 1
        state.record_latency(latency)
        SERVE_CYCLES.inc({"cls": state.cls, "path": path})
        SERVE_CYCLE_SECONDS.observe(latency)
        if slo.enabled():
            slo.on_serve_latency(state.cls, latency)
            flight.record(
                flight.KIND_SERVE_COMPLETE, cls=state.cls, tenant=req.tenant,
                latency_s=round(latency, 6), path=path,
            )
        req.ticket.resolve(ServeOutcome(
            status=STATUS_OK, tenant=req.tenant, result=result,
            latency_s=latency, path=path,
        ))

    # -- introspection (/debug/tenants, /statusz) -----------------------------

    def snapshot(self) -> Dict:
        with self._cond:
            tenants = [
                self._tenants[tid].snapshot() for tid in self._order
            ]
            return {
                "closed": self._closed,
                "dispatcher_alive": (
                    self._thread is not None and self._thread.is_alive()
                ),
                "name": self.name,
                "batching": self.batching,
                "batch_lanes": self.batch_lanes,
                "quantum": self.quantum,
                "queue_depth": self.queue_depth,
                "max_tenants": self.max_tenants,
                "admit_deadline_s": self.admit_deadline_s,
                "backlog": self._backlog,
                "ewma_solve_s": round(self._wait.per_request_s(), 6),
                "wait_estimator": self._wait.snapshot(),
                "classes": [
                    c.snapshot() for c in self._classes.values()
                ],
                "sched": {
                    "decisions": self._decisions,
                    "scans": self._scans,
                    "replenish_rounds": self._replenish_rounds,
                },
                "pool": self._pool.snapshot(),
                "tenants": tenants,
            }

    def summary(self) -> Dict:
        """The /statusz serve section: fleet totals, not per-tenant rows
        (those live in /debug/tenants)."""
        with self._cond:
            totals = {"submitted": 0, "completed": 0, "shed": 0, "errors": 0,
                      "batched": 0}
            circuits: Dict[str, int] = {}
            for state in self._tenants.values():
                for key in totals:
                    totals[key] += state.counters[key]
                circuit = state.circuit_state()
                if circuit is not None:
                    circuits[circuit] = circuits.get(circuit, 0) + 1
            return {
                "tenants": len(self._tenants),
                "classes": len(self._classes),
                "queued": self._backlog,
                "healthy": self.healthy(),
                "batching": self.batching,
                "circuits": circuits,
                **totals,
            }
