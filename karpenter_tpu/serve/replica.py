"""Serve replicas: N dispatchers, each owning a carved mesh slice.

One dispatcher thread serializes every tenant's solves onto one device
set — correct, but at fleet scale a single serializing loop is the
throughput ceiling. A ``ReplicaSet`` runs N ``SolveService`` replicas side
by side, each with its OWN dispatcher thread and its OWN slice of the local
devices (parallel/mesh.carve_meshes): replicas never contend for a device,
so aggregate pods/s scales with the slice count while every per-replica
property (fairness, isolation, classified admission) is untouched — a
replica IS a SolveService.

Placement is sticky and CLASSIFIED — every tenant->replica decision carries
a reason, the same no-unclassified-outcomes rule admission follows:

  pinned      the operator said so (tests, forced co-location)
  big-tenant  expected pods >= KARPENTER_TPU_SERVE_BIG_PODS: the stream
              rides replica 0, which owns the LARGEST carved slice (where
              the sharded screen path pays off)
  hash        everyone else: stable crc32(tenant) % n — deterministic
              across processes, no coordination state to lose

Stickiness is what keeps the isolation contract: a tenant's solver stack
(circuit, warm state, quarantine namespace) lives on exactly one replica,
so replica routing never splits a stream's state.

Degraded-mesh failover (docs/ROBUSTNESS.md "Degraded mesh"): when a
replica's slice loses its devices, ``failover(dead_idx)`` migrates every
tenant placed there to the survivors under a fourth classified reason:

  failover    the original replica died; the tenant was re-hashed over the
              SURVIVING replicas (stable: crc32(tenant) % len(survivors))

The move is pessimistic about the surge it creates: each survivor's wait
estimator is seeded with 2x the worst per-request estimate either side had
learned, so admission backpressure engages BEFORE the first migrated solve
lands rather than after the queue has already built. A dead replica never
receives new placements, and re-running ``failover`` for the same replica
is a no-op — stickiness holds on the new home too.
"""

from __future__ import annotations

import threading
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

from karpenter_tpu.metrics.registry import SERVE_REPLICA_PLACEMENTS
from karpenter_tpu.serve.dispatcher import AUTO_MESH, SolveService

PLACE_PINNED = "pinned"
PLACE_BIG_TENANT = "big-tenant"
PLACE_HASH = "hash"
PLACE_FAILOVER = "failover"

# seed for the survivors' wait estimators when nobody has a measurement yet:
# pessimistic enough to engage predicted-wait shedding on a deep backlog
FAILOVER_SEED_S = 0.05


class ReplicaSet:
    """N SolveService replicas over carved mesh slices, with classified
    sticky tenant placement. Construct explicitly; knobs fill the gaps
    (KARPENTER_TPU_SERVE_REPLICAS, KARPENTER_TPU_SERVE_BIG_PODS)."""

    def __init__(
        self,
        n_replicas: Optional[int] = None,
        meshes: Optional[Sequence] = None,
        big_tenant_pods: Optional[int] = None,
        **service_kwargs,
    ):
        from karpenter_tpu import serve as cfg

        self.n = max(1, int(n_replicas if n_replicas is not None else cfg.replicas()))
        self.big_tenant_pods = (
            big_tenant_pods
            if big_tenant_pods is not None
            else cfg.big_tenant_pods()
        )
        if meshes is None:
            if self.n == 1:
                # one replica owns everything: same mesh the flat service uses
                meshes = [AUTO_MESH]
            else:
                from karpenter_tpu.parallel.mesh import carve_meshes

                meshes = carve_meshes(self.n)
        if len(meshes) != self.n:
            raise ValueError(
                f"{len(meshes)} meshes for {self.n} replicas"
            )
        self.replicas: List[SolveService] = [
            SolveService(name=f"r{i}", mesh=meshes[i], **service_kwargs)
            for i in range(self.n)
        ]
        # sticky placement: tenant -> (replica index, classified reason)
        self._placements: Dict[str, Tuple[int, str]] = {}
        self._dead: set = set()
        self._failovers = 0  # tenant migrations, for accounting
        self._lock = threading.Lock()

    def _survivors(self) -> List[int]:
        """Live replica indices, ascending (caller holds the lock). Index 0
        stays first while alive, so big-tenant placement keeps the largest
        carved slice."""
        return [i for i in range(self.n) if i not in self._dead]

    # -- placement ------------------------------------------------------------

    def place(
        self,
        tenant_id: str,
        expected_pods: int = 0,
        pinned: Optional[int] = None,
    ) -> Tuple[int, str]:
        """Resolve (and remember) a tenant's replica. Idempotent: the first
        decision sticks — a tenant's solver state lives on one replica."""
        with self._lock:
            existing = self._placements.get(tenant_id)
            if existing is not None:
                return existing
            live = self._survivors()
            if not live:
                raise RuntimeError("no live replicas (all failed over)")
            if pinned is not None:
                decision = (live[pinned % len(live)], PLACE_PINNED)
            elif expected_pods >= self.big_tenant_pods:
                # the first LIVE replica holds the largest surviving carved
                # slice (carve_meshes gives remainder devices to the first
                # chunks, and failover never revives a dead index)
                decision = (live[0], PLACE_BIG_TENANT)
            else:
                decision = (
                    live[zlib.crc32(tenant_id.encode()) % len(live)],
                    PLACE_HASH,
                )
            self._placements[tenant_id] = decision
        SERVE_REPLICA_PLACEMENTS.inc({"reason": decision[1]})
        return decision

    def failover(self, dead_idx: int, close_timeout: float = 5.0) -> Dict[str, int]:
        """Declare replica ``dead_idx`` dead (its mesh slice lost devices)
        and migrate every tenant placed on it to the survivors. Returns
        ``{tenant: new_replica}`` for the tenants moved; idempotent — a
        second call for the same replica moves nothing.

        Every migrated tenant is re-placed with the classified ``failover``
        reason and re-registered on its survivor with the SAME weight,
        deadline, and class (a fresh solver stack — device-resident state
        died with the slice and is never resurrected). Survivors' wait
        estimators are seeded pessimistically so admission backpressure
        covers the migration surge."""
        dead_idx = int(dead_idx)
        with self._lock:
            if dead_idx in self._dead or not (0 <= dead_idx < self.n):
                return {}
            self._dead.add(dead_idx)
            live = self._survivors()
            if not live:
                # the last replica died: nothing to migrate onto. Leave the
                # placements — healthy() reports the set down.
                return {}
            moved: Dict[str, int] = {}
            for tenant, (idx, _reason) in list(self._placements.items()):
                if idx != dead_idx:
                    continue
                new_idx = live[zlib.crc32(tenant.encode()) % len(live)]
                self._placements[tenant] = (new_idx, PLACE_FAILOVER)
                moved[tenant] = new_idx
            self._failovers += len(moved)
        dead = self.replicas[dead_idx]
        # seed BEFORE re-registering: backpressure should precede the surge
        worst = max(
            [FAILOVER_SEED_S, dead._wait.per_request_s()]
            + [self.replicas[i]._wait.per_request_s() for i in live]
        )
        for i in live:
            self.replicas[i]._wait.seed(2.0 * worst)
        for tenant, new_idx in moved.items():
            state = dead._tenants.get(tenant)
            try:
                self.replicas[new_idx].register_tenant(
                    tenant,
                    weight=state.weight if state is not None else None,
                    deadline_s=state.deadline_s if state is not None else 0.0,
                    tenant_class=state.cls if state is not None else None,
                )
            except ValueError:
                # survivor at tenant capacity: submit classifies the miss
                # as rejected-max-tenants — still never unclassified
                pass
        for tenant in moved:
            SERVE_REPLICA_PLACEMENTS.inc({"reason": PLACE_FAILOVER})
        # drain the dead dispatcher: anything still queued there resolves
        # classified (rejected-shutdown), never silently dropped
        try:
            dead.close(timeout=close_timeout)
        except Exception:
            pass
        return moved

    def dead_replicas(self) -> List[int]:
        with self._lock:
            return sorted(self._dead)

    def replica_for(self, tenant_id: str, expected_pods: int = 0) -> SolveService:
        idx, _ = self.place(tenant_id, expected_pods=expected_pods)
        return self.replicas[idx]

    # -- the SolveService surface, routed -------------------------------------

    def register_tenant(self, tenant_id: str, expected_pods: int = 0, **kwargs):
        return self.replica_for(
            tenant_id, expected_pods=expected_pods
        ).register_tenant(tenant_id, **kwargs)

    def submit(self, tenant_id: str, pods, instance_types, templates, **kwargs):
        return self.replica_for(
            tenant_id, expected_pods=len(pods)
        ).submit(tenant_id, pods, instance_types, templates, **kwargs)

    def solve(self, tenant_id: str, pods, instance_types, templates, **kwargs):
        return self.replica_for(
            tenant_id, expected_pods=len(pods)
        ).solve(tenant_id, pods, instance_types, templates, **kwargs)

    def start(self) -> "ReplicaSet":
        for r in self.replicas:
            r.start()
        return self

    def close(self, timeout: float = 10.0) -> None:
        for r in self.replicas:
            r.close(timeout=timeout)

    def healthy(self) -> bool:
        """Live replicas only: a failed-over replica is expected-dead, not
        unhealthy — the set stays ready as long as one survivor serves."""
        with self._lock:
            live = self._survivors()
        return bool(live) and all(self.replicas[i].healthy() for i in live)

    # -- introspection --------------------------------------------------------

    def placements(self) -> Dict[str, Tuple[int, str]]:
        with self._lock:
            return dict(self._placements)

    def snapshot(self) -> Dict:
        placed = self.placements()
        reasons: Dict[str, int] = {}
        for _, reason in placed.values():
            reasons[reason] = reasons.get(reason, 0) + 1
        return {
            "replicas": [r.snapshot() for r in self.replicas],
            "placements": len(placed),
            "placement_reasons": reasons,
            "dead_replicas": self.dead_replicas(),
            "failovers": self._failovers,
        }

    def summary(self) -> Dict:
        out: Dict = {"replicas": self.n, "placements": len(self._placements)}
        totals: Dict[str, int] = {}
        for r in self.replicas:
            for key, value in r.summary().items():
                if isinstance(value, (int, float)) and not isinstance(value, bool):
                    totals[key] = totals.get(key, 0) + value
        out.update(totals)
        out["healthy"] = self.healthy()
        return out
