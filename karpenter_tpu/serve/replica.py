"""Serve replicas: N dispatchers, each owning a carved mesh slice.

One dispatcher thread serializes every tenant's solves onto one device
set — correct, but at fleet scale a single serializing loop is the
throughput ceiling. A ``ReplicaSet`` runs N ``SolveService`` replicas side
by side, each with its OWN dispatcher thread and its OWN slice of the local
devices (parallel/mesh.carve_meshes): replicas never contend for a device,
so aggregate pods/s scales with the slice count while every per-replica
property (fairness, isolation, classified admission) is untouched — a
replica IS a SolveService.

Placement is sticky and CLASSIFIED — every tenant->replica decision carries
a reason, the same no-unclassified-outcomes rule admission follows:

  pinned      the operator said so (tests, forced co-location)
  big-tenant  expected pods >= KARPENTER_TPU_SERVE_BIG_PODS: the stream
              rides replica 0, which owns the LARGEST carved slice (where
              the sharded screen path pays off)
  hash        everyone else: stable crc32(tenant) % n — deterministic
              across processes, no coordination state to lose

Stickiness is what keeps the isolation contract: a tenant's solver stack
(circuit, warm state, quarantine namespace) lives on exactly one replica,
so replica routing never splits a stream's state.
"""

from __future__ import annotations

import threading
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

from karpenter_tpu.metrics.registry import SERVE_REPLICA_PLACEMENTS
from karpenter_tpu.serve.dispatcher import AUTO_MESH, SolveService

PLACE_PINNED = "pinned"
PLACE_BIG_TENANT = "big-tenant"
PLACE_HASH = "hash"


class ReplicaSet:
    """N SolveService replicas over carved mesh slices, with classified
    sticky tenant placement. Construct explicitly; knobs fill the gaps
    (KARPENTER_TPU_SERVE_REPLICAS, KARPENTER_TPU_SERVE_BIG_PODS)."""

    def __init__(
        self,
        n_replicas: Optional[int] = None,
        meshes: Optional[Sequence] = None,
        big_tenant_pods: Optional[int] = None,
        **service_kwargs,
    ):
        from karpenter_tpu import serve as cfg

        self.n = max(1, int(n_replicas if n_replicas is not None else cfg.replicas()))
        self.big_tenant_pods = (
            big_tenant_pods
            if big_tenant_pods is not None
            else cfg.big_tenant_pods()
        )
        if meshes is None:
            if self.n == 1:
                # one replica owns everything: same mesh the flat service uses
                meshes = [AUTO_MESH]
            else:
                from karpenter_tpu.parallel.mesh import carve_meshes

                meshes = carve_meshes(self.n)
        if len(meshes) != self.n:
            raise ValueError(
                f"{len(meshes)} meshes for {self.n} replicas"
            )
        self.replicas: List[SolveService] = [
            SolveService(name=f"r{i}", mesh=meshes[i], **service_kwargs)
            for i in range(self.n)
        ]
        # sticky placement: tenant -> (replica index, classified reason)
        self._placements: Dict[str, Tuple[int, str]] = {}
        self._lock = threading.Lock()

    # -- placement ------------------------------------------------------------

    def place(
        self,
        tenant_id: str,
        expected_pods: int = 0,
        pinned: Optional[int] = None,
    ) -> Tuple[int, str]:
        """Resolve (and remember) a tenant's replica. Idempotent: the first
        decision sticks — a tenant's solver state lives on one replica."""
        with self._lock:
            existing = self._placements.get(tenant_id)
            if existing is not None:
                return existing
            if pinned is not None:
                decision = (pinned % self.n, PLACE_PINNED)
            elif expected_pods >= self.big_tenant_pods:
                # replica 0 holds the largest carved slice (carve_meshes
                # gives the remainder devices to the first chunks)
                decision = (0, PLACE_BIG_TENANT)
            else:
                decision = (
                    zlib.crc32(tenant_id.encode()) % self.n, PLACE_HASH
                )
            self._placements[tenant_id] = decision
        SERVE_REPLICA_PLACEMENTS.inc({"reason": decision[1]})
        return decision

    def replica_for(self, tenant_id: str, expected_pods: int = 0) -> SolveService:
        idx, _ = self.place(tenant_id, expected_pods=expected_pods)
        return self.replicas[idx]

    # -- the SolveService surface, routed -------------------------------------

    def register_tenant(self, tenant_id: str, expected_pods: int = 0, **kwargs):
        return self.replica_for(
            tenant_id, expected_pods=expected_pods
        ).register_tenant(tenant_id, **kwargs)

    def submit(self, tenant_id: str, pods, instance_types, templates, **kwargs):
        return self.replica_for(
            tenant_id, expected_pods=len(pods)
        ).submit(tenant_id, pods, instance_types, templates, **kwargs)

    def solve(self, tenant_id: str, pods, instance_types, templates, **kwargs):
        return self.replica_for(
            tenant_id, expected_pods=len(pods)
        ).solve(tenant_id, pods, instance_types, templates, **kwargs)

    def start(self) -> "ReplicaSet":
        for r in self.replicas:
            r.start()
        return self

    def close(self, timeout: float = 10.0) -> None:
        for r in self.replicas:
            r.close(timeout=timeout)

    def healthy(self) -> bool:
        return all(r.healthy() for r in self.replicas)

    # -- introspection --------------------------------------------------------

    def placements(self) -> Dict[str, Tuple[int, str]]:
        with self._lock:
            return dict(self._placements)

    def snapshot(self) -> Dict:
        placed = self.placements()
        reasons: Dict[str, int] = {}
        for _, reason in placed.values():
            reasons[reason] = reasons.get(reason, 0) + 1
        return {
            "replicas": [r.snapshot() for r in self.replicas],
            "placements": len(placed),
            "placement_reasons": reasons,
        }

    def summary(self) -> Dict:
        out: Dict = {"replicas": self.n, "placements": len(self._placements)}
        totals: Dict[str, int] = {}
        for r in self.replicas:
            for key, value in r.summary().items():
                if isinstance(value, (int, float)) and not isinstance(value, bool):
                    totals[key] = totals.get(key, 0) + value
        out.update(totals)
        out["healthy"] = self.healthy()
        return out
