"""Shared per-shape program pools: the co-batch rider index at fleet scale.

The dispatcher used to find co-batch riders by sweeping the WHOLE tenant
rotation on every dispatch — O(registered) work that thrashes at 1,000
tenants (990 idle streams scanned per decision for nothing). This pool keeps
a process-cheap index from shape FAMILY to the ready tenants whose head
request could ride a stacked dispatch of that family, maintained
incrementally at enqueue/pop time. Gathering riders is then O(family), and a
family is by construction a subset of the backlogged streams.

The family key is a coarse host-side predictor of padded-program shape
(pod-axis bucket, claim-slot bucket, catalog sizes — the axes
ops/padding.py buckets by). It deliberately over-groups: serve/batch.py
stacked_solve still computes the EXACT padded shape key per lane and stands
mismatched lanes down to solo, so a false family hit costs one wasted
candidate scan, never a wrong stack. Tenant-private state is untouched —
the pool indexes requests, it never shares solver state across tenants
(that remains the round-17 isolation contract).

Guarded by the service lock (the dispatcher and submitters already hold it
at every call site); no locking of its own.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from karpenter_tpu.ops.padding import claim_axis_bucket, pod_axis_bucket


def shape_family(request) -> Tuple:
    """Coarse padded-shape family of a request: requests in different
    families can never stack, requests in the same family usually can."""
    n = max(1, len(request.pods))
    return (
        pod_axis_bucket(n),
        claim_axis_bucket(n),
        len(request.instance_types),
        len(request.templates),
    )


class ProgramPool:
    """Index: shape family -> insertion-ordered set of tenant ids whose HEAD
    request is a co-batch candidate of that family."""

    def __init__(self):
        # dict-as-ordered-set: candidates() preserves note order, giving the
        # same first-come rider priority the old rotation sweep had
        self._families: Dict[Tuple, Dict[str, None]] = {}
        self._key_of: Dict[str, Tuple] = {}
        self.noted = 0
        self.cleared = 0

    def note_head(self, tenant_id: str, request, eligible: bool) -> None:
        """(Re)index a tenant's head request. ``eligible`` is the caller's
        batchable() verdict at note time; ineligible heads are only
        de-indexed (solver state can change by dispatch time either way —
        the gather re-verifies batchable before stacking)."""
        self.clear(tenant_id)
        if not eligible:
            return
        key = shape_family(request)
        self._families.setdefault(key, {})[tenant_id] = None
        self._key_of[tenant_id] = key
        self.noted += 1

    def clear(self, tenant_id: str) -> None:
        key = self._key_of.pop(tenant_id, None)
        if key is None:
            return
        family = self._families.get(key)
        if family is not None:
            family.pop(tenant_id, None)
            if not family:
                del self._families[key]
        self.cleared += 1

    def key_of(self, tenant_id: str) -> Optional[Tuple]:
        return self._key_of.get(tenant_id)

    def candidates(self, key: Tuple) -> Tuple[str, ...]:
        """Tenant ids whose head request sits in this family, note order."""
        family = self._families.get(key)
        return tuple(family) if family else ()

    def families(self) -> int:
        return len(self._families)

    def indexed(self) -> int:
        return len(self._key_of)

    def snapshot(self) -> Dict:
        return {
            "families": self.families(),
            "indexed": self.indexed(),
            "noted": self.noted,
            "cleared": self.cleared,
        }
