"""Informers — watch-stream pumps from the kube store into the Cluster cache.

Equivalent of reference pkg/controllers/state/informer/{node,pod,nodeclaim,
nodepool,daemonset}.go: five thin controllers whose only job is to translate
ADDED/MODIFIED/DELETED watch events into Cluster updates. With the in-memory
kube client the watch delivery is synchronous, so the cache is consistent the
moment a write returns — `Cluster.synced()` still guards the crash-recovery
path where a Cluster is attached to a pre-populated store.
"""

from __future__ import annotations

from karpenter_tpu.apis.nodeclaim import NodeClaim
from karpenter_tpu.apis.nodepool import NodePool
from karpenter_tpu.apis.objects import DaemonSet, Node, Pod
from karpenter_tpu.kube.client import DELETED, KubeClient
from karpenter_tpu.state.cluster import Cluster


def start_informers(kube: KubeClient, cluster: Cluster) -> None:
    """Register all five informers, replaying current store contents
    (LIST+WATCH)."""

    def on_node(event: str, obj: Node):
        if event == DELETED:
            cluster.delete_node(obj.metadata.name)
        else:
            cluster.update_node(obj)

    def on_nodeclaim(event: str, obj: NodeClaim):
        if event == DELETED:
            cluster.delete_node_claim(obj.metadata.name)
        else:
            cluster.update_node_claim(obj)

    def on_pod(event: str, obj: Pod):
        if event == DELETED:
            cluster.delete_pod(f"{obj.metadata.namespace}/{obj.metadata.name}")
        else:
            cluster.update_pod(obj)

    def on_daemonset(event: str, obj: DaemonSet):
        if event == DELETED:
            cluster.delete_daemonset(f"{obj.metadata.namespace}/{obj.metadata.name}")
        else:
            cluster.update_daemonset(obj)

    def on_nodepool(event: str, obj: NodePool):
        # any NodePool change invalidates consolidation decisions
        # (informer/nodepool.go)
        cluster.mark_unconsolidated()

    kube.watch(Node, on_node)
    kube.watch(NodeClaim, on_nodeclaim)
    kube.watch(Pod, on_pod)
    kube.watch(DaemonSet, on_daemonset)
    kube.watch(NodePool, on_nodepool)
