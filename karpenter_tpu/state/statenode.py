"""StateNode — the Node + NodeClaim union view with resource accounting.

Equivalent of reference pkg/controllers/state/statenode.go. A StateNode exists
as soon as either the NodeClaim or the Node object is known and fuses both
sides: before the node registers, capacity/taints come from the claim; after,
from the node. `available = allocatable - pod requests` (statenode.go:259-261)
is the quantity every scheduling and consolidation decision reads.
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.nodeclaim import NodeClaim
from karpenter_tpu.apis.objects import NO_SCHEDULE, Node, Pod, Taint
from karpenter_tpu.scheduling.hostports import HostPort, get_host_ports
from karpenter_tpu.scheduling.taints import KNOWN_EPHEMERAL_TAINTS, Taints
from karpenter_tpu.utils import resources as res


def disruption_taint() -> Taint:
    """The karpenter.tpu/disruption:NoSchedule=disrupting taint
    (reference v1beta1/taints.go)."""
    return Taint(
        key=wk.DISRUPTION_TAINT_KEY,
        effect=NO_SCHEDULE,
        value=wk.DISRUPTING_NO_SCHEDULE_TAINT_VALUE,
    )


class StateNode:
    def __init__(self, node: Optional[Node] = None, node_claim: Optional[NodeClaim] = None):
        self.node = node
        self.node_claim = node_claim
        # pod key -> resource list (terminal/terminating pods are not tracked)
        self.pod_requests: Dict[str, Dict[str, float]] = {}
        self.pod_limits: Dict[str, Dict[str, float]] = {}
        # subset of pod_requests owned by daemonsets (statenode.go:64-66)
        self.daemonset_requests: Dict[str, Dict[str, float]] = {}
        self.daemonset_limits: Dict[str, Dict[str, float]] = {}
        self.host_port_usage: Dict[str, List[HostPort]] = {}
        self.mark_for_deletion = False
        self.nominated_until: float = 0.0

    # -- identity -------------------------------------------------------------

    @property
    def name(self) -> str:
        if self.node is not None:
            return self.node.metadata.name
        if self.node_claim is not None:
            return self.node_claim.status.node_name or self.node_claim.metadata.name
        return ""

    @property
    def provider_id(self) -> str:
        if self.node is not None and self.node.spec.provider_id:
            return self.node.spec.provider_id
        if self.node_claim is not None:
            return self.node_claim.status.provider_id
        return ""

    def labels(self) -> Dict[str, str]:
        # registered node labels win; claim labels fill the pre-registration gap
        out: Dict[str, str] = {}
        if self.node_claim is not None:
            out.update(self.node_claim.metadata.labels)
        if self.node is not None:
            out.update(self.node.metadata.labels)
        return out

    def annotations(self) -> Dict[str, str]:
        out: Dict[str, str] = {}
        if self.node_claim is not None:
            out.update(self.node_claim.metadata.annotations)
        if self.node is not None:
            out.update(self.node.metadata.annotations)
        return out

    @property
    def nodepool_name(self) -> Optional[str]:
        return self.labels().get(wk.NODEPOOL_LABEL_KEY)

    # -- lifecycle predicates (statenode.go:206-230) --------------------------

    def managed(self) -> bool:
        """Owned by this framework: a NodeClaim exists or the node carries the
        nodepool label."""
        return self.node_claim is not None or wk.NODEPOOL_LABEL_KEY in self.labels()

    def registered(self) -> bool:
        if self.node is None:
            return False
        return self.node.metadata.labels.get(wk.NODE_REGISTERED_LABEL_KEY) == "true"

    def initialized(self) -> bool:
        if self.node is None:
            return False
        return self.node.metadata.labels.get(wk.NODE_INITIALIZED_LABEL_KEY) == "true"

    def marked_for_deletion(self) -> bool:
        """Deleting, or tracked by an in-flight disruption command
        (statenode.go:291-299)."""
        if self.mark_for_deletion:
            return True
        if self.node_claim is not None and self.node_claim.metadata.deletion_timestamp is not None:
            return True
        return self.node is not None and self.node.metadata.deletion_timestamp is not None

    def nominate(self, until: float) -> None:
        self.nominated_until = until

    def nominated(self, now: float) -> bool:
        return self.nominated_until > now

    # -- resources (statenode.go:232-276) -------------------------------------

    def capacity(self) -> Dict[str, float]:
        """Claim capacity until the node registers (the claim knows the
        instance type's shape before kubelet reports it)."""
        if not self.registered() and self.node_claim is not None:
            return dict(self.node_claim.status.capacity)
        if self.node is not None:
            return dict(self.node.status.capacity)
        if self.node_claim is not None:
            return dict(self.node_claim.status.capacity)
        return {}

    def allocatable(self) -> Dict[str, float]:
        if not self.registered() and self.node_claim is not None:
            return dict(self.node_claim.status.allocatable)
        if self.node is not None:
            return dict(self.node.status.allocatable)
        if self.node_claim is not None:
            return dict(self.node_claim.status.allocatable)
        return {}

    def pod_request_total(self) -> Dict[str, float]:
        return res.merge(*self.pod_requests.values()) if self.pod_requests else {}

    def daemonset_request_total(self) -> Dict[str, float]:
        return (
            res.merge(*self.daemonset_requests.values()) if self.daemonset_requests else {}
        )

    def available(self) -> Dict[str, float]:
        """allocatable - Σ pod requests (statenode.go:259-261)."""
        return res.subtract(self.allocatable(), self.pod_request_total())

    # -- taints (statenode.go:183-204) ----------------------------------------

    def taints(self) -> Taints:
        """Until initialized, a managed node's taints come from the claim spec
        (kubelet hasn't synced yet) and startup taints are carved out; known
        ephemeral taints are always ignored."""
        ephemeral = list(KNOWN_EPHEMERAL_TAINTS)
        use_claim = not self.initialized() and self.managed() and self.node_claim is not None
        if use_claim:
            ephemeral.extend(self.node_claim.spec.startup_taints)
            source = list(self.node_claim.spec.taints)
        elif self.node is not None:
            source = list(self.node.spec.taints)
        else:
            source = []
        return Taints(t for t in source if not any(t.match(e) for e in ephemeral))

    # -- pod bookkeeping (cluster.updateNodeUsageFromPod) ---------------------

    def update_for_pod(self, pod: Pod, is_daemonset: bool) -> None:
        key = pod.key()
        self.pod_requests[key] = res.pod_requests(pod)
        self.pod_limits[key] = res.pod_limits(pod)
        if is_daemonset:
            self.daemonset_requests[key] = res.pod_requests(pod)
            self.daemonset_limits[key] = res.pod_limits(pod)
        ports = get_host_ports(pod)
        if ports:
            self.host_port_usage[key] = ports
        else:
            self.host_port_usage.pop(key, None)

    def cleanup_for_pod(self, pod_key: str) -> None:
        self.pod_requests.pop(pod_key, None)
        self.pod_limits.pop(pod_key, None)
        self.daemonset_requests.pop(pod_key, None)
        self.daemonset_limits.pop(pod_key, None)
        self.host_port_usage.pop(pod_key, None)

    def host_ports(self) -> List[HostPort]:
        out: List[HostPort] = []
        for ports in self.host_port_usage.values():
            out.extend(ports)
        return out

    def pod_keys(self) -> List[str]:
        return list(self.pod_requests)

    def deep_copy(self) -> "StateNode":
        return copy.deepcopy(self)

    def __repr__(self) -> str:
        return (
            f"StateNode(name={self.name!r}, provider_id={self.provider_id!r}, "
            f"pods={len(self.pod_requests)})"
        )
