from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.state.statenode import StateNode

__all__ = ["Cluster", "StateNode"]
