"""Cluster — the thread-safe in-memory mirror of cluster state.

Equivalent of reference pkg/controllers/state/cluster.go. All durable state
lives in the kube store; this cache is rebuilt from LIST/WATCH on startup
(informer.py) and gated by `synced()` before any provisioning or disruption
decision runs (cluster.go:89-123). Snapshots handed to the scheduler are deep
copies (cluster.go:161-168) so a simulation can never corrupt live state.
"""

from __future__ import annotations

import copy
import threading
from typing import Dict, List, Optional

from karpenter_tpu.apis.nodeclaim import NodeClaim
from karpenter_tpu.apis.objects import DaemonSet, Node, ObjectMeta, Pod
from karpenter_tpu.kube.client import KubeClient
from karpenter_tpu.state.statenode import StateNode
from karpenter_tpu.utils import pod as podutil
from karpenter_tpu.utils.clock import Clock

# How long a nomination protects a node from consolidation: 2x the max batch
# window (cluster.go nominationWindow, 20s with default options).
NOMINATION_WINDOW_SECONDS = 20.0

# Forced consolidation revisit period (cluster.go:299-325).
CONSOLIDATION_TIMEOUT_SECONDS = 300.0


class Cluster:
    def __init__(self, kube: KubeClient, clock: Clock):
        self._kube = kube
        self._clock = clock
        self._lock = threading.RLock()
        # state key (providerID, or "node/<name>" pre-providerID) -> StateNode
        self._nodes: Dict[str, StateNode] = {}
        self._node_name_to_key: Dict[str, str] = {}
        self._claim_name_to_key: Dict[str, str] = {}
        # pod key -> state key of the node the pod is bound to
        self._bindings: Dict[str, str] = {}
        # pod key -> Pod for pods with required anti-affinity (cluster.go:128-144)
        self._anti_affinity_pods: Dict[str, Pod] = {}
        # daemonset key -> template Pod (daemon overhead source)
        self._daemonsets: Dict[str, Pod] = {}
        self._unconsolidated_at: float = clock.now()
        self._consolidated_at: float = 0.0
        self._consolidated: bool = False

    # -- sync gate (cluster.go:89-123) ----------------------------------------

    def synced(self) -> bool:
        """True when every NodeClaim and Node in the store is reflected here.
        The informers in this framework are synchronous, so this is primarily
        the crash-recovery / startup gate."""
        # List outside the cluster lock: watch emission holds the kube lock and
        # then takes ours, so taking them in the opposite order here would be
        # an ABBA deadlock.
        claims = self._kube.list(NodeClaim)
        nodes = self._kube.list(Node)
        with self._lock:
            for claim in claims:
                # a claim that hasn't resolved its providerID hasn't resolved
                # its status: decisions on top of it would race the launch
                # (cluster.go:106-110)
                if not claim.status.provider_id:
                    return False
                if claim.metadata.name not in self._claim_name_to_key:
                    return False
            for node in nodes:
                if node.metadata.name not in self._node_name_to_key:
                    return False
            return True

    # -- snapshots ------------------------------------------------------------

    def nodes(self) -> List[StateNode]:
        """Deep-copy snapshot (cluster.go:161-168)."""
        with self._lock:
            return [n.deep_copy() for n in self._nodes.values()]

    def node_for_name(self, name: str) -> Optional[StateNode]:
        with self._lock:
            key = self._node_name_to_key.get(name)
            return self._nodes[key].deep_copy() if key is not None else None

    def node_for_claim(self, claim_name: str) -> Optional[StateNode]:
        with self._lock:
            key = self._claim_name_to_key.get(claim_name)
            return self._nodes[key].deep_copy() if key is not None else None

    def anti_affinity_pods(self) -> List[Pod]:
        with self._lock:
            return [copy.deepcopy(p) for p in self._anti_affinity_pods.values()]

    def daemonset_pods(self) -> List[Pod]:
        with self._lock:
            return [copy.deepcopy(p) for p in self._daemonsets.values()]

    def pods_bound_to(self, node_name: str) -> List[str]:
        """Pod keys currently tracked against a node."""
        with self._lock:
            key = self._node_name_to_key.get(node_name)
            if key is None:
                return []
            return self._nodes[key].pod_keys()

    # -- node / nodeclaim updates (cluster.go:220-294) ------------------------

    def _state_key(self, provider_id: str, node_name: str = "", claim_name: str = "") -> str:
        if provider_id:
            return provider_id
        if node_name:
            return f"node/{node_name}"
        return f"nodeclaim/{claim_name}"

    def _rekey(self, old_key: str, new_key: str) -> None:
        """A claim/node gained its providerID: migrate the shell entry."""
        state = self._nodes.pop(old_key)
        existing = self._nodes.get(new_key)
        if existing is not None:
            # merge the two views: object references from whichever side has
            # them, and the union of both sides' pod bookkeeping
            if state.node is not None:
                existing.node = state.node
            if state.node_claim is not None:
                existing.node_claim = state.node_claim
            existing.pod_requests.update(state.pod_requests)
            existing.pod_limits.update(state.pod_limits)
            existing.daemonset_requests.update(state.daemonset_requests)
            existing.daemonset_limits.update(state.daemonset_limits)
            existing.host_port_usage.update(state.host_port_usage)
            existing.mark_for_deletion = existing.mark_for_deletion or state.mark_for_deletion
            existing.nominated_until = max(existing.nominated_until, state.nominated_until)
            state = existing
        self._nodes[new_key] = state
        for mapping in (self._node_name_to_key, self._claim_name_to_key):
            for name, key in list(mapping.items()):
                if key == old_key:
                    mapping[name] = new_key
        for pod_key, key in list(self._bindings.items()):
            if key == old_key:
                self._bindings[pod_key] = new_key

    def update_node(self, node: Node) -> None:
        with self._lock:
            name = node.metadata.name
            key = self._state_key(node.spec.provider_id, node_name=name)
            old_key = self._node_name_to_key.get(name)
            if old_key is not None and old_key != key:
                self._rekey(old_key, key)
            state = self._nodes.get(key)
            if state is None:
                # a NodeClaim with the same providerID may already hold state
                state = StateNode()
                self._nodes[key] = state
            state.node = node
            self._node_name_to_key[name] = key
            self._mark_unconsolidated_locked()

    def delete_node(self, name: str) -> None:
        with self._lock:
            key = self._node_name_to_key.pop(name, None)
            if key is None:
                return
            state = self._nodes.get(key)
            if state is not None:
                state.node = None
                if state.node_claim is None:
                    self._drop_state(key)
            self._mark_unconsolidated_locked()

    def update_node_claim(self, claim: NodeClaim) -> None:
        with self._lock:
            name = claim.metadata.name
            key = self._state_key(claim.status.provider_id, claim_name=name)
            old_key = self._claim_name_to_key.get(name)
            if old_key is not None and old_key != key:
                self._rekey(old_key, key)
            state = self._nodes.get(key)
            if state is None:
                state = StateNode()
                self._nodes[key] = state
            state.node_claim = claim
            self._claim_name_to_key[name] = key
            self._mark_unconsolidated_locked()

    def delete_node_claim(self, name: str) -> None:
        with self._lock:
            key = self._claim_name_to_key.pop(name, None)
            if key is None:
                return
            state = self._nodes.get(key)
            if state is not None:
                state.node_claim = None
                if state.node is None:
                    self._drop_state(key)
            self._mark_unconsolidated_locked()

    def _drop_state(self, key: str) -> None:
        self._nodes.pop(key, None)
        for pod_key, k in list(self._bindings.items()):
            if k == key:
                del self._bindings[pod_key]

    # -- pod updates (cluster.go:262-294, 547-557) ----------------------------

    def update_pod(self, pod: Pod) -> None:
        with self._lock:
            if podutil.is_terminal(pod) or podutil.is_terminating(pod):
                self._cleanup_pod(pod.key())
            else:
                self._update_pod_binding(pod)
            if podutil.has_required_pod_anti_affinity(pod):
                if podutil.is_terminal(pod) or podutil.is_terminating(pod):
                    self._anti_affinity_pods.pop(pod.key(), None)
                else:
                    self._anti_affinity_pods[pod.key()] = pod
            self._mark_unconsolidated_locked()

    def _update_pod_binding(self, pod: Pod) -> None:
        pod_key = pod.key()
        node_name = pod.spec.node_name
        if not node_name:
            return
        key = self._node_name_to_key.get(node_name)
        if key is None:
            # pod bound to a node we haven't seen yet: create a shell entry
            key = f"node/{node_name}"
            shell = StateNode(node=Node(metadata=ObjectMeta(name=node_name)))
            self._nodes[key] = shell
            self._node_name_to_key[node_name] = key
        old_key = self._bindings.get(pod_key)
        if old_key is not None and old_key != key:
            old = self._nodes.get(old_key)
            if old is not None:
                old.cleanup_for_pod(pod_key)
        newly_bound = old_key != key
        self._bindings[pod_key] = key
        self._nodes[key].update_for_pod(pod, podutil.is_owned_by_daemonset(pod))
        if newly_bound:
            # a pod landed: its nomination (if any) is spent; status-only
            # updates of already-bound pods must not spend it
            self._nodes[key].nominated_until = 0.0

    def delete_pod(self, pod_key: str) -> None:
        with self._lock:
            self._cleanup_pod(pod_key)
            self._anti_affinity_pods.pop(pod_key, None)
            self._mark_unconsolidated_locked()

    def _cleanup_pod(self, pod_key: str) -> None:
        key = self._bindings.pop(pod_key, None)
        if key is not None:
            state = self._nodes.get(key)
            if state is not None:
                state.cleanup_for_pod(pod_key)

    # -- daemonsets ------------------------------------------------------------

    def update_daemonset(self, ds: DaemonSet) -> None:
        with self._lock:
            pod = Pod(metadata=ObjectMeta(name=f"{ds.metadata.name}-template",
                                          namespace=ds.metadata.namespace),
                      spec=ds.pod_template_spec)
            self._daemonsets[f"{ds.metadata.namespace}/{ds.metadata.name}"] = pod
            self._mark_unconsolidated_locked()

    def delete_daemonset(self, ds_key: str) -> None:
        with self._lock:
            self._daemonsets.pop(ds_key, None)
            self._mark_unconsolidated_locked()

    # -- nomination (cluster.go:172-190) --------------------------------------

    def nominate_node_for_pod(self, node_name: str) -> None:
        with self._lock:
            key = self._node_name_to_key.get(node_name)
            if key is not None:
                self._nodes[key].nominate(self._clock.now() + NOMINATION_WINDOW_SECONDS)

    def is_nominated(self, node_name: str) -> bool:
        with self._lock:
            key = self._node_name_to_key.get(node_name)
            return key is not None and self._nodes[key].nominated(self._clock.now())

    # -- deletion marks (disruption in flight) --------------------------------

    def mark_for_deletion(self, *provider_ids: str) -> None:
        with self._lock:
            for pid in provider_ids:
                state = self._nodes.get(pid)
                if state is not None:
                    state.mark_for_deletion = True
            self._mark_unconsolidated_locked()

    def unmark_for_deletion(self, *provider_ids: str) -> None:
        with self._lock:
            for pid in provider_ids:
                state = self._nodes.get(pid)
                if state is not None:
                    state.mark_for_deletion = False
            self._mark_unconsolidated_locked()

    # -- consolidation timestamp (cluster.go:299-325) -------------------------

    def _mark_unconsolidated_locked(self) -> None:
        self._unconsolidated_at = self._clock.now()
        self._consolidated = False

    def mark_unconsolidated(self) -> None:
        with self._lock:
            self._mark_unconsolidated_locked()

    def mark_consolidated(self) -> float:
        with self._lock:
            self._consolidated = True
            self._consolidated_at = self._clock.now()
            return self._unconsolidated_at

    def consolidated(self) -> bool:
        """False if state changed since mark_consolidated, or the forced
        5-minute revisit window elapsed since that mark."""
        with self._lock:
            if not self._consolidated:
                return False
            return self._clock.now() - self._consolidated_at < CONSOLIDATION_TIMEOUT_SECONDS

    # -- test helpers ----------------------------------------------------------

    def reset(self) -> None:
        with self._lock:
            self._nodes.clear()
            self._node_name_to_key.clear()
            self._claim_name_to_key.clear()
            self._bindings.clear()
            self._anti_affinity_pods.clear()
            self._daemonsets.clear()
            self._mark_unconsolidated_locked()
