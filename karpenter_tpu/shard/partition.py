"""Host-side independence analysis: split a scheduling batch into
provably independent sub-problems.

Two pods interact during a solve only through *shared state*:

- an **existing node** both could land on (capacity, host ports, CSI
  attach counts),
- a **topology group** that counts both (spread skew, affinity, or
  anti-affinity domains),
- a **finite-template budget** both draw from (``remaining_resources``
  is a shared NodePool headroom counter), or
- a **shared claim** — but claims minted from infinite templates are
  fresh nodes, so pods in different partitions simply open separate
  claims and the post-solve merge (shard/solve.py) may re-join them.
  Shared claims therefore never force co-partitioning by themselves.

We build a union-find over pod *classes* (pods with identical
constraint signature — and identical override row when an override is
in play — encode to the same row modulo requests, so one representative
answers every compatibility question for the class) plus one element
per node, per topology group, and per finite template. An edge is the
exact host-side compatibility check the oracle uses: taints via
``Taints.tolerates`` (empty error list = tolerated) and requirements
via ``Requirements.is_compatible``. Edges only ever OVER-approximate
interaction — a spurious edge costs balance, a missing edge would cost
correctness, so every check mirrors solver/oracle.py verbatim.

Components that touch no node, group, or template element are
**splittable**: their pods share nothing, so the planner may chunk them
across partitions freely for balance (the provisioning-style fleet
batches that motivate this subsystem are almost entirely splittable).
All other components are atomic and placed whole via LPT.

The two-stage count classifies non-decomposable inputs: if the batch
only collapses to one component once finite-template edges are applied,
the standdown reason is ``cross-partition-claims`` (shared budget);
if it is monolithic even without them, ``single-partition``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.objects import Pod
from karpenter_tpu.provisioning.topology import TopologyGroup
from karpenter_tpu.scheduling import Requirements, pod_requirements
from karpenter_tpu.solver.encode import (
    NodeInfo,
    TemplateInfo,
    _reqs_digest,
    constraint_signature,
)
from karpenter_tpu import shard as _shard_flags


class _UnionFind:
    __slots__ = ("parent", "rank")

    def __init__(self, n: int):
        self.parent = list(range(n))
        self.rank = [0] * n

    def find(self, x: int) -> int:
        parent = self.parent
        root = x
        while parent[root] != root:
            root = parent[root]
        while parent[x] != root:  # path compression
            parent[x], x = root, parent[x]
        return root

    def union(self, a: int, b: int) -> None:
        ra, rb = self.find(a), self.find(b)
        if ra == rb:
            return
        if self.rank[ra] < self.rank[rb]:
            ra, rb = rb, ra
        self.parent[rb] = ra
        if self.rank[ra] == self.rank[rb]:
            self.rank[ra] += 1


@dataclass
class Partition:
    """One independent sub-problem: row maps into the original batch.
    Both index lists preserve original order so per-partition decode maps
    straight back to caller indices."""

    pod_idx: List[int] = field(default_factory=list)
    node_idx: List[int] = field(default_factory=list)


@dataclass
class PartitionPlan:
    """Output of partition_pods. When ``reason`` is set the batch did not
    decompose and ``parts`` is empty — the caller stands down."""

    parts: List[Partition] = field(default_factory=list)
    reason: Optional[str] = None
    # telemetry: how the component graph looked before balancing
    atomic_components: int = 0
    splittable_pods: int = 0
    dropped_nodes: int = 0  # nodes no pod in the batch can reach


def _effective_reqs(
    pod: Pod, i: int, override: Optional[Sequence[Requirements]]
) -> Requirements:
    # Mirrors the encode fold: the device solve judges node/template
    # compatibility against the override row when one is supplied,
    # else preference-inclusive pod requirements.
    if override is not None:
        return override[i]
    return pod_requirements(pod)


def partition_pods(
    pods: Sequence[Pod],
    templates: Sequence[TemplateInfo],
    nodes: Sequence[NodeInfo],
    groups: Sequence[TopologyGroup],
    n_parts: int,
    pod_requirements_override: Optional[Sequence[Requirements]] = None,
) -> PartitionPlan:
    """Partition ``pods``/``nodes`` into at most ``n_parts`` independent
    sub-problems, or classify why that is impossible."""
    n_pods = len(pods)
    plan = PartitionPlan()
    if n_pods < 2 or n_parts < 2:
        plan.reason = _shard_flags.REASON_SINGLE_PARTITION
        return plan

    # ---- pod classes -------------------------------------------------
    # Same constraint signature (and same override digest when an override
    # is in play) => identical encoded row modulo requests => one
    # representative per class answers every compatibility question.
    class_ids: Dict[object, int] = {}
    pod_class: List[int] = []
    class_rep: List[int] = []  # class -> representative pod index
    for i, p in enumerate(pods):
        key: object = constraint_signature(p)
        if pod_requirements_override is not None:
            key = (key, _reqs_digest(pod_requirements_override[i]))
        ci = class_ids.get(key)
        if ci is None:
            ci = len(class_rep)
            class_ids[key] = ci
            class_rep.append(i)
        pod_class.append(ci)
    n_classes = len(class_rep)

    # ---- union-find elements -----------------------------------------
    # [0, n_classes)                      pod classes
    # [n_classes, +len(nodes))            nodes
    # [.., +len(groups))                  topology groups
    # [.., +len(finite templates))        finite-template budgets
    node_base = n_classes
    group_base = node_base + len(nodes)
    finite_tpls = [ti for ti, t in enumerate(templates) if t.remaining_resources is not None]
    tpl_base = group_base + len(groups)
    uf = _UnionFind(tpl_base + len(finite_tpls))

    reps = [(ci, pods[class_rep[ci]], _effective_reqs(pods[class_rep[ci]], class_rep[ci], pod_requirements_override)) for ci in range(n_classes)]

    # Node edges — exact oracle checks (oracle.py: skip when
    # taints.tolerates returns errors, skip when requirements are
    # incompatible). Any pod class that can land on a node shares its
    # capacity/ports/attach state with every other such class.
    node_reached = [False] * len(nodes)
    for ni, n in enumerate(nodes):
        for ci, rep, reqs in reps:
            if n.taints.tolerates(rep):
                continue
            if not n.requirements.is_compatible(reqs):
                continue
            node_reached[ni] = True
            uf.union(ci, node_base + ni)

    # Group edges — membership is per-pod (owners are uid-keyed), memoised
    # by (namespace, labels) exactly like the encode fold's selects cache.
    if groups:
        sel_cache: Dict[Tuple[int, str, Tuple[Tuple[str, str], ...]], bool] = {}
        for i, p in enumerate(pods):
            labels_key = tuple(sorted(p.metadata.labels.items()))
            for gi, tg in enumerate(groups):
                if p.uid in tg.owners:
                    uf.union(pod_class[i], group_base + gi)
                    continue
                ck = (gi, p.namespace, labels_key)
                hit = sel_cache.get(ck)
                if hit is None:
                    hit = sel_cache[ck] = tg.selects(p)
                if hit:
                    uf.union(pod_class[i], group_base + gi)

    # Snapshot BEFORE finite-template edges: distinguishes a batch glued
    # together only by a shared NodePool budget (cross-partition-claims)
    # from one that is monolithic outright (single-partition).
    def _component_stats() -> Tuple[int, int]:
        """(atomic component count, splittable pod count)."""
        comp_pods: Dict[int, int] = {}
        anchored: set = set()
        for ci in range(n_classes):
            comp_pods.setdefault(uf.find(ci), 0)
        for i in range(n_pods):
            comp_pods[uf.find(pod_class[i])] += 1
        for e in range(node_base, len(uf.parent)):
            anchored.add(uf.find(e))
        atomic = sum(1 for r in comp_pods if r in anchored)
        splittable = sum(c for r, c in comp_pods.items() if r not in anchored)
        return atomic, splittable

    def _partitionable(atomic: int, splittable: int) -> bool:
        return atomic >= 2 or (atomic >= 1 and splittable >= 1) or splittable >= 2

    pre_atomic, pre_split = _component_stats()

    # Finite-template edges: remaining_resources is one shared headroom
    # counter, so every class that can mint from the template must solve
    # in the same partition to see the same budget.
    for k, ti in enumerate(finite_tpls):
        t = templates[ti]
        for ci, rep, reqs in reps:
            if t.taints.tolerates(rep):
                continue
            if not t.requirements.is_compatible(reqs, wk.WELL_KNOWN_LABELS):
                continue
            uf.union(ci, tpl_base + k)

    atomic, splittable = _component_stats()
    plan.atomic_components = atomic
    plan.splittable_pods = splittable
    if not _partitionable(atomic, splittable):
        plan.reason = (
            _shard_flags.REASON_CROSS_PARTITION_CLAIMS
            if _partitionable(pre_atomic, pre_split)
            else _shard_flags.REASON_SINGLE_PARTITION
        )
        return plan

    # ---- balance into bins (LPT + splittable backfill) ----------------
    comp_members: Dict[int, List[int]] = {}  # root -> pod indices
    comp_anchored: Dict[int, bool] = {}
    for e in range(node_base, len(uf.parent)):
        comp_anchored[uf.find(e)] = True
    for i in range(n_pods):
        root = uf.find(pod_class[i])
        comp_members.setdefault(root, []).append(i)

    atomic_comps = [(root, m) for root, m in comp_members.items() if comp_anchored.get(root)]
    split_pods = [i for root, m in comp_members.items() if not comp_anchored.get(root) for i in m]

    bins: List[List[int]] = [[] for _ in range(n_parts)]
    bin_root: List[List[int]] = [[] for _ in range(n_parts)]  # roots per bin (node routing)
    loads = [0] * n_parts
    for root, members in sorted(atomic_comps, key=lambda rm: -len(rm[1])):
        b = loads.index(min(loads))
        bins[b].extend(members)
        bin_root[b].append(root)
        loads[b] += len(members)
    # Splittable pods level the bins: repeatedly top up the lightest bin
    # toward the ideal share. Chunked (not one-by-one) to stay O(parts).
    split_pods.sort()
    remaining = len(split_pods)
    pos = 0
    target = (n_pods + n_parts - 1) // n_parts
    order = sorted(range(n_parts), key=lambda b: loads[b])
    for b in order:
        take = min(remaining, max(0, target - loads[b]))
        if take:
            bins[b].extend(split_pods[pos : pos + take])
            loads[b] += take
            pos += take
            remaining -= take
    while remaining:  # rounding leftovers
        b = loads.index(min(loads))
        bins[b].append(split_pods[pos])
        loads[b] += 1
        pos += 1
        remaining -= 1

    # Route each reachable node to the bin owning its component; a node no
    # pod can reach belongs to no sub-problem (it could not have received
    # a pod in the unsharded solve either) and is dropped.
    root_to_bin: Dict[int, int] = {}
    for b, roots in enumerate(bin_root):
        for root in roots:
            root_to_bin[root] = b
    node_bins: List[List[int]] = [[] for _ in range(n_parts)]
    dropped = 0
    for ni in range(len(nodes)):
        if not node_reached[ni]:
            dropped += 1
            continue
        node_bins[root_to_bin[uf.find(node_base + ni)]].append(ni)
    plan.dropped_nodes = dropped

    for b in range(n_parts):
        if bins[b]:
            bins[b].sort()
            plan.parts.append(Partition(pod_idx=bins[b], node_idx=node_bins[b]))
    if len(plan.parts) < 2:
        plan.parts = []
        plan.reason = _shard_flags.REASON_SINGLE_PARTITION
    return plan
