"""Mesh-sharded partitioned solve (KARPENTER_TPU_SHARD).

Fleet-scale batches (100k+ pods) do not fit one dense FFD scan: the pod axis
is the sequential scan length, so a single program's wall time grows linearly
no matter how wide the accelerator is. This package splits a scheduling batch
into provably independent sub-problems (shard/partition.py — the same
constraint-signature independence the wavefront proves per-lane, lifted to
whole subgraphs), encodes each partition against ONE frozen vocabulary,
pads them to a common bucket shape, and runs all of them as ONE
``shard_map``-partitioned program over the device mesh
(parallel/mesh.py shard_sweeps_program), then merges the per-partition claim
landscapes back into a single SolveResult behind the full-level verification
gate (shard/solve.py).

The contract is Karpenter's own: a shard-path bug may cost latency, never
correctness. Every result is gated (device gate per partition + exact
host-side merge checks), and ANY non-decomposable input or gate rejection is
a *classified standdown* — try_shard_solve returns None with a reason
(`solver_shard_fallback_total{reason}`) and the caller runs the ordinary
unsharded path. Flag off, nothing changes: the entry is one env read.
"""

from __future__ import annotations

import os
from typing import Optional

# classified standdown reasons — the bounded label-value set for
# solver_shard_fallback_total and the vocabulary of tests/test_shard_parity.py
REASON_SINGLE_DEVICE = "single-device"
REASON_SMALL_BATCH = "small-batch"
REASON_RELAXABLE = "relaxable"
REASON_UNSUPPORTED_ARGS = "unsupported-args"
REASON_SINGLE_PARTITION = "single-partition"
REASON_CROSS_PARTITION_CLAIMS = "cross-partition-claims"
REASON_SHAPE_MISMATCH = "shape-mismatch"
REASON_SLOT_OVERFLOW = "slot-overflow"
REASON_MERGE_REJECTED = "merge-rejected"
REASON_ERROR = "error"

REASONS = (
    REASON_SINGLE_DEVICE, REASON_SMALL_BATCH, REASON_RELAXABLE,
    REASON_UNSUPPORTED_ARGS, REASON_SINGLE_PARTITION,
    REASON_CROSS_PARTITION_CLAIMS, REASON_SHAPE_MISMATCH,
    REASON_SLOT_OVERFLOW, REASON_MERGE_REJECTED, REASON_ERROR,
)


def enabled() -> bool:
    """KARPENTER_TPU_SHARD, default OFF: the partitioned solve is opt-in
    until the fleet-scale bench history matures. Off = zero overhead and a
    bit-identical dispatch path (the census pin holds the proof)."""
    return os.environ.get("KARPENTER_TPU_SHARD", "0") not in ("", "0")


def min_pods() -> int:
    """KARPENTER_TPU_SHARD_MIN_PODS: batches below this never shard — the
    partition/merge overhead only amortizes on large batches. Tests lower it
    to exercise the path on small corpora."""
    try:
        return int(os.environ.get("KARPENTER_TPU_SHARD_MIN_PODS", "512"))
    except ValueError:
        return 512


def min_devices() -> int:
    """KARPENTER_TPU_SHARD_MIN_DEVICES: the smallest mesh worth sharding
    over (1-device 'meshes' only add dispatch overhead)."""
    try:
        return int(os.environ.get("KARPENTER_TPU_SHARD_MIN_DEVICES", "2"))
    except ValueError:
        return 2


def target_partitions(n_devices: int) -> int:
    """KARPENTER_TPU_SHARD_PARTITIONS: how many partitions to balance the
    component graph into (0 = one per mesh device, the default). More
    partitions than devices round-robin onto the mesh axis; fewer waste
    devices."""
    try:
        knob = int(os.environ.get("KARPENTER_TPU_SHARD_PARTITIONS", "0"))
    except ValueError:
        knob = 0
    return knob if knob > 0 else n_devices


def max_partition_pods() -> int:
    """KARPENTER_TPU_SHARD_MAX_PART_PODS: hard ceiling on one partition's
    pod count (0 = no ceiling). A partition above the ceiling means the
    component graph did not decompose enough to be worth padding — the
    caller stands down to the unsharded path instead of running one huge
    lane plus many tiny ones."""
    try:
        return int(os.environ.get("KARPENTER_TPU_SHARD_MAX_PART_PODS", "0"))
    except ValueError:
        return 0


def merge_enabled() -> bool:
    """KARPENTER_TPU_SHARD_MERGE, default ON: compact cross-partition claims
    with identical narrowed requirements into shared claims after the solve
    (shard/solve.py _merge_claims). Off = claims pass through concatenated
    (more launched nodes, never an invalid placement)."""
    return os.environ.get("KARPENTER_TPU_SHARD_MERGE", "1") not in ("", "0")


def full_validate_max() -> int:
    """KARPENTER_TPU_SHARD_VALIDATE_MAX: run the float64 host validator at
    full level over the MERGED result when the batch is at most this many
    pods (belt-and-braces over the per-partition device gates; the merge
    step's own checks are exact either way). 0 disables; large batches rely
    on the device gates + the supervisor's configured validation."""
    try:
        return int(os.environ.get("KARPENTER_TPU_SHARD_VALIDATE_MAX", "4096"))
    except ValueError:
        return 4096


from karpenter_tpu.shard.partition import (  # noqa: E402
    Partition,
    PartitionPlan,
    partition_pods,
)
from karpenter_tpu.shard.solve import try_shard_solve  # noqa: E402

__all__ = [
    "enabled", "min_pods", "min_devices", "target_partitions",
    "max_partition_pods", "merge_enabled", "full_validate_max",
    "Partition", "PartitionPlan", "partition_pods", "try_shard_solve",
    "REASONS",
]
