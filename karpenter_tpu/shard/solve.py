"""Partitioned solve orchestration: encode, pad, dispatch, gate, merge.

``try_shard_solve`` is the single entry the backend calls
(solver/jax_backend.py, KARPENTER_TPU_SHARD). It either returns a complete
SolveResult produced by the mesh-partitioned program, or None after recording
one classified standdown reason (``solver_shard_fallback_total{reason}``) —
the caller then runs the ordinary unsharded path, so nothing here is ever a
correctness dependency.

The pipeline:

1. **Partition** (shard/partition.py): union-find over the exact oracle
   compatibility checks splits the batch into independent sub-problems.
2. **Encode against ONE vocabulary**: every partition encodes with
   ``vocab_pods``/``vocab_reqs``/``vocab_nodes`` seeded from the FULL batch
   and a clone of ONE full-batch Topology (with every node hostname
   registered before cloning), so K/V/R/G/D/PT and the group order are
   identical across partitions by construction — the precondition for
   stacking them into one program.
3. **Pad to a common bucket** (ops/padding.py min_pods/min_nodes/min_runs
   floors), round the lane count up to a mesh multiple with inert
   all-masked lanes, and stack.
4. **Dispatch ONE program** (parallel/mesh.py shard_sweeps_program):
   shard_map lays the partition axis across the mesh so each device runs
   its own sweeps while-loop to local convergence. NO_SLOT in any lane
   escalates the shared claim bucket exactly like the unsharded ladder.
   With KARPENTER_TPU_RELAX2 on, shard_relax2_sweeps_program fuses the
   per-lane convex phase-1 + carried repair instead — as a sharded
   jit(vmap), not shard_map (see its docstring for the SPMD miscompile
   that forces the difference).
5. **Gate per partition**: each lane's decoded result carries its own
   GateContext (the padded tensors it decoded from) through the existing
   full-level device gate — sound because partitions are constraint-disjoint,
   so partition-local invariants ARE the full-problem invariants.
6. **Merge**: per-partition results concatenate on disjoint index sets;
   cross-partition claims may additionally be joined by exact host
   arithmetic (identical narrowed requirements, infinite template, no
   ports/groups/volumes, combined requests fit a shared instance type).
   Any gate violation or merge inconsistency is a ``merge-rejected``
   standdown, never a returned result.

Why scheduled-set parity holds (tests/test_shard_parity.py fuzzes this):
pods in different partitions share no node, no group, and no finite
template budget, so each pod's feasibility is decided by partition-local
state that matches the full solve's state exactly; claims from infinite
templates are always mintable, so separating two pods into different
claims can change claim groupings but never whether a pod schedules.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from karpenter_tpu import shard as flags
from karpenter_tpu.apis import labels as wk
from karpenter_tpu.metrics.registry import (
    COMPILE_CACHE,
    SHARD_FALLBACK,
    SHARD_MERGE_REJECTIONS,
    SHARD_PAD_FRACTION,
    SHARD_PARTITIONS,
    TRANSFER_BYTES,
)
from karpenter_tpu.obs import flight, programs, trace
from karpenter_tpu.ops.ffd_core import (
    KIND_CLAIM,
    KIND_NEW_CLAIM,
    KIND_NODE,
    KIND_NO_SLOT,
    problem_bounds_free,
)
from karpenter_tpu.ops.padding import claim_axis_bucket, pad_problem
from karpenter_tpu.provisioning.preferences import Preferences
from karpenter_tpu.provisioning.topology import Topology
from karpenter_tpu.scheduling.hostports import get_host_ports
from karpenter_tpu.shard.partition import partition_pods
from karpenter_tpu.solver.backend import FAIL_INCOMPATIBLE, Placement, SolveResult
from karpenter_tpu.solver.encode import Encoder, _reqs_digest


def _standdown(solver, reason: str, **info) -> None:
    """Record one classified fallback and return None to the caller."""
    SHARD_FALLBACK.inc({"reason": reason})
    flight.record(flight.KIND_SHARD_STANDDOWN, reason=reason)
    solver.last_shard = {"reason": reason, **info}
    with trace.span("shard_standdown", reason=reason):
        pass
    return None


def _tree_shapes(problem) -> tuple:
    return tuple(
        (tuple(leaf.shape), str(getattr(leaf, "dtype", type(leaf).__name__)))
        for leaf in jax.tree_util.tree_leaves(problem)
    )


def _nbytes(tree) -> int:
    return int(
        sum(getattr(a, "nbytes", 0) for a in jax.tree_util.tree_leaves(tree))
    )


def _free_pods(pods, groups) -> List[bool]:
    """Pods with no host ports and no topology-group membership: the only
    pods whose claims the cross-partition merge may join (everything that
    could make a join observable — port clashes, skew counts, affinity —
    is absent). Memoised by (namespace, labels) like the encode fold."""
    sel_cache: Dict[tuple, bool] = {}
    out = []
    for p in pods:
        if get_host_ports(p):
            out.append(False)
            continue
        free = True
        labels_key = None
        for gi, tg in enumerate(groups):
            if p.uid in tg.owners:
                free = False
                break
            if labels_key is None:
                labels_key = tuple(sorted(p.metadata.labels.items()))
            ck = (gi, p.namespace, labels_key)
            hit = sel_cache.get(ck)
            if hit is None:
                hit = sel_cache[ck] = tg.selects(p)
            if hit:
                free = False
                break
        out.append(free)
    return out


def _merge_claims(
    out: SolveResult,
    claim_lanes: List[int],
    templates,
    instance_types,
    free: List[bool],
) -> int:
    """Conservatively join claims from DIFFERENT partitions that are
    observably identical: same template (with no finite budget), identical
    narrowed Requirements, only merge-free pods, and combined requests —
    counting the daemonset overhead once — fit some instance type both
    claims kept. Every check is exact host arithmetic; claims the device
    kept apart WITHIN a lane stay apart (the device already decided their
    packing). Returns the number of joins performed."""
    by_key: Dict[tuple, List[int]] = {}
    for ci, claim in enumerate(out.new_claims):
        tpl = templates[claim.template_index]
        if tpl.remaining_resources is not None:
            continue
        if not all(free[pi] for pi in claim.pod_indices):
            continue
        key = (claim.template_index, _reqs_digest(claim.requirements))
        by_key.setdefault(key, []).append(ci)

    merged_into: Dict[int, int] = {}
    joins = 0
    for (tpl_idx, _digest), members in by_key.items():
        overhead = templates[tpl_idx].daemon_overhead
        for i, ci in enumerate(members):
            if ci in merged_into:
                continue
            a = out.new_claims[ci]
            for cj in members[i + 1 :]:
                if cj in merged_into or claim_lanes[cj] == claim_lanes[ci]:
                    continue
                b = out.new_claims[cj]
                shared_its = sorted(
                    set(a.instance_type_indices) & set(b.instance_type_indices)
                )
                if not shared_its:
                    continue
                combined: Dict[str, float] = dict(a.requests)
                for name, v in b.requests.items():
                    combined[name] = combined.get(name, 0.0) + v
                for name, v in overhead.items():
                    if name in combined:
                        combined[name] = combined[name] - float(v)
                fits = [
                    ti
                    for ti in shared_its
                    if all(
                        v <= instance_types[ti].allocatable().get(name, 0.0)
                        for name, v in combined.items()
                        if v > 0
                    )
                ]
                if not fits:
                    continue
                a.pod_indices.extend(b.pod_indices)
                a.instance_type_indices = fits
                a.requests = {k: v for k, v in combined.items() if v > 0}
                merged_into[cj] = ci
                joins += 1
    if merged_into:
        out.new_claims = [
            c for ci, c in enumerate(out.new_claims) if ci not in merged_into
        ]
    return joins


def try_shard_solve(
    solver,
    pods,
    instance_types,
    templates,
    nodes,
    pod_requirements_override,
    topology,
    cluster_pods,
    domains,
    pod_volumes,
) -> Optional[SolveResult]:
    """The KARPENTER_TPU_SHARD entry (see module docstring). ``solver`` is
    the JaxSolver — its claim-slot ladder, program-cache counters, and
    ``last_shard`` telemetry are shared with the unsharded path."""
    try:
        return _try_shard_solve(
            solver, pods, instance_types, templates, nodes,
            pod_requirements_override, topology, cluster_pods, domains,
            pod_volumes,
        )
    except Exception as exc:  # noqa: BLE001 — the shard path never raises
        import logging

        logging.getLogger(__name__).warning(
            "shard: partitioned solve degraded to unsharded path: %s: %s",
            type(exc).__name__, exc, exc_info=True,
        )
        return _standdown(solver, flags.REASON_ERROR, error=str(exc))


def _try_shard_solve(
    solver, pods, instance_types, templates, nodes,
    pod_requirements_override, topology, cluster_pods, domains, pod_volumes,
) -> Optional[SolveResult]:
    from karpenter_tpu.parallel.mesh import (
        default_mesh,
        shard_relax2_sweeps_program,
        shard_sweeps_program,
        stack_problems,
    )

    solver.last_shard = None
    if len(pods) < flags.min_pods():
        return _standdown(solver, flags.REASON_SMALL_BATCH, pods=len(pods))
    mesh = default_mesh(flags.min_devices())
    if mesh is None:
        return _standdown(solver, flags.REASON_SINGLE_DEVICE)
    from karpenter_tpu.solver import jax_backend as jb

    if jb._USE_RUNS:
        # the sharded program is sweeps-only; the runs opt-in keeps the
        # unsharded path it was measured on
        return _standdown(solver, flags.REASON_UNSUPPORTED_ARGS, arg="runs-mode")
    from karpenter_tpu.obs import explain as obs_explain

    if obs_explain.enabled():
        # failure attribution reads end-of-pass bin state the shard path
        # does not fetch — explain cycles keep the unsharded program
        return _standdown(solver, flags.REASON_UNSUPPORTED_ARGS, arg="explain")
    prefs = Preferences(
        tolerate_prefer_no_schedule=any(
            t.effect == "PreferNoSchedule" for tpl in templates for t in tpl.taints
        )
    )
    if prefs.tolerate_prefer_no_schedule or any(
        Preferences.is_relaxable(p) for p in pods
    ):
        # the relaxation ladder re-encodes between passes per pod — that
        # host loop has no partition-stacked equivalent yet
        return _standdown(solver, flags.REASON_RELAXABLE)

    # ONE full-batch topology: every partition solves against a clone, so
    # group order, domain censuses, and G/F shapes are identical lanes;
    # foreign groups are inert (no partition pod matches them — the
    # partitioner co-locates every group's pods).
    topo_full = (
        topology.clone()
        if topology is not None
        else Topology(domains, batch_pods=list(pods), cluster_pods=cluster_pods)
    )
    for n in nodes:
        topo_full.register(wk.LABEL_HOSTNAME, n.name)
    groups = list(topo_full.topologies.values()) + list(
        topo_full.inverse_topologies.values()
    )

    def _plan_for(mesh_):
        # re-invoked after a mesh recarve: partition fan-out tracks the
        # CURRENT device count, so a shrunken mesh gets a shrunken plan
        with trace.span("shard_partition", pods=len(pods)):
            return partition_pods(
                pods, templates, nodes, groups,
                flags.target_partitions(mesh_.devices.size),
                pod_requirements_override,
            )

    plan = _plan_for(mesh)
    if plan.reason is not None:
        return _standdown(
            solver, plan.reason,
            atomic=plan.atomic_components, splittable=plan.splittable_pods,
        )
    max_part = max(len(pt.pod_idx) for pt in plan.parts)
    ceiling = flags.max_partition_pods()
    if 0 < ceiling < max_part:
        return _standdown(
            solver, flags.REASON_SINGLE_PARTITION, dominant=max_part,
        )

    encoder = Encoder(solver.well_known)
    vocab_pods = list(pods)
    max_claims = min(solver.claim_slots, claim_axis_bucket(max_part))
    claim_cap = claim_axis_bucket(max_part)
    n_dev = mesh.devices.size
    from karpenter_tpu.solver import mesh_health

    recarves = 0
    while True:
        padded, metas = [], []
        with trace.span(
            "shard_encode", partitions=len(plan.parts), max_claims=max_claims
        ):
            for part in plan.parts:
                enc = encoder.encode(
                    [pods[i] for i in part.pod_idx],
                    instance_types,
                    templates,
                    [nodes[j] for j in part.node_idx],
                    pod_reqs_override=(
                        [pod_requirements_override[i] for i in part.pod_idx]
                        if pod_requirements_override is not None
                        else None
                    ),
                    topology=topo_full.clone(),
                    num_claim_slots=max_claims,
                    vocab_pods=vocab_pods,
                    vocab_reqs=pod_requirements_override,
                    pod_volumes=(
                        [pod_volumes[i] for i in part.pod_idx]
                        if pod_volumes is not None
                        else None
                    ),
                    vocab_nodes=nodes,
                )
                padded.append(enc)
                metas.append(enc.meta)
            max_n = max(len(pt.node_idx) for pt in plan.parts)
            max_rn = max(e.problem.num_runs for e in padded)
            padded = [
                pad_problem(
                    e.problem, min_pods=max_part, min_nodes=max_n,
                    min_runs=max_rn,
                )
                for e in padded
            ]
        shapes = _tree_shapes(padded[0])
        if any(_tree_shapes(p) != shapes for p in padded[1:]):
            # the one-vocabulary construction should make this impossible;
            # if it ever fires, the unsharded path is the answer, not a crash
            return _standdown(solver, flags.REASON_SHAPE_MISMATCH)

        # round the lane axis up to a device multiple with inert lanes (all
        # pods masked out: the local while-loop exits after one sweep)
        lanes = list(padded)
        while len(lanes) % n_dev:
            lanes.append(
                dataclasses.replace(
                    padded[0],
                    pod_active=np.zeros_like(np.asarray(padded[0].pod_active)),
                )
            )
        batch = stack_problems(lanes)
        bucket_pods = int(np.asarray(padded[0].pod_active).shape[0])
        pad_frac = 1.0 - len(pods) / float(max(1, len(lanes) * bucket_pods))

        bounds_free = problem_bounds_free(batch)
        from karpenter_tpu.ops.ffd_sweeps import _wavefront_lanes

        wavefront = _wavefront_lanes()
        # KARPENTER_TPU_RELAX2 rides the mesh too: when the stacked batch
        # is relax-applicable (infinite pools across every lane), each lane
        # runs the fused convex-solve + carried-repair program instead of
        # the fresh sweeps — the env check gates the import so the module
        # never loads flag-off (tests/test_relax2.py pins that).
        relax2_on = False
        if os.environ.get("KARPENTER_TPU_RELAX2", "0") == "1":
            from karpenter_tpu.ops import relax2

            relax2_on = relax2.relax_applicable(batch)
        if relax2_on:
            from karpenter_tpu.ops.relax import relax_passes

            r2_statics = (relax2.pgd_iters(), relax2.pgd_step(), relax_passes())
            fn = shard_relax2_sweeps_program(
                mesh, max_claims, bounds_free, wavefront, *r2_statics
            )
            program_name = "shard_relax2_sweeps"
        else:
            fn = shard_sweeps_program(mesh, max_claims, bounds_free, wavefront)
            program_name = "shard_sweeps"

        key = jb._program_key(fn, max_claims, batch)
        cache_hit = key in jb._COMPILED_PROGRAMS
        jb._COMPILED_PROGRAMS.add(key)
        COMPILE_CACHE.inc({"result": "hit" if cache_hit else "miss"})
        if cache_hit:
            solver.compile_cache_hits += 1
            span_name = program_name
        else:
            solver.compile_cache_misses += 1
            span_name = "compile"
        prob_bytes = _nbytes(batch)
        TRANSFER_BYTES.inc({"direction": "h2d"}, prob_bytes)
        reg_eqns = None
        if not cache_hit and programs.eqns_enabled():
            reg_eqns = programs.maybe_count_eqns(
                lambda: jax.make_jaxpr(lambda: fn(batch))()
            )
        from karpenter_tpu.solver import aot

        aot_handle = aot.maybe_begin(fn, batch, max_claims, None)
        obs = programs.begin_dispatch(
            program_name, max_claims, batch,
            statics={
                "partitions": len(plan.parts), "devices": n_dev,
                "bounds_free": bounds_free, "wavefront": wavefront,
            },
        )
        try:
            mesh_health.dispatch_check(list(mesh.devices.flat))
            with trace.span(
                span_name,
                cache="hit" if cache_hit else "miss",
                program=program_name,
                partitions=len(plan.parts),
            ) as sp:
                if aot_handle is not None:
                    result = aot_handle.call()
                else:
                    result = fn(batch)
                r2_stats = None
                if relax2_on:
                    result, r2_stats = result
                state = result.state
                fetched = jax.device_get(
                    (
                        result.kind,
                        result.index,
                        result.iters,
                        state.claim_open,
                        state.claim_tpl,
                        state.claim_it_ok,
                        state.claim_requests,
                        state.claim_req.admitted,
                        state.claim_req.comp,
                        state.claim_req.gt,
                        state.claim_req.lt,
                        state.claim_req.defined,
                    )
                )
                (kinds, indices, iters, claim_open, claim_tpl, claim_it_ok,
                 claim_requests, claim_adm, claim_comp, claim_gt, claim_lt,
                 claim_def) = fetched
                if r2_stats is not None:
                    r2_stats = jax.device_get(r2_stats)
                d2h = _nbytes(fetched) + _nbytes(r2_stats)
                TRANSFER_BYTES.inc({"direction": "d2h"}, d2h)
                if obs is not None:
                    source = obs.finish(
                        problem_bytes=prob_bytes,
                        result_bytes=d2h,
                        eqns=reg_eqns,
                        source_override=(
                            aot_handle.source_override
                            if aot_handle is not None else None
                        ),
                    )
                    if sp is not None:
                        sp.attrs["program_key"] = obs.key
                        sp.attrs["cache_source"] = source
                if sp is not None:
                    sp.count("h2d_bytes", prob_bytes)
                    sp.count("d2h_bytes", d2h)
        except Exception as exc:  # noqa: BLE001 — classified or re-raised
            if mesh_health.handle_dispatch_failure(exc) is None:
                raise
            # a mesh device died mid-dispatch: the tracker recarved around
            # it. Re-plan against the shrunken device count and re-dispatch
            # the WHOLE lane set from host-side problem data — every loop
            # iteration re-encodes/pads/stacks from host, so nothing
            # device-resident (donated or otherwise) is resurrected.
            recarves += 1
            mesh = default_mesh(flags.min_devices())
            if mesh is None:
                # below 2 healthy devices the mesh buys nothing: the same
                # single-device standdown the seed path already classifies
                return _standdown(
                    solver, flags.REASON_SINGLE_DEVICE, recarves=recarves,
                )
            n_dev = mesh.devices.size
            plan = _plan_for(mesh)
            if plan.reason is not None:
                return _standdown(
                    solver, plan.reason,
                    atomic=plan.atomic_components,
                    splittable=plan.splittable_pods,
                )
            max_part = max(len(pt.pod_idx) for pt in plan.parts)
            if 0 < ceiling < max_part:
                return _standdown(
                    solver, flags.REASON_SINGLE_PARTITION, dominant=max_part,
                )
            claim_cap = claim_axis_bucket(max_part)
            max_claims = min(solver.claim_slots, claim_cap)
            continue
        programs.note_shard_lanes(
            len(plan.parts), len(lanes),
            [len(pt.pod_idx) for pt in plan.parts],
            [len(pt.node_idx) for pt in plan.parts],
        )

        overflow = False
        for li, part in enumerate(plan.parts):
            if (kinds[li, : len(part.pod_idx)] == KIND_NO_SLOT).any():
                overflow = True
                break
        if not overflow:
            break
        if max_claims >= claim_cap:
            return _standdown(
                solver, flags.REASON_SLOT_OVERFLOW, max_claims=max_claims,
            )
        max_claims = min(claim_axis_bucket(max_claims + 1), claim_cap)
        solver.claim_slots = max(solver.claim_slots, max_claims)
        solver.claim_escalations += 1
        with trace.span("escalate", max_claims=max_claims):
            pass

    # -- decode + gate each partition, then merge -------------------------
    from karpenter_tpu import verify
    from karpenter_tpu.solver.forensics import failure_reason

    out = SolveResult()
    claim_lanes: List[int] = []  # source lane per merged-in claim
    gate_rejections = 0
    with trace.span("shard_decode", partitions=len(plan.parts)):
        for li, part in enumerate(plan.parts):
            meta = metas[li]
            part_pods = [pods[i] for i in part.pod_idx]
            part_nodes = [nodes[j] for j in part.node_idx]
            part_override = (
                [pod_requirements_override[i] for i in part.pod_idx]
                if pod_requirements_override is not None
                else None
            )
            local = SolveResult()
            pod_kinds: Dict[int, Tuple[int, int]] = {}
            for row in range(len(meta.pod_order)):
                loc = meta.pod_order[row]
                kind, index = int(kinds[li, row]), int(indices[li, row])
                if kind in (KIND_NODE, KIND_CLAIM, KIND_NEW_CLAIM):
                    pod_kinds[loc] = (kind, index)
                else:
                    local.failures[loc] = failure_reason(
                        part_pods[loc],
                        instance_types,
                        templates,
                        pod_reqs=(
                            part_override[loc]
                            if part_override is not None
                            else None
                        ),
                        well_known=solver.well_known,
                    ) or FAIL_INCOMPATIBLE
            slot_to_claim: Dict[int, Placement] = {}
            for slot in range(max_claims):
                if slot < claim_open.shape[1] and claim_open[li, slot]:
                    tpl_idx = int(claim_tpl[li, slot])
                    placement = Placement(
                        template_index=tpl_idx,
                        nodepool_name=meta.template_names[tpl_idx],
                        instance_type_indices=[
                            int(t)
                            for t in np.flatnonzero(claim_it_ok[li, slot])
                            if t < len(meta.instance_type_names)
                        ],
                        requirements=jb.decode_claim_requirements(
                            meta, claim_adm[li, slot], claim_comp[li, slot],
                            claim_gt[li, slot], claim_lt[li, slot],
                            claim_def[li, slot],
                        ),
                        requests={
                            name: float(claim_requests[li, slot, ri])
                            for ri, name in enumerate(meta.resource_names)
                            if claim_requests[li, slot, ri] > 0
                        },
                    )
                    slot_to_claim[slot] = placement
                    local.new_claims.append(placement)
            for loc, (kind, index) in pod_kinds.items():
                if kind == KIND_NODE:
                    local.node_pods.setdefault(
                        meta.node_names[index], []
                    ).append(loc)
                else:
                    slot_to_claim[index].pod_indices.append(loc)

            # the per-partition full-level device gate: partition-local
            # invariants ARE the full-problem invariants (disjoint
            # constraints), and the lane's padded tensors are the exact
            # context the unsharded gate would see for this sub-problem
            local.verify_ctx = verify.make_context(
                padded[li], meta, max_claims, len(part_pods),
                pod_requirements_override is not None,
            )
            outcome = verify.full_gate(
                local, part_pods, instance_types, templates, part_nodes,
                part_override, cluster_pods, domains,
            )
            if outcome is not None and outcome.violations:
                gate_rejections += 1
                SHARD_MERGE_REJECTIONS.inc()
                return _standdown(
                    solver, flags.REASON_MERGE_REJECTED,
                    partition=li, violations=len(outcome.violations),
                )

            # fold into the global result (original pod indices)
            for name, plist in local.node_pods.items():
                out.node_pods.setdefault(name, []).extend(
                    part.pod_idx[i] for i in plist
                )
            for loc, reason in local.failures.items():
                out.failures[part.pod_idx[loc]] = reason
            for claim in local.new_claims:
                claim.pod_indices = [part.pod_idx[i] for i in claim.pod_indices]
                out.new_claims.append(claim)
                claim_lanes.append(li)

    merged = 0
    if flags.merge_enabled() and pod_volumes is None:
        with trace.span("shard_merge", claims=len(out.new_claims)):
            merged = _merge_claims(
                out, claim_lanes, templates, instance_types,
                _free_pods(pods, groups),
            )

    if 0 < len(pods) <= flags.full_validate_max():
        # belt-and-braces at small scale: the float64 validator over the
        # MERGED result (the per-partition gates covered everything except
        # the merge step, whose checks are exact — this confirms that)
        from karpenter_tpu.solver.validator import validate_result

        violations = validate_result(
            out, pods, instance_types, templates, nodes,
            pod_requirements_override, cluster_pods, domains, level="full",
        )
        if violations:
            SHARD_MERGE_REJECTIONS.inc()
            return _standdown(
                solver, flags.REASON_MERGE_REJECTED,
                violations=len(violations),
            )

    SHARD_PARTITIONS.set(float(len(plan.parts)))
    SHARD_PAD_FRACTION.set(round(pad_frac, 6))
    solver.last_iters = None
    solver.last_wave_hist = None
    solver.last_relax = None
    solver.last_relax2 = None
    if r2_stats is not None:
        # real lanes come first in the stack; inert pad lanes contribute
        # zeros anyway, but slice to keep the aggregates honest
        k = len(plan.parts)
        solver.last_relax2 = {
            "reason": None,
            "sharded": True,
            "lanes": k,
            "eligible": int(np.asarray(r2_stats.eligible)[:k].sum()),
            "placed": int(np.asarray(r2_stats.placed)[:k].sum()),
            "demoted": int(np.asarray(r2_stats.demoted)[:k].sum()),
            "claims": int(np.asarray(r2_stats.claims)[:k].sum()),
            "pgd_iterations": int(np.asarray(r2_stats.pgd_iterations)[:k].max()),
            "residual": float(np.asarray(r2_stats.residual)[:k].max()),
            "capviol": float(np.asarray(r2_stats.capviol)[:k].max()),
            "rounding": {
                "overflow": int(np.asarray(r2_stats.overflow)[:k].sum()),
                "demoted": int(np.asarray(r2_stats.round_demoted)[:k].sum()),
            },
        }
    solver.last_shard = {
        "reason": None,
        "partitions": len(plan.parts),
        "lanes": len(lanes),
        "bucket_pods": bucket_pods,
        "pad_frac": round(pad_frac, 6),
        "max_claims": max_claims,
        "merged_claims": merged,
        "dropped_nodes": plan.dropped_nodes,
        "splittable_pods": plan.splittable_pods,
        "atomic_components": plan.atomic_components,
        "narrow_iters": int(np.asarray(iters.narrow).sum()),
        "sweep_iters": int(np.asarray(iters.sweeps).sum()),
        "gate_rejections": gate_rejections,
        "recarves": recarves,
    }
    # first green solve after a device failure closes the recovery clock
    mesh_health.note_green()
    programs.sample_memory(pods=len(pods), cycle=trace.current_trace_id())
    return out
