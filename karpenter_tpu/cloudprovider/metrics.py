"""Metrics decorator around any CloudProvider.

Equivalent of reference pkg/cloudprovider/metrics/cloudprovider.go: wraps each
SPI method with a duration histogram and error counter.
"""

from __future__ import annotations

from karpenter_tpu.cloudprovider.types import CloudProvider
from karpenter_tpu.metrics import REGISTRY, measure

_method_duration = REGISTRY.histogram(
    "cloudprovider_duration_seconds",
    "Duration of cloud provider method calls.",
)
_method_errors = REGISTRY.counter(
    "cloudprovider_errors_total",
    "Total cloud provider method errors.",
)


class MetricsCloudProvider(CloudProvider):
    """Decorator pattern: every call is timed and errors counted, labeled by
    method and provider name."""

    def __init__(self, inner: CloudProvider):
        self._inner = inner

    def _call(self, method: str, fn, *args):
        labels = {"method": method, "provider": self._inner.name()}
        try:
            with measure(_method_duration, labels):
                return fn(*args)
        except Exception:
            _method_errors.inc(labels)
            raise

    def create(self, node_claim):
        return self._call("Create", self._inner.create, node_claim)

    def delete(self, node_claim):
        return self._call("Delete", self._inner.delete, node_claim)

    def get(self, provider_id):
        return self._call("Get", self._inner.get, provider_id)

    def list(self):
        return self._call("List", self._inner.list)

    def get_instance_types(self, nodepool):
        return self._call("GetInstanceTypes", self._inner.get_instance_types, nodepool)

    def is_drifted(self, node_claim):
        return self._call("IsDrifted", self._inner.is_drifted, node_claim)

    def name(self):
        return self._inner.name()
