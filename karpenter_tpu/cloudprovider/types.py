"""CloudProvider SPI.

Equivalent of reference pkg/cloudprovider/types.go: the pluggable seam each
cloud implements (Create/Delete/Get/List/GetInstanceTypes/IsDrifted/Name), the
InstanceType/Offering model that feeds the solver, and the typed errors that
drive lifecycle retry/delete decisions.
"""

from __future__ import annotations

import abc
import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.nodeclaim import NodeClaim
from karpenter_tpu.apis.nodepool import NodePool
from karpenter_tpu.scheduling import Requirements
from karpenter_tpu.utils import resources as res


@dataclass(frozen=True)
class Offering:
    """(capacityType, zone, price, available) (types.go:127-134)."""

    capacity_type: str
    zone: str
    price: float
    available: bool = True


class Offerings(list):
    """Decorated list of Offering (types.go:136-166)."""

    def get(self, capacity_type: str, zone: str) -> Optional[Offering]:
        for o in self:
            if o.capacity_type == capacity_type and o.zone == zone:
                return o
        return None

    def available(self) -> "Offerings":
        return Offerings(o for o in self if o.available)

    def requirements(self, reqs: Requirements) -> "Offerings":
        """Offerings compatible with zone / capacity-type requirements
        (types.go:154-159)."""
        return Offerings(
            o
            for o in self
            if (not reqs.has(wk.LABEL_TOPOLOGY_ZONE) or reqs.get(wk.LABEL_TOPOLOGY_ZONE).has(o.zone))
            and (
                not reqs.has(wk.CAPACITY_TYPE_LABEL_KEY)
                or reqs.get(wk.CAPACITY_TYPE_LABEL_KEY).has(o.capacity_type)
            )
        )

    def cheapest(self) -> Optional[Offering]:
        return min(self, key=lambda o: o.price) if self else None


@dataclass
class InstanceTypeOverhead:
    """Reserved capacity outside k8s (types.go:112-123)."""

    kube_reserved: Dict[str, float] = field(default_factory=dict)
    system_reserved: Dict[str, float] = field(default_factory=dict)
    eviction_threshold: Dict[str, float] = field(default_factory=dict)

    def total(self) -> Dict[str, float]:
        return res.merge(self.kube_reserved, self.system_reserved, self.eviction_threshold)


class InstanceType:
    """A potential node shape: capacity, requirement set (one per well-known
    label at minimum), and offerings (types.go:83-110)."""

    __slots__ = ("name", "requirements", "offerings", "capacity", "overhead", "_allocatable")

    def __init__(
        self,
        name: str,
        requirements: Requirements,
        offerings: Sequence[Offering],
        capacity: Dict[str, float],
        overhead: Optional[InstanceTypeOverhead] = None,
    ):
        self.name = name
        self.requirements = requirements
        self.offerings = Offerings(offerings)
        self.capacity = capacity
        self.overhead = overhead or InstanceTypeOverhead()
        self._allocatable: Optional[Dict[str, float]] = None

    def allocatable(self) -> Dict[str, float]:
        """capacity - overhead, cached (types.go:101-110)."""
        if self._allocatable is None:
            self._allocatable = res.subtract(self.capacity, self.overhead.total())
        return self._allocatable

    def __repr__(self):
        return f"InstanceType({self.name})"


def order_by_price(
    instance_types: Sequence[InstanceType], reqs: Requirements
) -> List[InstanceType]:
    """Cheapest compatible-offering first, name tiebreak (types.go:62-79)."""

    def price_of(it: InstanceType) -> float:
        compatible = it.offerings.available().requirements(reqs)
        cheapest = compatible.cheapest()
        return cheapest.price if cheapest else math.inf

    return sorted(instance_types, key=lambda it: (price_of(it), it.name))


# -- typed errors (types.go:169-256) -----------------------------------------


class CloudProviderError(Exception):
    pass


class NodeClaimNotFoundError(CloudProviderError):
    """The machine behind a NodeClaim no longer exists."""


class InsufficientCapacityError(CloudProviderError):
    """Launch failed for lack of capacity (ICE); the claim is deleted and
    scheduling retries elsewhere (lifecycle/launch.go:80-96)."""


class NodeClassNotReadyError(CloudProviderError):
    """The referenced NodeClass is not fully resolved yet."""


class RateLimitError(CloudProviderError):
    """The provider API throttled the call. Transient: the lifecycle
    controller retries the same claim with jittered exponential backoff
    rather than deleting it."""


class CreateTimeoutError(CloudProviderError):
    """The Create call timed out at the provider. Transient, same backoff
    treatment as RateLimitError."""


class CloudProvider(abc.ABC):
    """The SPI every cloud implements (types.go:38-58)."""

    @abc.abstractmethod
    def create(self, node_claim: NodeClaim) -> NodeClaim:
        """Launch a machine for the claim; returns a hydrated claim with
        resolved labels, provider id, and capacity."""

    @abc.abstractmethod
    def delete(self, node_claim: NodeClaim) -> None:
        """Terminate the machine behind the claim."""

    @abc.abstractmethod
    def get(self, provider_id: str) -> NodeClaim:
        """Fetch one machine by provider id."""

    @abc.abstractmethod
    def list(self) -> List[NodeClaim]:
        """All machines owned by the framework."""

    @abc.abstractmethod
    def get_instance_types(self, nodepool: Optional[NodePool]) -> List[InstanceType]:
        """All instance types (including currently-unavailable offerings)."""

    @abc.abstractmethod
    def is_drifted(self, node_claim: NodeClaim) -> str:
        """Non-empty drift reason if the machine no longer matches its
        provisioning requirements."""

    @abc.abstractmethod
    def name(self) -> str:
        ...
