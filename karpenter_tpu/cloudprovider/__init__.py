from karpenter_tpu.cloudprovider.types import (  # noqa: F401
    CloudProvider,
    InstanceType,
    InstanceTypeOverhead,
    Offering,
    InsufficientCapacityError,
    NodeClaimNotFoundError,
    NodeClassNotReadyError,
    order_by_price,
)
