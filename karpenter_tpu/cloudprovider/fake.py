"""In-memory CloudProvider for tests and benchmarks.

Equivalent of reference pkg/cloudprovider/fake/{cloudprovider,instancetype}.go —
the test substrate everything downstream builds on: a provider whose Create
picks the cheapest compatible instance type, plus deterministic instance-type
catalog generators.
"""

from __future__ import annotations

import itertools
import math
import uuid
from typing import Dict, List, Optional, Sequence

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.labels import (
    CAPACITY_TYPE_ON_DEMAND,
    CAPACITY_TYPE_SPOT,
)
from karpenter_tpu.apis.nodeclaim import NodeClaim, NodeClaimStatus
from karpenter_tpu.apis.nodepool import NodePool
from karpenter_tpu.apis.objects import DOES_NOT_EXIST, IN, ObjectMeta
from karpenter_tpu.cloudprovider.types import (
    CloudProvider,
    InstanceType,
    InstanceTypeOverhead,
    NodeClaimNotFoundError,
    Offering,
    Offerings,
)
from karpenter_tpu.scheduling import Requirement, Requirements
from karpenter_tpu.utils import resources as res

# extra label keys the fake catalog exposes (fake/instancetype.go:34-40); they
# are treated as well-known for compatibility purposes in tests
LABEL_INSTANCE_SIZE = "size"
EXOTIC_INSTANCE_LABEL_KEY = "special"
INTEGER_INSTANCE_LABEL_KEY = "integer"
RESOURCE_GPU_VENDOR_A = "fake.com/vendor-a"
RESOURCE_GPU_VENDOR_B = "fake.com/vendor-b"

FAKE_WELL_KNOWN_LABELS = frozenset(
    wk.WELL_KNOWN_LABELS
    | {LABEL_INSTANCE_SIZE, EXOTIC_INSTANCE_LABEL_KEY, INTEGER_INSTANCE_LABEL_KEY}
)

GI = 1024.0**3


def price_from_resources(resources: Dict[str, float]) -> float:
    """Simple capacity-proportional price (fake/instancetype.go:176-189)."""
    price = 0.0
    for name, value in resources.items():
        if name == res.CPU:
            price += 0.1 * value
        elif name == res.MEMORY:
            price += 0.1 * value / 1e9
        elif name in (RESOURCE_GPU_VENDOR_A, RESOURCE_GPU_VENDOR_B):
            price += 1.0
    return price


def make_instance_type(
    name: str,
    resources: Optional[Dict[str, float]] = None,
    offerings: Optional[Sequence[Offering]] = None,
    architecture: str = "amd64",
    operating_systems: Sequence[str] = ("linux", "windows", "darwin"),
) -> InstanceType:
    """Build one fake instance type with defaulted capacity (4cpu/4Gi/5pods)
    and a 5-offering spread over 3 zones (fake/instancetype.go:50-107)."""
    resources = dict(resources or {})
    resources.setdefault(res.CPU, 4.0)
    resources.setdefault(res.MEMORY, 4 * GI)
    resources.setdefault(res.PODS, 5.0)
    price = price_from_resources(resources)
    if offerings is None:
        offerings = [
            Offering(CAPACITY_TYPE_SPOT, "test-zone-1", price, True),
            Offering(CAPACITY_TYPE_SPOT, "test-zone-2", price, True),
            Offering(CAPACITY_TYPE_ON_DEMAND, "test-zone-1", price, True),
            Offering(CAPACITY_TYPE_ON_DEMAND, "test-zone-2", price, True),
            Offering(CAPACITY_TYPE_ON_DEMAND, "test-zone-3", price, True),
        ]
    offerings = Offerings(offerings)
    available = offerings.available()
    requirements = Requirements(
        Requirement(wk.LABEL_INSTANCE_TYPE_STABLE, IN, [name]),
        Requirement(wk.LABEL_ARCH_STABLE, IN, [architecture]),
        Requirement(wk.LABEL_OS_STABLE, IN, list(operating_systems)),
        Requirement(wk.LABEL_TOPOLOGY_ZONE, IN, [o.zone for o in available]),
        Requirement(wk.CAPACITY_TYPE_LABEL_KEY, IN, [o.capacity_type for o in available]),
        Requirement(INTEGER_INSTANCE_LABEL_KEY, IN, [str(int(resources[res.CPU]))]),
    )
    if resources[res.CPU] > 4 and resources[res.MEMORY] > 8 * GI:
        requirements.add(Requirement(LABEL_INSTANCE_SIZE, IN, ["large"]))
        requirements.add(Requirement(EXOTIC_INSTANCE_LABEL_KEY, IN, ["optional"]))
    else:
        requirements.add(Requirement(LABEL_INSTANCE_SIZE, IN, ["small"]))
        requirements.add(Requirement(EXOTIC_INSTANCE_LABEL_KEY, DOES_NOT_EXIST))
    return InstanceType(
        name=name,
        requirements=requirements,
        offerings=offerings,
        capacity=resources,
        overhead=InstanceTypeOverhead(
            kube_reserved={res.CPU: 0.1, res.MEMORY: 10 * 1024.0**2}
        ),
    )


def instance_types(total: int) -> List[InstanceType]:
    """Incrementing catalog: i+1 vcpu, 2(i+1)Gi, 10(i+1) pods
    (fake/instancetype.go:153-166)."""
    return [
        make_instance_type(
            f"fake-it-{i}",
            resources={
                res.CPU: float(i + 1),
                res.MEMORY: (i + 1) * 2 * GI,
                res.PODS: float((i + 1) * 10),
            },
        )
        for i in range(total)
    ]


def instance_types_assorted() -> List[InstanceType]:
    """Cross product over cpu × mem × zone × capacity-type × os × arch, one
    offering each (fake/instancetype.go:111-145)."""
    out = []
    for cpu, mem, zone, ct, os_, arch in itertools.product(
        [1, 2, 4, 8, 16, 32, 64],
        [1, 2, 4, 8, 16, 32, 64, 128],
        ["test-zone-1", "test-zone-2", "test-zone-3"],
        [CAPACITY_TYPE_SPOT, CAPACITY_TYPE_ON_DEMAND],
        ["linux", "windows"],
        ["amd64", "arm64"],
    ):
        resources = {res.CPU: float(cpu), res.MEMORY: mem * GI}
        out.append(
            make_instance_type(
                f"{cpu}-cpu-{mem}-mem-{arch}-{os_}-{zone}-{ct}",
                resources=resources,
                offerings=[Offering(ct, zone, price_from_resources(resources), True)],
                architecture=arch,
                operating_systems=[os_],
            )
        )
    return out


def default_instance_types() -> List[InstanceType]:
    """The provider's built-in 6-type catalog (fake/cloudprovider.go:177-215)."""
    return [
        make_instance_type("default-instance-type"),
        make_instance_type(
            "small-instance-type", resources={res.CPU: 2.0, res.MEMORY: 2 * GI}
        ),
        make_instance_type(
            "gpu-vendor-instance-type", resources={RESOURCE_GPU_VENDOR_A: 2.0}
        ),
        make_instance_type(
            "gpu-vendor-b-instance-type", resources={RESOURCE_GPU_VENDOR_B: 2.0}
        ),
        make_instance_type(
            "arm-instance-type",
            resources={res.CPU: 16.0, res.MEMORY: 128 * GI},
            architecture="arm64",
            operating_systems=["ios", "linux", "windows", "darwin"],
        ),
        make_instance_type("single-pod-instance-type", resources={res.PODS: 1.0}),
    ]


def random_provider_id() -> str:
    return f"fake:///{uuid.uuid4()}"


class FakeCloudProvider(CloudProvider):
    """Launches are bookkeeping: Create picks the cheapest instance type
    compatible with the claim's requirements/requests and fabricates a
    provider id (fake/cloudprovider.go:82-143). Error knobs
    (next_create_error, allowed_create_calls, errors_for_nodepool) drive
    fault-injection in tests."""

    def __init__(self):
        self.instance_types: Optional[List[InstanceType]] = None
        self.instance_types_for_nodepool: Dict[str, List[InstanceType]] = {}
        self.errors_for_nodepool: Dict[str, Exception] = {}
        self.create_calls: List[NodeClaim] = []
        self.delete_calls: List[NodeClaim] = []
        self.allowed_create_calls: int = 2**31
        self.next_create_error: Optional[Exception] = None
        self.created_nodeclaims: Dict[str, NodeClaim] = {}
        self.drifted: str = "drifted"
        # per-instance fault injector (testing/faults.py); None falls through
        # to the ambient/env-installed one so KARPENTER_TPU_FAULTS reaches
        # the provider without test plumbing
        self.fault_injector = None

    def reset(self):
        self.__init__()

    def _draw_fault(self, site: str):
        from karpenter_tpu.testing import faults

        injector = (
            self.fault_injector if self.fault_injector is not None else faults.active()
        )
        if injector is None:
            return
        rule = injector.draw(site)
        if rule is not None:
            raise faults.cloud_exception(rule)

    # -- SPI ------------------------------------------------------------------

    def create(self, node_claim: NodeClaim) -> NodeClaim:
        if self.next_create_error is not None:
            err, self.next_create_error = self.next_create_error, None
            raise err
        self._draw_fault("create")
        self.create_calls.append(node_claim)
        if len(self.create_calls) > self.allowed_create_calls:
            raise RuntimeError("number of allowed create calls exceeded")

        reqs = Requirements.from_node_selector_requirements(*node_claim.spec.requirements)
        nodepool = NodePool(metadata=ObjectMeta(name=node_claim.nodepool_name or ""))
        candidates = [
            it
            for it in self.get_instance_types(nodepool)
            if reqs.is_compatible(it.requirements, FAKE_WELL_KNOWN_LABELS)
            and len(it.offerings.requirements(reqs).available()) > 0
            and res.fits(node_claim.spec.resource_requests, it.allocatable())
        ]
        if not candidates:
            raise RuntimeError(f"no compatible instance type for claim {node_claim.name}")
        candidates.sort(
            key=lambda it: it.offerings.available().requirements(reqs).cheapest().price
        )
        instance_type = candidates[0]

        labels = {}
        for key in instance_type.requirements:
            requirement = instance_type.requirements.get(key)
            if requirement.operator() == IN:
                labels[key] = requirement.sorted_values()[0]
        for o in instance_type.offerings.available():
            offering_reqs = Requirements(
                Requirement(wk.LABEL_TOPOLOGY_ZONE, IN, [o.zone]),
                Requirement(wk.CAPACITY_TYPE_LABEL_KEY, IN, [o.capacity_type]),
            )
            if reqs.is_compatible(offering_reqs, FAKE_WELL_KNOWN_LABELS):
                labels[wk.LABEL_TOPOLOGY_ZONE] = o.zone
                labels[wk.CAPACITY_TYPE_LABEL_KEY] = o.capacity_type
                break

        created = NodeClaim(
            metadata=ObjectMeta(
                name=node_claim.name,
                labels={**labels, **node_claim.metadata.labels},
                annotations=dict(node_claim.metadata.annotations),
            ),
            spec=node_claim.spec,
            status=NodeClaimStatus(
                provider_id=random_provider_id(),
                capacity=res.positive_part(instance_type.capacity),
                allocatable=res.positive_part(instance_type.allocatable()),
            ),
        )
        self.created_nodeclaims[created.status.provider_id] = created
        return created

    def get(self, provider_id: str) -> NodeClaim:
        if provider_id in self.created_nodeclaims:
            return self.created_nodeclaims[provider_id]
        raise NodeClaimNotFoundError(f"no nodeclaim exists with id {provider_id!r}")

    def list(self) -> List[NodeClaim]:
        return list(self.created_nodeclaims.values())

    def get_instance_types(self, nodepool: Optional[NodePool]) -> List[InstanceType]:
        if nodepool is not None:
            if nodepool.name in self.errors_for_nodepool:
                raise self.errors_for_nodepool[nodepool.name]
            if nodepool.name in self.instance_types_for_nodepool:
                return self.instance_types_for_nodepool[nodepool.name]
        if self.instance_types is not None:
            return self.instance_types
        return default_instance_types()

    def delete(self, node_claim: NodeClaim) -> None:
        self._draw_fault("delete")
        self.delete_calls.append(node_claim)
        if node_claim.status.provider_id in self.created_nodeclaims:
            del self.created_nodeclaims[node_claim.status.provider_id]
            return
        raise NodeClaimNotFoundError(
            f"no nodeclaim exists with provider id {node_claim.status.provider_id!r}"
        )

    def is_drifted(self, node_claim: NodeClaim) -> str:
        return self.drifted

    def name(self) -> str:
        return "fake"
