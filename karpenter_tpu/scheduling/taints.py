"""Taint / toleration checks (reference pkg/scheduling/taints.go)."""

from __future__ import annotations

from typing import Iterable, List

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.objects import NO_SCHEDULE, Pod, Taint

# Taints added/removed by kubelet or cloud controllers during startup; ignored
# when deciding whether a node can serve pods (taints.go:28-32).
KNOWN_EPHEMERAL_TAINTS = (
    Taint(key=wk.TAINT_NODE_NOT_READY, effect=NO_SCHEDULE),
    Taint(key=wk.TAINT_NODE_UNREACHABLE, effect=NO_SCHEDULE),
    Taint(key=wk.TAINT_EXTERNAL_CLOUD_PROVIDER, effect=NO_SCHEDULE, value="true"),
)


class Taints(list):
    """Decorated list of Taint (taints.go:35-65)."""

    def __init__(self, taints: Iterable[Taint] = ()):
        super().__init__(taints)

    def tolerates(self, pod: Pod) -> List[str]:
        """Error strings for every taint the pod does not tolerate
        (taints.go:38-50); empty means fully tolerated."""
        errs = []
        for taint in self:
            if not any(t.tolerates(taint) for t in pod.spec.tolerations):
                errs.append(f"did not tolerate {taint.key}={taint.value}:{taint.effect}")
        return errs

    def merge(self, other: Iterable[Taint]) -> "Taints":
        """Union keeping existing entries on (key, effect) conflict
        (taints.go:53-65)."""
        out = Taints(self)
        for taint in other:
            if not any(taint.match(t) for t in out):
                out.append(taint)
        return out
