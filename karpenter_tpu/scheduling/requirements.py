"""The scheduling requirements algebra.

Host-side twin of the reference's pkg/scheduling/{requirement,requirements}.go:
a ``Requirement`` is a set over the (unbounded) space of label-value strings,
stored either as a finite admitted set (In / DoesNotExist) or as the complement
of a finite excluded set (NotIn / Exists / Gt / Lt with integer bounds). A
``Requirements`` maps label key -> Requirement with intersection-on-add.

This module is the semantic ground truth that the tensorized codec in
``solver/encode.py`` is property-tested against. The closed-world tensor
encoding is documented there.

Semantics mirrored exactly (file:line refer to /root/reference):
  - constructor normalization            pkg/scheduling/requirement.go:41-79
  - Intersection incl. bound handling    requirement.go:128-161
  - Has with bounds                      requirement.go:182-187
  - Operator / Len complement logic      requirement.go:197-215
  - Requirements.Add intersects          requirements.go:118-125
  - Compatible undefined-key rules       requirements.go:163-174
  - Intersects NotIn/DoesNotExist escape requirements.go:241-258
"""

from __future__ import annotations

import random
import sys
from typing import Dict, Iterable, Iterator, List, Optional, Set

from karpenter_tpu.apis import labels as wk
from karpenter_tpu.apis.objects import (
    DOES_NOT_EXIST,
    EXISTS,
    GT,
    IN,
    LT,
    NOT_IN,
    NodeSelectorRequirement,
    Pod,
)

# Stand-in for Go's math.MaxInt64-based "infinite" set size.
INFINITE = sys.maxsize


class Requirement:
    """A set over label values for one key.

    ``complement=False``: the requirement admits exactly ``values``.
    ``complement=True``: it admits everything except ``values``, further
    clipped to integer bounds ``(greater_than, less_than)`` when set.
    """

    __slots__ = ("key", "complement", "values", "greater_than", "less_than")

    def __init__(
        self,
        key: str,
        operator: str,
        values: Iterable[str] = (),
        *,
        _raw: bool = False,
    ):
        values = list(values)
        if not _raw:
            key = wk.NORMALIZED_LABELS.get(key, key)
        self.key = key
        self.greater_than: Optional[int] = None
        self.less_than: Optional[int] = None
        if operator == IN:
            self.values: Set[str] = set(values)
            self.complement = False
            return
        self.values = set()
        self.complement = operator != DOES_NOT_EXIST
        if operator == NOT_IN:
            self.values.update(values)
        elif operator == GT:
            self.greater_than = int(values[0])
        elif operator == LT:
            self.less_than = int(values[0])
        elif operator not in (EXISTS, DOES_NOT_EXIST):
            raise ValueError(f"unsupported operator {operator!r}")

    # -- constructors ---------------------------------------------------------

    @classmethod
    def _make(cls, key, complement, values, greater_than=None, less_than=None) -> "Requirement":
        r = cls.__new__(cls)
        r.key = key
        r.complement = complement
        r.values = set(values)
        r.greater_than = greater_than
        r.less_than = less_than
        return r

    def copy(self) -> "Requirement":
        return Requirement._make(self.key, self.complement, self.values, self.greater_than, self.less_than)

    # -- algebra --------------------------------------------------------------

    def intersection(self, other: "Requirement") -> "Requirement":
        """Narrow this requirement by another (requirement.go:128-161)."""
        complement = self.complement and other.complement

        greater_than = _max_opt(self.greater_than, other.greater_than)
        less_than = _min_opt(self.less_than, other.less_than)
        if greater_than is not None and less_than is not None and greater_than >= less_than:
            return Requirement(self.key, DOES_NOT_EXIST)

        if self.complement and other.complement:
            values = self.values | other.values
        elif self.complement:
            values = other.values - self.values
        elif other.complement:
            values = self.values - other.values
        else:
            values = self.values & other.values
        values = {v for v in values if _within_bounds(v, greater_than, less_than)}

        if not complement:
            greater_than, less_than = None, None
        return Requirement._make(self.key, complement, values, greater_than, less_than)

    def has(self, value: str) -> bool:
        """True if the requirement admits ``value`` (requirement.go:182-187)."""
        in_set = value in self.values
        if self.complement:
            return not in_set and _within_bounds(value, self.greater_than, self.less_than)
        return in_set and _within_bounds(value, self.greater_than, self.less_than)

    def insert(self, *values: str) -> None:
        self.values.update(values)

    def operator(self) -> str:
        if self.complement:
            return NOT_IN if self.values else EXISTS
        return IN if self.values else DOES_NOT_EXIST

    def __len__(self) -> int:
        # Matches the reference's Len(): bounds are deliberately ignored for
        # complement sets (requirement.go:210-215).
        if self.complement:
            return INFINITE - len(self.values)
        return len(self.values)

    def is_empty(self) -> bool:
        return len(self) == 0

    def any_value(self) -> str:
        """Some admitted value, for label synthesis (requirement.go:163-179)."""
        op = self.operator()
        if op == IN:
            return min(self.values)  # deterministic, unlike the reference
        if op in (NOT_IN, EXISTS):
            lo = 0 if self.greater_than is None else self.greater_than + 1
            hi = (1 << 31) if self.less_than is None else self.less_than
            for _ in range(100):
                v = str(random.randrange(lo, hi))
                if v not in self.values:
                    return v
        return ""

    def sorted_values(self) -> List[str]:
        return sorted(self.values)

    def to_node_selector_requirement(self) -> NodeSelectorRequirement:
        """Project back to a NodeSelectorRequirement (requirement.go:81-124)."""
        if self.greater_than is not None:
            return NodeSelectorRequirement(self.key, GT, [str(self.greater_than)])
        if self.less_than is not None:
            return NodeSelectorRequirement(self.key, LT, [str(self.less_than)])
        if self.complement:
            if self.values:
                return NodeSelectorRequirement(self.key, NOT_IN, self.sorted_values())
            return NodeSelectorRequirement(self.key, EXISTS)
        if self.values:
            return NodeSelectorRequirement(self.key, IN, self.sorted_values())
        return NodeSelectorRequirement(self.key, DOES_NOT_EXIST)

    def __repr__(self) -> str:
        op = self.operator()
        if op in (EXISTS, DOES_NOT_EXIST):
            s = f"{self.key} {op}"
        else:
            vals = self.sorted_values()
            if len(vals) > 5:
                vals = vals[:5] + [f"and {len(vals) - 5} others"]
            s = f"{self.key} {op} {vals}"
        if self.greater_than is not None:
            s += f" >{self.greater_than}"
        if self.less_than is not None:
            s += f" <{self.less_than}"
        return s

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Requirement)
            and self.key == other.key
            and self.complement == other.complement
            and self.values == other.values
            and self.greater_than == other.greater_than
            and self.less_than == other.less_than
        )

    def __hash__(self):
        return hash((self.key, self.complement, frozenset(self.values), self.greater_than, self.less_than))


def _within_bounds(value: str, greater_than: Optional[int], less_than: Optional[int]) -> bool:
    """Integer bound check; non-integers fail when bounds are set
    (requirement.go:238-254)."""
    if greater_than is None and less_than is None:
        return True
    try:
        num = int(value)
    except (TypeError, ValueError):
        return False
    if greater_than is not None and greater_than >= num:
        return False
    if less_than is not None and less_than <= num:
        return False
    return True


def _min_opt(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return min(a, b)


def _max_opt(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return max(a, b)


_NEGATIVE_POLARITY = (NOT_IN, DOES_NOT_EXIST)


class Requirements:
    """Label key -> Requirement, intersecting on add (requirements.go:36-125)."""

    __slots__ = ("_reqs",)

    def __init__(self, *requirements: Requirement):
        self._reqs: Dict[str, Requirement] = {}
        self.add(*requirements)

    @classmethod
    def from_node_selector_requirements(cls, *nsrs: NodeSelectorRequirement) -> "Requirements":
        return cls(*(Requirement(n.key, n.operator, n.values) for n in nsrs))

    @classmethod
    def from_labels(cls, labels: Dict[str, str]) -> "Requirements":
        return cls(*(Requirement(k, IN, [v]) for k, v in labels.items()))

    # -- mapping surface ------------------------------------------------------

    def add(self, *requirements: Requirement) -> None:
        for req in requirements:
            existing = self._reqs.get(req.key)
            if existing is not None:
                req = req.intersection(existing)
            self._reqs[req.key] = req

    def keys(self) -> Set[str]:
        return set(self._reqs)

    def values(self) -> List[Requirement]:
        return list(self._reqs.values())

    def has(self, key: str) -> bool:
        return key in self._reqs

    def get(self, key: str) -> Requirement:
        """Undefined keys read as Exists (requirements.go:145-151)."""
        req = self._reqs.get(key)
        if req is None:
            return Requirement(key, EXISTS)
        return req

    def __iter__(self) -> Iterator[str]:
        return iter(self._reqs)

    def __len__(self) -> int:
        return len(self._reqs)

    def __contains__(self, key: str) -> bool:
        return key in self._reqs

    def copy(self) -> "Requirements":
        out = Requirements()
        out._reqs = {k: v.copy() for k, v in self._reqs.items()}
        return out

    def delete(self, key: str) -> None:
        self._reqs.pop(key, None)

    def to_node_selector_requirements(self) -> List[NodeSelectorRequirement]:
        return [r.to_node_selector_requirement() for r in self._reqs.values()]

    # -- compatibility --------------------------------------------------------

    def intersects(self, incoming: "Requirements") -> List[str]:
        """Error strings for keys in both whose intersection is empty, except
        when both sides have negative polarity (requirements.go:241-258)."""
        errs = []
        for key in self.keys() & incoming.keys():
            existing = self.get(key)
            inc = incoming.get(key)
            if len(existing.intersection(inc)) == 0:
                if inc.operator() in _NEGATIVE_POLARITY and existing.operator() in _NEGATIVE_POLARITY:
                    continue
                errs.append(f"key {key}, {inc!r} not in {existing!r}")
        return errs

    def compatible(self, incoming: "Requirements", allow_undefined: frozenset = frozenset()) -> List[str]:
        """Loose compatibility (requirements.go:163-174): keys in ``incoming``
        outside ``allow_undefined`` must be defined here unless the incoming
        operator is NotIn/DoesNotExist; then requirements must intersect.
        Returns error strings, empty when compatible."""
        errs = []
        for key in incoming.keys() - allow_undefined:
            if self.has(key) or incoming.get(key).operator() in _NEGATIVE_POLARITY:
                continue
            errs.append(
                f'label "{key}" does not have known values'
                + _label_hint(self, key, allow_undefined)
            )
        errs.extend(self.intersects(incoming))
        return errs

    def is_compatible(self, incoming: "Requirements", allow_undefined: frozenset = frozenset()) -> bool:
        return not self.compatible(incoming, allow_undefined)

    def labels(self) -> Dict[str, str]:
        """Synthesize node labels from the requirements (requirements.go:260-270)."""
        out = {}
        for key, req in self._reqs.items():
            if not wk.is_restricted_node_label(key):
                value = req.any_value()
                if value:
                    out[key] = value
        return out

    def __repr__(self) -> str:
        parts = sorted(
            repr(r) for r in self._reqs.values() if r.key not in wk.RESTRICTED_LABELS
        )
        return ", ".join(parts)

    def __eq__(self, other) -> bool:
        return isinstance(other, Requirements) and self._reqs == other._reqs


def _edit_distance(s: str, t: str) -> int:
    """Levenshtein distance, two-row DP (requirements.go:177-213)."""
    if not s:
        return len(t)
    if not t:
        return len(s)
    prev = list(range(len(t) + 1))
    for i, cs in enumerate(s):
        cur = [i + 1]
        for j, ct in enumerate(t):
            cur.append(min(prev[j + 1] + 1, cur[j] + 1, prev[j] + (cs != ct)))
        prev = cur
    return prev[-1]


def _get_suffix(key: str) -> str:
    """The part after the domain slash, or the whole key (requirements.go:215-218)."""
    before, sep, after = key.partition("/")
    return after if sep else before


def _label_hint(reqs: "Requirements", key: str, allow_undefined: frozenset) -> str:
    """' (typo of "...") ?' suggestion for an unknown label key, matched
    against the allowed-undefined set and the defined keys by containment,
    edit distance (< len/5), or domain-suffix equality
    (requirements.go:220-239)."""
    for candidates in (sorted(allow_undefined), sorted(reqs.keys())):
        for known in candidates:
            if key in known or _edit_distance(key, known) < len(known) // 5:
                return f' (typo of "{known}"?)'
            if known.endswith(_get_suffix(key)):
                return f' (typo of "{known}"?)'
    return ""


ALLOW_UNDEFINED_WELL_KNOWN_LABELS = frozenset(wk.WELL_KNOWN_LABELS)


def label_requirements(labels: Dict[str, str]) -> Requirements:
    return Requirements.from_labels(labels)


def has_preferred_node_affinity(pod: Pod) -> bool:
    return bool(
        pod
        and pod.spec.affinity
        and pod.spec.affinity.node_affinity
        and pod.spec.affinity.node_affinity.preferred
    )


def _pod_requirements(pod: Pod, include_preferred: bool) -> Requirements:
    """Build requirements from node selector + node affinity
    (requirements.go:81-101): the heaviest preferred term is treated as
    required (relaxation drops it later) and only the FIRST required OR-term is
    used (relaxation pops the rest)."""
    reqs = Requirements.from_labels(pod.spec.node_selector)
    affinity = pod.spec.affinity.node_affinity if pod.spec.affinity else None
    if affinity is None:
        return reqs
    if include_preferred and affinity.preferred:
        heaviest = max(affinity.preferred, key=lambda term: term.weight)
        reqs.add(
            *Requirements.from_node_selector_requirements(
                *heaviest.preference.match_expressions
            ).values()
        )
    if affinity.required:
        reqs.add(
            *Requirements.from_node_selector_requirements(
                *affinity.required[0].match_expressions
            ).values()
        )
    return reqs


def pod_requirements(pod: Pod) -> Requirements:
    """Requirements treating preferences as required (requirements.go:65-67)."""
    return _pod_requirements(pod, include_preferred=True)


def strict_pod_requirements(pod: Pod) -> Requirements:
    """Only true requirements, no preferences (requirements.go:70-72)."""
    return _pod_requirements(pod, include_preferred=False)
