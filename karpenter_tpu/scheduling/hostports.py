"""Host-port conflict tracking (reference pkg/scheduling/hostportusage.go).

Each <hostIP, hostPort, protocol> on a node must be unique; an unspecified IP
(0.0.0.0 / ::) wildcards against every IP on the same port+protocol.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Tuple

from karpenter_tpu.apis.objects import Pod

UNSPECIFIED_IPS = ("0.0.0.0", "::")


@dataclass(frozen=True)
class HostPort:
    ip: str
    port: int
    protocol: str = "TCP"

    def matches(self, other: "HostPort") -> bool:
        """Conflict test (hostportusage.go:49-60): same protocol and port, and
        IPs equal or either side unspecified."""
        if self.protocol != other.protocol or self.port != other.port:
            return False
        if self.ip == other.ip:
            return True
        return self.ip in UNSPECIFIED_IPS or other.ip in UNSPECIFIED_IPS

    def __str__(self):
        return f"IP={self.ip} Port={self.port} Proto={self.protocol}"


def get_host_ports(pod: Pod) -> List[HostPort]:
    """Collect the pod's host ports; empty hostIP defaults to 0.0.0.0
    (hostportusage.go:92-110)."""
    out = []
    for c in pod.spec.containers:
        for p in c.ports:
            if not p.host_port:
                continue
            out.append(
                HostPort(ip=p.host_ip or "0.0.0.0", port=p.host_port, protocol=p.protocol or "TCP")
            )
    return out


class HostPortUsage:
    """Per-node reservation table keyed by pod (hostportusage.go:33-90)."""

    def __init__(self):
        self._reserved: Dict[Tuple[str, str], List[HostPort]] = {}

    def conflicts(self, pod: Pod, ports: List[HostPort]) -> str | None:
        key = (pod.namespace, pod.name)
        for new in ports:
            for pod_key, entries in self._reserved.items():
                if pod_key == key:
                    continue
                for existing in entries:
                    if new.matches(existing):
                        return f"{new} conflicts with existing HostPort configuration {existing}"
        return None

    def add(self, pod: Pod, ports: List[HostPort]) -> None:
        self._reserved[(pod.namespace, pod.name)] = list(ports)

    def delete_pod(self, namespace: str, name: str) -> None:
        self._reserved.pop((namespace, name), None)

    def all_ports(self) -> List[HostPort]:
        return [p for entries in self._reserved.values() for p in entries]
