from karpenter_tpu.scheduling.requirements import (  # noqa: F401
    Requirement,
    Requirements,
    pod_requirements,
    strict_pod_requirements,
    has_preferred_node_affinity,
    label_requirements,
)
from karpenter_tpu.scheduling.taints import Taints, KNOWN_EPHEMERAL_TAINTS  # noqa: F401
