"""CSI volume-attachment tracking.

Equivalent of reference pkg/scheduling/volumeusage.go: resolves a pod's
volumes to (CSI driver, unique volume id) pairs and tracks per-node usage
against CSINode attach limits (volumeusage.go:82,202,211).

Solver-level note: the tensorized existing-node gate counts volumes per pod
rather than deduplicating shared PVCs across pods on a node — a conservative
approximation (it can only refuse placements the set-based reference would
allow when pods share a PVC). The host-side VolumeUsage here keeps the exact
set semantics for cluster-state accounting.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, Optional, Set

from karpenter_tpu.apis.objects import CSINode, PersistentVolume, PersistentVolumeClaim, Pod
from karpenter_tpu.kube.client import KubeClient
from karpenter_tpu.scheduling.storageclass import resolve_storage_class

# Lane for volumes whose PVC/StorageClass can't be resolved. No CSINode ever
# publishes a limit for it, so these volumes never gate a placement — the same
# skip-on-unresolvable behavior the reference takes; the pod will be bound by
# its real driver's limit once the PVC resolves and the next pass runs.
UNKNOWN_DRIVER = "unknown"

# CSI migration (the reference goes through k8s csi-translation-lib,
# scheduling/volumeusage.go:96-118): volumes provisioned by an in-tree
# plugin count against the MIGRATED CSI driver's attach limits, whether the
# plugin name arrives via a StorageClass provisioner or a PV's in-tree
# volume source.
IN_TREE_DRIVER_MIGRATIONS = {
    "kubernetes.io/aws-ebs": "ebs.csi.aws.com",
    "kubernetes.io/gce-pd": "pd.csi.storage.gke.io",
    "kubernetes.io/azure-disk": "disk.csi.azure.com",
    "kubernetes.io/azure-file": "file.csi.azure.com",
    "kubernetes.io/cinder": "cinder.csi.openstack.org",
    "kubernetes.io/vsphere-volume": "csi.vsphere.vmware.com",
}


def migrate_in_tree_driver(name: str) -> str:
    """Translate an in-tree plugin/provisioner name to its CSI driver;
    unknown names pass through unchanged."""
    return IN_TREE_DRIVER_MIGRATIONS.get(name, name)


VolumeSet = Dict[str, FrozenSet[str]]  # driver -> unique volume ids


class VolumeResolver:
    """Caches PVC/PV/StorageClass lookups for one scheduling pass — the
    resolution chain is pure reads, and re-deep-copying them per pod per node
    would dominate a large pass."""

    def __init__(self, kube: KubeClient):
        self.kube = kube
        self._pvc: Dict[str, Optional[PersistentVolumeClaim]] = {}
        self._pv: Dict[str, Optional[PersistentVolume]] = {}
        self._sc_driver: Dict[Optional[str], str] = {}
        self._pod: Dict[str, VolumeSet] = {}

    def pod_volumes(self, pod: Pod) -> VolumeSet:
        """Resolve every PVC/ephemeral volume on the pod to its CSI driver
        (volumeusage.go:82-160)."""
        key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        cached = self._pod.get(key)
        if cached is not None:
            return cached
        out: Dict[str, Set[str]] = {}
        for volume in pod.spec.volumes:
            if volume.persistent_volume_claim is not None:
                claim_name = volume.persistent_volume_claim.claim_name
                vol_id = f"{pod.metadata.namespace}/{claim_name}"
                driver = self._driver_for_pvc(pod.metadata.namespace, claim_name)
            elif volume.ephemeral is not None:
                # generic ephemeral volumes materialize as <pod>-<volume> PVCs
                vol_id = f"{pod.metadata.namespace}/{pod.metadata.name}-{volume.name}"
                driver = self._sc(volume.ephemeral.storage_class_name)
            else:
                continue
            out.setdefault(driver, set()).add(vol_id)
        result = {d: frozenset(v) for d, v in out.items()}
        self._pod[key] = result
        return result

    def _driver_for_pvc(self, namespace: str, claim_name: str) -> str:
        key = f"{namespace}/{claim_name}"
        if key not in self._pvc:
            self._pvc[key] = self.kube.get_opt(
                PersistentVolumeClaim, claim_name, namespace
            )
        pvc = self._pvc[key]
        if pvc is None:
            return UNKNOWN_DRIVER
        if pvc.volume_name:
            if pvc.volume_name not in self._pv:
                self._pv[pvc.volume_name] = self.kube.get_opt(
                    PersistentVolume, pvc.volume_name, ""
                )
            pv = self._pv[pvc.volume_name]
            if pv is not None and pv.csi_driver:
                return pv.csi_driver
            if pv is not None and pv.in_tree_plugin:
                return migrate_in_tree_driver(pv.in_tree_plugin)
        return self._sc(pvc.storage_class_name)

    def _sc(self, name: Optional[str]) -> str:
        if name not in self._sc_driver:
            sc = resolve_storage_class(self.kube, name)
            self._sc_driver[name] = (
                migrate_in_tree_driver(sc.provisioner)
                if sc is not None
                else UNKNOWN_DRIVER
            )
        return self._sc_driver[name]


def get_pod_volumes(kube: KubeClient, pod: Pod) -> VolumeSet:
    """One-shot resolution (tests, webhooks); hot paths share a VolumeResolver."""
    return VolumeResolver(kube).pod_volumes(pod)


def node_volume_limits(kube: KubeClient, node_name: str) -> Dict[str, int]:
    csinode = kube.get_opt(CSINode, node_name, "")
    return dict(csinode.driver_limits) if csinode is not None else {}


class VolumeUsage:
    """Per-node attach tracking with exact unique-volume (set) semantics."""

    def __init__(self):
        self._volumes: Dict[str, Set[str]] = {}  # driver -> ids

    def add(self, volumes: VolumeSet) -> None:
        for driver, ids in volumes.items():
            self._volumes.setdefault(driver, set()).update(ids)

    def counts(self) -> Dict[str, int]:
        return {d: len(v) for d, v in self._volumes.items()}

    def exceeds_limits(self, volumes: VolumeSet, limits: Dict[str, int]) -> Optional[str]:
        """The driver that would overflow, if any (volumeusage.go:202)."""
        for driver, ids in volumes.items():
            limit = limits.get(driver)
            if limit is None:
                continue
            combined = self._volumes.get(driver, set()) | set(ids)
            if len(combined) > limit:
                return f"{driver}: {len(combined)} > limit {limit}"
        return None

    def copy(self) -> "VolumeUsage":
        out = VolumeUsage()
        out._volumes = {d: set(v) for d, v in self._volumes.items()}
        return out
