"""Default StorageClass discovery (reference pkg/scheduling/storageclass.go:41):
an unbound PVC without an explicit class uses the cluster default."""

from __future__ import annotations

from typing import Optional

from karpenter_tpu.apis.objects import StorageClass
from karpenter_tpu.kube.client import KubeClient


def default_storage_class(kube: KubeClient) -> Optional[StorageClass]:
    defaults = [sc for sc in kube.list(StorageClass) if sc.is_default]
    # newest default wins, matching the apiserver's admission behavior
    defaults.sort(key=lambda sc: sc.metadata.creation_timestamp or 0.0, reverse=True)
    return defaults[0] if defaults else None


def resolve_storage_class(kube: KubeClient, name: Optional[str]) -> Optional[StorageClass]:
    if name:
        return kube.get_opt(StorageClass, name, "")
    return default_storage_class(kube)
