from karpenter_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    shard_batch,
    batched_solve,
    stack_problems,
)
