"""Device-mesh sharding of solver batches.

The scale axis of this framework is the *batch of scheduling problems* — most
importantly the consolidation search, which scores hundreds of candidate
node-subsets, each candidate being an independent simulated Solve
(SURVEY.md §2.9 / §5: candidate scoring is embarrassingly parallel; no
collectives are algorithmically required). We lay the candidate axis across a
1-D ``jax.sharding.Mesh``:

    mesh = Mesh(devices, ("candidates",))
    problems: SchedulingProblem with leading [B] batch axis, B sharded

``vmap(solve)`` batches the FFD scan over candidates; jit with NamedSharding
on the inputs lets XLA partition the batch across ICI with no communication
until the final result reduction (inserted automatically). Multi-host slices
extend the same mesh over DCN; nothing in the program changes.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from karpenter_tpu.models.problem import SchedulingProblem
from karpenter_tpu.obs import programs
from karpenter_tpu.ops.ffd import (
    FFDResult,
    _solve_ffd_jit,
    _solve_ffd_runs_jit,
    has_topo_runs as _has_topo_runs,
    initial_state,
    max_run_bucket as _max_run_bucket,
)

CANDIDATE_AXIS = "candidates"


def _tree_bytes(tree) -> int:
    return int(
        sum(getattr(a, "nbytes", 0) for a in jax.tree_util.tree_leaves(tree))
    )


def make_mesh(
    n_devices: Optional[int] = None,
    axis: str = CANDIDATE_AXIS,
    devices: Optional[Sequence] = None,
) -> Mesh:
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis,))


def healthy_devices() -> list:
    """The local device list mesh carving starts from: every device, minus
    whatever the mesh-health tracker (solver/mesh_health.py) currently
    excludes when KARPENTER_TPU_MESH_HEALTH is on. Flag off this is exactly
    ``jax.devices()`` — one env read, no tracker construction."""
    from karpenter_tpu.solver import mesh_health

    devices = jax.devices()
    if mesh_health.enabled() and mesh_health.has_tracker():
        devices = mesh_health.tracker().healthy_devices(devices)
    return list(devices)


def stack_problems(problems: Sequence[SchedulingProblem]) -> SchedulingProblem:
    """Stack identically-shaped problems along a new leading candidate axis.
    Callers pad (ops/padding.py) to a common bucket first."""
    return jax.tree_util.tree_map(lambda *xs: np.stack(xs), *problems)


def shard_batch(batch: SchedulingProblem, mesh: Mesh, axis: str = CANDIDATE_AXIS):
    """Place a stacked problem so its candidate axis is split across the mesh."""
    sharding = NamedSharding(mesh, P(axis))
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), batch)


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def _batched_solve_jit(
    batch: SchedulingProblem, max_claims: int, max_run: int, with_topo: bool
) -> FFDResult:
    return jax.vmap(
        lambda p: _solve_ffd_runs_jit.__wrapped__(
            p, initial_state(p, max_claims), max_run, with_topo
        )
    )(batch)


def _pad_lane_axis(tree, mesh: Mesh):
    """Pad every leaf's leading (candidate) axis up to a multiple of the mesh
    size by repeating the last lane — NamedSharding needs the sharded axis
    divisible by the device count, and a duplicated valid lane is inert (its
    rows are sliced off the result by ``_trim_lane_axis``). Returns the padded
    tree and the original lane count."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return tree, 0
    b = int(leaves[0].shape[0])
    n_dev = mesh.devices.size
    pad = (-b) % n_dev
    if pad == 0:
        return tree, b
    padded = jax.tree_util.tree_map(
        lambda x: np.concatenate(
            [np.asarray(x), np.repeat(np.asarray(x[-1:]), pad, axis=0)]
        ),
        tree,
    )
    return padded, b


def _trim_lane_axis(result, b: int):
    """Drop the lanes ``_pad_lane_axis`` added (no-op when nothing was)."""
    leaves = jax.tree_util.tree_leaves(result)
    if not leaves or int(leaves[0].shape[0]) == b:
        return result
    return jax.tree_util.tree_map(lambda x: x[:b], result)


def batched_solve(
    batch: SchedulingProblem, max_claims: int, mesh: Optional[Mesh] = None
) -> FFDResult:
    """Solve B independent scheduling problems in one compiled program; with a
    mesh, the candidate axis is sharded across devices and each device runs
    its slice of the scan batch."""
    max_run = _max_run_bucket(batch)
    with_topo = _has_topo_runs(batch)
    b_orig = 0
    if mesh is not None:
        batch, b_orig = _pad_lane_axis(batch, mesh)
        batch = shard_batch(batch, mesh)
    obs = programs.begin_dispatch(
        "batched_solve", max_claims, batch,
        statics={"max_run": max_run, "with_topo": with_topo},
    )
    result = _batched_solve_jit(batch, max_claims, max_run, with_topo)
    if mesh is not None:
        result = _trim_lane_axis(result, b_orig)
    if obs is not None:
        obs.finish(problem_bytes=_tree_bytes(batch))
    return result


@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4))
def _batched_screen_jit(
    batch: SchedulingProblem, max_claims: int, passes: int, max_run: int,
    with_topo: bool,
) -> FFDResult:
    """Multi-pass batched solve: after each pass, pods that placed are masked
    out via pod_active (preserving the run structure) and the scan re-runs
    over the carried bin state so order-dependent pods (affinity on a pod
    placed later in the queue) get their retry — the sequential backend's
    requeue loop (solver/jax_backend.py pass structure) without relaxation and
    without a host round-trip. All passes run in one compiled program."""
    import dataclasses

    from karpenter_tpu.ops.ffd import KIND_FAIL

    def one(p: SchedulingProblem) -> FFDResult:
        r = _solve_ffd_runs_jit.__wrapped__(
            p, initial_state(p, max_claims), max_run, with_topo
        )
        kind, index = r.kind, r.index
        for _ in range(passes - 1):
            placed = kind < KIND_FAIL
            p2 = dataclasses.replace(p, pod_active=p.pod_active & ~placed)
            r = _solve_ffd_runs_jit.__wrapped__(p2, r.state, max_run, with_topo)
            kind = jnp.where(placed, kind, r.kind)
            index = jnp.where(placed, index, r.index)
        return FFDResult(kind=kind, index=index, state=r.state)

    return jax.vmap(one)(batch)


def batched_screen(
    batch: SchedulingProblem,
    max_claims: int,
    mesh: Optional[Mesh] = None,
    passes: int = 3,
) -> FFDResult:
    """batched_solve with ``passes`` placement passes per problem (see
    _batched_screen_jit) — the consolidation scorer's workhorse."""
    max_run = _max_run_bucket(batch)
    with_topo = _has_topo_runs(batch)
    b_orig = 0
    if mesh is not None:
        # actually distribute the candidate lanes: pad B to a device multiple
        # (a 100-candidate screen on 8 devices was previously unshardable)
        # and place the stacked tree with NamedSharding so each device runs
        # its slice of the vmapped scan
        batch, b_orig = _pad_lane_axis(batch, mesh)
        batch = shard_batch(batch, mesh)
    obs = programs.begin_dispatch(
        "batched_screen", max_claims, batch,
        statics={"passes": passes, "max_run": max_run, "with_topo": with_topo},
    )
    result = _batched_screen_jit(batch, max_claims, passes, max_run, with_topo)
    if mesh is not None:
        result = _trim_lane_axis(result, b_orig)
    if obs is not None:
        obs.finish(problem_bytes=_tree_bytes(batch))
    return result


class ScreenVariants:
    """The four arrays a consolidation subset variant actually changes on the
    shared union problem — batching these (leading [B] axis) instead of
    stacking the whole SchedulingProblem B times cuts the screen's host
    stacking, upload, and per-variant statics recompute to the variant data
    itself."""

    def __init__(self, node_avail, pod_active, grp_counts0, grp_registered0):
        self.node_avail = node_avail
        self.pod_active = pod_active
        self.grp_counts0 = grp_counts0
        self.grp_registered0 = grp_registered0

    def tree(self):
        return (self.node_avail, self.pod_active, self.grp_counts0, self.grp_registered0)


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5))
def _lean_screen_jit(
    base: SchedulingProblem,
    variants,  # 4-tuple of [B, ...] arrays (ScreenVariants.tree())
    max_claims: int,
    passes: int,
    max_run: int,
    with_topo: bool,
) -> FFDResult:
    import dataclasses

    from karpenter_tpu.ops.ffd import KIND_FAIL

    # vmap over ONLY the variant arrays; the base problem rides along
    # un-batched (XLA broadcasts it, statics are computed once)
    def one(node_avail, pod_active, grp_counts0, grp_registered0) -> FFDResult:
        p = dataclasses.replace(
            base,
            node_avail=node_avail,
            pod_active=pod_active,
            grp_counts0=grp_counts0,
            grp_registered0=grp_registered0,
        )
        r = _solve_ffd_runs_jit.__wrapped__(
            p, initial_state(p, max_claims), max_run, with_topo
        )
        kind, index = r.kind, r.index
        for _ in range(passes - 1):
            placed = kind < KIND_FAIL
            p2 = dataclasses.replace(p, pod_active=p.pod_active & ~placed)
            r = _solve_ffd_runs_jit.__wrapped__(p2, r.state, max_run, with_topo)
            kind = jnp.where(placed, kind, r.kind)
            index = jnp.where(placed, index, r.index)
        return FFDResult(kind=kind, index=index, state=r.state)

    return jax.vmap(one)(*variants)


def lean_screen(
    base: SchedulingProblem,
    variants: ScreenVariants,
    max_claims: int,
    mesh: Optional[Mesh] = None,
    passes: int = 3,
) -> FFDResult:
    """The consolidation screen on a shared base problem + per-subset variant
    arrays (see ScreenVariants). With a mesh, the variant axis is sharded and
    the base is replicated."""
    max_run = _max_run_bucket(base)
    with_topo = _has_topo_runs(base)
    tree = variants.tree()
    b_orig = 0
    if mesh is not None:
        tree, b_orig = _pad_lane_axis(tree, mesh)
        sharding = NamedSharding(mesh, P(CANDIDATE_AXIS))
        tree = tuple(jax.device_put(a, sharding) for a in tree)
        replicate = NamedSharding(mesh, P())
        base = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, replicate), base
        )
    obs = programs.begin_dispatch(
        "lean_screen", max_claims, (base, tree),
        statics={"passes": passes, "max_run": max_run, "with_topo": with_topo},
    )
    result = _lean_screen_jit(base, tree, max_claims, passes, max_run, with_topo)
    if mesh is not None:
        result = _trim_lane_axis(result, b_orig)
    if obs is not None:
        obs.finish(problem_bytes=_tree_bytes((base, tree)))
    return result


class ResidualVariants:
    """The two arrays a residual-screen lane changes on the shared union
    problem (disruption/screen_delta.py): the subset's node rows masked out
    and ONLY its resident pod rows active. The lane's evicted residents are
    the active rows; everything the base world placed rides along pinned in
    the carried state. Group census arrays are deliberately absent: the
    delta path stands down whenever any pod consults the census, so the base
    problem's arrays ride along inert."""

    def __init__(self, node_avail, pod_active):
        self.node_avail = node_avail
        self.pod_active = pod_active

    def tree(self):
        return (self.node_avail, self.pod_active)


@functools.partial(jax.jit, static_argnums=(4, 5))
def _residual_screen_jit(
    base: SchedulingProblem,
    carried,  # FFDState: base-world consumption pinned, broadcast per lane
    variants,  # 2-tuple of [B, ...] arrays (ResidualVariants.tree())
    run_idx,  # i32[RNr] SHARED across lanes: union of touched runs, -1 pads
    max_run: int,
    with_topo: bool,
) -> FFDResult:
    import dataclasses

    # the run trim is SHARED across lanes on purpose: a batched (per-lane)
    # run axis would batch the scan's xs, so vmap could no longer hoist the
    # per-run representative computation out of the lane axis — measured
    # 2.4x slower than this form at B=100, wiping out the trim. Per-lane
    # trimming also buys nothing a shared trim doesn't: lane cost is linear
    # in the run axis and independent of how many rows are active
    # (docs/PERF_NOTES.md round 20), and skipped lanes' rows in a shared
    # run are inert via pod_active. -1 entries gather run 0 with length
    # forced to 0 — the same (start=0, len=0, mode=ANALYTIC) no-op the
    # padded run axis already proves out (ops/padding.pad_problem).
    valid = run_idx >= 0
    ridx = jnp.where(valid, run_idx, 0)
    p0 = dataclasses.replace(
        base,
        run_start=jnp.asarray(base.run_start)[ridx],
        run_len=jnp.where(valid, jnp.asarray(base.run_len)[ridx], 0),
        run_mode=jnp.where(valid, jnp.asarray(base.run_mode)[ridx], 1),
    )

    # single pass by construction: the delta path only dispatches when one
    # placement pass is a fixed point (no topology interaction — the same
    # passes=1 condition score_subsets already proves)
    def one(node_avail, pod_active) -> FFDResult:
        p = dataclasses.replace(p0, node_avail=node_avail, pod_active=pod_active)
        return _solve_ffd_runs_jit.__wrapped__(p, carried, max_run, with_topo)

    return jax.vmap(one)(*variants)


def residual_screen(
    base: SchedulingProblem,
    carried,
    variants: ResidualVariants,
    run_idx,
    max_claims: int,
    mesh: Optional[Mesh] = None,
) -> FFDResult:
    """The incremental consolidation screen: every lane re-solves ONLY its
    resident rows, over the shared union of touched runs, against the shared
    carried base world. Same dispatch shape as lean_screen — variant axis
    sharded across the mesh; base problem, carried state, and the run-trim
    indices replicated."""
    max_run = _max_run_bucket(base)
    # with_topo is False by contract: screen_delta.batch_standdown rejects
    # any base problem with topology-coupled runs before this is reached
    # (lax.switch would silently clamp a RUN_TOPO mode into the analytic
    # branch otherwise)
    with_topo = False
    tree = variants.tree()
    run_idx = np.asarray(run_idx, dtype=np.int32)
    b_orig = 0
    if mesh is not None:
        tree, b_orig = _pad_lane_axis(tree, mesh)
        sharding = NamedSharding(mesh, P(CANDIDATE_AXIS))
        tree = tuple(jax.device_put(a, sharding) for a in tree)
        replicate = NamedSharding(mesh, P())
        base = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, replicate), base
        )
        carried = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, replicate), carried
        )
        run_idx = jax.device_put(run_idx, replicate)
    from karpenter_tpu.solver import aot

    handle = aot.maybe_begin(
        residual_screen, (base, carried, tree, run_idx), max_claims, None
    )
    obs = programs.begin_dispatch(
        "residual_screen", max_claims, (base, carried, tree, run_idx),
        statics={"max_run": max_run, "with_topo": with_topo},
    )
    if handle is not None:
        result = handle.call()
    else:
        result = _residual_screen_jit(
            base, carried, tree, run_idx, max_run, with_topo
        )
    if mesh is not None:
        result = _trim_lane_axis(result, b_orig)
    if obs is not None:
        obs.finish(
            problem_bytes=_tree_bytes((base, carried, tree, run_idx)),
            source_override=(
                handle.source_override if handle is not None else None
            ),
        )
    return result


def default_mesh(min_devices: int = 2) -> Optional[Mesh]:
    """A 1-D candidate mesh over every HEALTHY local device, or None below
    ``min_devices`` (vmap alone already uses the whole chip — the same
    standdown a recarve below 2 devices degrades to). Flag-off mesh health
    changes nothing: healthy_devices() is then jax.devices() verbatim."""
    devices = healthy_devices()
    if len(devices) < min_devices:
        return None
    return make_mesh(devices=devices)


def carve_meshes(n_slices: int, devices=None) -> list:
    """Carve the local devices into ``n_slices`` contiguous 1-D candidate
    meshes — one per serve replica (serve/replica.py), so fleets partition
    the host instead of contending for all of it.

    The split is balanced with the remainder devices going to the FIRST
    slices: slice 0 is always the largest, and the replica set pins
    big-tenant streams there. A slice that lands fewer than 2 devices gets
    None (a mesh over one device buys nothing over vmap — same contract as
    default_mesh). Device discovery happens at call time, never at import
    time, and excludes mesh-health-failed devices when the flag is on.

    The carve is a DETERMINISTIC function of the device SET: devices sort by
    id before chunking, so a shrunken list (post-recarve) always yields the
    same slices regardless of the order the health filter or a caller
    produced it in — failover placement stays stable across repeated
    recarves (tests/test_mesh_health.py pins this)."""
    if devices is None:
        devices = healthy_devices()
    devices = sorted(devices, key=lambda d: int(getattr(d, "id", 0)))
    n_slices = max(1, int(n_slices))
    base, extra = divmod(len(devices), n_slices)
    out = []
    start = 0
    for i in range(n_slices):
        size = base + (1 if i < extra else 0)
        chunk = devices[start:start + size]
        start += size
        if len(chunk) >= 2:
            out.append(Mesh(np.array(chunk), (CANDIDATE_AXIS,)))
        else:
            out.append(None)
    return out


@functools.lru_cache(maxsize=None)
def shard_sweeps_program(
    mesh: Mesh, max_claims: int, bounds_free: bool, wavefront: int
):
    """ONE compiled program running a batch of independent sweeps solves with
    the partition axis laid across ``mesh`` (shard/solve.py).

    ``shard_map`` (not plain vmap-of-sharded-batch) is the load-bearing
    choice: the sweeps solve is a data-dependent ``while_loop``, and under a
    single partitioned program every device would iterate in lockstep to the
    GLOBAL worst-case sweep count. ``shard_map`` gives each device its own
    while-loop over its local partitions, so a device whose sub-problems
    converge early goes idle instead of replaying dead sweeps
    (check_rep=False — the outputs are genuinely per-shard, nothing is
    replicated). The jit wrapper pins in_shardings/out_shardings to the mesh
    and donates the stacked problem: the batch is consumed by the dispatch,
    so XLA reuses its device pages for the result landscape.

    Cached per (mesh, claim bucket, bounds_free, wavefront): Mesh is hashable
    and each distinct static tuple is its own executable, mirroring the
    unsharded program-key discipline."""
    from jax.experimental.shard_map import shard_map

    from karpenter_tpu.ops.ffd_sweeps import _solve_ffd_sweeps_fresh_jit

    def _local(batch: SchedulingProblem) -> FFDResult:
        return jax.vmap(
            lambda p: _solve_ffd_sweeps_fresh_jit.__wrapped__(
                p, max_claims, bounds_free, wavefront
            )
        )(batch)

    spec = P(CANDIDATE_AXIS)
    mapped = shard_map(
        _local, mesh=mesh, in_specs=spec, out_specs=spec, check_rep=False
    )
    sharding = NamedSharding(mesh, spec)

    def shard_sweeps(batch: SchedulingProblem) -> FFDResult:
        return mapped(batch)

    return jax.jit(
        shard_sweeps,
        in_shardings=sharding,
        out_shardings=sharding,
        donate_argnums=(0,),
    )


@functools.lru_cache(maxsize=None)
def shard_relax2_sweeps_program(
    mesh: Mesh, max_claims: int, bounds_free: bool, wavefront: int,
    iters: int, step: float, n_passes: int,
):
    """The convex-relaxation twin of ``shard_sweeps_program``
    (KARPENTER_TPU_RELAX2, round 22): each lane runs the windowed
    projected-gradient phase-1 solve (ops/relax2.py) and hands its claim
    landscape straight into the carried sweeps repair — ONE fused program,
    so the fractional solve, the rounding ladder, and the repair loop share
    a single dispatch per escalation round and the phase-1 state never
    round-trips to the host. Per-lane Relax2Stats ride out alongside the
    FFDResult (vmap gives every scalar stat a [lanes] axis) so the backend
    can aggregate placed_frac/pgd_iterations without a second fetch.

    Deliberately a sharded ``jit(vmap)``, NOT ``shard_map`` like the fresh
    sweeps program. Under shard_map on the multi-device SPMD path, the
    carried repair's data-dependent while_loop MISCOMPILES when the loop
    carry is phase-1 state (not constants): every device except device 0
    returns the carry's INPUT state with the state updates dropped, while
    kinds/idxs partially update — decoded claims then disagree with their
    own request sums and the per-partition gate rejects the merge
    (tests/test_shard_parity.py::test_relax2_shard_consistency pins the
    repro; the fresh path and a cold fresh_carry are unaffected, so
    shard_sweeps_program keeps shard_map). vmap's batched while runs every
    lane to the GLOBAL trip count with converged lanes masked — lockstep
    the shard_map design avoided — but relax2 makes that cheap: phase 1 is
    a fixed-trip scan and the residue queues are a fraction of the fresh
    queues, so the worst lane's few extra sweeps cost far less than the
    round trip a standdown (the alternative) would.

    Cached per (mesh, claim bucket, bounds_free, wavefront, PGD statics) —
    iters/step/passes are compiled in, mirroring the unsharded relax2
    program key."""
    import dataclasses

    from karpenter_tpu.ops.ffd_sweeps import _solve_ffd_sweeps_carried_jit
    from karpenter_tpu.ops.relax2 import _relax2_place_jit

    def _lane(p: SchedulingProblem):
        r = _relax2_place_jit.__wrapped__(
            p, max_claims, bounds_free, iters, step, n_passes
        )
        residue = dataclasses.replace(p, pod_active=r.residue_active)
        res = _solve_ffd_sweeps_carried_jit.__wrapped__(
            residue, (r.state, r.kind, r.index), max_claims, bounds_free,
            wavefront,
        )
        return res, r.stats

    sharding = NamedSharding(mesh, P(CANDIDATE_AXIS))

    def shard_relax2_sweeps(batch: SchedulingProblem):
        return jax.vmap(_lane)(batch)

    return jax.jit(
        shard_relax2_sweeps,
        in_shardings=sharding,
        out_shardings=sharding,
        donate_argnums=(0,),
    )


def scheduled_counts(result: FFDResult) -> jnp.ndarray:
    """[B] number of pods placed per candidate problem — the consolidation
    scoring reduction (does the cluster still fit with these nodes gone?)."""
    from karpenter_tpu.ops.ffd import KIND_CLAIM, KIND_NEW_CLAIM, KIND_NODE

    ok = (
        (result.kind == KIND_NODE)
        | (result.kind == KIND_CLAIM)
        | (result.kind == KIND_NEW_CLAIM)
    )
    return ok.sum(axis=-1)
