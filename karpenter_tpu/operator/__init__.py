from karpenter_tpu.operator.operator import Operator
from karpenter_tpu.operator.options import Options

__all__ = ["Operator", "Options"]
