"""Metrics + health endpoints.

Equivalent of the reference's metrics port and health probes
(operator.go:139-182): /metrics serves the registry in Prometheus text
format. /healthz is liveness-only (the process answers — always 200);
/readyz reflects REAL readiness when the operator wires an OperatorStatus in
(solver warmup finished and the solver circuit not hard-open), and /statusz
exposes the supervisor's circuit/failure state as JSON for humans and
dashboards. --enable-profiling maps to the JAX profiler (the reference
mounts net/http/pprof; the TPU-native analogue is a jax.profiler trace,
SURVEY.md §5).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from karpenter_tpu.metrics import REGISTRY

# Every debug endpoint the handler resolves — /statusz indexes these and
# tools/metrics_lint.py checks docs/OBSERVABILITY.md names each one (and
# names nothing that is not here).
DEBUG_ENDPOINTS = (
    "/debug/explain",
    "/debug/flight",
    "/debug/programs",
    "/debug/slo",
    "/debug/tenants",
    "/debug/traces",
)


def _series(name: str, labels, value) -> str:
    if labels:
        label_s = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
        return f"{name}{{{label_s}}} {value}"
    return f"{name} {value}"


def render_prometheus() -> str:
    # HELP/TYPE headers come from describe() so every REGISTERED metric
    # appears in the exposition even before its first sample — scrape configs
    # and tools/metrics_lint.py see the full surface from process start.
    from karpenter_tpu.obs import slo

    # burn-rate gauges are computed on the read path (the engine's hot path
    # never allocates label dicts); one flag check when the engine is off
    slo.refresh_metrics()
    samples: dict = {}
    for kind, name, labels, value in REGISTRY.collect():
        samples.setdefault(name, []).append((kind, labels, value))
    lines = []
    for kind, name, help_ in REGISTRY.describe():
        lines.append(f"# HELP {name} {help_}")
        lines.append(f"# TYPE {name} {kind}")
        for _, labels, value in samples.pop(name, ()):
            if kind == "histogram":
                lines.append(_series(name + "_count", labels, value["count"]))
                lines.append(_series(name + "_sum", labels, value["sum"]))
            else:
                lines.append(_series(name, labels, value))
    for name, entries in samples.items():  # unregistered strays, if any
        for kind, labels, value in entries:
            if kind == "histogram":
                lines.append(_series(name + "_count", labels, value["count"]))
                lines.append(_series(name + "_sum", labels, value["sum"]))
            else:
                lines.append(_series(name, labels, value))
    return "\n".join(lines) + "\n"


class OperatorStatus:
    """Readiness/introspection the endpoints consult. ``supervisor`` is the
    SupervisedSolver (or None for an unwrapped backend); ``warmup_ready``
    answers whether startup compilation finished."""

    def __init__(
        self,
        supervisor=None,
        warmup_ready: Optional[Callable[[], bool]] = None,
        serve_service=None,
    ):
        self.supervisor = supervisor
        self.warmup_ready = warmup_ready
        # the multi-tenant SolveService (serve/), when the operator runs one
        # (KARPENTER_TPU_SERVE=1): readiness then also requires a live
        # dispatcher, and /statusz + /debug/tenants expose its streams
        self.serve_service = serve_service

    def ready(self) -> bool:
        """Ready to serve traffic: warmup done, no restart recovery in
        flight, and the primary solve path not hard-open. Half-open counts as
        ready — the next solve probes the primary and the fallback still
        answers either way. Recovery blocks only while restoring/probing: a
        FAILED recovery un-blocks (it degrades to cold compiles)."""
        if self.warmup_ready is not None and not self.warmup_ready():
            return False
        from karpenter_tpu.solver import aot

        if aot.recovery_blocking():
            # restored AOT executables must pass the probe solve before any
            # traffic can land on them (solver/warmup.py restore_and_probe)
            return False
        if self.supervisor is not None:
            from karpenter_tpu.solver.supervisor import CIRCUIT_OPEN

            if self.supervisor.circuit_state() == CIRCUIT_OPEN:
                return False
        if self.serve_service is not None and not self.serve_service.healthy():
            # a closed service or dead dispatcher thread would queue
            # requests forever — stop routing traffic here
            return False
        return True

    def statusz(self) -> dict:
        from karpenter_tpu.obs import programs, trace

        out = {"ready": self.ready()}
        if self.warmup_ready is not None:
            out["warmup_complete"] = bool(self.warmup_ready())
        if self.supervisor is not None:
            out["solver"] = self.supervisor.status()
        captured = trace.ring().snapshot()
        summary = {"enabled": trace.enabled(), "captured": len(captured)}
        if captured:
            last = captured[0]
            summary["last"] = {
                k: last.get(k)
                for k in ("trace_id", "name", "backend", "duration_s", "phases")
            }
        out["traces"] = summary
        # restart recovery (solver/aot.py): current phase plus the last
        # completed recovery record — restore summary, probe verdict, wall
        # seconds, and the recovery trace id for /debug/traces drill-down
        from karpenter_tpu.solver import aot

        recovery = {"phase": aot.recovery_phase()}
        last = aot.last_recovery()
        if last is not None:
            recovery["last_restart_recovery"] = last
        out["recovery"] = recovery
        # program registry one-liner (obs/programs.py): compiled-program
        # count, launch totals, cache-source split, last memory sample
        out["programs"] = programs.registry().summary()
        from karpenter_tpu.obs import explain

        # unschedulable summary over the report ring (/debug/explain drills in)
        out["unschedulable"] = explain.summary()
        if self.serve_service is not None:
            # multi-tenant fleet totals (/debug/tenants has per-stream rows)
            out["serve"] = self.serve_service.summary()
        # degraded-mesh health (solver/mesh_health.py): per-device states,
        # recarve log, last recovery wall time — only once a tracker exists
        # (flag off or no failures yet means no section, zero cost)
        from karpenter_tpu.solver import mesh_health

        if mesh_health.has_tracker():
            out["mesh_health"] = mesh_health.tracker().snapshot()
        # fleet SLO rollup (obs/slo.py): single verdict (ok/warn/breach)
        # with worst-objective attribution; /debug/slo has the full table
        from karpenter_tpu.obs import slo

        out["slo"] = slo.rollup()
        out["debug_endpoints"] = list(DEBUG_ENDPOINTS)
        return out


class _Handler(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 (http.server API)
        status: Optional[OperatorStatus] = getattr(self.server, "status", None)
        if self.path.startswith("/metrics"):
            body = render_prometheus().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
        elif self.path.startswith("/healthz"):
            # liveness only: if this handler runs, the process is alive
            body = b"ok\n"
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
        elif self.path.startswith("/readyz"):
            # no wired status (tests, bare serve()) preserves always-ready
            if status is None or status.ready():
                body = b"ok\n"
                self.send_response(200)
            else:
                body = b"not ready\n"
                self.send_response(503)
            self.send_header("Content-Type", "text/plain")
        elif self.path.startswith("/statusz"):
            payload = status.statusz() if status is not None else {"ready": True}
            body = (json.dumps(payload, indent=1, default=str) + "\n").encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
        elif self.path.startswith("/debug/programs"):
            from karpenter_tpu.obs import programs

            # full program inventory: keys, compile times by cache source,
            # launch counters, byte accounting, device-memory sample ring
            body = (
                json.dumps(programs.registry().snapshot(), indent=1,
                           default=str)
                + "\n"
            ).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
        elif self.path.startswith("/debug/explain"):
            from karpenter_tpu.obs import explain

            # decision provenance of recent solves: per-pod reasons, hints,
            # raw gate bits, nomination margins (most recent report first)
            payload = {
                "enabled": explain.enabled(),
                "captured": len(explain.ring()),
                "reports": explain.ring().snapshot(),
            }
            body = (json.dumps(payload, indent=1, default=str) + "\n").encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
        elif self.path.startswith("/debug/tenants"):
            from karpenter_tpu import serve as serve_pkg

            # per-tenant stream rows of the multi-tenant solve service:
            # queue pressure, DWRR balance, outcome counters, latency
            # quantiles, circuit state. Resolves the wired service first,
            # then the process-wide one (a bare serve() still answers).
            service = (
                getattr(status, "serve_service", None)
                or serve_pkg.current_service()
            )
            payload = (
                service.snapshot()
                if service is not None
                else {"enabled": serve_pkg.enabled(), "tenants": []}
            )
            body = (json.dumps(payload, indent=1, default=str) + "\n").encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
        elif self.path.startswith("/debug/slo"):
            from karpenter_tpu.obs import slo

            # the full objective table: per-objective burn rates, event
            # counts, breach history, plus the fleet rollup verdict
            body = (
                json.dumps(slo.debug_payload(), indent=1, default=str) + "\n"
            ).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
        elif self.path.startswith("/debug/flight"):
            from karpenter_tpu.obs import flight

            # the flight-recorder ring (chronological) and the on-disk dump
            # inventory; tools/flight_report.py renders either as a timeline
            body = (
                json.dumps(flight.debug_payload(), indent=1, default=str)
                + "\n"
            ).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
        elif self.path.startswith("/debug/traces"):
            from karpenter_tpu.obs import trace

            captured = trace.ring().snapshot()  # most recent first
            if "chrome" in self.path or "format=chrome" in self.path:
                # Perfetto/chrome://tracing-loadable trace-event JSON
                body = (trace.chrome_trace_json(captured, indent=1) + "\n").encode()
            else:
                payload = {
                    "enabled": trace.enabled(),
                    "captured": len(captured),
                    "traces": captured,
                }
                body = (json.dumps(payload, indent=1, default=str) + "\n").encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
        else:
            body = b"not found\n"
            self.send_response(404)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # quiet
        pass


def serve(
    port: int, host: str = "", status: Optional[OperatorStatus] = None
) -> ThreadingHTTPServer:
    """Start the endpoint server on a daemon thread; returns the server (call
    .shutdown() to stop). Binds all interfaces by default so in-cluster
    probes/scrapes against the pod IP work."""
    server = ThreadingHTTPServer((host, port), _Handler)
    server.status = status
    threading.Thread(target=server.serve_forever, daemon=True,
                     name=f"karpenter-tpu/serve-{port}").start()
    return server


def start_profiler(trace_dir: str = "/tmp/karpenter-tpu-profile") -> Optional[str]:
    """--enable-profiling: begin a jax profiler trace (SURVEY.md §5)."""
    try:
        import jax

        jax.profiler.start_trace(trace_dir)
        return trace_dir
    except Exception:
        return None


def stop_profiler() -> None:
    try:
        import jax

        jax.profiler.stop_trace()
    except Exception:
        pass
