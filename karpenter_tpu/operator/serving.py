"""Metrics + health endpoints.

Equivalent of the reference's metrics port and health probes
(operator.go:139-182): /metrics serves the registry in Prometheus text
format, /healthz and /readyz answer 200. --enable-profiling maps to the JAX
profiler (the reference mounts net/http/pprof; the TPU-native analogue is a
jax.profiler trace, SURVEY.md §5).
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from karpenter_tpu.metrics import REGISTRY


def _series(name: str, labels, value) -> str:
    if labels:
        label_s = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
        return f"{name}{{{label_s}}} {value}"
    return f"{name} {value}"


def render_prometheus() -> str:
    lines = []
    for kind, name, labels, value in REGISTRY.collect():
        if kind == "histogram":
            lines.append(_series(name + "_count", labels, value["count"]))
            lines.append(_series(name + "_sum", labels, value["sum"]))
        else:
            lines.append(_series(name, labels, value))
    return "\n".join(lines) + "\n"


class _Handler(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 (http.server API)
        if self.path.startswith("/metrics"):
            body = render_prometheus().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
        elif self.path.startswith(("/healthz", "/readyz")):
            body = b"ok\n"
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
        else:
            body = b"not found\n"
            self.send_response(404)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # quiet
        pass


def serve(port: int, host: str = "") -> ThreadingHTTPServer:
    """Start the endpoint server on a daemon thread; returns the server (call
    .shutdown() to stop). Binds all interfaces by default so in-cluster
    probes/scrapes against the pod IP work."""
    server = ThreadingHTTPServer((host, port), _Handler)
    threading.Thread(target=server.serve_forever, daemon=True,
                     name=f"karpenter-tpu/serve-{port}").start()
    return server


def start_profiler(trace_dir: str = "/tmp/karpenter-tpu-profile") -> Optional[str]:
    """--enable-profiling: begin a jax profiler trace (SURVEY.md §5)."""
    try:
        import jax

        jax.profiler.start_trace(trace_dir)
        return trace_dir
    except Exception:
        return None


def stop_profiler() -> None:
    try:
        import jax

        jax.profiler.stop_trace()
    except Exception:
        pass
