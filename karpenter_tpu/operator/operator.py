"""Operator — assembles and runs the whole framework.

Equivalent of reference pkg/operator/operator.go plus
pkg/controllers/controllers.go:47-82 (the definitive controller registry) and
the Singleton loop abstraction (operator/controller/singleton.go:53-182).

The reference runs each controller on controller-runtime goroutines; here
every controller exposes a poll-style reconcile and the Operator drives them
either cooperatively (``step()`` — deterministic, what tests and simulations
use) or on real threads (``start()``). The watch-driven paths (informers, the
provisioning trigger) stay event-driven through the kube store's synchronous
watch fan-out either way.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, List, Optional

from karpenter_tpu.cloudprovider.metrics import MetricsCloudProvider
from karpenter_tpu.cloudprovider.types import CloudProvider
from karpenter_tpu.controllers.metrics_exporters import MetricsExporter
from karpenter_tpu.controllers.nodeclaim_consistency import (
    ConsistencyController,
    POLL_PERIOD_SECONDS as CONSISTENCY_PERIOD,
)
from karpenter_tpu.controllers.nodeclaim_disruption import DisruptionMarkerController
from karpenter_tpu.controllers.nodeclaim_garbagecollection import (
    GarbageCollectionController,
    POLL_PERIOD_SECONDS as GC_PERIOD,
)
from karpenter_tpu.controllers.nodeclaim_lifecycle import LifecycleController
from karpenter_tpu.controllers.nodeclaim_termination import TerminationController
from karpenter_tpu.controllers.node_termination import NodeTerminationController
from karpenter_tpu.controllers.nodepool_controllers import (
    LeaseGarbageCollectionController,
    NodePoolCounterController,
    NodePoolHashController,
)
from karpenter_tpu.disruption.controller import (
    Controller as DisruptionController,
    POLL_PERIOD_SECONDS as DISRUPTION_PERIOD,
)
from karpenter_tpu.events import Recorder
from karpenter_tpu.kube.client import KubeClient
from karpenter_tpu.provisioning.batcher import Batcher
from karpenter_tpu.provisioning.controller import watch_pods
from karpenter_tpu.provisioning.provisioner import Provisioner
from karpenter_tpu.operator.options import Options
from karpenter_tpu.solver.jax_backend import JaxSolver
from karpenter_tpu.solver.oracle import OracleSolver
from karpenter_tpu.state.cluster import Cluster
from karpenter_tpu.state.informer import start_informers
from karpenter_tpu.utils.clock import Clock


@dataclass
class _Registered:
    name: str
    reconcile: Callable[[], object]
    period_s: float
    next_run: float = 0.0


class Operator:
    def __init__(
        self,
        cloud_provider: CloudProvider,
        options: Optional[Options] = None,
        kube: Optional[KubeClient] = None,
        clock: Optional[Clock] = None,
        recorder: Optional[Recorder] = None,
    ):
        self.options = options or Options()
        self.clock = clock or Clock()
        self.kube = kube or KubeClient(clock=self.clock)
        self.recorder = recorder or Recorder(clock=self.clock)
        # method-duration decorator around the provider (cloudprovider/metrics)
        self.cloud_provider = MetricsCloudProvider(cloud_provider)
        self.cluster = Cluster(self.kube, self.clock)
        # every controller solve (provisioning AND the disruption
        # simulations, which call provisioner.solver directly) goes through
        # the supervisor: deadline, retries, invariant gate, circuit-broken
        # oracle fallback (solver/supervisor.py)
        from karpenter_tpu.solver.supervisor import SupervisedSolver

        if self.options.solver_backend == "jax":
            self.supervisor = SupervisedSolver(JaxSolver(), fallback=OracleSolver())
        else:
            self.supervisor = SupervisedSolver(OracleSolver(), fallback=None)
        self.provisioner = Provisioner(
            self.kube, self.cloud_provider, self.cluster, self.clock,
            self.recorder, solver=self.supervisor,
        )
        self.batcher = Batcher(
            self.clock,
            idle_duration=self.options.batch_idle_duration_s,
            max_duration=self.options.batch_max_duration_s,
        )
        self.disruption = DisruptionController(
            self.kube, self.cluster, self.provisioner, self.cloud_provider,
            self.clock, self.recorder,
            drift_enabled=self.options.drift_enabled(),
        )
        self.lifecycle = LifecycleController(
            self.kube, self.cloud_provider, self.clock, self.recorder
        )
        self.markers = DisruptionMarkerController(
            self.kube, self.cloud_provider, self.clock,
            drift_enabled=self.options.drift_enabled(),
            cluster=self.cluster,
        )
        self.claim_termination = TerminationController(self.kube, self.cloud_provider)
        from karpenter_tpu.controllers.eviction_queue import EvictionQueue

        self.eviction_queue = EvictionQueue(self.kube, self.clock, self.recorder)
        self.node_termination = NodeTerminationController(
            self.kube, self.cloud_provider, self.clock, self.recorder,
            eviction_queue=self.eviction_queue,
        )
        self.gc = GarbageCollectionController(
            self.kube, self.cloud_provider, self.clock, self.recorder
        )
        self.consistency = ConsistencyController(self.kube, self.clock, self.recorder)
        self.nodepool_hash = NodePoolHashController(self.kube)
        self.nodepool_counter = NodePoolCounterController(self.kube)
        self.lease_gc = LeaseGarbageCollectionController(self.kube)
        self.metrics_exporter = MetricsExporter(self.kube)
        self._controllers: List[_Registered] = []
        self._stop = threading.Event()
        self._wired = False

    # -- registry (controllers.go:47-82) --------------------------------------

    def wire(self) -> "Operator":
        """Attach informers/watches and register every polling controller."""
        if self._wired:
            return self
        if not self.options.disable_webhook:
            from karpenter_tpu.webhooks import register_webhooks

            register_webhooks(self.kube)
        start_informers(self.kube, self.cluster)
        watch_pods(self.kube, self.batcher)
        reg = [
            ("provisioner", self._provision_once, 1.0),
            ("disruption", self.disruption.reconcile, DISRUPTION_PERIOD),
            ("nodeclaim.lifecycle", self.lifecycle.reconcile_all, 1.0),
            ("nodeclaim.markers", self.markers.reconcile_all, 10.0),
            ("nodeclaim.termination", self.claim_termination.reconcile_all, 1.0),
            ("node.termination", self.node_termination.reconcile_all, 1.0),
            # sub-second so PDB-429 backoffs (100ms base) retry promptly
            ("node.eviction_queue", self.eviction_queue.reconcile, 0.1),
            ("nodeclaim.garbagecollection", self.gc.reconcile, GC_PERIOD),
            ("nodeclaim.consistency", self.consistency.reconcile, CONSISTENCY_PERIOD),
            ("nodepool.hash", self.nodepool_hash.reconcile_all, 10.0),
            ("nodepool.counter", self.nodepool_counter.reconcile_all, 10.0),
            ("lease.garbagecollection", self.lease_gc.reconcile_all, 120.0),
            ("metrics", self.metrics_exporter.reconcile, 10.0),
        ]
        now = self.clock.now()
        self._controllers = [
            _Registered(name=n, reconcile=r, period_s=p, next_run=now)
            for n, r, p in reg
        ]
        self._wired = True
        return self

    def _provision_once(self):
        # the batcher gates real runs; in cooperative mode we only provision
        # when a trigger is pending so step() never blocks on the window
        if self.batcher._trigger.is_set():
            self.batcher._trigger.clear()
            return self.provisioner.reconcile()
        return None

    # -- cooperative driver (deterministic; the test/simulation mode) ---------

    def step(self) -> List[str]:
        """Run every controller whose period elapsed; returns their names."""
        self.wire()
        ran = []
        now = self.clock.now()
        for c in self._controllers:
            if now >= c.next_run:
                c.reconcile()
                c.next_run = now + c.period_s
                ran.append(c.name)
        return ran

    def run_until_settled(self, max_steps: int = 50) -> int:
        """Step until a full pass changes nothing in the store (test helper)."""
        self.wire()
        steps = 0
        for _ in range(max_steps):
            before = self.kube._rv
            for c in self._controllers:
                c.reconcile()
            steps += 1
            if self.kube._rv == before:
                break
        return steps

    # -- threaded driver (operator.go:223) ------------------------------------

    def start(self) -> None:
        from karpenter_tpu.operator import logging as oplog
        from karpenter_tpu.operator import serving
        from karpenter_tpu.provisioning.controller import ProvisioningLoop

        self.wire()
        self._stop.clear()
        logger = oplog.configure(self.options.log_level)
        warm_thread = None
        if self.options.solver_backend == "jax":
            from karpenter_tpu.solver.warmup import (
                maybe_prewarm_in_background,
                maybe_recover_in_background,
            )

            # restart recovery first (solver/aot.py): marks /readyz blocked
            # synchronously, then deserializes AOT executable snapshots and
            # probe-solves on a daemon thread — a restarted process reaches
            # warm service in seconds instead of retracing the ladder
            maybe_recover_in_background()
            warm_thread = maybe_prewarm_in_background(
                self.options, self.cloud_provider
            )
        from karpenter_tpu.solver.warmup import warmup_ready

        status = serving.OperatorStatus(
            supervisor=self.supervisor,
            warmup_ready=lambda: warmup_ready(warm_thread),
        )
        self._servers = [serving.serve(self.options.metrics_port, status=status)]
        if self.options.health_probe_port != self.options.metrics_port:
            self._servers.append(
                serving.serve(self.options.health_probe_port, status=status)
            )
        if self.options.enable_profiling:
            serving.start_profiler()

        def loop(name, reconcile, period):
            while not self._stop.is_set():
                try:
                    reconcile()
                except Exception:
                    # a controller error must never kill its loop
                    # (singleton.go requeues on error the same way)
                    logger.exception("controller %s reconcile failed", name)
                # Event.wait, not clock.sleep: stop() interrupts promptly
                self._stop.wait(period)

        # threaded mode provisions through the real batch window
        # (ProvisioningLoop blocks in Batcher.wait, singleton.go:81)
        prov_loop = ProvisioningLoop(self.provisioner, self.batcher)
        self._threads = [
            threading.Thread(
                target=loop, args=("provisioner", prov_loop.run_once, 0.0),
                daemon=True, name="karpenter-tpu/provisioner",
            )
        ]
        self._threads += [
            threading.Thread(target=loop, args=(c.name, c.reconcile, c.period_s),
                             daemon=True, name=f"karpenter-tpu/{c.name}")
            for c in self._controllers
            if c.name != "provisioner"
        ]
        for t in self._threads:
            t.start()

    def stop(self) -> None:
        self._stop.set()
        for server in getattr(self, "_servers", []):
            server.shutdown()
        if self.options.enable_profiling:
            from karpenter_tpu.operator import serving

            serving.stop_profiler()
