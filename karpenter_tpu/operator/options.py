"""Operator configuration.

Equivalent of reference pkg/operator/options/options.go:47-150: a flat Options
struct populated flags-first with environment-variable fallback, carried to
every decision point (the reference threads it through context; we pass the
object explicitly).
"""

from __future__ import annotations

import argparse
import os
from dataclasses import dataclass, field, fields
from typing import Dict, List, Optional


def _env_name(flag: str) -> str:
    return flag.upper().replace("-", "_")


@dataclass
class Options:
    # service ports (options.go:49-55); Operator.start() serves /metrics on
    # metrics_port and /healthz on health_probe_port
    metrics_port: int = 8000
    health_probe_port: int = 8081
    # apiserver client tuning; carried for configuration-surface parity — the
    # in-memory kube client has no rate limiter to tune (options.go:56-60)
    kube_client_qps: int = 200
    kube_client_burst: int = 300
    # leader election: parity field; this runtime is single-process, a
    # deployment wrapper running multiple replicas must provide its own lock
    enable_leader_election: bool = True
    # memory limit fraction mirrored from GOMEMLIMIT (operator.go:110-113);
    # parity field — Python has no equivalent soft-limit knob
    memory_limit_fraction: float = 0.9
    # batching window (options.go:95-96)
    batch_max_duration_s: float = 10.0
    batch_idle_duration_s: float = 1.0
    # profiling (operator.go:164-180); enables jax profiler traces here
    enable_profiling: bool = False
    # admission webhooks, default-disabled like the reference (options.go:84)
    disable_webhook: bool = True
    # feature gates (options.go:97,123-137)
    feature_gates: Dict[str, bool] = field(default_factory=lambda: {"Drift": True})
    log_level: str = "info"
    # solver backend for the scheduling cores: "jax" or "oracle"
    solver_backend: str = "jax"
    # pre-compile the standard solver shape buckets at startup (TPU only,
    # where the persistent compile cache makes the warm outlive the process;
    # solver/warmup.py)
    prewarm_solver: bool = True
    # largest pod batch to pre-compile solver buckets for (0 = only the
    # small standard buckets). A fleet that sees 10k-pod bursts should set
    # this to 10000 so the big scan executables compile at startup, not on
    # the first burst (solver/warmup.py walks the bucket ladder up to it).
    prewarm_max_pods: int = 0
    # candidate-subset counts to pre-compile the consolidation screen for
    # (solver/warmup.py prewarm_screen); 0 disables
    prewarm_screen_candidates: int = 0

    def drift_enabled(self) -> bool:
        return self.feature_gates.get("Drift", True)

    @classmethod
    def parse(cls, argv: Optional[List[str]] = None,
              env: Optional[Dict[str, str]] = None) -> "Options":
        """Flags win over env vars over defaults (options.go:82-121).
        argv=None reads sys.argv[1:], the standard argparse contract."""
        import sys

        if argv is None:
            argv = sys.argv[1:]
        env = dict(os.environ if env is None else env)
        defaults = cls()
        parser = argparse.ArgumentParser(prog="karpenter-tpu", add_help=False)
        for f in fields(cls):
            if f.name == "feature_gates":
                continue
            flag = "--" + f.name.replace("_", "-")
            env_val = env.get(_env_name(f.name))
            default = getattr(defaults, f.name)
            if env_val is not None:
                if f.type == "bool" or isinstance(default, bool):
                    default = env_val.lower() in ("1", "true", "yes")
                elif isinstance(default, int):
                    default = int(env_val)
                elif isinstance(default, float):
                    default = float(env_val)
                else:
                    default = env_val
            if isinstance(default, bool):
                # BooleanOptionalAction: bare '--enable-profiling' works like a
                # conventional CLI boolean and '--no-enable-profiling' negates
                # (ADVICE r1: type=lambda made the bare flag an argparse error)
                parser.add_argument(flag, dest=f.name, default=default,
                                    action=argparse.BooleanOptionalAction)
            else:
                parser.add_argument(flag, dest=f.name, default=default,
                                    type=type(default))
        parser.add_argument("--feature-gates", dest="feature_gates",
                            default=env.get("FEATURE_GATES", ""))
        ns = parser.parse_args(argv)
        opts = cls(**{f.name: getattr(ns, f.name) for f in fields(cls)
                      if f.name != "feature_gates"})
        gates = dict(defaults.feature_gates)
        raw = ns.feature_gates
        if raw:
            for pair in raw.split(","):
                name, _, value = pair.partition("=")
                gates[name.strip()] = value.strip().lower() in ("1", "true", "yes")
        opts.feature_gates = gates
        return opts
