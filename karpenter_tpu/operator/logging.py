"""Logging setup (equivalent of reference pkg/operator/logging/logging.go:
zap-via-knative there, stdlib logging here; --log-level wires through, and
debug-event suppression maps to the events logger's level)."""

from __future__ import annotations

import logging
import os
import sys

_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
}

_xla_quieted = False


def quiet_xla_warnings(logger=None, notify_stderr: bool = False) -> bool:
    """Suppress XLA/TSL C++ warning spam (the per-process "machine feature
    mismatch ... SIGILL" flag dump) by raising ``TF_CPP_MIN_LOG_LEVEL`` to
    errors-only, replacing the multi-line dump with a one-line notice.

    Must run BEFORE jax initializes its backend — the C++ logger reads the
    env var once at load. Respects an operator override: a caller-set
    ``TF_CPP_MIN_LOG_LEVEL`` or ``KARPENTER_TPU_XLA_VERBOSE=1`` keeps the
    native verbosity. The value ``"1"`` is NOT treated as a caller preset:
    ``import jax`` setdefaults it to 1, which is indistinguishable from an
    explicit 1 — and 1 still passes the WARNING-level feature-mismatch dump.
    Operators who want level 1 specifically have the VERBOSE flag. Returns
    whether suppression is active."""
    global _xla_quieted
    if os.environ.get("KARPENTER_TPU_XLA_VERBOSE", "") == "1":
        return False
    preset = os.environ.get("TF_CPP_MIN_LOG_LEVEL")
    if preset is not None and preset != "1":
        return preset >= "2"
    os.environ["TF_CPP_MIN_LOG_LEVEL"] = "2"  # 2 = warnings off, errors kept
    if not _xla_quieted:
        _xla_quieted = True
        notice = (
            "XLA C++ warnings suppressed (host ISA/feature notices included); "
            "set KARPENTER_TPU_XLA_VERBOSE=1 to restore them"
        )
        if logger is not None:
            logger.debug(notice)
        elif notify_stderr:
            sys.stderr.write(f"[karpenter-tpu] {notice}\n")
    return True


def configure(log_level: str = "info") -> logging.Logger:
    level = _LEVELS.get(log_level.lower(), logging.INFO)
    logging.basicConfig(
        level=level,
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )
    logger = logging.getLogger("karpenter_tpu")
    logger.setLevel(level)
    # debug-event suppression (logging.go): events stay quiet unless debug
    logging.getLogger("karpenter_tpu.events").setLevel(
        logging.DEBUG if level == logging.DEBUG else logging.WARNING
    )
    quiet_xla_warnings(logger=logger)
    return logger
