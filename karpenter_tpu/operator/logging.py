"""Logging setup (equivalent of reference pkg/operator/logging/logging.go:
zap-via-knative there, stdlib logging here; --log-level wires through, and
debug-event suppression maps to the events logger's level)."""

from __future__ import annotations

import logging

_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
}


def configure(log_level: str = "info") -> logging.Logger:
    level = _LEVELS.get(log_level.lower(), logging.INFO)
    logging.basicConfig(
        level=level,
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )
    logger = logging.getLogger("karpenter_tpu")
    logger.setLevel(level)
    # debug-event suppression (logging.go): events stay quiet unless debug
    logging.getLogger("karpenter_tpu.events").setLevel(
        logging.DEBUG if level == logging.DEBUG else logging.WARNING
    )
    return logger
