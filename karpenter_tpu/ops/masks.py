"""Requirement-algebra kernels.

Vectorized twins of the host-side algebra in scheduling/requirements.py
(reference pkg/scheduling/{requirement,requirements}.go). All functions are
pure jnp over ReqTensor rows shaped [K, V] / [K]; callers vmap over entity
axes. See models/problem.py for the encoding invariants that make these exact.

These run on the TPU's vector unit: boolean lane ops fused by XLA. The hot
product — every (pod-placement, instance-type) compatibility test, reference
nodeclaim.go:262-264 — becomes `vmap(intersects_ok)` over the instance-type
axis.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax, vmap

from karpenter_tpu.models.problem import ReqTensor

# ``bounds_free`` (threaded from ops/ffd_core.problem_bounds_free as a STATIC
# trace-time bool): no requirement anywhere in the problem carries a finite
# integer Gt/Lt bound, so every gt is the -inf sentinel and every lt the +inf
# sentinel for the whole solve (intersection max/min and the topology/hostname
# passthroughs preserve sentinels). Bounds are already folded into the
# admitted lanes at encode (models/problem.py), so under bounds_free the
# gt/lt arrays carry zero information and every kernel here statically elides
# their math — the (comp & gt < lt) term is comp, _in_bounds is lane_valid,
# and intersection passes gt/lt through untouched (loop-invariant, so commit
# sites skip their writes and XLA hoists the arrays out of the solve loop).


def intersect(a: ReqTensor, b: ReqTensor, bounds_free: bool = False) -> ReqTensor:
    """Keywise requirement intersection (requirement.go:128-161).

    Admitted lanes already satisfy each side's bounds (folded at encode), so
    lane-AND applies the combined bounds for free; undefined keys are encoded
    as full-admit complements and act as identities."""
    if bounds_free:
        gt, lt = a.gt, a.lt  # both sides sentinel — max/min are identities
    else:
        gt, lt = jnp.maximum(a.gt, b.gt), jnp.minimum(a.lt, b.lt)
    return ReqTensor(
        admitted=a.admitted & b.admitted,
        comp=a.comp & b.comp,
        gt=gt,
        lt=lt,
        defined=a.defined | b.defined,
    )


def nonempty(r: ReqTensor, bounds_free: bool = False) -> jnp.ndarray:
    """Per-key Len() != 0 (requirement.go:210-215): a concrete set is nonempty
    if any lane is admitted; a complement set is nonempty unless its integer
    bounds collapsed (gt >= lt, requirement.go:135-137 — the reference's Len()
    ignores bounds otherwise, and we match that exactly)."""
    if bounds_free:
        return jnp.any(r.admitted, axis=-1) | r.comp
    return jnp.any(r.admitted, axis=-1) | (r.comp & (r.gt < r.lt))


def _in_bounds(lane_numeric: jnp.ndarray, lane_valid: jnp.ndarray, gt, lt) -> jnp.ndarray:
    """Which vocab lanes satisfy integer bounds (requirement.go:238-254):
    without bounds every valid lane passes; with bounds only numeric lanes
    strictly inside (gt, lt)."""
    unbounded = (gt[..., None] <= jnp.int32(-(2**31) + 1)) & (lt[..., None] >= jnp.int32(2**31 - 1))
    numeric_ok = (
        ~jnp.isnan(lane_numeric)
        & (lane_numeric > gt[..., None].astype(jnp.float32))
        & (lane_numeric < lt[..., None].astype(jnp.float32))
    )
    return lane_valid & (unbounded | numeric_ok)


def negative_polarity(r: ReqTensor, lane_valid, lane_numeric, bounds_free: bool = False) -> jnp.ndarray:
    """Per-key Operator() in {NotIn, DoesNotExist} (requirement.go:197-208).

    Complement sets read as NotIn when they exclude at least one in-bounds
    vocab value (exclusions are always vocab members in the closed world);
    concrete sets read as DoesNotExist when no lane is admitted."""
    if bounds_free:
        excl = jnp.any(lane_valid & ~r.admitted, axis=-1)
    else:
        excl = jnp.any(
            lane_valid & _in_bounds(lane_numeric, lane_valid, r.gt, r.lt) & ~r.admitted,
            axis=-1,
        )
    return jnp.where(r.comp, excl, ~jnp.any(r.admitted, axis=-1))


def intersects_ok(a: ReqTensor, b: ReqTensor, lane_valid, lane_numeric, bounds_free: bool = False) -> jnp.ndarray:
    """Requirements.Intersects as a scalar bool (requirements.go:241-258):
    keys defined on both sides must have a nonempty intersection, except when
    both sides read as NotIn/DoesNotExist."""
    inter = intersect(a, b, bounds_free)
    ne = nonempty(inter, bounds_free)
    both_defined = a.defined & b.defined
    both_neg = negative_polarity(a, lane_valid, lane_numeric, bounds_free) & negative_polarity(
        b, lane_valid, lane_numeric, bounds_free
    )
    return jnp.all(~both_defined | ne | both_neg)


def compatible_ok(
    r: ReqTensor, incoming: ReqTensor, lane_valid, lane_numeric, key_wellknown,
    bounds_free: bool = False,
) -> jnp.ndarray:
    """Requirements.Compatible (requirements.go:163-174): incoming keys that
    are neither defined on ``r`` nor allowed-undefined must have negative
    polarity; then the requirement sets must intersect. ``key_wellknown`` is
    the allow-undefined mask (zeros for the strict variant used by existing
    nodes, existingnode.go:94)."""
    neg_inc = negative_polarity(incoming, lane_valid, lane_numeric, bounds_free)
    undef_bad = incoming.defined & ~r.defined & ~key_wellknown & ~neg_inc
    return ~jnp.any(undef_bad) & intersects_ok(r, incoming, lane_valid, lane_numeric, bounds_free)


def compatible_from_merged(
    merged_ne: jnp.ndarray,  # bool[..., K] nonempty(intersect(r, incoming))
    r_defined: jnp.ndarray,  # bool[..., K]
    r_neg: jnp.ndarray,  # bool[..., K] negative_polarity(r)
    inc_defined: jnp.ndarray,  # bool[K] (broadcasts over leading axes)
    inc_neg: jnp.ndarray,  # bool[K] negative_polarity(incoming)
    key_wellknown: jnp.ndarray,  # bool[K]
) -> jnp.ndarray:
    """Requirements.Compatible for callers that already hold the merged rows
    (the narrow step intersects state x pod for the topology gate anyway —
    recomputing the intersection inside compatible_ok doubled the gate's op
    count). Exactly compatible_ok(r, incoming, ...) given
    merged_ne = nonempty(intersect(r, incoming)) and each side's own
    defined/polarity masks; the per-iteration pod-side masks are computed
    once and shared across the node/claim/template phases."""
    both_defined = r_defined & inc_defined
    both_neg = r_neg & inc_neg
    intersects = jnp.all(~both_defined | merged_ne | both_neg, axis=-1)
    undef_bad = jnp.any(inc_defined & ~r_defined & ~key_wellknown & ~inc_neg, axis=-1)
    return ~undef_bad & intersects


def fits(requests: jnp.ndarray, available: jnp.ndarray) -> jnp.ndarray:
    """resources.Fits with a small tolerance for float accumulation; shapes
    broadcast over leading axes, reduction over the trailing resource axis."""
    eps = 1e-6 + 1e-6 * jnp.abs(available)
    return jnp.all(requests <= available + eps, axis=-1)


def it_compatible(it_reqs: ReqTensor, state: ReqTensor, lane_valid, lane_numeric) -> jnp.ndarray:
    """[T] mask: instance type requirements intersect the (narrowed) claim
    state — the reference's `compatible` hot spot (nodeclaim.go:262-264)."""
    return vmap(lambda it: intersects_ok(it, state, lane_valid, lane_numeric))(it_reqs)


def pack_lanes(admitted: jnp.ndarray) -> jnp.ndarray:
    """bool[..., V] -> uint32[..., V/32]: bitpack value lanes so the hot
    [bins x instance-types] compatibility product runs on 32 lanes per word —
    the TPU VPU chews packed int32 lanes at full rate where byte-bools waste
    31/32 of the bandwidth. V is padded to a multiple of 32 (ops/padding.py)."""
    *lead, V = admitted.shape
    words = admitted.reshape(*lead, V // 32, 32).astype(jnp.uint32)
    return (words << jnp.arange(32, dtype=jnp.uint32)).sum(axis=-1).astype(jnp.uint32)


def packed_pairwise_compat(
    a: ReqTensor,
    a_packed: jnp.ndarray,  # uint32[A, K, W]
    a_neg: jnp.ndarray,  # bool[A, K]
    b: ReqTensor,
    b_packed: jnp.ndarray,  # uint32[B, K, W]
    b_neg: jnp.ndarray,  # bool[B, K]
    bounds_free: bool = False,
) -> jnp.ndarray:
    """[A, B] all-pairs Requirements.Intersects on bitpacked lanes — the
    solver's hot product (every open bin x every instance type per pod step,
    reference nodeclaim.go:236-258). Semantics identical to intersects_ok;
    negative-polarity masks are precomputed by the caller (they depend only on
    each side's own state)."""
    inter_any = jnp.any(
        (a_packed[:, None, :, :] & b_packed[None, :, :, :]) != 0, axis=-1
    )  # [A, B, K]
    comp_ab = a.comp[:, None, :] & b.comp[None, :, :]
    if bounds_free:
        ne = inter_any | comp_ab
    else:
        gt_ab = jnp.maximum(a.gt[:, None, :], b.gt[None, :, :])
        lt_ab = jnp.minimum(a.lt[:, None, :], b.lt[None, :, :])
        ne = inter_any | (comp_ab & (gt_ab < lt_ab))
    both_defined = a.defined[:, None, :] & b.defined[None, :, :]
    both_neg = a_neg[:, None, :] & b_neg[None, :, :]
    return jnp.all(~both_defined | ne | both_neg, axis=-1)  # [A, B]


# --- single-tensor bitword requirement rows -------------------------------
#
# pack_req folds a ReqTensor row into ONE uint32 tensor [..., K, W + 3]
# (W = V / 32 lane words):
#
#   [..., :W]    admitted lane bits (pack_lanes layout)
#   [..., W]     flags word: bit0 comp, bit1 defined, bit2 negative polarity
#   [..., W+1]   gt bitcast to uint32
#   [..., W+2]   lt bitcast to uint32
#
# The flags are chosen so one bitwise AND of two packed rows answers every
# pairwise gate question: lane-AND gives the intersection's admitted bits,
# flag-AND bit0 is the intersection's complement bit, bit1 is both_defined,
# and bit2 is both_negative — exactly the terms Intersects/Compatible
# consume. Polarity is baked at pack time (it depends only on the row's own
# state, bounds included via _in_bounds), so packed gates never touch
# lane_numeric. gt/lt ride along as raw words for the non-bounds_free case.

_FLAG_COMP = jnp.uint32(1)
_FLAG_DEFINED = jnp.uint32(2)
_FLAG_NEG = jnp.uint32(4)


def pack_req(r: ReqTensor, lane_valid, lane_numeric, bounds_free: bool = False) -> jnp.ndarray:
    """ReqTensor[..., K, V] -> uint32[..., K, W+3] bitword rows (layout
    above). ``lane_valid``/``lane_numeric`` feed the polarity bit."""
    words = pack_lanes(r.admitted)  # [..., K, W]
    neg = negative_polarity(r, lane_valid, lane_numeric, bounds_free)
    flags = (
        r.comp.astype(jnp.uint32) * _FLAG_COMP
        | r.defined.astype(jnp.uint32) * _FLAG_DEFINED
        | neg.astype(jnp.uint32) * _FLAG_NEG
    )
    gt_w = lax.bitcast_convert_type(r.gt, jnp.uint32)
    lt_w = lax.bitcast_convert_type(r.lt, jnp.uint32)
    return jnp.concatenate(
        [words, flags[..., None], gt_w[..., None], lt_w[..., None]], axis=-1
    )


def _packed_intersect_terms(pa: jnp.ndarray, pb: jnp.ndarray, bounds_free: bool):
    """(nonempty[..., K], both_defined[..., K], both_neg[..., K]) of two
    packed rows (broadcasting over leading axes)."""
    and_w = pa & pb  # [..., K, W+3]
    inter_any = jnp.any(and_w[..., :-3] != 0, axis=-1)
    fl = and_w[..., -3]
    comp_ab = (fl & _FLAG_COMP) != 0
    if bounds_free:
        ne = inter_any | comp_ab
    else:
        gt_ab = jnp.maximum(
            lax.bitcast_convert_type(pa[..., -2], jnp.int32),
            lax.bitcast_convert_type(pb[..., -2], jnp.int32),
        )
        lt_ab = jnp.minimum(
            lax.bitcast_convert_type(pa[..., -1], jnp.int32),
            lax.bitcast_convert_type(pb[..., -1], jnp.int32),
        )
        ne = inter_any | (comp_ab & (gt_ab < lt_ab))
    return ne, (fl & _FLAG_DEFINED) != 0, (fl & _FLAG_NEG) != 0


def packed_intersects_ok(pa: jnp.ndarray, pb: jnp.ndarray, bounds_free: bool = False) -> jnp.ndarray:
    """Requirements.Intersects on pack_req rows — equals
    intersects_ok(a, b, ...) on the unpacked rows (the fuzz in
    tests/test_mask_kernels.py pins that)."""
    ne, both_defined, both_neg = _packed_intersect_terms(pa, pb, bounds_free)
    return jnp.all(~both_defined | ne | both_neg, axis=-1)


def packed_compatible_ok(
    pr: jnp.ndarray, pinc: jnp.ndarray, key_wellknown, bounds_free: bool = False
) -> jnp.ndarray:
    """Requirements.Compatible on pack_req rows — equals
    compatible_ok(r, incoming, ...) on the unpacked rows."""
    ne, both_defined, both_neg = _packed_intersect_terms(pr, pinc, bounds_free)
    inc_fl, r_fl = pinc[..., -3], pr[..., -3]
    undef_bad = (
        ((inc_fl & _FLAG_DEFINED) != 0)
        & ((r_fl & _FLAG_DEFINED) == 0)
        & ~key_wellknown
        & ((inc_fl & _FLAG_NEG) == 0)
    )
    return ~jnp.any(undef_bad, axis=-1) & jnp.all(~both_defined | ne | both_neg, axis=-1)


def family_bitmask(fails: jnp.ndarray, cand: jnp.ndarray) -> jnp.ndarray:
    """int32[3] (union, blockers, near) gate-attribution byte for ONE
    candidate class — the device twin of obs/explain.encode_family_bits
    (tests/test_explain.py pins the byte-for-byte equivalence).

    ``fails``: bool[F, E] — family f failed on candidate e (F <= 7 families,
    obs/explain.FAM_*). ``cand``: bool[E] — candidate liveness (open claims;
    all-True for nodes/templates). One wide OR/AND reduction over predicates
    the gate kernels already computed — no gathers:

      union    bit f: family f failed on >= 1 live candidate
      blockers bit f: family f failed on EVERY live candidate; bit 7 when the
               class has no live candidate at all (EMPTY)
      near     bit f: some live candidate failed ONLY family f — the
               counterfactual "relax this one gate and the pod schedules"
    """
    F = fails.shape[0]
    present = jnp.any(cand)
    hit = fails & cand[None, :]  # [F, E]
    union = jnp.any(hit, axis=-1)
    blockers = present & jnp.all(fails | ~cand[None, :], axis=-1)
    nfail = jnp.sum(hit, axis=0)  # [E] families failing each live candidate
    near = jnp.any(hit & (nfail[None, :] == 1), axis=-1)
    bits = jnp.int32(1) << jnp.arange(F, dtype=jnp.int32)
    return jnp.stack(
        [
            jnp.sum(jnp.where(union, bits, 0)),
            jnp.sum(jnp.where(blockers, bits, 0))
            + jnp.where(present, 0, jnp.int32(1 << 7)),
            jnp.sum(jnp.where(near, bits, 0)),
        ]
    ).astype(jnp.int32)


def has_offering_zc(
    state_admitted: jnp.ndarray,  # bool[B, K, V] — bin states' admitted lanes
    zone_key: int,
    ct_key: int,
    offer_zc: jnp.ndarray,  # bool[T, Zb, Cb] dense availability
) -> jnp.ndarray:
    """[B, T] has_offering as one MXU matmul: count the available offerings
    whose (zone lane, ct lane) pair the bin state admits —
    sum_{z,c} zone_adm[b,z] * ct_adm[b,c] * offer_zc[t,z,c] — and test > 0.
    Exact vs the gather formulation (inputs are 0/1; f32 accumulation), and
    far cheaper on TPU, where per-offering lane gathers dominate the step."""
    T, Zb, Cb = offer_zc.shape
    z = state_admitted[..., zone_key, :Zb].astype(jnp.float32)  # [B, Zb]
    c = state_admitted[..., ct_key, :Cb].astype(jnp.float32)  # [B, Cb]
    pairs = (z[..., :, None] * c[..., None, :]).reshape(*z.shape[:-1], Zb * Cb)
    m = offer_zc.reshape(T, Zb * Cb).astype(jnp.float32)
    hits = jnp.matmul(pairs, m.T, preferred_element_type=jnp.float32)
    return hits > 0.5


def has_offering(
    state_admitted: jnp.ndarray,  # bool[K, V] — the claim state's admitted lanes
    zone_key: int,
    ct_key: int,
    offer_zone: jnp.ndarray,  # int32[T, O]
    offer_ct: jnp.ndarray,  # int32[T, O]
    offer_ok: jnp.ndarray,  # bool[T, O]
) -> jnp.ndarray:
    """[T] mask: some available offering's zone and capacity type are admitted
    by the claim state (nodeclaim.go:270-278). Undefined zone/ct requirements
    encode as full-admit, matching the reference's 'no requirement -> any
    offering' rule."""
    zone_adm = state_admitted[zone_key][offer_zone]  # [T, O]
    ct_adm = state_admitted[ct_key][offer_ct]
    return jnp.any(offer_ok & zone_adm & ct_adm, axis=-1)
