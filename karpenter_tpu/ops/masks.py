"""Requirement-algebra kernels.

Vectorized twins of the host-side algebra in scheduling/requirements.py
(reference pkg/scheduling/{requirement,requirements}.go). All functions are
pure jnp over ReqTensor rows shaped [K, V] / [K]; callers vmap over entity
axes. See models/problem.py for the encoding invariants that make these exact.

These run on the TPU's vector unit: boolean lane ops fused by XLA. The hot
product — every (pod-placement, instance-type) compatibility test, reference
nodeclaim.go:262-264 — becomes `vmap(intersects_ok)` over the instance-type
axis.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import vmap

from karpenter_tpu.models.problem import ReqTensor


def intersect(a: ReqTensor, b: ReqTensor) -> ReqTensor:
    """Keywise requirement intersection (requirement.go:128-161).

    Admitted lanes already satisfy each side's bounds (folded at encode), so
    lane-AND applies the combined bounds for free; undefined keys are encoded
    as full-admit complements and act as identities."""
    return ReqTensor(
        admitted=a.admitted & b.admitted,
        comp=a.comp & b.comp,
        gt=jnp.maximum(a.gt, b.gt),
        lt=jnp.minimum(a.lt, b.lt),
        defined=a.defined | b.defined,
    )


def nonempty(r: ReqTensor) -> jnp.ndarray:
    """Per-key Len() != 0 (requirement.go:210-215): a concrete set is nonempty
    if any lane is admitted; a complement set is nonempty unless its integer
    bounds collapsed (gt >= lt, requirement.go:135-137 — the reference's Len()
    ignores bounds otherwise, and we match that exactly)."""
    return jnp.any(r.admitted, axis=-1) | (r.comp & (r.gt < r.lt))


def _in_bounds(lane_numeric: jnp.ndarray, lane_valid: jnp.ndarray, gt, lt) -> jnp.ndarray:
    """Which vocab lanes satisfy integer bounds (requirement.go:238-254):
    without bounds every valid lane passes; with bounds only numeric lanes
    strictly inside (gt, lt)."""
    unbounded = (gt[..., None] <= jnp.int32(-(2**31) + 1)) & (lt[..., None] >= jnp.int32(2**31 - 1))
    numeric_ok = (
        ~jnp.isnan(lane_numeric)
        & (lane_numeric > gt[..., None].astype(jnp.float32))
        & (lane_numeric < lt[..., None].astype(jnp.float32))
    )
    return lane_valid & (unbounded | numeric_ok)


def negative_polarity(r: ReqTensor, lane_valid, lane_numeric) -> jnp.ndarray:
    """Per-key Operator() in {NotIn, DoesNotExist} (requirement.go:197-208).

    Complement sets read as NotIn when they exclude at least one in-bounds
    vocab value (exclusions are always vocab members in the closed world);
    concrete sets read as DoesNotExist when no lane is admitted."""
    excl = jnp.any(lane_valid & _in_bounds(lane_numeric, lane_valid, r.gt, r.lt) & ~r.admitted, axis=-1)
    return jnp.where(r.comp, excl, ~jnp.any(r.admitted, axis=-1))


def intersects_ok(a: ReqTensor, b: ReqTensor, lane_valid, lane_numeric) -> jnp.ndarray:
    """Requirements.Intersects as a scalar bool (requirements.go:241-258):
    keys defined on both sides must have a nonempty intersection, except when
    both sides read as NotIn/DoesNotExist."""
    inter = intersect(a, b)
    ne = nonempty(inter)
    both_defined = a.defined & b.defined
    both_neg = negative_polarity(a, lane_valid, lane_numeric) & negative_polarity(
        b, lane_valid, lane_numeric
    )
    return jnp.all(~both_defined | ne | both_neg)


def compatible_ok(
    r: ReqTensor, incoming: ReqTensor, lane_valid, lane_numeric, key_wellknown
) -> jnp.ndarray:
    """Requirements.Compatible (requirements.go:163-174): incoming keys that
    are neither defined on ``r`` nor allowed-undefined must have negative
    polarity; then the requirement sets must intersect. ``key_wellknown`` is
    the allow-undefined mask (zeros for the strict variant used by existing
    nodes, existingnode.go:94)."""
    neg_inc = negative_polarity(incoming, lane_valid, lane_numeric)
    undef_bad = incoming.defined & ~r.defined & ~key_wellknown & ~neg_inc
    return ~jnp.any(undef_bad) & intersects_ok(r, incoming, lane_valid, lane_numeric)


def fits(requests: jnp.ndarray, available: jnp.ndarray) -> jnp.ndarray:
    """resources.Fits with a small tolerance for float accumulation; shapes
    broadcast over leading axes, reduction over the trailing resource axis."""
    eps = 1e-6 + 1e-6 * jnp.abs(available)
    return jnp.all(requests <= available + eps, axis=-1)


def it_compatible(it_reqs: ReqTensor, state: ReqTensor, lane_valid, lane_numeric) -> jnp.ndarray:
    """[T] mask: instance type requirements intersect the (narrowed) claim
    state — the reference's `compatible` hot spot (nodeclaim.go:262-264)."""
    return vmap(lambda it: intersects_ok(it, state, lane_valid, lane_numeric))(it_reqs)


def pack_lanes(admitted: jnp.ndarray) -> jnp.ndarray:
    """bool[..., V] -> uint32[..., V/32]: bitpack value lanes so the hot
    [bins x instance-types] compatibility product runs on 32 lanes per word —
    the TPU VPU chews packed int32 lanes at full rate where byte-bools waste
    31/32 of the bandwidth. V is padded to a multiple of 32 (ops/padding.py)."""
    *lead, V = admitted.shape
    words = admitted.reshape(*lead, V // 32, 32).astype(jnp.uint32)
    return (words << jnp.arange(32, dtype=jnp.uint32)).sum(axis=-1).astype(jnp.uint32)


def packed_pairwise_compat(
    a: ReqTensor,
    a_packed: jnp.ndarray,  # uint32[A, K, W]
    a_neg: jnp.ndarray,  # bool[A, K]
    b: ReqTensor,
    b_packed: jnp.ndarray,  # uint32[B, K, W]
    b_neg: jnp.ndarray,  # bool[B, K]
) -> jnp.ndarray:
    """[A, B] all-pairs Requirements.Intersects on bitpacked lanes — the
    solver's hot product (every open bin x every instance type per pod step,
    reference nodeclaim.go:236-258). Semantics identical to intersects_ok;
    negative-polarity masks are precomputed by the caller (they depend only on
    each side's own state)."""
    inter_any = jnp.any(
        (a_packed[:, None, :, :] & b_packed[None, :, :, :]) != 0, axis=-1
    )  # [A, B, K]
    comp_ab = a.comp[:, None, :] & b.comp[None, :, :]
    gt_ab = jnp.maximum(a.gt[:, None, :], b.gt[None, :, :])
    lt_ab = jnp.minimum(a.lt[:, None, :], b.lt[None, :, :])
    ne = inter_any | (comp_ab & (gt_ab < lt_ab))
    both_defined = a.defined[:, None, :] & b.defined[None, :, :]
    both_neg = a_neg[:, None, :] & b_neg[None, :, :]
    return jnp.all(~both_defined | ne | both_neg, axis=-1)  # [A, B]


def has_offering_zc(
    state_admitted: jnp.ndarray,  # bool[B, K, V] — bin states' admitted lanes
    zone_key: int,
    ct_key: int,
    offer_zc: jnp.ndarray,  # bool[T, Zb, Cb] dense availability
) -> jnp.ndarray:
    """[B, T] has_offering as one MXU matmul: count the available offerings
    whose (zone lane, ct lane) pair the bin state admits —
    sum_{z,c} zone_adm[b,z] * ct_adm[b,c] * offer_zc[t,z,c] — and test > 0.
    Exact vs the gather formulation (inputs are 0/1; f32 accumulation), and
    far cheaper on TPU, where per-offering lane gathers dominate the step."""
    T, Zb, Cb = offer_zc.shape
    z = state_admitted[..., zone_key, :Zb].astype(jnp.float32)  # [B, Zb]
    c = state_admitted[..., ct_key, :Cb].astype(jnp.float32)  # [B, Cb]
    pairs = (z[..., :, None] * c[..., None, :]).reshape(*z.shape[:-1], Zb * Cb)
    m = offer_zc.reshape(T, Zb * Cb).astype(jnp.float32)
    hits = jnp.matmul(pairs, m.T, preferred_element_type=jnp.float32)
    return hits > 0.5


def has_offering(
    state_admitted: jnp.ndarray,  # bool[K, V] — the claim state's admitted lanes
    zone_key: int,
    ct_key: int,
    offer_zone: jnp.ndarray,  # int32[T, O]
    offer_ct: jnp.ndarray,  # int32[T, O]
    offer_ok: jnp.ndarray,  # bool[T, O]
) -> jnp.ndarray:
    """[T] mask: some available offering's zone and capacity type are admitted
    by the claim state (nodeclaim.go:270-278). Undefined zone/ct requirements
    encode as full-admit, matching the reference's 'no requirement -> any
    offering' rule."""
    zone_adm = state_admitted[zone_key][offer_zone]  # [T, O]
    ct_adm = state_admitted[ct_key][offer_ct]
    return jnp.any(offer_ok & zone_adm & ct_adm, axis=-1)
