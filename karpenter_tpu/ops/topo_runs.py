"""Topology-run commit: one scan step places a whole run of identical
topology-interacting pods through a light per-pod inner loop.

The per-pod FFD step (ops/ffd.py _make_step) evaluates EVERY bin with full
[bins, K, V] requirement algebra and [bins, T] instance-type products per
pod — the right shape for arbitrary pods, but wasteful for a run of
identical ones where the merges, compatibilities, and static gates are
loop-invariant. This kernel hoists those and keeps per pod only:

  - the node-side topo_gate over the PRECOMPUTED merged node rows (dynamic
    only through the topology counters) + integer fill capacities;
  - a fewest-pods retry loop for claims: candidates are tried in rank order
    and each is VERIFIED with the real topo_gate / it_gate at B=1 before
    committing — the per-pod step evaluates the same gates for every claim
    and takes the argmin passing one, so the first passing candidate in
    rank order is the identical choice;
  - the fresh-template phase (same helpers as the step);
  - Topology.Record via the shared record kernel.

What makes this cheaper than the step: no [C, K, V] claim merges, no
[C, T] / [TPL, T] instance-type products for every pod — only the chosen
claim pays [T]-sized verification, and the template block only runs when no
claim accepts.

Eligibility is decided by the encoder (solver/encode.py RUN_TOPO): identical
rows, match == selects == owned for every group, no spread node-filters, no
host ports, no CSI volumes. Anything else stays on the per-pod step.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax, vmap

from karpenter_tpu.models.problem import (
    HOSTNAME_KEY,
    ReqTensor,
    SchedulingProblem,
)
from karpenter_tpu.ops import masks
from karpenter_tpu.ops.ffd import (
    KIND_CLAIM,
    KIND_FAIL,
    KIND_NEW_CLAIM,
    KIND_NODE,
    KIND_NO_SLOT,
    FFDState,
    _capacity,
    _first_true,
    _fresh_template_rows,
    _intersect_rows,
    _make_it_gate,
    _mix_req_rows,
)
from karpenter_tpu.ops.topology_kernels import PodTopoStatics, record, topo_gate

_BIG = 2**30


def _bcast_req(row: ReqTensor, E: int, K: int, V: int) -> ReqTensor:
    return ReqTensor(
        admitted=jnp.broadcast_to(row.admitted, (E, K, V)),
        comp=jnp.broadcast_to(row.comp, (E, K)),
        gt=jnp.broadcast_to(row.gt, (E, K)),
        lt=jnp.broadcast_to(row.lt, (E, K)),
        defined=jnp.broadcast_to(row.defined, (E, K)),
    )


def make_topo_run_commit(problem: SchedulingProblem, statics, C: int, max_run: int):
    # the topo run commits stay on the legacy (non-dieted) gate kernels;
    # they consume only the first six statics fields
    lv, ln, wellknown, no_allow, it_packed, it_neg = statics[:6]
    it_gate = _make_it_gate(problem, statics)
    N = problem.num_nodes
    T = problem.num_instance_types
    TPL = problem.num_templates
    K = problem.num_keys
    V = problem.num_lanes
    G = problem.grp_key.shape[0]
    # chain-identical run members share every gate-relevant array with the
    # head but may differ on the SELECT side (own labels) — gates only read
    # selects through match∩selects (equal across the run by the encoder's
    # chain predicate), while Topology.Record needs each member's own row.
    # Scratch tail so a window starting near P never clamp-shifts.
    sel_concat = (
        jnp.concatenate(
            [jnp.asarray(problem.pod_grp_selects), jnp.zeros((max_run, G), bool)]
        )
        if G > 0
        else None
    )

    def commit(state: FFDState, pod, start, length, active_arr):
        (
            pod_req,
            pod_strict,
            pod_requests,
            tol_tpl,
            tol_node,
            pod_ports,
            pod_conflict,
            grp_match,
            grp_selects,
            grp_owned,
            _pod_vols,
            _pa,
            _pod_neg,
        ) = pod
        topo_pod_head = PodTopoStatics(
            strict_admitted=pod_strict.admitted,
            grp_match=grp_match,
            grp_selects=grp_selects,
            grp_owned=grp_owned,
        )
        win = jnp.arange(max_run)
        act = lax.dynamic_slice(active_arr, (start,), (max_run,)) & (win < length)
        sel_win = (
            lax.dynamic_slice(sel_concat, (start, 0), (max_run, G))
            if G > 0
            else None
        )

        # ---- loop-invariant statics (the step pays these per pod) --------
        if N > 0:
            # resource capacity and port conflicts are invariant across the
            # run (identical pods; eligibility excludes host ports), but the
            # requirement-side merge/compat must read the FRESH node rows
            # inside the loop — an earlier pod of this run can narrow a
            # node's row (complement-key merges, topology collapse) in ways
            # later pods must observe, exactly as the per-pod step does
            node_port_ok = ~jnp.any(
                state.node_used_ports & pod_conflict[None, :], axis=-1
            )
            node_res_cap = _capacity(
                problem.node_avail, state.node_requests, pod_requests[None, :]
            )

        # ---- per-pod loop -------------------------------------------------
        def body(carry):
            i, taken_nodes, st, kind_row, index_row = carry
            is_active = act[i]
            # member-specific statics: only the select row varies across a
            # chain-identical run (gates read it solely at matched groups,
            # where it equals the head's; records read it everywhere)
            topo_pod = (
                topo_pod_head._replace(grp_selects=sel_win[i])
                if G > 0
                else topo_pod_head
            )

            def place(args):
                taken_nodes, st, kind_row, index_row = args

                # -- 1. existing nodes: the step's node phase on fresh rows
                if N > 0:
                    node_merged = _intersect_rows(st.node_req, pod_req)
                    node_compat = vmap(
                        lambda nr: masks.compatible_ok(nr, pod_req, lv, ln, no_allow)
                    )(st.node_req)
                    node_topo_ok, node_final = topo_gate(
                        problem,
                        st.grp_counts,
                        st.grp_registered,
                        topo_pod,
                        node_merged,
                        no_allow,
                    )
                    n_ok = (
                        tol_node
                        & node_compat
                        & node_port_ok
                        & (node_res_cap - taken_nodes > 0)
                        & node_topo_ok
                    )
                    node_pick = _first_true(n_ok)
                    any_node = jnp.any(n_ok)
                else:
                    any_node = jnp.bool_(False)

                def commit_node(a):
                    taken_nodes, st, kind_row, index_row = a
                    hot = jnp.arange(N) == node_pick
                    final = node_final.row(jnp.minimum(node_pick, N - 1))
                    counts, registered = record(
                        problem, st.grp_counts, st.grp_registered, topo_pod,
                        final, no_allow, jnp.bool_(True), lv, ln,
                    )
                    st2 = dataclasses.replace(
                        st,
                        node_req=_mix_req_rows(st.node_req, node_final, hot),
                        grp_counts=counts,
                        grp_registered=registered,
                    )
                    return (
                        taken_nodes + hot.astype(jnp.int32),
                        st2,
                        kind_row.at[i].set(KIND_NODE),
                        index_row.at[i].set(node_pick.astype(jnp.int32)),
                    )

                # -- 2. claims: fewest-pods retry with exact B=1 verification
                def try_claims(a):
                    taken_nodes, st, kind_row, index_row = a
                    opt = (
                        st.claim_open
                        & tol_tpl[st.claim_tpl]
                        & ~jnp.any(
                            st.claim_used_ports & pod_conflict[None, :], axis=-1
                        )
                    )
                    zero_final = st.claim_req.row(0)

                    def c_cond(cc):
                        cand, found = cc[0], cc[1]
                        return jnp.any(cand) & ~found

                    def c_body(cc):
                        cand, _found, _pick, f_keep, itok_keep = cc
                        rank = jnp.where(
                            cand, st.claim_npods * C + jnp.arange(C), _BIG
                        )
                        c = jnp.argmin(rank)
                        row = st.claim_req.row(c)
                        merged = masks.intersect(row, pod_req)
                        compat = masks.compatible_ok(row, pod_req, lv, ln, wellknown)
                        merged1 = _bcast_req(merged, 1, K, V)
                        ok_t, final1 = topo_gate(
                            problem, st.grp_counts, st.grp_registered, topo_pod,
                            merged1, wellknown,
                        )
                        requests2 = st.claim_requests[c] + pod_requests
                        itok2 = it_gate(
                            final1, requests2[None, :], st.claim_it_ok[c][None, :]
                        )[0]
                        ok = compat & ok_t[0] & jnp.any(itok2)
                        final = jax.tree_util.tree_map(lambda x: x[0], final1)
                        f2 = jax.tree_util.tree_map(
                            lambda keep, new: jnp.where(ok, new, keep), f_keep, final
                        )
                        return (
                            cand & (jnp.arange(C) != c),
                            ok,
                            jnp.where(ok, c, 0).astype(jnp.int32),
                            f2,
                            jnp.where(ok, itok2, itok_keep),
                        )

                    _cand, found, pick, final, itok2 = lax.while_loop(
                        c_cond,
                        c_body,
                        (opt, jnp.bool_(False), jnp.int32(0), zero_final,
                         st.claim_it_ok[0]),
                    )

                    def commit_claim(a2):
                        taken_nodes, st, kind_row, index_row = a2
                        hot = jnp.arange(C) == pick
                        counts, registered = record(
                            problem, st.grp_counts, st.grp_registered, topo_pod,
                            final, wellknown, jnp.bool_(True), lv, ln,
                        )
                        st2 = dataclasses.replace(
                            st,
                            claim_req=_mix_req_rows(
                                st.claim_req, _bcast_req(final, C, K, V), hot
                            ),
                            claim_requests=st.claim_requests
                            + hot[:, None] * pod_requests[None, :],
                            claim_it_ok=jnp.where(
                                hot[:, None], itok2[None, :], st.claim_it_ok
                            ),
                            claim_npods=st.claim_npods + hot.astype(jnp.int32),
                            claim_used_ports=st.claim_used_ports
                            | (hot[:, None] & pod_ports[None, :]),
                            grp_counts=counts,
                            grp_registered=registered,
                        )
                        return (
                            taken_nodes,
                            st2,
                            kind_row.at[i].set(KIND_CLAIM),
                            index_row.at[i].set(pick),
                        )

                    # -- 3. fresh template claim (step phase 3, B=TPL bins)
                    def try_templates(a2):
                        taken_nodes, st, kind_row, index_row = a2
                        free_slot = _first_true(~st.claim_open)
                        has_slot = jnp.any(~st.claim_open)
                        tpl_merged, tpl_compat, host_onehot = _fresh_template_rows(
                            problem, lv, ln, wellknown, pod_req, free_slot
                        )
                        mint = problem.claim_hostname_lane.shape[0] > 0
                        reg_for_tpl = st.grp_registered | (
                            mint
                            & (problem.grp_key == HOSTNAME_KEY)[:, None]
                            & host_onehot[None, :]
                        )
                        tpl_ok_t, tpl_final = topo_gate(
                            problem, st.grp_counts, reg_for_tpl, topo_pod,
                            tpl_merged, wellknown,
                        )
                        tpl_requests2 = problem.tpl_overhead + pod_requests[None, :]
                        within = masks.fits(
                            problem.it_cap[None, :, :], st.remaining[:, None, :]
                        )
                        tpl_it_ok2 = it_gate(
                            tpl_final, tpl_requests2, problem.tpl_it_ok & within
                        )
                        tpl_ok = (
                            tol_tpl
                            & tpl_compat
                            & tpl_ok_t
                            & jnp.any(tpl_it_ok2, axis=-1)
                        )
                        tpick = _first_true(tpl_ok)
                        any_tpl = jnp.any(tpl_ok)
                        tpick_c = jnp.minimum(tpick, TPL - 1)

                        def open_claim(a3):
                            taken_nodes, st, kind_row, index_row = a3
                            hot = jnp.arange(C) == free_slot
                            slot_req = tpl_final.row(tpick_c)
                            row_itok = tpl_it_ok2[tpick_c]
                            max_cap = jnp.max(
                                jnp.where(row_itok[:, None], problem.it_cap, 0.0),
                                axis=0,
                            )
                            opened_tpl_hot = jnp.arange(TPL) == tpick_c
                            counts, registered = record(
                                problem, st.grp_counts, reg_for_tpl, topo_pod,
                                slot_req, wellknown, jnp.bool_(True), lv, ln,
                            )
                            st2 = dataclasses.replace(
                                st,
                                claim_req=_mix_req_rows(
                                    st.claim_req, _bcast_req(slot_req, C, K, V), hot
                                ),
                                claim_requests=jnp.where(
                                    hot[:, None],
                                    tpl_requests2[tpick_c][None, :],
                                    st.claim_requests,
                                ),
                                claim_it_ok=jnp.where(
                                    hot[:, None], row_itok[None, :], st.claim_it_ok
                                ),
                                claim_open=st.claim_open | hot,
                                claim_npods=st.claim_npods + hot.astype(jnp.int32),
                                claim_tpl=jnp.where(
                                    hot, tpick_c.astype(jnp.int32), st.claim_tpl
                                ),
                                claim_used_ports=st.claim_used_ports
                                | (hot[:, None] & pod_ports[None, :]),
                                remaining=jnp.where(
                                    opened_tpl_hot[:, None],
                                    st.remaining - max_cap[None, :],
                                    st.remaining,
                                ),
                                grp_counts=counts,
                                grp_registered=registered,
                            )
                            return (
                                taken_nodes,
                                st2,
                                kind_row.at[i].set(KIND_NEW_CLAIM),
                                index_row.at[i].set(free_slot.astype(jnp.int32)),
                            )

                        def no_open(a3):
                            taken_nodes, st, kind_row, index_row = a3
                            # ~has_slot => NO_SLOT regardless of any_tpl: the
                            # prospective row evaluated a clamped (used) slot
                            # hostname, so its verdict can't distinguish
                            # "unplaceable" from "out of slots" (ops/ffd.py
                            # step classification)
                            fail = jnp.where(
                                ~has_slot, KIND_NO_SLOT, KIND_FAIL
                            ).astype(jnp.int32)
                            return (
                                taken_nodes,
                                st,
                                kind_row.at[i].set(fail),
                                index_row.at[i].set(-1),
                            )

                        return lax.cond(any_tpl & has_slot, open_claim, no_open, a2)

                    return lax.cond(found, commit_claim, try_templates, a)

                if N > 0:
                    return lax.cond(any_node, commit_node, try_claims, args)
                return try_claims(args)

            def skip_pod(args):
                taken_nodes, st, kind_row, index_row = args
                return (
                    taken_nodes,
                    st,
                    kind_row.at[i].set(KIND_FAIL),
                    index_row.at[i].set(-1),
                )

            args = (taken_nodes, st, kind_row, index_row)
            taken_nodes, st, kind_row, index_row = lax.cond(
                is_active, place, skip_pod, args
            )
            return (i + 1, taken_nodes, st, kind_row, index_row)

        def cond(carry):
            return carry[0] < jnp.minimum(length, max_run)

        (_i, taken_nodes, st, kind_row, index_row) = lax.while_loop(
            cond,
            body,
            (
                jnp.int32(0),
                jnp.zeros((N,), jnp.int32),
                state,
                jnp.full((max_run,), KIND_FAIL, jnp.int32),
                jnp.full((max_run,), -1, jnp.int32),
            ),
        )

        # bulk-apply node resource fills (requirement rows were committed
        # in-loop with their topo-narrowed finals)
        if N > 0:
            took = taken_nodes > 0
            st = dataclasses.replace(
                st,
                node_requests=st.node_requests
                + taken_nodes[:, None] * pod_requests[None, :],
                node_npods=st.node_npods + taken_nodes,
                node_used_ports=st.node_used_ports
                | (took[:, None] & pod_ports[None, :]),
            )
        return st, (kind_row, index_row)

    return commit
