"""Device-resident world programs: jitted delta patching + fused solve/gate.

Two programs back the ``DeviceWorld`` handle (streaming/device_world.py):

``patch_world``
    Applies a ``DeltaEncoder`` row splice (streaming/delta.py) ON DEVICE: the
    previous cycle's padded ``SchedulingProblem`` is DONATED and rewritten in
    place from a small ``PatchArgs`` bundle — a gather index over surviving
    rows, a fresh-row stack for arrivals/spec-changes, and the full (tiny)
    run tables. Pad rows are synthesized from the same deterministic fills
    ``ops/padding.pad_problem`` uses, so the patched device world is
    bit-identical to ``pad_problem(spliced_host_problem)`` by construction —
    the invariant tests/test_device_world.py fuzzes array-for-array.

``solve_ffd_fused_gate``
    The fresh sweeps solve (ops/ffd_sweeps._sweeps_impl) with the device
    verification gate (verify/device._gate_impl) traced into the SAME
    program: one dispatch returns (FFDResult, invariant counts). The gate
    args are built on device from the final FFDState — the solver's own
    claim rows, surviving instance types, and accumulated requests — so
    verification reads exactly what the solve committed. The host screen,
    skew check, and sampled float64 audit (verify/gate.py) still run on the
    decoded result; the fused counts only displace the separate gate
    dispatch.

Both keep the flag-off programs untouched: they are NEW entry points the
DeviceWorld path selects, never edits of ``_solve_ffd_sweeps_fresh_jit`` or
``_gate_jit`` (the kernel-census pins in tests/test_kernel_census.py hold the
line).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from karpenter_tpu.models.problem import (
    GT_NONE,
    LT_NONE,
    ReqTensor,
    SchedulingProblem,
)
from karpenter_tpu.ops.ffd_core import (
    KIND_CLAIM,
    KIND_NEW_CLAIM,
    KIND_NODE,
    _pad_lanes_mult32,
    initial_state,
)
from karpenter_tpu.ops.padding import pow2_bucket


class PatchArgs(NamedTuple):
    """Everything one device row-patch ships: O(P) index/mask lanes, a
    bucketed fresh-row stack per pod-axis leaf, and the full run tables
    (small — host ``segment_runs`` output depends on neighbouring rows, so
    it is recomputed host-side and shipped whole). All arrays are in PADDED
    coordinates; the stack's tail rows hold pad_problem's fill constants so
    pad rows and fresh rows share one gather."""

    gather_idx: Any  # i32[P] previous-world row per surviving row
    use_fresh: Any  # bool[P] row comes from the fresh stack (incl. pad rows)
    fresh_sel: Any  # i32[P] stack row for fresh/pad rows
    # fresh-row stacks [S, ...tail buckets...]
    req_admitted: Any
    req_comp: Any
    req_gt: Any
    req_lt: Any
    req_defined: Any
    strict_admitted: Any
    strict_comp: Any
    strict_gt: Any
    strict_lt: Any
    strict_defined: Any
    requests: Any  # f32[S, R]
    tol_tpl: Any  # bool[S, TPL]
    tol_node: Any  # bool[S, N]
    ports: Any  # bool[S, PT]
    port_conflict: Any  # bool[S, PT]
    vol_counts: Any  # i32[S, D]
    grp_match: Any  # bool[S, G] (G=0 on every patchable world)
    grp_selects: Any
    grp_owned: Any
    # full-ship small arrays
    pod_active: Any  # bool[P]
    eqprev: Any  # bool[P]
    eqprev_gate: Any  # bool[P]
    eqprev_chain: Any  # bool[P]
    run_start: Any  # i32[RN]
    run_len: Any  # i32[RN]
    run_mode: Any  # i32[RN]


# pad_problem's constant fills for the pod-axis leaves (ops/padding.py). The
# fuzz suite holds these to the source of truth: any drift from pad_problem
# breaks patched-vs-cold bit identity and fails tests/test_device_world.py.
_REQ_FILLS = {
    "admitted": False,
    "comp": True,
    "gt": GT_NONE,
    "lt": LT_NONE,
    "defined": False,
}


def build_patch_args(
    spliced: SchedulingProblem, rows_prev: np.ndarray, resident: SchedulingProblem
) -> PatchArgs:
    """Host-side plan build (numpy): map the delta encoder's row splice onto
    the resident padded world. ``spliced`` is the UNPADDED patched problem the
    DeltaEncoder produced (the bit-identity reference), ``rows_prev`` its
    per-row previous-world index (-1 = freshly encoded), ``resident`` the
    device world whose leaf shapes fix every tail bucket. The caller has
    already proven the pod/node/lane buckets match (streaming/device_world.py
    adopt-on-drift)."""
    P_cur = int(np.asarray(spliced.pod_requests).shape[0])
    Pb = int(resident.pod_requests.shape[0])
    rows_prev = np.asarray(rows_prev, dtype=np.int64)
    fresh_pos = np.where(rows_prev < 0)[0]
    F = len(fresh_pos)
    # S > F always: the stack's tail rows ARE the pad-row template
    S = pow2_bucket(F + 1, lo=8)

    gather_idx = np.zeros(Pb, dtype=np.int32)
    gather_idx[:P_cur] = np.maximum(rows_prev, 0)
    use_fresh = np.ones(Pb, dtype=bool)  # pad rows gather the fill row
    use_fresh[:P_cur] = rows_prev < 0
    fresh_sel = np.full(Pb, F, dtype=np.int32)
    fresh_sel[fresh_pos] = np.arange(F, dtype=np.int32)

    def stack(arr, tail, fill):
        arr = np.asarray(arr)
        out = np.full((S,) + tuple(tail), fill, dtype=arr.dtype)
        sub = arr[fresh_pos]
        out[(slice(0, F),) + tuple(slice(0, d) for d in sub.shape[1:])] = sub
        return out

    def req_stacks(src: ReqTensor, ref: ReqTensor):
        return {
            f: stack(getattr(src, f), ref_leaf.shape[1:], _REQ_FILLS[f])
            for f, ref_leaf in (
                ("admitted", ref.admitted),
                ("comp", ref.comp),
                ("gt", ref.gt),
                ("lt", ref.lt),
                ("defined", ref.defined),
            )
        }

    reqs = req_stacks(spliced.pod_reqs, resident.pod_reqs)
    strict = req_stacks(spliced.pod_strict_reqs, resident.pod_strict_reqs)

    def full(arr, length, fill):
        arr = np.asarray(arr)
        out = np.full((length,), fill, dtype=arr.dtype)
        out[: arr.shape[0]] = arr
        return out

    RNb = pow2_bucket(int(np.asarray(spliced.run_len).shape[0]), lo=4)
    return PatchArgs(
        gather_idx=gather_idx,
        use_fresh=use_fresh,
        fresh_sel=fresh_sel,
        req_admitted=reqs["admitted"],
        req_comp=reqs["comp"],
        req_gt=reqs["gt"],
        req_lt=reqs["lt"],
        req_defined=reqs["defined"],
        strict_admitted=strict["admitted"],
        strict_comp=strict["comp"],
        strict_gt=strict["gt"],
        strict_lt=strict["lt"],
        strict_defined=strict["defined"],
        requests=stack(
            spliced.pod_requests, resident.pod_requests.shape[1:], 0.0
        ),
        tol_tpl=stack(spliced.pod_tol_tpl, resident.pod_tol_tpl.shape[1:], False),
        tol_node=stack(
            spliced.pod_tol_node, resident.pod_tol_node.shape[1:], False
        ),
        ports=stack(spliced.pod_ports, resident.pod_ports.shape[1:], False),
        port_conflict=stack(
            spliced.pod_port_conflict, resident.pod_port_conflict.shape[1:], False
        ),
        vol_counts=stack(
            spliced.pod_vol_counts, resident.pod_vol_counts.shape[1:], 0
        ),
        grp_match=stack(spliced.pod_grp_match, resident.pod_grp_match.shape[1:], False),
        grp_selects=stack(
            spliced.pod_grp_selects, resident.pod_grp_selects.shape[1:], False
        ),
        grp_owned=stack(
            spliced.pod_grp_owned, resident.pod_grp_owned.shape[1:], False
        ),
        pod_active=full(spliced.pod_active, Pb, False),
        eqprev=full(spliced.pod_eqprev, Pb, False),
        eqprev_gate=full(spliced.pod_eqprev_gate, Pb, False),
        eqprev_chain=full(spliced.pod_eqprev_chain, Pb, False),
        run_start=full(spliced.run_start, RNb, 0),
        run_len=full(spliced.run_len, RNb, 0),
        run_mode=full(spliced.run_mode, RNb, 1),
    )


@functools.partial(jax.jit, donate_argnums=(0,))
def _patch_world_jit(prev: SchedulingProblem, args: PatchArgs) -> SchedulingProblem:
    """Rewrite the pod-axis leaves of the donated world in place: surviving
    rows gather from the previous buffer, fresh/pad rows gather from the
    shipped stack. Non-pod leaves pass through the donation untouched (the
    patch preconditions proved them unchanged)."""

    def rows(prev_leaf, stk):
        mask = args.use_fresh.reshape((-1,) + (1,) * (prev_leaf.ndim - 1))
        return jnp.where(mask, stk[args.fresh_sel], prev_leaf[args.gather_idx])

    def req_rows(prev_t: ReqTensor, names) -> ReqTensor:
        a, c, g, l, d = names
        return ReqTensor(
            admitted=rows(prev_t.admitted, a),
            comp=rows(prev_t.comp, c),
            gt=rows(prev_t.gt, g),
            lt=rows(prev_t.lt, l),
            defined=rows(prev_t.defined, d),
        )

    return dataclasses.replace(
        prev,
        pod_reqs=req_rows(
            prev.pod_reqs,
            (args.req_admitted, args.req_comp, args.req_gt, args.req_lt,
             args.req_defined),
        ),
        pod_strict_reqs=req_rows(
            prev.pod_strict_reqs,
            (args.strict_admitted, args.strict_comp, args.strict_gt,
             args.strict_lt, args.strict_defined),
        ),
        pod_requests=rows(prev.pod_requests, args.requests),
        pod_tol_tpl=rows(prev.pod_tol_tpl, args.tol_tpl),
        pod_tol_node=rows(prev.pod_tol_node, args.tol_node),
        pod_ports=rows(prev.pod_ports, args.ports),
        pod_port_conflict=rows(prev.pod_port_conflict, args.port_conflict),
        pod_vol_counts=rows(prev.pod_vol_counts, args.vol_counts),
        pod_grp_match=rows(prev.pod_grp_match, args.grp_match),
        pod_grp_selects=rows(prev.pod_grp_selects, args.grp_selects),
        pod_grp_owned=rows(prev.pod_grp_owned, args.grp_owned),
        pod_active=args.pod_active,
        pod_eqprev=args.eqprev,
        pod_eqprev_gate=args.eqprev_gate,
        pod_eqprev_chain=args.eqprev_chain,
        run_start=args.run_start,
        run_len=args.run_len,
        run_mode=args.run_mode,
    )


def patch_world(prev: SchedulingProblem, args: PatchArgs) -> SchedulingProblem:
    """Named entry for the device row patch — the name keys the program
    cache, the AOT executable table (solver/aot.py), and the registry row."""
    return _patch_world_jit(prev, args)


patch_world._donates_carry = True  # the world is consumed in place


def fused_gate_counts(problem, kind, index, state, pod_check, max_claims, gate_bf):
    """The fused program's verification epilogue, traceable standalone (the
    kernel census pins it separately from the narrow loop body): build
    GateArgs from the final FFDState and run the invariant reduction.

    The claim rows checked here are the solver's own requirement state —
    including the minted hostname pin the published rows drop — so the gate
    is consistent-by-construction with the solve; the decoded RESULT is still
    covered by the host screen + skew + sampled audit (verify/gate.py)."""
    from karpenter_tpu.verify.device import GateArgs, _gate_impl, gate_problem

    C = int(max_claims)
    on_claim = (kind == KIND_CLAIM) | (kind == KIND_NEW_CLAIM)
    on_node = kind == KIND_NODE
    pod_bin = jnp.where(
        on_claim, index, jnp.where(on_node, C + index, -1)
    ).astype(jnp.int32)
    ga = GateArgs(
        claim_req=state.claim_req,
        claim_tpl=state.claim_tpl,
        claim_active=state.claim_open,
        claim_reported=state.claim_requests,
        claim_its=state.claim_it_ok,
        claim_has_reqs=state.claim_open,
        pod_bin=pod_bin,
        pod_check=pod_check,
    )
    return _gate_impl(gate_problem(problem), ga, bool(gate_bf))


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5))
def _solve_ffd_fused_gate_jit(
    problem: SchedulingProblem,
    pod_check,
    max_claims: int,
    bounds_free: bool = False,
    wavefront: int = 0,
    gate_bf: bool = False,
):
    """Fresh sweeps solve + device gate in ONE dispatch. The world is NOT
    donated here — it stays resident for the next cycle's patch (the patch
    program owns the donation)."""
    from karpenter_tpu.ops.ffd_sweeps import _sweeps_impl

    problem = _pad_lanes_mult32(problem)
    result = _sweeps_impl(
        problem, initial_state(problem, max_claims), max_claims,
        bounds_free, wavefront,
    )
    counts = fused_gate_counts(
        problem, result.kind, result.index, result.state, pod_check,
        max_claims, gate_bf,
    )
    return result, counts


def solve_ffd_fused_gate(
    problem, pod_check, max_claims, bounds_free=False, wavefront=0, gate_bf=False
):
    """Named entry for the fused solve+gate program (see patch_world)."""
    return _solve_ffd_fused_gate_jit(
        problem, pod_check, int(max_claims), bool(bounds_free), int(wavefront),
        bool(gate_bf),
    )
