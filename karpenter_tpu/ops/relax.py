"""Relaxation-first phase-1 placement: dense waterfill over pod x template
bins (KARPENTER_TPU_RELAX, round 15).

The sweeps solver walks the queue one narrow iteration per gate-identical
chain (~2,066 iterations at the 10k bench shape) because every commit can
narrow the chosen bin and shift the topology counters. But for the easy
majority of a batch none of that feedback exists: a pod with no host ports,
no matched/owned topology group, no hostname requirement, and no candidate
existing node interacts with the rest of the solve only through the claim it
lands in. Those pods can be placed in a CONSTANT number of dense tensor
passes:

  1. eligibility — one [P] mask from the encoded tensors (ports, topology
     roles, hostname requirement, a conservative any-node screen, and a
     nodepool-limits guard: finite ``remaining`` burns sequentially on open,
     so relaxation stands down and phase 2 does everything);
  2. bin-groups — maximal runs of adjacent eligible pods whose requirement
     rows and template tolerations are byte-equal (requests MAY differ; the
     FFD queue order packs identical specs adjacent, so runs are long);
  3. template pick — first template whose merged row is compatible and
     admits the group's elementwise-max request on some instance type, via
     the same fused masks.compatible_from_merged / it_gate kernels the
     narrow step uses;
  4. waterfill rounding — each pod's demand becomes a normalized scalar
     against the group's best-packing instance type, a prefix sum assigns
     floor(level) bins, and a fixed ladder of KARPENTER_TPU_RELAX_PASSES
     feasibility passes re-checks every claim with the REAL instance-type
     gate, demoting the last-assigned pod of any infeasible claim per rung
     (then the whole claim if it never becomes feasible);
  5. state build — surviving claims become ordinary FFDState rows (pinned
     minted hostname, exact claim_it_ok, topology counts/registration via
     the same record_delta kernel), and everything demoted plus everything
     ineligible is the residue handed to the sweeps repair pass as carried
     state.

Steps 1-3 and the ladder/commit of steps 4-5 are SHARED with the round-22
projected-gradient solver (ops/relax2.py) via ops/relax_common.py — one
host screen, one eligibility mask, one commit path; only the assignment
math between them differs (prefix-sum waterfill here, PGD + largest-
fraction rounding there).

Phase-1 placements are NOT bit-identical to pure FFD (pods land in
group-order claims, not fewest-pods order); the contract is the validator's:
every gate the narrow step enforces is re-checked here with the same
kernels, and the backend full-gates relaxed results (solver/validator.py).
Flag off, nothing in this module is traced and the sweeps program is
byte-identical (census-pinned at 2394 eqns)."""

import functools
import os
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp

from karpenter_tpu.models.problem import SchedulingProblem
from karpenter_tpu.ops.ffd_core import (
    FFDState,
    _pad_lanes_mult32,
    _statics,
    problem_bounds_free,
)
from karpenter_tpu.ops.relax_common import (
    commit_assignment,
    eligibility as _eligibility,  # shared mask builder (relax_common.py)
    plan_groups,
    relax_applicable,
)

__all__ = [
    "RelaxOut",
    "RelaxStats",
    "enabled",
    "relax_applicable",
    "relax_passes",
    "relax_place",
]


def enabled() -> bool:
    """KARPENTER_TPU_RELAX=0 turns the two-phase solve off. Read at call
    time (not import) so the parity fuzz can A/B flag-on and flag-off in one
    process. Default ON since round 16: the diverse 10k corpus showed the
    relaxed path scheduling no fewer pods with a solve-time win
    (docs/PERF_NOTES.md round 16), every relaxed result is still full-gated
    with automatic flag-off fallback on violation, and the oracle
    differential keeps its bit-identity contract by pinning the flag off
    (tests/conftest.py) — relaxed placements are validator-equivalent but
    not bit-identical to the oracle."""
    return os.environ.get("KARPENTER_TPU_RELAX", "1") == "1"


def relax_passes() -> int:
    """Feasibility-repair rungs after rounding (static jit argument). Each
    rung demotes the LAST-assigned pod of every claim the real instance-type
    gate rejects; 2 covers the <=1-pod overshoot of the homogeneous rounding
    plus one slack rung. More rungs trade device work for residue."""
    return max(int(os.environ.get("KARPENTER_TPU_RELAX_PASSES", "2")), 1)


class RelaxStats(NamedTuple):
    """Device-side phase-1 telemetry (fetched in one tiny roundtrip)."""

    eligible: Any  # i32 pods that passed the eligibility screen
    placed: Any  # i32 pods phase 1 committed
    demoted: Any  # i32 eligible pods the rounding ladder sent to repair
    claims: Any  # i32 claims phase 1 opened


class RelaxOut(NamedTuple):
    """Phase-1 result: the claim landscape the repair pass starts from.
    ``kind``/``index`` are the already-final verdict rows for placed pods
    (KIND_FAIL elsewhere); ``residue_active`` replaces pod_active for the
    phase-2 problem. Shared by both phase-1 solvers — relax2's ``stats``
    slot carries its richer Relax2Stats instead."""

    state: FFDState
    kind: Any  # i32[P]
    index: Any  # i32[P]
    residue_active: Any  # bool[P]
    stats: Any  # RelaxStats (waterfill) or Relax2Stats (relax2)


def _relax_impl(
    problem: SchedulingProblem, C: int, bounds_free: bool, n_passes: int
) -> RelaxOut:
    statics = _statics(problem, bounds_free)
    plan = plan_groups(problem, C, statics)
    elig, gid, gidc, hp, w = plan.elig, plan.gid, plan.gidc, plan.hp, plan.w

    # -- waterfill rounding: prefix-sum level of the normalized demand ->
    # floor bin within the group's slot window
    csum = jnp.cumsum(w)
    start = csum - w
    level = start - (csum - w)[hp][gidc]  # per-group prefix level
    binp = jnp.maximum(jnp.floor(level + 1e-6).astype(jnp.int32), 0)
    nbins = (
        jnp.full((C,), -1, jnp.int32)
        .at[jnp.where(elig, gid, C)]
        .max(binp, mode="drop")
        + 1
    )
    nbins = jnp.where(plan.gvalid, nbins, 0)
    slotbase = jnp.cumsum(nbins) - nbins  # exclusive prefix
    slot = slotbase[gidc] + binp  # [P]
    assigned = elig & (slot < C)  # slot overflow -> repair pass

    com = commit_assignment(problem, C, statics, plan, slot, assigned, n_passes)
    stats = RelaxStats(
        eligible=jnp.sum(plan.elig0).astype(jnp.int32),
        placed=jnp.sum(com.assigned).astype(jnp.int32),
        demoted=jnp.sum(plan.elig0 & ~com.assigned).astype(jnp.int32),
        claims=jnp.sum(com.open_c).astype(jnp.int32),
    )
    return RelaxOut(
        state=com.state, kind=com.kind, index=com.index,
        residue_active=com.residue_active, stats=stats,
    )


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def _relax_place_jit(
    problem: SchedulingProblem, max_claims: int, bounds_free: bool, n_passes: int
) -> RelaxOut:
    problem = _pad_lanes_mult32(problem)
    return _relax_impl(problem, max_claims, bounds_free, n_passes)


def relax_place(
    problem: SchedulingProblem, max_claims: int, init: Optional[FFDState] = None
) -> RelaxOut:
    """Phase 1 of the two-phase solve (see module docstring). ``init`` must
    be None — relaxation only ever runs on a fresh solve; the signature
    matches the other entry points for the backend/aot dispatch plumbing."""
    assert init is None, "relaxation always starts a fresh solve"
    return _relax_place_jit(
        problem, int(max_claims), problem_bounds_free(problem), relax_passes()
    )
