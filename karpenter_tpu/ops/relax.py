"""Relaxation-first phase-1 placement: dense waterfill over pod x template
bins (KARPENTER_TPU_RELAX, round 15).

The sweeps solver walks the queue one narrow iteration per gate-identical
chain (~2,066 iterations at the 10k bench shape) because every commit can
narrow the chosen bin and shift the topology counters. But for the easy
majority of a batch none of that feedback exists: a pod with no host ports,
no matched/owned topology group, no hostname requirement, and no candidate
existing node interacts with the rest of the solve only through the claim it
lands in. Those pods can be placed in a CONSTANT number of dense tensor
passes:

  1. eligibility — one [P] mask from the encoded tensors (ports, topology
     roles, hostname requirement, a conservative any-node screen, and a
     nodepool-limits guard: finite ``remaining`` burns sequentially on open,
     so relaxation stands down and phase 2 does everything);
  2. bin-groups — maximal runs of adjacent eligible pods whose requirement
     rows and template tolerations are byte-equal (requests MAY differ; the
     FFD queue order packs identical specs adjacent, so runs are long);
  3. template pick — first template whose merged row is compatible and
     admits the group's elementwise-max request on some instance type, via
     the same fused masks.compatible_from_merged / it_gate kernels the
     narrow step uses;
  4. waterfill rounding — each pod's demand becomes a normalized scalar
     against the group's best-packing instance type, a prefix sum assigns
     floor(level) bins, and a fixed ladder of KARPENTER_TPU_RELAX_PASSES
     feasibility passes re-checks every claim with the REAL instance-type
     gate, demoting the last-assigned pod of any infeasible claim per rung
     (then the whole claim if it never becomes feasible);
  5. state build — surviving claims become ordinary FFDState rows (pinned
     minted hostname, exact claim_it_ok, topology counts/registration via
     the same record_delta kernel), and everything demoted plus everything
     ineligible is the residue handed to the sweeps repair pass as carried
     state.

Phase-1 placements are NOT bit-identical to pure FFD (pods land in
group-order claims, not fewest-pods order); the contract is the validator's:
every gate the narrow step enforces is re-checked here with the same
kernels, and the backend full-gates relaxed results (solver/validator.py).
Flag off, nothing in this module is traced and the sweeps program is
byte-identical (census-pinned at 2394 eqns)."""

import functools
import os
from dataclasses import replace
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import vmap

from karpenter_tpu.models.problem import (
    HOSTNAME_KEY,
    ReqTensor,
    SchedulingProblem,
)
from karpenter_tpu.ops import masks
from karpenter_tpu.ops.ffd_core import (
    FFDState,
    KIND_CLAIM,
    KIND_FAIL,
    KIND_NEW_CLAIM,
    _first_true,
    _intersect_rows,
    _make_it_gate,
    _mix_req_rows,
    _pad_lanes_mult32,
    _pin_hostname,
    _statics,
    initial_state,
    problem_bounds_free,
)
from karpenter_tpu.ops.topology_kernels import (
    TYPE_ANTI_AFFINITY,
    PodTopoStatics,
    record_delta,
)


def enabled() -> bool:
    """KARPENTER_TPU_RELAX=0 turns the two-phase solve off. Read at call
    time (not import) so the parity fuzz can A/B flag-on and flag-off in one
    process. Default ON since round 16: the diverse 10k corpus showed the
    relaxed path scheduling no fewer pods with a solve-time win
    (docs/PERF_NOTES.md round 16), every relaxed result is still full-gated
    with automatic flag-off fallback on violation, and the oracle
    differential keeps its bit-identity contract by pinning the flag off
    (tests/conftest.py) — relaxed placements are validator-equivalent but
    not bit-identical to the oracle."""
    return os.environ.get("KARPENTER_TPU_RELAX", "1") == "1"


def relax_passes() -> int:
    """Feasibility-repair rungs after rounding (static jit argument). Each
    rung demotes the LAST-assigned pod of every claim the real instance-type
    gate rejects; 2 covers the <=1-pod overshoot of the homogeneous rounding
    plus one slack rung. More rungs trade device work for residue."""
    return max(int(os.environ.get("KARPENTER_TPU_RELAX_PASSES", "2")), 1)


class RelaxStats(NamedTuple):
    """Device-side phase-1 telemetry (fetched in one tiny roundtrip)."""

    eligible: Any  # i32 pods that passed the eligibility screen
    placed: Any  # i32 pods phase 1 committed
    demoted: Any  # i32 eligible pods the rounding ladder sent to repair
    claims: Any  # i32 claims phase 1 opened


class RelaxOut(NamedTuple):
    """Phase-1 result: the claim landscape the repair pass starts from.
    ``kind``/``index`` are the already-final verdict rows for placed pods
    (KIND_FAIL elsewhere); ``residue_active`` replaces pod_active for the
    phase-2 problem."""

    state: FFDState
    kind: Any  # i32[P]
    index: Any  # i32[P]
    residue_active: Any  # bool[P]
    stats: RelaxStats


def relax_applicable(problem: SchedulingProblem) -> bool:
    """Host-side screen (numpy, pre-jit): finite nodepool limits make claim
    opens burn ``remaining`` sequentially, which the vectorized open cannot
    reproduce — the backend skips the phase-1 dispatch entirely."""
    import numpy as np

    return bool(np.all(np.isinf(np.asarray(problem.tpl_remaining))))


def _eligibility(problem: SchedulingProblem, state0: FFDState, statics):
    """bool[P] — pods phase 1 may place, by construction of the mask unable
    to interact with any phase-2 pod except through claim membership:

      - host ports reserve per-claim lanes sequentially -> demoted;
      - matched topology groups are GATED by counters other pods move;
        owned groups feed inverse (anti-affinity) gates; pods selected by an
        inverse or anti-affinity group record into a BLOCKING gate, and
        recording out of queue order could fail a pod FFD would have placed
        -> all demoted. Pods selected by spread/affinity groups stay: their
        recording only rides domains spread pods also mint fresh, and the
        validator + parity corpus hold the line (docs/PERF_NOTES.md r15);
      - a hostname requirement may pin to another claim's minted lane;
      - any possibly-compatible existing node (over-approximate screen at
        the INITIAL node state — node gates only narrow as the solve fills
        them) must keep node-priority semantics -> demoted;
      - finite remaining headroom disables relaxation (traced twin of
        relax_applicable, for direct kernel callers)."""
    lv, ln = statics.lv, statics.ln
    bounds_free = statics.bounds_free
    G = problem.grp_key.shape[0]
    N = problem.num_nodes
    pr = problem.pod_reqs
    req = jnp.asarray(problem.pod_requests)

    elig = jnp.asarray(problem.pod_active)
    if problem.pod_ports.shape[1] > 0:
        elig &= ~jnp.any(problem.pod_ports, axis=1)
        elig &= ~jnp.any(problem.pod_port_conflict, axis=1)
    if G > 0:
        elig &= ~jnp.any(problem.pod_grp_match, axis=1)
        elig &= ~jnp.any(problem.pod_grp_owned, axis=1)
        blocking = problem.grp_inverse | (problem.grp_type == TYPE_ANTI_AFFINITY)
        elig &= ~jnp.any(problem.pod_grp_selects & blocking[None, :], axis=1)
    elig &= ~pr.defined[:, HOSTNAME_KEY]
    elig &= jnp.all(jnp.isinf(state0.remaining))
    if N > 0:
        node_fit = masks.fits(
            jnp.asarray(problem.node_overhead)[None, :, :] + req[:, None, :],
            jnp.asarray(problem.node_avail)[None, :, :],
        )  # [P, N]
        pod_packed = masks.pack_lanes(pr.admitted)
        pod_neg = vmap(lambda r: masks.negative_polarity(r, lv, ln, bounds_free))(pr)
        node_packed = masks.pack_lanes(jnp.asarray(problem.node_reqs.admitted))
        node_neg = vmap(
            lambda r: masks.negative_polarity(r, lv, ln, bounds_free)
        )(problem.node_reqs)
        compat = masks.packed_pairwise_compat(
            pr, pod_packed, pod_neg,
            problem.node_reqs, node_packed, node_neg, bounds_free,
        )  # [P, N] — allowance-free, exactly the node gate's no_allow
        maybe = jnp.asarray(problem.pod_tol_node) & node_fit & compat
        if problem.pod_vol_counts.shape[1] > 0:
            vol_ok = jnp.all(
                jnp.asarray(problem.node_vol_used)[None, :, :]
                + jnp.asarray(problem.pod_vol_counts)[:, None, :]
                <= jnp.asarray(problem.node_vol_limits)[None, :, :],
                axis=-1,
            )
            maybe &= vol_ok
        elig &= ~jnp.any(maybe, axis=1)
    return elig


def _relax_impl(
    problem: SchedulingProblem, C: int, bounds_free: bool, n_passes: int
) -> RelaxOut:
    P, R = problem.num_pods, problem.num_resources
    TPL, T = problem.num_templates, problem.num_instance_types
    K, V = problem.num_keys, problem.num_lanes
    G = problem.grp_key.shape[0]
    statics = _statics(problem, bounds_free)
    lv, ln, wellknown = statics.lv, statics.ln, statics.wellknown
    it_gate = _make_it_gate(problem, statics)
    state0 = initial_state(problem, C)
    mint_hostnames = problem.claim_hostname_lane.shape[0] > 0
    pr = problem.pod_reqs
    req = jnp.asarray(problem.pod_requests)
    pidx = jnp.arange(P, dtype=jnp.int32)

    elig0 = _eligibility(problem, state0, statics)

    # -- bin-groups: adjacent eligible pods with byte-equal requirement rows
    # and template tolerations (requests may differ — the rounding handles
    # size spread). Direct row comparison, NOT pod_eqprev_gate: that chain
    # predicate also requires equal requests and gate-blind topology, which
    # would shatter groups the relaxation merges fine.
    def eq_prev(a):
        flat = a.reshape(P, -1)
        return jnp.all(flat[1:] == flat[:-1], axis=1)

    same = (
        eq_prev(jnp.asarray(pr.admitted))
        & eq_prev(jnp.asarray(pr.comp))
        & eq_prev(jnp.asarray(pr.defined))
        & eq_prev(jnp.asarray(problem.pod_tol_tpl))
    )
    if not bounds_free:
        same &= eq_prev(jnp.asarray(pr.gt)) & eq_prev(jnp.asarray(pr.lt))
    same = jnp.concatenate([jnp.zeros((1,), bool), same])
    join = elig0 & same & jnp.concatenate([jnp.zeros((1,), bool), elig0[:-1]])
    head = elig0 & ~join
    gid = jnp.cumsum(head.astype(jnp.int32)) - 1  # [P], valid where elig0
    # group axis statically capped at C: a group beyond C slots could not
    # open a claim anyway — demote it wholesale to the repair pass
    elig = elig0 & (gid < C)
    head &= gid < C
    gidc = jnp.clip(gid, 0, C - 1)
    gscatter = jnp.where(head, gid, C)
    hp = jnp.zeros((C,), jnp.int32).at[gscatter].set(pidx, mode="drop")
    gvalid = jnp.zeros((C,), bool).at[gscatter].set(True, mode="drop")
    escatter = jnp.where(elig, gid, C)
    gmax = jnp.zeros((C, R), jnp.float32).at[escatter].max(req, mode="drop")

    # -- template pick per group, from the head row (byte-equal across the
    # group) and the group's elementwise-max request: if the max member fits
    # an instance type per-resource, every member does
    rep = pr.row(hp)  # [C, K, V...] representative rows
    rep_neg = vmap(lambda r: masks.negative_polarity(r, lv, ln, bounds_free))(rep)
    merged = vmap(lambda r: _intersect_rows(problem.tpl_reqs, r, bounds_free))(
        rep
    )  # [C, TPL, K, V...]
    if bounds_free:
        tpl_compat = vmap(
            lambda m, d, n: masks.compatible_from_merged(
                masks.nonempty(m, True),
                problem.tpl_reqs.defined, statics.tpl_neg,
                d, n, wellknown,
            )
        )(merged, rep.defined, rep_neg)  # [C, TPL]
    else:
        tpl_compat = vmap(
            lambda row: vmap(
                lambda tr: masks.compatible_ok(tr, row, lv, ln, wellknown)
            )(problem.tpl_reqs)
        )(rep)
    within_limits = masks.fits(
        jnp.asarray(problem.it_cap)[None, :, :], state0.remaining[:, None, :]
    )  # [TPL, T]
    prior = jnp.asarray(problem.tpl_it_ok) & within_limits  # [TPL, T]
    tol = jnp.asarray(problem.pod_tol_tpl)[hp]  # [C, TPL]
    overhead = jnp.asarray(problem.tpl_overhead)  # [TPL, R]
    flat_rows = ReqTensor(
        admitted=merged.admitted.reshape(C * TPL, K, V),
        comp=merged.comp.reshape(C * TPL, K),
        gt=merged.gt.reshape(C * TPL, K),
        lt=merged.lt.reshape(C * TPL, K),
        defined=merged.defined.reshape(C * TPL, K),
    )
    # instance-type survival against the max member; hostname pinning cannot
    # move this gate (instance types never define the hostname key), and the
    # committed claim_it_ok below re-runs it on the pinned rows regardless
    it_ok_max = it_gate(
        flat_rows,
        (overhead[None, :, :] + gmax[:, None, :]).reshape(C * TPL, R),
        jnp.tile(prior, (C, 1)),
    ).reshape(C, TPL, T)
    tpl_ok = tol & tpl_compat & jnp.any(it_ok_max, axis=-1)  # [C, TPL]
    tpick = vmap(_first_true)(tpl_ok).astype(jnp.int32)  # [C]; TPL when none
    gvalid &= jnp.any(tpl_ok, axis=1)
    tpick = jnp.minimum(tpick, TPL - 1)
    elig &= gvalid[gidc]

    # -- waterfill rounding: normalized demand against the group's best
    # packing instance type, prefix-sum level -> floor bin
    garange = jnp.arange(C)
    it_pick_ok = it_ok_max[garange, tpick]  # [C, T]
    capvec_t = (
        jnp.asarray(problem.it_alloc)[None, :, :] - overhead[tpick][:, None, :]
    )  # [C, T, R]
    gsum = jnp.zeros((C, R), jnp.float32).at[
        jnp.where(elig, gid, C)
    ].add(jnp.where(elig[:, None], req, 0.0), mode="drop")
    demand = gsum[:, None, :] > 0  # [C, 1->T, R]
    frac = jnp.max(
        jnp.where(demand, gsum[:, None, :] / jnp.maximum(capvec_t, 1e-9), 0.0),
        axis=-1,
    )  # [C, T] fractional bins if the group packed on that instance type
    no_room = jnp.any(demand & (capvec_t <= 0), axis=-1)
    frac = jnp.where(no_room, jnp.inf, frac)
    tau = jnp.argmin(jnp.where(it_pick_ok, frac, jnp.inf), axis=-1)  # [C]
    capvec = jnp.asarray(problem.it_alloc)[tau] - overhead[tpick]  # [C, R]
    cv = capvec[gidc]  # [P, R]
    size = jnp.max(jnp.where(req > 0, req / jnp.maximum(cv, 1e-9), 0.0), axis=-1)
    size = jnp.clip(size, 1e-6, 1.0)
    w = jnp.where(elig, size, 0.0)
    csum = jnp.cumsum(w)
    start = csum - w
    level = start - (csum - w)[hp][gidc]  # per-group prefix level
    binp = jnp.maximum(jnp.floor(level + 1e-6).astype(jnp.int32), 0)
    nbins = (
        jnp.full((C,), -1, jnp.int32)
        .at[jnp.where(elig, gid, C)]
        .max(binp, mode="drop")
        + 1
    )
    nbins = jnp.where(gvalid, nbins, 0)
    slotbase = jnp.cumsum(nbins) - nbins  # exclusive prefix
    slot = slotbase[gidc] + binp  # [P]
    assigned = elig & (slot < C)  # slot overflow -> repair pass
    slotc = jnp.clip(slot, 0, C - 1)
    g_of_c = jnp.zeros((C,), jnp.int32).at[
        jnp.where(assigned, slot, C)
    ].max(gid, mode="drop")

    # -- per-claim rows (constant across the ladder): merged template row of
    # the owning group, pinned to the slot's minted hostname exactly like
    # _fresh_template_rows does for the narrow step
    tpl_of_c = tpick[g_of_c]  # [C]
    rows_c = ReqTensor(
        admitted=merged.admitted[g_of_c, tpl_of_c],
        comp=merged.comp[g_of_c, tpl_of_c],
        gt=merged.gt[g_of_c, tpl_of_c],
        lt=merged.lt[g_of_c, tpl_of_c],
        defined=merged.defined[g_of_c, tpl_of_c],
    )
    if mint_hostnames:
        lanes = problem.claim_hostname_lane[
            jnp.minimum(garange, problem.claim_hostname_lane.shape[0] - 1)
        ]
        host1 = jnp.arange(V)[None, :] == lanes[:, None]  # [C, V]
        rows_c = _pin_hostname(rows_c, host1)
    else:
        host1 = jnp.zeros((C, V), bool)
    prior_c = prior[tpl_of_c]  # [C, T]
    overhead_c = overhead[tpl_of_c]  # [C, R]

    # -- rounding ladder: the REAL instance-type gate (compat x fits x
    # offering, same kernel as the narrow step) over every claim; each rung
    # demotes the last-assigned pod of an infeasible claim, the final rung
    # demotes whole claims that never became feasible
    for rung in range(n_passes + 1):
        sidx = jnp.where(assigned, slot, C)
        sums = jnp.zeros((C, R), jnp.float32).at[sidx].add(
            jnp.where(assigned[:, None], req, 0.0), mode="drop"
        )
        ok_c = it_gate(rows_c, overhead_c + sums, prior_c)  # [C, T]
        feas = jnp.any(ok_c, axis=-1)
        if rung < n_passes:
            lastp = jnp.full((C,), -1, jnp.int32).at[sidx].max(pidx, mode="drop")
            assigned &= feas[slotc] | (pidx != lastp[slotc])
        else:
            assigned &= feas[slotc]

    # -- commit: final sums/gates over the surviving assignment
    sidx = jnp.where(assigned, slot, C)
    npods = jnp.zeros((C,), jnp.int32).at[sidx].add(1, mode="drop")
    sums = jnp.zeros((C, R), jnp.float32).at[sidx].add(
        jnp.where(assigned[:, None], req, 0.0), mode="drop"
    )
    creq = overhead_c + sums
    ok_c = it_gate(rows_c, creq, prior_c)
    open_c = (npods > 0) & jnp.any(ok_c, axis=-1)

    new_registered = state0.grp_registered
    new_counts = state0.grp_counts
    if G > 0:
        if mint_hostnames:
            # a claim open registers its minted hostname lane for every
            # hostname-keyed group (mirrors the narrow step's open commit)
            minted = jnp.any(open_c[:, None] & host1, axis=0)  # [V]
            new_registered = new_registered | (
                (problem.grp_key == HOSTNAME_KEY)[:, None] & minted[None, :]
            )
        # record_delta depends on the pod only through grp_selects/grp_owned:
        # one all-select probe per claim row yields the per-group unit delta,
        # and the per-pod records are that unit scaled by how many assigned
        # pods of the claim actually select the group (eligible pods never
        # own, so the inverse term is identically zero)
        probe = PodTopoStatics(
            strict_admitted=jnp.zeros((K, V), bool),
            grp_match=jnp.zeros((G,), bool),
            grp_selects=jnp.ones((G,), bool),
            grp_owned=jnp.zeros((G,), bool),
        )
        units = vmap(
            lambda row, committed: record_delta(
                problem, probe, row, wellknown, committed, lv, ln
            )
        )(rows_c, open_c)  # [C, G, V]
        selcnt = jnp.zeros((C, G), jnp.int32).at[sidx].add(
            jnp.where(
                assigned[:, None], jnp.asarray(problem.pod_grp_selects), False
            ).astype(jnp.int32),
            mode="drop",
        )
        new_counts = new_counts + jnp.sum(
            selcnt[:, :, None] * units.astype(jnp.int32), axis=0
        )
        new_registered = new_registered | jnp.any(
            (selcnt > 0)[:, :, None] & units, axis=0
        )

    state1 = replace(
        state0,
        claim_req=_mix_req_rows(state0.claim_req, rows_c, open_c, bounds_free),
        claim_requests=jnp.where(open_c[:, None], creq, 0.0),
        claim_it_ok=ok_c & open_c[:, None],
        claim_open=open_c,
        claim_npods=jnp.where(open_c, npods, 0),
        claim_tpl=jnp.where(open_c, tpl_of_c, 0),
        grp_counts=new_counts,
        grp_registered=new_registered,
    )
    firstp = jnp.full((C,), P, jnp.int32).at[sidx].min(pidx, mode="drop")
    kind = jnp.where(
        assigned,
        jnp.where(pidx == firstp[slotc], KIND_NEW_CLAIM, KIND_CLAIM),
        KIND_FAIL,
    ).astype(jnp.int32)
    index = jnp.where(assigned, slot, -1).astype(jnp.int32)
    residue = jnp.asarray(problem.pod_active) & ~assigned
    stats = RelaxStats(
        eligible=jnp.sum(elig0).astype(jnp.int32),
        placed=jnp.sum(assigned).astype(jnp.int32),
        demoted=jnp.sum(elig0 & ~assigned).astype(jnp.int32),
        claims=jnp.sum(open_c).astype(jnp.int32),
    )
    return RelaxOut(
        state=state1, kind=kind, index=index, residue_active=residue, stats=stats
    )


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def _relax_place_jit(
    problem: SchedulingProblem, max_claims: int, bounds_free: bool, n_passes: int
) -> RelaxOut:
    problem = _pad_lanes_mult32(problem)
    return _relax_impl(problem, max_claims, bounds_free, n_passes)


def relax_place(
    problem: SchedulingProblem, max_claims: int, init: Optional[FFDState] = None
) -> RelaxOut:
    """Phase 1 of the two-phase solve (see module docstring). ``init`` must
    be None — relaxation only ever runs on a fresh solve; the signature
    matches the other entry points for the backend/aot dispatch plumbing."""
    assert init is None, "relaxation always starts a fresh solve"
    return _relax_place_jit(
        problem, int(max_claims), problem_bounds_free(problem), relax_passes()
    )
