"""The narrow per-pod FFD scan step and the plain one-pass scan entry.

One lax.scan step places one pod (scheduler.go:238-285 priority order);
see ops/ffd.py (facade) for the module map.

This step is the PARITY ANCHOR for every batched commit: the sweeps path's
chain commits (ffd_sweeps: waterfill, closed-form round, spread mini-sim —
batched over pod_eqprev_chain runs whose members may differ on the select
side), the round-8 wavefront lanes (ffd_sweeps._wave_extend: extra queue
heads committed per iteration under explicit independence proofs), and the
run solver's analytic commits must all be bit-identical to stepping pods one
at a time through THIS body. The randomized fuzz suites (test_solver_parity,
test_chain_parity, test_wavefront_parity) enforce that; gate changes must
land here first and in the batched paths second.
"""


import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax, vmap

from karpenter_tpu.models.problem import (
    HOSTNAME_KEY,
    ReqTensor,
    SchedulingProblem,
)
from karpenter_tpu.ops import masks
from karpenter_tpu.ops.topology_kernels import (
    PodTopoStatics,
    record,
    topo_gate,
)


from karpenter_tpu.ops.ffd_core import (  # noqa: F401
    FFDResult,
    FFDState,
    KIND_CLAIM,
    KIND_FAIL,
    KIND_NEW_CLAIM,
    KIND_NODE,
    KIND_NO_SLOT,
    _ABLATE,
    _BIG,
    _UNROLL,
    _first_true,
    _fresh_template_rows,
    _intersect_rows,
    _lane_align,
    _make_it_gate,
    _mix_req_rows,
    _offer_rows,
    _pad_lanes_mult32,
    _pod_xs,
    _row_sentinel_bounds,
    _statics,
    initial_state,
    problem_bounds_free,
)

def solve_ffd(
    problem: SchedulingProblem, max_claims: int, init: Optional[FFDState] = None
) -> FFDResult:
    """Run one pack pass. Shapes are static per bucket; XLA caches the
    compiled executable across batches. ``init`` carries bin + topology state
    between relax-and-retry passes (the queue requeue of scheduler.go:150-170).

    A fresh solve builds the initial state *inside* the jit: each eager
    device op outside a jit is a separate launch through the (possibly
    remote) TPU runtime, and initial_state's ~13 of them cost more than the
    whole small-batch scan."""
    bounds_free = problem_bounds_free(problem)
    if init is None:
        return _solve_ffd_fresh_jit(problem, max_claims, bounds_free)
    return _solve_ffd_jit(problem, init, bounds_free)


# carried calls donate ``init`` (see _solve_ffd_jit): the backend reports the
# carried bytes as reclaimed in the program registry's donated accounting
solve_ffd._donates_carry = True



def _make_step(problem: SchedulingProblem, statics, C: int):
    lv, ln = statics.lv, statics.ln
    wellknown, no_allow = statics.wellknown, statics.no_allow
    # static gate-diet switch (ops/ffd_core.problem_bounds_free): True picks
    # the fused bounds-free gate phases below; False is the pre-diet program
    bounds_free = statics.bounds_free
    N = problem.num_nodes
    T = problem.num_instance_types
    TPL = problem.num_templates
    K = problem.num_keys
    V = problem.num_lanes
    it_gate = _make_it_gate(problem, statics)

    def step(state: FFDState, pod):
        (
            pod_req,
            pod_strict,
            pod_requests,
            tol_tpl,
            tol_node,
            pod_ports,
            pod_conflict,
            grp_match,
            grp_selects,
            grp_owned,
            pod_vols,
            pod_is_active,
            pod_neg,
        ) = pod
        topo_pod = PodTopoStatics(
            strict_admitted=pod_strict.admitted,
            grp_match=grp_match,
            grp_selects=grp_selects,
            grp_owned=grp_owned,
        )
        # NOTE on lax.cond here: conditionals only pay off when branch
        # outputs are small — a cond whose identity branch passes [B, K, V]
        # requirement tensors through forces per-step copies that cost more
        # than the gate it skips (measured +0.15s on the 10k bench). So the
        # topo gates stay unconditional; only the template phase (small
        # row outputs) and record (two [G, V] outputs) are conditional.

        def gated(merged, allow, registered):
            return topo_gate(
                problem, state.grp_counts, registered, topo_pod, merged, allow,
                fuse=bounds_free,
            )

        # -- 1. existing nodes (scheduler.go:240-244; existingnode.go:64-124)
        if bounds_free and N == 0:
            # static empty-node-set skip: provisioning-from-scratch problems
            # carry zero-size node tensors, but the gates over them still
            # trace (and launch) ~a dozen kernels per step; elide the phase
            node_requests2 = state.node_requests
            node_final = state.node_req
            node_ok = jnp.zeros((0,), bool)
            node_pick = jnp.int32(0)
            any_node = jnp.bool_(False)
        else:
            node_requests2 = state.node_requests + pod_requests[None, :]
            node_fit = masks.fits(node_requests2, problem.node_avail)
            node_merged = _intersect_rows(state.node_req, pod_req, bounds_free)
            if bounds_free:
                # fused gate: compatible_ok re-derives the intersection we
                # already hold, so feed it the merged rows instead
                node_neg = vmap(
                    lambda r: masks.negative_polarity(r, lv, ln, True)
                )(state.node_req)
                node_compat = masks.compatible_from_merged(
                    masks.nonempty(node_merged, True),
                    state.node_req.defined,
                    node_neg,
                    pod_req.defined,
                    pod_neg,
                    no_allow,
                )
            else:
                node_compat = vmap(
                    lambda nr: masks.compatible_ok(nr, pod_req, lv, ln, no_allow)
                )(state.node_req)
            node_port_ok = ~jnp.any(state.node_used_ports & pod_conflict[None, :], axis=-1)
            # CSI attach limits gate existing nodes only (existingnode.go:100-106)
            node_vol_ok = jnp.all(
                state.node_vol_used + pod_vols[None, :] <= problem.node_vol_limits, axis=-1
            )
            node_topo_ok, node_final = gated(node_merged, no_allow, state.grp_registered)
            node_ok = tol_node & node_fit & node_compat & node_port_ok & node_vol_ok & node_topo_ok
            node_pick = _first_true(node_ok)
            any_node = jnp.any(node_ok)

        # -- 2. open claims, fewest pods first (scheduler.go:247-254)
        claim_merged = _intersect_rows(state.claim_req, pod_req, bounds_free)
        if bounds_free:
            claim_neg = vmap(
                lambda r: masks.negative_polarity(r, lv, ln, True)
            )(state.claim_req)
            claim_compat = masks.compatible_from_merged(
                masks.nonempty(claim_merged, True),
                state.claim_req.defined,
                claim_neg,
                pod_req.defined,
                pod_neg,
                wellknown,
            )
        else:
            claim_compat = vmap(
                lambda cr: masks.compatible_ok(cr, pod_req, lv, ln, wellknown)
            )(state.claim_req)
        if "ctopo" in _ABLATE:
            claim_topo_ok, claim_final = jnp.ones((C,), bool), claim_merged
        else:
            claim_topo_ok, claim_final = gated(
                claim_merged, wellknown, state.grp_registered
            )
        claim_requests2 = state.claim_requests + pod_requests[None, :]
        if "citgate" in _ABLATE:
            claim_it_ok2 = state.claim_it_ok
        else:
            claim_it_ok2 = it_gate(claim_final, claim_requests2, state.claim_it_ok)
        claim_port_ok = ~jnp.any(state.claim_used_ports & pod_conflict[None, :], axis=-1)
        claim_ok = (
            state.claim_open
            & tol_tpl[state.claim_tpl]
            & claim_port_ok
            & claim_compat
            & claim_topo_ok
            & jnp.any(claim_it_ok2, axis=-1)
        )
        claim_rank = jnp.where(claim_ok, state.claim_npods * C + jnp.arange(C), _BIG)
        claim_pick = jnp.argmin(claim_rank)
        if bounds_free:
            # ranks max out at npods*C + C << _BIG, so the min rank being a
            # real rank is exactly "some claim passed" — a 1-element gather
            # instead of another [C] reduction
            any_claim = claim_rank[claim_pick] < _BIG
        else:
            any_claim = jnp.any(claim_ok)

        # -- 3. fresh claim from templates, weight order (scheduler.go:256-283);
        # the prospective slot's hostname is minted before evaluation
        # (nodeclaim.go:46-63) and its lane registered for topology if opened.
        # The whole phase runs under lax.cond: it can only influence the
        # outcome when the node and claim phases both failed and a slot is
        # free, which on large packs is a small minority of steps (opens +
        # terminal failures).
        free_slot = _first_true(~state.claim_open)
        if bounds_free:
            # _first_true returns C when no slot is free — a scalar compare
            # replaces the [C] any-reduction
            has_slot = free_slot < C
        else:
            has_slot = jnp.any(~state.claim_open)
        # hostname minting is active only when the encoder allotted claim
        # hostname lanes (static shape decision)
        mint_hostnames = problem.claim_hostname_lane.shape[0] > 0
        need_tpl = (~any_node) & (~any_claim) & has_slot & pod_is_active

        def eval_tpl():
            tpl_requests2 = problem.tpl_overhead + pod_requests[None, :]
            tpl_merged, tpl_compat, host_onehot = _fresh_template_rows(
                problem,
                lv,
                ln,
                wellknown,
                pod_req,
                free_slot,
                bounds_free=bounds_free,
                tpl_neg=statics.tpl_neg,
                pod_neg=pod_neg,
            )
            # the new hostname is registered before the gate evaluates
            reg_for_tpl = state.grp_registered | (
                (problem.grp_key == HOSTNAME_KEY)[:, None] & host_onehot[None, :]
            )
            if "ttopo" in _ABLATE:
                tpl_topo_ok, tpl_final = jnp.ones((TPL,), bool), tpl_merged
            else:
                tpl_topo_ok, tpl_final = gated(tpl_merged, wellknown, reg_for_tpl)
            within_limits = masks.fits(
                problem.it_cap[None, :, :], state.remaining[:, None, :]
            )  # [TPL, T]
            if "titgate" in _ABLATE:
                tpl_it_ok2 = problem.tpl_it_ok & within_limits
            else:
                tpl_it_ok2 = it_gate(
                    tpl_final, tpl_requests2, problem.tpl_it_ok & within_limits
                )
            tpl_ok = tol_tpl & tpl_compat & tpl_topo_ok & jnp.any(tpl_it_ok2, axis=-1)
            tpl_pick = _first_true(tpl_ok)
            pick_c = jnp.minimum(tpl_pick, TPL - 1)
            if bounds_free:
                slot_req = _row_sentinel_bounds(tpl_final, pick_c)
            else:
                slot_req = tpl_final.row(pick_c)
            tpl_row_it_ok = tpl_it_ok2[pick_c]
            max_cap = jnp.max(
                jnp.where(tpl_row_it_ok[:, None], problem.it_cap, 0.0), axis=0
            )  # [R]
            return (
                jnp.any(tpl_ok),
                tpl_pick.astype(jnp.int32),
                slot_req,
                tpl_requests2[pick_c],
                tpl_row_it_ok,
                max_cap,
                host_onehot,
            )

        def skip_tpl():
            R = problem.tpl_overhead.shape[1]
            return (
                jnp.bool_(False),
                jnp.int32(0),
                ReqTensor(
                    admitted=jnp.zeros((K, V), bool),
                    comp=jnp.zeros((K,), bool),
                    gt=jnp.zeros((K,), jnp.int32),
                    lt=jnp.zeros((K,), jnp.int32),
                    defined=jnp.zeros((K,), bool),
                ),
                jnp.zeros((R,), problem.tpl_overhead.dtype),
                jnp.zeros((T,), bool),
                jnp.zeros((R,), problem.it_cap.dtype),
                jnp.zeros((V,), bool),
            )

        (
            any_tpl,
            tpl_pick,
            slot_req,
            tpl_row_requests,
            tpl_row_it_ok,
            max_cap,
            host_onehot,
        ) = lax.cond(need_tpl, eval_tpl, skip_tpl)

        # with every slot taken, free_slot clamps to slot 0 and the template
        # phase evaluated a USED hostname — its verdict is meaningless, so the
        # no-slot case must classify as KIND_NO_SLOT unconditionally (the
        # backend's doubled-slot retry then produces the true answer); mapping
        # it through any_tpl misread "slot 0's hostname is taken" as a
        # permanent KIND_FAIL and starved the slot-growth path
        kind = jnp.where(
            any_node,
            KIND_NODE,
            jnp.where(
                any_claim,
                KIND_CLAIM,
                jnp.where(
                    ~has_slot,
                    KIND_NO_SLOT,
                    jnp.where(any_tpl, KIND_NEW_CLAIM, KIND_FAIL),
                ),
            ),
        ).astype(jnp.int32)
        # masked-out rows (pod_active=False: padding, or a consolidation
        # variant's inert candidate pods) fail without touching state — all
        # one-hot commits below derive from kind
        kind = jnp.where(pod_is_active, kind, KIND_FAIL)

        # -- commit via one-hot masks
        node_hot = (jnp.arange(N) == node_pick) & (kind == KIND_NODE)
        claim_hot = (jnp.arange(C) == claim_pick) & (kind == KIND_CLAIM)
        slot_hot = (jnp.arange(C) == free_slot) & (kind == KIND_NEW_CLAIM)

        mix_req = functools.partial(_mix_req_rows, bounds_free=bounds_free)

        def gather_row(rows: ReqTensor, idx, cap) -> ReqTensor:
            return rows.row(jnp.minimum(idx, cap - 1))

        # node commit (existingnode.go:116-123)
        new_node_req = mix_req(state.node_req, node_final, node_hot)
        new_node_requests = jnp.where(node_hot[:, None], node_requests2, state.node_requests)
        new_node_npods = state.node_npods + node_hot.astype(jnp.int32)
        new_node_used_ports = state.node_used_ports | (node_hot[:, None] & pod_ports[None, :])
        new_node_vol_used = state.node_vol_used + node_hot[:, None].astype(jnp.int32) * pod_vols[None, :]

        # claim commit (nodeclaim.go:111-118); slot_req / tpl_row_* come from
        # the conditional template phase above
        new_claim_req = mix_req(
            mix_req(state.claim_req, claim_final, claim_hot),
            ReqTensor(
                admitted=jnp.broadcast_to(slot_req.admitted, (C, K, V)),
                comp=jnp.broadcast_to(slot_req.comp, (C, K)),
                gt=jnp.broadcast_to(slot_req.gt, (C, K)),
                lt=jnp.broadcast_to(slot_req.lt, (C, K)),
                defined=jnp.broadcast_to(slot_req.defined, (C, K)),
            ),
            slot_hot,
        )
        new_claim_requests = jnp.where(
            claim_hot[:, None],
            claim_requests2,
            jnp.where(slot_hot[:, None], tpl_row_requests[None, :], state.claim_requests),
        )
        new_claim_it_ok = jnp.where(
            claim_hot[:, None],
            claim_it_ok2,
            jnp.where(slot_hot[:, None], tpl_row_it_ok[None, :], state.claim_it_ok),
        )
        new_claim_open = state.claim_open | slot_hot
        new_claim_npods = state.claim_npods + claim_hot.astype(jnp.int32) + slot_hot.astype(jnp.int32)
        new_claim_tpl = jnp.where(slot_hot, tpl_pick.astype(jnp.int32), state.claim_tpl)
        new_claim_used_ports = state.claim_used_ports | (
            (claim_hot | slot_hot)[:, None] & pod_ports[None, :]
        )

        # opening a claim burns pessimistic headroom (subtractMax) and
        # registers its hostname lane for hostname topologies
        opened = kind == KIND_NEW_CLAIM
        opened_tpl_hot = (jnp.arange(TPL) == tpl_pick) & opened
        new_remaining = jnp.where(
            opened_tpl_hot[:, None], state.remaining - max_cap[None, :], state.remaining
        )
        new_registered = state.grp_registered | (
            opened
            & mint_hostnames
            & (problem.grp_key == HOSTNAME_KEY)[:, None]
            & host_onehot[None, :]
        )

        # topology record for the chosen bin (topology.go:125-148) — an
        # identity unless a placement happened AND some group selects or is
        # owned by this pod, so it runs under lax.cond (generic pods with
        # labels no selector matches skip it entirely)
        committed = (kind == KIND_NODE) | (kind == KIND_CLAIM) | (kind == KIND_NEW_CLAIM)
        should_record = committed & (
            jnp.any(topo_pod.grp_selects) | jnp.any(topo_pod.grp_owned)
        )

        def do_record():
            chosen_final = gather_row(node_final, node_pick, N) if N > 0 else None
            claim_row = gather_row(claim_final, claim_pick, C)
            slot_row = slot_req

            def pick_rows(a, b, cond):
                return jax.tree_util.tree_map(
                    lambda x, y: jnp.where(
                        jnp.reshape(cond, (1,) * x.ndim), x, y
                    ),
                    a,
                    b,
                )

            rec_row = pick_rows(claim_row, slot_row, kind == KIND_CLAIM)
            if N > 0:
                rec_row = pick_rows(chosen_final, rec_row, kind == KIND_NODE)
            rec_allow = jnp.where(kind == KIND_NODE, no_allow, wellknown)
            return record(
                problem,
                state.grp_counts,
                new_registered,
                topo_pod,
                rec_row,
                rec_allow,
                committed,
                lv,
                ln,
            )

        if "record" in _ABLATE:
            new_counts = state.grp_counts
        else:
            new_counts, new_registered = lax.cond(
                should_record, do_record, lambda: (state.grp_counts, new_registered)
            )

        index = jnp.where(
            kind == KIND_NODE,
            node_pick,
            jnp.where(kind == KIND_CLAIM, claim_pick, jnp.where(kind == KIND_NEW_CLAIM, free_slot, -1)),
        ).astype(jnp.int32)

        new_state = FFDState(
            claim_req=new_claim_req,
            claim_requests=new_claim_requests,
            claim_it_ok=new_claim_it_ok,
            claim_open=new_claim_open,
            claim_npods=new_claim_npods,
            claim_tpl=new_claim_tpl,
            claim_used_ports=new_claim_used_ports,
            node_req=new_node_req,
            node_requests=new_node_requests,
            node_npods=new_node_npods,
            node_used_ports=new_node_used_ports,
            node_vol_used=new_node_vol_used,
            remaining=new_remaining,
            grp_counts=new_counts,
            grp_registered=new_registered,
        )
        return new_state, (kind, index)

    return step


@functools.partial(jax.jit, static_argnums=(2,), donate_argnums=(1,))
def _solve_ffd_jit(
    problem: SchedulingProblem, init: FFDState, bounds_free: bool = False
) -> FFDResult:
    """Reference per-pod scan: one pod per step — the provisioning
    production default (faster than the run-compressed scan on diverse
    workloads, see solver/jax_backend.py) and the semantic anchor the
    run-compressed solver is fuzz-checked against.

    The carried state is donated: the relax-and-retry loop only ever reads
    the RESULT's state (the previous pass's landscape is dead the moment the
    next pass dispatches), so XLA reuses the claim/topology buffers in place
    across passes — see obs/programs.py donated-bytes accounting."""
    problem, init = _lane_align(problem, init)
    step = _make_step(
        problem, _statics(problem, bounds_free), init.claim_open.shape[0]
    )
    final_state, (kinds, indices) = lax.scan(
        step, init, _pod_xs(problem, bounds_free), unroll=_UNROLL
    )
    return FFDResult(kind=kinds, index=indices, state=final_state)


@functools.partial(jax.jit, static_argnums=(1, 2))
def _solve_ffd_fresh_jit(
    problem: SchedulingProblem, max_claims: int, bounds_free: bool = False
) -> FFDResult:
    """Fresh-state variant: initial_state is traced into the program so a
    first-pass solve is a single device launch."""
    problem = _pad_lanes_mult32(problem)
    return _solve_ffd_jit.__wrapped__(
        problem, initial_state(problem, max_claims), bounds_free
    )


# -- placement explainability (obs/explain.py): post-pass gate attribution ----
#
# A SEPARATE program from the solve, run only when KARPENTER_TPU_EXPLAIN is on
# and only over the pods the pack failed: it re-evaluates the narrow step's
# gate families against the FINAL FFDState. That is exact, not approximate —
# a terminal pass by definition made no commits (no progress, no relaxation),
# and state only mutates on commits, so the final state equals the state every
# failed pod was last evaluated against. _make_step is untouched; the solve
# program (and the census pin, tests/test_kernel_census.py) cannot move.

# pods per attribution launch: bounds the [B, C, T] / [B, TPL, T] gate
# intermediates while keeping shapes static (one compile per problem bucket)
_EXPLAIN_CHUNK = 32


def _make_attribution(problem: SchedulingProblem, statics, C: int, state: FFDState):
    """Per-pod gate-family attribution closure, vmapped by _attribute_jit.
    Mirrors _make_step's node/claim/template gate phases, but instead of
    picking a bin it reduces per-family fail predicates into the
    obs/explain.py wire words via masks.family_bitmask. Family bit order is
    obs/explain.FAM_*: resources, requirements, taints, host-ports, topology,
    claim-capacity, volume."""
    lv, ln = statics.lv, statics.ln
    wellknown, no_allow = statics.wellknown, statics.no_allow
    bounds_free = statics.bounds_free
    N = problem.num_nodes
    TPL = problem.num_templates

    def it_terms(state_rows, requests):
        """(compat&offer, fit) halves of _make_it_gate's product [B, T] —
        split so requirements-vs-resources attribution can see which half
        killed the last surviving instance type."""
        state_packed = masks.pack_lanes(state_rows.admitted)
        state_neg = vmap(
            lambda r: masks.negative_polarity(r, lv, ln, bounds_free)
        )(state_rows)
        compat = masks.packed_pairwise_compat(
            state_rows, state_packed, state_neg,
            problem.it_reqs, statics.it_packed, statics.it_neg, bounds_free,
        )
        offer = _offer_rows(problem, state_rows.admitted)
        fit = masks.fits(requests[:, None, :], problem.it_alloc[None, :, :])
        return compat & offer, fit

    # template-side capacity terms are pod-independent: hoisted out of vmap
    tpl_base0 = jnp.asarray(problem.tpl_it_ok)  # [TPL, T] static tpl x IT compat
    within_limits = masks.fits(
        problem.it_cap[None, :, :], state.remaining[:, None, :]
    )  # [TPL, T]
    tpl_cap_ok = tpl_base0 & within_limits
    tpl_has_base = jnp.any(tpl_base0, axis=-1)
    tpl_has_cap = jnp.any(tpl_cap_ok, axis=-1)
    tpl_fail_cap = tpl_has_base & ~tpl_has_cap  # nodepool limits ate the headroom

    def attr(pod):
        (
            pod_req,
            pod_strict,
            pod_requests,
            tol_tpl,
            tol_node,
            pod_ports,
            pod_conflict,
            grp_match,
            grp_selects,
            grp_owned,
            pod_vols,
            pod_is_active,
            pod_neg,
        ) = pod
        topo_pod = PodTopoStatics(
            strict_admitted=pod_strict.admitted,
            grp_match=grp_match,
            grp_selects=grp_selects,
            grp_owned=grp_owned,
        )

        def gated(merged, allow, registered):
            return topo_gate(
                problem, state.grp_counts, registered, topo_pod, merged, allow,
                fuse=bounds_free,
            )

        # -- node class (mirror of step phase 1)
        if N == 0:
            node_ubn = jnp.array([0, 1 << 7, 0], jnp.int32)
        else:
            node_requests2 = state.node_requests + pod_requests[None, :]
            node_fit = masks.fits(node_requests2, problem.node_avail)
            node_merged = _intersect_rows(state.node_req, pod_req, bounds_free)
            if bounds_free:
                node_neg = vmap(
                    lambda r: masks.negative_polarity(r, lv, ln, True)
                )(state.node_req)
                node_compat = masks.compatible_from_merged(
                    masks.nonempty(node_merged, True),
                    state.node_req.defined,
                    node_neg,
                    pod_req.defined,
                    pod_neg,
                    no_allow,
                )
            else:
                node_compat = vmap(
                    lambda nr: masks.compatible_ok(nr, pod_req, lv, ln, no_allow)
                )(state.node_req)
            node_port_ok = ~jnp.any(
                state.node_used_ports & pod_conflict[None, :], axis=-1
            )
            node_vol_ok = jnp.all(
                state.node_vol_used + pod_vols[None, :] <= problem.node_vol_limits,
                axis=-1,
            )
            node_topo_ok, _ = gated(node_merged, no_allow, state.grp_registered)
            zeros_n = jnp.zeros((N,), bool)
            node_ubn = masks.family_bitmask(
                jnp.stack([
                    ~node_fit,       # resources
                    ~node_compat,    # requirements
                    ~tol_node,       # taints
                    ~node_port_ok,   # host-ports
                    ~node_topo_ok,   # topology
                    zeros_n,         # claim-capacity (n/a on existing nodes)
                    ~node_vol_ok,    # volume
                ]),
                # padded node rows carry node_avail = -1 (padding.py); keep
                # them out of the candidate set so they don't pollute unions
                jnp.any(problem.node_avail >= 0, axis=-1),
            )

        # -- open-claim class (mirror of step phase 2)
        claim_merged = _intersect_rows(state.claim_req, pod_req, bounds_free)
        if bounds_free:
            claim_neg = vmap(
                lambda r: masks.negative_polarity(r, lv, ln, True)
            )(state.claim_req)
            claim_compat = masks.compatible_from_merged(
                masks.nonempty(claim_merged, True),
                state.claim_req.defined,
                claim_neg,
                pod_req.defined,
                pod_neg,
                wellknown,
            )
        else:
            claim_compat = vmap(
                lambda cr: masks.compatible_ok(cr, pod_req, lv, ln, wellknown)
            )(state.claim_req)
        claim_topo_ok, claim_final = gated(
            claim_merged, wellknown, state.grp_registered
        )
        claim_requests2 = state.claim_requests + pod_requests[None, :]
        it_co, it_fit = it_terms(claim_final, claim_requests2)
        claim_co = state.claim_it_ok & it_co
        claim_fit = claim_co & it_fit
        has_base = jnp.any(state.claim_it_ok, axis=-1)
        has_co = jnp.any(claim_co, axis=-1)
        has_fit = jnp.any(claim_fit, axis=-1)
        claim_port_ok = ~jnp.any(
            state.claim_used_ports & pod_conflict[None, :], axis=-1
        )
        zeros_c = jnp.zeros((C,), bool)
        claim_ubn = masks.family_bitmask(
            jnp.stack([
                (has_co & ~has_fit) | ~has_base,       # resources
                ~claim_compat | (has_base & ~has_co),  # requirements (incl offering)
                ~tol_tpl[state.claim_tpl],             # taints
                ~claim_port_ok,                        # host-ports
                ~claim_topo_ok,                        # topology
                zeros_c,                               # claim-capacity
                zeros_c,                               # volume
            ]),
            state.claim_open,
        )

        # -- fresh-template class (mirror of step phase 3, same minted slot)
        free_slot = _first_true(~state.claim_open)
        tpl_requests2 = problem.tpl_overhead + pod_requests[None, :]
        tpl_merged, tpl_compat, host_onehot = _fresh_template_rows(
            problem, lv, ln, wellknown, pod_req, free_slot,
            bounds_free=bounds_free, tpl_neg=statics.tpl_neg, pod_neg=pod_neg,
        )
        reg_for_tpl = state.grp_registered | (
            (problem.grp_key == HOSTNAME_KEY)[:, None] & host_onehot[None, :]
        )
        tpl_topo_ok, tpl_final = gated(tpl_merged, wellknown, reg_for_tpl)
        it_co_t, it_fit_t = it_terms(tpl_final, tpl_requests2)
        tpl_co = tpl_cap_ok & it_co_t
        tpl_fit = tpl_co & it_fit_t
        has_co_t = jnp.any(tpl_co, axis=-1)
        has_fit_t = jnp.any(tpl_fit, axis=-1)
        zeros_t = jnp.zeros((TPL,), bool)
        tpl_ubn = masks.family_bitmask(
            jnp.stack([
                has_co_t & ~has_fit_t,                               # resources
                ~tpl_compat | ~tpl_has_base | (tpl_has_cap & ~has_co_t),  # requirements
                ~tol_tpl,                                            # taints
                zeros_t,                                             # host-ports
                ~tpl_topo_ok,                                        # topology
                tpl_fail_cap,                                        # claim-capacity
                zeros_t,                                             # volume
            ]),
            # padded template rows have tpl_it_ok all-False (padding.py)
            jnp.any(problem.tpl_it_ok, axis=-1),
        )

        # one int32 triple per pod: class bytes packed node | claim<<8 | tpl<<16
        return node_ubn + claim_ubn * 256 + tpl_ubn * 65536

    return attr


@functools.partial(jax.jit, static_argnums=(3,))
def _attribute_jit(problem, state, rows, bounds_free):
    statics = _statics(problem, bounds_free)
    C = state.claim_open.shape[0]
    xs = _pod_xs(problem, bounds_free)
    sel = jax.tree_util.tree_map(lambda a: a[rows], xs)
    return vmap(_make_attribution(problem, statics, C, state))(sel)


def attribute_pods(problem: SchedulingProblem, state: FFDState, rows):
    """int32[B, 3] explain words (union, blockers, near — obs/explain.py wire
    format) for the pod rows ``rows``, evaluated against the final ``state``.
    Host entry: chunks the rows so the [chunk, C, T] gate intermediates stay
    bounded, pads the tail chunk (shape-static programs), returns numpy."""
    import numpy as np

    rows = np.asarray(rows, dtype=np.int32)
    if rows.size == 0:
        return np.zeros((0, 3), np.int32)
    bounds_free = problem_bounds_free(problem)
    problem, state = _lane_align(problem, state)
    out = []
    for i in range(0, len(rows), _EXPLAIN_CHUNK):
        chunk = rows[i : i + _EXPLAIN_CHUNK]
        pad = _EXPLAIN_CHUNK - len(chunk)
        padded = np.pad(chunk, (0, pad), constant_values=chunk[-1])
        words = _attribute_jit(problem, state, jnp.asarray(padded), bounds_free)
        out.append(np.asarray(words)[: len(chunk)])
    return np.concatenate(out, axis=0)
