"""Run-compressed FFD: one scan step commits a RUN of identical pods

via closed-form waterfill over claims/domains. Fuzz-checked against the
per-pod scan (tests/test_runs_solver.py); see ops/ffd.py for the map.
"""


import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax, vmap

from karpenter_tpu.models.problem import (
    HOSTNAME_KEY,
    ReqTensor,
    SchedulingProblem,
)
from karpenter_tpu.ops import masks
from karpenter_tpu.ops.topology_kernels import (
    record,
)


from karpenter_tpu.ops.ffd_core import (  # noqa: F401
    FFDResult,
    FFDState,
    KIND_CLAIM,
    KIND_FAIL,
    KIND_NEW_CLAIM,
    KIND_NODE,
    KIND_NO_SLOT,
    _BIG_CAP,
    _UNROLL,
    _capacity,
    _first_true,
    _fresh_template_rows,
    _intersect_rows,
    _lane_align,
    _mint_host_onehot,
    _mix_req_rows,
    _offer_rows,
    _pad_lanes_mult32,
    _pin_hostname,
    _pod_xs,
    _statics,
    _water_level,
    initial_state,
)
from karpenter_tpu.ops.ffd_step import _make_step  # noqa: F401

def _make_run_commit(problem: SchedulingProblem, statics, C: int, max_run: int):
    """The analytic multi-pod commit: one scan step places an entire run of
    identical, topology-inert pods, reproducing the per-pod step's outcome
    (including each pod's (kind, index) in temporal order) in closed form.

    Correctness argument, phase by phase (all against _make_step's semantics):
      nodes   — a pod takes the FIRST node that passes the static gates with
                room, so k pods fill nodes in index order up to each node's
                integer capacity: cumsum fill. Narrowing commits are
                idempotent for identical pods.
      claims  — a pod takes the open claim with the FEWEST pods (index
                tie-break), i.e. pods waterfill claim levels bounded by each
                claim's capacity (max over surviving instance types of how
                many more such pods fit). The temporal order of assignments
                is (level-before, claim index) lexicographic — recovered per
                ordinal to keep exact per-pod parity with the oracle.
      opens   — pods that exhaust claim capacity open fresh template claims
                one at a time; each opened claim absorbs pods up to its own
                capacity before the next opens (it is the unique unsaturated
                claim), so openings assign consecutive ordinal blocks in
                slot order. Limit headroom burns once per open (subtractMax,
                scheduler.go:347-364).
    """
    # the run commits stay on the legacy (non-dieted) gate kernels; they
    # consume only the first six statics fields
    lv, ln, wellknown, no_allow, it_packed, it_neg = statics[:6]
    N = problem.num_nodes
    T = problem.num_instance_types
    TPL = problem.num_templates
    K = problem.num_keys
    V = problem.num_lanes
    D = problem.pod_vol_counts.shape[1]
    mint_hostnames = problem.claim_hostname_lane.shape[0] > 0

    def has_offering_rows(admitted):
        return _offer_rows(problem, admitted)

    def commit(state: FFDState, pod, start, length, active_arr):
        (
            pod_req,
            _pod_strict,
            pod_requests,
            tol_tpl,
            tol_node,
            pod_ports,
            pod_conflict,
            _gm,
            _gs,
            _go,
            pod_vols,
            _pa,
            _pod_neg,
        ) = pod
        win = jnp.arange(max_run)
        act = lax.dynamic_slice(active_arr, (start,), (max_run,)) & (win < length)
        k = act.sum().astype(jnp.int32)
        ordinal = (jnp.cumsum(act) - 1).astype(jnp.int32)  # [MR]
        port_cap = jnp.where(jnp.any(pod_ports), 1, _BIG_CAP).astype(jnp.int32)

        # ---- 1. existing nodes: first-fit fill in node order
        if N > 0:
            node_merged = _intersect_rows(state.node_req, pod_req)
            node_compat = vmap(
                lambda nr: masks.compatible_ok(nr, pod_req, lv, ln, no_allow)
            )(state.node_req)
            node_port_ok = ~jnp.any(state.node_used_ports & pod_conflict[None, :], axis=-1)
            if D > 0:
                # clamp: pre-existing over-limit attach counts read as 0
                # capacity, not negative (the per-pod gate simply fails)
                vol_room = jnp.maximum(
                    (problem.node_vol_limits - state.node_vol_used)
                    // jnp.maximum(pod_vols[None, :], 1),
                    0,
                )
                vol_cap = jnp.min(
                    jnp.where(pod_vols[None, :] > 0, vol_room, _BIG_CAP), axis=-1
                ).astype(jnp.int32)
            else:
                vol_cap = jnp.full((N,), _BIG_CAP, jnp.int32)
            res_cap = _capacity(
                problem.node_avail, state.node_requests, pod_requests[None, :]
            )
            node_ok = tol_node & node_compat & node_port_ok
            ncap = jnp.where(node_ok, jnp.minimum(jnp.minimum(res_cap, vol_cap), port_cap), 0)
            ncum = jnp.cumsum(ncap)
            placed_n = jnp.minimum(k, ncum[-1])
            node_take = jnp.clip(k - (ncum - ncap), 0, ncap)
            took_n = node_take > 0
            new_node_req = _mix_req_rows(state.node_req, node_merged, took_n)
            new_node_requests = state.node_requests + node_take[:, None] * pod_requests[None, :]
            new_node_npods = state.node_npods + node_take
            new_node_ports = state.node_used_ports | (took_n[:, None] & pod_ports[None, :])
            new_node_vol = state.node_vol_used + node_take[:, None] * pod_vols[None, :]
            node_of = jnp.searchsorted(ncum, ordinal, side="right").astype(jnp.int32)
        else:
            placed_n = jnp.int32(0)
            node_of = jnp.zeros((max_run,), jnp.int32)
            new_node_req = state.node_req
            new_node_requests = state.node_requests
            new_node_npods = state.node_npods
            new_node_ports = state.node_used_ports
            new_node_vol = state.node_vol_used
        rem = k - placed_n

        # ---- 2. open claims: fewest-pods waterfill bounded by capacity
        claim_merged = _intersect_rows(state.claim_req, pod_req)
        claim_compat = vmap(
            lambda cr: masks.compatible_ok(cr, pod_req, lv, ln, wellknown)
        )(state.claim_req)
        claim_port_ok = ~jnp.any(state.claim_used_ports & pod_conflict[None, :], axis=-1)
        m_packed = masks.pack_lanes(claim_merged.admitted)
        m_neg = vmap(lambda r: masks.negative_polarity(r, lv, ln))(claim_merged)
        itc = masks.packed_pairwise_compat(
            claim_merged, m_packed, m_neg, problem.it_reqs, it_packed, it_neg
        )  # [C, T]
        itok = state.claim_it_ok & itc & has_offering_rows(claim_merged.admitted)
        cap_ct = _capacity(
            problem.it_alloc[None, :, :],
            state.claim_requests[:, None, :],
            pod_requests[None, None, :],
        )  # [C, T]
        cap_c = jnp.max(jnp.where(itok, cap_ct, 0), axis=-1)
        elig = (
            state.claim_open
            & tol_tpl[state.claim_tpl]
            & claim_compat
            & claim_port_ok
        )
        cap_c = jnp.where(elig, jnp.minimum(cap_c, port_cap), 0)
        p_lvl = state.claim_npods
        m = jnp.minimum(rem, cap_c.sum())
        L = _water_level(p_lvl, cap_c, m)
        take0 = jnp.clip(L - p_lvl, 0, cap_c)
        leftover = m - take0.sum()
        at_level = (p_lvl + take0 == L) & (take0 < cap_c)
        extra = at_level & (jnp.cumsum(at_level) <= leftover)
        claim_take = take0 + extra.astype(jnp.int32)
        tookc = claim_take > 0
        i_claim_req = _mix_req_rows(state.claim_req, claim_merged, tookc)
        i_requests = state.claim_requests + claim_take[:, None] * pod_requests[None, :]
        i_npods = state.claim_npods + claim_take
        i_itok = jnp.where(tookc[:, None], itok & (cap_ct >= claim_take[:, None]), state.claim_it_ok)
        i_ports = state.claim_used_ports | (tookc[:, None] & pod_ports[None, :])
        rem2 = rem - claim_take.sum()

        # temporal ordinal -> claim: assignments sort by (level-before, claim)
        jj = ordinal - placed_n
        lev = _water_level(p_lvl, claim_take, jnp.maximum(jj, 0))
        before = jnp.sum(
            jnp.clip(lev[:, None] - p_lvl[None, :], 0, claim_take[None, :]), axis=-1
        )
        pos = jnp.maximum(jj, 0) - before
        at_lev = (p_lvl[None, :] <= lev[:, None]) & (
            lev[:, None] < (p_lvl + claim_take)[None, :]
        )  # [MR, C]
        lev_cum = jnp.cumsum(at_lev, axis=-1)
        claim_of = jnp.argmax(at_lev & (lev_cum == (pos + 1)[:, None]), axis=-1).astype(
            jnp.int32
        )

        # ---- 3. fresh template claims, one open at a time. The heavy
        # template-side products are loop-invariant and hoisted out of the
        # open-loop: the merged rows, compat mask, [TPL, T] pairwise
        # instance-type compat, offerings, and per-pod capacities depend only
        # on (pod_req, pod_requests) — the minted-hostname pin (the one
        # free_slot-dependent piece of _fresh_template_rows) cannot change
        # them because instance types never constrain the hostname key (the
        # claim mints a fresh name precisely because nothing else names it,
        # nodeclaim.go:46-63); only the committed slot row must carry the pin
        tpl_merged_u = _intersect_rows(problem.tpl_reqs, pod_req)
        tpl_compat = vmap(
            lambda tr: masks.compatible_ok(tr, pod_req, lv, ln, wellknown)
        )(problem.tpl_reqs)
        t_packed = masks.pack_lanes(tpl_merged_u.admitted)
        t_neg = vmap(lambda r: masks.negative_polarity(r, lv, ln))(tpl_merged_u)
        itc_t = masks.packed_pairwise_compat(
            tpl_merged_u, t_packed, t_neg, problem.it_reqs, it_packed, it_neg
        )  # [TPL, T]
        cap_tt = _capacity(
            problem.it_alloc[None, :, :],
            problem.tpl_overhead[:, None, :],
            pod_requests[None, None, :],
        )  # [TPL, T]
        itok_t_static = (
            problem.tpl_it_ok
            & itc_t
            & has_offering_rows(tpl_merged_u.admitted)
            & (cap_tt >= 1)
        )

        def nc_cond(c):
            return c[0] & (c[1] > 0)

        def nc_body(c):
            (
                _keep,
                c_rem,
                c_req,
                c_requests,
                c_itok,
                c_open,
                c_npods,
                c_tpl,
                c_ports,
                c_remaining,
                c_registered,
                c_newtake,
                c_noslot,
            ) = c
            free_slot = _first_true(~c_open)
            has_slot = jnp.any(~c_open)
            host_onehot = _mint_host_onehot(problem, free_slot)
            within = masks.fits(problem.it_cap[None, :, :], c_remaining[:, None, :])
            itok_t = itok_t_static & within
            q_t = jnp.max(jnp.where(itok_t, cap_tt, 0), axis=-1)  # [TPL]
            tpl_ok = tol_tpl & tpl_compat & (q_t >= 1)
            pick = _first_true(tpl_ok)
            any_tpl = jnp.any(tpl_ok)
            pick_c = jnp.minimum(pick, TPL - 1)
            can = any_tpl & has_slot
            take = jnp.where(can, jnp.minimum(c_rem, jnp.minimum(q_t[pick_c], port_cap)), 0)
            slot_hot = (jnp.arange(C) == free_slot) & (take > 0)
            slot_req_u = tpl_merged_u.row(pick_c)
            # the committed claim row carries its minted hostname
            # (nodeclaim.go:46-63), exactly as _fresh_template_rows pins it
            slot_req = (
                _pin_hostname(slot_req_u, host_onehot) if mint_hostnames else slot_req_u
            )
            new_req = _mix_req_rows(
                c_req,
                ReqTensor(
                    admitted=jnp.broadcast_to(slot_req.admitted, (C, K, V)),
                    comp=jnp.broadcast_to(slot_req.comp, (C, K)),
                    gt=jnp.broadcast_to(slot_req.gt, (C, K)),
                    lt=jnp.broadcast_to(slot_req.lt, (C, K)),
                    defined=jnp.broadcast_to(slot_req.defined, (C, K)),
                ),
                slot_hot,
            )
            surv1 = itok_t[pick_c]  # [T] survivors with the first pod aboard
            new_itok = jnp.where(
                slot_hot[:, None], surv1[None, :] & (cap_tt[pick_c][None, :] >= take), c_itok
            )
            new_requests = jnp.where(
                slot_hot[:, None],
                (problem.tpl_overhead[pick_c] + take * pod_requests)[None, :],
                c_requests,
            )
            opened = take > 0
            opened_tpl_hot = (jnp.arange(TPL) == pick_c) & opened
            max_cap = jnp.max(jnp.where(surv1[:, None], problem.it_cap, 0.0), axis=0)
            new_remaining = jnp.where(
                opened_tpl_hot[:, None], c_remaining - max_cap[None, :], c_remaining
            )
            new_registered = c_registered | (
                opened
                & mint_hostnames
                & (problem.grp_key == HOSTNAME_KEY)[:, None]
                & host_onehot[None, :]
            )
            return (
                can,
                c_rem - take,
                new_req,
                new_requests,
                new_itok,
                c_open | slot_hot,
                c_npods + slot_hot * take,
                jnp.where(slot_hot, pick_c.astype(jnp.int32), c_tpl),
                c_ports | (slot_hot[:, None] & pod_ports[None, :]),
                new_remaining,
                new_registered,
                c_newtake + slot_hot * take,
                # ~has_slot alone: with no free slot the template verdict is
                # unreliable (see the step's kind classification) — always
                # signal NO_SLOT so the backend's slot-growth retry decides
                c_noslot | ~has_slot,
            )

        nc0 = (
            jnp.bool_(True),
            rem2,
            i_claim_req,
            i_requests,
            i_itok,
            state.claim_open,
            i_npods,
            state.claim_tpl,
            i_ports,
            state.remaining,
            state.grp_registered,
            jnp.zeros((C,), jnp.int32),
            jnp.bool_(False),
        )
        (
            _keep,
            rem3,
            f_claim_req,
            f_requests,
            f_itok,
            f_open,
            f_npods,
            f_tpl,
            f_ports,
            f_remaining,
            f_registered,
            new_take,
            noslot,
        ) = lax.while_loop(nc_cond, nc_body, nc0)
        placed_new = rem2 - rem3
        new_cum = jnp.cumsum(new_take)  # slot order == temporal opening order
        nc_ord = ordinal - placed_n - m  # ordinal within the new-claim phase
        newclaim_of = jnp.searchsorted(new_cum, nc_ord, side="right").astype(jnp.int32)
        # the pod that OPENS a slot reads KIND_NEW_CLAIM, later joiners
        # KIND_CLAIM — matching the per-pod step's labels exactly
        opens_slot = nc_ord == (new_cum - new_take)[jnp.minimum(newclaim_of, C - 1)]

        # ---- 4. per-row outputs, written into the run's queue window
        fail_kind = jnp.where(noslot, KIND_NO_SLOT, KIND_FAIL).astype(jnp.int32)
        kind_row = jnp.where(
            ~act,
            KIND_FAIL,
            jnp.where(
                ordinal < placed_n,
                KIND_NODE,
                jnp.where(
                    ordinal < placed_n + m,
                    KIND_CLAIM,
                    jnp.where(
                        ordinal < placed_n + m + placed_new,
                        jnp.where(opens_slot, KIND_NEW_CLAIM, KIND_CLAIM),
                        fail_kind,
                    ),
                ),
            ),
        ).astype(jnp.int32)
        # index by PHASE (new-phase joiners are labeled KIND_CLAIM but their
        # slot comes from the opening partition, not the waterfill)
        index_row = jnp.where(
            ~act,
            -1,
            jnp.where(
                ordinal < placed_n,
                node_of,
                jnp.where(
                    ordinal < placed_n + m,
                    claim_of,
                    jnp.where(ordinal < placed_n + m + placed_new, newclaim_of, -1),
                ),
            ),
        ).astype(jnp.int32)

        # ---- 5. record aggregation (Topology.Record, topology.go:125-148).
        # Run members are topology-BLIND (no matched/owned groups — run mode
        # rule in solver/encode.py) but may still be SELECTED by other pods'
        # groups; each placed member records its select mask against the
        # dom-lanes of the bin it landed on. Deltas never feed back into any
        # member's own gates, so they sum: member-per-bin counts contract
        # against per-bin dom masks. Identical to applying record() per pod.
        G = problem.grp_key.shape[0]
        new_counts = state.grp_counts
        if G > 0:
            sel_arr = jnp.concatenate(
                [jnp.asarray(problem.pod_grp_selects), jnp.zeros((max_run, G), bool)]
            )
            sel = lax.dynamic_slice(sel_arr, (start, 0), (max_run, G))  # [MR, G]
            placed_row = kind_row < KIND_FAIL
            B = N + C
            bin_of = jnp.where(kind_row == KIND_NODE, index_row, N + index_row)
            ob = placed_row[:, None] & (
                jnp.clip(bin_of, 0, B - 1)[:, None] == jnp.arange(B)[None, :]
            )  # [MR, B]
            cnt_bg = jnp.matmul(
                ob.astype(jnp.float32).T,
                sel.astype(jnp.float32),
                preferred_element_type=jnp.float32,
            )  # [B, G]
            if N > 0:
                radm = jnp.concatenate(
                    [new_node_req.admitted, f_claim_req.admitted], axis=0
                )
                rcomp = jnp.concatenate([new_node_req.comp, f_claim_req.comp], axis=0)
            else:
                radm, rcomp = f_claim_req.admitted, f_claim_req.comp
            dom = radm[:, problem.grp_key, :]  # [B, G, V]
            concrete = ~rcomp[:, problem.grp_key]  # [B, G]
            single = dom.sum(axis=-1) == 1
            spread_or_aff = (problem.grp_type == 0) | (problem.grp_type == 1)
            F = problem.grp_filter_valid.shape[1]
            if F > 0:
                if N > 0:
                    bin_rows = ReqTensor(
                        admitted=radm,
                        comp=rcomp,
                        gt=jnp.concatenate([new_node_req.gt, f_claim_req.gt], axis=0),
                        lt=jnp.concatenate([new_node_req.lt, f_claim_req.lt], axis=0),
                        defined=jnp.concatenate(
                            [new_node_req.defined, f_claim_req.defined], axis=0
                        ),
                    )
                    allow_b = jnp.concatenate(
                        [
                            jnp.zeros((N, no_allow.shape[0]), bool),
                            jnp.broadcast_to(wellknown, (C, wellknown.shape[0])),
                        ]
                    )
                else:
                    bin_rows = f_claim_req
                    allow_b = jnp.broadcast_to(wellknown, (C, wellknown.shape[0]))

                def bin_filt(row, allow):
                    def grp_filt(g):
                        terms = problem.grp_filter.row(g)
                        term_ok = vmap(
                            lambda t: masks.compatible_ok(row, t, lv, ln, allow)
                        )(terms)
                        return ~problem.grp_has_filter[g] | jnp.any(
                            problem.grp_filter_valid[g] & term_ok
                        )

                    return vmap(grp_filt)(jnp.arange(G))

                filt = vmap(bin_filt)(bin_rows, allow_b)  # [B, G]
            else:
                filt = jnp.ones((B, G), bool)
            dom_ok = (
                concrete
                & jnp.where(spread_or_aff[None, :], single, True)
                & filt
                & ~problem.grp_inverse[None, :]
            )
            dom_final = dom & dom_ok[:, :, None]  # [B, G, V]
            recorded = jnp.einsum(
                "bg,bgv->gv",
                cnt_bg,
                dom_final.astype(jnp.float32),
                preferred_element_type=jnp.float32,
            )
            new_counts = state.grp_counts + jnp.round(recorded).astype(jnp.int32)
            f_registered = f_registered | jnp.any(
                (cnt_bg[:, :, None] > 0.5) & dom_final, axis=0
            )

        new_state = FFDState(
            claim_req=f_claim_req,
            claim_requests=f_requests,
            claim_it_ok=f_itok,
            claim_open=f_open,
            claim_npods=f_npods,
            claim_tpl=f_tpl,
            claim_used_ports=f_ports,
            node_req=new_node_req,
            node_requests=new_node_requests,
            node_npods=new_node_npods,
            node_used_ports=new_node_ports,
            node_vol_used=new_node_vol,
            remaining=f_remaining,
            grp_counts=new_counts,
            grp_registered=f_registered,
        )
        return new_state, (kind_row, index_row)

    return commit


@functools.partial(jax.jit, static_argnums=(2, 3))
def _solve_ffd_runs_jit(
    problem: SchedulingProblem, init: FFDState, max_run: int, with_topo: bool
) -> FFDResult:
    """Run-compressed scan: one step per run of identical pods (encode.py
    segmentation). Topology-inert runs take the closed-form analytic commit,
    topology-interacting runs the light inner loop (ops/topo_runs.py), and
    length-1 runs the per-pod step. 10k diverse pods collapse to a few
    hundred steps. ``with_topo=False`` compiles the two-branch program —
    topology-free batches (the whole consolidation path) skip the topo
    branch's compile cost."""
    from karpenter_tpu.ops.topo_runs import make_topo_run_commit

    problem, init = _lane_align(problem, init)
    C = init.claim_open.shape[0]
    statics = _statics(problem)
    step = _make_step(problem, statics, C)
    commit = _make_run_commit(problem, statics, C, max_run)
    topo_commit = make_topo_run_commit(problem, statics, C, max_run) if with_topo else None
    P = problem.num_pods
    pods_xs = _pod_xs(problem)
    rep_xs = jax.tree_util.tree_map(lambda a: a[problem.run_start], pods_xs)
    # scratch tail so a window starting near P never clamps backwards
    active_arr = jnp.concatenate(
        [jnp.asarray(problem.pod_active), jnp.zeros((max_run,), dtype=bool)]
    )

    def outer(state, xs):
        rep, start, length, mode = xs

        def single(_):
            new_state, (kind, index) = step(state, rep)
            kind_row = jnp.full((max_run,), KIND_FAIL, jnp.int32).at[0].set(kind)
            index_row = jnp.full((max_run,), -1, jnp.int32).at[0].set(index)
            return new_state, (kind_row, index_row)

        def analytic(_):
            return commit(state, rep, start, length, active_arr)

        if with_topo:
            def topo(_):
                return topo_commit(state, rep, start, length, active_arr)

            return lax.switch(mode, (single, analytic, topo), None)
        return lax.switch(mode, (single, analytic), None)

    run_start = jnp.asarray(problem.run_start)
    run_len = jnp.asarray(problem.run_len)
    final_state, (kind_ys, index_ys) = lax.scan(
        outer,
        init,
        (rep_xs, run_start, run_len, jnp.asarray(problem.run_mode)),
        unroll=_UNROLL,
    )
    # scatter the per-run windows back into queue order; rows no run covers
    # (padding pods) keep KIND_FAIL. Windows are disjoint, so the masked
    # scatter writes each real row exactly once.
    RN = run_start.shape[0]
    win = jnp.arange(max_run)
    rows = run_start[:, None] + win[None, :]  # [RN, MR]
    valid = win[None, :] < run_len[:, None]
    target = jnp.where(valid, rows, P + max_run - 1)  # dump padding in scratch
    kinds = (
        jnp.full((P + max_run,), KIND_FAIL, jnp.int32)
        .at[target.ravel()]
        .set(kind_ys.ravel())
    )
    idxs = (
        jnp.full((P + max_run,), -1, jnp.int32).at[target.ravel()].set(index_ys.ravel())
    )
    return FFDResult(kind=kinds[:P], index=idxs[:P], state=final_state)


def max_run_bucket(problem: SchedulingProblem) -> int:
    """Static max-run window bucket for a (possibly stacked) problem —
    single definition shared with parallel/mesh.py."""
    import numpy as np

    from karpenter_tpu.ops.padding import pow2_bucket

    return pow2_bucket(int(np.max(np.asarray(problem.run_len), initial=1)), lo=1)


def has_topo_runs(problem: SchedulingProblem) -> bool:
    """Whether any run needs the topology inner-loop commit. MUST be threaded
    into _solve_ffd_runs_jit's static with_topo: lax.switch clamps an
    out-of-range mode index, so a RUN_TOPO run fed to the two-branch program
    silently takes the topology-ignoring analytic commit (the round-2
    21/64-seed parity regression)."""
    import numpy as np

    from karpenter_tpu.models.problem import RUN_TOPO

    return bool(np.any(np.asarray(problem.run_mode) == RUN_TOPO))


def solve_ffd_runs(
    problem: SchedulingProblem, max_claims: int, init: Optional[FFDState] = None
) -> FFDResult:
    """Run one pack pass through the run-compressed solver."""
    if init is None:
        return _solve_ffd_runs_fresh_jit(
            problem, max_claims, max_run_bucket(problem), has_topo_runs(problem)
        )
    return _solve_ffd_runs_jit(
        problem, init, max_run_bucket(problem), has_topo_runs(problem)
    )


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def _solve_ffd_runs_fresh_jit(
    problem: SchedulingProblem, max_claims: int, max_run: int, with_topo: bool
) -> FFDResult:
    """Fresh-state runs variant: initial_state traced into the program (one
    launch per solve; see _solve_ffd_fresh_jit)."""
    init = initial_state(_pad_lanes_mult32(problem), max_claims)
    return _solve_ffd_runs_jit(problem, init, max_run, with_topo)
