from karpenter_tpu.ops import masks  # noqa: F401
from karpenter_tpu.ops.ffd import solve_ffd, FFDResult  # noqa: F401
