"""Convex-relaxation phase-1 placement: projected gradient over the
fractional pod x bin assignment polytope (KARPENTER_TPU_RELAX2, round 22).

The round-15 waterfill (ops/relax.py) places each eligible pod at its
prefix-sum level in one shot — no feedback between the assignment and the
per-bin load, so heterogeneous sizes overshoot bins and the rounding ladder
demotes the overflow to the sequential repair loop. This module replaces
the assignment math with a real first-order convex solve (CvxCluster's
relaxation recipe, PAPERS.md):

  variables   X[p, j] — fractional assignment of pod p to the j-th bin of
              its group's slot window (the same bin-groups, template pick,
              and normalized scalar demand w_p as the waterfill, via the
              shared ops/relax_common.plan_groups);
  polytope    row simplex  sum_j X[p, j] <= 1, X >= 0  (a pod places at
              most once), bin capacity  load_c = sum_p w_p X[p, c] <= 1
              handled by penalty;
  objective   minimize  sum w_p X[p,c] (price_c - 1) + (rho/2) sum_c
              max(0, load_c - 1)^2 — placed mass is rewarded, a linear
              within-group bin price (gamma * bin index + beta * distance
              from the pod's waterfill bin) biases mass into early bins
              and breaks the symmetry of identical pods, and the quadratic
              term prices capacity violations.

The solve is a fixed-trip-count jitted ``lax.scan``: each trip is one
projected-gradient step — scatter the bin loads, form the gradient, clip
to [0, 1], and radially rescale rows whose mass exceeds 1 (a cheap
feasible map onto the simplex, not the exact Euclidean projection; exact
projection needs a per-row sort and buys nothing because the rounding and
the real instance-type gate re-check everything). The support of X is a
static window of ``_WINDOW`` bins centered on the pod's waterfill bin, so
memory is O(P * W), not O(P * C) — the gradient flow only ever needs to
push a pod a few bins off its warm start to smooth overloads.

Rounding is deterministic and jitted: each pod's largest fraction names
its bin, pods sort by (bin, -fraction), and a segmented prefix sum admits
pods while the bin's scalar load stays <= 1 (largest-fraction-first with
capacity bookkeeping). The admitted assignment then goes through the SAME
real-gate rounding ladder and FFDState commit as the waterfill
(relax_common.commit_assignment), and the residue rides the carried
sweeps repair unchanged.

Correctness is the round-15 contract, unchanged: every relax2 result is
full-gated before the backend returns it (a relax2 bug costs latency,
never correctness), and flag off nothing here is ever imported on the
solve path. Classified standdowns (STANDDOWN_REASONS) ride the round-15
counter: solver_relax_fallback_total{reason}."""

import functools
import os
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from karpenter_tpu.models.problem import SchedulingProblem
from karpenter_tpu.ops.ffd_core import (
    FFDState,
    _pad_lanes_mult32,
    _statics,
    problem_bounds_free,
)
from karpenter_tpu.ops.relax import RelaxOut, relax_passes
from karpenter_tpu.ops.relax_common import (
    commit_assignment,
    eligibility,
    plan_groups,
    relax_applicable,
)
from karpenter_tpu.ops.topology_kernels import TYPE_ANTI_AFFINITY

__all__ = [
    "Relax2Stats",
    "STANDDOWN_REASONS",
    "classify_ineligible",
    "converged",
    "enabled",
    "pgd_iters",
    "pgd_step",
    "pgd_tol",
    "relax2_place",
    "relax_applicable",
]

# Bounded standdown vocabulary (solver_relax_fallback_total{reason}; the
# bare "gate-rejected" covers BOTH phase-1 solvers' validator fallbacks):
#   finite-pool       nodepool limits — relax_applicable false, no dispatch
#   ports / topology  nothing eligible, dominant blocker named
#   no-eligible       nothing eligible, no single dominant blocker
#   non-convergence   PGD still moving AND capacity-violating at the trip
#                     limit — the fractional point is not worth rounding
#   rounding-overflow eligible mass existed but rounding + the real-gate
#                     ladder demoted every pod (phase 1 placed nothing)
#   gate-rejected     the committed result failed the full validator gate;
#                     re-solved with the flag off
#   error             any exception inside the phase — fall through to the
#                     proven path
STANDDOWN_REASONS = (
    "finite-pool",
    "ports",
    "topology",
    "no-eligible",
    "non-convergence",
    "rounding-overflow",
    "gate-rejected",
    "error",
)

# static window of candidate bins per pod, centered on its waterfill bin.
# W=16 keeps X at O(P*16) floats and still lets the gradient flow move a
# pod 8 bins either way — overload smoothing is local by construction
# (neighboring prefix-sum bins), so a wider window only adds zeros.
_WINDOW = 16
_RHO = 8.0  # quadratic capacity-violation price
_GAMMA = 0.02  # linear within-group bin price (first-fill bias)
_BETA = 0.05  # distance-from-waterfill-bin tilt (symmetry breaking)
# rounding floor, RELATIVE to the uniform share of the pod's valid window:
# the LP optimum is routinely diffuse (many equal-price bins), so an
# absolute floor would demote rows the solve fully committed. The real
# eviction signal is row mass driven toward zero (positive gradient =
# overloaded everywhere), which puts best-fraction x valid-columns well
# below 1; a committed row — however spread — keeps it at >= 1.
_MIN_REL_MASS = 1.0 - 1e-4
_CAPVIOL_OK = 0.05  # fractional overload the rounding absorbs routinely


def enabled() -> bool:
    """KARPENTER_TPU_RELAX2=1 turns the convex phase-1 solve on. Read at
    call time (not import) so the parity fuzz can A/B both arms in one
    process. Ships OFF: the round-22 A/B (docs/PERF_NOTES.md) measured the
    CPU-fallback wall; flip per deployment once the win is measured on the
    target accelerator."""
    return os.environ.get("KARPENTER_TPU_RELAX2", "0") == "1"


def pgd_iters() -> int:
    """Fixed trip count of the projected-gradient scan (static jit
    argument). The warm start is the waterfill assignment itself, so the
    scan only needs enough trips to drain overloaded bins; 24 converges the
    bench corpora with slack (last_relax2.pgd_iterations tells you where a
    workload actually lands)."""
    return max(int(os.environ.get("KARPENTER_TPU_RELAX2_ITERS", "24")), 1)


def pgd_step() -> float:
    """Gradient step size (static jit argument). The gradient is scaled by
    the pod's normalized demand, so the effective per-unit-mass step is
    workload-independent; 0.3 is stable against rho=8 (step * rho < 3
    keeps the capacity term from oscillating)."""
    return float(os.environ.get("KARPENTER_TPU_RELAX2_STEP", "0.3"))


def pgd_tol() -> float:
    """Host-side convergence tolerance on the final step's max |dX|. Only
    consulted together with the capacity violation — a still-sliding but
    capacity-feasible point rounds fine (see ``converged``)."""
    return float(os.environ.get("KARPENTER_TPU_RELAX2_TOL", "0.01"))


class Relax2Stats(NamedTuple):
    """Device-side relax2 telemetry (fetched in one tiny roundtrip)."""

    eligible: Any  # i32 pods that passed the shared eligibility screen
    placed: Any  # i32 pods phase 1 committed (post-ladder)
    demoted: Any  # i32 eligible pods sent to repair (any stage)
    claims: Any  # i32 claims phase 1 opened
    pgd_iterations: Any  # i32 first trip where max|dX| < tol (trip count if never)
    residual: Any  # f32 final max|dX|
    capviol: Any  # f32 final max fractional bin overload (load - 1)+
    overflow: Any  # i32 eligible pods whose slot window fell beyond C
    round_demoted: Any  # i32 eligible pods the rounding (pre-ladder) demoted


_SCAN_TOL = 1e-3  # device-side tolerance for the iterations-to-convergence
# counter only; the go/no-go convergence decision is the host's (pgd_tol)


def _pgd_step_op(X, valid, absc, price, wcol, C, step):
    """One projected-gradient step over the windowed fractional assignment:
    scatter bin loads, form the mass-weighted gradient, clip to the box,
    radially rescale over-full rows back onto the simplex. This is the
    entire scan-body math — census-pinned by tests/test_kernel_census.py
    (relax2_scan_body_jaxpr_eqns) and iteration-count invariant because
    the scan traces it exactly once."""
    cidx = jnp.where(valid, absc, C)
    load = jnp.zeros((C,), jnp.float32).at[cidx].add(X * wcol, mode="drop")
    over = jnp.maximum(load - 1.0, 0.0)
    overp = jnp.where(valid, over[jnp.clip(absc, 0, C - 1)], 0.0)
    grad = wcol * (price - 1.0 + _RHO * overp)
    Xn = jnp.where(valid, jnp.clip(X - step * grad, 0.0, 1.0), 0.0)
    rowsum = jnp.sum(Xn, axis=1)
    Xn = Xn / jnp.maximum(rowsum, 1.0)[:, None]
    return Xn, jnp.max(over)


def _round_lff(X, valid, absc, w, C):
    """Deterministic largest-fraction-first rounding with per-bin capacity
    bookkeeping: each pod's heaviest window column names its bin; pods sort
    by (bin, -fraction, index); a segmented prefix sum over the sorted
    normalized demands admits pods while the bin's scalar load stays <= 1.
    Pods whose best fraction falls below the uniform share of their valid
    window (the solve evicted them — see _MIN_REL_MASS) go to repair.
    Returns (slot, admitted, cand)."""
    P = X.shape[0]
    pidx = jnp.arange(P, dtype=jnp.int32)
    Xm = jnp.where(valid, X, -1.0)
    bestj = jnp.argmax(Xm, axis=1).astype(jnp.int32)
    frac = Xm[pidx, bestj]
    slot = absc[pidx, bestj].astype(jnp.int32)
    nvalid = jnp.sum(valid, axis=1).astype(jnp.float32)
    cand = frac * nvalid >= _MIN_REL_MASS  # no valid column -> frac=-1 -> out
    key_bin = jnp.where(cand, slot, C).astype(jnp.int32)
    order = jnp.lexsort((pidx, -frac, key_bin))
    ws = jnp.where(cand, w, 0.0)[order]
    bs = key_bin[order]
    cum = jnp.cumsum(ws)
    newseg = jnp.concatenate([jnp.ones((1,), bool), bs[1:] != bs[:-1]])
    segbase = lax.cummax(jnp.where(newseg, cum - ws, -jnp.inf))
    binload = cum - segbase
    admit_sorted = (binload <= 1.0 + 1e-6) & (bs < C)
    admitted = jnp.zeros((P,), bool).at[order].set(admit_sorted)
    return slot, admitted, cand


def _relax2_impl(
    problem: SchedulingProblem,
    C: int,
    bounds_free: bool,
    iters: int,
    step: float,
    n_passes: int,
) -> RelaxOut:
    statics = _statics(problem, bounds_free)
    plan = plan_groups(problem, C, statics)
    elig, gid, gidc, hp, w = plan.elig, plan.gid, plan.gidc, plan.hp, plan.w
    P = problem.num_pods

    # -- slot windows: ceil(group mass) + 1 bins per group (the slack bin
    # absorbs integral fragmentation the fractional optimum doesn't see)
    gw = jnp.zeros((C,), jnp.float32).at[jnp.where(elig, gid, C)].add(
        w, mode="drop"
    )
    nbins = jnp.where(
        plan.gvalid & (gw > 0),
        jnp.minimum(jnp.ceil(gw).astype(jnp.int32) + 1, C),
        0,
    )
    slotbase = jnp.cumsum(nbins) - nbins  # exclusive prefix
    lo = slotbase[gidc]  # [P]
    hi = lo + nbins[gidc]  # [P]

    # -- warm start: the waterfill bin (same prefix-sum level as relax.py).
    # It doubles as the symmetry anchor — identical pods get DISTINCT
    # preferred bins, so the rounding never has to break a tie the
    # objective left open.
    csum = jnp.cumsum(w)
    level = (csum - w) - (csum - w)[hp][gidc]
    binp = jnp.maximum(jnp.floor(level + 1e-6).astype(jnp.int32), 0)
    pref = jnp.minimum(lo + binp, jnp.maximum(hi - 1, lo))  # [P]

    offs = jnp.arange(_WINDOW, dtype=jnp.int32)[None, :]  # [1, W]
    absc = pref[:, None] + offs - _WINDOW // 2  # [P, W]
    valid = (
        elig[:, None] & (absc >= lo[:, None]) & (absc < hi[:, None]) & (absc < C)
    )
    has_bin = jnp.any(valid, axis=1)
    overflow = elig & ~has_bin  # window truncated past the claim axis

    price = (
        _GAMMA * (absc - lo[:, None]).astype(jnp.float32)
        + _BETA * jnp.abs(absc - pref[:, None]).astype(jnp.float32)
    )
    wcol = w[:, None]
    X0 = jnp.where(valid & (absc == pref[:, None]), 1.0, 0.0)

    def body(carry, t):
        X, conv, _ = carry
        Xn, capviol = _pgd_step_op(X, valid, absc, price, wcol, C, step)
        delta = jnp.max(jnp.abs(Xn - X))
        conv = jnp.where((conv < 0) & (delta < _SCAN_TOL), t + 1, conv)
        return (Xn, conv, delta), None

    init = (X0, jnp.int32(-1), jnp.float32(jnp.inf))
    (X, conv, delta), _ = lax.scan(
        body, init, jnp.arange(iters, dtype=jnp.int32)
    )
    _, capviol = _pgd_step_op(X, valid, absc, price, wcol, C, step)

    # -- rounding + the shared real-gate ladder/commit
    slot, admitted, cand = _round_lff(X, valid, absc, w, C)
    assigned0 = elig & admitted & (slot < C)
    com = commit_assignment(
        problem, C, statics, plan, slot, assigned0, n_passes
    )
    stats = Relax2Stats(
        eligible=jnp.sum(plan.elig0).astype(jnp.int32),
        placed=jnp.sum(com.assigned).astype(jnp.int32),
        demoted=jnp.sum(plan.elig0 & ~com.assigned).astype(jnp.int32),
        claims=jnp.sum(com.open_c).astype(jnp.int32),
        pgd_iterations=jnp.where(conv >= 0, conv, iters).astype(jnp.int32),
        residual=delta.astype(jnp.float32),
        capviol=capviol.astype(jnp.float32),
        overflow=jnp.sum(overflow).astype(jnp.int32),
        round_demoted=jnp.sum(elig & ~assigned0).astype(jnp.int32),
    )
    return RelaxOut(
        state=com.state, kind=com.kind, index=com.index,
        residue_active=com.residue_active, stats=stats,
    )


@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4, 5))
def _relax2_place_jit(
    problem: SchedulingProblem,
    max_claims: int,
    bounds_free: bool,
    iters: int,
    step: float,
    n_passes: int,
) -> RelaxOut:
    problem = _pad_lanes_mult32(problem)
    return _relax2_impl(problem, max_claims, bounds_free, iters, step, n_passes)


def relax2_place(
    problem: SchedulingProblem, max_claims: int, init: Optional[FFDState] = None
) -> RelaxOut:
    """The convex phase-1 solve (see module docstring). ``init`` must be
    None — phase 1 only ever runs on a fresh solve; the signature matches
    the other entry points for the backend/aot dispatch plumbing."""
    assert init is None, "relaxation always starts a fresh solve"
    return _relax2_place_jit(
        problem, int(max_claims), problem_bounds_free(problem),
        pgd_iters(), pgd_step(), relax_passes(),
    )


def converged(residual: float, capviol: float) -> bool:
    """The go/no-go rounding decision: a point still sliding AND still
    capacity-violating at the trip limit is not worth rounding (the ladder
    would demote most of it anyway) — the backend stands down with
    reason="non-convergence". A capacity-feasible point rounds fine even if
    mass is still drifting between equivalent bins."""
    return residual <= pgd_tol() or capviol <= _CAPVIOL_OK


def classify_ineligible(problem: SchedulingProblem) -> str:
    """Name the dominant blocker when the shared screen left nothing
    eligible (host-side numpy, bounded vocabulary): "ports" when port-bearing
    pods dominate, "topology" when topology-role pods dominate, else
    "no-eligible" (hostname pins, node candidates, mixed causes)."""
    import numpy as np

    active = np.asarray(problem.pod_active)
    n_port = n_topo = 0
    if problem.pod_ports.shape[1] > 0:
        ports = np.any(np.asarray(problem.pod_ports), axis=1) | np.any(
            np.asarray(problem.pod_port_conflict), axis=1
        )
        n_port = int(np.sum(active & ports))
    G = problem.grp_key.shape[0]
    if G > 0:
        blocking = np.asarray(problem.grp_inverse) | (
            np.asarray(problem.grp_type) == TYPE_ANTI_AFFINITY
        )
        topo = (
            np.any(np.asarray(problem.pod_grp_match), axis=1)
            | np.any(np.asarray(problem.pod_grp_owned), axis=1)
            | np.any(
                np.asarray(problem.pod_grp_selects) & blocking[None, :], axis=1
            )
        )
        n_topo = int(np.sum(active & topo))
    if n_port >= n_topo and n_port > 0:
        return "ports"
    if n_topo > 0:
        return "topology"
    return "no-eligible"


# re-exported so callers (and the satellite parity test) can assert both
# solvers consume the literally-same screen and mask builder
_eligibility = eligibility
