"""First-fit-decreasing bin-pack as a lax.scan.

TPU-native re-design of the reference's Scheduler.Solve pod loop
(scheduler.go:140-189, :238-285): pods arrive pre-sorted by the FFD queue
order; one scan step places one pod. Placement *scoring* — which existing
nodes / open claims / fresh template claims could accept the pod, including
the topology domain selection — is computed for every candidate at once with
the vectorized mask kernels (the reference walks them one by one,
O(candidates × instanceTypes) set intersections per pod); the *commit* stays
sequential inside the scan because every placement narrows the chosen bin's
requirement state and shifts the topology counters.

Placement priority per pod (scheduler.go:238-285):
  1. first existing node (pre-sorted initialized-first) that tolerates, fits,
     has no host-port conflict, is requirement-compatible, and satisfies
     topology (existingnode.go:64-124, strict Compatible);
  2. open claim with the fewest pods whose topology-narrowed state keeps >= 1
     instance type satisfying requirements + resources + offerings
     (nodeclaim.go:65-119);
  3. first template (weight order) whose fresh claim — minted hostname
     included — accepts the pod, subject to nodepool limit headroom
     (filterByRemainingResources / subtractMax, scheduler.go:343-383);
  4. otherwise the pod fails this pass (relaxation happens host-side between
     passes, the carried FFDState preserving earlier placements).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax, vmap

from karpenter_tpu.models.problem import ReqTensor, SchedulingProblem
from karpenter_tpu.ops import masks
from karpenter_tpu.ops.topology_kernels import (
    PodTopoStatics,
    record,
    record_delta,
    topo_gate,
)

# placement kinds emitted per pod
KIND_NODE = 0
KIND_CLAIM = 1
KIND_NEW_CLAIM = 2
KIND_FAIL = 3
KIND_NO_SLOT = 4  # a fresh claim would accept the pod, but slots ran out

# vocab key indices the encoder pins (single source: models/problem.py)
from karpenter_tpu.models.problem import CT_KEY, HOSTNAME_KEY, ZONE_KEY  # noqa: E402

# plain int: a module-level jnp scalar would initialize the JAX backend at
# import time (and block on the TPU tunnel in processes that never use it)
_BIG = 2**30

# scan unroll factor: amortizes per-iteration dispatch overhead on
# accelerators at the cost of a proportionally bigger program to compile.
# Measured on TPU v5e at the 2500-pod bench shape (r3): unroll=4 left steady
# solve time unchanged (1.38s vs 1.39s) and 2.3x'd compile time — the step
# body is large enough that dispatch overhead is negligible, so 1 stays the
# default on both backends
import os as _os  # noqa: E402

_UNROLL = int(_os.environ.get("KARPENTER_TPU_SCAN_UNROLL", "1"))

# dev-only cost-attribution knob: comma-set of step phases to stub out
# (results become WRONG — never set outside tools/profile_step.py)
_ABLATE = frozenset(
    p for p in _os.environ.get("KARPENTER_TPU_ABLATE", "").split(",") if p
)


@jax.tree_util.register_dataclass
@dataclass
class FFDState:
    claim_req: ReqTensor  # [C, K, V] narrowed requirement state per claim
    claim_requests: Any  # f32[C, R] accumulated requests (incl daemon overhead)
    claim_it_ok: Any  # bool[C, T] surviving instance types
    claim_open: Any  # bool[C]
    claim_npods: Any  # i32[C]
    claim_tpl: Any  # i32[C]
    claim_used_ports: Any  # bool[C, PT] reserved host-port lanes
    node_req: ReqTensor  # [N, K, V] narrowed existing-node requirements
    node_requests: Any  # f32[N, R] accumulated requests (incl daemon overhead)
    node_npods: Any  # i32[N]
    node_used_ports: Any  # bool[N, PT]
    node_vol_used: Any  # i32[N, D] CSI attach counts per limited driver
    remaining: Any  # f32[TPL, R] nodepool limits headroom (+inf unlimited)
    grp_counts: Any  # i32[G, V] topology domain counts
    grp_registered: Any  # bool[G, V] known topology domains


@jax.tree_util.register_dataclass
@dataclass
class FFDResult:
    kind: Any  # i32[P]
    index: Any  # i32[P] node index / claim slot (meaning depends on kind)
    state: FFDState  # final bin state


def _first_true(mask: jnp.ndarray) -> jnp.ndarray:
    """Index of the first True (or len(mask) when none)."""
    return jnp.argmax(jnp.concatenate([mask, jnp.array([True])]))


def _intersect_rows(reqs: ReqTensor, row: ReqTensor) -> ReqTensor:
    return vmap(lambda r: masks.intersect(r, row))(reqs)


def initial_state(problem: SchedulingProblem, max_claims: int) -> FFDState:
    K, V = problem.num_keys, problem.num_lanes
    T, R = problem.num_instance_types, problem.num_resources
    N, C = problem.num_nodes, max_claims
    PT = problem.pod_ports.shape[1]
    lv = jnp.asarray(problem.lane_valid)
    return FFDState(
        claim_req=ReqTensor(
            admitted=jnp.broadcast_to(lv, (C, K, V)),
            comp=jnp.ones((C, K), dtype=bool),
            gt=jnp.full((C, K), -(2**31) + 1, dtype=jnp.int32),
            lt=jnp.full((C, K), 2**31 - 1, dtype=jnp.int32),
            defined=jnp.zeros((C, K), dtype=bool),
        ),
        claim_requests=jnp.zeros((C, R), dtype=jnp.float32),
        claim_it_ok=jnp.zeros((C, T), dtype=bool),
        claim_open=jnp.zeros((C,), dtype=bool),
        claim_npods=jnp.zeros((C,), dtype=jnp.int32),
        claim_tpl=jnp.zeros((C,), dtype=jnp.int32),
        claim_used_ports=jnp.zeros((C, PT), dtype=bool),
        node_req=jax.tree_util.tree_map(jnp.asarray, problem.node_reqs),
        node_requests=jnp.asarray(problem.node_overhead),
        node_npods=jnp.zeros((N,), dtype=jnp.int32),
        node_used_ports=jnp.asarray(problem.node_used_ports),
        node_vol_used=jnp.asarray(problem.node_vol_used),
        remaining=jnp.asarray(problem.tpl_remaining),
        grp_counts=jnp.asarray(problem.grp_counts0),
        grp_registered=jnp.asarray(problem.grp_registered0),
    )


def solve_ffd(
    problem: SchedulingProblem, max_claims: int, init: Optional[FFDState] = None
) -> FFDResult:
    """Run one pack pass. Shapes are static per bucket; XLA caches the
    compiled executable across batches. ``init`` carries bin + topology state
    between relax-and-retry passes (the queue requeue of scheduler.go:150-170).

    A fresh solve builds the initial state *inside* the jit: each eager
    device op outside a jit is a separate launch through the (possibly
    remote) TPU runtime, and initial_state's ~13 of them cost more than the
    whole small-batch scan."""
    if init is None:
        return _solve_ffd_fresh_jit(problem, max_claims)
    return _solve_ffd_jit(problem, init)


def _pad_lanes_mult32(problem: SchedulingProblem) -> SchedulingProblem:
    """Pad the value-lane axis to a multiple of 32 for bitpacking. Shape-static
    (plain Python under trace); ops/padding.py already does this for bucketed
    callers, so this is a no-op on the production path."""
    V = problem.num_lanes
    pad = (-V) % 32
    if pad == 0:
        return problem
    import dataclasses

    def pad_req(r: ReqTensor) -> ReqTensor:
        return dataclasses.replace(
            r, admitted=jnp.pad(r.admitted, [(0, 0)] * (r.admitted.ndim - 1) + [(0, pad)])
        )

    lane_pad = [(0, 0), (0, pad)]
    return dataclasses.replace(
        problem,
        lane_valid=jnp.pad(problem.lane_valid, lane_pad),
        lane_numeric=jnp.pad(problem.lane_numeric, lane_pad, constant_values=jnp.nan),
        lane_lex_rank=jnp.pad(problem.lane_lex_rank, lane_pad, constant_values=2**30),
        pod_reqs=pad_req(problem.pod_reqs),
        pod_strict_reqs=pad_req(problem.pod_strict_reqs),
        it_reqs=pad_req(problem.it_reqs),
        tpl_reqs=pad_req(problem.tpl_reqs),
        node_reqs=pad_req(problem.node_reqs),
        grp_filter=pad_req(problem.grp_filter),
        grp_counts0=jnp.pad(problem.grp_counts0, lane_pad),
        grp_registered0=jnp.pad(problem.grp_registered0, lane_pad),
    )


def _lane_align(problem: SchedulingProblem, init: FFDState):
    problem = _pad_lanes_mult32(problem)
    V = problem.num_lanes
    # lane-pad carried state to match (no-op when init came from initial_state)
    if init.grp_counts.shape[-1] != V:
        pad = V - init.grp_counts.shape[-1]
        import dataclasses

        def pad_adm(r):
            return dataclasses.replace(
                r, admitted=jnp.pad(r.admitted, [(0, 0)] * (r.admitted.ndim - 1) + [(0, pad)])
            )

        init = dataclasses.replace(
            init,
            claim_req=pad_adm(init.claim_req),
            node_req=pad_adm(init.node_req),
            grp_counts=jnp.pad(init.grp_counts, [(0, 0), (0, pad)]),
            grp_registered=jnp.pad(init.grp_registered, [(0, 0), (0, pad)]),
        )
    return problem, init


def _statics(problem: SchedulingProblem):
    """Per-solve invariants shared by the per-pod step and the run commit."""
    lv, ln = jnp.asarray(problem.lane_valid), jnp.asarray(problem.lane_numeric)
    wellknown = jnp.asarray(problem.key_wellknown)
    no_allow = jnp.zeros_like(wellknown)
    # instance-type side of the hot compat product: packed lanes + polarity,
    # computed once per solve (instance types never change during a pack)
    it_packed = masks.pack_lanes(jnp.asarray(problem.it_reqs.admitted))  # [T, K, W]
    it_neg = vmap(lambda r: masks.negative_polarity(r, lv, ln))(problem.it_reqs)
    return lv, ln, wellknown, no_allow, it_packed, it_neg


def _make_it_gate(problem, statics):
    lv, ln, wellknown, no_allow, it_packed, it_neg = statics

    def it_gate(state_rows: ReqTensor, requests: jnp.ndarray, prior_ok: jnp.ndarray):
        """[B, T] mask of instance types surviving a narrowed state +
        accumulated requests (nodeclaim.go:225-260)."""
        state_packed = masks.pack_lanes(state_rows.admitted)  # [B, K, W]
        state_neg = vmap(lambda r: masks.negative_polarity(r, lv, ln))(state_rows)
        compat = masks.packed_pairwise_compat(
            state_rows, state_packed, state_neg, problem.it_reqs, it_packed, it_neg
        )  # [B, T]
        fit = masks.fits(requests[:, None, :], problem.it_alloc[None, :, :])  # [B, T]
        offer = _offer_rows(problem, state_rows.admitted)  # [B, T]
        return prior_ok & compat & fit & offer

    return it_gate


def _offer_rows(problem: SchedulingProblem, admitted) -> jnp.ndarray:
    """[B, T] has_offering over a batch of bin states — MXU matmul when the
    dense offer_zc table exists, per-offering lane gathers otherwise."""
    if problem.offer_zc is not None:
        return masks.has_offering_zc(admitted, ZONE_KEY, CT_KEY, problem.offer_zc)
    return vmap(
        lambda adm: masks.has_offering(
            adm, ZONE_KEY, CT_KEY, problem.offer_zone, problem.offer_ct, problem.offer_ok
        )
    )(admitted)


def _mix_req_rows(cur: ReqTensor, upd: ReqTensor, hot) -> ReqTensor:
    """Commit updated requirement rows where ``hot`` (bool[E]) is set."""
    sel2, sel3 = hot[:, None], hot[:, None, None]
    return ReqTensor(
        admitted=jnp.where(sel3, upd.admitted, cur.admitted),
        comp=jnp.where(sel2, upd.comp, cur.comp),
        gt=jnp.where(sel2, upd.gt, cur.gt),
        lt=jnp.where(sel2, upd.lt, cur.lt),
        defined=jnp.where(sel2, upd.defined, cur.defined),
    )


def _mint_host_onehot(problem: SchedulingProblem, free_slot):
    """One-hot of the hostname lane minted for the prospective slot
    (nodeclaim.go:46-63); all-False when the encoder allotted no lanes."""
    V = problem.num_lanes
    if problem.claim_hostname_lane.shape[0] == 0:
        return jnp.zeros((V,), dtype=bool)
    host_lane = problem.claim_hostname_lane[
        jnp.minimum(free_slot, problem.claim_hostname_lane.shape[0] - 1)
    ]
    return jnp.arange(V) == host_lane


def _pin_hostname(row: ReqTensor, host_onehot) -> ReqTensor:
    """Pin requirement row(s) ([K, V] or [E, K, V]) to the minted hostname:
    admitted lanes collapse to the mint, the key becomes a defined concrete
    set. Shared by the per-pod step's template rows and the run commit so the
    pin semantics can never diverge between them."""
    return ReqTensor(
        admitted=row.admitted.at[..., HOSTNAME_KEY, :].set(
            row.admitted[..., HOSTNAME_KEY, :] & host_onehot
        ),
        comp=row.comp.at[..., HOSTNAME_KEY].set(False),
        gt=row.gt,
        lt=row.lt,
        defined=row.defined.at[..., HOSTNAME_KEY].set(True),
    )


def _fresh_template_rows(problem: SchedulingProblem, lv, ln, wellknown, pod_req, free_slot):
    """Fresh-claim template evaluation shared by the per-pod step and the run
    commit: the prospective slot's hostname is minted and pinned into the
    merged template rows before any gate sees them (nodeclaim.go:46-63), and
    template compatibility uses the well-known allowance. Returns
    (tpl_merged, tpl_compat, host_onehot)."""
    mint_hostnames = problem.claim_hostname_lane.shape[0] > 0
    host_onehot = _mint_host_onehot(problem, free_slot)
    tpl_compat = vmap(
        lambda tr: masks.compatible_ok(tr, pod_req, lv, ln, wellknown)
    )(problem.tpl_reqs)
    tpl_merged = _intersect_rows(problem.tpl_reqs, pod_req)
    if mint_hostnames:
        tpl_merged = _pin_hostname(tpl_merged, host_onehot)
    return tpl_merged, tpl_compat, host_onehot


def _pod_xs(problem: SchedulingProblem):
    return (
        problem.pod_reqs,
        problem.pod_strict_reqs,
        jnp.asarray(problem.pod_requests),
        jnp.asarray(problem.pod_tol_tpl),
        jnp.asarray(problem.pod_tol_node),
        jnp.asarray(problem.pod_ports),
        jnp.asarray(problem.pod_port_conflict),
        jnp.asarray(problem.pod_grp_match),
        jnp.asarray(problem.pod_grp_selects),
        jnp.asarray(problem.pod_grp_owned),
        jnp.asarray(problem.pod_vol_counts),
        jnp.asarray(problem.pod_active),
    )


def _make_step(problem: SchedulingProblem, statics, C: int):
    lv, ln, wellknown, no_allow, it_packed, it_neg = statics
    N = problem.num_nodes
    T = problem.num_instance_types
    TPL = problem.num_templates
    K = problem.num_keys
    V = problem.num_lanes
    it_gate = _make_it_gate(problem, statics)

    def step(state: FFDState, pod):
        (
            pod_req,
            pod_strict,
            pod_requests,
            tol_tpl,
            tol_node,
            pod_ports,
            pod_conflict,
            grp_match,
            grp_selects,
            grp_owned,
            pod_vols,
            pod_is_active,
        ) = pod
        topo_pod = PodTopoStatics(
            strict_admitted=pod_strict.admitted,
            grp_match=grp_match,
            grp_selects=grp_selects,
            grp_owned=grp_owned,
        )
        # NOTE on lax.cond here: conditionals only pay off when branch
        # outputs are small — a cond whose identity branch passes [B, K, V]
        # requirement tensors through forces per-step copies that cost more
        # than the gate it skips (measured +0.15s on the 10k bench). So the
        # topo gates stay unconditional; only the template phase (small
        # row outputs) and record (two [G, V] outputs) are conditional.

        def gated(merged, allow, registered):
            return topo_gate(
                problem, state.grp_counts, registered, topo_pod, merged, allow
            )

        # -- 1. existing nodes (scheduler.go:240-244; existingnode.go:64-124)
        node_requests2 = state.node_requests + pod_requests[None, :]
        node_fit = masks.fits(node_requests2, problem.node_avail)
        node_compat = vmap(
            lambda nr: masks.compatible_ok(nr, pod_req, lv, ln, no_allow)
        )(state.node_req)
        node_port_ok = ~jnp.any(state.node_used_ports & pod_conflict[None, :], axis=-1)
        # CSI attach limits gate existing nodes only (existingnode.go:100-106)
        node_vol_ok = jnp.all(
            state.node_vol_used + pod_vols[None, :] <= problem.node_vol_limits, axis=-1
        )
        node_merged = _intersect_rows(state.node_req, pod_req)
        node_topo_ok, node_final = gated(node_merged, no_allow, state.grp_registered)
        node_ok = tol_node & node_fit & node_compat & node_port_ok & node_vol_ok & node_topo_ok
        node_pick = _first_true(node_ok)
        any_node = jnp.any(node_ok)

        # -- 2. open claims, fewest pods first (scheduler.go:247-254)
        claim_compat = vmap(
            lambda cr: masks.compatible_ok(cr, pod_req, lv, ln, wellknown)
        )(state.claim_req)
        claim_merged = _intersect_rows(state.claim_req, pod_req)
        if "ctopo" in _ABLATE:
            claim_topo_ok, claim_final = jnp.ones((C,), bool), claim_merged
        else:
            claim_topo_ok, claim_final = gated(
                claim_merged, wellknown, state.grp_registered
            )
        claim_requests2 = state.claim_requests + pod_requests[None, :]
        if "citgate" in _ABLATE:
            claim_it_ok2 = state.claim_it_ok
        else:
            claim_it_ok2 = it_gate(claim_final, claim_requests2, state.claim_it_ok)
        claim_port_ok = ~jnp.any(state.claim_used_ports & pod_conflict[None, :], axis=-1)
        claim_ok = (
            state.claim_open
            & tol_tpl[state.claim_tpl]
            & claim_port_ok
            & claim_compat
            & claim_topo_ok
            & jnp.any(claim_it_ok2, axis=-1)
        )
        claim_rank = jnp.where(claim_ok, state.claim_npods * C + jnp.arange(C), _BIG)
        claim_pick = jnp.argmin(claim_rank)
        any_claim = jnp.any(claim_ok)

        # -- 3. fresh claim from templates, weight order (scheduler.go:256-283);
        # the prospective slot's hostname is minted before evaluation
        # (nodeclaim.go:46-63) and its lane registered for topology if opened.
        # The whole phase runs under lax.cond: it can only influence the
        # outcome when the node and claim phases both failed and a slot is
        # free, which on large packs is a small minority of steps (opens +
        # terminal failures).
        free_slot = _first_true(~state.claim_open)
        has_slot = jnp.any(~state.claim_open)
        # hostname minting is active only when the encoder allotted claim
        # hostname lanes (static shape decision)
        mint_hostnames = problem.claim_hostname_lane.shape[0] > 0
        need_tpl = (~any_node) & (~any_claim) & has_slot & pod_is_active

        def eval_tpl():
            tpl_requests2 = problem.tpl_overhead + pod_requests[None, :]
            tpl_merged, tpl_compat, host_onehot = _fresh_template_rows(
                problem, lv, ln, wellknown, pod_req, free_slot
            )
            # the new hostname is registered before the gate evaluates
            reg_for_tpl = state.grp_registered | (
                (problem.grp_key == HOSTNAME_KEY)[:, None] & host_onehot[None, :]
            )
            if "ttopo" in _ABLATE:
                tpl_topo_ok, tpl_final = jnp.ones((TPL,), bool), tpl_merged
            else:
                tpl_topo_ok, tpl_final = gated(tpl_merged, wellknown, reg_for_tpl)
            within_limits = masks.fits(
                problem.it_cap[None, :, :], state.remaining[:, None, :]
            )  # [TPL, T]
            if "titgate" in _ABLATE:
                tpl_it_ok2 = problem.tpl_it_ok & within_limits
            else:
                tpl_it_ok2 = it_gate(
                    tpl_final, tpl_requests2, problem.tpl_it_ok & within_limits
                )
            tpl_ok = tol_tpl & tpl_compat & tpl_topo_ok & jnp.any(tpl_it_ok2, axis=-1)
            tpl_pick = _first_true(tpl_ok)
            pick_c = jnp.minimum(tpl_pick, TPL - 1)
            slot_req = tpl_final.row(pick_c)
            tpl_row_it_ok = tpl_it_ok2[pick_c]
            max_cap = jnp.max(
                jnp.where(tpl_row_it_ok[:, None], problem.it_cap, 0.0), axis=0
            )  # [R]
            return (
                jnp.any(tpl_ok),
                tpl_pick.astype(jnp.int32),
                slot_req,
                tpl_requests2[pick_c],
                tpl_row_it_ok,
                max_cap,
                host_onehot,
            )

        def skip_tpl():
            R = problem.tpl_overhead.shape[1]
            return (
                jnp.bool_(False),
                jnp.int32(0),
                ReqTensor(
                    admitted=jnp.zeros((K, V), bool),
                    comp=jnp.zeros((K,), bool),
                    gt=jnp.zeros((K,), jnp.int32),
                    lt=jnp.zeros((K,), jnp.int32),
                    defined=jnp.zeros((K,), bool),
                ),
                jnp.zeros((R,), problem.tpl_overhead.dtype),
                jnp.zeros((T,), bool),
                jnp.zeros((R,), problem.it_cap.dtype),
                jnp.zeros((V,), bool),
            )

        (
            any_tpl,
            tpl_pick,
            slot_req,
            tpl_row_requests,
            tpl_row_it_ok,
            max_cap,
            host_onehot,
        ) = lax.cond(need_tpl, eval_tpl, skip_tpl)

        # with every slot taken, free_slot clamps to slot 0 and the template
        # phase evaluated a USED hostname — its verdict is meaningless, so the
        # no-slot case must classify as KIND_NO_SLOT unconditionally (the
        # backend's doubled-slot retry then produces the true answer); mapping
        # it through any_tpl misread "slot 0's hostname is taken" as a
        # permanent KIND_FAIL and starved the slot-growth path
        kind = jnp.where(
            any_node,
            KIND_NODE,
            jnp.where(
                any_claim,
                KIND_CLAIM,
                jnp.where(
                    ~has_slot,
                    KIND_NO_SLOT,
                    jnp.where(any_tpl, KIND_NEW_CLAIM, KIND_FAIL),
                ),
            ),
        ).astype(jnp.int32)
        # masked-out rows (pod_active=False: padding, or a consolidation
        # variant's inert candidate pods) fail without touching state — all
        # one-hot commits below derive from kind
        kind = jnp.where(pod_is_active, kind, KIND_FAIL)

        # -- commit via one-hot masks
        node_hot = (jnp.arange(N) == node_pick) & (kind == KIND_NODE)
        claim_hot = (jnp.arange(C) == claim_pick) & (kind == KIND_CLAIM)
        slot_hot = (jnp.arange(C) == free_slot) & (kind == KIND_NEW_CLAIM)

        mix_req = _mix_req_rows

        def gather_row(rows: ReqTensor, idx, cap) -> ReqTensor:
            return rows.row(jnp.minimum(idx, cap - 1))

        # node commit (existingnode.go:116-123)
        new_node_req = mix_req(state.node_req, node_final, node_hot)
        new_node_requests = jnp.where(node_hot[:, None], node_requests2, state.node_requests)
        new_node_npods = state.node_npods + node_hot.astype(jnp.int32)
        new_node_used_ports = state.node_used_ports | (node_hot[:, None] & pod_ports[None, :])
        new_node_vol_used = state.node_vol_used + node_hot[:, None].astype(jnp.int32) * pod_vols[None, :]

        # claim commit (nodeclaim.go:111-118); slot_req / tpl_row_* come from
        # the conditional template phase above
        new_claim_req = mix_req(
            mix_req(state.claim_req, claim_final, claim_hot),
            ReqTensor(
                admitted=jnp.broadcast_to(slot_req.admitted, (C, K, V)),
                comp=jnp.broadcast_to(slot_req.comp, (C, K)),
                gt=jnp.broadcast_to(slot_req.gt, (C, K)),
                lt=jnp.broadcast_to(slot_req.lt, (C, K)),
                defined=jnp.broadcast_to(slot_req.defined, (C, K)),
            ),
            slot_hot,
        )
        new_claim_requests = jnp.where(
            claim_hot[:, None],
            claim_requests2,
            jnp.where(slot_hot[:, None], tpl_row_requests[None, :], state.claim_requests),
        )
        new_claim_it_ok = jnp.where(
            claim_hot[:, None],
            claim_it_ok2,
            jnp.where(slot_hot[:, None], tpl_row_it_ok[None, :], state.claim_it_ok),
        )
        new_claim_open = state.claim_open | slot_hot
        new_claim_npods = state.claim_npods + claim_hot.astype(jnp.int32) + slot_hot.astype(jnp.int32)
        new_claim_tpl = jnp.where(slot_hot, tpl_pick.astype(jnp.int32), state.claim_tpl)
        new_claim_used_ports = state.claim_used_ports | (
            (claim_hot | slot_hot)[:, None] & pod_ports[None, :]
        )

        # opening a claim burns pessimistic headroom (subtractMax) and
        # registers its hostname lane for hostname topologies
        opened = kind == KIND_NEW_CLAIM
        opened_tpl_hot = (jnp.arange(TPL) == tpl_pick) & opened
        new_remaining = jnp.where(
            opened_tpl_hot[:, None], state.remaining - max_cap[None, :], state.remaining
        )
        new_registered = state.grp_registered | (
            opened
            & mint_hostnames
            & (problem.grp_key == HOSTNAME_KEY)[:, None]
            & host_onehot[None, :]
        )

        # topology record for the chosen bin (topology.go:125-148) — an
        # identity unless a placement happened AND some group selects or is
        # owned by this pod, so it runs under lax.cond (generic pods with
        # labels no selector matches skip it entirely)
        committed = (kind == KIND_NODE) | (kind == KIND_CLAIM) | (kind == KIND_NEW_CLAIM)
        should_record = committed & (
            jnp.any(topo_pod.grp_selects) | jnp.any(topo_pod.grp_owned)
        )

        def do_record():
            chosen_final = gather_row(node_final, node_pick, N) if N > 0 else None
            claim_row = gather_row(claim_final, claim_pick, C)
            slot_row = slot_req

            def pick_rows(a, b, cond):
                return jax.tree_util.tree_map(
                    lambda x, y: jnp.where(
                        jnp.reshape(cond, (1,) * x.ndim), x, y
                    ),
                    a,
                    b,
                )

            rec_row = pick_rows(claim_row, slot_row, kind == KIND_CLAIM)
            if N > 0:
                rec_row = pick_rows(chosen_final, rec_row, kind == KIND_NODE)
            rec_allow = jnp.where(kind == KIND_NODE, no_allow, wellknown)
            return record(
                problem,
                state.grp_counts,
                new_registered,
                topo_pod,
                rec_row,
                rec_allow,
                committed,
                lv,
                ln,
            )

        if "record" in _ABLATE:
            new_counts = state.grp_counts
        else:
            new_counts, new_registered = lax.cond(
                should_record, do_record, lambda: (state.grp_counts, new_registered)
            )

        index = jnp.where(
            kind == KIND_NODE,
            node_pick,
            jnp.where(kind == KIND_CLAIM, claim_pick, jnp.where(kind == KIND_NEW_CLAIM, free_slot, -1)),
        ).astype(jnp.int32)

        new_state = FFDState(
            claim_req=new_claim_req,
            claim_requests=new_claim_requests,
            claim_it_ok=new_claim_it_ok,
            claim_open=new_claim_open,
            claim_npods=new_claim_npods,
            claim_tpl=new_claim_tpl,
            claim_used_ports=new_claim_used_ports,
            node_req=new_node_req,
            node_requests=new_node_requests,
            node_npods=new_node_npods,
            node_used_ports=new_node_used_ports,
            node_vol_used=new_node_vol_used,
            remaining=new_remaining,
            grp_counts=new_counts,
            grp_registered=new_registered,
        )
        return new_state, (kind, index)

    return step


@jax.jit
def _solve_ffd_jit(problem: SchedulingProblem, init: FFDState) -> FFDResult:
    """Reference per-pod scan: one pod per step — the provisioning
    production default (faster than the run-compressed scan on diverse
    workloads, see solver/jax_backend.py) and the semantic anchor the
    run-compressed solver is fuzz-checked against."""
    problem, init = _lane_align(problem, init)
    step = _make_step(problem, _statics(problem), init.claim_open.shape[0])
    final_state, (kinds, indices) = lax.scan(step, init, _pod_xs(problem), unroll=_UNROLL)
    return FFDResult(kind=kinds, index=indices, state=final_state)


@functools.partial(jax.jit, static_argnums=(1,))
def _solve_ffd_fresh_jit(problem: SchedulingProblem, max_claims: int) -> FFDResult:
    """Fresh-state variant: initial_state is traced into the program so a
    first-pass solve is a single device launch."""
    problem = _pad_lanes_mult32(problem)
    return _solve_ffd_jit.__wrapped__(problem, initial_state(problem, max_claims))


# max pods committed per sweep iteration by the stride commit (see
# _make_stride); identical consecutive pods beyond this window simply take
# another iteration
_STRIDE = int(_os.environ.get("KARPENTER_TPU_STRIDE", "64"))
# experimental chain-dispatch sweep structure (see _sweeps_impl)
_CHAIN_DISPATCH = _os.environ.get("KARPENTER_TPU_CHAIN_DISPATCH", "") == "1"


def _make_stride(problem: SchedulingProblem, statics, C: int, S: int, pods_xs):
    """One sweep iteration: evaluate ONE pod exactly (the narrow per-pod
    gates), then commit it together with up to S-1 byte-identical consecutive
    queue successors in closed form — bit-identical to stepping them one at a
    time:

      - identical pods against unchanged state get identical verdicts, so a
        FAIL (or NO_SLOT) verdict extends to the whole identical chain at
        zero cost — one iteration requeues (or flags) all of them;
      - a placed pod's chain may stack into its chosen bin while j such pods
        still fit (the per-pod fit gate's closed form over instance types /
        node capacity, ports and CSI limits included) and, for claims, while
        the bin remains the fewest-pods pick with j-1 stack-mates aboard
        (rank stays below the second-best eligible rank — competitors' ranks
        never improve, so the bound is exact);
      - stacking is allowed only when the pod's own record set cannot feed
        back into its own gate set: no matched group is recorded into,
        EXCEPT regular affinity groups, whose gate is monotone in the
        counters — the first pod's narrowed row makes every successor's
        merge, gate verdict, and record delta identical (the allowed-domain
        set only grows, and the bin state is already narrowed inside it);
      - record deltas are then identical per stack member: counts += k*delta.

    A claim-open commits alone (it moves free_slot, limits headroom, and the
    fewest-pods ranking). Every iteration consumes >= 1 pod.
    """
    lv, ln, wellknown, no_allow, it_packed, it_neg = statics
    N = problem.num_nodes
    T = problem.num_instance_types
    TPL = problem.num_templates
    K = problem.num_keys
    V = problem.num_lanes
    R = problem.pod_requests.shape[1]
    it_gate = _make_it_gate(problem, statics)
    mint_hostnames = problem.claim_hostname_lane.shape[0] > 0
    G = problem.grp_key.shape[0]
    P = problem.num_pods
    eqprev_arr = (
        jnp.asarray(problem.pod_eqprev)
        if problem.pod_eqprev is not None
        else jnp.zeros((P,), bool)
    )
    eqgate_arr = (
        jnp.asarray(problem.pod_eqprev_gate)
        if problem.pod_eqprev_gate is not None
        else jnp.zeros((P,), bool)
    )
    # the analytic waterfill commit consumes whole gate-identical chains
    # (record sum included); scratch tail so a window near P never clamps
    run_commit = _make_run_commit(problem, statics, C, S)
    active_concat = jnp.concatenate(
        [jnp.asarray(problem.pod_active), jnp.zeros((S,), bool)]
    )
    Srange = jnp.arange(S)

    def topo_of(pod):
        return PodTopoStatics(
            strict_admitted=pod[1].admitted,
            grp_match=pod[7],
            grp_selects=pod[8],
            grp_owned=pod[9],
        )

    def _zeros_row():
        return ReqTensor(
            admitted=jnp.zeros((K, V), bool),
            comp=jnp.zeros((K,), bool),
            gt=jnp.zeros((K,), jnp.int32),
            lt=jnp.zeros((K,), jnp.int32),
            defined=jnp.zeros((K,), bool),
        )

    def eval_base(state: FFDState, pod):
        # NOTE: the node/claim gate phases below intentionally mirror
        # _make_step's — _make_step stays the scan-path anchor the
        # randomized-parity fuzz cross-checks this path against (and both
        # are anchored to the host oracle). Any gate change must land in
        # BOTH, and the 64-seed fuzz is the guard that they did.
        (
            pod_req,
            _pod_strict,
            pod_requests,
            tol_tpl,
            tol_node,
            pod_ports,
            pod_conflict,
            _gm,
            _gs,
            _go,
            pod_vols,
            pod_is_active,
        ) = pod
        topo_pod = topo_of(pod)
        port_cap = jnp.where(jnp.any(pod_ports), 1, _BIG_CAP).astype(jnp.int32)

        # -- existing nodes (same gates as _make_step)
        node_requests2 = state.node_requests + pod_requests[None, :]
        node_fit = masks.fits(node_requests2, problem.node_avail)
        node_compat = vmap(
            lambda nr: masks.compatible_ok(nr, pod_req, lv, ln, no_allow)
        )(state.node_req)
        node_port_ok = ~jnp.any(state.node_used_ports & pod_conflict[None, :], axis=-1)
        node_vol_ok = jnp.all(
            state.node_vol_used + pod_vols[None, :] <= problem.node_vol_limits, axis=-1
        )
        node_merged = _intersect_rows(state.node_req, pod_req)
        node_topo_ok, node_final = topo_gate(
            problem, state.grp_counts, state.grp_registered, topo_pod, node_merged, no_allow
        )
        node_ok = tol_node & node_fit & node_compat & node_port_ok & node_vol_ok & node_topo_ok
        node_pick = _first_true(node_ok)
        any_node = jnp.any(node_ok)
        if N > 0:
            pick_n = jnp.minimum(node_pick, N - 1)
            node_final_row = node_final.row(pick_n)
            res_cap = _capacity(
                problem.node_avail[pick_n], state.node_requests[pick_n], pod_requests
            )
            if problem.pod_vol_counts.shape[1] > 0:
                vol_room = jnp.maximum(
                    (problem.node_vol_limits[pick_n] - state.node_vol_used[pick_n])
                    // jnp.maximum(pod_vols, 1),
                    0,
                )
                vol_cap = jnp.min(
                    jnp.where(pod_vols > 0, vol_room, _BIG_CAP)
                ).astype(jnp.int32)
            else:
                vol_cap = jnp.int32(_BIG_CAP)
            node_fit_count = jnp.minimum(jnp.minimum(res_cap, vol_cap), port_cap)
        else:
            node_final_row = _zeros_row()
            node_fit_count = jnp.int32(0)

        # -- open claims (same gates as _make_step)
        claim_compat = vmap(
            lambda cr: masks.compatible_ok(cr, pod_req, lv, ln, wellknown)
        )(state.claim_req)
        claim_merged = _intersect_rows(state.claim_req, pod_req)
        claim_topo_ok, claim_final = topo_gate(
            problem, state.grp_counts, state.grp_registered, topo_pod, claim_merged, wellknown
        )
        claim_requests2 = state.claim_requests + pod_requests[None, :]
        claim_it_ok2 = it_gate(claim_final, claim_requests2, state.claim_it_ok)
        claim_port_ok = ~jnp.any(state.claim_used_ports & pod_conflict[None, :], axis=-1)
        claim_ok = (
            state.claim_open
            & tol_tpl[state.claim_tpl]
            & claim_port_ok
            & claim_compat
            & claim_topo_ok
            & jnp.any(claim_it_ok2, axis=-1)
        )
        claim_rank = jnp.where(claim_ok, state.claim_npods * C + jnp.arange(C), _BIG)
        claim_pick = jnp.argmin(claim_rank)
        any_claim = jnp.any(claim_ok)
        rank2 = jnp.min(jnp.where(jnp.arange(C) == claim_pick, _BIG, claim_rank))
        claim_final_row = claim_final.row(claim_pick)
        itok_row = claim_it_ok2[claim_pick]
        cap_ct = _capacity(
            problem.it_alloc,
            state.claim_requests[claim_pick][None, :],
            pod_requests[None, :],
        )  # [T]
        claim_fit_count = jnp.minimum(
            jnp.max(jnp.where(itok_row, cap_ct, 0)), port_cap
        ).astype(jnp.int32)
        claim_npods0 = state.claim_npods[claim_pick]

        return (
            any_node,
            node_pick.astype(jnp.int32),
            node_final_row,
            node_fit_count,
            any_claim,
            claim_pick.astype(jnp.int32),
            rank2.astype(jnp.int32),
            claim_final_row,
            itok_row,
            cap_ct,
            claim_fit_count,
            claim_npods0,
            pod_is_active,
        )

    def eval_tpl_one(state: FFDState, free_slot, host_onehot, pod):
        pod_req, pod_requests, tol_tpl = pod[0], pod[2], pod[3]
        topo_pod = topo_of(pod)
        reg_for_tpl = state.grp_registered | (
            (problem.grp_key == HOSTNAME_KEY)[:, None] & host_onehot[None, :]
        )
        tpl_requests2 = problem.tpl_overhead + pod_requests[None, :]
        # shared helper so the mint/pin semantics can never diverge between
        # the per-pod step, the run commit, and this sweeps path
        tpl_merged, tpl_compat, _host = _fresh_template_rows(
            problem, lv, ln, wellknown, pod_req, free_slot
        )
        tpl_topo_ok, tpl_final = topo_gate(
            problem, state.grp_counts, reg_for_tpl, topo_pod, tpl_merged, wellknown
        )
        within_limits = masks.fits(
            problem.it_cap[None, :, :], state.remaining[:, None, :]
        )
        tpl_it_ok2 = it_gate(tpl_final, tpl_requests2, problem.tpl_it_ok & within_limits)
        tpl_ok = tol_tpl & tpl_compat & tpl_topo_ok & jnp.any(tpl_it_ok2, axis=-1)
        tpl_pick = _first_true(tpl_ok)
        pick_c = jnp.minimum(tpl_pick, TPL - 1)
        tpl_row_it_ok = tpl_it_ok2[pick_c]
        max_cap = jnp.max(
            jnp.where(tpl_row_it_ok[:, None], problem.it_cap, 0.0), axis=0
        )
        return (
            jnp.any(tpl_ok),
            tpl_pick.astype(jnp.int32),
            tpl_final.row(pick_c),
            tpl_requests2[pick_c],
            tpl_row_it_ok,
            max_cap,
        )

    def chain_ahead(queue, i, qlen, p):
        """True when the NEXT queue entry extends a gate-identical chain from
        the cursor — the narrow loop's exit test (cheap: three gathers)."""
        nxt_in = (i + 1) < qlen
        qn = queue[jnp.clip(i + 1, 0, P - 1)]
        return nxt_in & (qn == p + 1) & eqgate_arr[jnp.clip(p + 1, 0, P - 1)]

    def analytic_iter(state, queue, i, qlen, kinds, idxs, nq, nqlen):
        """Commit one whole gate-identical chain (>= 1 pods) via the
        closed-form waterfill run commit (record sum included)."""
        p = queue[jnp.clip(i, 0, P - 1)]
        pod = jax.tree_util.tree_map(lambda a: a[p], pods_xs)
        ahead = queue[jnp.clip(i + Srange, 0, P - 1)]  # [S]
        adj = (ahead == p + Srange) & ((i + Srange) < qlen)
        succ = jnp.clip(p + Srange, 0, P - 1)
        gate_chain = lax.cummin(
            (adj & ((Srange == 0) | eqgate_arr[succ])).astype(jnp.int32)
        ).astype(bool)
        k_gate = gate_chain.sum().astype(jnp.int32)
        state, (kind_row, index_row) = run_commit(
            state, pod, p, k_gate, active_concat
        )
        covered = Srange < k_gate
        rows = p + Srange
        out_idx = jnp.where(covered, rows, P + 1)
        kinds = kinds.at[out_idx].set(kind_row, mode="drop")
        idxs = idxs.at[out_idx].set(index_row, mode="drop")
        requeue = covered & (kind_row == KIND_FAIL)
        frank = jnp.cumsum(requeue.astype(jnp.int32)) - 1
        nq_idx = jnp.where(requeue, nqlen + frank, P + 1)
        nq = nq.at[nq_idx].set(rows, mode="drop")
        nqlen = nqlen + requeue.sum().astype(jnp.int32)
        noslot = jnp.any(covered & (kind_row == KIND_NO_SLOT))
        return state, kinds, idxs, nq, nqlen, k_gate, noslot

    def narrow_iter(state, queue, i, qlen, kinds, idxs, nq, nqlen):
        """One exact narrow step, batched over the strict-identical chain
        where verdict replication is provable (FAIL/NO_SLOT always;
        placements while capacity and fewest-pods rank hold and no
        record->gate feedback is possible)."""
        p = queue[jnp.clip(i, 0, P - 1)]
        pod = jax.tree_util.tree_map(lambda a: a[p], pods_xs)
        ahead = queue[jnp.clip(i + Srange, 0, P - 1)]
        adj = (ahead == p + Srange) & ((i + Srange) < qlen)
        succ = jnp.clip(p + Srange, 0, P - 1)
        strict_chain = lax.cummin(
            (adj & ((Srange == 0) | eqprev_arr[succ])).astype(jnp.int32)
        ).astype(bool)
        k_strict = strict_chain.sum().astype(jnp.int32)

        (
            any_node,
            node_pick,
            node_row,
            node_fit_count,
            any_claim,
            claim_pick,
            rank2,
            claim_row,
            itok_row,
            cap_ct,
            claim_fit_count,
            claim_npods0,
            active,
        ) = eval_base(state, pod)

        free_slot = _first_true(~state.claim_open)
        has_slot = jnp.any(~state.claim_open)
        host_onehot = _mint_host_onehot(problem, free_slot)
        need_tpl = (~any_node) & (~any_claim) & has_slot & active

        def do_tpl():
            return eval_tpl_one(state, free_slot, host_onehot, pod)

        def skip_tpl():
            return (
                jnp.bool_(False),
                jnp.int32(0),
                _zeros_row(),
                jnp.zeros((R,), problem.tpl_overhead.dtype),
                jnp.zeros((T,), bool),
                jnp.zeros((R,), problem.it_cap.dtype),
            )

        any_tpl, tpl_pick, slot_req, tpl_req_row, tpl_itok, max_cap = lax.cond(
            need_tpl, do_tpl, skip_tpl
        )

        kind = jnp.where(
            any_node,
            KIND_NODE,
            jnp.where(
                any_claim,
                KIND_CLAIM,
                jnp.where(
                    ~has_slot,
                    KIND_NO_SLOT,
                    jnp.where(any_tpl, KIND_NEW_CLAIM, KIND_FAIL),
                ),
            ),
        ).astype(jnp.int32)
        kind = jnp.where(active, kind, KIND_FAIL)
        index = jnp.where(
            kind == KIND_NODE,
            node_pick,
            jnp.where(
                kind == KIND_CLAIM,
                claim_pick,
                jnp.where(kind == KIND_NEW_CLAIM, free_slot, -1),
            ),
        ).astype(jnp.int32)
        placed = kind < KIND_FAIL
        is_open = kind == KIND_NEW_CLAIM

        # stacking within a strict-identical chain: FAIL / NO_SLOT verdicts
        # replicate for free; placed pods stack into the chosen bin while
        # capacity and (for claims) the fewest-pods rank hold, and only when
        # record->gate feedback is impossible (regular affinity groups are
        # monotone-safe; see _make_stride docstring)
        match, selects, owned = pod[7], pod[8], pod[9]
        if G > 0:
            aff_safe = (problem.grp_type == 1) & ~problem.grp_inverse
            stack_safe = ~jnp.any(match & (selects | owned) & ~aff_safe)
        else:
            stack_safe = jnp.bool_(True)
        j_rank = jnp.where(
            kind == KIND_CLAIM,
            (rank2 - 1 - index) // C - claim_npods0 + 1,
            jnp.int32(_BIG_CAP),
        ).astype(jnp.int32)
        fitc = jnp.where(kind == KIND_NODE, node_fit_count, claim_fit_count)
        k_placed = jnp.where(
            is_open,
            1,
            jnp.where(stack_safe, jnp.minimum(fitc, j_rank), 1),
        )
        k = jnp.maximum(
            jnp.minimum(k_strict, jnp.where(placed, k_placed, _BIG_CAP)),
            1,
        ).astype(jnp.int32)

        # ---- commit k pods into the one chosen bin
        pod_requests = pod[2]
        pod_ports = pod[5]
        pod_vols = pod[10]
        kf = k.astype(jnp.float32)

        is_claim = kind == KIND_CLAIM
        cidx = jnp.where(is_claim, index, C + 1)
        new_claim_req = ReqTensor(
            admitted=state.claim_req.admitted.at[cidx].set(claim_row.admitted, mode="drop"),
            comp=state.claim_req.comp.at[cidx].set(claim_row.comp, mode="drop"),
            gt=state.claim_req.gt.at[cidx].set(claim_row.gt, mode="drop"),
            lt=state.claim_req.lt.at[cidx].set(claim_row.lt, mode="drop"),
            defined=state.claim_req.defined.at[cidx].set(claim_row.defined, mode="drop"),
        )
        new_claim_requests = state.claim_requests.at[cidx].add(
            kf * pod_requests, mode="drop"
        )
        new_claim_it_ok = state.claim_it_ok.at[cidx].set(
            itok_row & (cap_ct >= k), mode="drop"
        )
        new_claim_npods = state.claim_npods.at[cidx].add(k, mode="drop")
        new_claim_ports = state.claim_used_ports.at[cidx].max(pod_ports, mode="drop")

        if N > 0:
            is_node = kind == KIND_NODE
            nodex = jnp.where(is_node, index, N + 1)
            new_node_req = ReqTensor(
                admitted=state.node_req.admitted.at[nodex].set(node_row.admitted, mode="drop"),
                comp=state.node_req.comp.at[nodex].set(node_row.comp, mode="drop"),
                gt=state.node_req.gt.at[nodex].set(node_row.gt, mode="drop"),
                lt=state.node_req.lt.at[nodex].set(node_row.lt, mode="drop"),
                defined=state.node_req.defined.at[nodex].set(node_row.defined, mode="drop"),
            )
            new_node_requests = state.node_requests.at[nodex].add(
                kf * pod_requests, mode="drop"
            )
            new_node_npods = state.node_npods.at[nodex].add(k, mode="drop")
            new_node_ports = state.node_used_ports.at[nodex].max(pod_ports, mode="drop")
            new_node_vol = state.node_vol_used.at[nodex].add(k * pod_vols, mode="drop")
        else:
            new_node_req = state.node_req
            new_node_requests = state.node_requests
            new_node_npods = state.node_npods
            new_node_ports = state.node_used_ports
            new_node_vol = state.node_vol_used

        # the (alone-committing) claim-open
        sidx = jnp.where(is_open, free_slot, C + 1)
        new_claim_req = ReqTensor(
            admitted=new_claim_req.admitted.at[sidx].set(slot_req.admitted, mode="drop"),
            comp=new_claim_req.comp.at[sidx].set(slot_req.comp, mode="drop"),
            gt=new_claim_req.gt.at[sidx].set(slot_req.gt, mode="drop"),
            lt=new_claim_req.lt.at[sidx].set(slot_req.lt, mode="drop"),
            defined=new_claim_req.defined.at[sidx].set(slot_req.defined, mode="drop"),
        )
        new_claim_requests = new_claim_requests.at[sidx].set(tpl_req_row, mode="drop")
        new_claim_it_ok = new_claim_it_ok.at[sidx].set(tpl_itok, mode="drop")
        new_claim_open = state.claim_open.at[sidx].set(True, mode="drop")
        new_claim_npods = new_claim_npods.at[sidx].add(1, mode="drop")
        new_claim_tpl = state.claim_tpl.at[sidx].set(tpl_pick, mode="drop")
        new_claim_ports = new_claim_ports.at[sidx].max(pod_ports, mode="drop")
        opened_tpl_hot = (jnp.arange(TPL) == tpl_pick) & is_open
        new_remaining = jnp.where(
            opened_tpl_hot[:, None],
            state.remaining - max_cap[None, :],
            state.remaining,
        )
        new_registered = state.grp_registered | (
            is_open
            & mint_hostnames
            & (problem.grp_key == HOSTNAME_KEY)[:, None]
            & host_onehot[None, :]
        )

        # topology record: identical stack members record identical deltas
        if G > 0:
            rec_needed = placed & (jnp.any(selects) | jnp.any(owned))

            def do_record():
                rec_row = claim_row
                rec_row = jax.tree_util.tree_map(
                    lambda s, c: jnp.where(is_open, s, c), slot_req, rec_row
                )
                if N > 0:
                    rec_row = jax.tree_util.tree_map(
                        lambda n, c: jnp.where(kind == KIND_NODE, n, c),
                        node_row,
                        rec_row,
                    )
                allow = jnp.where(kind == KIND_NODE, no_allow, wellknown)
                delta = record_delta(
                    problem, topo_of(pod), rec_row, allow, jnp.bool_(True), lv, ln
                )
                return k * delta.astype(jnp.int32), delta

            counts_add, reg_add = lax.cond(
                rec_needed,
                do_record,
                lambda: (
                    jnp.zeros((G, V), jnp.int32),
                    jnp.zeros((G, V), bool),
                ),
            )
            new_counts = state.grp_counts + counts_add
            new_registered = new_registered | reg_add
        else:
            new_counts = state.grp_counts

        new_state = FFDState(
            claim_req=new_claim_req,
            claim_requests=new_claim_requests,
            claim_it_ok=new_claim_it_ok,
            claim_open=new_claim_open,
            claim_npods=new_claim_npods,
            claim_tpl=new_claim_tpl,
            claim_used_ports=new_claim_ports,
            node_req=new_node_req,
            node_requests=new_node_requests,
            node_npods=new_node_npods,
            node_used_ports=new_node_ports,
            node_vol_used=new_node_vol,
            remaining=new_remaining,
            grp_counts=new_counts,
            grp_registered=new_registered,
        )
        covered = Srange < k
        kind_row = jnp.where(covered, kind, KIND_FAIL)
        index_row = jnp.where(covered, index, -1)
        rows = p + Srange
        out_idx = jnp.where(covered, rows, P + 1)
        kinds = kinds.at[out_idx].set(kind_row, mode="drop")
        idxs = idxs.at[out_idx].set(index_row, mode="drop")
        requeue = covered & (kind_row == KIND_FAIL)
        frank = jnp.cumsum(requeue.astype(jnp.int32)) - 1
        nq_idx = jnp.where(requeue, nqlen + frank, P + 1)
        nq = nq.at[nq_idx].set(rows, mode="drop")
        nqlen = nqlen + requeue.sum().astype(jnp.int32)
        noslot = jnp.any(covered & (kind_row == KIND_NO_SLOT))
        return new_state, kinds, idxs, nq, nqlen, k, noslot

    return narrow_iter, analytic_iter, chain_ahead


def _sweeps_impl(problem: SchedulingProblem, init: FFDState, C: int) -> FFDResult:
    """All retry passes of a solve in ONE device program.

    The reference's Solve loop requeues failed pods and retries while any
    placement makes progress (scheduler.go:150-170) — a pod whose required
    pod-affinity peers were placed later in the queue succeeds on the next
    pass. The host loop used to pay one device roundtrip per pass; here the
    requeue-until-no-progress loop IS the program: an outer while over
    sweeps; inside a sweep, a narrow-step loop walks the compact queue of
    still-unplaced pods and EXITS at every gate-identical chain boundary,
    where the closed-form analytic commit (_make_stride's analytic_iter)
    consumes the whole chain at once. Splitting the two at loop level keeps
    the narrow body free of a large-state conditional — a per-step
    lax.cond carrying the full FFDState measured ~80us/step in copies.
    Relaxation (preferences.py) stays host-side — it mutates pod specs and
    re-encodes — so a solve with relaxable pods costs one launch per ladder
    rung, and the common no-relaxation solve costs exactly one.

    Exactness vs the pass-per-launch loop: pods are processed in exactly the
    sequential queue order — the chain commits are provably equivalent to
    stepping their members one at a time (waterfill + record sum for
    topology-blind identical pods; verdict replication for strict-identical
    pods); KIND_NO_SLOT stops sweeping so the backend's slot-doubling retry
    sees it at the same pass boundary it used to.
    """
    P = problem.num_pods
    pods_xs = _pod_xs(problem)
    narrow_iter, analytic_iter, chain_ahead = _make_stride(
        problem, _statics(problem), C, _STRIDE, pods_xs
    )
    active = jnp.asarray(problem.pod_active)
    # compact initial queue: active rows first, original (FFD) order kept —
    # padding rows are never stepped at all, so bucket padding costs compile
    # cache entries but zero runtime
    queue0 = jnp.argsort(~active, stable=True).astype(jnp.int32)
    qlen0 = jnp.sum(active).astype(jnp.int32)
    kinds0 = jnp.full((P,), KIND_FAIL, jnp.int32)
    idxs0 = jnp.full((P,), -1, jnp.int32)

    def sweep_cond(c):
        _state, _queue, qlen, _kinds, _idxs, progress, noslot = c
        return progress & (qlen > 0) & ~noslot

    def sweep_body(c):
        state, queue, qlen, kinds, idxs, _progress, noslot0 = c
        i0 = (
            jnp.int32(0),
            state,
            jnp.zeros((P,), jnp.int32),
            jnp.int32(0),
            kinds,
            idxs,
            noslot0,
        )

        if _CHAIN_DISPATCH:
            # EXPERIMENTAL two-level structure: a narrow-step loop that
            # exits at gate-identical chain boundaries, with the analytic
            # waterfill commit consuming each whole chain. Measured on TPU
            # v5e (10k bench): the extra control flow costs MORE than the
            # chain commits save — XLA stops keeping the carried FFDState
            # in place across the nested while/cond boundaries and copies
            # it per iteration (flat loop 1.03s, this structure 1.43s, the
            # same chains behind a per-step cond 1.49s). Kept behind
            # KARPENTER_TPU_CHAIN_DISPATCH=1 for future XLA versions.
            def seg_cond(sc):
                i = sc[0]
                return i < qlen

            def seg_body(sc):
                i, state, nq, nqlen, kinds, idxs, noslot = sc

                def ncond(nc):
                    i = nc[0]
                    p = queue[jnp.clip(i, 0, P - 1)]
                    return (i < qlen) & ~chain_ahead(queue, i, qlen, p)

                def nbody(nc):
                    i, state, nq, nqlen, kinds, idxs, noslot = nc
                    state, kinds, idxs, nq, nqlen, k, nosl = narrow_iter(
                        state, queue, i, qlen, kinds, idxs, nq, nqlen
                    )
                    return i + k, state, nq, nqlen, kinds, idxs, noslot | nosl

                i, state, nq, nqlen, kinds, idxs, noslot = lax.while_loop(
                    ncond, nbody, (i, state, nq, nqlen, kinds, idxs, noslot)
                )

                def do_chain():
                    st, kk, ii, q, ql, k, nosl = analytic_iter(
                        state, queue, i, qlen, kinds, idxs, nq, nqlen
                    )
                    return i + k, st, q, ql, kk, ii, noslot | nosl

                def no_chain():
                    return i, state, nq, nqlen, kinds, idxs, noslot

                return lax.cond(i < qlen, do_chain, no_chain)

            _i, state, nq, nqlen, kinds, idxs, noslot = lax.while_loop(
                seg_cond, seg_body, i0
            )
        else:
            # flat production loop: ONE iteration shape, no in-loop
            # branching over the carried state — XLA keeps every FFDState
            # buffer in place across iterations
            def inner_cond(ic):
                i = ic[0]
                return i < qlen

            def inner_body(ic):
                i, state, nq, nqlen, kinds, idxs, noslot = ic
                state, kinds, idxs, nq, nqlen, k, nosl = narrow_iter(
                    state, queue, i, qlen, kinds, idxs, nq, nqlen
                )
                return i + k, state, nq, nqlen, kinds, idxs, noslot | nosl

            _i, state, nq, nqlen, kinds, idxs, noslot = lax.while_loop(
                inner_cond, inner_body, i0
            )
        progress = nqlen < qlen
        return state, nq, nqlen, kinds, idxs, progress, noslot

    state, _queue, _qlen, kinds, idxs, _prog, _noslot = lax.while_loop(
        sweep_cond,
        sweep_body,
        (init, queue0, qlen0, kinds0, idxs0, jnp.bool_(True), jnp.bool_(False)),
    )
    return FFDResult(kind=kinds, index=idxs, state=state)


@functools.partial(jax.jit, static_argnums=(1,))
def _solve_ffd_sweeps_fresh_jit(problem: SchedulingProblem, max_claims: int) -> FFDResult:
    problem = _pad_lanes_mult32(problem)
    return _sweeps_impl(problem, initial_state(problem, max_claims), max_claims)


def solve_ffd_sweeps(
    problem: SchedulingProblem, max_claims: int, init: Optional[FFDState] = None
) -> FFDResult:
    """Run ALL retry passes to convergence in one device launch (see
    _sweeps_impl). The production provisioning entrypoint. Always starts from
    a fresh state: the backend's sweeps mode never carries state across
    launches (nothing is relaxable, so there is no second launch)."""
    assert init is None, "sweeps mode always runs a whole solve in one launch"
    return _solve_ffd_sweeps_fresh_jit(problem, max_claims)


# integer "unbounded" sentinel for analytic pod-count capacities; large enough
# to never bind, small enough that int32 level arithmetic can't overflow
_BIG_CAP = 2**20


def _capacity(avail, used, req):
    """Integer count of additional identical pods with requests ``req`` that
    fit in ``avail - used`` (trailing resource axis), honoring fits()'s float
    tolerance: max j with used + j*req <= avail + eps — the closed form of
    iterating the per-pod fit check. Zero-request dims still gate: fits()
    fails on an already-overcommitted dim even when the pod adds nothing to
    it (and the -1 removed/padded-bin sentinel must reject every pod)."""
    eps = 1e-6 + 1e-6 * jnp.abs(avail)
    room = avail + eps - used
    roomf = room / jnp.where(req > 0, req, 1.0)
    per_r = jnp.where(req > 0, jnp.floor(roomf), jnp.float32(_BIG_CAP))
    zero_ok = jnp.all((req > 0) | (room >= 0), axis=-1)
    cap = jnp.clip(jnp.min(per_r, axis=-1), 0, _BIG_CAP).astype(jnp.int32)
    return jnp.where(zero_ok, cap, 0)


def _water_level(levels, caps, units, iters=22):
    """Largest integer L with sum(clip(L - levels, 0, caps)) <= units — the
    common fill level after pouring ``units`` one-by-one into the bin with the
    lowest level (argmin with index tie-break), each bin bounded by its cap.
    ``levels``/``caps`` are 1-D [C]; ``units`` may be any shape (the search
    runs elementwise over it)."""
    lo = jnp.zeros_like(units)
    hi = jnp.full_like(units, 2 * _BIG_CAP)

    def bs(_, lohi):
        lo, hi = lohi
        mid = (lo + hi + 1) // 2
        used = jnp.sum(jnp.clip(mid[..., None] - levels, 0, caps), axis=-1)
        ok = used <= units
        return jnp.where(ok, mid, lo), jnp.where(ok, hi, mid - 1)

    lo, hi = lax.fori_loop(0, iters, bs, (lo, hi))
    return lo


def _make_run_commit(problem: SchedulingProblem, statics, C: int, max_run: int):
    """The analytic multi-pod commit: one scan step places an entire run of
    identical, topology-inert pods, reproducing the per-pod step's outcome
    (including each pod's (kind, index) in temporal order) in closed form.

    Correctness argument, phase by phase (all against _make_step's semantics):
      nodes   — a pod takes the FIRST node that passes the static gates with
                room, so k pods fill nodes in index order up to each node's
                integer capacity: cumsum fill. Narrowing commits are
                idempotent for identical pods.
      claims  — a pod takes the open claim with the FEWEST pods (index
                tie-break), i.e. pods waterfill claim levels bounded by each
                claim's capacity (max over surviving instance types of how
                many more such pods fit). The temporal order of assignments
                is (level-before, claim index) lexicographic — recovered per
                ordinal to keep exact per-pod parity with the oracle.
      opens   — pods that exhaust claim capacity open fresh template claims
                one at a time; each opened claim absorbs pods up to its own
                capacity before the next opens (it is the unique unsaturated
                claim), so openings assign consecutive ordinal blocks in
                slot order. Limit headroom burns once per open (subtractMax,
                scheduler.go:347-364).
    """
    lv, ln, wellknown, no_allow, it_packed, it_neg = statics
    N = problem.num_nodes
    T = problem.num_instance_types
    TPL = problem.num_templates
    K = problem.num_keys
    V = problem.num_lanes
    D = problem.pod_vol_counts.shape[1]
    mint_hostnames = problem.claim_hostname_lane.shape[0] > 0

    def has_offering_rows(admitted):
        return _offer_rows(problem, admitted)

    def commit(state: FFDState, pod, start, length, active_arr):
        (
            pod_req,
            _pod_strict,
            pod_requests,
            tol_tpl,
            tol_node,
            pod_ports,
            pod_conflict,
            _gm,
            _gs,
            _go,
            pod_vols,
            _pa,
        ) = pod
        win = jnp.arange(max_run)
        act = lax.dynamic_slice(active_arr, (start,), (max_run,)) & (win < length)
        k = act.sum().astype(jnp.int32)
        ordinal = (jnp.cumsum(act) - 1).astype(jnp.int32)  # [MR]
        port_cap = jnp.where(jnp.any(pod_ports), 1, _BIG_CAP).astype(jnp.int32)

        # ---- 1. existing nodes: first-fit fill in node order
        if N > 0:
            node_merged = _intersect_rows(state.node_req, pod_req)
            node_compat = vmap(
                lambda nr: masks.compatible_ok(nr, pod_req, lv, ln, no_allow)
            )(state.node_req)
            node_port_ok = ~jnp.any(state.node_used_ports & pod_conflict[None, :], axis=-1)
            if D > 0:
                # clamp: pre-existing over-limit attach counts read as 0
                # capacity, not negative (the per-pod gate simply fails)
                vol_room = jnp.maximum(
                    (problem.node_vol_limits - state.node_vol_used)
                    // jnp.maximum(pod_vols[None, :], 1),
                    0,
                )
                vol_cap = jnp.min(
                    jnp.where(pod_vols[None, :] > 0, vol_room, _BIG_CAP), axis=-1
                ).astype(jnp.int32)
            else:
                vol_cap = jnp.full((N,), _BIG_CAP, jnp.int32)
            res_cap = _capacity(
                problem.node_avail, state.node_requests, pod_requests[None, :]
            )
            node_ok = tol_node & node_compat & node_port_ok
            ncap = jnp.where(node_ok, jnp.minimum(jnp.minimum(res_cap, vol_cap), port_cap), 0)
            ncum = jnp.cumsum(ncap)
            placed_n = jnp.minimum(k, ncum[-1])
            node_take = jnp.clip(k - (ncum - ncap), 0, ncap)
            took_n = node_take > 0
            new_node_req = _mix_req_rows(state.node_req, node_merged, took_n)
            new_node_requests = state.node_requests + node_take[:, None] * pod_requests[None, :]
            new_node_npods = state.node_npods + node_take
            new_node_ports = state.node_used_ports | (took_n[:, None] & pod_ports[None, :])
            new_node_vol = state.node_vol_used + node_take[:, None] * pod_vols[None, :]
            node_of = jnp.searchsorted(ncum, ordinal, side="right").astype(jnp.int32)
        else:
            placed_n = jnp.int32(0)
            node_of = jnp.zeros((max_run,), jnp.int32)
            new_node_req = state.node_req
            new_node_requests = state.node_requests
            new_node_npods = state.node_npods
            new_node_ports = state.node_used_ports
            new_node_vol = state.node_vol_used
        rem = k - placed_n

        # ---- 2. open claims: fewest-pods waterfill bounded by capacity
        claim_merged = _intersect_rows(state.claim_req, pod_req)
        claim_compat = vmap(
            lambda cr: masks.compatible_ok(cr, pod_req, lv, ln, wellknown)
        )(state.claim_req)
        claim_port_ok = ~jnp.any(state.claim_used_ports & pod_conflict[None, :], axis=-1)
        m_packed = masks.pack_lanes(claim_merged.admitted)
        m_neg = vmap(lambda r: masks.negative_polarity(r, lv, ln))(claim_merged)
        itc = masks.packed_pairwise_compat(
            claim_merged, m_packed, m_neg, problem.it_reqs, it_packed, it_neg
        )  # [C, T]
        itok = state.claim_it_ok & itc & has_offering_rows(claim_merged.admitted)
        cap_ct = _capacity(
            problem.it_alloc[None, :, :],
            state.claim_requests[:, None, :],
            pod_requests[None, None, :],
        )  # [C, T]
        cap_c = jnp.max(jnp.where(itok, cap_ct, 0), axis=-1)
        elig = (
            state.claim_open
            & tol_tpl[state.claim_tpl]
            & claim_compat
            & claim_port_ok
        )
        cap_c = jnp.where(elig, jnp.minimum(cap_c, port_cap), 0)
        p_lvl = state.claim_npods
        m = jnp.minimum(rem, cap_c.sum())
        L = _water_level(p_lvl, cap_c, m)
        take0 = jnp.clip(L - p_lvl, 0, cap_c)
        leftover = m - take0.sum()
        at_level = (p_lvl + take0 == L) & (take0 < cap_c)
        extra = at_level & (jnp.cumsum(at_level) <= leftover)
        claim_take = take0 + extra.astype(jnp.int32)
        tookc = claim_take > 0
        i_claim_req = _mix_req_rows(state.claim_req, claim_merged, tookc)
        i_requests = state.claim_requests + claim_take[:, None] * pod_requests[None, :]
        i_npods = state.claim_npods + claim_take
        i_itok = jnp.where(tookc[:, None], itok & (cap_ct >= claim_take[:, None]), state.claim_it_ok)
        i_ports = state.claim_used_ports | (tookc[:, None] & pod_ports[None, :])
        rem2 = rem - claim_take.sum()

        # temporal ordinal -> claim: assignments sort by (level-before, claim)
        jj = ordinal - placed_n
        lev = _water_level(p_lvl, claim_take, jnp.maximum(jj, 0))
        before = jnp.sum(
            jnp.clip(lev[:, None] - p_lvl[None, :], 0, claim_take[None, :]), axis=-1
        )
        pos = jnp.maximum(jj, 0) - before
        at_lev = (p_lvl[None, :] <= lev[:, None]) & (
            lev[:, None] < (p_lvl + claim_take)[None, :]
        )  # [MR, C]
        lev_cum = jnp.cumsum(at_lev, axis=-1)
        claim_of = jnp.argmax(at_lev & (lev_cum == (pos + 1)[:, None]), axis=-1).astype(
            jnp.int32
        )

        # ---- 3. fresh template claims, one open at a time. The heavy
        # template-side products are loop-invariant and hoisted out of the
        # open-loop: the merged rows, compat mask, [TPL, T] pairwise
        # instance-type compat, offerings, and per-pod capacities depend only
        # on (pod_req, pod_requests) — the minted-hostname pin (the one
        # free_slot-dependent piece of _fresh_template_rows) cannot change
        # them because instance types never constrain the hostname key (the
        # claim mints a fresh name precisely because nothing else names it,
        # nodeclaim.go:46-63); only the committed slot row must carry the pin
        tpl_merged_u = _intersect_rows(problem.tpl_reqs, pod_req)
        tpl_compat = vmap(
            lambda tr: masks.compatible_ok(tr, pod_req, lv, ln, wellknown)
        )(problem.tpl_reqs)
        t_packed = masks.pack_lanes(tpl_merged_u.admitted)
        t_neg = vmap(lambda r: masks.negative_polarity(r, lv, ln))(tpl_merged_u)
        itc_t = masks.packed_pairwise_compat(
            tpl_merged_u, t_packed, t_neg, problem.it_reqs, it_packed, it_neg
        )  # [TPL, T]
        cap_tt = _capacity(
            problem.it_alloc[None, :, :],
            problem.tpl_overhead[:, None, :],
            pod_requests[None, None, :],
        )  # [TPL, T]
        itok_t_static = (
            problem.tpl_it_ok
            & itc_t
            & has_offering_rows(tpl_merged_u.admitted)
            & (cap_tt >= 1)
        )

        def nc_cond(c):
            return c[0] & (c[1] > 0)

        def nc_body(c):
            (
                _keep,
                c_rem,
                c_req,
                c_requests,
                c_itok,
                c_open,
                c_npods,
                c_tpl,
                c_ports,
                c_remaining,
                c_registered,
                c_newtake,
                c_noslot,
            ) = c
            free_slot = _first_true(~c_open)
            has_slot = jnp.any(~c_open)
            host_onehot = _mint_host_onehot(problem, free_slot)
            within = masks.fits(problem.it_cap[None, :, :], c_remaining[:, None, :])
            itok_t = itok_t_static & within
            q_t = jnp.max(jnp.where(itok_t, cap_tt, 0), axis=-1)  # [TPL]
            tpl_ok = tol_tpl & tpl_compat & (q_t >= 1)
            pick = _first_true(tpl_ok)
            any_tpl = jnp.any(tpl_ok)
            pick_c = jnp.minimum(pick, TPL - 1)
            can = any_tpl & has_slot
            take = jnp.where(can, jnp.minimum(c_rem, jnp.minimum(q_t[pick_c], port_cap)), 0)
            slot_hot = (jnp.arange(C) == free_slot) & (take > 0)
            slot_req_u = tpl_merged_u.row(pick_c)
            # the committed claim row carries its minted hostname
            # (nodeclaim.go:46-63), exactly as _fresh_template_rows pins it
            slot_req = (
                _pin_hostname(slot_req_u, host_onehot) if mint_hostnames else slot_req_u
            )
            new_req = _mix_req_rows(
                c_req,
                ReqTensor(
                    admitted=jnp.broadcast_to(slot_req.admitted, (C, K, V)),
                    comp=jnp.broadcast_to(slot_req.comp, (C, K)),
                    gt=jnp.broadcast_to(slot_req.gt, (C, K)),
                    lt=jnp.broadcast_to(slot_req.lt, (C, K)),
                    defined=jnp.broadcast_to(slot_req.defined, (C, K)),
                ),
                slot_hot,
            )
            surv1 = itok_t[pick_c]  # [T] survivors with the first pod aboard
            new_itok = jnp.where(
                slot_hot[:, None], surv1[None, :] & (cap_tt[pick_c][None, :] >= take), c_itok
            )
            new_requests = jnp.where(
                slot_hot[:, None],
                (problem.tpl_overhead[pick_c] + take * pod_requests)[None, :],
                c_requests,
            )
            opened = take > 0
            opened_tpl_hot = (jnp.arange(TPL) == pick_c) & opened
            max_cap = jnp.max(jnp.where(surv1[:, None], problem.it_cap, 0.0), axis=0)
            new_remaining = jnp.where(
                opened_tpl_hot[:, None], c_remaining - max_cap[None, :], c_remaining
            )
            new_registered = c_registered | (
                opened
                & mint_hostnames
                & (problem.grp_key == HOSTNAME_KEY)[:, None]
                & host_onehot[None, :]
            )
            return (
                can,
                c_rem - take,
                new_req,
                new_requests,
                new_itok,
                c_open | slot_hot,
                c_npods + slot_hot * take,
                jnp.where(slot_hot, pick_c.astype(jnp.int32), c_tpl),
                c_ports | (slot_hot[:, None] & pod_ports[None, :]),
                new_remaining,
                new_registered,
                c_newtake + slot_hot * take,
                # ~has_slot alone: with no free slot the template verdict is
                # unreliable (see the step's kind classification) — always
                # signal NO_SLOT so the backend's slot-growth retry decides
                c_noslot | ~has_slot,
            )

        nc0 = (
            jnp.bool_(True),
            rem2,
            i_claim_req,
            i_requests,
            i_itok,
            state.claim_open,
            i_npods,
            state.claim_tpl,
            i_ports,
            state.remaining,
            state.grp_registered,
            jnp.zeros((C,), jnp.int32),
            jnp.bool_(False),
        )
        (
            _keep,
            rem3,
            f_claim_req,
            f_requests,
            f_itok,
            f_open,
            f_npods,
            f_tpl,
            f_ports,
            f_remaining,
            f_registered,
            new_take,
            noslot,
        ) = lax.while_loop(nc_cond, nc_body, nc0)
        placed_new = rem2 - rem3
        new_cum = jnp.cumsum(new_take)  # slot order == temporal opening order
        nc_ord = ordinal - placed_n - m  # ordinal within the new-claim phase
        newclaim_of = jnp.searchsorted(new_cum, nc_ord, side="right").astype(jnp.int32)
        # the pod that OPENS a slot reads KIND_NEW_CLAIM, later joiners
        # KIND_CLAIM — matching the per-pod step's labels exactly
        opens_slot = nc_ord == (new_cum - new_take)[jnp.minimum(newclaim_of, C - 1)]

        # ---- 4. per-row outputs, written into the run's queue window
        fail_kind = jnp.where(noslot, KIND_NO_SLOT, KIND_FAIL).astype(jnp.int32)
        kind_row = jnp.where(
            ~act,
            KIND_FAIL,
            jnp.where(
                ordinal < placed_n,
                KIND_NODE,
                jnp.where(
                    ordinal < placed_n + m,
                    KIND_CLAIM,
                    jnp.where(
                        ordinal < placed_n + m + placed_new,
                        jnp.where(opens_slot, KIND_NEW_CLAIM, KIND_CLAIM),
                        fail_kind,
                    ),
                ),
            ),
        ).astype(jnp.int32)
        # index by PHASE (new-phase joiners are labeled KIND_CLAIM but their
        # slot comes from the opening partition, not the waterfill)
        index_row = jnp.where(
            ~act,
            -1,
            jnp.where(
                ordinal < placed_n,
                node_of,
                jnp.where(
                    ordinal < placed_n + m,
                    claim_of,
                    jnp.where(ordinal < placed_n + m + placed_new, newclaim_of, -1),
                ),
            ),
        ).astype(jnp.int32)

        # ---- 5. record aggregation (Topology.Record, topology.go:125-148).
        # Run members are topology-BLIND (no matched/owned groups — run mode
        # rule in solver/encode.py) but may still be SELECTED by other pods'
        # groups; each placed member records its select mask against the
        # dom-lanes of the bin it landed on. Deltas never feed back into any
        # member's own gates, so they sum: member-per-bin counts contract
        # against per-bin dom masks. Identical to applying record() per pod.
        G = problem.grp_key.shape[0]
        new_counts = state.grp_counts
        if G > 0:
            sel_arr = jnp.concatenate(
                [jnp.asarray(problem.pod_grp_selects), jnp.zeros((max_run, G), bool)]
            )
            sel = lax.dynamic_slice(sel_arr, (start, 0), (max_run, G))  # [MR, G]
            placed_row = kind_row < KIND_FAIL
            B = N + C
            bin_of = jnp.where(kind_row == KIND_NODE, index_row, N + index_row)
            ob = placed_row[:, None] & (
                jnp.clip(bin_of, 0, B - 1)[:, None] == jnp.arange(B)[None, :]
            )  # [MR, B]
            cnt_bg = jnp.matmul(
                ob.astype(jnp.float32).T,
                sel.astype(jnp.float32),
                preferred_element_type=jnp.float32,
            )  # [B, G]
            if N > 0:
                radm = jnp.concatenate(
                    [new_node_req.admitted, f_claim_req.admitted], axis=0
                )
                rcomp = jnp.concatenate([new_node_req.comp, f_claim_req.comp], axis=0)
            else:
                radm, rcomp = f_claim_req.admitted, f_claim_req.comp
            dom = radm[:, problem.grp_key, :]  # [B, G, V]
            concrete = ~rcomp[:, problem.grp_key]  # [B, G]
            single = dom.sum(axis=-1) == 1
            spread_or_aff = (problem.grp_type == 0) | (problem.grp_type == 1)
            F = problem.grp_filter_valid.shape[1]
            if F > 0:
                if N > 0:
                    bin_rows = ReqTensor(
                        admitted=radm,
                        comp=rcomp,
                        gt=jnp.concatenate([new_node_req.gt, f_claim_req.gt], axis=0),
                        lt=jnp.concatenate([new_node_req.lt, f_claim_req.lt], axis=0),
                        defined=jnp.concatenate(
                            [new_node_req.defined, f_claim_req.defined], axis=0
                        ),
                    )
                    allow_b = jnp.concatenate(
                        [
                            jnp.zeros((N, no_allow.shape[0]), bool),
                            jnp.broadcast_to(wellknown, (C, wellknown.shape[0])),
                        ]
                    )
                else:
                    bin_rows = f_claim_req
                    allow_b = jnp.broadcast_to(wellknown, (C, wellknown.shape[0]))

                def bin_filt(row, allow):
                    def grp_filt(g):
                        terms = problem.grp_filter.row(g)
                        term_ok = vmap(
                            lambda t: masks.compatible_ok(row, t, lv, ln, allow)
                        )(terms)
                        return ~problem.grp_has_filter[g] | jnp.any(
                            problem.grp_filter_valid[g] & term_ok
                        )

                    return vmap(grp_filt)(jnp.arange(G))

                filt = vmap(bin_filt)(bin_rows, allow_b)  # [B, G]
            else:
                filt = jnp.ones((B, G), bool)
            dom_ok = (
                concrete
                & jnp.where(spread_or_aff[None, :], single, True)
                & filt
                & ~problem.grp_inverse[None, :]
            )
            dom_final = dom & dom_ok[:, :, None]  # [B, G, V]
            recorded = jnp.einsum(
                "bg,bgv->gv",
                cnt_bg,
                dom_final.astype(jnp.float32),
                preferred_element_type=jnp.float32,
            )
            new_counts = state.grp_counts + jnp.round(recorded).astype(jnp.int32)
            f_registered = f_registered | jnp.any(
                (cnt_bg[:, :, None] > 0.5) & dom_final, axis=0
            )

        new_state = FFDState(
            claim_req=f_claim_req,
            claim_requests=f_requests,
            claim_it_ok=f_itok,
            claim_open=f_open,
            claim_npods=f_npods,
            claim_tpl=f_tpl,
            claim_used_ports=f_ports,
            node_req=new_node_req,
            node_requests=new_node_requests,
            node_npods=new_node_npods,
            node_used_ports=new_node_ports,
            node_vol_used=new_node_vol,
            remaining=f_remaining,
            grp_counts=new_counts,
            grp_registered=f_registered,
        )
        return new_state, (kind_row, index_row)

    return commit


@functools.partial(jax.jit, static_argnums=(2, 3))
def _solve_ffd_runs_jit(
    problem: SchedulingProblem, init: FFDState, max_run: int, with_topo: bool
) -> FFDResult:
    """Run-compressed scan: one step per run of identical pods (encode.py
    segmentation). Topology-inert runs take the closed-form analytic commit,
    topology-interacting runs the light inner loop (ops/topo_runs.py), and
    length-1 runs the per-pod step. 10k diverse pods collapse to a few
    hundred steps. ``with_topo=False`` compiles the two-branch program —
    topology-free batches (the whole consolidation path) skip the topo
    branch's compile cost."""
    from karpenter_tpu.ops.topo_runs import make_topo_run_commit

    problem, init = _lane_align(problem, init)
    C = init.claim_open.shape[0]
    statics = _statics(problem)
    step = _make_step(problem, statics, C)
    commit = _make_run_commit(problem, statics, C, max_run)
    topo_commit = make_topo_run_commit(problem, statics, C, max_run) if with_topo else None
    P = problem.num_pods
    pods_xs = _pod_xs(problem)
    rep_xs = jax.tree_util.tree_map(lambda a: a[problem.run_start], pods_xs)
    # scratch tail so a window starting near P never clamps backwards
    active_arr = jnp.concatenate(
        [jnp.asarray(problem.pod_active), jnp.zeros((max_run,), dtype=bool)]
    )

    def outer(state, xs):
        rep, start, length, mode = xs

        def single(_):
            new_state, (kind, index) = step(state, rep)
            kind_row = jnp.full((max_run,), KIND_FAIL, jnp.int32).at[0].set(kind)
            index_row = jnp.full((max_run,), -1, jnp.int32).at[0].set(index)
            return new_state, (kind_row, index_row)

        def analytic(_):
            return commit(state, rep, start, length, active_arr)

        if with_topo:
            def topo(_):
                return topo_commit(state, rep, start, length, active_arr)

            return lax.switch(mode, (single, analytic, topo), None)
        return lax.switch(mode, (single, analytic), None)

    run_start = jnp.asarray(problem.run_start)
    run_len = jnp.asarray(problem.run_len)
    final_state, (kind_ys, index_ys) = lax.scan(
        outer,
        init,
        (rep_xs, run_start, run_len, jnp.asarray(problem.run_mode)),
        unroll=_UNROLL,
    )
    # scatter the per-run windows back into queue order; rows no run covers
    # (padding pods) keep KIND_FAIL. Windows are disjoint, so the masked
    # scatter writes each real row exactly once.
    RN = run_start.shape[0]
    win = jnp.arange(max_run)
    rows = run_start[:, None] + win[None, :]  # [RN, MR]
    valid = win[None, :] < run_len[:, None]
    target = jnp.where(valid, rows, P + max_run - 1)  # dump padding in scratch
    kinds = (
        jnp.full((P + max_run,), KIND_FAIL, jnp.int32)
        .at[target.ravel()]
        .set(kind_ys.ravel())
    )
    idxs = (
        jnp.full((P + max_run,), -1, jnp.int32).at[target.ravel()].set(index_ys.ravel())
    )
    return FFDResult(kind=kinds[:P], index=idxs[:P], state=final_state)


def max_run_bucket(problem: SchedulingProblem) -> int:
    """Static max-run window bucket for a (possibly stacked) problem —
    single definition shared with parallel/mesh.py."""
    import numpy as np

    from karpenter_tpu.ops.padding import pow2_bucket

    return pow2_bucket(int(np.max(np.asarray(problem.run_len), initial=1)), lo=1)


def has_topo_runs(problem: SchedulingProblem) -> bool:
    """Whether any run needs the topology inner-loop commit. MUST be threaded
    into _solve_ffd_runs_jit's static with_topo: lax.switch clamps an
    out-of-range mode index, so a RUN_TOPO run fed to the two-branch program
    silently takes the topology-ignoring analytic commit (the round-2
    21/64-seed parity regression)."""
    import numpy as np

    from karpenter_tpu.models.problem import RUN_TOPO

    return bool(np.any(np.asarray(problem.run_mode) == RUN_TOPO))


def solve_ffd_runs(
    problem: SchedulingProblem, max_claims: int, init: Optional[FFDState] = None
) -> FFDResult:
    """Run one pack pass through the run-compressed solver."""
    if init is None:
        return _solve_ffd_runs_fresh_jit(
            problem, max_claims, max_run_bucket(problem), has_topo_runs(problem)
        )
    return _solve_ffd_runs_jit(
        problem, init, max_run_bucket(problem), has_topo_runs(problem)
    )


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def _solve_ffd_runs_fresh_jit(
    problem: SchedulingProblem, max_claims: int, max_run: int, with_topo: bool
) -> FFDResult:
    """Fresh-state runs variant: initial_state traced into the program (one
    launch per solve; see _solve_ffd_fresh_jit)."""
    init = initial_state(_pad_lanes_mult32(problem), max_claims)
    return _solve_ffd_runs_jit(problem, init, max_run, with_topo)
