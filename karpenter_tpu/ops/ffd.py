"""First-fit-decreasing bin-pack as a lax.scan.

TPU-native re-design of the reference's Scheduler.Solve pod loop
(scheduler.go:140-189, :238-285): pods arrive pre-sorted by the FFD queue
order; one scan step places one pod. Placement *scoring* — which existing
nodes / open claims / fresh template claims could accept the pod — is computed
for every candidate at once with the vectorized mask kernels (the reference
walks them one by one, O(candidates × instanceTypes) set intersections per
pod); the *commit* stays sequential inside the scan because every placement
narrows the chosen bin's requirement state.

Placement priority per pod (scheduler.go:238-285):
  1. first existing node (pre-sorted initialized-first) that tolerates, fits,
     and is requirement-compatible (existingnode.go:64-124, strict Compatible);
  2. open claim with the fewest pods whose narrowed state keeps >= 1 instance
     type satisfying requirements + resources + offerings (nodeclaim.go:65-119);
  3. first template (weight order) whose fresh claim accepts the pod -> opens
     a new claim in the first free slot;
  4. otherwise the pod fails this pass (relaxation happens host-side).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax, vmap

from karpenter_tpu.models.problem import ReqTensor, SchedulingProblem
from karpenter_tpu.ops import masks

# placement kinds emitted per pod
KIND_NODE = 0
KIND_CLAIM = 1
KIND_NEW_CLAIM = 2
KIND_FAIL = 3
KIND_NO_SLOT = 4  # a fresh claim would accept the pod, but slots ran out

_BIG = jnp.int32(2**30)


@jax.tree_util.register_dataclass
@dataclass
class FFDState:
    claim_req: ReqTensor  # [C, K, V] narrowed requirement state per claim
    claim_requests: Any  # f32[C, R] accumulated requests (incl daemon overhead)
    claim_it_ok: Any  # bool[C, T] surviving instance types
    claim_open: Any  # bool[C]
    claim_npods: Any  # i32[C]
    claim_tpl: Any  # i32[C]
    node_req: ReqTensor  # [N, K, V] narrowed existing-node requirements
    node_requests: Any  # f32[N, R] accumulated requests (incl daemon overhead)
    node_npods: Any  # i32[N]


@jax.tree_util.register_dataclass
@dataclass
class FFDResult:
    kind: Any  # i32[P]
    index: Any  # i32[P] node index / claim slot (meaning depends on kind)
    state: FFDState  # final bin state


def _first_true(mask: jnp.ndarray) -> jnp.ndarray:
    """Index of the first True (or len(mask) when none)."""
    return jnp.argmax(jnp.concatenate([mask, jnp.array([True])]))


def _intersect_rows(reqs: ReqTensor, row: ReqTensor) -> ReqTensor:
    return vmap(lambda r: masks.intersect(r, row))(reqs)


def solve_ffd(problem: SchedulingProblem, max_claims: int) -> FFDResult:
    """Run the full pack. Shapes are static per (P, N, T, TPL, K, V, R,
    max_claims) bucket; XLA caches the compiled executable across batches."""
    return _solve_ffd_jit(problem, max_claims)


def _pad_lanes_mult32(problem: SchedulingProblem) -> SchedulingProblem:
    """Pad the value-lane axis to a multiple of 32 for bitpacking. Shape-static
    (plain Python under trace); ops/padding.py already does this for bucketed
    callers, so this is a no-op on the production path."""
    V = problem.num_lanes
    pad = (-V) % 32
    if pad == 0:
        return problem
    import dataclasses

    def pad_req(r: ReqTensor) -> ReqTensor:
        return dataclasses.replace(
            r, admitted=jnp.pad(r.admitted, [(0, 0)] * (r.admitted.ndim - 1) + [(0, pad)])
        )

    return dataclasses.replace(
        problem,
        lane_valid=jnp.pad(problem.lane_valid, [(0, 0), (0, pad)]),
        lane_numeric=jnp.pad(problem.lane_numeric, [(0, 0), (0, pad)], constant_values=jnp.nan),
        pod_reqs=pad_req(problem.pod_reqs),
        it_reqs=pad_req(problem.it_reqs),
        tpl_reqs=pad_req(problem.tpl_reqs),
        node_reqs=pad_req(problem.node_reqs),
    )


@functools.partial(jax.jit, static_argnums=(1,))
def _solve_ffd_jit(problem: SchedulingProblem, max_claims: int) -> FFDResult:
    problem = _pad_lanes_mult32(problem)
    P = problem.num_pods
    N = problem.num_nodes
    T = problem.num_instance_types
    TPL = problem.num_templates
    K = problem.num_keys
    V = problem.num_lanes
    R = problem.num_resources
    C = max_claims

    lv, ln = problem.lane_valid, problem.lane_numeric
    wellknown = problem.key_wellknown
    no_allow = jnp.zeros_like(wellknown)
    zone_k, ct_k = _zone_ct_static(problem)

    def empty_req(n):
        return ReqTensor(
            admitted=jnp.broadcast_to(lv, (n, K, V)),
            comp=jnp.ones((n, K), dtype=bool),
            gt=jnp.full((n, K), -(2**31) + 1, dtype=jnp.int32),
            lt=jnp.full((n, K), 2**31 - 1, dtype=jnp.int32),
            defined=jnp.zeros((n, K), dtype=bool),
        )

    init = FFDState(
        claim_req=empty_req(C),
        claim_requests=jnp.zeros((C, R), dtype=jnp.float32),
        claim_it_ok=jnp.zeros((C, T), dtype=bool),
        claim_open=jnp.zeros((C,), dtype=bool),
        claim_npods=jnp.zeros((C,), dtype=jnp.int32),
        claim_tpl=jnp.zeros((C,), dtype=jnp.int32),
        node_req=ReqTensor(
            admitted=jnp.asarray(problem.node_reqs.admitted),
            comp=jnp.asarray(problem.node_reqs.comp),
            gt=jnp.asarray(problem.node_reqs.gt),
            lt=jnp.asarray(problem.node_reqs.lt),
            defined=jnp.asarray(problem.node_reqs.defined),
        ),
        node_requests=jnp.asarray(problem.node_overhead),
        node_npods=jnp.zeros((N,), dtype=jnp.int32),
    )

    # instance-type side of the hot compat product: packed lanes + polarity,
    # computed once per solve (instance types never change during a pack)
    it_packed = masks.pack_lanes(jnp.asarray(problem.it_reqs.admitted))  # [T, K, W]
    it_neg = vmap(lambda r: masks.negative_polarity(r, lv, ln))(problem.it_reqs)

    def it_gate(state_rows: ReqTensor, requests: jnp.ndarray, prior_ok: jnp.ndarray):
        """[B, T] mask of instance types surviving a hypothetical narrowed
        state + accumulated requests (nodeclaim.go:225-260: requirements,
        fits, offerings)."""
        state_packed = masks.pack_lanes(state_rows.admitted)  # [B, K, W]
        state_neg = vmap(lambda r: masks.negative_polarity(r, lv, ln))(state_rows)
        compat = masks.packed_pairwise_compat(
            state_rows, state_packed, state_neg, problem.it_reqs, it_packed, it_neg
        )  # [B, T]
        fit = masks.fits(requests[:, None, :], problem.it_alloc[None, :, :])  # [B, T]
        offer = vmap(
            lambda adm: masks.has_offering(
                adm, zone_k, ct_k, problem.offer_zone, problem.offer_ct, problem.offer_ok
            )
        )(state_rows.admitted)  # [B, T]
        return prior_ok & compat & fit & offer

    def step(state: FFDState, pod):
        pod_req, pod_requests, tol_tpl, tol_node = pod

        # -- 1. existing nodes (scheduler.go:240-244)
        node_requests2 = state.node_requests + pod_requests[None, :]
        node_fit = masks.fits(node_requests2, problem.node_avail)
        node_compat = vmap(
            lambda nr: masks.compatible_ok(nr, pod_req, lv, ln, no_allow)
        )(state.node_req)
        node_ok = tol_node & node_fit & node_compat
        node_pick = _first_true(node_ok)
        any_node = jnp.any(node_ok)

        # -- 2. open claims, fewest pods first (scheduler.go:247-254)
        claim_new_req = _intersect_rows(state.claim_req, pod_req)
        claim_compat = vmap(
            lambda cr: masks.compatible_ok(cr, pod_req, lv, ln, wellknown)
        )(state.claim_req)
        claim_requests2 = state.claim_requests + pod_requests[None, :]
        claim_it_ok2 = it_gate(claim_new_req, claim_requests2, state.claim_it_ok)
        claim_ok = (
            state.claim_open
            & tol_tpl[state.claim_tpl]
            & claim_compat
            & jnp.any(claim_it_ok2, axis=-1)
        )
        claim_rank = jnp.where(claim_ok, state.claim_npods * C + jnp.arange(C), _BIG)
        claim_pick = jnp.argmin(claim_rank)
        any_claim = jnp.any(claim_ok)

        # -- 3. fresh claim from templates, weight order (scheduler.go:256-283)
        tpl_new_req = _intersect_rows(problem.tpl_reqs, pod_req)
        tpl_compat = vmap(
            lambda tr: masks.compatible_ok(tr, pod_req, lv, ln, wellknown)
        )(problem.tpl_reqs)
        tpl_requests2 = problem.tpl_overhead + pod_requests[None, :]
        tpl_it_ok2 = it_gate(tpl_new_req, tpl_requests2, problem.tpl_it_ok)
        tpl_ok = tol_tpl & tpl_compat & jnp.any(tpl_it_ok2, axis=-1)
        tpl_pick = _first_true(tpl_ok)
        any_tpl = jnp.any(tpl_ok)
        free_slot = _first_true(~state.claim_open)
        has_slot = jnp.any(~state.claim_open)

        kind = jnp.where(
            any_node,
            KIND_NODE,
            jnp.where(
                any_claim,
                KIND_CLAIM,
                jnp.where(
                    any_tpl,
                    jnp.where(has_slot, KIND_NEW_CLAIM, KIND_NO_SLOT),
                    KIND_FAIL,
                ),
            ),
        ).astype(jnp.int32)

        # -- commit via one-hot masks
        node_hot = (jnp.arange(N) == node_pick) & (kind == KIND_NODE)
        claim_hot = (jnp.arange(C) == claim_pick) & (kind == KIND_CLAIM)
        slot_hot = (jnp.arange(C) == free_slot) & (kind == KIND_NEW_CLAIM)

        def mix_req(cur: ReqTensor, upd: ReqTensor, hot) -> ReqTensor:
            sel2, sel3 = hot[:, None], hot[:, None, None]
            return ReqTensor(
                admitted=jnp.where(sel3, upd.admitted, cur.admitted),
                comp=jnp.where(sel2, upd.comp, cur.comp),
                gt=jnp.where(sel2, upd.gt, cur.gt),
                lt=jnp.where(sel2, upd.lt, cur.lt),
                defined=jnp.where(sel2, upd.defined, cur.defined),
            )

        # node commit (existingnode.go:116-123)
        node_upd = _intersect_rows(state.node_req, pod_req)
        new_node_req = mix_req(state.node_req, node_upd, node_hot)
        new_node_requests = jnp.where(node_hot[:, None], node_requests2, state.node_requests)
        new_node_npods = state.node_npods + node_hot.astype(jnp.int32)

        # claim commit (nodeclaim.go:111-118)
        tpl_row = lambda arr: arr[jnp.minimum(tpl_pick, TPL - 1)]
        slot_req = ReqTensor(
            admitted=tpl_row(tpl_new_req.admitted),
            comp=tpl_row(tpl_new_req.comp),
            gt=tpl_row(tpl_new_req.gt),
            lt=tpl_row(tpl_new_req.lt),
            defined=tpl_row(tpl_new_req.defined),
        )
        new_claim_req = mix_req(
            mix_req(state.claim_req, claim_new_req, claim_hot),
            ReqTensor(
                admitted=jnp.broadcast_to(slot_req.admitted, (C, K, V)),
                comp=jnp.broadcast_to(slot_req.comp, (C, K)),
                gt=jnp.broadcast_to(slot_req.gt, (C, K)),
                lt=jnp.broadcast_to(slot_req.lt, (C, K)),
                defined=jnp.broadcast_to(slot_req.defined, (C, K)),
            ),
            slot_hot,
        )
        new_claim_requests = jnp.where(
            claim_hot[:, None],
            claim_requests2,
            jnp.where(slot_hot[:, None], tpl_requests2[jnp.minimum(tpl_pick, TPL - 1)][None, :], state.claim_requests),
        )
        new_claim_it_ok = jnp.where(
            claim_hot[:, None],
            claim_it_ok2,
            jnp.where(slot_hot[:, None], tpl_it_ok2[jnp.minimum(tpl_pick, TPL - 1)][None, :], state.claim_it_ok),
        )
        new_claim_open = state.claim_open | slot_hot
        new_claim_npods = state.claim_npods + claim_hot.astype(jnp.int32) + slot_hot.astype(jnp.int32)
        new_claim_tpl = jnp.where(slot_hot, tpl_pick.astype(jnp.int32), state.claim_tpl)

        index = jnp.where(
            kind == KIND_NODE,
            node_pick,
            jnp.where(kind == KIND_CLAIM, claim_pick, jnp.where(kind == KIND_NEW_CLAIM, free_slot, -1)),
        ).astype(jnp.int32)

        new_state = FFDState(
            claim_req=new_claim_req,
            claim_requests=new_claim_requests,
            claim_it_ok=new_claim_it_ok,
            claim_open=new_claim_open,
            claim_npods=new_claim_npods,
            claim_tpl=new_claim_tpl,
            node_req=new_node_req,
            node_requests=new_node_requests,
            node_npods=new_node_npods,
        )
        return new_state, (kind, index)

    pods_xs = (
        problem.pod_reqs,
        jnp.asarray(problem.pod_requests),
        jnp.asarray(problem.pod_tol_tpl),
        jnp.asarray(problem.pod_tol_node),
    )
    final_state, (kinds, indices) = lax.scan(step, init, pods_xs)
    return FFDResult(kind=kinds, index=indices, state=final_state)


def _zone_ct_static(problem: SchedulingProblem) -> tuple:
    """Zone / capacity-type key indices: the encoder pins them to 0 and 1."""
    return 0, 1
