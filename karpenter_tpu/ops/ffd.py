"""First-fit-decreasing bin-pack as a lax.scan — import facade.

TPU-native re-design of the reference's Scheduler.Solve pod loop
(scheduler.go:140-189, :238-285): pods arrive pre-sorted by the FFD queue
order; one scan step places one pod. Placement *scoring* — which existing
nodes / open claims / fresh template claims could accept the pod, including
the topology domain selection — is computed for every candidate at once with
the vectorized mask kernels (the reference walks them one by one,
O(candidates x instanceTypes) set intersections per pod); the *commit* stays
sequential inside the scan because every placement narrows the chosen bin's
requirement state and shifts the topology counters.

Module map (split round-5 from the former 2k-line monolith):
  ffd_core.py   — FFDState/FFDResult, constants, initial state, lane
                  padding/alignment, shared per-pod gate builders, and the
                  closed-form capacity/water-level math
  ffd_step.py   — the narrow per-pod scan step + the plain one-pass entry
                  (solve_ffd)
  ffd_sweeps.py — ALL relax-and-retry passes in one device launch with
                  stride commits over strict-identical chains
                  (solve_ffd_sweeps, the production provisioning entry)
  ffd_runs.py   — run-compressed scan committing whole identical-pod runs
                  by waterfill (solve_ffd_runs, fuzz-anchored to the
                  per-pod step)
  relax.py      — phase-1 dense relaxation placement (KARPENTER_TPU_RELAX):
                  waterfill over pod-groups x template bins, residue repaired
                  by the carried sweeps entry (solve_ffd_sweeps_carried)

Every public (and test-visible private) name re-exports here so callers
keep one import surface.
"""

from karpenter_tpu.ops.ffd_core import (  # noqa: F401
    FFDResult,
    FFDState,
    IterCounts,
    KIND_CLAIM,
    KIND_FAIL,
    KIND_NEW_CLAIM,
    KIND_NODE,
    KIND_NO_SLOT,
    Statics,
    _capacity,
    _first_true,
    _fresh_template_rows,
    _intersect_rows,
    _lane_align,
    _make_it_gate,
    _mint_host_onehot,
    _mix_req_rows,
    _offer_rows,
    _pad_lanes_mult32,
    _pin_hostname,
    _pod_xs,
    _row_sentinel_bounds,
    _statics,
    _water_level,
    initial_state,
    problem_bounds_free,
)
from karpenter_tpu.ops.ffd_step import (  # noqa: F401
    _make_step,
    _solve_ffd_fresh_jit,
    _solve_ffd_jit,
    solve_ffd,
)
from karpenter_tpu.ops.ffd_sweeps import (  # noqa: F401
    _make_stride,
    _solve_ffd_sweeps_carried_jit,
    _solve_ffd_sweeps_carried_policy_jit,
    _solve_ffd_sweeps_fresh_jit,
    _solve_ffd_sweeps_fresh_policy_jit,
    _sweeps_impl,
    solve_ffd_sweeps,
    solve_ffd_sweeps_carried,
    solve_ffd_sweeps_carried_policy,
    solve_ffd_sweeps_policy,
)
from karpenter_tpu.ops.ffd_runs import (  # noqa: F401
    _make_run_commit,
    _solve_ffd_runs_fresh_jit,
    _solve_ffd_runs_jit,
    has_topo_runs,
    max_run_bucket,
    solve_ffd_runs,
)
