"""First-fit-decreasing bin-pack as a lax.scan.

TPU-native re-design of the reference's Scheduler.Solve pod loop
(scheduler.go:140-189, :238-285): pods arrive pre-sorted by the FFD queue
order; one scan step places one pod. Placement *scoring* — which existing
nodes / open claims / fresh template claims could accept the pod, including
the topology domain selection — is computed for every candidate at once with
the vectorized mask kernels (the reference walks them one by one,
O(candidates × instanceTypes) set intersections per pod); the *commit* stays
sequential inside the scan because every placement narrows the chosen bin's
requirement state and shifts the topology counters.

Placement priority per pod (scheduler.go:238-285):
  1. first existing node (pre-sorted initialized-first) that tolerates, fits,
     has no host-port conflict, is requirement-compatible, and satisfies
     topology (existingnode.go:64-124, strict Compatible);
  2. open claim with the fewest pods whose topology-narrowed state keeps >= 1
     instance type satisfying requirements + resources + offerings
     (nodeclaim.go:65-119);
  3. first template (weight order) whose fresh claim — minted hostname
     included — accepts the pod, subject to nodepool limit headroom
     (filterByRemainingResources / subtractMax, scheduler.go:343-383);
  4. otherwise the pod fails this pass (relaxation happens host-side between
     passes, the carried FFDState preserving earlier placements).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax, vmap

from karpenter_tpu.models.problem import ReqTensor, SchedulingProblem
from karpenter_tpu.ops import masks
from karpenter_tpu.ops.topology_kernels import PodTopoStatics, record, topo_gate

# placement kinds emitted per pod
KIND_NODE = 0
KIND_CLAIM = 1
KIND_NEW_CLAIM = 2
KIND_FAIL = 3
KIND_NO_SLOT = 4  # a fresh claim would accept the pod, but slots ran out

# vocab key indices the encoder pins (single source: models/problem.py)
from karpenter_tpu.models.problem import CT_KEY, HOSTNAME_KEY, ZONE_KEY  # noqa: E402

# plain int: a module-level jnp scalar would initialize the JAX backend at
# import time (and block on the TPU tunnel in processes that never use it)
_BIG = 2**30

# scan unroll factor: amortizes per-iteration dispatch overhead on
# accelerators at the cost of a proportionally bigger program to compile
import os as _os  # noqa: E402

_UNROLL = int(_os.environ.get("KARPENTER_TPU_SCAN_UNROLL", "1"))


@jax.tree_util.register_dataclass
@dataclass
class FFDState:
    claim_req: ReqTensor  # [C, K, V] narrowed requirement state per claim
    claim_requests: Any  # f32[C, R] accumulated requests (incl daemon overhead)
    claim_it_ok: Any  # bool[C, T] surviving instance types
    claim_open: Any  # bool[C]
    claim_npods: Any  # i32[C]
    claim_tpl: Any  # i32[C]
    claim_used_ports: Any  # bool[C, PT] reserved host-port lanes
    node_req: ReqTensor  # [N, K, V] narrowed existing-node requirements
    node_requests: Any  # f32[N, R] accumulated requests (incl daemon overhead)
    node_npods: Any  # i32[N]
    node_used_ports: Any  # bool[N, PT]
    node_vol_used: Any  # i32[N, D] CSI attach counts per limited driver
    remaining: Any  # f32[TPL, R] nodepool limits headroom (+inf unlimited)
    grp_counts: Any  # i32[G, V] topology domain counts
    grp_registered: Any  # bool[G, V] known topology domains


@jax.tree_util.register_dataclass
@dataclass
class FFDResult:
    kind: Any  # i32[P]
    index: Any  # i32[P] node index / claim slot (meaning depends on kind)
    state: FFDState  # final bin state


def _first_true(mask: jnp.ndarray) -> jnp.ndarray:
    """Index of the first True (or len(mask) when none)."""
    return jnp.argmax(jnp.concatenate([mask, jnp.array([True])]))


def _intersect_rows(reqs: ReqTensor, row: ReqTensor) -> ReqTensor:
    return vmap(lambda r: masks.intersect(r, row))(reqs)


def initial_state(problem: SchedulingProblem, max_claims: int) -> FFDState:
    K, V = problem.num_keys, problem.num_lanes
    T, R = problem.num_instance_types, problem.num_resources
    N, C = problem.num_nodes, max_claims
    PT = problem.pod_ports.shape[1]
    lv = jnp.asarray(problem.lane_valid)
    return FFDState(
        claim_req=ReqTensor(
            admitted=jnp.broadcast_to(lv, (C, K, V)),
            comp=jnp.ones((C, K), dtype=bool),
            gt=jnp.full((C, K), -(2**31) + 1, dtype=jnp.int32),
            lt=jnp.full((C, K), 2**31 - 1, dtype=jnp.int32),
            defined=jnp.zeros((C, K), dtype=bool),
        ),
        claim_requests=jnp.zeros((C, R), dtype=jnp.float32),
        claim_it_ok=jnp.zeros((C, T), dtype=bool),
        claim_open=jnp.zeros((C,), dtype=bool),
        claim_npods=jnp.zeros((C,), dtype=jnp.int32),
        claim_tpl=jnp.zeros((C,), dtype=jnp.int32),
        claim_used_ports=jnp.zeros((C, PT), dtype=bool),
        node_req=jax.tree_util.tree_map(jnp.asarray, problem.node_reqs),
        node_requests=jnp.asarray(problem.node_overhead),
        node_npods=jnp.zeros((N,), dtype=jnp.int32),
        node_used_ports=jnp.asarray(problem.node_used_ports),
        node_vol_used=jnp.asarray(problem.node_vol_used),
        remaining=jnp.asarray(problem.tpl_remaining),
        grp_counts=jnp.asarray(problem.grp_counts0),
        grp_registered=jnp.asarray(problem.grp_registered0),
    )


def solve_ffd(
    problem: SchedulingProblem, max_claims: int, init: Optional[FFDState] = None
) -> FFDResult:
    """Run one pack pass. Shapes are static per bucket; XLA caches the
    compiled executable across batches. ``init`` carries bin + topology state
    between relax-and-retry passes (the queue requeue of scheduler.go:150-170).
    """
    if init is None:
        init = initial_state(problem, max_claims)
    return _solve_ffd_jit(problem, init)


def _pad_lanes_mult32(problem: SchedulingProblem) -> SchedulingProblem:
    """Pad the value-lane axis to a multiple of 32 for bitpacking. Shape-static
    (plain Python under trace); ops/padding.py already does this for bucketed
    callers, so this is a no-op on the production path."""
    V = problem.num_lanes
    pad = (-V) % 32
    if pad == 0:
        return problem
    import dataclasses

    def pad_req(r: ReqTensor) -> ReqTensor:
        return dataclasses.replace(
            r, admitted=jnp.pad(r.admitted, [(0, 0)] * (r.admitted.ndim - 1) + [(0, pad)])
        )

    lane_pad = [(0, 0), (0, pad)]
    return dataclasses.replace(
        problem,
        lane_valid=jnp.pad(problem.lane_valid, lane_pad),
        lane_numeric=jnp.pad(problem.lane_numeric, lane_pad, constant_values=jnp.nan),
        lane_lex_rank=jnp.pad(problem.lane_lex_rank, lane_pad, constant_values=2**30),
        pod_reqs=pad_req(problem.pod_reqs),
        pod_strict_reqs=pad_req(problem.pod_strict_reqs),
        it_reqs=pad_req(problem.it_reqs),
        tpl_reqs=pad_req(problem.tpl_reqs),
        node_reqs=pad_req(problem.node_reqs),
        grp_filter=pad_req(problem.grp_filter),
        grp_counts0=jnp.pad(problem.grp_counts0, lane_pad),
        grp_registered0=jnp.pad(problem.grp_registered0, lane_pad),
    )


@jax.jit
def _solve_ffd_jit(problem: SchedulingProblem, init: FFDState) -> FFDResult:
    problem = _pad_lanes_mult32(problem)
    C = init.claim_open.shape[0]
    N = problem.num_nodes
    T = problem.num_instance_types
    TPL = problem.num_templates
    K = problem.num_keys
    V = problem.num_lanes

    # lane-pad carried state to match (no-op when init came from initial_state)
    if init.grp_counts.shape[-1] != V:
        pad = V - init.grp_counts.shape[-1]
        import dataclasses

        def pad_adm(r):
            return dataclasses.replace(
                r, admitted=jnp.pad(r.admitted, [(0, 0)] * (r.admitted.ndim - 1) + [(0, pad)])
            )

        init = dataclasses.replace(
            init,
            claim_req=pad_adm(init.claim_req),
            node_req=pad_adm(init.node_req),
            grp_counts=jnp.pad(init.grp_counts, [(0, 0), (0, pad)]),
            grp_registered=jnp.pad(init.grp_registered, [(0, 0), (0, pad)]),
        )

    lv, ln = jnp.asarray(problem.lane_valid), jnp.asarray(problem.lane_numeric)
    wellknown = jnp.asarray(problem.key_wellknown)
    no_allow = jnp.zeros_like(wellknown)

    # instance-type side of the hot compat product: packed lanes + polarity,
    # computed once per solve (instance types never change during a pack)
    it_packed = masks.pack_lanes(jnp.asarray(problem.it_reqs.admitted))  # [T, K, W]
    it_neg = vmap(lambda r: masks.negative_polarity(r, lv, ln))(problem.it_reqs)

    def it_gate(state_rows: ReqTensor, requests: jnp.ndarray, prior_ok: jnp.ndarray):
        """[B, T] mask of instance types surviving a narrowed state +
        accumulated requests (nodeclaim.go:225-260)."""
        state_packed = masks.pack_lanes(state_rows.admitted)  # [B, K, W]
        state_neg = vmap(lambda r: masks.negative_polarity(r, lv, ln))(state_rows)
        compat = masks.packed_pairwise_compat(
            state_rows, state_packed, state_neg, problem.it_reqs, it_packed, it_neg
        )  # [B, T]
        fit = masks.fits(requests[:, None, :], problem.it_alloc[None, :, :])  # [B, T]
        offer = vmap(
            lambda adm: masks.has_offering(
                adm, ZONE_KEY, CT_KEY, problem.offer_zone, problem.offer_ct, problem.offer_ok
            )
        )(state_rows.admitted)  # [B, T]
        return prior_ok & compat & fit & offer

    def step(state: FFDState, pod):
        (
            pod_req,
            pod_strict,
            pod_requests,
            tol_tpl,
            tol_node,
            pod_ports,
            pod_conflict,
            grp_match,
            grp_selects,
            grp_owned,
            pod_vols,
        ) = pod
        topo_pod = PodTopoStatics(
            strict_admitted=pod_strict.admitted,
            grp_match=grp_match,
            grp_selects=grp_selects,
            grp_owned=grp_owned,
        )

        # -- 1. existing nodes (scheduler.go:240-244; existingnode.go:64-124)
        node_requests2 = state.node_requests + pod_requests[None, :]
        node_fit = masks.fits(node_requests2, problem.node_avail)
        node_compat = vmap(
            lambda nr: masks.compatible_ok(nr, pod_req, lv, ln, no_allow)
        )(state.node_req)
        node_port_ok = ~jnp.any(state.node_used_ports & pod_conflict[None, :], axis=-1)
        # CSI attach limits gate existing nodes only (existingnode.go:100-106)
        node_vol_ok = jnp.all(
            state.node_vol_used + pod_vols[None, :] <= problem.node_vol_limits, axis=-1
        )
        node_merged = _intersect_rows(state.node_req, pod_req)
        node_topo_ok, node_final = topo_gate(
            problem, state.grp_counts, state.grp_registered, topo_pod, node_merged, no_allow
        )
        node_ok = tol_node & node_fit & node_compat & node_port_ok & node_vol_ok & node_topo_ok
        node_pick = _first_true(node_ok)
        any_node = jnp.any(node_ok)

        # -- 2. open claims, fewest pods first (scheduler.go:247-254)
        claim_compat = vmap(
            lambda cr: masks.compatible_ok(cr, pod_req, lv, ln, wellknown)
        )(state.claim_req)
        claim_merged = _intersect_rows(state.claim_req, pod_req)
        claim_topo_ok, claim_final = topo_gate(
            problem, state.grp_counts, state.grp_registered, topo_pod, claim_merged, wellknown
        )
        claim_requests2 = state.claim_requests + pod_requests[None, :]
        claim_it_ok2 = it_gate(claim_final, claim_requests2, state.claim_it_ok)
        claim_port_ok = ~jnp.any(state.claim_used_ports & pod_conflict[None, :], axis=-1)
        claim_ok = (
            state.claim_open
            & tol_tpl[state.claim_tpl]
            & claim_port_ok
            & claim_compat
            & claim_topo_ok
            & jnp.any(claim_it_ok2, axis=-1)
        )
        claim_rank = jnp.where(claim_ok, state.claim_npods * C + jnp.arange(C), _BIG)
        claim_pick = jnp.argmin(claim_rank)
        any_claim = jnp.any(claim_ok)

        # -- 3. fresh claim from templates, weight order (scheduler.go:256-283);
        # the prospective slot's hostname is minted before evaluation
        # (nodeclaim.go:46-63) and its lane registered for topology if opened
        free_slot = _first_true(~state.claim_open)
        has_slot = jnp.any(~state.claim_open)
        # hostname minting is active only when the encoder allotted claim
        # hostname lanes (static shape decision)
        mint_hostnames = problem.claim_hostname_lane.shape[0] > 0
        if mint_hostnames:
            host_lane = problem.claim_hostname_lane[
                jnp.minimum(free_slot, problem.claim_hostname_lane.shape[0] - 1)
            ]
            host_onehot = jnp.arange(V) == host_lane  # [V]
        else:
            host_onehot = jnp.zeros((V,), dtype=bool)

        tpl_compat = vmap(
            lambda tr: masks.compatible_ok(tr, pod_req, lv, ln, wellknown)
        )(problem.tpl_reqs)
        tpl_merged = _intersect_rows(problem.tpl_reqs, pod_req)
        if mint_hostnames:
            tpl_merged = ReqTensor(
                admitted=tpl_merged.admitted.at[:, HOSTNAME_KEY, :].set(
                    tpl_merged.admitted[:, HOSTNAME_KEY, :] & host_onehot[None, :]
                ),
                comp=tpl_merged.comp.at[:, HOSTNAME_KEY].set(False),
                gt=tpl_merged.gt,
                lt=tpl_merged.lt,
                defined=tpl_merged.defined.at[:, HOSTNAME_KEY].set(True),
            )
        # the new hostname is registered before the gate evaluates
        reg_for_tpl = state.grp_registered | (
            (problem.grp_key == HOSTNAME_KEY)[:, None] & host_onehot[None, :]
        )
        tpl_topo_ok, tpl_final = topo_gate(
            problem, state.grp_counts, reg_for_tpl, topo_pod, tpl_merged, wellknown
        )
        tpl_requests2 = problem.tpl_overhead + pod_requests[None, :]
        within_limits = masks.fits(
            problem.it_cap[None, :, :], state.remaining[:, None, :]
        )  # [TPL, T]
        tpl_it_ok2 = it_gate(tpl_final, tpl_requests2, problem.tpl_it_ok & within_limits)
        tpl_ok = tol_tpl & tpl_compat & tpl_topo_ok & jnp.any(tpl_it_ok2, axis=-1)
        tpl_pick = _first_true(tpl_ok)
        any_tpl = jnp.any(tpl_ok)

        kind = jnp.where(
            any_node,
            KIND_NODE,
            jnp.where(
                any_claim,
                KIND_CLAIM,
                jnp.where(
                    any_tpl,
                    jnp.where(has_slot, KIND_NEW_CLAIM, KIND_NO_SLOT),
                    KIND_FAIL,
                ),
            ),
        ).astype(jnp.int32)

        # -- commit via one-hot masks
        node_hot = (jnp.arange(N) == node_pick) & (kind == KIND_NODE)
        claim_hot = (jnp.arange(C) == claim_pick) & (kind == KIND_CLAIM)
        slot_hot = (jnp.arange(C) == free_slot) & (kind == KIND_NEW_CLAIM)

        def mix_req(cur: ReqTensor, upd: ReqTensor, hot) -> ReqTensor:
            sel2, sel3 = hot[:, None], hot[:, None, None]
            return ReqTensor(
                admitted=jnp.where(sel3, upd.admitted, cur.admitted),
                comp=jnp.where(sel2, upd.comp, cur.comp),
                gt=jnp.where(sel2, upd.gt, cur.gt),
                lt=jnp.where(sel2, upd.lt, cur.lt),
                defined=jnp.where(sel2, upd.defined, cur.defined),
            )

        def gather_row(rows: ReqTensor, idx, cap) -> ReqTensor:
            return rows.row(jnp.minimum(idx, cap - 1))

        # node commit (existingnode.go:116-123)
        new_node_req = mix_req(state.node_req, node_final, node_hot)
        new_node_requests = jnp.where(node_hot[:, None], node_requests2, state.node_requests)
        new_node_npods = state.node_npods + node_hot.astype(jnp.int32)
        new_node_used_ports = state.node_used_ports | (node_hot[:, None] & pod_ports[None, :])
        new_node_vol_used = state.node_vol_used + node_hot[:, None].astype(jnp.int32) * pod_vols[None, :]

        # claim commit (nodeclaim.go:111-118)
        slot_req = gather_row(tpl_final, tpl_pick, TPL)
        new_claim_req = mix_req(
            mix_req(state.claim_req, claim_final, claim_hot),
            ReqTensor(
                admitted=jnp.broadcast_to(slot_req.admitted, (C, K, V)),
                comp=jnp.broadcast_to(slot_req.comp, (C, K)),
                gt=jnp.broadcast_to(slot_req.gt, (C, K)),
                lt=jnp.broadcast_to(slot_req.lt, (C, K)),
                defined=jnp.broadcast_to(slot_req.defined, (C, K)),
            ),
            slot_hot,
        )
        tpl_row_requests = tpl_requests2[jnp.minimum(tpl_pick, TPL - 1)]
        new_claim_requests = jnp.where(
            claim_hot[:, None],
            claim_requests2,
            jnp.where(slot_hot[:, None], tpl_row_requests[None, :], state.claim_requests),
        )
        tpl_row_it_ok = tpl_it_ok2[jnp.minimum(tpl_pick, TPL - 1)]
        new_claim_it_ok = jnp.where(
            claim_hot[:, None],
            claim_it_ok2,
            jnp.where(slot_hot[:, None], tpl_row_it_ok[None, :], state.claim_it_ok),
        )
        new_claim_open = state.claim_open | slot_hot
        new_claim_npods = state.claim_npods + claim_hot.astype(jnp.int32) + slot_hot.astype(jnp.int32)
        new_claim_tpl = jnp.where(slot_hot, tpl_pick.astype(jnp.int32), state.claim_tpl)
        new_claim_used_ports = state.claim_used_ports | (
            (claim_hot | slot_hot)[:, None] & pod_ports[None, :]
        )

        # opening a claim burns pessimistic headroom (subtractMax) and
        # registers its hostname lane for hostname topologies
        opened = kind == KIND_NEW_CLAIM
        opened_tpl_hot = (jnp.arange(TPL) == tpl_pick) & opened
        max_cap = jnp.max(
            jnp.where(tpl_row_it_ok[:, None], problem.it_cap, 0.0), axis=0
        )  # [R]
        new_remaining = jnp.where(
            opened_tpl_hot[:, None], state.remaining - max_cap[None, :], state.remaining
        )
        new_registered = state.grp_registered | (
            opened
            & mint_hostnames
            & (problem.grp_key == HOSTNAME_KEY)[:, None]
            & host_onehot[None, :]
        )

        # topology record for the chosen bin (topology.go:125-148)
        committed = (kind == KIND_NODE) | (kind == KIND_CLAIM) | (kind == KIND_NEW_CLAIM)
        chosen_final = gather_row(node_final, node_pick, N) if N > 0 else None
        claim_row = gather_row(claim_final, claim_pick, C)
        slot_row = slot_req

        def pick_rows(a, b, cond):
            return jax.tree_util.tree_map(
                lambda x, y: jnp.where(
                    jnp.reshape(cond, (1,) * x.ndim), x, y
                ),
                a,
                b,
            )

        rec_row = pick_rows(claim_row, slot_row, kind == KIND_CLAIM)
        if N > 0:
            rec_row = pick_rows(chosen_final, rec_row, kind == KIND_NODE)
        rec_allow = jnp.where(kind == KIND_NODE, no_allow, wellknown)
        new_counts, new_registered = record(
            problem,
            state.grp_counts,
            new_registered,
            topo_pod,
            rec_row,
            rec_allow,
            committed,
            lv,
            ln,
        )

        index = jnp.where(
            kind == KIND_NODE,
            node_pick,
            jnp.where(kind == KIND_CLAIM, claim_pick, jnp.where(kind == KIND_NEW_CLAIM, free_slot, -1)),
        ).astype(jnp.int32)

        new_state = FFDState(
            claim_req=new_claim_req,
            claim_requests=new_claim_requests,
            claim_it_ok=new_claim_it_ok,
            claim_open=new_claim_open,
            claim_npods=new_claim_npods,
            claim_tpl=new_claim_tpl,
            claim_used_ports=new_claim_used_ports,
            node_req=new_node_req,
            node_requests=new_node_requests,
            node_npods=new_node_npods,
            node_used_ports=new_node_used_ports,
            node_vol_used=new_node_vol_used,
            remaining=new_remaining,
            grp_counts=new_counts,
            grp_registered=new_registered,
        )
        return new_state, (kind, index)

    pods_xs = (
        problem.pod_reqs,
        problem.pod_strict_reqs,
        jnp.asarray(problem.pod_requests),
        jnp.asarray(problem.pod_tol_tpl),
        jnp.asarray(problem.pod_tol_node),
        jnp.asarray(problem.pod_ports),
        jnp.asarray(problem.pod_port_conflict),
        jnp.asarray(problem.pod_grp_match),
        jnp.asarray(problem.pod_grp_selects),
        jnp.asarray(problem.pod_grp_owned),
        jnp.asarray(problem.pod_vol_counts),
    )
    final_state, (kinds, indices) = lax.scan(step, init, pods_xs, unroll=_UNROLL)
    return FFDResult(kind=kinds, index=indices, state=final_state)
